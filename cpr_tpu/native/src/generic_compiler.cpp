// Generic single-agent DAG-protocol MDP compiler, native edition.
//
// Reference counterpart: the Python model in cpr_tpu/mdp/generic/
// (model.py, dag.py, canon.py, protocols/*), itself a re-design of
// mdp/lib/models/generic_v1/model.py.  This file implements the SAME
// semantics — Release/Consider/Continue actions, alpha/gamma
// randomness, garbage collection, common-chain truncation, honest-loop
// reset, isomorphic-state merging by canonical labeling — as a
// single-pass C++ BFS, because on one host core the Python BFS tops out
// around 1k states/s while the capstone (BASELINE.md config 5: GhostDAG
// at full state space) needs millions of transitions.  The Python
// compiler stays the semantic anchor: tests assert state/transition
// counts and VI start values match it exactly on small cutoffs.
//
// Layout choices (vs the Python value types):
//   - a DAG is a fixed-size by-value struct: n, per-block parent
//     bitmask, attacker bitmask.  Block ids are dense and topologically
//     sorted (invariant), block 0 is genesis.
//   - sets of blocks are u32 bitmasks throughout (MAXN = 20).
//   - derived data (children/past/future/height) is recomputed on
//     demand with O(n^2) mask ops instead of cached per object.
//   - protocol miner-state is one int (head block id, or -1).
//
// C API (ctypes; see cpr_tpu/mdp/generic/native.py):
//   gmc_compile(...) -> handle          gmc_n_states/transitions/start
//   gmc_copy / gmc_copy_start           gmc_free, gmc_last_error

#include <algorithm>
#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

using u32 = uint32_t;
using u64 = uint64_t;

static const int MAXN = 20;
static const int ATTACKER = 0, DEFENDER = 1;

static inline int popcnt(u32 m) { return __builtin_popcount(m); }
static inline int lowbit(u32 m) { return __builtin_ctz(m); }

// ----------------------------------------------------------------- DAG

struct Dag {
    uint8_t n;
    u32 par[MAXN];  // parent mask per block
    u32 atk;        // attacker-mined blocks (genesis excluded, miner -1)

    bool operator==(const Dag& o) const {
        return n == o.n && atk == o.atk &&
               std::memcmp(par, o.par, n * sizeof(u32)) == 0;
    }
    u32 all_mask() const { return (n >= 32) ? ~0u : ((1u << n) - 1); }
    int miner_of(int b) const {
        return b == 0 ? -1 : ((atk >> b) & 1 ? ATTACKER : DEFENDER);
    }
};

static Dag genesis_dag() {
    Dag d;
    d.n = 1;
    d.par[0] = 0;
    d.atk = 0;
    return d;
}

struct Derived {
    u32 children[MAXN];
    u32 past[MAXN];
    int height[MAXN];
};

static void derive(const Dag& d, Derived& o) {
    for (int b = 0; b < d.n; b++) {
        o.children[b] = 0;
        o.past[b] = 0;
        o.height[b] = 0;
    }
    for (int b = 0; b < d.n; b++) {
        u32 ps = d.par[b];
        while (ps) {
            int p = lowbit(ps);
            ps &= ps - 1;
            o.children[p] |= 1u << b;
            o.past[b] |= o.past[p] | (1u << p);
            if (o.height[p] + 1 > o.height[b]) o.height[b] = o.height[p] + 1;
        }
    }
}

static u32 future_of(const Derived& dv, int n, int block) {
    u32 acc = 0, stack = dv.children[block];
    while (stack) {
        int b = lowbit(stack);
        stack &= stack - 1;
        if (!(acc & (1u << b))) {
            acc |= 1u << b;
            stack |= dv.children[b] & ~acc;
        }
    }
    (void)n;
    return acc;
}

struct DagOverflow {};  // thrown when a DAG outgrows the mask width

// append returns new block id; caller fills masks
static int dag_append(Dag& d, u32 parents, int miner) {
    if (d.n >= MAXN) throw DagOverflow();
    int b = d.n;
    d.par[b] = parents;
    if (miner == ATTACKER) d.atk |= 1u << b;
    d.n++;
    return b;
}

// ----------------------------------------------------------------- state

struct State {
    Dag dag;
    u32 avis, dvis, withheld, ignored;
    int16_t astate, dstate;  // protocol state: block id or -1

    bool operator==(const State& o) const {
        return avis == o.avis && dvis == o.dvis && withheld == o.withheld &&
               ignored == o.ignored && astate == o.astate &&
               dstate == o.dstate && dag == o.dag;
    }
};

static u64 mix(u64 h, u64 v) {
    h ^= v + 0x9e3779b97f4a7c15ull + (h << 6) + (h >> 2);
    return h;
}

struct StateHash {
    size_t operator()(const State& s) const {
        u64 h = s.dag.n;
        for (int b = 0; b < s.dag.n; b++) h = mix(h, s.dag.par[b]);
        h = mix(h, s.dag.atk);
        h = mix(h, s.avis);
        h = mix(h, s.dvis);
        h = mix(h, s.withheld);
        h = mix(h, s.ignored);
        h = mix(h, (u64)(uint16_t)s.astate << 16 | (uint16_t)s.dstate);
        return (size_t)h;
    }
};

// ----------------------------------------------------------------- view

struct View {
    const Dag& dag;
    const Derived& dv;
    u32 visible;
    int me;  // -1 for judge views

    u32 children(int b) const { return dv.children[b] & visible; }
    int height(int b) const { return dv.height[b]; }
    int miner_of(int b) const { return dag.miner_of(b); }
    u32 parents(int b) const { return dag.par[b]; }
    u32 tips(u32 subgraph) const {  // dag.py View.tips: unfiltered children
        u32 acc = 0, m = subgraph;
        while (m) {
            int b = lowbit(m);
            m &= m - 1;
            if (!(dv.children[b] & subgraph)) acc |= 1u << b;
        }
        return acc;
    }
};

// ----------------------------------------------------------------- protocols

struct Proto {
    virtual ~Proto() {}
    virtual int init(const View& v) const = 0;
    virtual u32 mining(const View& v, int pstate) const = 0;
    virtual int update(const View& v, int pstate, int block) const = 0;
    virtual void history(const View& v, int pstate,
                         std::vector<int>& out) const = 0;
    virtual double progress(const View& v, int block) const = 0;
    virtual void coinbase(const View& v, int block,
                          std::vector<std::pair<int, double>>& out) const = 0;
    virtual int relabel(int pstate, const int* new_ids) const = 0;
    virtual int color(const View& v, int pstate, int block) const = 0;
    virtual u32 keep(const View& v, int pstate) const = 0;
};

// -- bitcoin (protocols/bitcoin.py) -----------------------------------

struct Bitcoin : Proto {
    int init(const View&) const override { return 0; }
    u32 mining(const View&, int head) const override { return 1u << head; }
    int update(const View& v, int head, int block) const override {
        return v.height(block) > v.height(head) ? block : head;
    }
    void history(const View& v, int head, std::vector<int>& out) const override {
        out.clear();
        int b = head;
        while (true) {
            out.push_back(b);
            if (b == 0) break;
            b = lowbit(v.dag.par[b]);
        }
        std::reverse(out.begin(), out.end());
    }
    double progress(const View&, int) const override { return 1.0; }
    void coinbase(const View& v, int block,
                  std::vector<std::pair<int, double>>& out) const override {
        out.clear();
        out.emplace_back(v.miner_of(block), 1.0);
    }
    int relabel(int head, const int* new_ids) const override {
        return new_ids[head];
    }
    int color(const View&, int head, int block) const override {
        return block == head ? 1 : 0;
    }
    u32 keep(const View&, int head) const override { return 1u << head; }
};

// -- ghostdag (protocols/ghostdag.py) ---------------------------------

struct DagSub {
    Dag dag;
    u32 sub;
    bool operator==(const DagSub& o) const {
        return sub == o.sub && dag == o.dag;
    }
};
struct DagSubHash {
    size_t operator()(const DagSub& k) const {
        u64 h = k.dag.n;
        for (int b = 0; b < k.dag.n; b++) h = mix(h, k.dag.par[b]);
        h = mix(h, k.sub);
        return (size_t)h;
    }
};
struct Blue {
    u32 blue;
    std::vector<int8_t> hist;
};

struct GhostDag : Proto {
    int k;
    // memo shared across states; cleared when it grows past the cap —
    // but ONLY between top-level calls: unordered_map inserts keep
    // references valid (node-based), clear() does not, and outer
    // recursion frames hold references into the map
    mutable std::unordered_map<DagSub, Blue, DagSubHash> memo;
    mutable int depth = 0;
    explicit GhostDag(int k_) : k(k_) {}

    const Blue& blue_and_history(const Dag& dag, const Derived& dv,
                                 u32 subgraph) const {
        DagSub key{dag, subgraph};
        auto it = memo.find(key);
        if (it != memo.end()) return it->second;
        if (depth == 0 && memo.size() > (1u << 21)) memo.clear();
        depth++;

        Blue out;
        if (subgraph == 1) {  // genesis only
            out.blue = 1;
            out.hist = {0};
            depth--;
            return memo.emplace(key, std::move(out)).first->second;
        }
        // tips of the subgraph (children within subgraph)
        std::vector<int> tips;
        for (u32 m = subgraph; m;) {
            int b = lowbit(m);
            m &= m - 1;
            if (!(dv.children[b] & subgraph)) tips.push_back(b);
        }
        // recurse into each tip's past; pick max blue count, tie lowest id
        int b_max = -1, best_cnt = -1;
        std::vector<u32> blue_of(tips.size());
        std::vector<const std::vector<int8_t>*> hist_of(tips.size());
        for (size_t i = 0; i < tips.size(); i++) {
            int t = tips[i];
            const Blue& r = blue_and_history(dag, dv, dv.past[t] & subgraph);
            blue_of[i] = r.blue;
            hist_of[i] = &r.hist;
            int c = popcnt(r.blue);
            if (c > best_cnt || (c == best_cnt && t < b_max)) {
                best_cnt = c;
                b_max = t;
            }
        }
        size_t mi = 0;
        while (tips[mi] != b_max) mi++;
        u32 blue_set = blue_of[mi] | (1u << b_max);
        std::vector<int8_t> history(*hist_of[mi]);
        history.push_back((int8_t)b_max);

        auto anticone = [&](int b) {
            return subgraph & ~(1u << b) & ~(dv.past[b] & subgraph) &
                   ~(future_of(dv, dag.n, b) & subgraph);
        };
        u32 ac = anticone(b_max);
        std::vector<int> cand;
        for (u32 m = ac; m;) {
            cand.push_back(lowbit(m));
            m &= m - 1;
        }
        std::sort(cand.begin(), cand.end(), [&](int a, int b) {
            if (dv.height[a] != dv.height[b])
                return dv.height[a] < dv.height[b];
            return a < b;
        });
        for (int b : cand) {
            u32 s_mask = blue_set | (1u << b);
            bool ok = true;
            for (u32 m = s_mask; m && ok;) {
                int x = lowbit(m);
                m &= m - 1;
                if (popcnt(anticone(x) & s_mask) > k) ok = false;
            }
            if (ok) {
                blue_set |= 1u << b;
                history.push_back((int8_t)b);
            }
        }
        out.blue = blue_set;
        out.hist = std::move(history);
        depth--;
        return memo.emplace(key, std::move(out)).first->second;
    }

    int init(const View&) const override { return -1; }
    u32 mining(const View& v, int) const override { return v.tips(v.visible); }
    int update(const View&, int, int) const override { return -1; }
    void history(const View& v, int, std::vector<int>& out) const override {
        Derived dv2;  // view-independent derived is passed via v.dv
        (void)dv2;
        const Blue& r = blue_and_history(v.dag, v.dv, v.visible);
        out.assign(r.hist.begin(), r.hist.end());
    }
    double progress(const View&, int) const override { return 1.0; }
    void coinbase(const View& v, int block,
                  std::vector<std::pair<int, double>>& out) const override {
        out.clear();
        out.emplace_back(v.miner_of(block), 1.0);
    }
    int relabel(int, const int*) const override { return -1; }
    int color(const View&, int, int) const override { return 0; }
    u32 keep(const View& v, int) const override { return v.tips(v.visible); }
};

// -- parallel (protocols/parallel.py) ---------------------------------

struct Parallel : Proto {
    int k;
    explicit Parallel(int k_) : k(k_) {}
    bool is_vote(const View& v, int b) const {
        return popcnt(v.dag.par[b]) == 1;
    }
    int init(const View&) const override { return 0; }
    u32 mining(const View& v, int head) const override {
        std::vector<int> votes;
        for (u32 m = v.children(head); m;) {
            votes.push_back(lowbit(m));
            m &= m - 1;
        }
        if ((int)votes.size() >= k) {
            std::stable_sort(votes.begin(), votes.end(), [&](int a, int b) {
                bool na = v.miner_of(a) != v.me, nb = v.miner_of(b) != v.me;
                if (na != nb) return !na;
                return a < b;
            });
            u32 out = 0;
            for (int i = 0; i < k; i++) out |= 1u << votes[i];
            return out;
        }
        return 1u << head;
    }
    int update(const View& v, int head, int block) const override {
        if (is_vote(v, block)) block = lowbit(v.dag.par[block]);
        int bh = v.height(block), hh = v.height(head);
        if (bh > hh) return block;
        if (bh == hh && block != head) {
            if (popcnt(v.children(block)) > popcnt(v.children(head)))
                return block;
        }
        return head;
    }
    void history(const View& v, int head, std::vector<int>& out) const override {
        out.clear();
        int b = head;
        while (true) {
            if (!is_vote(v, b) || b == 0) out.push_back(b);
            if (b == 0) break;
            b = lowbit(v.dag.par[b]);
        }
        std::reverse(out.begin(), out.end());
    }
    double progress(const View&, int) const override { return (double)(k + 1); }
    void coinbase(const View& v, int block,
                  std::vector<std::pair<int, double>>& out) const override {
        out.clear();
        out.emplace_back(v.miner_of(block), 1.0);
        for (u32 m = v.dag.par[block]; m;) {
            out.emplace_back(v.miner_of(lowbit(m)), 1.0);
            m &= m - 1;
        }
    }
    int relabel(int head, const int* new_ids) const override {
        return new_ids[head];
    }
    int color(const View&, int head, int block) const override {
        return block == head ? 1 : 0;
    }
    u32 keep(const View& v, int head) const override {
        return (1u << head) | v.children(head);
    }
};

// -- ethereum whitepaper / byzantium (protocols/ethereum.py) ----------

struct Ethereum : Proto {
    int h;
    explicit Ethereum(int h_) : h(h_) {}

    // chain parent = lowest id among max-height parents (stable sort by
    // -height in the Python spec)
    int chain_parent(const View& v, int block, u32* uncles) const {
        int best = -1, bh = -1;
        for (u32 m = v.dag.par[block]; m;) {
            int p = lowbit(m);
            m &= m - 1;
            if (v.height(p) > bh) {
                bh = v.height(p);
                best = p;
            }
        }
        if (uncles) *uncles = v.dag.par[block] & ~(best >= 0 ? 1u << best : 0);
        return best;
    }
    void history(const View& v, int head, std::vector<int>& out) const override {
        out.clear();
        int b = head;
        while (b >= 0) {
            out.push_back(b);
            if (b == 0) break;
            b = chain_parent(v, b, nullptr);
        }
        std::reverse(out.begin(), out.end());
    }
    u32 available_uncles(const View& v, int head) const {
        std::vector<int> hist;
        history(v, head, hist);
        // window = hist[-h-1:-2]
        u32 window = 0;
        int n = (int)hist.size();
        int lo = std::max(0, n - h - 1), hi = std::max(0, n - 2);
        for (int i = lo; i < hi; i++) window |= 1u << hist[i];
        u32 out = 0;
        for (u32 m = v.visible; m;) {
            int b = lowbit(m);
            m &= m - 1;
            if (v.children(b)) continue;  // not a leaf
            int p = chain_parent(v, b, nullptr);
            if (p >= 0 && (window >> p & 1)) out |= 1u << b;
        }
        return out;
    }
    int init(const View&) const override { return 0; }
    u32 mining(const View& v, int head) const override {
        return (1u << head) | available_uncles(v, head);
    }
    int update(const View& v, int head, int block) const override {
        return v.height(block) > v.height(head) ? block : head;
    }
    double progress(const View&, int) const override { return 1.0; }
    void coinbase(const View& v, int block,
                  std::vector<std::pair<int, double>>& out) const override {
        out.clear();
        u32 uncles;
        chain_parent(v, block, &uncles);
        out.emplace_back(v.miner_of(block), 1.0);
        for (u32 m = uncles; m;) {
            out.emplace_back(v.miner_of(lowbit(m)), 1.0);
            m &= m - 1;
        }
    }
    int relabel(int head, const int* new_ids) const override {
        return new_ids[head];
    }
    int color(const View&, int head, int block) const override {
        return block == head ? 1 : 0;
    }
    u32 keep(const View& v, int head) const override {
        return (1u << head) | available_uncles(v, head);
    }
};

struct Byzantium : Ethereum {
    explicit Byzantium(int h_) : Ethereum(h_) {}
    u32 mining(const View& v, int head) const override {
        std::vector<int> uncles;
        for (u32 m = available_uncles(v, head); m;) {
            uncles.push_back(lowbit(m));
            m &= m - 1;
        }
        std::stable_sort(uncles.begin(), uncles.end(), [&](int a, int b) {
            bool na = v.miner_of(a) != v.me, nb = v.miner_of(b) != v.me;
            if (na != nb) return !na;
            return a < b;
        });
        u32 out = 1u << head;
        for (size_t i = 0; i < uncles.size() && i < 2; i++)
            out |= 1u << uncles[i];
        return out;
    }
    double progress(const View& v, int block) const override {
        u32 uncles;
        chain_parent(v, block, &uncles);
        return 1.0 + popcnt(uncles);
    }
    double weight(const View& v, int block) const {
        std::vector<int> hist;
        history(v, block, hist);
        double w = 0.0;
        for (size_t i = 1; i < hist.size(); i++) w += progress(v, hist[i]);
        return w;
    }
    int update(const View& v, int head, int block) const override {
        return weight(v, block) > weight(v, head) ? block : head;
    }
    void coinbase(const View& v, int block,
                  std::vector<std::pair<int, double>>& out) const override {
        out.clear();
        u32 uncles;
        chain_parent(v, block, &uncles);
        out.emplace_back(v.miner_of(block), 1.0 + 0.03125 * popcnt(uncles));
        int hb = v.height(block);
        double max_d = h + 1;
        for (u32 m = uncles; m;) {
            int u = lowbit(m);
            m &= m - 1;
            out.emplace_back(v.miner_of(u),
                             (max_d - (double)(hb - v.height(u))) / max_d);
        }
    }
};

// ------------------------------------------------- canonical labeling
// Exact port of cpr_tpu/mdp/generic/canon.py: directed 1-WL refinement
// + individualization search + lexicographically-smallest certificate,
// then (height, canonical position) sort to restore topological ids.

namespace canon {

struct Cert {  // (color, sorted new-id parents) rows, lexicographic
    std::vector<std::pair<int, std::vector<int>>> rows;
    bool operator<(const Cert& o) const { return rows < o.rows; }
};

static void refine(int n, const std::vector<std::vector<int>>& parents,
                   const std::vector<std::vector<int>>& children,
                   std::vector<int>& colors) {
    while (true) {
        bool discrete = true;
        {
            std::vector<int> seen(n, 0);
            std::vector<int> sorted_c(colors);
            std::sort(sorted_c.begin(), sorted_c.end());
            for (int i = 1; i < n; i++)
                if (sorted_c[i] == sorted_c[i - 1]) discrete = false;
            (void)seen;
        }
        if (discrete) return;
        // signature = (color, sorted parent colors, sorted child colors)
        typedef std::tuple<int, std::vector<int>, std::vector<int>> Sig;
        std::vector<Sig> sig(n);
        for (int v = 0; v < n; v++) {
            std::vector<int> pc, cc;
            for (int p : parents[v]) pc.push_back(colors[p]);
            for (int c : children[v]) cc.push_back(colors[c]);
            std::sort(pc.begin(), pc.end());
            std::sort(cc.begin(), cc.end());
            sig[v] = Sig(colors[v], std::move(pc), std::move(cc));
        }
        std::vector<Sig> uniq(sig);
        std::sort(uniq.begin(), uniq.end());
        uniq.erase(std::unique(uniq.begin(), uniq.end()), uniq.end());
        std::vector<int> fresh(n);
        bool changed = false;
        for (int v = 0; v < n; v++) {
            int r = (int)(std::lower_bound(uniq.begin(), uniq.end(), sig[v]) -
                          uniq.begin());
            fresh[v] = r;
            if (r != colors[v]) changed = true;
        }
        if (!changed) return;
        colors.swap(fresh);
    }
}

static Cert certificate(const std::vector<int>& order,
                        const std::vector<std::vector<int>>& parents,
                        const std::vector<int>& orig_colors) {
    int n = (int)order.size();
    std::vector<int> new_id(n);
    for (int i = 0; i < n; i++) new_id[order[i]] = i;
    Cert c;
    c.rows.reserve(n);
    for (int b : order) {
        std::vector<int> ps;
        for (int p : parents[b]) ps.push_back(new_id[p]);
        std::sort(ps.begin(), ps.end());
        c.rows.emplace_back(orig_colors[b], std::move(ps));
    }
    return c;
}

static void search(int n, const std::vector<std::vector<int>>& parents,
                   const std::vector<std::vector<int>>& children,
                   std::vector<int> colors,
                   const std::vector<int>& orig_colors, Cert& best_cert,
                   std::vector<int>& best_order, bool& have_best) {
    refine(n, parents, children, colors);
    // first non-singleton cell by color value
    std::unordered_map<int, std::vector<int>> cells;
    for (int v = 0; v < n; v++) cells[colors[v]].push_back(v);
    std::vector<int> cell_colors;
    for (auto& kv : cells) cell_colors.push_back(kv.first);
    std::sort(cell_colors.begin(), cell_colors.end());
    const std::vector<int>* target = nullptr;
    for (int c : cell_colors)
        if (cells[c].size() > 1) {
            target = &cells[c];
            break;
        }
    if (!target) {
        std::vector<int> order(n);
        for (int i = 0; i < n; i++) order[i] = i;
        std::stable_sort(order.begin(), order.end(),
                         [&](int a, int b) { return colors[a] < colors[b]; });
        Cert c = certificate(order, parents, orig_colors);
        if (!have_best || c < best_cert) {
            best_cert = std::move(c);
            best_order = std::move(order);
            have_best = true;
        }
        return;
    }
    for (int v : *target) {
        std::vector<int> branched(colors);
        branched[v] = n;  // fresh color, larger than every rank
        search(n, parents, children, branched, orig_colors, best_cert,
               best_order, have_best);
    }
}

// returns canonical topologically-sorted order of blocks
static void canonical_order(const Dag& dag, const Derived& dv,
                            const int* colors, std::vector<int>& out) {
    int n = dag.n;
    out.resize(n);
    bool discrete = true;
    {
        u32 seen_bits[8] = {0};  // colors < 256
        for (int b = 0; b < n; b++) {
            int c = colors[b];
            if (seen_bits[c >> 5] & (1u << (c & 31))) {
                discrete = false;
                break;
            }
            seen_bits[c >> 5] |= 1u << (c & 31);
        }
    }
    if (discrete) {
        for (int i = 0; i < n; i++) out[i] = i;
        std::stable_sort(out.begin(), out.end(), [&](int a, int b) {
            if (dv.height[a] != dv.height[b])
                return dv.height[a] < dv.height[b];
            return colors[a] < colors[b];
        });
        return;
    }
    std::vector<std::vector<int>> parents(n), children(n);
    for (int b = 0; b < n; b++)
        for (u32 m = dag.par[b]; m;) {
            int p = lowbit(m);
            m &= m - 1;
            parents[b].push_back(p);
            children[p].push_back(b);
        }
    std::vector<int> orig(colors, colors + n);
    // dense starting ranks
    std::vector<int> uniq(orig);
    std::sort(uniq.begin(), uniq.end());
    uniq.erase(std::unique(uniq.begin(), uniq.end()), uniq.end());
    std::vector<int> start(n);
    for (int v = 0; v < n; v++)
        start[v] = (int)(std::lower_bound(uniq.begin(), uniq.end(), orig[v]) -
                         uniq.begin());
    Cert best_cert;
    std::vector<int> order;
    bool have = false;
    search(n, parents, children, start, orig, best_cert, order, have);
    std::vector<int> pos(n);
    for (int i = 0; i < n; i++) pos[order[i]] = i;
    for (int i = 0; i < n; i++) out[i] = i;
    std::stable_sort(out.begin(), out.end(), [&](int a, int b) {
        if (dv.height[a] != dv.height[b]) return dv.height[a] < dv.height[b];
        return pos[a] < pos[b];
    });
}

}  // namespace canon

// ----------------------------------------------------------------- model

struct Transition {
    double prob;
    State state;
    double reward, progress;
};

struct Model {
    const Proto* proto;
    double alpha, gamma;
    int gc_mode;  // 0 none, 1 simple, 2 judge
    int dag_size_cutoff, height_cutoff;  // -1 = off
    bool merge_iso, truncate_cc, loop_honest, reward_cc, force_consider_own;
    State reset_attacker, reset_defender;  // loop_honest targets

    // scratch
    mutable std::vector<int> hist_a, hist_b;
    mutable std::vector<std::pair<int, double>> cb;

    State initial_state() const {
        State s;
        s.dag = genesis_dag();
        s.avis = s.dvis = 1;
        s.withheld = s.ignored = 0;
        Derived dv;
        derive(s.dag, dv);
        View av{s.dag, dv, s.avis, ATTACKER};
        View dvw{s.dag, dv, s.dvis, DEFENDER};
        s.astate = (int16_t)proto->init(av);
        s.dstate = (int16_t)proto->init(dvw);
        return s;
    }

    void deliver_defender(State& s, const Derived& dv, int block) const {
        s.dvis |= 1u << block;
        View v{s.dag, dv, s.dvis, DEFENDER};
        s.dstate = (int16_t)proto->update(v, s.dstate, block);
    }
    void do_consider(State& s, const Derived& dv, int block) const {
        s.ignored &= ~(1u << block);
        s.avis |= 1u << block;
        View v{s.dag, dv, s.avis, ATTACKER};
        s.astate = (int16_t)proto->update(v, s.astate, block);
    }
    void do_release(State& s, int block) const {
        s.withheld &= ~(1u << block);
    }
    u32 just_released(const State& s) const {
        return (s.dag.atk & ~s.withheld & ~s.dvis) & ~1u;
    }
    u32 defender_fresh(const State& s) const {
        u32 def = s.dag.all_mask() & ~s.dag.atk & ~1u;
        return def & ~s.dvis;
    }
    void do_communication(State& s, const Derived& dv, bool atk_fast) const {
        u32 rel = just_released(s), fresh = defender_fresh(s);
        u32 first = atk_fast ? rel : fresh, second = atk_fast ? fresh : rel;
        for (u32 m = first; m;) {
            deliver_defender(s, dv, lowbit(m));
            m &= m - 1;
        }
        for (u32 m = second; m;) {
            deliver_defender(s, dv, lowbit(m));
            m &= m - 1;
        }
    }
    void mine(State& s, const Derived& dv, int miner) const {
        if (miner == ATTACKER) {
            View v{s.dag, dv, s.avis, ATTACKER};
            u32 parents = proto->mining(v, s.astate);
            int b = dag_append(s.dag, parents, ATTACKER);
            s.ignored |= 1u << b;
            s.withheld |= 1u << b;
            if (force_consider_own) {
                Derived dv2;
                derive(s.dag, dv2);
                do_consider(s, dv2, b);
            }
            return;
        }
        View v{s.dag, dv, s.dvis, DEFENDER};
        u32 parents = proto->mining(v, s.dstate);
        int b = dag_append(s.dag, parents, DEFENDER);
        s.ignored |= 1u << b;
    }

    u32 to_release(const State& s) const {
        u32 out = 0;
        for (u32 m = s.withheld; m;) {
            int b = lowbit(m);
            m &= m - 1;
            if (!(s.dag.par[b] & s.withheld)) out |= 1u << b;
        }
        return out;
    }
    u32 to_consider(const State& s) const {
        u32 out = 0;
        for (u32 m = s.ignored; m;) {
            int b = lowbit(m);
            m &= m - 1;
            if (!(s.dag.par[b] & s.ignored)) out |= 1u << b;
        }
        return out;
    }

    // actions encoded: kind*64 + block; kinds 0 consider, 1 release, 2 cont
    void actions(const State& s, std::vector<int>& out) const {
        out.clear();
        if (height_cutoff >= 0) {
            Derived dv;
            derive(s.dag, dv);
            int mx = 0;
            for (int b = 0; b < s.dag.n; b++)
                if (dv.height[b] > mx) mx = dv.height[b];
            if (mx >= height_cutoff) {
                out.push_back(honest(s));
                return;
            }
        }
        if (dag_size_cutoff >= 0 && s.dag.n >= dag_size_cutoff) {
            out.push_back(honest(s));
            return;
        }
        for (u32 m = to_consider(s); m;) {
            out.push_back(0 * 64 + lowbit(m));
            m &= m - 1;
        }
        for (u32 m = to_release(s); m;) {
            out.push_back(1 * 64 + lowbit(m));
            m &= m - 1;
        }
        out.push_back(2 * 64);
    }
    int honest(const State& s) const {
        u32 tc = to_consider(s);
        if (tc) return 0 * 64 + lowbit(tc);
        u32 tr = to_release(s);
        if (tr) return 1 * 64 + lowbit(tr);
        return 2 * 64;
    }

    void measure(const State& s, const Derived& dv, const int* hist, int nh,
                 double& rew, double& prg) const {
        View v{s.dag, dv, s.dvis, DEFENDER};
        rew = prg = 0.0;
        for (int i = 0; i < nh; i++) {
            int b = hist[i];
            prg += proto->progress(v, b);
            proto->coinbase(v, b, cb);
            for (auto& mc : cb)
                if (mc.first == ATTACKER) rew += mc.second;
        }
    }

    State relabel_state(const State& s, const std::vector<int>& order) const {
        int new_ids[MAXN];
        for (int i = 0; i < MAXN; i++) new_ids[i] = -1;
        for (size_t i = 0; i < order.size(); i++) new_ids[order[i]] = (int)i;
        State o;
        o.dag.n = (uint8_t)order.size();
        o.dag.atk = 0;
        auto remap = [&](u32 mask) {
            u32 out = 0;
            for (u32 m = mask; m;) {
                int b = lowbit(m);
                m &= m - 1;
                if (new_ids[b] >= 0) out |= 1u << new_ids[b];
            }
            return out;
        };
        for (size_t i = 0; i < order.size(); i++) {
            int b = order[i];
            u32 ps = 0;
            for (u32 m = s.dag.par[b]; m;) {
                int p = lowbit(m);
                m &= m - 1;
                if (new_ids[p] >= 0) ps |= 1u << new_ids[p];
            }
            o.dag.par[i] = ps;
            if (i > 0 && (s.dag.atk >> b & 1)) o.dag.atk |= 1u << i;
        }
        o.avis = remap(s.avis);
        o.dvis = remap(s.dvis);
        o.withheld = remap(s.withheld);
        o.ignored = remap(s.ignored);
        o.astate = s.astate >= 0 ? (int16_t)proto->relabel(s.astate, new_ids)
                                 : s.astate;
        o.dstate = s.dstate >= 0 ? (int16_t)proto->relabel(s.dstate, new_ids)
                                 : s.dstate;
        return o;
    }

    State gc(const State& s) const {
        Derived dv;
        derive(s.dag, dv);
        u32 every = s.dag.all_mask();
        u32 keep = (every & ~s.avis) | (every & ~s.dvis);
        View av{s.dag, dv, s.avis, ATTACKER};
        View dw{s.dag, dv, s.dvis, DEFENDER};
        keep |= proto->keep(av, s.astate);
        keep |= proto->keep(dw, s.dstate);
        if (gc_mode == 2) {  // judge
            int dstate = s.dstate;
            u32 dvis = s.dvis;
            for (u32 m = every & ~dvis; m;) {
                int b = lowbit(m);
                m &= m - 1;
                dvis |= 1u << b;
                View v{s.dag, dv, dvis, DEFENDER};
                dstate = proto->update(v, dstate, b);
            }
            View v{s.dag, dv, dvis, DEFENDER};
            keep |= proto->keep(v, dstate);
        }
        keep |= 1;  // genesis
        u32 closed = keep;
        for (u32 m = keep; m;) {
            closed |= dv.past[lowbit(m)];
            m &= m - 1;
        }
        if (closed == every) return s;
        std::vector<int> order;
        for (u32 m = closed; m;) {
            order.push_back(lowbit(m));
            m &= m - 1;
        }
        return relabel_state(s, order);
    }

    // returns truncated state; cut history prefix in `cut`
    State truncate(const State& s, std::vector<int>& cut) const {
        cut.clear();
        Derived dv;
        derive(s.dag, dv);
        View av{s.dag, dv, s.avis, ATTACKER};
        View dw{s.dag, dv, s.dvis, DEFENDER};
        proto->history(av, s.astate, hist_a);
        proto->history(dw, s.dstate, hist_b);
        int next_genesis = 0;
        int lim = (int)std::min(hist_a.size(), hist_b.size());
        for (int i = 1; i < lim; i++) {
            int b = hist_a[i];
            if (b != hist_b[i]) break;
            u32 past = dv.past[b];
            u32 past_and_b = past | (1u << b);
            bool viable = true;
            for (u32 m = past; m && viable;) {
                int p = lowbit(m);
                m &= m - 1;
                if (dv.children[p] & ~past_and_b) viable = false;
            }
            if (viable) next_genesis = b;
        }
        if (next_genesis == 0) return s;
        for (size_t i = 1; i < hist_b.size(); i++) {
            cut.push_back(hist_b[i]);
            if (hist_b[i] == next_genesis) break;
        }
        u32 keep_mask =
            (1u << next_genesis) | future_of(dv, s.dag.n, next_genesis);
        std::vector<int> order;
        for (u32 m = keep_mask; m;) {
            order.push_back(lowbit(m));
            m &= m - 1;
        }
        return relabel_state(s, order);
    }

    State loop_honest_snap(const State& s) const {
        int last = s.dag.n - 1;
        if (last == 0) return s;
        u32 every = s.dag.all_mask();
        u32 last_bit = 1u << last;
        auto common = [&](const State& loop_state) -> State {
            if (s.dvis != (every & ~last_bit)) return s;
            Derived dv;
            derive(s.dag, dv);
            View av{s.dag, dv, s.avis, ATTACKER};
            View dw{s.dag, dv, s.dvis, DEFENDER};
            proto->history(av, s.astate, hist_a);
            proto->history(dw, s.dstate, hist_b);
            if (hist_a != hist_b) return s;
            u32 hist_mask = 0;
            for (size_t i = 0; i + 1 < hist_b.size(); i++)
                hist_mask |= 1u << hist_b[i];
            if (hist_mask != dv.past[hist_b.back()]) return s;
            return loop_state;
        };
        if (s.dag.miner_of(last) == ATTACKER && s.withheld == last_bit &&
            s.ignored == last_bit && s.avis == (every & ~last_bit))
            return common(reset_attacker);
        if (s.dag.miner_of(last) == DEFENDER && s.withheld == 0 &&
            s.ignored == last_bit && s.avis == (every & ~last_bit))
            return common(reset_defender);
        return s;
    }

    State normalize(const State& s) const {
        if (!merge_iso) return s;
        Derived dv;
        derive(s.dag, dv);
        View av{s.dag, dv, s.avis, ATTACKER};
        View dw{s.dag, dv, s.dvis, DEFENDER};
        int colors[MAXN];
        for (int b = 0; b < s.dag.n; b++) {
            int c = b == 0 ? 0 : (1 + s.dag.miner_of(b));
            c |= ((s.dvis >> b) & 1) << 2;
            c |= ((s.avis >> b) & 1) << 3;
            c |= ((s.withheld >> b) & 1) << 4;
            c |= ((s.ignored >> b) & 1) << 5;
            if (s.dvis & (1u << b))
                c |= proto->color(dw, s.dstate, b) << 6;
            if (s.avis & (1u << b))
                c |= proto->color(av, s.astate, b) << 7;
            colors[b] = c;
        }
        std::vector<int> order;
        canon::canonical_order(s.dag, dv, colors, order);
        bool identity = true;
        for (int i = 0; i < s.dag.n; i++)
            if (order[i] != i) {
                identity = false;
                break;
            }
        if (identity) return s;
        return relabel_state(s, order);
    }

    // defender-view measurement of a state's full history — hoisted out
    // of finalize so the BFS pays it once per state, not once per action
    void measure_state(const State& s, double& rew, double& prg) const {
        rew = prg = 0.0;
        if (reward_cc) return;
        Derived dv;
        derive(s.dag, dv);
        View dw{s.dag, dv, s.dvis, DEFENDER};
        proto->history(dw, s.dstate, hist_a);
        std::vector<int> h(hist_a);
        measure(s, dv, h.data() + 1, (int)h.size() - 1, rew, prg);
    }

    void finalize(const State& old, std::vector<Transition>& cases,
                  double old_rew, double old_prg) const {
        for (auto& t : cases) {
            double rew = 0.0, prg = 0.0;
            if (!reward_cc) {
                Derived dv;
                derive(t.state.dag, dv);
                View dw{t.state.dag, dv, t.state.dvis, DEFENDER};
                proto->history(dw, t.state.dstate, hist_a);
                std::vector<int> h(hist_a);
                double nr, np;
                measure(t.state, dv, h.data() + 1, (int)h.size() - 1, nr, np);
                rew = nr - old_rew;
                prg = np - old_prg;
            }
            if (gc_mode) t.state = gc(t.state);
            if (loop_honest) t.state = loop_honest_snap(t.state);
            if (truncate_cc) {
                State pre = t.state;
                std::vector<int> cut;
                t.state = truncate(t.state, cut);
                if (reward_cc) {
                    Derived dv;
                    derive(pre.dag, dv);
                    measure(pre, dv, cut.data(), (int)cut.size(), rew, prg);
                }
            }
            t.state = normalize(t.state);
            t.reward = rew;
            t.progress = prg;
        }
    }

    void apply(int action, const State& s, std::vector<Transition>& out,
               double old_rew, double old_prg) const {
        out.clear();
        int kind = action / 64, block = action % 64;
        Derived dv;
        derive(s.dag, dv);
        if (kind == 1) {  // release
            State n = s;
            do_release(n, block);
            out.push_back({1.0, n, 0.0, 0.0});
        } else if (kind == 0) {  // consider
            State n = s;
            do_consider(n, dv, block);
            out.push_back({1.0, n, 0.0, 0.0});
        } else {  // continue
            const double a = alpha, g = gamma;
            const double pc[2] = {g, 1.0 - g};
            const bool fast[2] = {true, false};
            const double pm[2] = {a, 1.0 - a};
            const int who[2] = {ATTACKER, DEFENDER};
            for (int ci = 0; ci < 2; ci++)
                for (int mi = 0; mi < 2; mi++) {
                    double p = pc[ci] * pm[mi];
                    if (p == 0.0) continue;
                    State n = s;
                    do_communication(n, dv, fast[ci]);
                    Derived dv2;
                    derive(n.dag, dv2);
                    mine(n, dv2, who[mi]);
                    out.push_back({p, n, 0.0, 0.0});
                }
        }
        finalize(s, out, old_rew, old_prg);
    }
};

// ----------------------------------------------------------------- BFS

struct Result {
    std::vector<int32_t> src, act, dst;
    std::vector<double> prob, reward, progress;
    std::vector<int32_t> start_sid;
    std::vector<double> start_p;
    int64_t n_states = 0;
    std::string error;
};

// thread_local: ctypes releases the GIL during gmc_compile, so two
// Python threads can compile concurrently; a shared global would let
// one thread's failure message clobber the other's nullptr-path report
static thread_local std::string g_last_error;

static Result* compile_impl(const std::string& proto_name, int k,
                            double alpha, double gamma, int dag_cutoff,
                            int height_cutoff, int gc_mode, int merge_iso,
                            int truncate_cc, int loop_honest, int reward_cc,
                            int force_consider_own, int64_t max_states) {
    // the BFS can transiently grow a DAG a few blocks past the cutoff
    // (post-cutoff honest mining before GC/truncation shrinks it), so
    // demand head-room against the u32-mask width rather than abort
    if (dag_cutoff < 0 && height_cutoff < 0) {
        g_last_error = "need dag_size_cutoff or traditional_height_cutoff "
                       "(the state space is unbounded without one)";
        return nullptr;
    }
    if (dag_cutoff > MAXN - 4) {
        g_last_error = "dag_size_cutoff too large for the native compiler: "
                       "max " + std::to_string(MAXN - 4) + " (DAGs are u" +
                       std::to_string(8 * sizeof(u32)) + " bitmasks capped "
                       "at MAXN=" + std::to_string(MAXN) + " blocks, with 4 "
                       "blocks of BFS head-room); use the Python compiler "
                       "for larger cutoffs";
        return nullptr;
    }
    // the Python anchor's constructor-time flag validation (model.py:97-102)
    if (truncate_cc && loop_honest) {
        g_last_error = "choose either truncate_common_chain or loop_honest";
        return nullptr;
    }
    if (reward_cc && !truncate_cc) {
        g_last_error = "reward_common_chain requires truncate_common_chain";
        return nullptr;
    }
    Proto* proto;
    if (proto_name == "bitcoin")
        proto = new Bitcoin();
    else if (proto_name == "ghostdag")
        proto = new GhostDag(k);
    else if (proto_name == "parallel")
        proto = new Parallel(k);
    else if (proto_name == "ethereum")
        proto = new Ethereum(k > 0 ? k : 7);
    else if (proto_name == "byzantium")
        proto = new Byzantium(k > 0 ? k : 7);
    else {
        g_last_error = "unknown protocol: " + proto_name;
        return nullptr;
    }

    Model m;
    m.proto = proto;
    m.alpha = alpha;
    m.gamma = gamma;
    m.gc_mode = gc_mode;
    m.dag_size_cutoff = dag_cutoff;
    m.height_cutoff = height_cutoff;
    m.merge_iso = merge_iso != 0;
    m.truncate_cc = truncate_cc != 0;
    m.loop_honest = loop_honest != 0;
    m.reward_cc = reward_cc != 0;
    m.force_consider_own = force_consider_own != 0;

    auto* res = new Result();

    std::unordered_map<State, int32_t, StateHash> ids;
    std::vector<State> queue_states;  // BFS by index
    auto id_of = [&](const State& s) -> int32_t {
        auto it = ids.find(s);
        if (it != ids.end()) return it->second;
        int32_t sid = (int32_t)ids.size();
        ids.emplace(s, sid);
        queue_states.push_back(s);
        return sid;
    };

    // start states
    if (m.loop_honest) {
        State init = m.initial_state();
        Derived dv;
        derive(init.dag, dv);
        State ra = init;
        m.mine(ra, dv, ATTACKER);
        m.reset_attacker = m.normalize(ra);
        State rd = init;
        m.mine(rd, dv, DEFENDER);
        m.reset_defender = m.normalize(rd);
        res->start_sid.push_back(id_of(m.reset_attacker));
        res->start_p.push_back(alpha);
        res->start_sid.push_back(id_of(m.reset_defender));
        res->start_p.push_back(1.0 - alpha);
    } else {
        State s0 = m.normalize(m.initial_state());
        res->start_sid.push_back(id_of(s0));
        res->start_p.push_back(1.0);
    }

    std::vector<int> acts;
    std::vector<Transition> trans;
    try {
    for (size_t qi = 0; qi < queue_states.size(); qi++) {
        if ((int64_t)ids.size() > max_states) {
            res->error = "state cap exceeded";
            g_last_error = res->error;
            delete proto;
            return res;  // partial result flagged by error
        }
        State s = queue_states[qi];  // copy: vector may reallocate
        int32_t sid = (int32_t)qi;
        m.actions(s, acts);
        double old_rew, old_prg;
        m.measure_state(s, old_rew, old_prg);
        for (size_t ai = 0; ai < acts.size(); ai++) {
            m.apply(acts[ai], s, trans, old_rew, old_prg);
            double total = 0.0;
            for (auto& t : trans) total += t.prob;
            if (std::fabs(total - 1.0) > 1e-9) {
                res->error = "probabilities do not sum to one";
                g_last_error = res->error;
                delete proto;
                return res;
            }
            for (auto& t : trans) {
                res->src.push_back(sid);
                res->act.push_back((int32_t)ai);
                res->dst.push_back(id_of(t.state));
                res->prob.push_back(t.prob);
                res->reward.push_back(t.reward);
                res->progress.push_back(t.progress);
            }
        }
    }
    } catch (const DagOverflow&) {
        res->error = "DAG exceeded the native mask width (MAXN blocks); "
                     "lower the cutoff or use the Python compiler";
        g_last_error = res->error;
        delete proto;
        return res;
    }
    res->n_states = (int64_t)ids.size();
    delete proto;
    return res;
}

extern "C" {

void* gmc_compile(const char* proto, int k, double alpha, double gamma,
                  int dag_cutoff, int height_cutoff, int gc_mode,
                  int merge_iso, int truncate_cc, int loop_honest,
                  int reward_cc, int force_consider_own, int64_t max_states) {
    try {
        Result* r = compile_impl(proto ? proto : "", k, alpha, gamma,
                                 dag_cutoff, height_cutoff, gc_mode,
                                 merge_iso, truncate_cc, loop_honest,
                                 reward_cc, force_consider_own, max_states);
        return (void*)r;
    } catch (const std::exception& e) {
        g_last_error = e.what();
        return nullptr;
    }
}

int64_t gmc_n_states(void* h) { return ((Result*)h)->n_states; }
int64_t gmc_n_transitions(void* h) {
    return (int64_t)((Result*)h)->src.size();
}
int64_t gmc_n_start(void* h) {
    return (int64_t)((Result*)h)->start_sid.size();
}
const char* gmc_error(void* h) {
    return h ? ((Result*)h)->error.c_str() : g_last_error.c_str();
}

void gmc_copy(void* h, int32_t* src, int32_t* act, int32_t* dst,
              double* prob, double* reward, double* progress) {
    Result* r = (Result*)h;
    size_t n = r->src.size();
    std::memcpy(src, r->src.data(), n * sizeof(int32_t));
    std::memcpy(act, r->act.data(), n * sizeof(int32_t));
    std::memcpy(dst, r->dst.data(), n * sizeof(int32_t));
    std::memcpy(prob, r->prob.data(), n * sizeof(double));
    std::memcpy(reward, r->reward.data(), n * sizeof(double));
    std::memcpy(progress, r->progress.data(), n * sizeof(double));
}

void gmc_copy_start(void* h, int32_t* sid, double* p) {
    Result* r = (Result*)h;
    std::memcpy(sid, r->start_sid.data(),
                r->start_sid.size() * sizeof(int32_t));
    std::memcpy(p, r->start_p.data(), r->start_p.size() * sizeof(double));
}

void gmc_free(void* h) { delete (Result*)h; }

}  // extern "C"
