"""Crash flight recorder: dump telemetry's in-process event ring.

`cpr_tpu.telemetry` keeps the last N emitted events in a bounded ring
(always on — one deque.append riding the emit path, sink or no sink).
This module turns that ring into a post-mortem artifact: one atomic
JSONL file, `blackbox-<run_id>-<pid>.jsonl`, whose first line is a
fresh run manifest (backend-bearing, so `tools/trace_summary.py
--validate` accepts the dump standalone) followed by the recorded
events oldest-first.  The write goes through
`resilience.atomic_write_text` — a dump can be torn by a second crash
mid-write, but the published file never can.

Dump triggers (wired in this PR): preemption drains, supervisor
escalations, unhandled exceptions unwinding the serve/router mains,
and `CPR_FAULT_INJECT` kills (InjectedKill unwinds like the crash it
stands in for, so the main-wrapper trigger catches it).  `dump_blackbox`
itself never raises — a broken dump on a crash path must not mask the
original failure — and returns the path written, or None.

The ring lives in telemetry and the dump here because of the import
order: resilience imports telemetry, so telemetry cannot import
resilience back for the atomic write.
"""

from __future__ import annotations

import json
import logging
import os

from cpr_tpu import resilience, telemetry

log = logging.getLogger(__name__)

# where dumps land: $CPR_BLACKBOX_DIR, else ./runs (next to the perf
# ledger and the smoke artifacts)
BLACKBOX_DIR_ENV_VAR = "CPR_BLACKBOX_DIR"
DEFAULT_BLACKBOX_DIR = "runs"


def blackbox_dir() -> str:
    return os.environ.get(BLACKBOX_DIR_ENV_VAR) or DEFAULT_BLACKBOX_DIR


def blackbox_path(dest_dir: str | None = None) -> str:
    """This process's dump path: one file per (run, pid), so a fleet's
    replicas never clobber each other's blackboxes."""
    d = dest_dir or blackbox_dir()
    return os.path.join(
        d, f"blackbox-{telemetry.run_id()}-{os.getpid()}.jsonl")


def dump_blackbox(reason: str, dest_dir: str | None = None) -> str | None:
    """Write the flight-recorder ring to the blackbox file.  Header
    manifest first (its config carries the dump reason + ring stats),
    then the recorded tail oldest-first.  Never raises; returns the
    written path or None."""
    try:
        events = telemetry.blackbox_events()
        man = telemetry.run_manifest(config=dict(
            entry="blackbox", reason=str(reason), pid=os.getpid(),
            n_events=len(events),
            capacity=telemetry.blackbox_capacity()))
        lines = [json.dumps(man, default=str)]
        lines += [json.dumps(e, default=str) for e in events]
        path = blackbox_path(dest_dir)
        resilience.atomic_write_text(path, "\n".join(lines) + "\n")
        return path
    except Exception as e:  # noqa: BLE001 — the dump rides crash
        # paths: it must never mask the failure it is recording
        log.warning("blackbox dump failed: %r", e)
        return None
