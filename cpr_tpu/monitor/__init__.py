"""Live fleet health plane (schema v14): metrics registry + exposition,
SLO burn-rate alerts, and the crash flight recorder.

Everything observability built before this package is post-hoc or
request-scoped: telemetry JSONL is read back by tools/trace_summary.py
after the run, latency quantiles surface only in `stats` replies and
drain reports, and the perf ledger judges rows after banking.  This
package is the *live* side — a pull-based signal plane the serving
layer (and eventually the autoscaler / the real-hardware campaign of
ROADMAP items 3 and 5) reads while the run is still in flight:

* `registry`  — process-local MetricsRegistry: counters, gauges, and
  the existing `cpr_tpu.latency` histograms, rendered as Prometheus
  text (stdlib only) or structured JSON.
* `expo`      — the `--metrics-port` HTTP endpoint (daemon-thread
  `http.server`, zero new deps).
* `alerts`    — multi-window SLO burn-rate evaluation over shed rate
  and per-class p99, emitting typed v14 `alert` events.
* `blackbox`  — dumps telemetry's in-process flight-recorder ring to
  an atomic `runs/blackbox-<run_id>-<pid>.jsonl` on crashes, so a
  wedged run leaves a readable last-N-events artifact.

Like telemetry/latency/perf, every module here is jax-free at import
(tests/test_observability.py enforces the pattern).
"""

from cpr_tpu.monitor.alerts import AlertEngine, emit_alert  # noqa: F401
from cpr_tpu.monitor.blackbox import dump_blackbox  # noqa: F401
from cpr_tpu.monitor.expo import MetricsServer  # noqa: F401
from cpr_tpu.monitor.registry import MetricsRegistry  # noqa: F401
