"""The `--metrics-port` HTTP exposition endpoint.

A stdlib `ThreadingHTTPServer` on a daemon thread serving GET
`/metrics` (and `/`) as Prometheus text format 0.0.4 — zero new
dependencies, invisible to the asyncio serve loop.  The server takes a
`render` callable rather than a registry so a process can compose its
payload (the router concatenates its own registry with fleet-board
gauges); whatever `render` returns at scrape time is the body, so the
exposition is always as live as the underlying counters.

Port 0 binds an ephemeral port; `start()` returns the bound port and
callers publish it (the serve ready-file gains a `metrics_port` key)
so scrapers can find it without a fixed allocation.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from cpr_tpu.monitor.registry import PROMETHEUS_CONTENT_TYPE

log = logging.getLogger(__name__)


class MetricsServer:
    """Daemon-thread HTTP scrape endpoint around one render callable."""

    def __init__(self, render, host: str = "127.0.0.1", port: int = 0):
        self._render = render
        self.host = host
        self.port = port  # replaced by the bound port after start()
        self._httpd = None
        self._thread = None

    def start(self) -> int:
        render = self._render

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = render().encode("utf-8")
                except Exception as e:  # noqa: BLE001 — a broken render
                    # must 500 the scrape, never kill the serve process
                    log.warning("metrics render failed: %r", e)
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are not stderr news
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="cpr-metrics",
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
