"""Multi-window SLO burn-rate alerting for the serving layer.

Classic error-budget alerting (the SRE-workbook shape) adapted to the
serve tick loop: each signal is judged over a FAST and a SLOW window
pair, both scaled from the deployment's own `--slo-s`.  A fast-window
breach at a high burn rate pages (the budget is vanishing in minutes);
a slow-window breach at a low burn rate files a ticket (the budget is
bleeding).  Two signals:

* `shed_rate`     — shed fraction of admission decisions over the
  window, judged against the shed error budget (default 2%).
* `p99_over_slo`  — per-priority-class p99 total latency over the
  window, judged against that class's SLO budget (`slo_s` times the
  server's per-class scale — the same budgets admission control sheds
  against).
* `snapshot_staleness` — age of the serving policy snapshot (v17
  always-on learning, docs/LEARNING.md), judged against
  `staleness_slo_s`.  A gauge, not a rate: the latest recorded
  reading IS the window value (no min-samples floor — one stale
  reading is already a fact), so a dead learner or a wedged publish
  pipeline burns the budget within one heartbeat of breaching.

`burn_rate = value / budget`; an alert fires when it crosses the
window's threshold.  Evaluation is cheap enough for every heartbeat:
samples live in bounded deques, a window evaluation is one pass.
Breaches emit the typed v14 `alert` event (one call site,
`emit_alert`, carrying every EVENT_FIELDS-declared field) and surface
as an `alerts` block in heartbeat/stats/drain-report — the trigger
surface ROADMAP item 3's autoscaler will subscribe to.

Re-fire is cooldown-gated per (signal, class, window): an alert
re-emits at most once per window length while the breach persists, so
a sustained breach is a handful of events, not one per tick.  A signal
with no budget, an empty window, or a `None` value is SKIPPED
explicitly — `None` never reaches burn-rate math (the empty-histogram
edge the v14 satellite pins with tests).
"""

from __future__ import annotations

from collections import deque

from cpr_tpu import telemetry

# severity thresholds: a fast-window breach must burn hard to page; a
# slow-window breach files a ticket at any over-budget burn
PAGE_BURN = 4.0
TICKET_BURN = 1.0
# default shed error budget: 2% of admission decisions may shed
# before the budget is considered burning
DEFAULT_SHED_BUDGET = 0.02
# windows need this many samples before a rate/quantile means anything
MIN_SAMPLES = 8
# per-signal sample retention (bounded: the engine's memory is
# O(max_samples) however long the process lives)
MAX_SAMPLES = 4096


def default_windows(slo_s: float) -> tuple:
    """(window_s, severity, burn threshold) pairs scaled from the SLO:
    fast ~10 SLOs (floored at 5 s, capped at 5 min) pages, slow ~60
    SLOs (floored at 30 s, capped at 1 h) tickets."""
    s = float(slo_s)
    fast = min(300.0, max(5.0, 10.0 * s))
    slow = min(3600.0, max(30.0, 60.0 * s))
    return ((fast, "page", PAGE_BURN), (slow, "ticket", TICKET_BURN))


def burn_rate(value, budget):
    """value/budget, or None when either side is missing or the budget
    is non-positive — the one place alert math meets missing data."""
    if value is None or budget is None or budget <= 0:
        return None
    return float(value) / float(budget)


def emit_alert(alert: dict):
    """The one typed v14 `alert` event call site
    (EVENT_FIELDS['alert'])."""
    telemetry.current().event(
        "alert", signal=alert["signal"], severity=alert["severity"],
        window_s=alert["window_s"], value=alert["value"],
        budget=alert["budget"], burn_rate=alert["burn_rate"],
        cls=alert.get("cls"), threshold=alert.get("threshold"),
        slo_s=alert.get("slo_s"))


class AlertEngine:
    """Windowed burn-rate evaluation over shed rate + per-class p99."""

    def __init__(self, slo_s: float | None = None, *,
                 shed_budget: float = DEFAULT_SHED_BUDGET,
                 class_slo: dict | None = None,
                 staleness_slo_s: float | None = None, windows=None,
                 min_samples: int = MIN_SAMPLES,
                 max_samples: int = MAX_SAMPLES, now_fn=telemetry.now):
        self.slo_s = slo_s
        self.shed_budget = shed_budget
        # snapshot-age budget for the always-on-learning deployments;
        # None (the default) skips the signal entirely
        self.staleness_slo_s = staleness_slo_s
        # class -> latency budget in seconds (the server passes its
        # admission-control budgets); classes without one fall back to
        # the raw slo_s, and with neither the signal is skipped
        self.class_slo = dict(class_slo or {})
        self.windows = tuple(windows) if windows is not None else \
            default_windows(slo_s if slo_s else 1.0)
        self.min_samples = min_samples
        self.max_samples = max_samples
        self._now = now_fn
        self._admissions: deque = deque(maxlen=max_samples)
        self._latencies: dict[str, deque] = {}
        self._staleness: deque = deque(maxlen=max_samples)
        self._active: dict[tuple, dict] = {}
        self._last_emit: dict[tuple, float] = {}
        self.n_fired = 0

    # -- feed ------------------------------------------------------------

    def record_admission(self, shed: bool):
        """One admission decision (admit or shed), any op."""
        self._admissions.append((self._now(), 1 if shed else 0))

    def record_latency(self, cls: str, dur_s):
        """One completed request's total latency for priority class
        `cls`.  None durations are dropped here, at the door."""
        if not isinstance(dur_s, (int, float)):
            return
        dq = self._latencies.get(cls)
        if dq is None:
            dq = self._latencies[cls] = deque(maxlen=self.max_samples)
        dq.append((self._now(), float(dur_s)))

    def record_staleness(self, staleness_s):
        """One snapshot-staleness reading (seconds since the serving
        policy last swapped); sampled per heartbeat by the server."""
        if not isinstance(staleness_s, (int, float)):
            return
        self._staleness.append((self._now(), float(staleness_s)))

    # -- evaluation ------------------------------------------------------

    def _signals(self, t: float, window_s: float):
        """(signal, cls, value, budget) readings over one window;
        under-sampled or budget-less signals are skipped, never
        yielded with None."""
        cut = t - window_s
        decisions = [s for ts, s in self._admissions if ts >= cut]
        if len(decisions) >= self.min_samples:
            yield ("shed_rate", None,
                   sum(decisions) / len(decisions), self.shed_budget)
        for cls, dq in sorted(self._latencies.items()):
            budget = self.class_slo.get(cls, self.slo_s)
            if budget is None or budget <= 0:
                continue
            durs = sorted(d for ts, d in dq if ts >= cut)
            if len(durs) < self.min_samples:
                continue
            p99 = durs[min(len(durs) - 1, int(0.99 * len(durs)))]
            yield ("p99_over_slo", cls, p99, budget)
        if self.staleness_slo_s is not None:
            readings = [v for ts, v in self._staleness if ts >= cut]
            if readings:  # gauge: the latest reading is the value
                yield ("snapshot_staleness", None, readings[-1],
                       self.staleness_slo_s)

    def evaluate(self) -> list[dict]:
        """Judge every (window, signal) pair now.  Returns the alerts
        to EMIT this round (breaches past their cooldown); `active`
        tracks every currently-breaching pair regardless."""
        t = self._now()
        out = []
        for window_s, severity, threshold in self.windows:
            for signal, cls, value, budget in self._signals(t, window_s):
                burn = burn_rate(value, budget)
                key = (signal, cls, window_s)
                if burn is None or burn < threshold:
                    self._active.pop(key, None)
                    continue
                alert = {"signal": signal, "cls": cls,
                         "severity": severity, "window_s": window_s,
                         "value": value, "budget": budget,
                         "burn_rate": burn, "threshold": threshold,
                         "slo_s": self.slo_s}
                self._active[key] = alert
                last = self._last_emit.get(key)
                if last is None or t - last >= window_s:
                    self._last_emit[key] = t
                    self.n_fired += 1
                    out.append(alert)
        return out

    def summary(self) -> dict:
        """The `alerts` block for heartbeat/stats/drain-report:
        currently-breaching alerts plus the lifetime fired count."""
        active = sorted(
            self._active.values(),
            key=lambda a: (a["signal"], str(a["cls"]), a["window_s"]))
        return {"active": [dict(a) for a in active],
                "fired": self.n_fired}
