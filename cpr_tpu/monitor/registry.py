"""Process-local live metrics registry with Prometheus-text exposition.

One `MetricsRegistry` per process, fed incrementally by the serving
layer's existing telemetry hooks: counters (`inc`), gauges (`set`),
and histograms — which are NOT a new type but the existing
`cpr_tpu.latency.LatencyBoard` attached by reference (`attach_board`),
so the registry renders live bucket counts without a second observe
path.  Exposed two ways, both zero-dependency:

* `render_prometheus()` — text format 0.0.4 for the `--metrics-port`
  HTTP endpoint (cpr_tpu/monitor/expo.py).  Histogram `le` buckets
  are cumulative sums over the board's log-scale bins; the half-open
  `[e_{i-1}, e_i)` bins make `le` an "< edge" approximation, which is
  inside the board's own ~7% quantile-interpolation error.
* `to_json()` — the same data structured, returned by the in-band
  `metrics.scrape` serve op.  Includes each board's raw mergeable
  wire form (`LatencyBoard.to_dict`), which is what the router
  bucket-sums into the fleet board.

Cardinality is bounded exactly like the latency board: at most
`max_series` label combinations per metric name; later novel
combinations fold into one series whose every label value is
`OVERFLOW_FAMILY` — explicit in the exposition, never dropped.
Empty histograms render explicitly (all-zero buckets, `_count 0`,
no quantile-derived values), so a `None` quantile can never leak
into the text format.

Thread-safety: mutations and renders take one lock — the HTTP
exposition thread scrapes while the asyncio loop updates.
"""

from __future__ import annotations

import threading

from cpr_tpu.latency import (DEFAULT_MAX_FAMILIES, OVERFLOW_FAMILY,
                             LatencyBoard)

# Prometheus text format 0.0.4 content type (the version is part of
# the grammar contract the fleet smoke parses against)
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(v) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v) -> str:
    """A Prometheus-parseable sample value: integral floats print as
    integers, everything else as repr (Go-float parseable)."""
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(items) -> str:
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
    return "{" + body + "}"


class _Family:
    """One metric name: its kind, help text, and label-keyed series."""

    __slots__ = ("kind", "help", "series")

    def __init__(self, kind: str, help_text: str):
        self.kind = kind
        self.help = help_text
        self.series: dict[tuple, float] = {}


class MetricsRegistry:
    """Counters + gauges + attached latency boards, rendered live."""

    def __init__(self, namespace: str = "cpr",
                 const_labels: dict | None = None,
                 max_series: int = DEFAULT_MAX_FAMILIES):
        if max_series <= 0:
            raise ValueError(f"max_series must be positive, "
                             f"got {max_series}")
        self.namespace = namespace
        self.const_labels = {str(k): str(v)
                             for k, v in (const_labels or {}).items()}
        self.max_series = max_series
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}
        # name -> (board, help, label name for the board's family key)
        self._boards: dict[str, tuple] = {}

    # -- feed ------------------------------------------------------------

    def _series_key(self, family: _Family, labels: dict) -> tuple:
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        if key in family.series or len(family.series) < self.max_series:
            return key
        # past the cap: fold the label VALUES into the explicit
        # overflow marker (same escape hatch as LatencyBoard) — the
        # folded series aggregates everything novel, visibly
        return tuple((k, OVERFLOW_FAMILY) for k, _ in key)

    def _family(self, name: str, kind: str, help_text) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = _Family(
                kind, str(help_text or f"{kind} {name}"))
        elif fam.kind != kind:
            raise ValueError(
                f"metric {name!r} is a {fam.kind}, not a {kind}")
        return fam

    def inc(self, name: str, n: float = 1.0, help: str | None = None,
            **labels):
        """Increment a counter series (monotonic by contract)."""
        with self._lock:
            fam = self._family(name, "counter", help)
            key = self._series_key(fam, labels)
            fam.series[key] = fam.series.get(key, 0.0) + n

    def set(self, name: str, value, help: str | None = None, **labels):
        """Set a gauge series.  `value=None` UNSETS the series — the
        explicit no-data path, so an unknown reading (e.g. a quantile
        of an empty histogram) disappears from the exposition instead
        of rendering as a bogus number or a `None` literal."""
        with self._lock:
            fam = self._family(name, "gauge", help)
            key = self._series_key(fam, labels)
            if value is None:
                fam.series.pop(key, None)
            else:
                fam.series[key] = float(value)

    def attach_board(self, name: str, board,
                     help: str | None = None, label: str = "family"):
        """Expose a live LatencyBoard as the histogram metric `name`,
        one series per board family under the `label` label.  Held by
        reference: the board keeps observing, the scrape reads the
        current counts.  `board` may also be a zero-arg callable
        returning the current board, for holders that REPLACE their
        board wholesale (the router rebuilds its fleet board from
        replica payloads each refresh)."""
        if not (callable(board) or isinstance(board, LatencyBoard)):
            raise TypeError(f"board must be a LatencyBoard or a "
                            f"callable returning one, got {type(board)}")
        with self._lock:
            if name in self._families:
                raise ValueError(f"metric {name!r} already registered")
            self._boards[name] = (board, str(help or f"histogram {name}"),
                                  str(label))

    # -- exposition ------------------------------------------------------

    def _full_labels(self, key: tuple) -> list:
        return sorted(list(self.const_labels.items()) + list(key))

    def render_prometheus(self) -> str:
        """The whole registry in text format 0.0.4."""
        with self._lock:
            out: list[str] = []
            for name in sorted(self._families):
                fam = self._families[name]
                full = f"{self.namespace}_{name}"
                out.append(f"# HELP {full} {_escape_help(fam.help)}")
                out.append(f"# TYPE {full} {fam.kind}")
                for key in sorted(fam.series):
                    out.append(
                        f"{full}{_label_str(self._full_labels(key))} "
                        f"{_fmt_value(fam.series[key])}")
            for name in sorted(self._boards):
                board, help_text, label = self._boards[name]
                if callable(board):
                    board = board()
                full = f"{self.namespace}_{name}"
                out.append(f"# HELP {full} {_escape_help(help_text)}")
                out.append(f"# TYPE {full} histogram")
                for family in board.families:
                    h = board.get(family)
                    base = self._full_labels(((label, family),))
                    cum = 0
                    for i, edge in enumerate(h.edges):
                        cum += h.counts[i]
                        items = base + [("le", repr(float(edge)))]
                        out.append(
                            f"{full}_bucket"
                            f"{_label_str(sorted(items))} {cum}")
                    items = base + [("le", "+Inf")]
                    out.append(f"{full}_bucket"
                               f"{_label_str(sorted(items))} {h.count}")
                    out.append(f"{full}_sum{_label_str(base)} "
                               f"{_fmt_value(h.sum_s)}")
                    out.append(f"{full}_count{_label_str(base)} "
                               f"{h.count}")
            return "\n".join(out) + "\n"

    def to_json(self) -> dict:
        """The same data structured: counters/gauges as
        {name: [{labels, value}]}, histograms as both the quantile
        snapshot and the raw mergeable wire form."""
        with self._lock:
            counters: dict = {}
            gauges: dict = {}
            for name, fam in self._families.items():
                dst = counters if fam.kind == "counter" else gauges
                dst[name] = [
                    {"labels": dict(self._full_labels(key)),
                     "value": fam.series[key]}
                    for key in sorted(fam.series)]
            resolved = {name: (board() if callable(board) else board)
                        for name, (board, _, _) in self._boards.items()}
            hists = {name: b.snapshot() for name, b in resolved.items()}
            raw = {name: b.to_dict() for name, b in resolved.items()}
            return {"namespace": self.namespace,
                    "const_labels": dict(self.const_labels),
                    "counters": counters, "gauges": gauges,
                    "histograms": hists, "histograms_raw": raw}
