"""JAX-aware runtime telemetry: spans, run manifests, profiler capture.

The reference ships structured *causal* logs (simulator/lib/log.ml —
mirrored by `cpr_tpu/trace.py`); this module is the *runtime* side the
reference never needed: on an async-dispatch backend a `time.time()`
bracket around a kernel call measures dispatch, not execution, and a
bench artifact without backend/device/window metadata cannot be compared
against its siblings (the BENCH_r05 CPU-fallback row read as a 306x
regression because nothing in it said "chip outage").

Three pieces, all host-side and dependency-free at import time:

* `Span` — a context-manager timer on `time.perf_counter` that FENCES
  with `jax.block_until_ready` on the values registered via
  `span.fence(...)`, so device work is attributed to the span that
  launched it, not to whichever later host line happens to block.
  Spans nest (events carry `path`/`depth`), carry counters
  (`env_steps=...`), and emit one JSONL event each with derived
  per-second throughput.

* `run_manifest()` — a self-describing snapshot of the run: backend,
  device kind/count, `memory_stats()`, jax/jaxlib versions, git SHA,
  host, argv, and the resolved config (window/ring settings etc.).
  Every BENCH_* row, sweep, and training run attaches one so artifacts
  can never be misread out of context.

* `maybe_profile()` — opt-in `jax.profiler` trace capture gated by the
  `CPR_PROFILE_DIR` env var, replacing the per-tool profiling
  boilerplate (tools/tpu_profile_env.py and friends).

Event stream: one JSON object per line.  `configure(path)` opens a
sink explicitly; otherwise the `CPR_TELEMETRY` env var names the file
and `current()` lazily opens it.  With no sink configured, spans still
time (drivers read `span.dur_s`) but emit nothing — the disabled path
is two `perf_counter` calls, well under the <2% overhead budget on the
nakamoto CPU bench config.

Interval timing anywhere under `cpr_tpu/` must go through `now()` (=
`time.perf_counter`) or `Span` — never `time.time()`, which is neither
monotonic nor high-resolution (tests/test_observability.py enforces
this repo-wide).
"""

from __future__ import annotations

import json
import logging
import os
import re
import socket
import subprocess
import sys
import threading
from collections import deque
from contextlib import contextmanager
from datetime import datetime, timezone
from time import perf_counter as now  # noqa: F401 — re-exported

SCHEMA_VERSION = 17
TELEMETRY_ENV_VAR = "CPR_TELEMETRY"
# flight-recorder ring capacity (v14): last N emitted events kept
# in-process for the crash blackbox (cpr_tpu/monitor/blackbox.py)
BLACKBOX_ENV_VAR = "CPR_BLACKBOX_EVENTS"
BLACKBOX_DEFAULT_EVENTS = 512
# trace context: one run id per process tree, exported so supervisor
# children and serve clients land their events under the same id
RUN_ID_ENV_VAR = "CPR_RUN_ID"
PROFILE_ENV_VAR = "CPR_PROFILE_DIR"
CHECKIFY_ENV_VAR = "CPR_CHECKIFY"
# in-graph metrics gate; canonical reader is cpr_tpu.device_metrics
# (this module stays jax-free at import, that one does not)
DEVICE_METRICS_ENV_VAR = "CPR_DEVICE_METRICS"

# every span event carries at least these keys (tools/trace_summary.py
# --validate and the schema tests check against this tuple)
SPAN_KEYS = ("kind", "name", "path", "depth", "t_start", "t_end",
             "dur_s")

# schema v2+: reserved point-event names -> the fields each must carry
# (tools/trace_summary.py --validate enforces this; other event names
# stay free-form exactly as in v1).  v3 adds the resilience events
# (cpr_tpu/resilience.py: retries, checkpoints, resume, preemption,
# fault injection).
EVENT_FIELDS = {
    "device_metrics": ("scope", "metrics"),
    "compile": ("fn", "compile_s"),
    "vi_residuals": ("impl", "n_sweeps", "residuals"),
    "tpu_outage": ("reason",),
    "checkpoint": ("path", "what"),
    "resume": ("path", "update"),
    "retry": ("attempt", "delay_s", "error"),
    "preempted": ("update",),
    "fault_injected": ("spec", "site"),
    # v4: one per netsim Engine.run — the vmap-batched network
    # simulator (cpr_tpu/netsim); `drops` sums every capacity-overflow
    # counter, so a healthy run reports drops=0
    "netsim": ("protocol", "lanes", "activations", "steps", "drops"),
    # v5: one per perf-regression gate (cpr_tpu/perf): verdict is
    # pass|warn|fail|skip, baseline names the banked rows judged
    # against (null when no same-backend history exists).  v15 makes
    # verdicts attributable: `run` is the candidate row's run id (null
    # when the row predates run stamping) and `baseline_runs` the run
    # ids of the banked baseline rows — both resolvable through the
    # run archive (cpr_tpu/perf/archive.py) into full trace streams,
    # which is how `perf_report --attribute` chases a FAIL into a
    # culprit span table (tools/trace_diff.py).
    "perf_gate": ("metric", "backend", "verdict", "value", "baseline",
                  "run", "baseline_runs"),
    # v6: one per supervisor decision (cpr_tpu/supervisor): action is
    # probe|heartbeat_stall|hang|warm_restart|escalation, site names the
    # supervised workload, reason says why (timings ride as extras)
    "supervisor": ("action", "site", "reason"),
    # v7: one per serving-layer decision (cpr_tpu/serve): action is
    # start|admit|complete|query|heartbeat|report|drain|stop, session is
    # the client session id (null for service-scope events), detail is a
    # free-form dict (lane/seed on admit, steps_per_sec/occupancy on
    # report — the perf ledger lifts report rows via iter_trace_rows)
    "serve": ("action", "session", "detail"),
    # v8: one per serve request, on BOTH sides of the wire (role
    # "server" in cpr_tpu/serve/server.py, role "client" in
    # protocol.ServeClient).  trace_id correlates the two streams
    # (tools/trace_stitch.py); the three latencies are the reply's own
    # queue_wait/service/total breakdown in seconds.  Extras ride
    # free-form: role, run, session, lane, splice_s, t_* stamps.
    "request": ("trace_id", "op", "status", "queue_wait_s",
                "service_s", "total_s"),
    # v9: one per admission-control refusal (cpr_tpu/serve/server.py)
    # — admitted sessions stay on the v7 serve admit trail, so this
    # event only fires when a session is shed.  reason is
    # queue_full|slo_breach|tenant_quota|replica_lost, priority is the
    # request's class name, tenant the quota key (null for untagged
    # traffic), retry_after_s the in-band backoff hint the refusal
    # reply carries.
    "admission": ("reason", "op", "priority", "tenant",
                  "retry_after_s"),
    # v9: one per router decision (cpr_tpu/serve/router.py): action is
    # route|requeue|refuse|replica_up|replica_down, replica the target
    # replica index (null when no replica was involved), op the wire op
    # being routed (null for lifecycle actions).  Extras ride
    # free-form: session, seed, reason, restarts.
    "route": ("action", "replica", "op"),
    # v10: one per grid-batched exact-MDP solve (cpr_tpu/mdp/grid.py
    # grid_value_iteration): grid is the [n_alphas, n_gammas] shape,
    # sweeps the total Bellman sweep count of the batched program,
    # converged how many grid points froze below stop_delta.  Extras
    # ride free-form: points, n_states, n_transitions, n_devices,
    # solve_s, points_per_sec (the ledger lifts the rate via
    # iter_trace_rows-style banking in tools/mdp_smoke.py).
    # v13: state-sharded solves (cpr_tpu/parallel/state_shard.py, and
    # grid_value_iteration's grid x state 2-D mesh) extend the extras
    # with state_shards (mesh size along the state axis, 1 when
    # unsharded), halo_bytes (per-sweep boundary-exchange traffic,
    # state_halo_bytes), and states_per_sec (n_states * sweeps /
    # solve_s — the ledger banks it as mdp_states_per_sec,
    # fingerprinted by cfg_state_shards).
    "mdp_solve": ("protocol", "cutoff", "grid", "sweeps", "converged"),
    # v11: one per adversary-in-the-network sweep
    # (cpr_tpu/netsim/attack.py AttackEngine.run): lanes counts the
    # vmapped (seed, delay, alpha, policy) tuples of the batch,
    # policies the size of the lane policy table, drops sums every
    # capacity-overflow counter including the common-ancestor walk cap
    # (healthy runs report drops=0).  Extras ride free-form:
    # activations, n_devices, sweep_s, lanes_per_sec (the perf ledger
    # lifts the rate into attack_sweep_lanes_per_sec rows).
    "attack_sweep": ("protocol", "topology", "lanes", "policies",
                     "drops"),
    # v12: one per frontier-batched MDP compile
    # (cpr_tpu/mdp/frontier.py FrontierCompiler.mdp): rounds counts the
    # whole-frontier BFS rounds, states/transitions size the compiled
    # MDP, n_workers is the expansion process count (1 = inline).
    # Extras ride free-form: compile_s, states_per_sec (the perf
    # ledger lifts the rate into mdp_compile_states_per_sec rows),
    # resumed.
    "mdp_compile": ("protocol", "cutoff", "rounds", "states",
                    "transitions", "n_workers"),
    # v14: one per SLO burn-rate breach (cpr_tpu/monitor/alerts.py,
    # evaluated on the serve tick loop): signal is
    # shed_rate|p99_over_slo, severity page|ticket (page = fast-window
    # breach, act now; ticket = slow-window breach, budget bleeding),
    # window_s the evaluation window, value the observed signal over
    # that window, budget the error budget it is judged against,
    # burn_rate = value / budget (>= the severity's threshold at emit
    # time).  Extras ride free-form: cls, threshold, slo_s.
    "alert": ("signal", "severity", "window_s", "value", "budget",
              "burn_rate"),
    # v15: one per MemoryWatermark scope (serve run loop, VI/grid
    # chunk drivers, frontier compiler): scope names the measured
    # region ("serve", "vi", "mdp_grid", "mdp_compile"), peak_bytes is
    # the per-device high-water mark over the scope (max across
    # devices), source says where the numbers came from — "device"
    # (allocator memory_stats) or "rss" (process fallback on backends
    # exposing none, XLA:CPU).  Extras ride free-form: in_use_bytes,
    # delta_bytes, limit_bytes, n_samples, devices, predicted_bytes
    # (the vi_working_set_bytes prediction, where the caller knows it).
    # The perf ledger lifts these into lower-is-better
    # `<scope>_peak_bytes` rows (iter_trace_rows).
    "memory": ("scope", "peak_bytes", "source"),
    # v16: one per artifact-integrity decision (cpr_tpu/integrity.py):
    # artifact is the on-disk path judged, artifact_kind the family —
    # named so because `kind` is the envelope discriminator ("event")
    # and a payload field would shadow it —
    # (train_snapshot, policy_snapshot, vi_checkpoint,
    # grid_vi_checkpoint, compile_checkpoint, mdp_grid_cache,
    # attack_cache, break_even_cache, ledger_row, archive_record),
    # reason why the bytes were rejected — checksum (seal digest
    # mismatch), truncated (short read / torn or unparseable frame),
    # version (sealed with a newer schema than this build reads),
    # sidecar_missing (payload present but its meta sidecar is gone or
    # contradicts it) — and action what the consumer did about it:
    # quarantined (moved to <path>.quarantine/, state untouched),
    # regenerated (treated as a cache miss and recomputed), refused
    # (load aborted loudly — serving a half-written artifact is worse
    # than crashing).  Extras ride free-form: quarantine path, detail.
    "integrity": ("artifact", "artifact_kind", "reason", "action"),
    # v17: one per leg of the always-on learning loop (cpr_tpu/learn,
    # sole emitter learn.learn_event): role is the leg — sample
    # (experience drained from the serve rings), feed (batch shipped
    # to the learner), update (one PPO update on fed experience),
    # publish (snapshot + latest.json written), swap (serving weights
    # replaced at a burst boundary) — steps/batches the volume moved,
    # fingerprint the snapshot payload_sha256 the leg acted under/on
    # (None before the first publish), staleness_s the age of the
    # serving weights at the leg (the gauge the AlertEngine budgets;
    # None where the emitting process cannot know it).  Extras ride
    # free-form: lanes, partial, dropped, pool, seq, losses.
    "learn": ("role", "steps", "batches", "fingerprint",
              "staleness_s"),
}


# -- trace context -----------------------------------------------------------
#
# `now()` is process-relative (perf_counter), so timestamps from two
# processes can never be compared directly; correlation is by ids —
# one `run_id` per process tree (minted once, inherited through the
# environment by supervisor children and smoke clients) and one
# `trace_id` per serve request (carried across the wire in the
# protocol's reserved `_trace` field).  Stitching therefore works on
# durations only (tools/trace_stitch.py).

_run_id: str | None = None


# -- flight recorder ring ----------------------------------------------------
#
# v14: every emitted event — sink or no sink — also lands in one
# process-wide bounded ring, so a crash leaves the last N events
# recoverable even when the JSONL tail was lost (or no sink was ever
# configured).  The ring is the recorder; the DUMP lives in
# cpr_tpu/monitor/blackbox.py (this module cannot import resilience —
# resilience imports telemetry).  Overhead is one deque.append per
# event; capacity comes from $CPR_BLACKBOX_EVENTS once per process.

_blackbox: deque | None = None

# one process-wide lock serializes the emit path (counter, ring append,
# sink write+flush) against concurrent emitters — the serve tick loop,
# the heartbeat thread, and the metrics HTTP threads all emit into the
# same sink — and guards the ring copy `dump_blackbox` takes (iterating
# a deque while another thread appends raises RuntimeError).  Emit is
# flushed-per-event already, so the lock adds no new syscall.
_emit_lock = threading.Lock()


def blackbox_capacity() -> int:
    """Ring capacity: $CPR_BLACKBOX_EVENTS (>=1), default 512."""
    try:
        n = int(os.environ.get(BLACKBOX_ENV_VAR,
                               BLACKBOX_DEFAULT_EVENTS))
    except ValueError:
        n = BLACKBOX_DEFAULT_EVENTS
    return max(1, n)


def _blackbox_ring() -> deque:
    global _blackbox
    if _blackbox is None:
        _blackbox = deque(maxlen=blackbox_capacity())
    return _blackbox


def blackbox_events() -> list[dict]:
    """The recorded tail, oldest first (a copy — safe to serialize
    while the emit path keeps appending; taken under the emit lock so
    a concurrent append can never abort the copy mid-iteration)."""
    with _emit_lock:
        return list(_blackbox_ring())


def run_id() -> str:
    """This process tree's run id: inherited from $CPR_RUN_ID when a
    parent minted one, else minted here and exported so every child
    spawned after this call lands in the same trace."""
    global _run_id
    if _run_id is None:
        rid = os.environ.get(RUN_ID_ENV_VAR)
        if not rid:
            import uuid

            rid = uuid.uuid4().hex[:16]
            os.environ[RUN_ID_ENV_VAR] = rid
        _run_id = rid
    return _run_id


def trace_env() -> dict:
    """The env-var dict that carries the trace context into a child
    process (merged into the child env by supervisor.run_child)."""
    return {RUN_ID_ENV_VAR: run_id()}


def reset_run_id(rid: str | None = None) -> str:
    """Mint (or install) a fresh run id for this process and every
    child spawned after this call.  Harness-side API: a parent that
    supervises several children as *separate* runs (the A/B pair
    tools/obs_smoke.py archives and diffs) must re-mint between them,
    or `run_child`'s trace_env() inheritance collapses the pair into
    one run record."""
    global _run_id
    if not rid:
        import uuid

        rid = uuid.uuid4().hex[:16]
    _run_id = rid
    os.environ[RUN_ID_ENV_VAR] = rid
    return rid


def new_trace_id() -> str:
    """A fresh per-request trace id (client side of a serve request)."""
    import uuid

    return uuid.uuid4().hex[:16]


class Span:
    """One timed region.  Use via `Telemetry.span`:

        with tele.span("measure", env_steps=n) as sp:
            out = sp.fence(fn(keys))

    On exit the fenced values are passed to `jax.block_until_ready`
    BEFORE the end timestamp is read, so asynchronously dispatched
    device work lands inside this span.  Counters become `per_sec`
    rates in the emitted event.
    """

    def __init__(self, tele: "Telemetry", name: str, counters: dict):
        self._tele = tele
        self.name = name
        self.counters = dict(counters)
        self._fenced: list = []
        self.path = name
        self.depth = 0
        self.t_start = self.t_end = self.dur_s = None

    def fence(self, value):
        """Register a (pytree of) device value(s) to block on at span
        exit; returns `value` so call sites stay one-liners."""
        self._fenced.append(value)
        return value

    def add(self, **counters):
        """Accumulate counters (e.g. env steps across reps)."""
        for k, v in counters.items():
            self.counters[k] = self.counters.get(k, 0) + v

    def __enter__(self):
        stack = self._tele._stack
        self.depth = len(stack)
        self.path = "/".join([s.name for s in stack] + [self.name])
        stack.append(self)
        self.t_start = now()
        return self

    def __exit__(self, exc_type, exc, tb):
        # on an exception the fenced values may be bogus — skip the
        # fence (the event still records the failure), else block so
        # async device work is attributed here
        if exc_type is None and self._fenced:
            import jax

            jax.block_until_ready(self._fenced)
        self.t_end = now()
        self.dur_s = self.t_end - self.t_start
        if self._tele._stack and self._tele._stack[-1] is self:
            self._tele._stack.pop()
        event = {
            "kind": "span", "name": self.name, "path": self.path,
            "depth": self.depth, "t_start": self.t_start,
            "t_end": self.t_end, "dur_s": self.dur_s,
        }
        if self.counters:
            event["counters"] = self.counters
            if self.dur_s > 0:
                event["per_sec"] = {
                    k: v / self.dur_s for k, v in self.counters.items()
                    if isinstance(v, (int, float))}
        if exc_type is not None:
            event["error"] = f"{exc_type.__name__}: {exc}"
        self._tele.emit(event)
        return False


class Telemetry:
    """A JSONL event sink plus the span-nesting stack.  `path=None`
    disables emission (spans still time)."""

    def __init__(self, path: str | None = None, stream=None):
        self.path = path
        self._own = stream is None and path is not None
        self._sink = stream if stream is not None else (
            open(path, "a") if path else None)
        self._stack: list[Span] = []
        self.n_emitted = 0

    @property
    def enabled(self) -> bool:
        return self._sink is not None

    def emit(self, event: dict):
        """Write one event line (no-op when disabled).  Flushed per
        event: telemetry exists for post-mortems, a crash must not eat
        the tail of the stream.  Serialized under the process-wide emit
        lock — the serve tick loop, the heartbeat thread, and the
        metrics HTTP threads share one sink, and two interleaved
        partial writes would corrupt the JSONL stream."""
        line = (json.dumps(event, default=str) + "\n"
                if self._sink is not None else None)
        with _emit_lock:
            # counted before the sink check: the supervisor heartbeat
            # reads this as a progress signal, sink or no sink
            self.n_emitted += 1
            # the flight recorder likewise rides every emit (v14): the
            # ring must capture the tail even when no sink is
            # configured — a sinkless crash is exactly when the
            # blackbox is the only record
            _blackbox_ring().append(event)
            sink = self._sink  # re-read under the lock: close() races
            if line is None or sink is None:
                return
            sink.write(line)
            sink.flush()

    def span_path(self) -> str | None:
        """Innermost open span's path, or None outside any span — the
        phase label the supervisor heartbeat reports.  Read from the
        beat thread while the main thread pushes/pops, hence the
        EAFP access instead of a check-then-index race."""
        try:
            return self._stack[-1].path
        except IndexError:
            return None

    def span(self, name: str, **counters) -> Span:
        return Span(self, name, counters)

    def event(self, name: str, **fields):
        """Point event (outages, reverts, phase markers)."""
        self.emit({"kind": "event", "name": name, "ts": now(), **fields})

    def manifest(self, config: dict | None = None) -> dict:
        """Emit (and return) a run manifest."""
        man = run_manifest(config)
        self.emit(man)
        return man

    def close(self):
        if self._sink is not None and self._own:
            self._sink.close()
        self._sink = None


_NULL = Telemetry()
_default: Telemetry | None = None


def configure(path: str | None = None, stream=None) -> Telemetry:
    """Install the process-wide default sink (closes any previous one).
    `configure(None)` disables emission."""
    global _default
    if _default is not None and _default is not _NULL:
        _default.close()
    _default = Telemetry(path, stream)
    return _default


def current() -> Telemetry:
    """The default telemetry: the configured sink, else one lazily
    opened from $CPR_TELEMETRY, else a disabled instance."""
    global _default
    if _default is None:
        path = os.environ.get(TELEMETRY_ENV_VAR)
        _default = Telemetry(path) if path else _NULL
    return _default


# -- run manifests -----------------------------------------------------------


def git_sha() -> str | None:
    """HEAD SHA of this checkout, or None outside a work tree."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:  # noqa: BLE001 — manifests are best-effort metadata
        pass
    return None


_MEM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_free_block_bytes")


def process_memory() -> tuple[int, int] | None:
    """This process's (rss_bytes, peak_rss_bytes), or None when the
    platform exposes neither /proc/self/status nor getrusage.  The v15
    memory plane's CPU-backend fallback: XLA:CPU implements no
    allocator `memory_stats`, and a watermark plane that is dead on
    the forced-CPU CI host would never be exercised in tier-1."""
    rss = peak = None
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                elif line.startswith("VmHWM:"):
                    peak = int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    if rss is not None:
        return rss, (peak if peak is not None else rss)
    try:
        import resource

        peak = int(resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss) * 1024  # linux: KiB
        # no live-RSS source without /proc — the peak stands in for
        # both (still a valid watermark, just a coarse in-use)
        return peak, peak
    except Exception:  # noqa: BLE001 — memory stats are best-effort
        return None


def device_memory_stats() -> dict | None:
    """Per-device allocator stats (subset of memory_stats keys).  On
    backends exposing none (XLA:CPU) falls back to one process-RSS
    entry tagged `source: "rss"` (v15) — consumers must treat a tagged
    entry as host-process memory, not device allocator state; real-chip
    entries are unchanged and untagged.  Returns None only when no
    source exists at all."""
    import jax

    out = {}
    for d in jax.devices():
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 — not all backends implement it
            ms = None
        if ms:
            out[f"{d.platform}:{d.id}"] = {
                k: int(ms[k]) for k in _MEM_KEYS if k in ms}
    if out:
        return out
    pm = process_memory()
    if pm is None:
        return None
    rss, peak = pm
    return {"process:rss": {"bytes_in_use": rss,
                            "peak_bytes_in_use": peak,
                            "source": "rss"}}


def run_manifest(config: dict | None = None) -> dict:
    """Self-describing snapshot of this process's runtime: enough that
    an artifact row can be interpreted with no other context (backend,
    devices, versions, git SHA, resolved config)."""
    man: dict = {
        "kind": "manifest",
        "schema": SCHEMA_VERSION,
        # v8: streams of one supervised run share a run id, which is
        # how trace_stitch groups server/child/client JSONL files
        "run": run_id(),
        "time_utc": datetime.now(timezone.utc).isoformat(
            timespec="seconds"),
        "argv": list(sys.argv),
        "hostname": socket.gethostname(),
        "python": sys.version.split()[0],
        "git_sha": git_sha(),
    }
    try:
        import jax
        import jaxlib

        devs = jax.devices()
        man["backend"] = devs[0].platform
        man["device_kind"] = devs[0].device_kind
        man["device_count"] = len(devs)
        man["process_count"] = jax.process_count()
        man["jax_version"] = jax.__version__
        man["jaxlib_version"] = jaxlib.__version__
        mem = device_memory_stats()
        if mem:
            man["memory_before"] = mem
    except Exception as e:  # noqa: BLE001 — a manifest must never kill a run
        man["jax_error"] = repr(e)
    if config is not None:
        man["config"] = config
    return man


# -- live memory watermarks (schema v15) -------------------------------------
#
# The binding constraint on the exact-analysis ceiling is device
# memory, but before v15 it was only visible as the one-shot manifest
# `memory_before` and the after-the-fact ViWorkingSetTooLarge /
# PaddedLayoutTooLarge refusals.  A MemoryWatermark samples the
# allocator (or the RSS fallback) at a scope's natural host seams —
# per VI chunk, per frontier round, per serve heartbeat — and emits
# ONE typed `memory` event per scope with the high-water mark.  One
# stats read per sample, never per device step, keeps it inside the
# <2% overhead budget every other plane honors.


class MemoryWatermark:
    """Track the device-memory high-water mark over a scope.

        with telemetry.memory_watermark("vi") as wm:
            for chunk in chunks:
                dispatch(chunk)
                wm.sample()          # cheap: one stats read

    Samples on enter, on every `sample()`, and on exit; exit emits a
    typed v15 `memory` event (scope, peak_bytes, source + extras).
    `peak_bytes` is the max per-device `peak_bytes_in_use` seen (the
    capacity limit is per chip, so devices are never summed);
    `in_use_bytes` the latest per-device max; `limit_bytes` the
    smallest per-device `bytes_limit` (headroom = limit - peak, the
    autoscaler signal); `delta_bytes` in-use now minus in-use at
    enter.  On XLA:CPU every number is process RSS, tagged
    `source: "rss"`.  All attributes are None until a sample
    succeeds; a backend with no memory source at all leaves the
    watermark inert (the event still emits, with nulls)."""

    def __init__(self, scope: str, tele: "Telemetry | None" = None,
                 **extra):
        self.scope = str(scope)
        self._tele = tele
        self.extra = dict(extra)
        self.source: str | None = None
        self.peak_bytes: int | None = None
        self.in_use_bytes: int | None = None
        self.limit_bytes: int | None = None
        self.baseline_bytes: int | None = None
        self.n_samples = 0
        self.devices: dict = {}

    def sample(self) -> dict | None:
        """Read the allocator once and fold it into the watermark.
        Returns the raw per-device stats (or None when no source
        exists).  Never raises — a memory probe must not kill the
        scope it is measuring."""
        try:
            stats = device_memory_stats()
        except Exception:  # noqa: BLE001 — probe failures stay silent
            return None
        if not stats:
            return None
        self.n_samples += 1
        in_use_max: int | None = None
        for dev, ms in stats.items():
            if ms.get("source") == "rss":
                self.source = "rss"
            elif self.source is None:
                self.source = "device"
            peak = ms.get("peak_bytes_in_use")
            in_use = ms.get("bytes_in_use")
            limit = ms.get("bytes_limit")
            rec = self.devices.setdefault(dev, {})
            if peak is not None:
                rec["peak_bytes"] = max(rec.get("peak_bytes", 0),
                                        int(peak))
                if self.peak_bytes is None or peak > self.peak_bytes:
                    self.peak_bytes = int(peak)
            if in_use is not None:
                rec["in_use_bytes"] = int(in_use)
                # the watermark must not miss a peak the allocator
                # doesn't track: in-use is a peak lower bound
                rec["peak_bytes"] = max(rec.get("peak_bytes", 0),
                                        int(in_use))
                if self.peak_bytes is None or in_use > self.peak_bytes:
                    self.peak_bytes = int(in_use)
                in_use_max = max(in_use_max or 0, int(in_use))
            if limit is not None:
                rec["limit_bytes"] = int(limit)
                if self.limit_bytes is None or limit < self.limit_bytes:
                    self.limit_bytes = int(limit)
        if in_use_max is not None:
            self.in_use_bytes = in_use_max
            if self.baseline_bytes is None:
                self.baseline_bytes = in_use_max
        return stats

    @property
    def delta_bytes(self) -> int | None:
        if self.in_use_bytes is None or self.baseline_bytes is None:
            return None
        return self.in_use_bytes - self.baseline_bytes

    @property
    def headroom_bytes(self) -> int | None:
        """limit - peak: how much the scope could still grow before
        the allocator refuses — the autoscaler's capacity signal.
        None without a limit (the RSS fallback reports none)."""
        if self.limit_bytes is None or self.peak_bytes is None:
            return None
        return self.limit_bytes - self.peak_bytes

    def snapshot(self) -> dict:
        """JSON-ready summary for heartbeat/stats/drain reports."""
        out = {"scope": self.scope, "source": self.source,
               "peak_bytes": self.peak_bytes,
               "in_use_bytes": self.in_use_bytes,
               "delta_bytes": self.delta_bytes,
               "n_samples": self.n_samples}
        if self.limit_bytes is not None:
            out["limit_bytes"] = self.limit_bytes
            out["headroom_bytes"] = self.headroom_bytes
        return out

    def emit(self, **extra):
        """Emit the typed v15 `memory` event (also called by exit)."""
        fields = dict(self.extra)
        fields.update(extra)
        tele = self._tele if self._tele is not None else current()
        tele.event(
            "memory", scope=self.scope, peak_bytes=self.peak_bytes,
            source=self.source, in_use_bytes=self.in_use_bytes,
            delta_bytes=self.delta_bytes, limit_bytes=self.limit_bytes,
            n_samples=self.n_samples, devices=self.devices or None,
            **fields)

    def __enter__(self) -> "MemoryWatermark":
        self.sample()
        return self

    def __exit__(self, exc_type, exc, tb):
        # sample + emit on the failure path too: memory at the crash
        # is exactly what a post-mortem wants
        self.sample()
        self.emit()
        return False


def memory_watermark(scope: str, tele: "Telemetry | None" = None,
                     **extra) -> MemoryWatermark:
    """A MemoryWatermark bound to the current sink (resolved at emit
    time, so configure() after construction still lands the event)."""
    return MemoryWatermark(scope, tele, **extra)


# -- compile observability ---------------------------------------------------
#
# jax has no public "a compile happened" callback, but with
# `jax_log_compiles` on it logs every trace/lower/compile through two
# private-module loggers in a stable format (verified on jax 0.4.37):
#
#   jax._src.interpreters.pxla  WARNING  "Compiling <fn> with global
#       shapes and types [ShapedArray(float32[4])]. Argument mapping: …"
#   jax._src.dispatch           WARNING  "Finished tracing +
#       transforming <fn> for pjit in <t> sec"
#   jax._src.dispatch           WARNING  "Finished XLA compilation of
#       jit(<fn>) in <t> sec"
#
# Cache hits (same fn, same shapes) log NOTHING — which is exactly the
# property the retrace regression test needs.  `compile_watch()` turns
# the flag on, attaches one handler to both loggers, and turns each
# Compiling/Finished pair into a machine-readable `compile` event.
# `jax.monitoring` duration listeners (no unregister API) are installed
# once per process and routed to whichever watchers are active.

_COMPILING_RE = re.compile(
    r"Compiling (\S+) with global shapes and types (\[.*?\])\.")
_XLA_DONE_RE = re.compile(
    r"Finished XLA compilation of (?:jit\()?([^)\s]+)\)? "
    r"in ([0-9.eE+-]+) sec")
_TRACE_DONE_RE = re.compile(
    r"Finished tracing \+ transforming (\S+) for pjit "
    r"in ([0-9.eE+-]+) sec")

_COMPILE_LOGGERS = ("jax._src.interpreters.pxla", "jax._src.dispatch")


class CompileWatcher:
    """Collects compile events while active inside `compile_watch()`.

    `events` is a list of dicts {fn, arg_shapes, trace_s, compile_s}
    — one per actual XLA compile (cache hits never log, so never
    count).  `durations` accumulates the `/jax/core/compile/*`
    monitoring totals observed while active."""

    def __init__(self):
        self.events: list[dict] = []
        self.durations: dict[str, float] = {}
        self._pending: dict[str, dict] = {}
        self._trace_s: dict[str, float] = {}

    def count(self, fn: str | None = None) -> int:
        """Number of compiles seen (optionally for one jitted fn)."""
        return sum(1 for e in self.events
                   if fn is None or e["fn"] == fn)

    def by_function(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self.events:
            out[e["fn"]] = out.get(e["fn"], 0) + 1
        return out

    # -- record handlers (called by the shared log handler) ---------------

    def _on_compiling(self, fn: str, arg_shapes: str):
        # the trace-done record precedes the Compiling record
        ev = {"fn": fn, "arg_shapes": arg_shapes,
              "trace_s": self._trace_s.pop(fn, None), "compile_s": None}
        self.events.append(ev)
        self._pending[fn] = ev

    def _on_trace_done(self, fn: str, secs: float):
        self._trace_s[fn] = secs

    def _on_xla_done(self, fn: str, secs: float) -> dict:
        ev = self._pending.pop(fn, None)
        if ev is None:  # Finished without a Compiling record: still real
            ev = {"fn": fn, "arg_shapes": None, "trace_s": None,
                  "compile_s": secs}
            self.events.append(ev)
        else:
            ev["compile_s"] = secs
        return ev


_active_watchers: list[CompileWatcher] = []
_monitoring_installed = False


class _CompileLogHandler(logging.Handler):
    def emit(self, record):  # noqa: A003 — logging.Handler API
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — never break the compile path
            return
        m = _COMPILING_RE.match(msg)
        if m:
            for w in _active_watchers:
                w._on_compiling(m.group(1), m.group(2))
            return
        m = _TRACE_DONE_RE.match(msg)
        if m:
            for w in _active_watchers:
                w._on_trace_done(m.group(1), float(m.group(2)))
            return
        m = _XLA_DONE_RE.match(msg)
        if m:
            for w in _active_watchers:
                ev = w._on_xla_done(m.group(1), float(m.group(2)))
                if getattr(w, "_emit", False):
                    current().event("compile", **ev)


_LOG_HANDLER = _CompileLogHandler(level=logging.WARNING)


def _monitoring_callback(event: str, secs: float, **attrs):
    if not event.startswith("/jax/core/compile"):
        return
    for w in _active_watchers:
        w.durations[event] = w.durations.get(event, 0.0) + secs


@contextmanager
def compile_watch(emit: bool = True):
    """Capture retrace/compile events while the body runs.

        with telemetry.compile_watch() as cw:
            fn(x)          # first call: compiles
            fn(x)          # same shapes: cache hit, NO event
        assert cw.count("fn") == 1

    Each compile becomes a `compile` point event on the current sink
    (`emit=False` collects without emitting) and is recorded on the
    yielded `CompileWatcher` regardless of any sink.  Nests cleanly;
    `jax_log_compiles` is restored on exit of the outermost watch."""
    import jax

    global _monitoring_installed
    w = CompileWatcher()
    w._emit = emit
    prev = jax.config.jax_log_compiles
    prev_prop = {}
    if not _active_watchers:
        jax.config.update("jax_log_compiles", True)
        for name in _COMPILE_LOGGERS:
            lg = logging.getLogger(name)
            lg.addHandler(_LOG_HANDLER)
            # the WARNING-level compile logs exist for this handler,
            # not for stderr: stop propagation while watching
            prev_prop[name] = lg.propagate
            lg.propagate = False
    if not _monitoring_installed:
        try:
            jax.monitoring.register_event_duration_secs_listener(
                _monitoring_callback)
        except Exception:  # noqa: BLE001 — durations are best-effort
            pass
        _monitoring_installed = True
    _active_watchers.append(w)
    try:
        yield w
    finally:
        _active_watchers.remove(w)
        if not _active_watchers:
            jax.config.update("jax_log_compiles", prev)
            for name in _COMPILE_LOGGERS:
                lg = logging.getLogger(name)
                lg.removeHandler(_LOG_HANDLER)
                lg.propagate = prev_prop.get(name, True)


def cost_snapshot(fn, *args) -> dict | None:
    """XLA's compile-time cost model of one jitted call — flops/bytes
    estimates for the run manifest, so cost regressions are diffable
    across artifacts.  Costs one EXTRA compile (`lower().compile()`
    does not share the jit executable cache): call it behind an opt-in
    gate (CPR_DEVICE_METRICS in train/driver.py), never on a fast
    path.  Returns None when the backend exposes no analysis."""
    try:
        import jax

        ca = jax.jit(fn).lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not ca:
            return None
        out = {}
        for k in ("flops", "bytes accessed", "transcendentals",
                  "optimal_seconds", "utilization operand 0"):
            if k in ca:
                out[k.replace(" ", "_")] = float(ca[k])
        return out or None
    except Exception:  # noqa: BLE001 — cost model is best-effort metadata
        return None


# -- profiler capture --------------------------------------------------------


@contextmanager
def profile_trace(trace_dir: str):
    """Explicit `jax.profiler` capture into `trace_dir` (the chrome
    trace + xplane files land under it)."""
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield trace_dir


@contextmanager
def maybe_profile(label: str = ""):
    """Opt-in profiler capture: no-op unless $CPR_PROFILE_DIR is set, in
    which case the trace lands under `$CPR_PROFILE_DIR/<label>`.  Yields
    the trace dir or None — the shared replacement for the copy-pasted
    per-tool `jax.profiler.trace` boilerplate."""
    base = os.environ.get(PROFILE_ENV_VAR)
    if not base:
        yield None
        return
    dest = os.path.join(base, label) if label else base
    with profile_trace(dest):
        current().event("profile_capture", trace_dir=dest, label=label)
        yield dest
