"""cpr_tpu — TPU-native framework for specifying, simulating, and attacking
proof-of-work consensus protocols.

Re-architects the capabilities of the reference (pkel/cpr: OCaml discrete-event
simulator + OCaml/Rust gym extensions + Python MDP toolbox) for JAX/XLA:

- protocols as pure state-transition functions over fixed-capacity block-DAG
  tensors (`cpr_tpu.core`; protocol rules live inside each attack env and
  in `cpr_tpu.mdp.generic.protocols`),
- selfish-mining attack environments as jittable, `vmap`-batched Monte-Carlo
  kernels (`cpr_tpu.envs`), exposed through gymnasium env ids
  (`cpr_tpu.gym`: core-v0, cpr-v0, cpr-nakamoto-v0, cpr-tailstorm-v0),
- the MDP attack-search stack (implicit->explicit compiler, value iteration,
  RTDP, policy-guided exploration, generic DAG-protocol models incl.
  GhostDAG) with JAX solvers (`cpr_tpu.mdp`),
- device-mesh parallelism (vmap env batch, pjit data-parallel episodes,
  sharded value-iteration sweeps) behind `cpr_tpu.parallel`.
"""

__version__ = "0.1.0"

from cpr_tpu.params import EnvParams  # noqa: F401
