"""In-graph device metrics: accumulator pytrees for jitted hot loops.

PR 2's telemetry spans time the rollout scan and the VI sweep from the
host but cannot say what happened INSIDE a traced program — episode
mix, reward range, NaN births.  This module provides the in-graph
half: a `MetricsSpec` describes a set of named cells (counters,
min/max/sum/count stats, small fixed-bin histograms); the accumulator
it `init()`s is a plain dict-of-arrays pytree that rides through
`lax.scan` / `lax.while_loop` carries and `vmap` lanes, is updated
with pure functional ops (`count`, `observe`, `observe_hist`), reduced
over batch axes ON DEVICE (`merge_axis`, `merge`), and read back to
the host ONCE per telemetry span via `summarize()` — the fast path
gains zero extra host syncs (tests/test_device_metrics.py proves this
under `jax.transfer_guard("disallow")`).

Everything is dtype-fixed and shape-static so threading an accumulator
through a scan body never changes the carry structure between steps:

- counter cells are int32 scalar sums (the headline bench span is
  131072 envs x 2200 steps x 3 reps = 8.7e8 < 2^31; one accumulator
  spans one measurement span, not a process lifetime),
- stats cells are {min, max, sum, count} float32 scalars (NaN inputs
  propagate into min/max — deliberate: a poisoned stats cell is itself
  a sentinel; the nonfinite counters say how many),
- hist cells are int32 count vectors over static bin edges
  (`len(edges) + 1` bins: underflow/overflow included).

Gating: `enabled()` reads the `CPR_DEVICE_METRICS` env var ("1" = on).
Builders (bench harness, `make_episode_stats_fn`, `make_train`) check
it at build time, so the off path compiles exactly the program it
compiled before this module existed.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

ENV_VAR = "CPR_DEVICE_METRICS"

# default ring length for VI residual trajectories (mdp/explicit.py):
# long enough for every solve seen so far to keep its full history,
# small enough that the while-loop carry cost is noise
RESID_LEN = 512


def enabled() -> bool:
    """True when in-graph metrics collection is requested
    (CPR_DEVICE_METRICS=1).  Read at build time by the producers."""
    return os.environ.get(ENV_VAR) == "1"


class MetricsSpec:
    """Declarative set of named metric cells + pure update/reduce ops.

    The spec itself is host-side and static (close over it; never pass
    it through jit boundaries); the accumulator dicts it produces are
    jax pytrees.  All update ops are functional: they return a new
    accumulator dict and never mutate."""

    def __init__(self):
        self._cells: dict[str, tuple] = {}

    # -- declaration ------------------------------------------------------

    def counter(self, name: str):
        self._cells[name] = ("counter",)
        return self

    def stats(self, name: str):
        self._cells[name] = ("stats",)
        return self

    def hist(self, name: str, edges):
        edges = np.asarray(edges, np.float32)
        assert edges.ndim == 1 and (np.diff(edges) > 0).all(), (
            "hist edges must be a 1-D increasing vector")
        self._cells[name] = ("hist", edges)
        return self

    @property
    def names(self):
        return tuple(self._cells)

    def kind(self, name: str) -> str:
        return self._cells[name][0]

    # -- accumulator lifecycle --------------------------------------------

    def init(self) -> dict:
        """Fresh zero accumulator (a dict pytree of scalars/vectors)."""
        acc = {}
        for name, cell in self._cells.items():
            if cell[0] == "counter":
                acc[name] = jnp.zeros((), jnp.int32)
            elif cell[0] == "stats":
                acc[name] = {
                    "min": jnp.asarray(jnp.inf, jnp.float32),
                    "max": jnp.asarray(-jnp.inf, jnp.float32),
                    "sum": jnp.zeros((), jnp.float32),
                    "count": jnp.zeros((), jnp.float32),
                }
            else:  # hist
                acc[name] = jnp.zeros(len(cell[1]) + 1, jnp.int32)
        return acc

    # -- update ops (inside the traced program) ---------------------------

    def count(self, acc: dict, name: str, n) -> dict:
        """acc[name] += sum(n).  `n` may be a bool/int scalar or array
        (e.g. a `done` mask); it is summed and cast to int32."""
        assert self._cells[name][0] == "counter", name
        inc = jnp.sum(jnp.asarray(n).astype(jnp.int32))
        return {**acc, name: acc[name] + inc}

    def observe(self, acc: dict, name: str, values, where=None) -> dict:
        """Fold `values` (any shape) into a stats cell, optionally
        masked by `where` (same shape, True = include)."""
        assert self._cells[name][0] == "stats", name
        x = jnp.asarray(values, jnp.float32)
        if where is None:
            mn, mx = x.min(), x.max()
            sm, ct = x.sum(), jnp.asarray(x.size, jnp.float32)
        else:
            w = jnp.asarray(where)
            mn = jnp.where(w, x, jnp.inf).min()
            mx = jnp.where(w, x, -jnp.inf).max()
            sm = jnp.where(w, x, 0.0).sum()
            ct = w.astype(jnp.float32).sum()
        c = acc[name]
        cell = {
            "min": jnp.minimum(c["min"], mn),
            "max": jnp.maximum(c["max"], mx),
            "sum": c["sum"] + sm,
            "count": c["count"] + ct,
        }
        return {**acc, name: cell}

    def observe_hist(self, acc: dict, name: str, values,
                     where=None) -> dict:
        """Bin `values` into a hist cell: bin i counts values in
        [edges[i-1], edges[i]) with open-ended under/overflow bins."""
        kind = self._cells[name]
        assert kind[0] == "hist", name
        edges = jnp.asarray(kind[1])
        x = jnp.asarray(values, jnp.float32).reshape(-1)
        idx = jnp.searchsorted(edges, x, side="right")
        w = (jnp.ones_like(x, jnp.int32) if where is None
             else jnp.asarray(where).reshape(-1).astype(jnp.int32))
        counts = jax.ops.segment_sum(w, idx,
                                     num_segments=len(kind[1]) + 1)
        return {**acc, name: acc[name] + counts}

    # -- reductions (still on device) -------------------------------------

    def _merge_cell(self, kind: str, a, b):
        if kind == "stats":
            return {
                "min": jnp.minimum(a["min"], b["min"]),
                "max": jnp.maximum(a["max"], b["max"]),
                "sum": a["sum"] + b["sum"],
                "count": a["count"] + b["count"],
            }
        return a + b  # counter / hist

    def merge(self, a: dict, b: dict) -> dict:
        """Combine two accumulators (e.g. across bench reps)."""
        return {name: self._merge_cell(cell[0], a[name], b[name])
                for name, cell in self._cells.items()}

    def merge_axis(self, acc: dict, axis: int = 0) -> dict:
        """Reduce a vmapped accumulator (every leaf gained `axis`)
        back to scalar cells — on device, inside the jitted program."""
        out = {}
        for name, cell in self._cells.items():
            c = acc[name]
            if cell[0] == "stats":
                out[name] = {
                    "min": c["min"].min(axis),
                    "max": c["max"].max(axis),
                    "sum": c["sum"].sum(axis),
                    "count": c["count"].sum(axis),
                }
            else:
                out[name] = c.sum(axis)
        return out

    # -- the single host readback -----------------------------------------

    def summarize(self, acc: dict) -> dict:
        """ONE `jax.device_get` of the whole accumulator -> plain
        python dict ready for `telemetry.event("device_metrics", ...)`.
        Stats cells gain a derived mean; empty stats cells (count 0)
        read as None min/max/mean."""
        host = jax.device_get(acc)
        out = {}
        for name, cell in self._cells.items():
            c = host[name]
            if cell[0] == "counter":
                out[name] = int(c)
            elif cell[0] == "stats":
                n = float(c["count"])
                out[name] = {
                    "min": float(c["min"]) if n else None,
                    "max": float(c["max"]) if n else None,
                    "sum": float(c["sum"]),
                    "count": n,
                    "mean": float(c["sum"]) / n if n else None,
                }
            else:
                out[name] = {
                    "edges": [float(e) for e in cell[1]],
                    "counts": [int(v) for v in c],
                }
        return out


def emit(scope: str, spec: MetricsSpec, acc: dict, **extra):
    """Summarize `acc` (the one host readback) and emit a
    `device_metrics` point event on the current telemetry sink."""
    from cpr_tpu import telemetry

    summary = spec.summarize(acc)
    telemetry.current().event("device_metrics", scope=scope,
                              metrics=summary, **extra)
    return summary


# -- the rollout specs --------------------------------------------------------

# episode-length bins: powers of two up to the dense-runaway ceiling
# (driver.py caps episodes at 4x episode_len; 2016-step nakamoto
# episodes land in the 2048 bin)
_EP_LEN_EDGES = tuple(float(2 ** i) for i in range(4, 14))


def rollout_spec() -> MetricsSpec:
    """Per-step cells for `rollout(with_metrics=True)`: step/episode
    counts, reward range, episode-length mix, and nonfinite sentinels
    on obs/reward.  Folded from the stacked trajectory the caller is
    already paying to materialize — do NOT wire this into the
    episode-stats bench drivers, where the trajectory is otherwise
    dead and every extra consumer of per-step data costs ~1% per
    fused pass on XLA:CPU (see episode_stats_spec)."""
    spec = MetricsSpec()
    spec.counter("env_steps")
    spec.counter("episodes")
    spec.counter("nonfinite_obs")
    spec.counter("nonfinite_reward")
    spec.stats("reward")
    spec.stats("episode_length")
    spec.hist("episode_length_hist", _EP_LEN_EDGES)
    return spec


def episode_stats_spec(stat_keys) -> MetricsSpec:
    """Cells for the batched episode-stats drivers
    (`make_episode_stats_fn(collect_metrics=True)`), derived entirely
    from per-env aggregates the driver already computes — the scan
    body stays the exact metrics-off program.  This is what keeps the
    leave-it-on overhead contract (<2% on the 512-env nakamoto CPU
    bench): folding per-step cells instead measured +7% (stats) to
    +28% (full spec), because XLA:CPU fuses any consumer of stacked
    scan outputs back into the sequential loop at ~7us/HLO/step.

    Cells: `env_steps`/`episodes` counters; one stats cell per
    `episode_*` info key (the spread ACROSS ENV LANES of each lane's
    completed-episode mean — lane granularity, not per-episode);
    `episode_n_steps_hist` over the per-lane mean episode length;
    `nonfinite_stats` (poisoned per-lane aggregates — a NaN born in
    any step's reward/info propagates into the lane's episode sums,
    so this is a whole-stream NaN sentinel at lane granularity) and
    `nonfinite_obs_boundary` (nonfinite elements in each lane's
    live observation at chunk boundaries / stream end)."""
    spec = MetricsSpec()
    spec.counter("env_steps")
    spec.counter("episodes")
    spec.counter("nonfinite_stats")
    spec.counter("nonfinite_obs_boundary")
    for k in stat_keys:
        spec.stats(k)
    if "episode_n_steps" in stat_keys:
        spec.hist("episode_n_steps_hist", _EP_LEN_EDGES)
    return spec


def fold_episode_stats(spec: MetricsSpec, acc: dict, *, stats,
                       n_episodes, stat_keys) -> dict:
    """Fold one env lane's completed-episode aggregates (its
    `episode_*` means and episode count) into an episode_stats_spec()
    accumulator.  Unbatched — vmap adds the env axis, `merge_axis`
    removes it on device.  Lanes that finished no episode are masked
    out of the stats cells (their 0/1-clamped means are meaningless),
    but still feed the nonfinite sentinel."""
    has_ep = n_episodes > 0
    nonfinite = jnp.zeros((), jnp.int32)
    for k in stat_keys:
        v = jnp.asarray(stats[k], jnp.float32)
        nonfinite = nonfinite + (~jnp.isfinite(v)).astype(jnp.int32)
        acc = spec.observe(acc, k, v, where=has_ep)
    acc = spec.count(acc, "nonfinite_stats", nonfinite)
    acc = spec.count(acc, "episodes", n_episodes)
    if "episode_n_steps" in stat_keys:
        acc = spec.observe_hist(acc, "episode_n_steps_hist",
                                stats["episode_n_steps"], where=has_ep)
    return acc


def obs_nonfinite(obs) -> jax.Array:
    """Per-step count of nonfinite observation elements: reduces the
    trailing feature axis, leading (time) axes survive.  The one
    rollout cell that must be computed inside the scan body — stacking
    full observations per step is exactly the HBM cost the chunked
    driver exists to avoid (envs/base.py)."""
    x = jnp.asarray(obs, jnp.float32)
    return jnp.sum(~jnp.isfinite(x), axis=-1).astype(jnp.int32)


def update_rollout(spec: MetricsSpec, acc: dict, *, reward, done,
                   ep_len, nonfinite_obs) -> dict:
    """Fold one rollout segment into a `rollout_spec()` accumulator —
    vectorized over the stacked (T,) step axis, once per scan, NOT once
    per step.  Per-step carry updates cost ~7us/HLO/step on XLA:CPU
    (measured +72% on the 512-env nakamoto bench before this was
    hoisted out of the scan body); the same reductions over the stacked
    segment are noise.

    `reward`/`done`/`ep_len` are (T,) slices of the scanned trajectory
    (`ep_len` = info["episode_n_steps"]); `nonfinite_obs` is the (T,)
    per-step nonfinite-element count from `obs_nonfinite`.  Scalars
    (T absent) also work — the ops are shape-polymorphic."""
    reward = jnp.asarray(reward, jnp.float32)
    acc = spec.count(acc, "env_steps", jnp.ones_like(reward, jnp.int32))
    acc = spec.count(acc, "episodes", done)
    acc = spec.count(acc, "nonfinite_obs", nonfinite_obs)
    acc = spec.count(acc, "nonfinite_reward", ~jnp.isfinite(reward))
    acc = spec.observe(acc, "reward", reward)
    acc = spec.observe(acc, "episode_length", ep_len, where=done)
    acc = spec.observe_hist(acc, "episode_length_hist", ep_len,
                            where=done)
    return acc


# -- the serving spec ---------------------------------------------------------


# log-scale edges (seconds) for the per-burst dispatch-latency
# histogram: half-decade buckets over 10us .. ~316s, bracketing every
# observed burst wall (CPU smoke ~100ms, TPU sub-ms) with headroom
_BURST_S_EDGES = tuple(10.0 ** (e / 2.0) for e in range(-10, 6))


def serve_spec() -> MetricsSpec:
    """Cells for the cpr_tpu.serve resident engine: throughput
    counters (`env_steps`/`episodes`/`bursts`), the `occupancy` spread
    (fraction of lanes assigned to client sessions, one observation
    per burst), and the per-burst dispatch latency twice over — the
    `burst_s` min/max/mean spread plus the `burst_s_hist` log-bucket
    histogram (so the drain-time device_metrics event carries the
    latency *distribution*, not just its envelope).  Both fold once at
    drain from the host walls the engine already records for its
    throughput report.

    Same overhead contract as the stats drivers: the in-graph cells
    fold ONCE PER BURST from the burst call's own inputs/outputs
    (occupancy scalar, stacked done column) — nothing new is consumed
    per step, so the scan-loop program is identical to the metrics-off
    build."""
    spec = MetricsSpec()
    spec.counter("env_steps")
    spec.counter("episodes")
    spec.counter("bursts")
    # admission-control refusals (queue_full / slo_breach /
    # tenant_quota / replica_lost): recorded host-side by the server
    # via ResidentEngine.record_shed, folded once at drain like burst_s
    spec.counter("shed_sessions")
    spec.stats("occupancy")
    spec.stats("burst_s")
    spec.hist("burst_s_hist", _BURST_S_EDGES)
    return spec


# -- the PPO update spec ------------------------------------------------------


def ppo_spec() -> MetricsSpec:
    """Cells the PPO epoch scan accumulates per train_step: numerical
    sentinels on advantages and losses, KL early-stop skips, and the
    surrogate-ratio KL range across minibatches."""
    spec = MetricsSpec()
    spec.counter("nonfinite_advantages")
    spec.counter("nonfinite_loss")
    spec.counter("minibatches")
    spec.counter("minibatches_skipped")
    spec.stats("approx_kl")
    return spec
