"""Observation field normalization.

JAX re-design of the reference observation normalizers
(reference: simulator/protocols/ssz_tools.ml:1-74 `NormalizeObs`):

- raw mode keeps the natural scale of each field,
- unit mode squashes each field into [0, 1]: unbounded non-negative ints via
  2/pi * atan(x / scale), signed ints via 0.5 + atan(x / scale)/pi, discrete
  fields via i/(n-1).

Where the reference builds per-record normalizers with ppx-derived field
folds, here an observation is declared as a tuple of `Field` specs and
encoded with one vectorized `encode` that jit/vmap compile away.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

BOOL = "bool"
DISCRETE = "discrete"
UINT = "uint"  # unbounded non-negative int
INT = "int"  # unbounded signed int


@dataclass(frozen=True)
class Field:
    name: str
    kind: str = UINT
    scale: int = 1  # atan squash scale for uint/int
    n: int = 2  # number of values for discrete


def field_to_float(field: Field, x, unit: bool):
    """Encode one field value as float (ssz_tools.ml:11-40)."""
    x = jnp.asarray(x, jnp.float32)
    if not unit:
        return x
    if field.kind == BOOL:
        return x
    if field.kind == DISCRETE:
        return x / jnp.float32(field.n - 1)
    if field.kind == UINT:
        return 2.0 / jnp.pi * jnp.arctan(x / field.scale)
    if field.kind == INT:
        return 0.5 + jnp.arctan(x / field.scale) / jnp.pi
    raise ValueError(field.kind)


def field_of_float(field: Field, v, unit: bool):
    """Decode one float back into the field's natural scale (ssz_tools.ml:20-59)."""
    v = jnp.asarray(v, jnp.float32)
    if not unit:
        return jnp.round(v) if field.kind != BOOL else v >= 0.5
    if field.kind == BOOL:
        return v >= 0.5
    if field.kind == DISCRETE:
        return jnp.floor(v * (field.n - 1))
    if field.kind == UINT:
        return jnp.round(jnp.tan(jnp.pi / 2.0 * v) * field.scale)
    if field.kind == INT:
        return jnp.round(jnp.tan(jnp.pi * (v - 0.5)) * field.scale)
    raise ValueError(field.kind)


def encode(fields: tuple[Field, ...], values, unit: bool):
    """Encode a tuple of natural-scale values into a float observation vector."""
    assert len(fields) == len(values)
    return jnp.stack(
        [field_to_float(f, v, unit) for f, v in zip(fields, values)], axis=-1
    )


def low_high(fields: tuple[Field, ...], unit: bool):
    """Observation-space bounds (ssz_tools.ml:64-73)."""
    low = np.zeros(len(fields), dtype=np.float32)
    high = np.zeros(len(fields), dtype=np.float32)
    for i, f in enumerate(fields):
        if unit:
            low[i], high[i] = 0.0, 1.0
        elif f.kind == BOOL:
            low[i], high[i] = 0.0, 1.0
        elif f.kind == DISCRETE:
            low[i], high[i] = 0.0, float(f.n - 1)
        elif f.kind == UINT:
            low[i], high[i] = 0.0, np.inf
        elif f.kind == INT:
            low[i], high[i] = -np.inf, np.inf
        else:
            raise ValueError(f.kind)
    return low, high
