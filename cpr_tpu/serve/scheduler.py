"""Host-side continuous-batching scheduler: sessions -> lanes.

Deliberately jax-free and asyncio-free (plain data structures, unit
testable in microseconds): the server's tick loop asks `place()` for
this tick's admissions, the engine does the device-side splice, and
`retire()` frees a lane the moment its session completes — the next
`place()` backfills it from the admission queue.  Lane state never
survives a retire->admit cycle on the device side either: admission
splices a wholly fresh `init_lanes` state over the slot (the
reclaimed-slot aliasing class of bug is structurally excluded, and
tests/test_serve.py proves it bit-for-bit).

Admission control (fleet PR): the queue is priority-ordered (lower
class number places first; FIFO within a class), optionally bounded
(`max_queued` — `enqueue` raises `QueueFull` instead of growing
without limit), and optionally tenant-quota'd (`tenant_quota` — a
tenant already holding that many lanes is skipped by `place()` without
blocking other tenants behind it).  The shedding *policy* — what to
refuse and what `retry_after` to quote — lives in the server; this
module only supplies the mechanisms and the accounting.
"""

from __future__ import annotations

# interval source shared with the rest of the runtime (perf_counter —
# monotonic, so queue ages can never go backwards); telemetry is
# jax-free at import, preserving this module's import weight
from cpr_tpu.telemetry import now


class QueueFull(Exception):
    """`enqueue` on a bounded queue already holding `max_queued`
    sessions.  The server turns this into an in-band shed refusal."""


class _Entry:
    __slots__ = ("session", "priority", "tenant", "t")

    def __init__(self, session, priority: int, tenant, t: float):
        self.session = session
        self.priority = priority
        self.tenant = tenant
        self.t = t


class LaneScheduler:
    """Tracks which session owns which lane plus the priority-ordered
    admission queue.  Sessions are opaque objects; identity is `is`."""

    def __init__(self, n_lanes: int, *, max_queued: int | None = None,
                 tenant_quota: int | None = None):
        if n_lanes <= 0:
            raise ValueError(f"n_lanes must be positive, got {n_lanes}")
        self.n_lanes = n_lanes
        self.max_queued = max_queued
        self.tenant_quota = tenant_quota
        self._owner: list = [None] * n_lanes
        # tenant tag per owned lane, parallel to _owner — the quota is
        # over *lanes held*, so it survives the session object itself
        self._owner_tenant: list = [None] * n_lanes
        # placement-ordered: sorted by (priority, enqueue order); the
        # queue is bounded so O(n) scans stay trivially cheap
        self._queue: list[_Entry] = []

    # -- admission queue --------------------------------------------------

    def enqueue(self, session, priority: int = 1, tenant=None) -> int:
        """Queue a session for admission; returns its queue position
        (0 = next to be placed).  Lower `priority` places first; ties
        keep FIFO order.  Raises `QueueFull` on a bounded queue at
        capacity — the caller sheds in-band instead of queueing."""
        if self.max_queued is not None and len(self._queue) >= self.max_queued:
            raise QueueFull(f"admission queue at capacity "
                            f"({self.max_queued})")
        pos = len(self._queue)
        while pos > 0 and self._queue[pos - 1].priority > priority:
            pos -= 1
        self._queue.insert(pos, _Entry(session, priority, tenant, now()))
        return pos

    def cancel(self, session) -> bool:
        """Drop a not-yet-placed session from the queue."""
        for i, e in enumerate(self._queue):
            if e.session is session:
                del self._queue[i]
                return True
        return False

    def place(self) -> list:
        """Assign queued sessions to free lanes (priority-FIFO x
        ascending lane id); returns [(lane, session), ...] for this
        tick's admissions.  A session whose tenant is at quota is
        skipped (it stays queued, aging normally) without blocking
        lower-priority sessions of other tenants."""
        placed = []
        free = [i for i in range(self.n_lanes) if self._owner[i] is None]
        if not free or not self._queue:
            return placed
        free.reverse()  # pop() yields ascending lane ids
        held: dict = {}
        for t in self._owner_tenant:
            if t is not None:
                held[t] = held.get(t, 0) + 1
        remaining = []
        for e in self._queue:
            if not free:
                remaining.append(e)
                continue
            if (self.tenant_quota is not None and e.tenant is not None
                    and held.get(e.tenant, 0) >= self.tenant_quota):
                remaining.append(e)
                continue
            lane = free.pop()
            self._owner[lane] = e.session
            self._owner_tenant[lane] = e.tenant
            if e.tenant is not None:
                held[e.tenant] = held.get(e.tenant, 0) + 1
            placed.append((lane, e.session))
        self._queue = remaining
        return placed

    # -- lane table -------------------------------------------------------

    def owner(self, lane: int):
        return self._owner[lane]

    def retire(self, lane: int):
        """Free a lane; returns the session that owned it."""
        session, self._owner[lane] = self._owner[lane], None
        self._owner_tenant[lane] = None
        return session

    def assigned(self) -> dict:
        """{lane: session} over currently owned lanes."""
        return {i: s for i, s in enumerate(self._owner) if s is not None}

    def drain(self) -> list:
        """Evict everything: returns every queued + placed session (in
        that order) and leaves the scheduler empty."""
        evicted = [e.session for e in self._queue]
        evicted += [s for s in self._owner if s is not None]
        self._queue.clear()
        self._owner = [None] * self.n_lanes
        self._owner_tenant = [None] * self.n_lanes
        return evicted

    # -- stats ------------------------------------------------------------

    def n_queued(self) -> int:
        return len(self._queue)

    def oldest_queued_s(self) -> float:
        """Age (seconds) of the oldest not-yet-placed session, 0.0 on
        an empty queue — growth here is the first sign admissions are
        falling behind (surfaced in the heartbeat and stats).  Oldest
        by *enqueue time*, not queue position: priority insertion can
        park a low-priority session behind later arrivals."""
        if not self._queue:
            return 0.0
        return now() - min(e.t for e in self._queue)

    def tenant_load(self, tenant) -> int:
        """Lanes held + queue slots occupied by `tenant` — the number
        the server's quota shed decision compares against."""
        if tenant is None:
            return 0
        held = sum(t == tenant for t in self._owner_tenant)
        return held + sum(e.tenant == tenant for e in self._queue)

    def n_assigned(self) -> int:
        return sum(s is not None for s in self._owner)

    def occupancy(self) -> float:
        return self.n_assigned() / self.n_lanes
