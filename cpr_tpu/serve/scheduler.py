"""Host-side continuous-batching scheduler: sessions -> lanes.

Deliberately jax-free and asyncio-free (plain data structures, unit
testable in microseconds): the server's tick loop asks `place()` for
this tick's admissions, the engine does the device-side splice, and
`retire()` frees a lane the moment its session completes — the next
`place()` backfills it from the admission queue.  Lane state never
survives a retire->admit cycle on the device side either: admission
splices a wholly fresh `init_lanes` state over the slot (the
reclaimed-slot aliasing class of bug is structurally excluded, and
tests/test_serve.py proves it bit-for-bit).
"""

from __future__ import annotations

from collections import deque

# interval source shared with the rest of the runtime (perf_counter —
# monotonic, so queue ages can never go backwards); telemetry is
# jax-free at import, preserving this module's import weight
from cpr_tpu.telemetry import now


class LaneScheduler:
    """Tracks which session owns which lane plus the FIFO admission
    queue.  Sessions are opaque objects; identity is `is`."""

    def __init__(self, n_lanes: int):
        if n_lanes <= 0:
            raise ValueError(f"n_lanes must be positive, got {n_lanes}")
        self.n_lanes = n_lanes
        self._owner: list = [None] * n_lanes
        self._queue: deque = deque()
        # enqueue stamps, parallel to _queue (FIFO: the head is always
        # the oldest) — the heartbeat's backlog-age signal
        self._queued_at: deque = deque()

    # -- admission queue --------------------------------------------------

    def enqueue(self, session) -> int:
        """Queue a session for admission; returns its queue position
        (0 = next to be placed)."""
        self._queue.append(session)
        self._queued_at.append(now())
        return len(self._queue) - 1

    def cancel(self, session) -> bool:
        """Drop a not-yet-placed session from the queue."""
        try:
            i = self._queue.index(session)
        except ValueError:
            return False
        del self._queue[i]
        del self._queued_at[i]
        return True

    def place(self) -> list:
        """Assign queued sessions to free lanes (FIFO x ascending lane
        id); returns [(lane, session), ...] for this tick's admissions."""
        placed = []
        for lane in range(self.n_lanes):
            if not self._queue:
                break
            if self._owner[lane] is None:
                session = self._queue.popleft()
                self._queued_at.popleft()
                self._owner[lane] = session
                placed.append((lane, session))
        return placed

    # -- lane table -------------------------------------------------------

    def owner(self, lane: int):
        return self._owner[lane]

    def retire(self, lane: int):
        """Free a lane; returns the session that owned it."""
        session, self._owner[lane] = self._owner[lane], None
        return session

    def assigned(self) -> dict:
        """{lane: session} over currently owned lanes."""
        return {i: s for i, s in enumerate(self._owner) if s is not None}

    def drain(self) -> list:
        """Evict everything: returns every queued + placed session (in
        that order) and leaves the scheduler empty."""
        evicted = list(self._queue) + [s for s in self._owner
                                       if s is not None]
        self._queue.clear()
        self._queued_at.clear()
        self._owner = [None] * self.n_lanes
        return evicted

    # -- stats ------------------------------------------------------------

    def n_queued(self) -> int:
        return len(self._queue)

    def oldest_queued_s(self) -> float:
        """Age (seconds) of the oldest not-yet-placed session, 0.0 on
        an empty queue — growth here is the first sign admissions are
        falling behind (surfaced in the heartbeat and stats)."""
        return now() - self._queued_at[0] if self._queued_at else 0.0

    def n_assigned(self) -> int:
        return sum(s is not None for s in self._owner)

    def occupancy(self) -> float:
        return self.n_assigned() / self.n_lanes
