"""Multi-replica serve front-end: one router, N supervised children.

`python -m cpr_tpu.serve.router --replicas N ...` launches N copies of
`cpr_tpu.serve.server` through `supervisor.run_child` (each with the
heartbeat watchdog, a per-replica telemetry sink, and a per-replica
`--replica-index` arming the `replica` fault-injection site) and
speaks the same length-prefixed JSON protocol to clients, so every
existing client — `ServeClient`, the smokes, the tests — talks to a
fleet exactly as it talked to one server.

Routing: sessions go to the up replica with the fewest in-flight
requests (lowest index breaks ties); admission control itself stays in
the replicas (priority classes, quotas, SLO shedding), whose in-band
shed refusals pass through to the client untouched.

Failover leans on the PR-9 bit-identity contract: an `episode.run` is
fully determined by (policy, seed), so the router stamps a seed on
every seedless run before the first forward, and when a replica dies
mid-flight it simply re-forwards the same request to a survivor — the
re-run episode is byte-identical to what the dead replica would have
returned.  Stateless queries (hello / netsim.query / break_even.*)
fail over the same way because they are idempotent.  Interactive
sessions are the documented exception: their lane state lives only in
the replica that admitted them, so on replica loss the router refuses
their next request in-band (`shed: replica_lost` with `retry_after`)
instead of guessing — the client reopens and replays its own actions
if it wants to resume.

Every decision is a typed v9 `route` telemetry event, and every client
request is mirrored as a `request` event with role "router", giving
`tools/trace_stitch.py` the middle segment of the critical path:
route -> queue -> splice -> burst -> reply.

A replica that exits outside a drain is warm-restarted (up to
`--max-restarts` times); restarted children run with CPR_FAULT_INJECT
stripped — the injected fault already fired, and a warm restart runs
clean, mirroring the resilience module's one-shot contract.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import os
import sys
import threading

from cpr_tpu import resilience, supervisor, telemetry
from cpr_tpu.latency import LatencyBoard
from cpr_tpu.monitor.blackbox import dump_blackbox
from cpr_tpu.monitor.expo import MetricsServer
from cpr_tpu.monitor.registry import MetricsRegistry
from cpr_tpu.serve import protocol as wire

_FWD_ERRORS = (wire.ProtocolError, ConnectionError, OSError)


def _fleet_event(action: str, **detail):
    """Router-side `serve` event call site: the fleet-scope records
    (action `fleet_report`) the perf ledger lifts into `fleet_p99_s`
    rows (EVENT_FIELDS['serve'])."""
    telemetry.current().event("serve", action=action, session=None,
                              detail=detail)


def _route_event(action: str, replica, op, **extra):
    """The one `route` event call site (EVENT_FIELDS['route'])."""
    telemetry.current().event("route", action=action, replica=replica,
                              op=op, **extra)


def _admission_event(reason, op, priority, tenant, retry_after_s):
    """The router-side `admission` call site: fires when the router
    itself must refuse (no live replica / pinned replica lost) — the
    same in-band contract as the replicas' shed path."""
    telemetry.current().event(
        "admission", reason=reason, op=op, priority=priority,
        tenant=tenant, retry_after_s=retry_after_s)


def _router_request_event(trace_id, op, status, queue_wait_s, service_s,
                          total_s):
    """The one router-side `request` event call site: queue_wait_s /
    service_s are the replica's own breakdown copied off the reply,
    total_s the router wall — so `total_s(router) - total_s(server)`
    is the routing hop (trace_stitch's `route` leg)."""
    telemetry.current().event(
        "request", trace_id=trace_id, op=op, status=status,
        queue_wait_s=queue_wait_s, service_s=service_s, total_s=total_s,
        role="router", run=telemetry.run_id())


class _Conn:
    __slots__ = ("reader", "writer")

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer


class Replica:
    """One supervised server child: lifecycle state, its live Popen
    (for orphan cleanup), and a small pool of persistent connections.
    A connection is held exclusively for the duration of one forward
    (the protocol answers in order per connection), so concurrency =
    pool size, grown on demand."""

    def __init__(self, index: int):
        self.index = index
        self.state = "starting"  # starting | up | down
        self.ready_file = None
        self.host = None
        self.port = None
        self.metrics_port = None  # the child's own HTTP exposition
        self.proc = None
        self.thread = None
        self.attempt = None  # supervisor.Attempt once the child exits
        self.exited = threading.Event()
        self.inflight = 0
        self.restarts = 0
        self._pool: list[_Conn] = []

    async def acquire(self) -> _Conn:
        if self._pool:
            return self._pool.pop()
        reader, writer = await asyncio.open_connection(self.host,
                                                       self.port)
        return _Conn(reader, writer)

    def release(self, conn: _Conn, broken: bool = False):
        if broken:
            conn.writer.close()
        else:
            self._pool.append(conn)

    def close_pool(self):
        for c in self._pool:
            c.writer.close()
        self._pool.clear()


class ServeRouter:
    """Front-end process: spawns/supervises the replicas and routes."""

    def __init__(self, child_args: list, n_replicas: int, *,
                 workdir: str, host: str = "127.0.0.1", port: int = 0,
                 ready_file: str | None = None, heartbeat_s: float = 1.0,
                 wall_s: float = 3600.0, quiet_s: float = 60.0,
                 max_restarts: int = 1, pick_wait_s: float = 60.0,
                 seed_base: int = 1 << 21,
                 metrics_port: int | None = None):
        if n_replicas <= 0:
            raise ValueError(f"need at least one replica, got {n_replicas}")
        self.child_args = list(child_args)
        self.workdir = workdir
        self.host = host
        self.port = port  # replaced by the bound port in run()
        self.ready_file = ready_file
        self.heartbeat_s = heartbeat_s
        self.wall_s = wall_s
        self.quiet_s = quiet_s
        self.max_restarts = max_restarts
        self.pick_wait_s = pick_wait_s
        self.replicas = [Replica(i) for i in range(n_replicas)]
        # router-stamped seeds live above the servers' own seed base
        # (1 << 20), so fleet-assigned and replica-assigned seeds can
        # never collide — and every episode.run that reaches a replica
        # carries an explicit seed, which is what makes failover replay
        # deterministic
        self._seed = itertools.count(seed_base)
        self._rsid = itertools.count(1)
        # router session id -> (replica index, replica session id)
        self._sessions: dict[int, tuple] = {}
        self._routed = 0
        self._requeued = 0
        self._refused = 0
        # fleet-wide latency view: REBUILT from the replicas' raw
        # bucket payloads on every refresh (exact bucket-sum merge of
        # cumulative per-replica counts — never quantile-of-quantiles,
        # and idempotent because each refresh starts from zero), so
        # the registry holds it through a callable, not a reference
        self._fleet_board = LatencyBoard()
        self.metrics = MetricsRegistry(namespace="cpr_router")
        self.metrics.attach_board(
            "fleet_latency_seconds", lambda: self._fleet_board,
            help="fleet-merged per-op-family reply latency (seconds)")
        self.metrics_port = metrics_port  # bound port after run() binds
        self.metrics_server: MetricsServer | None = None
        self._server = None
        self._draining = False
        self._drain_reason = None

    # -- child lifecycle ---------------------------------------------------

    def _child_cmd(self, rep: Replica) -> list:
        cmd = [sys.executable, "-m", "cpr_tpu.serve.server",
               *self.child_args,
               "--host", "127.0.0.1", "--port", "0",
               "--ready-file", rep.ready_file,
               "--replica-index", str(rep.index),
               "--heartbeat-s", str(self.heartbeat_s)]
        if self.metrics_port is not None:
            # a metrics-serving fleet exposes every layer: each child
            # binds its own ephemeral scrape port (published through
            # its ready file, read back in _try_ready)
            cmd += ["--metrics-port", "0"]
        return cmd

    def _child_env(self, rep: Replica) -> dict:
        env = dict(os.environ)
        sink = env.get(telemetry.TELEMETRY_ENV_VAR)
        if sink:
            # per-replica telemetry sinks: two processes appending one
            # JSONL file would interleave mid-line
            base, ext = os.path.splitext(sink)
            env[telemetry.TELEMETRY_ENV_VAR] = \
                f"{base}.replica{rep.index}{ext or '.jsonl'}"
        if rep.restarts > 0:
            # the injected fault already fired in the previous
            # incarnation; a warm restart runs clean (one-shot contract)
            env.pop(resilience.FAULT_ENV_VAR, None)
        return env

    def _spawn(self, rep: Replica):
        rep.state = "starting"
        rep.exited.clear()
        rep.proc = None
        rep.attempt = None
        rep.ready_file = os.path.join(
            self.workdir, f"replica{rep.index}-r{rep.restarts}.json")
        cmd = self._child_cmd(rep)
        env = self._child_env(rep)

        def run():
            try:
                rep.attempt = supervisor.run_child(
                    cmd, wall_timeout_s=self.wall_s,
                    quiet_s=self.quiet_s, heartbeat_s=self.heartbeat_s,
                    env=env,
                    on_start=lambda proc: setattr(rep, "proc", proc))
            finally:
                rep.exited.set()

        rep.thread = threading.Thread(
            target=run, name=f"cpr-replica{rep.index}", daemon=True)
        rep.thread.start()

    def _try_ready(self, rep: Replica):
        try:
            with open(rep.ready_file, encoding="utf-8") as f:
                info = json.load(f)
            rep.host, rep.port = info["host"], int(info["port"])
            rep.metrics_port = info.get("metrics_port")
        except (OSError, ValueError, KeyError):
            return
        rep.state = "up"
        _route_event("replica_up", rep.index, None, port=rep.port,
                     metrics_port=rep.metrics_port,
                     restarts=rep.restarts)

    def _mark_down(self, rep: Replica, reason: str):
        rep.state = "down"
        rep.close_pool()
        # pinned interactive sessions die with their replica: purge
        # now, refuse in-band at their next request
        lost = [k for k, v in self._sessions.items() if v[0] == rep.index]
        for k in lost:
            self._sessions.pop(k, None)
        att = rep.attempt
        _route_event("replica_down", rep.index, None, reason=reason,
                     status=getattr(att, "status", None),
                     rc=getattr(att, "rc", None),
                     lost_sessions=len(lost))
        if (not self._draining and self._drain_reason is None
                and rep.restarts < self.max_restarts):
            rep.restarts += 1
            self._spawn(rep)

    async def _monitor(self):
        while True:
            for rep in self.replicas:
                if rep.exited.is_set() and rep.state != "down":
                    att = rep.attempt
                    self._mark_down(
                        rep, f"child exited "
                             f"({getattr(att, 'status', 'unknown')})")
                elif rep.state == "starting":
                    self._try_ready(rep)
            await asyncio.sleep(0.05)

    async def _wait_all_up(self, timeout_s: float = 600.0):
        deadline = telemetry.now() + timeout_s
        while telemetry.now() < deadline:
            if all(r.state == "up" for r in self.replicas):
                return
            dead = [r for r in self.replicas
                    if r.state == "down" and r.restarts >= self.max_restarts]
            if dead:
                raise RuntimeError(
                    f"replica {dead[0].index} failed to start "
                    f"(status {getattr(dead[0].attempt, 'status', None)})")
            await asyncio.sleep(0.1)
        raise RuntimeError("replicas did not come up within "
                           f"{timeout_s}s")

    # -- routing -----------------------------------------------------------

    def _pick(self, exclude: set) -> Replica | None:
        up = [r for r in self.replicas
              if r.state == "up" and r.index not in exclude]
        if not up:
            return None
        return min(up, key=lambda r: (r.inflight, r.index))

    async def _pick_wait(self, exclude: set) -> Replica | None:
        """Least-loaded up replica; rides out a restart window (some
        replica still starting) up to pick_wait_s before giving up."""
        deadline = telemetry.now() + self.pick_wait_s
        while True:
            rep = self._pick(exclude)
            if rep is not None:
                return rep
            starting = any(r.state == "starting" and r.index not in exclude
                           for r in self.replicas)
            if (not starting or self._drain_reason is not None
                    or telemetry.now() > deadline):
                return None
            await asyncio.sleep(0.1)

    async def _forward(self, rep: Replica, req: dict) -> dict:
        rep.inflight += 1
        conn = None
        try:
            conn = await rep.acquire()
            await wire.write_frame(conn.writer, req)
            resp = await wire.read_frame(conn.reader)
            if resp is None:
                raise wire.ProtocolError("replica closed the connection")
            rep.release(conn)
            conn = None
            return resp
        finally:
            rep.inflight -= 1
            if conn is not None:
                rep.release(conn, broken=True)

    def _refuse(self, reason: str, op, priority=None, tenant=None,
                replica=None) -> dict:
        self._refused += 1
        # a restarting replica is capacity coming back: quote roughly
        # its bring-up time, else a short poll interval
        retry_after = 5.0 if any(r.state == "starting"
                                 for r in self.replicas) else 1.0
        _route_event("refuse", replica, op, reason=reason)
        _admission_event(reason, op, priority, tenant, retry_after)
        return dict(ok=False, error=f"shed: {reason}", shed=True,
                    reason=reason, retry_after=retry_after)

    async def _route_failover(self, req: dict, op: str) -> dict:
        """Forward with requeue-on-replica-loss.  Only called for
        requests that are safe to re-forward: episode.run (fully
        determined by its stamped seed) and the stateless queries."""
        tried: set = set()
        first = True
        while True:
            rep = await self._pick_wait(tried)
            if rep is None:
                return self._refuse("replica_lost", op,
                                    req.get("priority"), req.get("tenant"))
            if first:
                self._routed += 1
            else:
                self._requeued += 1
            _route_event("route" if first else "requeue", rep.index, op,
                         seed=req.get("seed"))
            try:
                resp = await self._forward(rep, req)
            except _FWD_ERRORS:
                tried.add(rep.index)
                first = False
                continue
            if (op == "hello" and isinstance(resp, dict)
                    and resp.get("ok")):
                resp["router"] = True
                resp["replicas"] = sum(r.state == "up"
                                       for r in self.replicas)
            return resp

    async def _route_episode_run(self, req: dict) -> dict:
        if req.get("seed") is None:
            req["seed"] = next(self._seed)
        return await self._route_failover(req, "episode.run")

    async def _route_episode_open(self, req: dict) -> dict:
        tried: set = set()
        rep = await self._pick_wait(tried)
        if rep is None:
            return self._refuse("replica_lost", "episode.open",
                                req.get("priority"), req.get("tenant"))
        self._routed += 1
        _route_event("route", rep.index, "episode.open")
        try:
            resp = await self._forward(rep, req)
        except _FWD_ERRORS:
            # the lane may or may not have been admitted; the state is
            # gone either way — refuse, the client reopens
            return self._refuse("replica_lost", "episode.open",
                                req.get("priority"), req.get("tenant"),
                                replica=rep.index)
        if isinstance(resp, dict) and resp.get("ok") \
                and "session" in resp:
            rsid = next(self._rsid)
            self._sessions[rsid] = (rep.index, resp["session"])
            resp["session"] = rsid
        return resp

    async def _route_pinned(self, req: dict, op: str) -> dict:
        rsid = req.get("session")
        pin = self._sessions.get(rsid)
        if pin is None:
            if op == "episode.close":
                return dict(ok=True)
            return dict(ok=False, error="no such open session")
        idx, sid = pin
        rep = self.replicas[idx]
        if rep.state != "up":
            self._sessions.pop(rsid, None)
            return self._refuse("replica_lost", op, replica=idx)
        try:
            resp = await self._forward(rep, dict(req, session=sid))
        except _FWD_ERRORS:
            self._sessions.pop(rsid, None)
            return self._refuse("replica_lost", op, replica=idx)
        if isinstance(resp, dict):
            if resp.get("session") == sid:
                resp["session"] = rsid
            if op == "episode.close" or resp.get("done"):
                self._sessions.pop(rsid, None)
        return resp

    async def _op_stats(self, req: dict) -> dict:
        per = {}
        for rep in self.replicas:
            if rep.state != "up":
                per[str(rep.index)] = dict(state=rep.state)
                continue
            try:
                r = await self._forward(rep, dict(op="stats"))
                r["state"] = "up"
                per[str(rep.index)] = r
            except _FWD_ERRORS:
                per[str(rep.index)] = dict(state="down")
        # the stats replies already carry each replica's raw bucket
        # payload — fold them into the fleet view on the way through
        board = self._merge_fleet(
            r.get("latencies_raw") for r in per.values())
        return dict(ok=True, router=self.router_stats(),
                    fleet=dict(latencies=board.snapshot(),
                               latencies_raw=board.to_dict()),
                    replicas=per)

    # -- fleet health plane ------------------------------------------------

    def _merge_fleet(self, raws) -> LatencyBoard:
        """Fresh fleet board from replica raw-bucket payloads: an
        EXACT bucket-sum merge of cumulative per-replica counts (the
        boards share one edge grid), never quantile-of-quantiles.
        Rebuilding from zero each time makes a refresh idempotent —
        cumulative payloads re-merged into a carried-over board would
        double-count.  The new board REPLACES the old one (the
        registry reads it through a callable)."""
        board = LatencyBoard()
        for raw in raws:
            if isinstance(raw, dict):
                board.merge_dict(raw)
        self._fleet_board = board
        return board

    async def _refresh_fleet(self) -> LatencyBoard:
        """Scrape every up replica in-band and rebuild the fleet
        board; refresh the router gauges alongside."""
        raws = []
        for rep in self.replicas:
            if rep.state != "up":
                continue
            try:
                r = await self._forward(rep, dict(op="metrics.scrape"))
            except _FWD_ERRORS:
                continue
            if isinstance(r, dict):
                raws.append(r.get("latencies_raw"))
        board = self._merge_fleet(raws)
        self._refresh_gauges()
        return board

    def _refresh_gauges(self):
        g = self.metrics.set
        g("routed", self._routed, help="sessions routed to replicas")
        g("requeued", self._requeued,
          help="failover re-forwards after replica loss")
        g("refused", self._refused, help="router-level refusals")
        g("open_sessions", len(self._sessions),
          help="pinned interactive sessions")
        for rep in self.replicas:
            g("replica_up", 1.0 if rep.state == "up" else 0.0,
              replica=str(rep.index), help="replica liveness (1 = up)")
            g("replica_restarts", rep.restarts,
              replica=str(rep.index), help="warm restarts, per replica")

    def fleet_p99_s(self, board: LatencyBoard | None = None) -> dict:
        """{family: p99 seconds} over the merged fleet board; empty
        families are omitted (never a None value — the ledger lift
        and burn-rate math downstream assume numbers)."""
        board = board if board is not None else self._fleet_board
        out = {}
        for fam in board.families:
            q = board.get(fam).quantile(0.99)
            if q is not None:
                out[fam] = q
        return out

    def router_stats(self) -> dict:
        return dict(
            routed=self._routed, requeued=self._requeued,
            refused=self._refused, open_sessions=len(self._sessions),
            replica_state={str(r.index): r.state
                           for r in self.replicas},
            restarts={str(r.index): r.restarts for r in self.replicas})

    # -- the front-end server ----------------------------------------------

    async def _handle(self, reader, writer):
        try:
            while True:
                req = await wire.read_frame(reader)
                if req is None:
                    break
                resp = await self._serve_request(req)
                await wire.write_frame(writer, resp)
        except (wire.ProtocolError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _serve_request(self, req: dict) -> dict:
        trace = req.get("_trace") if isinstance(req.get("_trace"),
                                                dict) else {}
        trace_id = trace.get("id") or telemetry.new_trace_id()
        # forward the client's trace id verbatim: all three streams
        # (client / router / replica) share one id per request
        req["_trace"] = dict(id=trace_id, run=telemetry.run_id(),
                             parent=trace.get("parent"))
        t0 = telemetry.now()
        try:
            resp = await self._dispatch(req)
        except Exception as e:  # noqa: BLE001 — per-request wall
            resp = dict(ok=False, error=f"{type(e).__name__}: {e}")
        if not isinstance(resp, dict):
            resp = dict(ok=False, error="handler returned no dict")
        total_s = telemetry.now() - t0
        lat = resp.get("latency")
        if not (isinstance(lat, dict) and "total_s" in lat):
            lat = dict(queue_wait_s=0.0, service_s=total_s,
                       total_s=total_s)
            resp["latency"] = lat
        resp["trace_id"] = trace_id
        status = ("ok" if resp.get("ok")
                  else "refused" if resp.get("draining")
                  or resp.get("shed") else "error")
        _router_request_event(trace_id, req.get("op"), status,
                              lat.get("queue_wait_s"),
                              lat.get("service_s"), total_s)
        return resp

    async def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "drain":
            self._drain_reason = self._drain_reason or str(
                req.get("reason", "client"))
            return dict(ok=True, draining=True)
        if op == "stats":
            return await self._op_stats(req)
        if op == "metrics.scrape":
            # answered at the router, not forwarded: the reply is the
            # router's own registry plus the freshly merged fleet view
            board = await self._refresh_fleet()
            return dict(ok=True, metrics=self.metrics.to_json(),
                        fleet=dict(latencies=board.snapshot(),
                                   latencies_raw=board.to_dict(),
                                   p99_s=self.fleet_p99_s(board)))
        if self._draining or self._drain_reason is not None:
            if op in ("episode.run", "episode.open"):
                return dict(ok=False, error="draining", draining=True)
        if op == "episode.run":
            return await self._route_episode_run(req)
        if op == "episode.open":
            return await self._route_episode_open(req)
        if op in ("episode.step", "episode.close"):
            return await self._route_pinned(req, op)
        # hello / netsim.query / break_even.* / unknown ops: stateless
        # and idempotent on the replicas, so plain failover forwarding
        return await self._route_failover(req, op)

    # -- lifecycle ---------------------------------------------------------

    async def run(self):
        os.makedirs(self.workdir, exist_ok=True)
        for rep in self.replicas:
            self._spawn(rep)
        monitor = asyncio.create_task(self._monitor())
        try:
            self._server = await asyncio.start_server(
                self._handle, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]
            if self.metrics_port is not None:
                self.metrics_server = MetricsServer(
                    self.metrics.render_prometheus, host=self.host,
                    port=self.metrics_port)
                self.metrics_port = self.metrics_server.start()
            # prime the gauges so a scrape between bind and the first
            # fleet refresh sees real samples, not comments only
            self._refresh_gauges()
            await self._wait_all_up()
            if self.ready_file:
                resilience.atomic_write_json(
                    self.ready_file,
                    dict(host=self.host, port=self.port,
                         pid=os.getpid(),
                         replicas=len(self.replicas),
                         metrics_port=self.metrics_port,
                         replica_metrics_ports={
                             str(r.index): r.metrics_port
                             for r in self.replicas}))
            fleet_last = telemetry.now()
            while (self._drain_reason is None
                   and not resilience.preempt_requested()):
                await asyncio.sleep(0.05)
                if telemetry.now() - fleet_last >= self.heartbeat_s:
                    # periodic fleet merge + gauge refresh, so the
                    # HTTP exposition stays live between client
                    # scrapes (one in-band scrape per replica per
                    # heartbeat — negligible next to the traffic)
                    fleet_last = telemetry.now()
                    await self._refresh_fleet()
            reason = self._drain_reason or \
                f"preempt:{resilience.preempt_reason()}"
            await self._drain(reason)
        finally:
            monitor.cancel()
            for rep in self.replicas:
                rep.close_pool()
                if rep.proc is not None and rep.proc.poll() is None:
                    rep.proc.kill()

    async def _drain(self, reason: str):
        self._draining = True
        _route_event("drain", None, None, reason=reason)
        # final fleet merge while the replicas are still up, then the
        # fleet_report record: perf/ledger.py lifts its fleet_p99_s
        # into per-family ledger rows (the fleet-wide SLO trail)
        board = await self._refresh_fleet()
        _fleet_event("fleet_report", reason=reason,
                     replicas=sum(r.state == "up"
                                  for r in self.replicas),
                     fleet_p99_s=self.fleet_p99_s(board),
                     latencies=board.snapshot())
        for rep in self.replicas:
            if rep.state != "up":
                continue
            try:
                await self._forward(rep, dict(
                    op="drain", reason=f"router:{reason}"))
            except _FWD_ERRORS:
                pass
        # bounded wait for the children's own drain -> report -> exit
        deadline = telemetry.now() + 120.0
        for rep in self.replicas:
            while (not rep.exited.is_set()
                   and telemetry.now() < deadline):
                await asyncio.sleep(0.1)
        _route_event("stop", None, None, reason=reason,
                     **self.router_stats())
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None


# -- entry point -------------------------------------------------------------


def main(argv=None) -> int:
    import argparse
    import tempfile

    p = argparse.ArgumentParser(
        description="cpr_tpu serve fleet router (see docs/SERVING.md)")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--ready-file", default=None,
                   help="atomic JSON {host,port,pid,replicas} once "
                        "every replica is up")
    p.add_argument("--workdir", default=None,
                   help="replica ready files (default: a temp dir)")
    p.add_argument("--heartbeat-s", type=float, default=1.0)
    p.add_argument("--replica-wall-s", type=float, default=3600.0)
    p.add_argument("--replica-quiet-s", type=float, default=60.0)
    p.add_argument("--max-restarts", type=int, default=1,
                   help="warm restarts per replica outside a drain")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve fleet-merged Prometheus metrics over"
                        " HTTP on this port (0 = ephemeral; lands in"
                        " the ready file) and give every replica its"
                        " own ephemeral scrape port; default: no HTTP"
                        " exposition")
    # pass-through server geometry/admission flags
    p.add_argument("--protocol", default="nakamoto")
    p.add_argument("--alpha", type=float, default=0.25)
    p.add_argument("--gamma", type=float, default=0.5)
    p.add_argument("--activation-delay", type=float, default=1.0)
    p.add_argument("--max-steps", type=int, default=256)
    p.add_argument("--lanes", type=int, default=32)
    p.add_argument("--burst", type=int, default=256)
    p.add_argument("--devices", type=int, default=1,
                   help="per-replica lane-block device span (forwarded"
                        " to each server child; docs/SCALING.md)")
    p.add_argument("--policy-snapshot", default=None)
    p.add_argument("--slo-s", type=float, default=None)
    p.add_argument("--max-queue", type=int, default=None)
    p.add_argument("--tenant-quota", type=int, default=None)
    args = p.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="cpr-router-")
    child_args = ["--protocol", args.protocol,
                  "--alpha", str(args.alpha),
                  "--gamma", str(args.gamma),
                  "--activation-delay", str(args.activation_delay),
                  "--max-steps", str(args.max_steps),
                  "--lanes", str(args.lanes),
                  "--burst", str(args.burst),
                  "--devices", str(args.devices)]
    if args.policy_snapshot:
        child_args += ["--policy-snapshot", args.policy_snapshot]
    if args.slo_s is not None:
        child_args += ["--slo-s", str(args.slo_s)]
    if args.max_queue is not None:
        child_args += ["--max-queue", str(args.max_queue)]
    if args.tenant_quota is not None:
        child_args += ["--tenant-quota", str(args.tenant_quota)]

    router = ServeRouter(
        child_args, args.replicas, workdir=workdir, host=args.host,
        port=args.port, ready_file=args.ready_file,
        heartbeat_s=args.heartbeat_s, wall_s=args.replica_wall_s,
        quiet_s=args.replica_quiet_s, max_restarts=args.max_restarts,
        metrics_port=args.metrics_port)
    # the router's own backend-bearing manifest: its trace carries the
    # fleet_report record, and the perf ledger attributes those rows
    # to this config (entry "router", fleet geometry) — without it the
    # router stream would not validate standalone
    telemetry.current().manifest(config=dict(
        entry="router", replicas=args.replicas,
        protocol=args.protocol, n_lanes=args.lanes, burst=args.burst,
        devices=args.devices, max_steps=args.max_steps,
        alpha=args.alpha, gamma=args.gamma))
    with resilience.preemption_guard():
        # flight recorder: a crash unwinding the router loop dumps the
        # telemetry ring before re-raising; a preemption drain dumps
        # on the way out (the preempt flag outlives the guard body)
        try:
            asyncio.run(router.run())
        except BaseException as e:  # noqa: BLE001 — dump-and-reraise
            dump_blackbox(f"router:{type(e).__name__}")
            raise
        if resilience.preempt_requested():
            dump_blackbox(
                f"router:preempt:{resilience.preempt_reason()}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
