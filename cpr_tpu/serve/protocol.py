"""Wire protocol for cpr_tpu.serve: length-prefixed JSON frames.

One frame = a 4-byte big-endian unsigned length followed by that many
bytes of UTF-8 JSON.  Requests and responses are JSON objects; every
request carries an `op` key, every response an `ok` bool.  The server
answers frames on one connection strictly in order, so a blocking
request/response client (`ServeClient`, used by tools/serve_smoke.py
and the tests) needs no correlation ids.

Trace context (schema v8): `_trace` is a reserved request field —
`{"id": <trace_id>, "run": <run_id>, "parent": <span or null>}` —
which the server propagates into its `request` telemetry event and
echoes back as `trace_id`, so the client- and server-side events of
one request correlate across JSONL streams (tools/trace_stitch.py).
`ServeClient.request` stamps it automatically, times the full
round-trip, and emits the client-side `request` event (a no-op
without a telemetry sink).
"""

from __future__ import annotations

import json
import socket
import struct

from cpr_tpu import telemetry

_HEADER = struct.Struct(">I")
# generous ceiling: the largest legitimate frame (an interactive step
# info dict) is well under 1 MB; anything bigger is a framing bug
MAX_FRAME = 16 << 20


class ProtocolError(RuntimeError):
    pass


def pack_frame(obj) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise ProtocolError(f"frame of {len(body)} bytes exceeds "
                            f"MAX_FRAME={MAX_FRAME}")
    return _HEADER.pack(len(body)) + body


def _decode(body: bytes):
    try:
        return json.loads(body.decode("utf-8"))
    except ValueError as e:
        raise ProtocolError(f"undecodable frame: {e}") from e


async def read_frame(reader):
    """Read one frame from an asyncio StreamReader; None on clean EOF
    at a frame boundary."""
    import asyncio

    try:
        header = await reader.readexactly(_HEADER.size)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise ProtocolError("connection closed mid-header") from e
    (n,) = _HEADER.unpack(header)
    if n > MAX_FRAME:
        raise ProtocolError(f"frame length {n} exceeds {MAX_FRAME}")
    try:
        body = await reader.readexactly(n)
    except asyncio.IncompleteReadError as e:
        raise ProtocolError("connection closed mid-frame") from e
    return _decode(body)


async def write_frame(writer, obj):
    writer.write(pack_frame(obj))
    await writer.drain()


def _client_request_event(trace_id, op, status, queue_wait_s,
                          service_s, total_s):
    """The one client-side `request` event call site
    (EVENT_FIELDS['request']); the server-side twin lives in
    server.py.  queue_wait/service are the server's own breakdown
    copied off the reply; total is the client wall, so
    `total_s(client) - total_s(server)` is the wire + framing
    overhead (the `reply` leg in trace_stitch's critical path)."""
    telemetry.current().event(
        "request", trace_id=trace_id, op=op, status=status,
        queue_wait_s=queue_wait_s, service_s=service_s,
        total_s=total_s, role="client", run=telemetry.run_id())


class ServeClient:
    """Blocking request/response client over one TCP connection."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self._sock.recv(n)
            if not chunk:
                raise ProtocolError("server closed the connection")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def request(self, op: str, **fields):
        trace_id = telemetry.new_trace_id()
        t0 = telemetry.now()
        self._sock.sendall(pack_frame(dict(
            fields, op=op,
            _trace=dict(id=trace_id, run=telemetry.run_id(),
                        parent=None))))
        (n,) = _HEADER.unpack(self._recv_exact(_HEADER.size))
        if n > MAX_FRAME:
            raise ProtocolError(f"frame length {n} exceeds {MAX_FRAME}")
        resp = _decode(self._recv_exact(n))
        total_s = telemetry.now() - t0
        lat = resp.get("latency") if isinstance(resp, dict) else None
        lat = lat if isinstance(lat, dict) else {}
        status = ("ok" if resp.get("ok")
                  else "refused" if resp.get("draining") else "error") \
            if isinstance(resp, dict) else "error"
        _client_request_event(trace_id, op, status,
                              lat.get("queue_wait_s"),
                              lat.get("service_s"), total_s)
        return resp

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
