"""Wire protocol for cpr_tpu.serve: length-prefixed JSON frames.

One frame = a 4-byte big-endian unsigned length followed by that many
bytes of UTF-8 JSON.  Requests and responses are JSON objects; every
request carries an `op` key, every response an `ok` bool.  The server
answers frames on one connection strictly in order, so a blocking
request/response client (`ServeClient`, used by tools/serve_smoke.py
and the tests) needs no correlation ids.

Trace context (schema v8): `_trace` is a reserved request field —
`{"id": <trace_id>, "run": <run_id>, "parent": <span or null>}` —
which the server propagates into its `request` telemetry event and
echoes back as `trace_id`, so the client- and server-side events of
one request correlate across JSONL streams (tools/trace_stitch.py).
`ServeClient.request` stamps it automatically, times the full
round-trip, and emits the client-side `request` event (a no-op
without a telemetry sink).
"""

from __future__ import annotations

import json
import socket
import struct
import time

from cpr_tpu import resilience, telemetry

_HEADER = struct.Struct(">I")
# generous ceiling: the largest legitimate frame (an interactive step
# info dict) is well under 1 MB; anything bigger is a framing bug
MAX_FRAME = 16 << 20


class ProtocolError(RuntimeError):
    pass


class ShedRefusal(resilience.TransientFault):
    """In-band admission-control refusal (`shed: ...` with a
    `retry_after` hint): transient in the shared taxonomy — the server
    is up, just loaded, so backing off and retrying is correct."""

    def __init__(self, resp: dict):
        super().__init__(resp.get("error", "shed"))
        self.resp = resp
        try:
            self.retry_after_s = float(resp.get("retry_after") or 0.0)
        except (TypeError, ValueError):
            self.retry_after_s = 0.0


class DrainRefusal(RuntimeError):
    """In-band drain refusal: terminal — this server is going away, so
    retrying against it is wrong (a router retries elsewhere)."""

    def __init__(self, resp: dict):
        super().__init__(resp.get("error", "draining"))
        self.resp = resp


def pack_frame(obj) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise ProtocolError(f"frame of {len(body)} bytes exceeds "
                            f"MAX_FRAME={MAX_FRAME}")
    return _HEADER.pack(len(body)) + body


def _decode(body: bytes):
    try:
        return json.loads(body.decode("utf-8"))
    except ValueError as e:
        raise ProtocolError(f"undecodable frame: {e}") from e


async def read_frame(reader):
    """Read one frame from an asyncio StreamReader; None on clean EOF
    at a frame boundary."""
    import asyncio

    try:
        header = await reader.readexactly(_HEADER.size)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise ProtocolError("connection closed mid-header") from e
    (n,) = _HEADER.unpack(header)
    if n > MAX_FRAME:
        raise ProtocolError(f"frame length {n} exceeds {MAX_FRAME}")
    try:
        body = await reader.readexactly(n)
    except asyncio.IncompleteReadError as e:
        raise ProtocolError("connection closed mid-frame") from e
    return _decode(body)


async def write_frame(writer, obj):
    writer.write(pack_frame(obj))
    await writer.drain()


def _client_request_event(trace_id, op, status, queue_wait_s,
                          service_s, total_s):
    """The one client-side `request` event call site
    (EVENT_FIELDS['request']); the server-side twin lives in
    server.py.  queue_wait/service are the server's own breakdown
    copied off the reply; total is the client wall, so
    `total_s(client) - total_s(server)` is the wire + framing
    overhead (the `reply` leg in trace_stitch's critical path)."""
    telemetry.current().event(
        "request", trace_id=trace_id, op=op, status=status,
        queue_wait_s=queue_wait_s, service_s=service_s,
        total_s=total_s, role="client", run=telemetry.run_id())


class ServeClient:
    """Blocking request/response client over one TCP connection."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._addr = (host, port)
        self._timeout = timeout
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self._sock.recv(n)
            if not chunk:
                raise ProtocolError("server closed the connection")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def request(self, op: str, **fields):
        trace_id = telemetry.new_trace_id()
        t0 = telemetry.now()
        self._sock.sendall(pack_frame(dict(
            fields, op=op,
            _trace=dict(id=trace_id, run=telemetry.run_id(),
                        parent=None))))
        (n,) = _HEADER.unpack(self._recv_exact(_HEADER.size))
        if n > MAX_FRAME:
            raise ProtocolError(f"frame length {n} exceeds {MAX_FRAME}")
        resp = _decode(self._recv_exact(n))
        total_s = telemetry.now() - t0
        lat = resp.get("latency") if isinstance(resp, dict) else None
        lat = lat if isinstance(lat, dict) else {}
        status = ("ok" if resp.get("ok")
                  else "refused" if resp.get("draining")
                  or resp.get("shed") else "error") \
            if isinstance(resp, dict) else "error"
        _client_request_event(trace_id, op, status,
                              lat.get("queue_wait_s"),
                              lat.get("service_s"), total_s)
        return resp

    def call_with_retry(self, op: str, *, max_attempts: int = 5,
                        base_delay_s: float = 0.25,
                        max_delay_s: float = 30.0, sleep=time.sleep,
                        **fields):
        """`request` through the shared retry taxonomy
        (resilience.with_retries): shed refusals are transient and the
        backoff honors the server's `retry_after` hint (the in-band
        contract: a shed reply quotes when capacity is expected back),
        connection loss is transient with an automatic reconnect, and
        a drain refusal is terminal — `DrainRefusal` propagates, since
        this server is going away and only a router can retry
        elsewhere.  Returns the successful reply dict."""
        hint = {"s": 0.0}

        def attempt():
            if self._sock is None:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
            try:
                resp = self.request(op, **fields)
            except (ProtocolError, ConnectionError, OSError):
                self.close()  # next attempt reconnects
                raise
            if isinstance(resp, dict) and not resp.get("ok"):
                if resp.get("shed"):
                    raise ShedRefusal(resp)
                if resp.get("draining"):
                    raise DrainRefusal(resp)
            return resp

        def classify(e) -> bool:
            if isinstance(e, ShedRefusal):
                hint["s"] = e.retry_after_s
                return True
            if isinstance(e, DrainRefusal):
                return False
            return resilience.default_classify(e)

        def _sleep(delay_s: float):
            # the exponential schedule is the floor; a larger server
            # hint stretches it (still capped), then the hint is spent
            sleep(min(max_delay_s, max(delay_s, hint["s"])))
            hint["s"] = 0.0

        return resilience.with_retries(
            attempt, classify=classify, max_attempts=max_attempts,
            base_delay_s=base_delay_s, max_delay_s=max_delay_s,
            sleep=_sleep, name=f"serve:{op}")

    def close(self):
        if self._sock is None:
            return
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
