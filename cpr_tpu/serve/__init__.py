"""cpr_tpu.serve — a continuously-batched evaluation & policy service.

One jitted, vmapped device program (the resident lane API grown on
`JaxEnv` in envs/base.py) stays resident for the life of the process;
an asyncio front-end multiplexes many concurrent client episodes onto
its lanes via continuous batching — lanes are admitted (spliced from a
fresh state) and retired on any device tick instead of padding work to
rollout boundaries.  The sampler/inference decoupling follows
*Accelerated Methods for Deep RL* (arXiv:1803.02811).

Layers (docs/SERVING.md has the full protocol and ops runbook):

  engine.py    ResidentEngine — owns the donated (state, obs) lane
               carry and the two resident programs: the interactive
               `step_lanes` tick and the K-step policy burst (scan with
               the policy table compiled in via `lax.switch`).
  scheduler.py LaneScheduler — host-side sessions->lanes placement and
               the admission queue (backfill source for freed lanes).
  server.py    asyncio front-end: length-prefixed JSON protocol,
               trained-policy / netsim / break-even endpoints,
               SLO-aware admission control (priority classes, tenant
               quotas, bounded queue, latency-aware shedding), serve
               telemetry, supervisor heartbeats, SIGTERM drain.
  router.py    multi-replica front-end: N supervised server children,
               load/priority routing, deterministic seed-replay
               failover on replica loss.
  protocol.py  frame codec + a blocking client (with retry_after-aware
               `call_with_retry`) for tools and tests.
"""

from cpr_tpu.serve.engine import ResidentEngine  # noqa: F401
from cpr_tpu.serve.protocol import ServeClient  # noqa: F401
from cpr_tpu.serve.scheduler import LaneScheduler  # noqa: F401
