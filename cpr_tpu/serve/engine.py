"""ResidentEngine: the device half of the serving layer.

Owns the (state, obs) lane carry and keeps TWO programs resident for
the life of the process, both jitted once and both with donated
carries (this file is on jaxlint's donate-carry hot-path list):

  * the interactive tick — `JaxEnv.step_lanes` (envs/base.py), shared
    with the gym adapters: admit/step/hold arbitrary lane subsets, one
    dispatch per tick;
  * the policy burst — a K-step `lax.scan` whose per-lane action is
    `lax.switch(policy_id, ...)` over the policy table compiled in at
    construction (env scripted policies + optional loaded PPO nets).
    K amortizes the host round-trip: at burst=256 and 32 lanes one
    dispatch advances 8192 env steps, which is what keeps sustained
    serve throughput within the 20%-of-`rollout()` acceptance band.
    Two details keep the burst at batch-`rollout()` speed: nothing is
    stacked per step — each lane's FIRST done (step index + episode
    aggregates) is captured into per-lane registers in the scan carry,
    which is all the server needs to complete a session — and loaded
    nets sit behind a scalar `lax.cond`, so bursts with no net-driven
    lane never execute the forward pass (a vmapped `switch` pays for
    every branch on every step).

Both paths advance lanes by the same `_lane_step` unit as `rollout`,
and admission seeds lanes through `init_lanes` (the rollout stream
prologue) — a session admitted with seed S therefore replays
`rollout(PRNGKey(S), ...)` bit-for-bit, mid-flight admissions and lane
reuse included (tests/test_serve.py).

Device-metrics cells (device_metrics.serve_spec) fold once per burst
INSIDE the jitted program from values the burst already produces —
never per step — plus one eager `burst_s` fold at drain from the
host-recorded dispatch walls.

`mesh=` shards the lane block over a 1-D device mesh
(parallel.make_sharded_lane_fns): both resident programs run with the
lane axis partitioned under matched NamedSharding in/out specs, the
burst's metrics cells reduce on-device (GSPMD inserts the psum for
the cross-shard sums the cells already compute), and `report()` /
drain reports stamp `n_devices` so the perf ledger banks per-device-
count rows (cfg_devices fingerprints).  `n_lanes` must divide the
mesh axis.  Per-lane semantics — admission, holds, seed replay — are
bit-identical to the single-device path (tests/test_sharded_lanes.py,
make multichip-smoke).  docs/SCALING.md covers the contract.

Always-on learning (docs/LEARNING.md) adds two orthogonal planes,
both build-time gated so the default burst is the exact pre-learning
program:

  * `swap_policies=` registers net policies whose parameters enter the
    jitted burst as an ARGUMENT rather than a closure constant —
    `swap_policy()` then replaces the host-side entry between bursts
    and the next dispatch runs the same compiled program with the new
    weights: zero drain, zero retrace, and lanes that completed before
    the swap boundary are bit-identical to a never-swapped engine
    (their registers were captured in earlier dispatches).
  * `experience=K` threads per-lane ring buffers (learn/buffer.py)
    through the donated burst carry: every live lane's transition is
    recorded in-graph with one masked scatter per step (ragged episode
    boundaries absorbed, never padded to the slowest lane), and
    `drain_experience()` consolidates full windows with one device_get
    at a burst boundary — the sampler half of the sampler/learner
    split.  `<name>#sample` policy variants draw categorical actions
    from fold_in-derived per-lane experience streams instead of the
    greedy argmax, which is what makes the served fleet explore.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cpr_tpu import device_metrics, telemetry
from cpr_tpu.envs.base import _lane_where

# per-lane first-done registers a burst returns: `done` (lane finished
# an episode this burst), `done_step` (step index within the burst),
# and the episode aggregates captured at that step
CAPTURE_FIELDS = ("episode_reward_attacker", "episode_reward_defender",
                  "episode_progress", "episode_n_steps")
BURST_FIELDS = ("done", "done_step") + CAPTURE_FIELDS


class ResidentEngine:
    """One resident lane block + policy table over a single JaxEnv."""

    def __init__(self, env, params, *, n_lanes: int, burst: int = 256,
                 extra_policies: dict | None = None,
                 swap_policies: dict | None = None,
                 sample_policies: tuple = (), experience: int = 0,
                 mesh=None, mesh_axis: str = "d"):
        if burst <= 0:
            raise ValueError(f"burst must be positive, got {burst}")
        self.env = env
        self.params = params
        self.n_lanes = int(n_lanes)
        self.burst = int(burst)
        self.mesh = mesh
        if mesh is not None:
            from cpr_tpu.parallel import make_sharded_lane_fns
            self._lanes = make_sharded_lane_fns(env, mesh,
                                                axis=mesh_axis)
            # fail at construction, not at the first dispatch
            from cpr_tpu.parallel import check_even_shards
            check_even_shards(self.n_lanes, mesh, axis=mesh_axis)
            self.n_devices = self._lanes.n_devices
        else:
            self._lanes = None
            self.n_devices = 1

        # policy table: the env's scripted policies (observation-only —
        # takes_state policies need the full state and cannot be served)
        # plus loaded nets, in a deterministic order so policy ids are
        # stable for the life of the process
        names = [n for n in sorted(env.policies)
                 if not getattr(env.policies[n], "takes_state", False)]
        fns = [env.policies[n] for n in names]
        for name in sorted(extra_policies or {}):
            names.append(name)
            fns.append(extra_policies[name])
        if not fns:
            raise ValueError("no servable policies: env has only "
                             "takes_state policies and no extra_policies")
        wrapped = tuple(
            (lambda o, f=f: jnp.asarray(f(o), jnp.int32)) for f in fns)
        # scripted policies form the always-on switch table; loaded
        # nets are gated behind a scalar lax.cond each (see _build_burst)
        n_scripted = len(names) - len(sorted(extra_policies or {}))
        if n_scripted:
            self._base_branches = wrapped[:n_scripted]
            self._gated = tuple(enumerate(wrapped[n_scripted:],
                                          start=n_scripted))
        else:
            self._base_branches = wrapped
            self._gated = ()

        # hot-swappable net policies: name -> (apply_fn, params,
        # fingerprint); params enter the burst as an argument (see
        # _build_burst) so swap_policy() never retraces.  `#sample`
        # variants draw from the experience key streams and therefore
        # require the experience plane.
        swap_policies = dict(swap_policies or {})
        sample_policies = tuple(sample_policies)
        unknown = [n for n in sample_policies if n not in swap_policies]
        if unknown:
            raise ValueError(f"sample_policies not registered as "
                             f"swap_policies: {unknown}")
        self.experience = int(experience)
        if sample_policies and not self.experience:
            raise ValueError(
                "sample_policies need the experience plane "
                "(experience > 0): per-lane action keys live in the "
                "experience buffer carry")
        self._swap_apply: dict = {}
        self._swap_params: dict = {}
        self._swap_fingerprint: dict = {}
        swap_gated, sample_gated = [], []
        for name in sorted(swap_policies):
            apply_fn, net_params, fp = swap_policies[name]
            if mesh is not None:
                net_params = jax.device_put(net_params,
                                            self._lanes.replicated)
            self._swap_apply[name] = apply_fn
            self._swap_params[name] = net_params
            self._swap_fingerprint[name] = fp
            names.append(name)
            swap_gated.append((len(names) - 1, name, apply_fn))
        for name in sorted(sample_policies):
            names.append(name + "#sample")
            sample_gated.append((len(names) - 1, name,
                                 self._swap_apply[name]))
        self._swap_gated = tuple(swap_gated)
        self._sample_gated = tuple(sample_gated)
        self.policy_names = tuple(names)
        self.policy_ids = {n: i for i, n in enumerate(names)}

        self._exp = None
        self._expbuf = None
        self._exp_stream = None
        if self.experience:
            from cpr_tpu.learn import buffer as expbuf
            self._expbuf = expbuf
            self._exp_stream = expbuf.experience_stream

        self._spec = device_metrics.serve_spec()
        self._with_metrics = device_metrics.enabled()
        self._macc = None
        self._burst_fn = self._build_burst()
        self._carry = None
        self._fresh0 = None

        # host-side throughput ledger (report() / the serve perf rows)
        self.steps = 0
        self.episodes = 0
        self.bursts = 0
        self.ticks = 0
        self.admitted = 0
        self.busy_s = 0.0
        self._occ_sum = 0.0
        self._burst_wall: list[float] = []
        # admission-control refusals, recorded by the server's shed
        # path; folded into the shed_sessions metrics cell at drain
        self.sheds = 0
        # learning-plane counters: total consolidated experience steps
        # drained, hot-swaps applied, and the dispatch-clock time of
        # the last swap (None until one lands) — the server derives
        # snapshot staleness from it
        self.samples = 0
        self.swaps = 0
        self.last_swap_t: float | None = None

    # -- program construction ---------------------------------------------

    def _build_burst(self):
        env, params, n = self.env, self.params, self.burst
        base, gated = self._base_branches, self._gated
        swap_gated, sample_gated = self._swap_gated, self._sample_gated
        spec, with_metrics = self._spec, self._with_metrics
        with_exp = bool(self.experience)
        expbuf = self._expbuf

        # the carry is (lane_carry, aux) where aux holds the optional
        # planes — metrics accumulator and experience rings — as dict
        # entries fixed at build time, so every gated-off combination
        # is the exact smaller program

        def burst(carry, policy_ids, live, occ, net_params):
            inner, aux = carry
            exp = aux.get("exp")
            # per-lane first-done registers: nothing is stacked per
            # step, so the scan's memory traffic is the carry alone
            info_sd = jax.eval_shape(
                lambda s: jax.vmap(lambda ss: env._lane_step(
                    ss, jnp.int32(0), params))(s)[5], inner[0])
            caps0 = {k: jnp.zeros(info_sd[k].shape, info_sd[k].dtype)
                     for k in CAPTURE_FIELDS}
            got0 = jnp.zeros(live.shape, bool)
            idx0 = jnp.zeros(live.shape, jnp.int32)

            def body(c, i):
                (state, obs), got, idx, caps, exp = c
                # scripted policies: one vmapped switch (ids of gated
                # lanes clamp into the table; their result is replaced)
                base_pid = jnp.clip(policy_ids, 0, len(base) - 1)
                actions = jax.vmap(
                    lambda pid, o: jax.lax.switch(pid, base, o)
                )(base_pid, obs)
                # loaded nets: scalar-predicate cond per net, so a
                # burst with no net-driven lane skips the forward pass
                for pid_c, fn in gated:
                    sel = (policy_ids == pid_c) & live
                    actions = jax.lax.cond(
                        jnp.any(sel),
                        lambda a, o=obs, s=sel, f=fn:
                            jnp.where(s, jax.vmap(f)(o), a),
                        lambda a: a, actions)
                # hot-swappable nets: weights come in through the
                # net_params ARGUMENT, not the closure — swap_policy()
                # replaces the host-side entry between bursts and this
                # same compiled program serves the new snapshot
                for pid_c, name, fn in swap_gated:
                    sel = (policy_ids == pid_c) & live
                    actions = jax.lax.cond(
                        jnp.any(sel),
                        lambda a, o=obs, s=sel, nm=name, f=fn:
                            jnp.where(s, jnp.argmax(jax.vmap(
                                lambda oo: f(net_params[nm], oo))(o),
                                axis=-1).astype(jnp.int32), a),
                        lambda a: a, actions)
                # sampling variants: categorical draws from the
                # per-lane experience streams (fold_in of the lane key
                # by the monotone step counter — learn/buffer.py)
                if sample_gated:
                    ks = expbuf.step_keys(exp)
                    for pid_c, name, fn in sample_gated:
                        sel = (policy_ids == pid_c) & live
                        actions = jax.lax.cond(
                            jnp.any(sel),
                            lambda a, o=obs, s=sel, nm=name, f=fn, kk=ks:
                                jnp.where(s, jax.vmap(
                                    lambda k1, oo: jax.random.categorical(
                                        k1, f(net_params[nm], oo))
                                )(kk, o).astype(jnp.int32), a),
                            lambda a: a, actions)
                new_state, obs_next, _, reward, done, info = jax.vmap(
                    lambda s, a: env._lane_step(s, a, params)
                )(state, actions)
                done = done & live
                if with_exp:
                    # one masked scatter per field, pre-step obs + the
                    # action taken from it; non-live lanes drop
                    exp = expbuf.record(exp, live, obs, actions, reward,
                                        done, info, policy_ids)
                state = jax.tree.map(
                    lambda a, b: _lane_where(live, a, b), new_state, state)
                obs = _lane_where(live, obs_next, obs)
                newly = done & ~got
                idx = jnp.where(newly, i, idx)
                caps = {k: jnp.where(newly, info[k], caps[k])
                        for k in caps}
                return ((state, obs), got | done, idx, caps, exp), None

            (inner, got, idx, caps, exp), _ = jax.lax.scan(
                body, (inner, got0, idx0, caps0, exp),
                jnp.arange(n, dtype=jnp.int32))
            regs = (got, idx) + tuple(caps[k] for k in CAPTURE_FIELDS)
            aux = {}
            if with_exp:
                aux["exp"] = exp
            if with_metrics:
                # per-burst cells, derived from the burst's own inputs
                # and the first-done registers — nothing per-step is
                # added, so the scan loop is the exact metrics-off
                # program
                macc = carry[1]["macc"]
                macc = spec.count(macc, "env_steps",
                                  jnp.sum(live.astype(jnp.int32)) * n)
                macc = spec.count(macc, "episodes", got)
                macc = spec.count(macc, "bursts", 1)
                macc = spec.observe(macc, "occupancy", occ)
                aux["macc"] = macc
            return (inner, aux), regs

        if self._lanes is None:
            return jax.jit(burst, donate_argnums=0)
        # sharded burst: lane-major trees partition on the mesh axis,
        # the metrics accumulator, swap params and occ scalar
        # replicate, and the in/out carry specs match so the donated
        # carry aliases in place per shard and chains with the sharded
        # step_lanes without a resharding collective.  The cross-shard
        # reductions the cells compute (sum over live lanes, first-done
        # episode count) come back replicated — GSPMD inserts the psum.
        # The experience rings are lane-major and shard with the lanes.
        lane, rep = self._lanes.lane, self._lanes.replicated
        aux_sh = {}
        if with_exp:
            aux_sh["exp"] = lane
        if with_metrics:
            aux_sh["macc"] = rep
        carry_sh = (lane, aux_sh)
        return jax.jit(burst, donate_argnums=0,
                       in_shardings=(carry_sh, lane, lane, rep, rep),
                       out_shardings=(carry_sh, lane))

    # -- lane program dispatch (single-device or mesh-sharded) ------------

    def _init_lanes(self, keys):
        if self._lanes is not None:
            return self._lanes.init_lanes(keys, self.params)
        return self.env.init_lanes(keys, self.params)

    def _step_lanes(self, carry, actions, admit, fresh, step):
        if self._lanes is not None:
            return self._lanes.step_lanes(carry, actions, admit, fresh,
                                          step, self.params)
        return self.env.step_lanes(carry, actions, admit, fresh, step,
                                   self.params)

    # -- lifecycle --------------------------------------------------------

    def start(self):
        """Materialize the lane carry and run both resident programs
        once with no lanes live, so every compile lands before the
        first client (the server's `serve:compile` phase)."""
        seeds = jnp.arange(self.n_lanes, dtype=jnp.uint32)
        keys = jax.vmap(jax.random.PRNGKey)(seeds)
        # two separate dispatches: the carry is donated on every tick
        # while the template must stay alive as the default
        # fresh_states argument of non-admitting ticks
        self._fresh0 = self._init_lanes(keys)
        self._carry = self._init_lanes(keys)
        zero_a = jnp.zeros(self.n_lanes, jnp.int32)
        zero_m = jnp.zeros(self.n_lanes, bool)
        self._carry, _ = self._step_lanes(
            self._carry, zero_a, zero_m, self._fresh0, zero_m)
        if self._with_metrics:
            self._macc = self._spec.init()
        if self.experience:
            # sampler key plane: each lane's stream is the fold_in
            # sibling of its admission key (experience_stream), so env
            # dynamics and action sampling can never alias; splice()
            # re-derives the stream from each admitted session's seed
            ekeys = jax.vmap(lambda s: self._exp_stream(
                jax.random.PRNGKey(s)))(seeds)
            self._exp = self._expbuf.init_buffer(
                ekeys, self.experience, self.env.observation_length)
            if self._lanes is not None:
                self._exp = jax.device_put(self._exp, self._lanes.lane)
        out, _ = self._burst_fn(self._carry_in(), zero_a, zero_m,
                                jnp.float32(0.0), self._swap_params)
        self._carry_out(out)
        if self._with_metrics:
            # warmup must not pollute the cells (it counts as a burst)
            self._macc = self._spec.init()

    def _carry_in(self):
        aux = {}
        if self._exp is not None:
            aux["exp"] = self._exp
        if self._with_metrics:
            aux["macc"] = self._macc
        return (self._carry, aux)

    def _carry_out(self, out):
        self._carry, aux = out
        if "exp" in aux:
            self._exp = aux["exp"]
        if "macc" in aux:
            self._macc = aux["macc"]

    # -- the three device entry points ------------------------------------

    def splice(self, lane_seeds: dict[int, int]) -> dict[int, np.ndarray]:
        """Admit sessions: splice a fresh `init_lanes` state (rollout
        stream prologue, so seed S replays rollout(PRNGKey(S))) over
        each given lane WITHOUT stepping anything.  Returns each
        admitted lane's first observation."""
        if not lane_seeds:
            return {}
        t0 = telemetry.now()
        seeds = np.zeros(self.n_lanes, np.uint32)
        admit = np.zeros(self.n_lanes, bool)
        for lane, seed in lane_seeds.items():
            seeds[lane] = seed
            admit[lane] = True
        keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(seeds))
        fresh = self._init_lanes(keys)
        hold = jnp.zeros(self.n_lanes, bool)
        carry, (obs, _, _, _) = self._step_lanes(
            self._carry, jnp.zeros(self.n_lanes, jnp.int32),
            jnp.asarray(admit), fresh, hold)
        self._carry = carry
        if self._exp is not None:
            # re-key the admitted lanes' sampler streams from their
            # session seeds (fold_in sibling of the admission key) and
            # restart their write windows — a re-admitted lane's stale
            # partial window must never be consolidated.  The monotone
            # step counter `t` keeps running; the key changed, so the
            # stream is fresh regardless.
            lanes = jnp.asarray(sorted(lane_seeds), jnp.int32)
            lseeds = jnp.asarray([lane_seeds[int(l)] for l in lanes],
                                 jnp.uint32)
            nk = jax.vmap(lambda s: self._exp_stream(
                jax.random.PRNGKey(s)))(lseeds)
            self._exp = dict(
                self._exp,
                key=self._exp["key"].at[lanes].set(nk),
                cursor=self._exp["cursor"].at[lanes].set(0))
        obs = np.asarray(obs)
        self.admitted += len(lane_seeds)
        self.busy_s += telemetry.now() - t0
        return {lane: obs[lane] for lane in lane_seeds}

    def tick(self, lane_actions: dict[int, int]) -> dict[int, dict]:
        """Advance exactly the given lanes by one step with the given
        client actions (interactive sessions); every other lane holds
        bit-exactly.  Returns per-lane {obs, reward, done, info}."""
        if not lane_actions:
            return {}
        t0 = telemetry.now()
        actions = np.zeros(self.n_lanes, np.int32)
        step = np.zeros(self.n_lanes, bool)
        for lane, a in lane_actions.items():
            actions[lane] = a
            step[lane] = True
        no_admit = jnp.zeros(self.n_lanes, bool)
        carry, out = self._step_lanes(
            self._carry, jnp.asarray(actions), no_admit, self._fresh0,
            jnp.asarray(step))
        self._carry = carry
        obs, reward, done, info = jax.device_get(out)
        self.ticks += 1
        self.steps += len(lane_actions)
        self.busy_s += telemetry.now() - t0
        return {
            lane: dict(obs=obs[lane], reward=float(reward[lane]),
                       done=bool(done[lane]),
                       info={k: float(v[lane]) for k, v in info.items()})
            for lane in lane_actions
        }

    def burst_run(self, lane_policy: dict[int, int],
                  occupancy: float | None = None) -> dict | None:
        """Advance every policy-driven lane by `burst` steps in one
        dispatch (actions computed in-graph from the policy table);
        non-listed lanes hold bit-exactly.  Returns the per-lane
        BURST_FIELDS first-done registers as (n_lanes,) numpy arrays,
        or None when no lane is policy-driven.  `occupancy` is the
        scheduler's assigned-lane fraction for the metrics cell
        (defaults to the live fraction)."""
        if not lane_policy:
            return None
        t0 = telemetry.now()
        pol = np.zeros(self.n_lanes, np.int32)
        live = np.zeros(self.n_lanes, bool)
        for lane, pid in lane_policy.items():
            pol[lane] = pid
            live[lane] = True
        occ = (len(lane_policy) / self.n_lanes
               if occupancy is None else float(occupancy))
        out, regs = self._burst_fn(
            self._carry_in(), jnp.asarray(pol), jnp.asarray(live),
            jnp.float32(occ), self._swap_params)
        self._carry_out(out)
        host = jax.device_get(regs)
        dur = telemetry.now() - t0
        self.bursts += 1
        self.steps += len(lane_policy) * self.burst
        self.episodes += int(host[0].sum())
        self.busy_s += dur
        self._occ_sum += occ
        self._burst_wall.append(dur)
        return dict(zip(BURST_FIELDS, host))

    # -- the learning plane -----------------------------------------------

    @property
    def swap_names(self) -> tuple:
        """Names of the hot-swappable net policies, sorted."""
        return tuple(sorted(self._swap_apply))

    def policy_fingerprint(self, name: str | None = None):
        """The snapshot fingerprint currently serving `name` (default:
        the first swappable policy), or None without swap policies."""
        if not self._swap_apply:
            return None
        if name is None:
            name = self.swap_names[0]
        return self._swap_fingerprint.get(name)

    def swap_policy(self, name: str, net_params, *,
                    fingerprint=None) -> dict:
        """Hot-swap a registered net policy's weights: the next burst
        dispatch serves `net_params`, in-flight lanes are untouched
        (their state lives in the lane carry, not the policy), and no
        program retraces — the weights are an argument of the compiled
        burst, so same-structure params reuse the executable.

        An identical fingerprint is a no-op (swapped=False) — the
        watch loop may see the same latest.json twice.  A params tree
        whose structure/shapes/dtypes differ from the serving entry is
        REFUSED with the typed IntegrityError path (reason="version"):
        accepting it would force a retrace mid-serve, which is exactly
        the drain this API exists to avoid.
        """
        if name not in self._swap_apply:
            raise ValueError(
                f"unknown swappable policy {name!r}; registered: "
                f"{sorted(self._swap_apply)}")
        if fingerprint is not None and \
                fingerprint == self._swap_fingerprint.get(name):
            return dict(swapped=False, reason="identical",
                        fingerprint=fingerprint)

        def sig(tree):
            return (jax.tree.structure(tree),
                    [(jnp.shape(x), jnp.result_type(x))
                     for x in jax.tree.leaves(tree)])

        if sig(net_params) != sig(self._swap_params[name]):
            from cpr_tpu.integrity import IntegrityError, integrity_event
            artifact = str(fingerprint or name)
            integrity_event(artifact=artifact, kind="policy_snapshot",
                            reason="version", action="refused",
                            detail="parameter tree does not match the "
                                   "serving program")
            raise IntegrityError(
                f"swap refused for {name!r}: snapshot parameter tree "
                f"does not match the serving program",
                artifact=artifact, kind="policy_snapshot",
                reason="version")
        if self._lanes is not None:
            net_params = jax.device_put(net_params,
                                        self._lanes.replicated)
        self._swap_params[name] = net_params
        self._swap_fingerprint[name] = fingerprint
        self.swaps += 1
        self.last_swap_t = telemetry.now()
        return dict(swapped=True, fingerprint=fingerprint)

    def drain_experience(self) -> dict | None:
        """Consolidate the experience rings into a feed batch — one
        device_get at a burst boundary, never per step.  Write cursors
        reset (the data is overwritten by the next window); key
        streams and the monotone step counters continue, so sampling
        stays reuse-free across drains.  Returns None when the plane
        is off or no lane filled a window (partial windows stay
        uncounted until re-admission resets them)."""
        if self._exp is None:
            return None
        host = jax.device_get({k: v for k, v in self._exp.items()
                               if k != "key"})
        last_obs = np.asarray(jax.device_get(self._carry[1]))
        self._exp = dict(self._exp,
                         cursor=jnp.zeros_like(self._exp["cursor"]))
        batch = self._expbuf.consolidate(host, last_obs)
        if not batch["steps"]:
            return None
        self.samples += batch["steps"]
        from cpr_tpu.learn import learn_event
        learn_event("sample", steps=batch["steps"], batches=1,
                    fingerprint=self.policy_fingerprint(),
                    staleness_s=None, lanes=int(len(batch["lanes"])),
                    partial=batch["partial"])
        return batch

    # -- reporting --------------------------------------------------------

    def report(self) -> dict:
        """Host-side throughput summary — the payload of the `serve`
        report telemetry event the perf ledger ingests.  Rates are over
        busy (dispatch) wall time, which is what compares against a
        batch rollout()'s span: idle time between client requests is a
        load property, not an engine property."""
        return dict(
            steps=self.steps, episodes=self.episodes, bursts=self.bursts,
            ticks=self.ticks, admitted=self.admitted,
            busy_s=self.busy_s,
            steps_per_sec=(self.steps / self.busy_s
                           if self.busy_s > 0 else 0.0),
            occupancy=(self._occ_sum / self.bursts
                       if self.bursts else 0.0),
            burst=self.burst, n_lanes=self.n_lanes,
            # device span of the lane block: the perf ledger lifts
            # this into the cfg_devices fingerprint so per-device-
            # count throughput rows gate separately (docs/SCALING.md)
            n_devices=self.n_devices,
            policies=list(self.policy_names),
            # learning plane (zeros when the plane is off)
            samples=self.samples, swaps=self.swaps)

    def record_shed(self):
        """Count one admission-control refusal (the server's shed
        path); surfaces as the shed_sessions device-metrics cell."""
        self.sheds += 1

    def emit_metrics(self, scope: str = "serve"):
        """Fold the host-recorded burst latencies — the `burst_s`
        spread and the `burst_s_hist` log-bucket distribution — plus
        the shed counter, and emit the device_metrics event (one
        readback).  No-op when in-graph metrics are off."""
        if self._macc is None:
            return None
        macc = self._macc
        if self._burst_wall:
            walls = np.asarray(self._burst_wall, np.float32)
            macc = self._spec.observe(macc, "burst_s", walls)
            macc = self._spec.observe_hist(macc, "burst_s_hist", walls)
        if self.sheds:
            macc = self._spec.count(macc, "shed_sessions", self.sheds)
        return device_metrics.emit(scope, self._spec, macc)
