"""asyncio front-end of the serving layer.

The tick loop is the only writer of device state: each iteration it
(1) backfills freed lanes from the admission queue (one `splice`
dispatch seeds all of this tick's admissions), (2) advances every
interactive lane with a pending client action (one `step_lanes`
dispatch), (3) advances every policy-driven lane by one K-step burst
(one dispatch), completing sessions at their first `done` and retiring
their lanes.  Connection handlers never touch the device — they
enqueue sessions / pending actions and await futures the tick loop
resolves, so continuous batching falls out of plain asyncio ordering.

Endpoints beyond the episode surface (netsim honest-net queries and
break-even lookups) run their own compiled programs on a single-worker
executor thread, keeping the tick loop responsive; netsim Engines are
cached per query shape because constructing one compiles.

Operability: every decision emits a typed v7 `serve` telemetry event;
the child heartbeats to the supervisor (progress = emitted events, so
an idle-but-alive server never trips the watchdog); SIGTERM lands in
`resilience.preemption_guard` and the loop drains gracefully — evict
queued and in-flight sessions with a `draining` reply, emit the
throughput `report` event (ingested by the perf ledger) and the
device-metrics summary, close, exit 0.

Always-on learning (v17, docs/LEARNING.md): with `--learner
host:port` the engine records sampler-lane experience in device rings
and the tick loop ships drained batches to the learner through an
`ExperienceFeeder` (drop-oldest — a slow learner costs samples, never
serve latency).  With `--learn-watch dir` the heartbeat block polls
the learner's `latest.json` pointer and hot-swaps the `ppo` policy's
params at the next burst boundary (`engine.swap_policy`) — zero
drain, zero retrace, in-flight sessions unperturbed; a snapshot that
fails integrity or protocol validation is refused with a typed event
and the server keeps serving the previous params.  Heartbeats, stats
and the drain report carry `policy_fingerprint` +
`snapshot_staleness_s`, and `--staleness-slo-s` arms the
snapshot-staleness burn-rate alert next to the latency SLOs.

Run: `python -m cpr_tpu.serve.server --protocol nakamoto ...`
(tools/serve_smoke.py supervises exactly this; tools/learn_smoke.py
supervises the server + learner pair).
"""

from __future__ import annotations

import asyncio
import itertools
import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from cpr_tpu import resilience, telemetry
from cpr_tpu.latency import LatencyBoard
from cpr_tpu.monitor import alerts as slo_alerts
from cpr_tpu.monitor.blackbox import dump_blackbox
from cpr_tpu.monitor.expo import MetricsServer
from cpr_tpu.monitor.registry import MetricsRegistry
from cpr_tpu.serve import protocol as wire
from cpr_tpu.serve.engine import ResidentEngine
from cpr_tpu.serve.scheduler import LaneScheduler, QueueFull

# priority classes on the wire: requests say `priority="batch"` (or
# the class number); lower number places first.  Interactive sessions
# default to the front, batch traffic is shed first under SLO breach.
PRIORITY_CLASSES = {"interactive": 0, "normal": 1, "batch": 2}
_CLASS_NAMES = {v: k for k, v in PRIORITY_CLASSES.items()}
# SLO budget multiplier per priority class: the shed threshold is
# slo_s * scale, so batch traffic sheds at half the SLO while
# interactive traffic rides out twice the SLO before refusal
_SLO_SCALE = {0: 2.0, 1: 1.0, 2: 0.5}


def _priority_of(req: dict, default: int = 1) -> tuple:
    """(priority int, class name) from a request's `priority` field —
    a class name or an int (clamped into the known classes)."""
    raw = req.get("priority", default)
    if isinstance(raw, str):
        if raw not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority {raw!r}; one of "
                f"{sorted(PRIORITY_CLASSES)} or 0..{len(_SLO_SCALE) - 1}")
        p = PRIORITY_CLASSES[raw]
    else:
        p = min(max(int(raw), 0), len(_SLO_SCALE) - 1)
    return p, _CLASS_NAMES[p]


def _serve_event(action: str, session=None, **detail):
    """The one `serve` event call site (EVENT_FIELDS['serve'])."""
    telemetry.current().event("serve", action=action, session=session,
                              detail=detail)


def _admission_event(reason, op, priority, tenant, retry_after_s):
    """The one `admission` event call site (EVENT_FIELDS['admission']):
    fires per shed refusal only — admitted sessions stay on the v7
    serve admit trail."""
    telemetry.current().event(
        "admission", reason=reason, op=op, priority=priority,
        tenant=tenant, retry_after_s=retry_after_s)


def _request_event(trace_id, op, status, queue_wait_s, service_s,
                   total_s, session, lane, splice_s):
    """The one server-side `request` event call site
    (EVENT_FIELDS['request']); the client-side twin lives in
    protocol.ServeClient.  `role`/`run` correlate streams in
    tools/trace_stitch.py."""
    telemetry.current().event(
        "request", trace_id=trace_id, op=op, status=status,
        queue_wait_s=queue_wait_s, service_s=service_s, total_s=total_s,
        role="server", run=telemetry.run_id(), session=session,
        lane=lane, splice_s=splice_s)


def _op_family(op) -> str:
    """Latency-board family for one op (break_even.* variants share
    one histogram; everything else is its own family)."""
    op = str(op)
    return "break_even" if op.startswith("break_even.") else op


class _Session:
    __slots__ = ("sid", "kind", "seed", "policy", "policy_id", "lane",
                 "future", "done", "t_enqueue", "t_admit",
                 "t_first_burst", "t_complete", "splice_s",
                 "priority", "cls", "tenant")

    def __init__(self, sid, kind, seed, policy, policy_id, future,
                 priority=1, cls="normal", tenant=None):
        self.sid = sid
        self.kind = kind
        self.seed = seed
        self.policy = policy
        self.policy_id = policy_id
        self.lane = None
        self.future = future
        self.done = False
        self.priority = priority
        self.cls = cls
        self.tenant = tenant
        # request-scoped trace stamps (telemetry.now() clock): queued,
        # admitted (lane spliced), first policy burst dispatched,
        # session completed — the reply's latency breakdown
        self.t_enqueue = telemetry.now()
        self.t_admit = None
        self.t_first_burst = None
        self.t_complete = None
        self.splice_s = None


class ServeServer:
    """One engine + scheduler + TCP front-end."""

    def __init__(self, engine: ResidentEngine, *, host: str = "127.0.0.1",
                 port: int = 0, heartbeat_s: float = 1.0,
                 idle_sleep_s: float = 0.002, seed_base: int = 1 << 20,
                 slo_s: float | None = None,
                 max_queued: int | None = None,
                 tenant_quota: int | None = None,
                 replica_index: int | None = None,
                 metrics_port: int | None = None,
                 feeder=None, learn_watch: str | None = None,
                 staleness_slo_s: float | None = None,
                 protocol: str | None = None):
        self.engine = engine
        # serving protocol key (main() passes --protocol): swap
        # validation refuses snapshots trained for another protocol
        self.protocol = protocol
        # always-on learning plane: the feeder ships drained
        # experience to the learner; learn_watch is the snapshot
        # directory whose latest.json pointer the heartbeat polls
        self.feeder = feeder
        self.learn_watch = learn_watch
        self.staleness_slo_s = staleness_slo_s
        self._watch_seq = -1
        # staleness baseline before the first swap: process start
        # (telemetry.now() clock — never compared across processes)
        self._serve_t0 = telemetry.now()
        # bounded queue by default: 8x the lane count is ~8 bursts of
        # backlog, past which queueing only manufactures SLO misses —
        # shed instead.  Explicit <= 0 restores the unbounded queue.
        if max_queued is None:
            max_queued = 8 * engine.n_lanes
        elif max_queued <= 0:
            max_queued = None
        self.slo_s = slo_s
        self.replica_index = replica_index
        self.sched = LaneScheduler(engine.n_lanes, max_queued=max_queued,
                                   tenant_quota=tenant_quota)
        self._sheds = 0
        self._shed_reasons: dict[str, int] = {}
        self.host = host
        self.port = port  # replaced by the bound port after start()
        self.heartbeat_s = heartbeat_s
        self.idle_sleep_s = idle_sleep_s
        self._sid = itertools.count(1)
        # server-assigned seeds for seedless sessions, clear of the
        # small integers clients use for reproducible requests
        self._seed = itertools.count(seed_base)
        self._sessions: dict[int, _Session] = {}
        # lane -> (action, fut, session, t_requested)
        self._pending: dict[int, tuple] = {}
        self._executor = ThreadPoolExecutor(max_workers=1)
        # asyncio futures of executor ops still in flight — drained
        # before the loop exits so no client hangs on a dropped future
        self._inflight_exec: set = set()
        # per-op-family reply latency + per-entry-point device
        # dispatch walls (the `stats`/`heartbeat`/`report` SLO surface)
        self.latency = LatencyBoard()
        # v14 live health plane: the registry mirrors the counters the
        # event stream already carries (pull-based, scrapeable while
        # serving), with the latency board attached by reference as
        # the histogram family — no second observe path
        self.metrics = MetricsRegistry(
            namespace="cpr_serve",
            const_labels=({"replica": str(replica_index)}
                          if replica_index is not None else None))
        self.metrics.attach_board(
            "latency_seconds", self.latency,
            help="per-op-family reply latency (seconds)")
        # v15 live memory watermark: sampled once per heartbeat (one
        # allocator stats read — well under the <2% overhead budget),
        # surfaced as gauges in both scrape paths, in the heartbeat /
        # stats / drain reports, and emitted as the typed `memory`
        # event at drain (the ledger lifts it to serve_peak_bytes)
        self.mem = telemetry.MemoryWatermark("serve")
        # SLO burn-rate alerting: per-class latency budgets are the
        # SAME scaled budgets admission control sheds against, so an
        # alert and a shed always agree on what "over SLO" means
        self.alerts = slo_alerts.AlertEngine(
            slo_s,
            class_slo=({name: slo_s * _SLO_SCALE[p]
                        for name, p in PRIORITY_CLASSES.items()}
                       if slo_s is not None else None),
            staleness_slo_s=staleness_slo_s)
        self.metrics_port = metrics_port  # bound port after start()
        self.metrics_server: MetricsServer | None = None
        self._netsim_engines: dict[tuple, object] = {}
        # loaded nets servable as attack policies (main() mirrors the
        # engine's snapshot table here; the fingerprint — the snapshot
        # path — keys the attack-sweep disk cache, since callables
        # cannot be hashed)
        self.attack_policies: dict = {}
        self.attack_fingerprint: str = ""
        self._server = None
        self._loop_task = None
        self._draining = False
        self._drain_reason = None

    # -- lifecycle --------------------------------------------------------

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.metrics_port is not None:
            self.metrics_server = MetricsServer(
                self.metrics.render_prometheus, host=self.host,
                port=self.metrics_port)
            self.metrics_port = self.metrics_server.start()
        # prime the gauges so a scrape between bind and the first
        # heartbeat sees real samples, not a comment-only exposition
        self._refresh_gauges()
        _serve_event("start", port=self.port,
                     n_lanes=self.engine.n_lanes,
                     burst=self.engine.burst,
                     policies=list(self.engine.policy_names),
                     metrics_port=self.metrics_port)
        self._loop_task = asyncio.create_task(self._tick_loop())

    async def serve_until_drained(self):
        await self._loop_task

    def request_drain(self, reason: str):
        self._drain_reason = self._drain_reason or reason

    # -- the tick loop ----------------------------------------------------

    async def _tick_loop(self):
        hb_last = telemetry.now()
        while True:
            if resilience.preempt_requested():
                self.request_drain(
                    f"preempt:{resilience.preempt_reason()}")
            if self._drain_reason is not None:
                await self._drain(self._drain_reason)
                return
            progressed = self._tick_once()
            t = telemetry.now()
            if t - hb_last >= self.heartbeat_s:
                # periodic even when idle: emitted events are the
                # supervisor's progress signal, so an idle server
                # stays distinguishable from a wedged one
                hb_last = t
                if self.learn_watch is not None:
                    self._poll_snapshots()
                self.alerts.record_staleness(
                    self.snapshot_staleness_s())
                self._refresh_gauges()
                for a in self.alerts.evaluate():
                    slo_alerts.emit_alert(a)
                _serve_event(
                    "heartbeat",
                    queued=self.sched.n_queued(),
                    occupancy=self.sched.occupancy(),
                    steps=self.engine.steps,
                    episodes=self.engine.episodes,
                    # backlog age + in-flight op counts: growth here
                    # shows up before clients start timing out
                    oldest_queued_s=self.sched.oldest_queued_s(),
                    pending_steps=len(self._pending),
                    exec_ops=len(self._inflight_exec),
                    sheds=self._sheds,
                    policy_fingerprint=self.engine.policy_fingerprint(),
                    snapshot_staleness_s=self.snapshot_staleness_s(),
                    alerts=self.alerts.summary(),
                    memory=self.mem.snapshot())
            await asyncio.sleep(0.0 if progressed else self.idle_sleep_s)

    def _tick_once(self) -> bool:
        progressed = False
        # 1. admissions: backfill freed lanes from the queue; one
        # splice dispatch seeds every admission this tick
        placed = self.sched.place()
        if placed:
            t0 = telemetry.now()
            obs_rows = self.engine.splice(
                {lane: s.seed for lane, s in placed})
            t1 = telemetry.now()
            self.latency.observe("device.splice", t1 - t0)
            for lane, s in placed:
                s.lane = lane
                s.t_admit = t1
                s.splice_s = t1 - t0
                _serve_event("admit", s.sid, lane=lane, seed=s.seed,
                             kind=s.kind)
                if s.kind == "interactive" and not s.future.done():
                    s.future.set_result(obs_rows[lane])
            progressed = True
        # 2. interactive lanes with a pending client action
        if self._pending:
            pending, self._pending = self._pending, {}
            t0 = telemetry.now()
            out = self.engine.tick(
                {lane: a for lane, (a, _, _, _) in pending.items()})
            t1 = telemetry.now()
            self.latency.observe("device.tick", t1 - t0)
            for lane, (_, fut, s, t_req) in pending.items():
                row = out[lane]
                # the step's own breakdown: waited for this tick's
                # dispatch, then one shared device tick served it
                row["latency"] = dict(
                    queue_wait_s=max(0.0, t0 - t_req),
                    service_s=t1 - t0,
                    total_s=max(0.0, t1 - t_req))
                if row["done"]:
                    s.done = True
                    s.t_complete = t1
                    self._sessions.pop(s.sid, None)
                    self.sched.retire(lane)
                    _serve_event(
                        "complete", s.sid, kind="interactive",
                        n_steps=row["info"]["episode_n_steps"],
                        reward=row["info"]["episode_reward_attacker"])
                if not fut.done():
                    fut.set_result(row)
            progressed = True
        # 3. policy-driven lanes: one burst; complete each session at
        # its first done (the lane keeps streaming to the end of the
        # burst — executed steps count toward throughput either way —
        # then retires and is backfilled next tick)
        policy_lanes = {lane: s
                        for lane, s in self.sched.assigned().items()
                        if s.kind == "policy"}
        if policy_lanes:
            # v15: the burst dispatch is a SPAN, not just a latency
            # observation — span paths are what tools/trace_diff.py
            # aligns two runs by, so the serving layer's device work
            # (and the replica chaos seam below, whose injected
            # `slow@replica` sleep lands inside this scope) is
            # attributable to a named path
            with telemetry.current().span(
                    "serve_burst",
                    env_steps=len(policy_lanes) * self.engine.burst):
                t0 = telemetry.now()
                for s in policy_lanes.values():
                    if s.t_first_burst is None:
                        s.t_first_burst = t0
                out = self.engine.burst_run(
                    {lane: s.policy_id
                     for lane, s in policy_lanes.items()},
                    occupancy=self.sched.occupancy())
                t1 = telemetry.now()
                self.latency.observe("device.burst", t1 - t0)
                for lane, s in policy_lanes.items():
                    if not out["done"][lane]:
                        continue  # episode spans into the next burst
                    s.t_complete = t1
                    att = float(out["episode_reward_attacker"][lane])
                    dfn = float(out["episode_reward_defender"][lane])
                    episode = dict(
                        reward_attacker=att, reward_defender=dfn,
                        progress=float(out["episode_progress"][lane]),
                        n_steps=int(out["episode_n_steps"][lane]),
                        relative_reward=(att / (att + dfn)
                                         if (att + dfn) else 0.0))
                    if not s.future.done():
                        # the fingerprint that served this episode's
                        # final burst: the revenue-vs-snapshot
                        # windowing key of tools/learn_smoke.py (None
                        # without swap policies)
                        s.future.set_result(dict(
                            ok=True, session=s.sid, seed=s.seed,
                            policy=s.policy, episode=episode,
                            policy_fingerprint=(
                                self.engine.policy_fingerprint())))
                    self.sched.retire(lane)
                    _serve_event(
                        "complete", s.sid, kind="policy",
                        n_steps=episode["n_steps"],
                        relative_reward=episode["relative_reward"])
                # chaos seam for the fleet smoke: a replica-tagged
                # server checks the injector after each completed
                # burst, so CPR_FAULT_INJECT="kill@replica=<i>"
                # deterministically kills exactly replica i at its
                # first burst under load (hang@replica wedges its tick
                # loop for the supervisor's quiet watchdog;
                # slow@replica sleeps INSIDE the serve_burst span —
                # the deterministic stand-in for a perf regression
                # that tools/obs_smoke.py asserts trace_diff blames)
                if self.replica_index is not None:
                    resilience.fault_point("replica",
                                           self.replica_index)
            # experience plane: one drain per burst boundary (the ring
            # capacity equals the burst, so full windows are ready
            # exactly now); submit never blocks — drop-oldest beyond
            # the feeder's small queue
            if self.feeder is not None:
                batch = self.engine.drain_experience()
                if batch is not None:
                    self.feeder.submit(batch)
            progressed = True
        return progressed

    # -- the learning plane -----------------------------------------------

    def snapshot_staleness_s(self):
        """Seconds since the serving policy last swapped (process
        start stands in before the first swap), or None when no
        swappable policy is registered.  Process-relative
        telemetry.now() stamps only — never compared across
        processes."""
        if not self.engine.swap_names:
            return None
        t0 = self.engine.last_swap_t
        return telemetry.now() - (t0 if t0 is not None
                                  else self._serve_t0)

    def _poll_snapshots(self):
        """One watch-loop poll: if the learner's latest.json moved
        past the last seq this server acted on, try the swap.  Every
        failure mode — unreadable pointer, missing snapshot, integrity
        refusal, protocol mismatch — leaves the previous params
        serving; zero-drain means the learning plane may fall behind
        but can never take the data plane down."""
        import json

        path = os.path.join(self.learn_watch, "latest.json")
        try:
            with open(path, "rb") as f:
                latest = json.load(f)
            seq = int(latest["seq"])
        except (OSError, ValueError, KeyError, TypeError):
            return  # not published yet / torn read: next poll retries
        if seq <= self._watch_seq:
            return
        self._watch_seq = seq
        self._swap_from_path(latest.get("path"), seq=seq)

    def _swap_from_path(self, path, seq=None) -> dict:
        """Load + validate + hot-swap one snapshot; shared by the
        watch poll and the in-band `policy.publish` op.  Returns the
        reply block (ok/swapped/fingerprint or the refusal)."""
        from cpr_tpu.integrity import IntegrityError, integrity_event
        from cpr_tpu.train.driver import load_policy_network

        name = self.engine.swap_names[0] if self.engine.swap_names \
            else None
        if name is None:
            return dict(ok=False, error="no swappable policy "
                                        "(start with --policy-snapshot)")
        staleness = self.snapshot_staleness_s()
        try:
            _, net_params, meta = load_policy_network(str(path))
            # the snapshot must rebuild the net this engine compiled:
            # protocol and dims are checked here against the serving
            # env; hidden-layer mismatches surface as the param-tree
            # structure refusal inside swap_policy
            env = self.engine.env
            if ((self.protocol is not None
                 and meta.get("protocol") not in (None, self.protocol))
                    or int(meta.get("n_actions", env.n_actions))
                    != int(env.n_actions)
                    or int(meta.get("observation_length",
                                    env.observation_length))
                    != int(env.observation_length)):
                integrity_event(
                    artifact=str(path), kind="policy_snapshot",
                    reason="version", action="refused",
                    expected=dict(protocol=self.protocol,
                                  n_actions=int(env.n_actions)),
                    found=dict(protocol=meta.get("protocol"),
                               n_actions=meta.get("n_actions")))
                return dict(ok=False, error="snapshot/env mismatch",
                            refused=True)
            out = self.engine.swap_policy(
                name, net_params,
                fingerprint=meta.get("payload_sha256"))
        except IntegrityError as e:
            # the typed event already fired inside the loader/engine
            return dict(ok=False, error=str(e), refused=True)
        if out.get("swapped"):
            from cpr_tpu.learn import learn_event

            learn_event("swap", steps=None, batches=None,
                        fingerprint=out["fingerprint"],
                        staleness_s=staleness, seq=seq,
                        policy=name, swaps=self.engine.swaps)
            if self.feeder is not None:
                self.feeder.fingerprint = out["fingerprint"]
        return dict(ok=True, **out)

    def _refresh_gauges(self):
        """Refresh the registry's gauge families from live scheduler /
        engine state — the same readings the heartbeat event carries,
        pull-scrapeable between heartbeats."""
        g = self.metrics.set
        # one allocator read per refresh keeps the watermark live in
        # both scrape paths without a second sampling thread
        self.mem.sample()
        if self.mem.peak_bytes is not None:
            g("memory_peak_bytes", self.mem.peak_bytes,
              help="peak device/process memory over the serve run "
                   "(bytes; max across devices)")
        if self.mem.in_use_bytes is not None:
            g("memory_in_use_bytes", self.mem.in_use_bytes,
              help="device/process memory in use at last sample "
                   "(bytes)")
        if self.mem.headroom_bytes is not None:
            g("memory_headroom_bytes", self.mem.headroom_bytes,
              help="allocator limit minus peak (bytes) — remaining "
                   "capacity before the allocator refuses")
        g("queued", self.sched.n_queued(),
          help="admission queue depth")
        g("occupancy", self.sched.occupancy(),
          help="fraction of lanes assigned")
        g("oldest_queued_s", self.sched.oldest_queued_s(),
          help="age of the oldest queued session (seconds)")
        g("pending_steps", len(self._pending),
          help="interactive steps awaiting the next device tick")
        g("exec_ops", len(self._inflight_exec),
          help="executor-thread query ops in flight")
        g("steps", self.engine.steps,
          help="device steps executed since start")
        g("episodes", self.engine.episodes,
          help="episodes completed since start")
        g("sheds", self._sheds,
          help="admission refusals since start")
        staleness = self.snapshot_staleness_s()
        if staleness is not None:
            g("snapshot_staleness_s", staleness,
              help="age of the serving policy snapshot (seconds "
                   "since the last hot-swap; process start before "
                   "the first)")

    def _session_latency(self, s: _Session) -> dict:
        """One completed (or refused) session's reply breakdown.
        Monotonic stamps, clamped at 0 anyway so a reply can never
        carry a negative latency."""
        t_end = s.t_complete if s.t_complete is not None \
            else telemetry.now()
        t_admit = s.t_admit if s.t_admit is not None else t_end
        return dict(
            queue_wait_s=max(0.0, t_admit - s.t_enqueue),
            service_s=max(0.0, t_end - t_admit),
            total_s=max(0.0, t_end - s.t_enqueue))

    async def _drain(self, reason: str):
        self._draining = True
        _serve_event("drain", reason=reason)
        refusal = dict(ok=False, error="draining", draining=True)
        for s in self.sched.drain():
            if not s.future.done():
                s.future.set_result(dict(refusal, session=s.sid))
        for _, fut, _s, _t in self._pending.values():
            if not fut.done():
                fut.set_result(dict(refusal))
        self._pending.clear()
        self._sessions.clear()
        # executor: cancel queued work (each cancelled future resolves
        # to a draining refusal inside _blocking, so no client ever
        # hangs on a dropped future), then wait out the op that is
        # already running on the worker thread — it gets a real reply
        self._executor.shutdown(wait=False, cancel_futures=True)
        if self._inflight_exec:
            await asyncio.wait(list(self._inflight_exec), timeout=60.0)
            # one short turn so the handlers awaiting those futures
            # write their replies before the loop winds down
            await asyncio.sleep(0.05)
        report = dict(self.engine.report(),
                      latency=self.latency.snapshot())
        # headline SLO: the policy-episode endpoint's total-latency
        # quantiles, lifted into the perf ledger as serve_p50_s /
        # serve_p99_s rows (perf/ledger.py _SERVE_METRICS)
        run_lat = report["latency"].get("episode.run") or {}
        report["p50_s"] = run_lat.get("p50_s")
        report["p99_s"] = run_lat.get("p99_s")
        # per-priority-class tails + the shed accounting: the ledger
        # lifts class_p99_s into cfg_class-tagged serve_p99_s rows and
        # shed_rate into a lower-is-better serve_shed_rate row
        report["class_p99_s"] = {
            fam.split(":", 1)[1]: report["latency"][fam].get("p99_s")
            for fam in report["latency"]
            if fam.startswith("episode.run:")}
        report["sheds"] = self._sheds
        report["shed_reasons"] = dict(self._shed_reasons)
        denom = self._sheds + self.engine.admitted
        report["shed_rate"] = self._sheds / denom if denom else 0.0
        # one last alert evaluation before the report: breaches that
        # built up between heartbeats still emit their typed events,
        # and the report carries the final alert surface
        for a in self.alerts.evaluate():
            slo_alerts.emit_alert(a)
        report["alerts"] = self.alerts.summary()
        # final watermark sample rides the report AND the typed
        # `memory` event — the report block is what survives when a
        # stream gets cut before the final event lands
        self.mem.sample()
        self.mem.emit()
        report["memory"] = self.mem.snapshot()
        # learning plane: fingerprint + staleness always ride the
        # report; the `learn` block (ledger rows
        # learn_samples_per_sec / learn_snapshot_staleness_s) only
        # when the experience plane is on
        report["policy_fingerprint"] = self.engine.policy_fingerprint()
        staleness = self.snapshot_staleness_s()
        report["snapshot_staleness_s"] = staleness
        if self.engine.experience:
            busy = self.engine.busy_s
            report["learn"] = dict(
                samples=self.engine.samples,
                samples_per_sec=(self.engine.samples / busy
                                 if busy > 0 else 0.0),
                snapshot_staleness_s=staleness,
                swaps=self.engine.swaps,
                feeder=(self.feeder.stats()
                        if self.feeder is not None else None))
        if self.feeder is not None:
            self.feeder.close()
        _serve_event("report", **report)
        self.engine.emit_metrics()
        _serve_event("stop", reason=reason, steps=report["steps"],
                     episodes=report["episodes"])
        self._server.close()
        await self._server.wait_closed()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    # -- connections ------------------------------------------------------

    async def _handle(self, reader, writer):
        try:
            while True:
                req = await wire.read_frame(reader)
                if req is None:
                    break
                resp = await self._serve_request(req)
                await wire.write_frame(writer, resp)
        except (wire.ProtocolError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _serve_request(self, req: dict) -> dict:
        """Dispatch one request with its trace context: stamp receipt,
        propagate (or mint) the trace id, backfill a wall-clock latency
        breakdown on ops that carry none of their own, fold the total
        into the per-family latency board, emit the v8 `request`
        event, and echo `trace_id` + `latency` in the reply."""
        trace = req.get("_trace") if isinstance(req.get("_trace"),
                                                dict) else {}
        trace_id = trace.get("id") or telemetry.new_trace_id()
        t_recv = telemetry.now()
        try:
            resp = await self._dispatch(req)
        except Exception as e:  # noqa: BLE001 — per-request wall
            resp = dict(ok=False, error=f"{type(e).__name__}: {e}")
        if not isinstance(resp, dict):  # defensive: handlers return dicts
            resp = dict(ok=False, error="handler returned no dict")
        lat = resp.get("latency")
        if not (isinstance(lat, dict) and "total_s" in lat):
            # immediate ops (hello/stats/executor queries): no queue,
            # service is the whole wall
            wall = telemetry.now() - t_recv
            lat = dict(queue_wait_s=0.0, service_s=wall, total_s=wall)
            resp["latency"] = lat
        resp["trace_id"] = trace_id
        status = ("ok" if resp.get("ok")
                  else "refused" if resp.get("draining")
                  or resp.get("shed") else "error")
        op = req.get("op")
        cls = resp.pop("_class", None)
        self.latency.observe(_op_family(op), lat["total_s"])
        self.metrics.inc("requests_total", op=str(op), status=status,
                         help="requests served, by op and status")
        if cls is not None:
            # per-priority-class tail latency: the drain report lifts
            # these into per-class serve_p99_s ledger rows
            self.latency.observe(f"{_op_family(op)}:{cls}",
                                 lat["total_s"])
            # the burn-rate engine sees the same per-class totals the
            # board does, judged against the class SLO budgets
            self.alerts.record_latency(cls, lat["total_s"])
        _request_event(trace_id, op, status, lat["queue_wait_s"],
                       lat["service_s"], lat["total_s"],
                       resp.get("session"), resp.pop("_lane", None),
                       resp.pop("_splice_s", None))
        return resp

    async def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "hello":
            return dict(ok=True, schema=telemetry.SCHEMA_VERSION,
                        run=telemetry.run_id(),
                        n_lanes=self.engine.n_lanes,
                        burst=self.engine.burst,
                        policies=list(self.engine.policy_names),
                        policy_fingerprint=(
                            self.engine.policy_fingerprint()))
        if op == "stats":
            return dict(ok=True, report=self.engine.report(),
                        queued=self.sched.n_queued(),
                        assigned=self.sched.n_assigned(),
                        occupancy=self.sched.occupancy(),
                        oldest_queued_s=self.sched.oldest_queued_s(),
                        pending_steps=len(self._pending),
                        exec_ops=len(self._inflight_exec),
                        sheds=self._sheds,
                        shed_reasons=dict(self._shed_reasons),
                        # per-op-family histogram summaries; named
                        # `latencies` because the singular `latency`
                        # reply key is the per-request breakdown
                        latencies=self.latency.snapshot(),
                        # the raw mergeable wire form: the router
                        # bucket-sums these into the fleet board
                        latencies_raw=self.latency.to_dict(),
                        alerts=self.alerts.summary(),
                        memory=self.mem.snapshot(),
                        policy_fingerprint=(
                            self.engine.policy_fingerprint()),
                        snapshot_staleness_s=(
                            self.snapshot_staleness_s()),
                        feeder=(self.feeder.stats()
                                if self.feeder is not None else None))
        if op == "policy.publish":
            # in-band twin of the latest.json watch: swap to the named
            # snapshot at the next burst boundary, or refuse with the
            # typed integrity path — either way, keep serving
            return self._swap_from_path(req.get("path"))
        if op == "metrics.scrape":
            # the in-band twin of the --metrics-port HTTP endpoint:
            # the registry's structured form (histograms_raw inside is
            # the fleet-merge input) plus the live alert surface
            return dict(ok=True, metrics=self.metrics.to_json(),
                        alerts=self.alerts.summary(),
                        latencies_raw=self.latency.to_dict())
        if op == "drain":
            self.request_drain(str(req.get("reason", "client")))
            return dict(ok=True, draining=True)
        if op == "episode.run":
            return await self._op_episode_run(req)
        if op == "episode.open":
            return await self._op_episode_open(req)
        if op == "episode.step":
            return await self._op_episode_step(req)
        if op == "episode.close":
            return self._op_episode_close(req)
        if op == "netsim.query":
            out = await self._blocking(self._netsim_query, req)
            _serve_event("query", endpoint="netsim",
                         protocol=out.get("protocol"))
            return out
        if op in ("break_even.revenue", "break_even.alpha"):
            out = await self._blocking(self._break_even, req, op)
            _serve_event("query", endpoint=op,
                         protocol=req.get("protocol"))
            return out
        if op == "mdp.solve_grid":
            out = await self._blocking(self._mdp_solve_grid, req)
            _serve_event("query", endpoint="mdp.solve_grid",
                         protocol=req.get("protocol"))
            return out
        if op == "netsim.attack_sweep":
            out = await self._blocking(self._attack_sweep, req)
            _serve_event("query", endpoint="netsim.attack_sweep",
                         protocol=req.get("protocol"))
            return out
        return dict(ok=False, error=f"unknown op {op!r}")

    # -- admission control -------------------------------------------------

    def _retry_after_s(self) -> float:
        """Latency-aware backoff hint for a shed reply: the backlog's
        estimated drain time (queue depth x the episode.run p50 from
        the latency board, spread over the lanes), clamped to
        [0.1, 30] seconds.  Before any episode has completed, the SLO
        itself (or 1s) stands in for the per-episode estimate."""
        h = self.latency.get("episode.run")
        per = h.quantile(0.5) if h is not None and h.count else None
        if per is None:
            per = self.slo_s if self.slo_s is not None else 1.0
        est = (self.sched.n_queued() + 1) * per / max(1, self.engine.n_lanes)
        return round(min(30.0, max(0.1, est)), 3)

    def _shed(self, reason: str, op: str, cls: str, tenant) -> dict:
        """One shed decision: count it, emit the typed v9 `admission`
        event, and build the in-band refusal (the connection stays up;
        `retry_after` tells the client when to come back)."""
        retry_after = self._retry_after_s()
        self._sheds += 1
        self._shed_reasons[reason] = self._shed_reasons.get(reason, 0) + 1
        self.engine.record_shed()
        self.alerts.record_admission(True)
        self.metrics.inc("sheds_total", reason=reason, op=str(op),
                         cls=str(cls), tenant=str(tenant or ""),
                         help="admission refusals, by reason")
        _admission_event(reason, op, cls, tenant, retry_after)
        return dict(ok=False, error=f"shed: {reason}", shed=True,
                    reason=reason, retry_after=retry_after)

    def _admission_check(self, op: str, priority: int, cls: str,
                         tenant) -> dict | None:
        """Shed refusal for a new session, or None to admit.  Checked
        before the session exists: a shed request never consumes a
        sid/seed, so the seed sequence of admitted traffic is
        unperturbed by load."""
        if (self.sched.max_queued is not None
                and self.sched.n_queued() >= self.sched.max_queued):
            return self._shed("queue_full", op, cls, tenant)
        if (self.sched.tenant_quota is not None and tenant is not None
                and self.sched.tenant_load(tenant)
                >= self.sched.tenant_quota):
            return self._shed("tenant_quota", op, cls, tenant)
        if self.slo_s is not None:
            budget = self.slo_s * _SLO_SCALE[priority]
            if self.sched.oldest_queued_s() > budget:
                return self._shed("slo_breach", op, cls, tenant)
        return None

    def _new_session(self, kind: str, req: dict, priority: int = 1,
                     cls: str = "normal") -> _Session:
        if self._draining or self._drain_reason is not None:
            raise RuntimeError("draining")
        policy = req.get("policy", "honest")
        if kind == "policy" and policy not in self.engine.policy_ids:
            raise ValueError(
                f"unknown policy {policy!r}; serving "
                f"{list(self.engine.policy_names)}")
        seed = int(req["seed"]) if "seed" in req and req["seed"] is not None \
            else next(self._seed)
        tenant = req.get("tenant")
        return _Session(next(self._sid), kind, seed, policy,
                        self.engine.policy_ids.get(policy),
                        asyncio.get_running_loop().create_future(),
                        priority=priority, cls=cls,
                        tenant=str(tenant) if tenant is not None else None)

    async def _op_episode_run(self, req):
        prio, cls = _priority_of(req, default=1)
        tenant = req.get("tenant")
        tenant = str(tenant) if tenant is not None else None
        refusal = self._admission_check("episode.run", prio, cls, tenant)
        if refusal is not None:
            return refusal
        s = self._new_session("policy", req, prio, cls)
        try:
            self.sched.enqueue(s, priority=prio, tenant=s.tenant)
        except QueueFull:
            return self._shed("queue_full", "episode.run", cls, s.tenant)
        self.alerts.record_admission(False)
        self.metrics.inc("admitted_total", cls=cls,
                         help="sessions admitted, by priority class")
        resp = await s.future
        lat = self._session_latency(s)
        if s.t_complete is not None:
            # the reply can leave late: between the burst stamping
            # t_complete and this coroutine resuming, the tick loop
            # may stall (GC, a wedged device, an injected
            # slow@replica) — wall the client is actually waiting, so
            # it belongs in the latency the board/drain report gate on
            stall = max(0.0, telemetry.now() - s.t_complete)
            lat["service_s"] += stall
            lat["total_s"] += stall
        return dict(resp, latency=lat,
                    _lane=s.lane, _splice_s=s.splice_s, _class=s.cls)

    async def _op_episode_open(self, req):
        prio, cls = _priority_of(req, default=PRIORITY_CLASSES["interactive"])
        tenant = req.get("tenant")
        tenant = str(tenant) if tenant is not None else None
        refusal = self._admission_check("episode.open", prio, cls, tenant)
        if refusal is not None:
            return refusal
        s = self._new_session("interactive", req, prio, cls)
        try:
            self.sched.enqueue(s, priority=prio, tenant=s.tenant)
        except QueueFull:
            return self._shed("queue_full", "episode.open", cls, s.tenant)
        self.alerts.record_admission(False)
        self.metrics.inc("admitted_total", cls=cls,
                         help="sessions admitted, by priority class")
        obs = await s.future
        if isinstance(obs, dict):  # drained before admission
            return dict(obs, latency=self._session_latency(s))
        self._sessions[s.sid] = s
        return dict(ok=True, session=s.sid, seed=s.seed,
                    obs=np.asarray(obs, np.float64).tolist(),
                    latency=self._session_latency(s),
                    _lane=s.lane, _splice_s=s.splice_s)

    async def _op_episode_step(self, req):
        s = self._sessions.get(req.get("session"))
        if s is None or s.lane is None or s.done:
            return dict(ok=False, error="no such open session")
        if s.lane in self._pending:
            return dict(ok=False, error="step already in flight")
        fut = asyncio.get_running_loop().create_future()
        self._pending[s.lane] = (int(req["action"]), fut, s,
                                 telemetry.now())
        row = await fut
        if "ok" in row:  # drained refusal
            return row
        return dict(ok=True, session=s.sid,
                    obs=np.asarray(row["obs"], np.float64).tolist(),
                    reward=row["reward"], done=row["done"],
                    info=row["info"], latency=row["latency"],
                    _lane=s.lane)

    def _op_episode_close(self, req):
        s = self._sessions.pop(req.get("session"), None)
        if s is not None and s.lane is not None and not s.done \
                and self.sched.owner(s.lane) is s:
            self.sched.retire(s.lane)
            _serve_event("complete", s.sid, kind="interactive",
                         closed=True)
        return dict(ok=True)

    async def _blocking(self, fn, *args):
        if self._draining or self._drain_reason is not None:
            return dict(ok=False, error="draining", draining=True)
        fut = asyncio.get_running_loop().run_in_executor(
            self._executor, fn, *args)
        self._inflight_exec.add(fut)
        try:
            return await fut
        except asyncio.CancelledError:
            # the drain's shutdown(cancel_futures=True) cancelled this
            # queued work item: the client gets a refusal, never a
            # silently dropped future.  A cancellation from anywhere
            # else (e.g. the connection handler) still propagates.
            if self._draining or self._drain_reason is not None:
                return dict(ok=False, error="draining", draining=True)
            raise
        finally:
            self._inflight_exec.discard(fut)

    # -- query endpoints (executor thread) --------------------------------

    def _netsim_query(self, req: dict) -> dict:
        from cpr_tpu import netsim
        from cpr_tpu.network import symmetric_clique

        proto = req.get("protocol", "nakamoto")
        k = int(req.get("k", 1))
        scheme = req.get("scheme", "constant")
        if not netsim.supports(proto, k, scheme):
            raise ValueError(
                f"netsim does not support ({proto}, k={k}, {scheme}); "
                f"supported protocols: {netsim.SUPPORTED_PROTOCOLS}")
        n_nodes = int(req.get("n_nodes", 10))
        act_delay = float(req.get("activation_delay", 1.0))
        prop_delay = float(req.get("propagation_delay", 1.0))
        n_act = int(req.get("activations", 1000))
        seed = int(req.get("seed", 0))
        ckey = (proto, k, scheme, n_nodes, act_delay, prop_delay, n_act)
        eng = self._netsim_engines.get(ckey)
        if eng is None:
            # constructing an Engine compiles its XLA program — cache
            # per query shape so repeated queries cost one dispatch
            net = symmetric_clique(n_nodes, activation_delay=act_delay,
                                   propagation_delay=prop_delay)
            eng = netsim.Engine(net, protocol=proto, k=k, scheme=scheme,
                                activations=n_act)
            self._netsim_engines[ckey] = eng
        out = eng.run([seed], [act_delay])
        progress = float(out["progress"][0])
        return dict(
            ok=True, protocol=proto, seed=seed,
            rewards=[float(r) for r in out["reward"][0]],
            activations=[int(a) for a in out["node_act"][0]],
            progress=progress,
            orphan_rate=max(0.0, 1.0 - progress / n_act),
            sim_time=float(out["sim_time"][0]),
            head_height=int(out["head_height"][0]),
            n_blocks=int(out["n_blocks"][0]),
            on_chain=float(out["on_chain"][0]))

    def _break_even(self, req: dict, op: str) -> dict:
        # the package re-exports the function under the module's name,
        # so pull the callables straight from the submodule
        from cpr_tpu.experiments.break_even import break_even, revenue

        if req.get("mode") == "exact":
            return self._break_even_exact(req, op)
        proto = req["protocol"]
        policy = req["policy"]
        gamma = float(req["gamma"])
        episode_len = int(req.get("episode_len", 256))
        reps = int(req.get("reps", 512))
        if op == "break_even.revenue":
            value = revenue(
                proto, policy, alpha=float(req["alpha"]), gamma=gamma,
                episode_len=episode_len, reps=reps,
                seed=int(req.get("seed", 0)))
            return dict(ok=True, protocol=proto, policy=policy,
                        revenue=value)
        value = break_even(
            proto, policy, gamma=gamma,
            support=tuple(req.get("support", (0.1, 0.5))),
            tol=float(req.get("tol", 0.005)),
            episode_len=episode_len, reps=reps,
            seed=int(req.get("seed", 0)))
        return dict(ok=True, protocol=proto, policy=policy, alpha=value)

    def _break_even_exact(self, req: dict, op: str) -> dict:
        """`mode: "exact"` break-even queries ride solve_grid_cached
        (ROADMAP item 3): the optimal-attack revenue curve / break-even
        alpha from one fingerprint-cached exact grid solve — a repeat
        query for the same protocol/cutoff/grid is a disk-cache hit,
        surfaced by the `cached` flag in the reply (no `policy` field:
        the exact path optimizes over all policies)."""
        from cpr_tpu.experiments.break_even import (break_even_exact,
                                                    exact_revenue_curve)

        proto = req["protocol"]
        gamma = float(req["gamma"])
        cutoff = int(req.get("cutoff", 8))
        kw = dict(gamma=gamma, cutoff=cutoff,
                  horizon=int(req.get("horizon", 100)),
                  stop_delta=float(req.get("stop_delta", 1e-6)),
                  native=bool(req.get("native", False)),
                  k=int(req.get("k", 2)), full=True)
        if op == "break_even.revenue":
            alphas = req.get("alphas") or [float(req["alpha"])]
            out = exact_revenue_curve(
                proto, alphas=tuple(float(a) for a in alphas), **kw)
            return dict(ok=True, protocol=proto, mode="exact",
                        cutoff=cutoff, revenue=out["revenue"],
                        alphas=out["alphas"], cached=out["cached"],
                        fingerprint=out["fingerprint"])
        out = break_even_exact(
            proto, support=tuple(req.get("support", (0.1, 0.5))),
            grid=int(req.get("grid", 17)), **kw)
        return dict(ok=True, protocol=proto, mode="exact",
                    cutoff=cutoff, alpha=out["alpha"],
                    cached=out["cached"],
                    fingerprint=out["fingerprint"])

    def _mdp_solve_grid(self, req: dict) -> dict:
        """Exact-MDP optimal-policy tables over an (alpha, gamma) grid:
        one parametric compile + one batched grid solve, served from
        the content-fingerprint disk cache (cpr_tpu.mdp.
        solve_grid_cached) — a repeated query for the same protocol/
        cutoff/grid costs one cache read, never a re-solve."""
        from cpr_tpu.mdp.grid import solve_grid_cached

        out = solve_grid_cached(
            req["protocol"], cutoff=int(req["cutoff"]),
            alphas=tuple(float(a) for a in req["alphas"]),
            gammas=tuple(float(g) for g in req["gammas"]),
            horizon=int(req.get("horizon", 100)),
            stop_delta=float(req.get("stop_delta", 1e-6)),
            native=bool(req.get("native", False)),
            k=int(req.get("k", 2)),
            include_policy=bool(req.get("include_policy", False)))
        return dict(ok=True, **out)

    def _attack_sweep(self, req: dict) -> dict:
        """Adversary-in-the-network sweeps (netsim.attack_sweep_cached):
        the whole protocol x topology x delay x alpha x policy grid of
        one request runs as a single vmapped lane batch, served from
        the topology-fingerprint disk cache.  `topology` selects the
        network: {"kind": "two-agents"} (default, the degenerate
        anchor), {"kind": "clique", "n", "propagation_delay"}, or
        {"kind": "graphml", "xml", "label"} for arbitrary topologies
        over the wire.  Loaded policy snapshots (--policy-snapshot)
        are addressable by name next to the scripted SSZ policies."""
        from cpr_tpu import netsim
        from cpr_tpu.netsim.attack import DEFAULT_ALPHAS
        from cpr_tpu.network import (of_graphml, symmetric_clique,
                                     two_agents)

        topo = req.get("topology") or {"kind": "two-agents"}
        kind = topo.get("kind", "two-agents")
        act_delay = float(topo.get("activation_delay", 60.0))
        if kind == "graphml":
            net = of_graphml(topo["xml"])
            label = str(topo.get("label", "graphml"))
        elif kind == "clique":
            n = int(topo.get("n", 4))
            net = symmetric_clique(
                n, activation_delay=act_delay,
                propagation_delay=float(
                    topo.get("propagation_delay", 1.0)))
            label = f"clique-{n}"
        elif kind == "two-agents":
            net = two_agents(alpha=0.5, activation_delay=act_delay)
            label = "two-agents"
        else:
            raise ValueError(f"unknown topology kind {kind!r}")
        policies = tuple(req.get("policies",
                                 netsim.DEFAULT_ATTACK_POLICIES))
        extra = {nm: fn for nm, fn in self.attack_policies.items()
                 if nm in policies}
        out = netsim.attack_sweep_cached(
            net, label,
            protocol=req.get("protocol", "nakamoto"),
            k=int(req.get("k", 1)),
            scheme=req.get("scheme", "constant"),
            policies=tuple(p for p in policies if p not in extra),
            extra_policies=extra or None,
            extra_fingerprint=self.attack_fingerprint if extra else "",
            alphas=tuple(float(a)
                         for a in req.get("alphas", DEFAULT_ALPHAS)),
            activation_delays=tuple(
                float(d) for d in req.get("activation_delays",
                                          (act_delay,))),
            activations=int(req.get("activations", 2000)),
            reps=int(req.get("reps", 4)),
            seed=int(req.get("seed", 0)),
            cache=bool(req.get("cache", True)))
        return dict(ok=True, **out)


# -- child entry point ----------------------------------------------------


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="cpr_tpu serving child (see docs/SERVING.md)")
    p.add_argument("--protocol", default="nakamoto")
    p.add_argument("--alpha", type=float, default=0.25)
    p.add_argument("--gamma", type=float, default=0.5)
    p.add_argument("--activation-delay", type=float, default=1.0)
    p.add_argument("--max-steps", type=int, default=256)
    p.add_argument("--lanes", type=int, default=32)
    p.add_argument("--burst", type=int, default=256)
    p.add_argument("--devices", type=int, default=1,
                   help="shard the lane block over this many devices"
                        " (1-D mesh; lanes must divide it; see"
                        " docs/SCALING.md)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--policy-snapshot", default=None,
                   help="serving snapshot (driver.export_policy_snapshot"
                        " / train checkpoints); served as policy 'ppo'")
    p.add_argument("--ready-file", default=None,
                   help="atomic JSON {host,port,pid} once accepting")
    p.add_argument("--heartbeat-s", type=float, default=1.0)
    p.add_argument("--slo-s", type=float, default=None,
                   help="shed new sessions in-band when oldest_queued_s"
                        " breaches this (scaled per priority class);"
                        " default: no SLO shedding")
    p.add_argument("--max-queue", type=int, default=None,
                   help="admission queue bound (default 8x lanes;"
                        " <= 0 for unbounded)")
    p.add_argument("--tenant-quota", type=int, default=None,
                   help="max lanes+queue slots one tenant may hold")
    p.add_argument("--replica-index", type=int, default=None,
                   help="fleet replica id (set by serve.router); arms"
                        " the per-replica fault-injection site")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus text metrics over HTTP on"
                        " this port (0 = ephemeral; the bound port"
                        " lands in the ready file); default: no HTTP"
                        " exposition (metrics.scrape stays available)")
    p.add_argument("--learner", default=None, metavar="HOST:PORT",
                   help="feed sampler-lane experience to this learner"
                        " (cpr_tpu.learn.learner); requires"
                        " --policy-snapshot, turns the snapshot into a"
                        " sampling policy ('ppo#sample') and arms the"
                        " device experience rings")
    p.add_argument("--learn-watch", default=None, metavar="DIR",
                   help="watch DIR/latest.json and hot-swap the 'ppo'"
                        " policy at burst boundaries (zero drain);"
                        " requires --policy-snapshot")
    p.add_argument("--staleness-slo-s", type=float, default=None,
                   help="snapshot-staleness budget for the burn-rate"
                        " alert engine (docs/LEARNING.md); default:"
                        " signal off")
    args = p.parse_args(argv)

    from cpr_tpu import supervisor

    supervisor.maybe_start_heartbeat()
    with supervisor.child_phase("serve:init"):
        from cpr_tpu.envs.registry import get_sized
        from cpr_tpu.params import make_params

        env = get_sized(args.protocol, args.max_steps)
        params = make_params(alpha=args.alpha, gamma=args.gamma,
                             activation_delay=args.activation_delay,
                             max_steps=args.max_steps)
        learn_mode = bool(args.learner or args.learn_watch)
        if learn_mode and not args.policy_snapshot:
            raise SystemExit(
                "--learner/--learn-watch require --policy-snapshot "
                "(the engine needs an initial swappable net)")
        extra = {}
        swap = None
        sample = ()
        if args.policy_snapshot:
            if learn_mode:
                # swappable registration: the params stay an argument
                # of the compiled burst (engine.swap_policy replaces
                # them between bursts, zero retrace); with a learner
                # attached the same net also samples ('ppo#sample')
                # into the experience rings
                from cpr_tpu.train.driver import load_policy_network

                net, net_params, meta = load_policy_network(
                    args.policy_snapshot)
                swap = {"ppo": (lambda p, o: net.apply(p, o)[0],
                                net_params, meta["payload_sha256"])}
                if args.learner:
                    sample = ("ppo",)
            else:
                from cpr_tpu.train.driver import load_policy_snapshot

                policy, meta = load_policy_snapshot(args.policy_snapshot)
                extra["ppo"] = policy
            if meta.get("protocol") not in (None, args.protocol):
                raise SystemExit(
                    f"snapshot trained on {meta.get('protocol')!r}, "
                    f"serving {args.protocol!r}")
        mesh = None
        if args.devices > 1:
            import jax

            from cpr_tpu.parallel import default_mesh

            devs = jax.devices()
            if len(devs) < args.devices:
                raise SystemExit(
                    f"--devices {args.devices} but only {len(devs)} "
                    f"device(s) visible to JAX")
            mesh = default_mesh(devices=devs[:args.devices])
        engine = ResidentEngine(env, params, n_lanes=args.lanes,
                                burst=args.burst, extra_policies=extra,
                                swap_policies=swap,
                                sample_policies=sample,
                                # ring capacity == burst: every burst
                                # fills exactly one feed window
                                experience=(args.burst if args.learner
                                            else 0),
                                mesh=mesh)
    with supervisor.child_phase("serve:compile"):
        engine.start()
    # backend-bearing manifest BEFORE traffic: the perf ledger
    # attributes every later serve report row to this record (the
    # `devices` key lands as cfg_devices on every lifted row, so
    # per-device-count throughput gates separately — docs/SCALING.md)
    telemetry.current().manifest(config=dict(
        entry="serve", protocol=args.protocol, n_lanes=args.lanes,
        burst=args.burst, devices=args.devices,
        max_steps=args.max_steps, alpha=args.alpha, gamma=args.gamma,
        learner=bool(args.learner),
        learn_watch=bool(args.learn_watch)))

    feeder = None
    if args.learner:
        from cpr_tpu.learn.feed import ExperienceFeeder

        lhost, _, lport = args.learner.rpartition(":")
        feeder = ExperienceFeeder(lhost or "127.0.0.1", int(lport),
                                  fingerprint=(
                                      engine.policy_fingerprint()))

    async def amain():
        server = ServeServer(engine, host=args.host, port=args.port,
                             heartbeat_s=args.heartbeat_s,
                             slo_s=args.slo_s, max_queued=args.max_queue,
                             tenant_quota=args.tenant_quota,
                             replica_index=args.replica_index,
                             metrics_port=args.metrics_port,
                             feeder=feeder,
                             learn_watch=args.learn_watch,
                             staleness_slo_s=args.staleness_slo_s,
                             protocol=args.protocol)
        # the same loaded nets double as in-network attack policies
        # (netsim.attack_sweep); the snapshot path is the cache
        # fingerprint for their sweep results
        server.attack_policies = dict(extra)
        server.attack_fingerprint = args.policy_snapshot or ""
        await server.start()
        if args.ready_file:
            resilience.atomic_write_json(
                args.ready_file,
                dict(host=args.host, port=server.port, pid=os.getpid(),
                     metrics_port=server.metrics_port))
        await server.serve_until_drained()

    with supervisor.child_phase("serve:run"), resilience.preemption_guard():
        # the flight recorder's crash trigger: any exception unwinding
        # the serve loop (including an injected kill standing in for
        # one) dumps the telemetry ring before re-raising; a graceful
        # preemption drain dumps on the way out too (the preempt flag
        # outlives the guard)
        try:
            asyncio.run(amain())
        except BaseException as e:  # noqa: BLE001 — dump-and-reraise
            dump_blackbox(f"serve:{type(e).__name__}")
            raise
        if resilience.preempt_requested():
            dump_blackbox(f"serve:preempt:{resilience.preempt_reason()}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
