"""JAX-native, vmap-batched multi-node network simulator.

The reference's first hot loop — the discrete-event simulator over
arbitrary topologies (simulator/lib/simulator.ml + network.ml) — exists
in this repo only as the single-threaded C++ oracle
(cpr_tpu/native/src/oracle.cpp), so every honest-net sweep runs the
protocols x activation-delays x seeds grid serially on one host core.
This package compiles a `network.Network` into dense device arrays and
drives the honest-node dynamics inside one jitted `lax.while_loop`,
with `vmap` over lanes carrying independent (seed, activation_delay)
so a whole sweep grid executes as a single device program.

Semantics follow oracle.cpp (flooding + dedup + parent-gated delivery
+ same-timestamp unlock); statistical parity against the unmodified
oracle is the correctness anchor (PARITY.md, tests/test_netsim.py).
See docs/NETSIM.md for the event-engine design, the documented
approximations, and the capacity limits.
"""

from cpr_tpu.netsim.compile import (  # noqa: F401
    CompiledNet, compile_network, sample_delay_matrix, NETSIM_KINDS,
)
from cpr_tpu.netsim.engine import (  # noqa: F401
    Engine, SUPPORTED_PROTOCOLS, grid, supports,
)
from cpr_tpu.netsim.attack import (  # noqa: F401
    ATTACK_PROTOCOLS, AttackEngine, DEFAULT_ATTACK_POLICIES,
    attack_supports, attack_sweep, attack_sweep_cached,
)

__all__ = ["CompiledNet", "compile_network", "sample_delay_matrix",
           "NETSIM_KINDS", "Engine", "SUPPORTED_PROTOCOLS", "grid",
           "supports", "ATTACK_PROTOCOLS", "AttackEngine",
           "DEFAULT_ATTACK_POLICIES", "attack_supports", "attack_sweep",
           "attack_sweep_cached"]
