"""Compile a `network.Network` into dense device arrays.

Mirrors the row-major (src*n + dst) link encoding the oracle's custom-
topology C API uses (network.simulate: kind/p0/p1 triples, kind -1 for
"no link"), but keeps the result on the JAX side: the engine samples a
whole (N, N) delay matrix per event from the same formulas as
`Distribution.sample_jax`, so the declaration that drives the host
oracle drives the in-graph engine too.

The oracle accepts constant/uniform/exponential link delays; netsim
additionally supports geometric (the `sample_jax` face already does).
`discrete` link delays are rejected at compile time with a clear
message — same failure surface as `network.simulate`, but before any
device work happens.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from cpr_tpu.distributions import GEOM_TAIL_CLAMP
from cpr_tpu.network import Network

# link-delay kinds the in-graph sampler implements (superset of the
# oracle's _KINDS: geometric comes for free from the sample_jax face)
NETSIM_KINDS = {"constant": 0, "uniform": 1, "exponential": 2,
                "geometric": 3}


@dataclass(frozen=True)
class CompiledNet:
    """Dense device-ready topology: per-node compute weights plus
    row-major per-edge (kind, p0, p1) delay planes, kind -1 = no
    link."""
    n: int
    compute: np.ndarray        # (N,) f32, normalized to sum 1
    kind: np.ndarray           # (N, N) i32, NETSIM_KINDS or -1
    p0: np.ndarray             # (N, N) f64
    p1: np.ndarray             # (N, N) f64
    activation_delay: float
    flooding: bool


def compile_network(net: Network) -> CompiledNet:
    if net.dissemination not in ("simple", "flooding"):
        raise ValueError(f"unknown dissemination '{net.dissemination}'")
    n = len(net.nodes)
    if n < 2:
        raise ValueError("netsim needs at least 2 nodes")
    compute = np.array([nd.compute for nd in net.nodes], np.float64)
    total = compute.sum()
    if not (total > 0):
        raise ValueError("total compute must be positive")
    kind = np.full((n, n), -1, np.int32)
    p0 = np.zeros((n, n), np.float64)
    p1 = np.zeros((n, n), np.float64)
    for i, nd in enumerate(net.nodes):
        for link in nd.links:
            d = link.delay
            if d.kind not in NETSIM_KINDS:
                raise ValueError(
                    f"netsim supports constant/uniform/exponential/"
                    f"geometric link delays, not '{d.kind}'")
            kind[i, link.dest] = NETSIM_KINDS[d.kind]
            p0[i, link.dest] = d.params[0]
            p1[i, link.dest] = d.params[1] if len(d.params) > 1 else 0.0
    return CompiledNet(
        n=n, compute=(compute / total).astype(np.float32), kind=kind,
        p0=p0, p1=p1, activation_delay=float(net.activation_delay),
        flooding=net.dissemination == "flooding")


def sample_delay_matrix(key, kind, p0, p1, dtype):
    """One (N, N) draw of every link's delay, matching
    `Distribution.sample_jax` per kind (elementwise over the dense
    planes; unlinked entries produce garbage that callers mask via
    kind >= 0)."""
    import jax
    import jax.numpy as jnp

    k_u, k_e = jax.random.split(key)
    u = jax.random.uniform(key=k_u, shape=kind.shape,
                           minval=GEOM_TAIL_CLAMP, maxval=1.0,
                           dtype=dtype)
    e = jax.random.exponential(k_e, shape=kind.shape, dtype=dtype)
    const = p0
    unif = p0 + u * (p1 - p0)
    expo = e * p0
    # geometric: trials to first success at prob p0, >= 1; the p0 >= 1
    # degenerate case collapses to 1 exactly as both Distribution faces
    log1mp = jnp.log(jnp.clip(1.0 - p0, 1e-300, 1.0))
    geom = jnp.where(p0 >= 1.0, 1.0,
                     jnp.maximum(jnp.ceil(jnp.log(u) / log1mp), 1.0))
    out = jnp.where(kind == 0, const,
                    jnp.where(kind == 1, unif,
                              jnp.where(kind == 2, expo, geom)))
    return out.astype(dtype)
