"""Adversary-in-the-network: withholding attacks inside the netsim.

Every selfish-mining env in this repo collapses the network to the
paper's two-party abstraction (attacker vs. one aggregated defender
cloud, zero propagation structure, gamma as an explicit parameter).
This module puts the attacker *inside* the simulated network instead:
node 0 of an arbitrary `network.Network` topology runs a withholding
policy over the SSZ observation space while the remaining nodes mine
and flood honestly through the event engine's queue/pending/flooding
machinery (`netsim/engine.py`).  Withholding and break-even sweeps
thus run under realistic network assumptions — per-link delay
distributions, GraphML topologies, flooding relays — the exact axis
arXiv:2501.10888 sweeps.

Attacker semantics (nakamoto; mirrors envs/nakamoto.py which mirrors
nakamoto_ssz.ml):

* node 0 mines on its **private** tip and never announces at mint;
  honest nodes run unmodified nakamoto (mine on preference, send on
  links, flood on first delivery).
* the attacker keeps a public-view pointer `pub` (highest block
  delivered to node 0) and a private tip `priv`; after every own mint
  (event `PoW`) or public-view advance (event `Network`) it computes
  (a, h) relative to the common ancestor, encodes the SSZ observation
  `(h, a, a - h, event)`, and applies the lane's policy:
  Adopt | Override | Match | Wait.
* Adopt resets `priv <- pub` and abandons the withheld suffix.
  Override releases the private chain up to height h(pub)+1; Match up
  to h(pub).  A release emits the withheld blocks lowest-id-first,
  one per engine step at the decision timestamp, onto node 0's real
  links with sampled delays — whether a Match splits the honest
  miners is decided by message racing, not by a gamma parameter
  (gamma therefore reports as -1.0 in sweep rows).
* common-ancestor search is a bounded two-pointer height walk over
  the ledger (cap `walk_cap`); overflow counts into `win_miss`,
  asserted zero by the tests.

Degenerate-network anchor: on `network.two_agents` (two nodes, zero
link delay) a Match can never split the single honest node, so the
lane must reproduce the two-party env at gamma=0 — the tier-1
cross-check in tests/test_netsim_attack.py holds the relative revenue
gap under a stated tolerance on matched (policy, alpha, seed) grids.

`attack_sweep()` runs protocols x topologies x delays x alphas x
policies as ONE vmapped (and mesh-shardable) program per topology —
alpha and policy id are lane inputs, so the whole grid shares a
single compiled executable per lane count.
"""

from __future__ import annotations

import hashlib
import json
import os
from functools import partial

import numpy as np

from cpr_tpu import telemetry
from cpr_tpu.netsim.compile import (CompiledNet, compile_network,
                                    sample_delay_matrix)

ATTACK_PROTOCOLS = ("nakamoto",)
SCRIPTED_POLICIES = ("honest", "simple", "eyal-sirer-2014",
                     "sapirshtein-2016-sm1")
DEFAULT_ATTACK_POLICIES = ("honest", "eyal-sirer-2014",
                           "sapirshtein-2016-sm1")
DEFAULT_ALPHAS = (0.15, 0.25, 0.33, 0.4, 0.45)


def attack_supports(protocol: str, k: int = 1,
                    scheme: str = "constant") -> bool:
    """True when the attack lane implements this protocol config.
    Only nakamoto for now: the other engine protocols (bk, ethereum,
    spar) run honest-only; their withholding spaces need per-protocol
    release semantics (vote withholding, uncle games) — see
    docs/NETSIM.md's supported-protocol matrix."""
    return protocol in ATTACK_PROTOCOLS


def _attack_lane_fn(cn: CompiledNet, activations: int, B: int, M: int,
                    F: int, S: int, WA: int, branches,
                    strict_match: bool = True):
    """Build lane(key, activation_delay, alpha, policy_id) -> metrics.
    Structure follows engine._lane_fn's nakamoto path; the deltas are
    the private/public bookkeeping, the release step type, and the
    policy handle."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from cpr_tpu import obs as obslib
    from cpr_tpu.envs.nakamoto import (ADOPT, EV_NETWORK, EV_POW, MATCH,
                                       OBS_FIELDS, OVERRIDE)

    N = int(cn.n)
    A = int(activations)
    C = N * F + N * N
    ft = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    i32 = jnp.int32
    INF = jnp.asarray(jnp.inf, ft)

    kindm = jnp.asarray(cn.kind, i32)
    p0m = jnp.asarray(cn.p0, ft)
    p1m = jnp.asarray(cn.p1, ft)
    has_link = kindm >= 0
    # honest compute shares renormalized over nodes 1..N-1; node 0's
    # weight is the lane's alpha (the declared topology weight of the
    # attacker node is overridden per lane)
    _wh = np.asarray(cn.compute[1:], np.float64)
    whon = jnp.asarray(_wh / _wh.sum(), jnp.float32)
    arangeN = jnp.arange(N, dtype=i32)
    idsB = jnp.arange(B, dtype=i32)
    n_pol = len(branches)

    def init(key, activation_delay):
        key, k0 = jax.random.split(key)
        first = jax.random.exponential(k0, dtype=ft) * activation_delay
        return dict(
            key=key,
            now=jnp.asarray(0.0, ft),
            next_act=first,
            n_act=jnp.asarray(0, i32),
            nb=jnp.asarray(1, i32),
            seq=jnp.asarray(0, i32),
            steps=jnp.asarray(0, i32),
            live=jnp.asarray(True, bool),
            parent0=jnp.full((B,), -1, i32),
            height=jnp.zeros((B,), i32),
            miner=jnp.full((B,), -1, i32),
            pref=jnp.zeros((N,), i32),
            vis=jnp.zeros((N, B), bool).at[:, 0].set(True),
            vis_at=jnp.full((N, B), jnp.inf, ft).at[:, 0].set(0.0),
            known=jnp.zeros((N, B), bool).at[:, 0].set(True),
            node_act=jnp.zeros((N,), i32),
            q_time=jnp.full((M,), jnp.inf, ft),
            q_dst=jnp.zeros((M,), i32),
            q_blk=jnp.zeros((M,), i32),
            q_seq=jnp.zeros((M,), i32),
            pend=jnp.full((N, F), -1, i32),
            priv=jnp.asarray(0, i32),
            pub=jnp.asarray(0, i32),
            withheld=jnp.zeros((B,), bool),
            rel_h=jnp.asarray(-1, i32),
            drop_q=jnp.asarray(0, i32),
            drop_p=jnp.asarray(0, i32),
            drop_b=jnp.asarray(0, i32),
            win_miss=jnp.asarray(0, i32),
        )

    def body(st, activation_delay, logw, pid):
        key, k_mine, k_next, k_delay = jax.random.split(st["key"], 4)
        tmin = jnp.min(st["q_time"])
        has_q = jnp.isfinite(tmin)
        can_act = st["n_act"] < A
        # a pending release preempts both activations and deliveries:
        # the whole withheld prefix goes out at the decision timestamp
        wh_ok = st["withheld"] & (st["height"] <= st["rel_h"])
        is_rel = jnp.any(wh_ok)
        act_now = can_act & (st["next_act"] <= tmin)
        recv_ok = has_q & ~(~can_act & (tmin >= st["next_act"]))
        is_act = ~is_rel & act_now
        is_recv = ~is_rel & ~act_now & recv_ok
        now2 = jnp.where(is_act, st["next_act"],
                         jnp.where(is_recv, tmin, st["now"]))

        # ---- delivery wave (engine semantics, nakamoto preference) --
        wave0 = is_recv & (st["q_time"] == tmin)
        seqs = jnp.where(wave0, st["q_seq"],
                         jnp.asarray(2**31 - 1, i32))
        i0 = jnp.argmin(seqs)
        b = jnp.where(is_recv, st["q_blk"][i0], 0)
        wave = wave0 & (st["q_blk"] == b)
        dvec = jnp.zeros((N + 1,), bool).at[
            jnp.where(wave, st["q_dst"], N)].max(True)
        dmask = dvec[:N]
        q_time_pop = jnp.where(wave, INF, st["q_time"])

        pb = st["parent0"][b]
        pbc = jnp.clip(pb, 0)
        pv = (pb < 0) | st["vis"][:, pbc]
        fresh = dmask & ~st["known"][:, b]
        deliver = dmask & ~st["vis"][:, b] & pv
        blocked = fresh & ~pv
        known2 = st["known"].at[arangeN, b].max(dmask)
        vis2 = st["vis"].at[arangeN, b].max(deliver)
        vis_at2 = st["vis_at"].at[arangeN, b].min(
            jnp.where(deliver, tmin, INF))

        occ = st["pend"] >= 0
        has_free = ~jnp.all(occ, axis=1)
        slot = jnp.argmin(occ, axis=1).astype(i32)
        park = blocked & has_free
        pend2 = st["pend"].at[arangeN, slot].set(
            jnp.where(park, b, st["pend"][arangeN, slot]))
        drop_p2 = st["drop_p"] + jnp.sum(
            blocked & ~has_free).astype(i32)

        better = st["height"][b] > st["height"][st["pref"]]
        pref2 = jnp.where(deliver & better, b, st["pref"])
        # the attacker's public view advances on first delivery of a
        # strictly higher block at node 0
        pub_gain = is_recv & deliver[0] & (
            st["height"][b] > st["height"][st["pub"]])
        pub2 = jnp.where(pub_gain, b, st["pub"])

        par_p = st["parent0"][jnp.clip(pend2, 0)]
        vis_par = (par_p < 0) | vis2[arangeN[:, None],
                                     jnp.clip(par_p, 0)]
        unl = (pend2 >= 0) & deliver[:, None] & vis_par
        pend3 = jnp.where(unl, -1, pend2)

        # ---- release step: lowest-id withheld block <= rel_h --------
        # (lowest id first keeps the released chain parent-before-
        # child, so honest delivery never parks more than transiently)
        rb = jnp.clip(jnp.min(jnp.where(wh_ok, idsB, B)), 0, B - 1)
        withheld2 = st["withheld"].at[
            jnp.where(is_rel, rb, B)].set(False)
        rel_done = is_rel & (jnp.sum(wh_ok).astype(i32) <= 1)
        rel_h2 = jnp.where(rel_done, -1, st["rel_h"])
        pub3 = jnp.where(
            is_rel & (st["height"][rb] > st["height"][pub2]), rb, pub2)

        # ---- activation: node 0 mines privately, honest on pref -----
        m = jax.random.categorical(k_mine, logw).astype(i32)
        next_act2 = jnp.where(
            is_act,
            st["next_act"]
            + jax.random.exponential(k_next, dtype=ft)
            * activation_delay,
            st["next_act"])
        atk_mine = m == 0
        parent_act = jnp.where(atk_mine, st["priv"], st["pref"][m])
        h_parent = st["height"][parent_act]
        n_act2 = st["n_act"] + is_act.astype(i32)
        node_act2 = st["node_act"].at[jnp.where(is_act, m, N)].add(1)

        ok_act = is_act & (st["nb"] < B)
        drop_b2 = st["drop_b"] + (is_act & (st["nb"] >= B)).astype(i32)
        idxs = jnp.where(ok_act, st["nb"], B)
        parent3 = st["parent0"].at[idxs].set(parent_act)
        height3 = st["height"].at[idxs].set(h_parent + 1)
        miner3 = st["miner"].at[idxs].set(m)
        nb2 = st["nb"] + ok_act.astype(i32)
        vis3 = vis2.at[m, idxs].set(True)
        known3 = known2.at[m, idxs].set(True)
        vis_at3 = vis_at2.at[m, idxs].min(now2)
        # honest miners advance their preference at mint; the
        # attacker's mint stays private (pref[0] is public-view only)
        pref3 = pref2.at[
            jnp.where(ok_act & ~atk_mine, m, N)].set(st["nb"])
        atk_new = ok_act & atk_mine
        priv2 = jnp.where(atk_new, st["nb"], st["priv"])
        withheld3 = withheld2.at[
            jnp.where(atk_new, st["nb"], B)].set(True)

        # ---- SSZ handle: own PoW or public-view advance -------------
        ev = jnp.where(atk_new, EV_POW, EV_NETWORK).astype(i32)
        do_handle = atk_new | pub_gain

        # bounded two-pointer common-ancestor walk (equal heights step
        # both sides; distinct blocks share height only off-chain, so
        # the walk meets at the fork point)
        x0 = jnp.where(do_handle, priv2, 0)
        y0 = jnp.where(do_handle, pub3, 0)

        def wcond(c):
            x, y, i = c
            return (x != y) & (i < WA)

        def wstep(c):
            x, y, i = c
            hx = height3[x]
            hy = height3[y]
            x2 = jnp.where(hx >= hy, jnp.maximum(parent3[x], 0), x)
            y2 = jnp.where(hy >= hx, jnp.maximum(parent3[y], 0), y)
            return (x2, y2, i + 1)

        xf, yf, _ = lax.while_loop(
            wcond, wstep, (x0, y0, jnp.asarray(0, i32)))
        win_miss2 = st["win_miss"] + (do_handle
                                      & (xf != yf)).astype(i32)
        h_ca = height3[xf]
        a_rel = height3[priv2] - h_ca
        h_rel = height3[pub3] - h_ca
        obs = obslib.encode(OBS_FIELDS,
                            (h_rel, a_rel, a_rel - h_rel, ev), True)
        action = lax.switch(pid, branches, obs).astype(i32)
        adopt = do_handle & (action == ADOPT)
        override_eff = do_handle & (action == OVERRIDE) & (a_rel > h_rel)
        match_eff = (do_handle & (action == MATCH) & (a_rel >= h_rel)
                     & (h_rel > 0))
        if strict_match:
            match_eff = match_eff & (ev == EV_NETWORK)
        priv3 = jnp.where(adopt, pub3, priv2)
        withheld4 = jnp.where(adopt, jnp.zeros((B,), bool), withheld3)
        h_pub = height3[pub3]
        rel_h3 = jnp.where(override_eff, h_pub + 1,
                           jnp.where(match_eff, h_pub, rel_h2))

        # ---- push: unlock re-queues + link sends --------------------
        delays = sample_delay_matrix(k_delay, kindm, p0m, p1m, ft)
        if cn.flooding:
            flood_src = deliver & (st["miner"][b] != arangeN)
        else:
            flood_src = jnp.zeros((N,), bool)
        send_src = jnp.where(
            is_recv, flood_src,
            jnp.where(is_rel, arangeN == 0,
                      (arangeN == m) & ok_act & ~atk_mine))
        s_valid = send_src[:, None] & has_link
        s_time = now2 + delays
        s_blk = jnp.where(is_recv, b, jnp.where(is_rel, rb, st["nb"]))

        c_valid = jnp.concatenate([unl.reshape(-1),
                                   s_valid.reshape(-1)])
        c_time = jnp.concatenate([jnp.full((N * F,), 1.0, ft) * now2,
                                  s_time.reshape(-1)])
        c_dst = jnp.concatenate([jnp.repeat(arangeN, F),
                                 jnp.tile(arangeN, N)])
        c_blk = jnp.concatenate([jnp.clip(pend2.reshape(-1), 0),
                                 jnp.full((N * N,), 1, i32) * s_blk])

        free = ~jnp.isfinite(q_time_pop)
        rank = jnp.cumsum(c_valid.astype(i32))
        n_valid = rank[-1]
        frank = jnp.cumsum(free.astype(i32))
        n_free = frank[-1]
        n_place = jnp.minimum(n_valid, n_free)
        placed = c_valid & (rank <= n_place)
        r2c = jnp.zeros((max(C, M) + 1,), i32).at[
            jnp.where(placed, rank, 0)].set(jnp.arange(C, dtype=i32))
        fill = free & (frank <= n_place)
        cidx = r2c[jnp.clip(frank, 0, C)]
        q_time2 = jnp.where(fill, c_time[cidx], q_time_pop)
        q_dst2 = jnp.where(fill, c_dst[cidx], st["q_dst"])
        q_blk2 = jnp.where(fill, c_blk[cidx], st["q_blk"])
        q_seq2 = jnp.where(fill, st["seq"] + frank, st["q_seq"])
        seq2 = st["seq"] + n_valid
        drop_q2 = st["drop_q"] + (n_valid - n_place)

        new = dict(
            key=key, now=now2, next_act=next_act2, n_act=n_act2,
            nb=nb2, seq=seq2, steps=st["steps"] + 1,
            parent0=parent3, height=height3, miner=miner3,
            pref=pref3, vis=vis3, vis_at=vis_at3, known=known3,
            node_act=node_act2, q_time=q_time2, q_dst=q_dst2,
            q_blk=q_blk2, q_seq=q_seq2, pend=pend3,
            priv=priv3, pub=pub3, withheld=withheld4, rel_h=rel_h3,
            drop_q=drop_q2, drop_p=drop_p2, drop_b=drop_b2,
            win_miss=win_miss2,
        )
        tmin2 = jnp.min(q_time2)
        rel_pending = jnp.any(withheld4 & (height3 <= rel_h3))
        new["live"] = (rel_pending | (n_act2 < A)
                       | ((tmin2 < next_act2) & jnp.isfinite(tmin2)))
        return new

    def finalize(st):
        height = st["height"]
        hp = height[st["pref"]]
        h_hon = jnp.where(arangeN >= 1, hp, -1)
        jb = jnp.argmax(h_hon).astype(i32)
        best_h = jnp.max(h_hon)
        h_priv = height[st["priv"]]
        # the withheld suffix competes at episode end; ties go to the
        # attacker (engine.ml winner fold order, envs/nakamoto.py)
        head = jnp.where(h_priv >= best_h, st["priv"], st["pref"][jb])
        head_height = height[head]

        def rstep(cur, _):
            ok = cur > 0
            cc = jnp.clip(cur, 0)
            return (jnp.where(ok, st["parent0"][cc], 0),
                    jnp.where(ok, st["miner"][cc], N))

        _, miners = lax.scan(rstep, head, None, length=A + 2)
        rewards = jnp.zeros((N + 1,), jnp.float32).at[
            miners].add(1.0)[:N]
        return dict(
            head=head, head_height=head_height,
            progress=head_height.astype(ft),
            on_chain=head_height.astype(ft),
            reward=rewards,
            reward_attacker=rewards[0],
            reward_defender=jnp.sum(rewards[1:]),
            sim_time=st["now"], n_blocks=st["nb"] - 1,
            n_act=st["n_act"], node_act=st["node_act"],
            steps=st["steps"],
            drop_q=st["drop_q"], drop_p=st["drop_p"],
            drop_b=st["drop_b"], win_miss=st["win_miss"],
            exhausted=st["live"] & (st["steps"] >= S),
        )

    def lane(key, activation_delay, alpha, policy_id):
        alpha32 = jnp.asarray(alpha, jnp.float32)
        logw = jnp.log(jnp.concatenate(
            [alpha32[None], (1.0 - alpha32) * whon]))
        pid = jnp.clip(policy_id, 0, n_pol - 1)
        st = init(key, activation_delay)
        st = jax.lax.while_loop(
            lambda s: s["live"] & (s["steps"] < S),
            partial(body, activation_delay=activation_delay,
                    logw=logw, pid=pid), st)
        return finalize(st)

    return lane


class AttackEngine:
    """One compiled attacker-in-the-network program: fixed topology and
    activation target; `run()` executes a batch of lanes — independent
    (seed, activation_delay, alpha, policy_id) tuples — as a single
    jitted, vmapped (and optionally mesh-sharded) call.

        eng = AttackEngine(net, activations=2000,
                           policies=("honest", "sapirshtein-2016-sm1"))
        out = eng.run(seeds=[0, 1], activation_delays=[60.0, 60.0],
                      alphas=[0.33, 0.33], policy_ids=[0, 1])

    Alpha and policy id are LANE inputs: a whole alphas x policies grid
    shares one executable.  `extra_policies` maps names to obs->action
    callables (e.g. a loaded PPO snapshot via
    train.driver.load_policy_snapshot); scripted names come from
    envs.nakamoto.NakamotoSSZ.policies.
    """

    def __init__(self, net, *, protocol: str = "nakamoto", k: int = 1,
                 scheme: str = "constant", activations: int,
                 policies=DEFAULT_ATTACK_POLICIES, extra_policies=None,
                 strict_match: bool = True, topology: str = "custom",
                 block_cap: int | None = None,
                 queue_cap: int | None = None, pend_cap: int = 8,
                 walk_cap: int | None = None,
                 max_steps: int | None = None,
                 x64: bool = True, mesh=None, mesh_axis: str = "d"):
        if not attack_supports(protocol, k, scheme):
            raise ValueError(
                f"netsim attack supports protocols {ATTACK_PROTOCOLS}, "
                f"not '{protocol}'")
        extra_policies = dict(extra_policies or {})
        bad = [p for p in policies
               if p not in SCRIPTED_POLICIES and p not in extra_policies]
        if bad:
            raise ValueError(
                f"unknown attack policies {bad}; scripted: "
                f"{SCRIPTED_POLICIES}, extra: "
                f"{sorted(extra_policies)}")
        self.net = (net if isinstance(net, CompiledNet)
                    else compile_network(net))
        self.protocol = protocol
        self.topology = str(topology)
        self.activations = int(activations)
        self.policies = tuple(policies)
        self.extra_policies = extra_policies
        # extras not named in `policies` ride along after them, so a
        # PPO snapshot can be addressed by id without reordering
        self.policy_names = self.policies + tuple(
            nm for nm in extra_policies if nm not in self.policies)
        self.strict_match = bool(strict_match)
        n, a = self.net.n, self.activations
        self.B = block_cap or a + 2
        # releases re-send the withheld chain: up to 2x the mint sends
        self.M = queue_cap or max(256, 32 * n)
        self.F = int(pend_cap)
        # common-ancestor walk cap: the batched while_loop exits as
        # soon as every lane's walk meets, so the absolute bound (one
        # chain can never be longer than the ledger) costs nothing at
        # runtime — high-alpha MATCH play sustains forks hundreds deep
        self.WA = int(walk_cap or a + 2)
        self.S = max_steps or a * (n + 5) + 4096
        self.x64 = bool(x64)
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.n_devices = (int(mesh.shape[mesh_axis])
                          if mesh is not None else 1)
        self._exe = {}

    def _ctx(self):
        import contextlib

        from jax.experimental import enable_x64

        return enable_x64() if self.x64 else contextlib.nullcontext()

    def _branches(self):
        from cpr_tpu.envs.nakamoto import NakamotoSSZ

        env = NakamotoSSZ(unit_observation=True,
                          strict_match=self.strict_match)
        out = []
        for nm in self.policy_names:
            out.append(self.extra_policies.get(nm) or env.policies[nm])
        return out

    def _compiled(self, keys, delays, alphas, pids):
        import jax

        L = keys.shape[0]
        exe = self._exe.get(L)
        if exe is None:
            fn = _attack_lane_fn(self.net, self.activations, self.B,
                                 self.M, self.F, self.S, self.WA,
                                 self._branches(), self.strict_match)
            jitted = jax.jit(jax.vmap(fn))
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                from cpr_tpu.parallel.lanes import check_even_shards
                check_even_shards(L, self.mesh, axis=self.mesh_axis,
                                  what="attack lanes")
                lane = NamedSharding(self.mesh,
                                     PartitionSpec(self.mesh_axis))
                jitted = jax.jit(
                    jax.vmap(fn),
                    in_shardings=(lane, lane, lane, lane),
                    out_shardings=lane)
            tele = telemetry.current()
            with telemetry.compile_watch(), \
                    tele.span("attack:compile", lanes=L):
                exe = jitted.lower(keys, delays, alphas, pids).compile()
            self._exe[L] = exe
        return exe

    def run(self, seeds, activation_delays, alphas, policy_ids) -> dict:
        """Execute len(seeds) attack lanes as one device program;
        returns numpy arrays with lane axis 0 plus the v11
        `attack_sweep` typed telemetry event."""
        import jax
        import jax.numpy as jnp

        seeds = list(seeds)
        delays = list(activation_delays)
        alphas = [float(a) for a in alphas]
        pids = [int(p) for p in policy_ids]
        L = len(seeds)
        if not (len(delays) == len(alphas) == len(pids) == L):
            raise ValueError(
                "seeds, activation_delays, alphas, policy_ids must "
                "pair up")
        bad_a = [a for a in alphas if not 0.0 < a < 1.0]
        if bad_a:
            raise ValueError(f"alphas must lie in (0, 1), got {bad_a}")
        tele = telemetry.current()
        with self._ctx():
            keys = jnp.stack([jax.random.PRNGKey(s) for s in seeds])
            dl = jnp.asarray(delays,
                             jnp.float64 if self.x64 else jnp.float32)
            al = jnp.asarray(alphas, jnp.float32)
            pi = jnp.asarray(pids, jnp.int32)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                from cpr_tpu.parallel.lanes import check_even_shards
                check_even_shards(L, self.mesh, axis=self.mesh_axis,
                                  what="attack lanes")
                lane = NamedSharding(self.mesh,
                                     PartitionSpec(self.mesh_axis))
                keys = jax.device_put(keys, lane)
                dl = jax.device_put(dl, lane)
                al = jax.device_put(al, lane)
                pi = jax.device_put(pi, lane)
            exe = self._compiled(keys, dl, al, pi)
            with tele.span("attack:run", lanes=L,
                           activations=L * self.activations) as sp:
                out = sp.fence(exe(keys, dl, al, pi))
        out = {kk: np.asarray(v) for kk, v in out.items()}
        drops = int(out["drop_q"].sum() + out["drop_p"].sum()
                    + out["drop_b"].sum() + out["win_miss"].sum())
        tele.event("attack_sweep", protocol=self.protocol,
                   topology=self.topology, lanes=L,
                   policies=len(self.policy_names), drops=drops,
                   activations=int(np.sum(out["n_act"])),
                   n_devices=self.n_devices,
                   sweep_s=round(sp.dur_s, 6),
                   lanes_per_sec=round(L / max(sp.dur_s, 1e-9), 3))
        return out


def attack_sweep(topologies, *, protocols=(("nakamoto", {}),),
                 policies=DEFAULT_ATTACK_POLICIES, extra_policies=None,
                 alphas=DEFAULT_ALPHAS, activation_delays=(60.0,),
                 activations: int = 2000, reps: int = 4, seed: int = 0,
                 strict_match: bool = True, mesh=None,
                 engine_kwargs=None) -> list[dict]:
    """The vmapped attack grid: protocols x topologies x delays x
    alphas x policies, one engine (one compiled program) per
    (protocol, topology), every other axis a lane input.  Rows use the
    `experiments/withholding.py` schema (protocol, attack, alpha,
    gamma, reward_attacker, reward_defender, relative_reward, ...)
    plus topology/activation_delay/n_nodes extras; gamma reports -1.0
    because the communication advantage emerges from message racing on
    the real topology.  Unsupported protocols degrade to error rows
    with a machine-readable `reason`, mirroring honest_net_rows."""
    items = (list(topologies.items()) if isinstance(topologies, dict)
             else list(topologies))
    pols = list(policies) + [nm for nm in (extra_policies or {})
                             if nm not in policies]
    grid_pts = [(d, a, pi) for d in activation_delays for a in alphas
                for pi in range(len(pols))]
    rows: list[dict] = []
    for proto, kw in protocols:
        kk = int(kw.get("k", 1))
        scheme = kw.get("scheme", "constant")
        for tname, net in items:
            ident = {"protocol": proto, "topology": str(tname),
                     "engine": "netsim-attack"}
            t0 = telemetry.now()
            if not attack_supports(proto, kk, scheme):
                rows.append({
                    **ident,
                    "error": (f"netsim attack supports protocols "
                              f"{ATTACK_PROTOCOLS}, not '{proto}'"),
                    "reason": "unsupported-protocol",
                    "machine_duration_s": telemetry.now() - t0,
                })
                continue
            try:
                eng = AttackEngine(
                    net, protocol=proto, k=kk, scheme=scheme,
                    activations=activations, policies=policies,
                    extra_policies=extra_policies,
                    strict_match=strict_match, topology=str(tname),
                    mesh=mesh, **(engine_kwargs or {}))
                ss, dd, aa, pp = [], [], [], []
                for gi, (d, a, pi) in enumerate(grid_pts):
                    for r in range(reps):
                        ss.append(seed + gi * reps + r)
                        dd.append(float(d))
                        aa.append(float(a))
                        pp.append(pi)
                out = eng.run(ss, dd, aa, pp)
            except Exception as e:  # mirror experiments.sweep.run_task
                rows.append({
                    **ident,
                    "error": f"{type(e).__name__}: {e}",
                    "reason": "runtime-error",
                    "machine_duration_s": telemetry.now() - t0,
                })
                continue
            dt = telemetry.now() - t0
            atk = out["reward_attacker"].reshape(len(grid_pts), reps)
            dfn = out["reward_defender"].reshape(len(grid_pts), reps)
            prg = np.asarray(out["progress"]).reshape(
                len(grid_pts), reps)
            for gi, (d, a, pi) in enumerate(grid_pts):
                ra = float(atk[gi].mean())
                rd = float(dfn[gi].mean())
                pg = float(prg[gi].mean())
                total = ra + rd
                rows.append({
                    **ident,
                    "attack": f"{proto}-{pols[pi]}",
                    "alpha": float(a),
                    "gamma": -1.0,
                    "episode_len": int(activations),
                    "reps": int(reps),
                    "reward_attacker": ra,
                    "reward_defender": rd,
                    "relative_reward": ra / total if total else 0.0,
                    "reward_per_progress": ra / pg if pg else 0.0,
                    "machine_duration_s": dt / len(grid_pts),
                    "activation_delay": float(d),
                    "n_nodes": int(eng.net.n),
                })
    return rows


def _cache_dir() -> str:
    """Sweep-cache directory: CPR_ATTACK_CACHE >
    <CPR_TPU_CACHE>/attack_sweep > ~/.cache/cpr_tpu/attack_sweep (the
    mdp_grid cache-dir pattern; delete the directory to bust)."""
    d = os.environ.get("CPR_ATTACK_CACHE")
    if d:
        return d
    base = os.environ.get("CPR_TPU_CACHE")
    if base:
        return os.path.join(base, "attack_sweep")
    return os.path.join(os.path.expanduser("~"), ".cache", "cpr_tpu",
                        "attack_sweep")


def attack_sweep_cached(net, topology: str, *,
                        protocol: str = "nakamoto", k: int = 1,
                        scheme: str = "constant",
                        policies=DEFAULT_ATTACK_POLICIES,
                        alphas=DEFAULT_ALPHAS,
                        activation_delays=(60.0,),
                        activations: int = 2000, reps: int = 4,
                        seed: int = 0, strict_match: bool = True,
                        cache: bool = True, mesh=None,
                        extra_policies=None,
                        extra_fingerprint: str = "") -> dict:
    """`attack_sweep` for one (protocol, topology), with the result
    cached on disk keyed by the topology's GraphML fingerprint + every
    sweep knob (the `mdp.solve_grid` caching pattern): anything that
    changes the network or the grid changes the key.  The serve
    `netsim.attack_sweep` op sits on this.  `extra_fingerprint` must
    name any extra policy content (e.g. the PPO snapshot path) since
    callables cannot be hashed."""
    import cpr_tpu
    from cpr_tpu import resilience
    from cpr_tpu.network import to_graphml

    topo_fp = hashlib.sha256(
        to_graphml(net).encode()).hexdigest()[:16]
    pols = list(policies) + [nm for nm in (extra_policies or {})
                             if nm not in policies]
    key = dict(kind="attack_sweep", protocol=protocol, k=int(k),
               scheme=scheme, topology=str(topology), topo_fp=topo_fp,
               policies=pols, alphas=[float(a) for a in alphas],
               activation_delays=[float(d) for d in activation_delays],
               activations=int(activations), reps=int(reps),
               seed=int(seed), strict_match=bool(strict_match),
               extra_fingerprint=str(extra_fingerprint),
               _version=cpr_tpu.__version__)
    h = hashlib.sha256(
        json.dumps(key, sort_keys=True).encode()).hexdigest()[:24]
    path = os.path.join(_cache_dir(), h + ".json")
    if cache and os.path.exists(path):
        # corruption is a MISS, never a crash (the solve_grid_cached
        # policy): quarantine + typed `integrity` event + recompute;
        # pre-v19 unsealed entries read tagged integrity: "unverified"
        from cpr_tpu import integrity
        try:
            data, tag = resilience.sealed_read_json(
                path, kind="attack_cache", action="regenerated")
            return dict(data["value"], cached=True, integrity=tag)
        except resilience.IntegrityError:
            pass
        except (OSError, KeyError, TypeError):
            integrity.quarantine(path, kind="attack_cache",
                                 reason="truncated", action="regenerated")
    t0 = telemetry.now()
    rows = attack_sweep(
        [(topology, net)], protocols=((protocol, dict(k=k,
                                                      scheme=scheme)),),
        policies=policies, extra_policies=extra_policies,
        alphas=alphas, activation_delays=activation_delays,
        activations=activations, reps=reps, seed=seed,
        strict_match=strict_match, mesh=mesh)
    value = dict(
        protocol=protocol, topology=str(topology),
        topo_fingerprint=topo_fp, policies=pols,
        alphas=[float(a) for a in alphas],
        activation_delays=[float(d) for d in activation_delays],
        activations=int(activations), reps=int(reps), seed=int(seed),
        rows=rows, sweep_s=round(telemetry.now() - t0, 6),
        cached=False)
    if cache:
        resilience.sealed_write_json(path, {"key": key, "value": value},
                                     site="cache")
    return value
