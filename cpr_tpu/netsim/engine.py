"""The jittable multi-node discrete-event engine.

One `lax.while_loop` drives a whole honest-node simulation: a shared
append-only block ledger (dense per-field arrays + per-(node, block)
visibility bits/times), in-flight messages as a fixed-capacity queue,
and each step advancing to the min over next-activation vs. earliest
pending delivery.  `vmap` over lanes carries independent
(seed, activation_delay) pairs, so a sweep grid is one device program.

Event semantics follow oracle.cpp (the house multi-node engine):

* activation: exponential inter-arrival, compute-weighted miner draw,
  append the protocol block (nakamoto: child of preference; bk: a vote
  on the preference), self-visibility, per-destination sampled link
  delays into the queue.
* delivery: the earliest queue entry — plus every same-(time, block)
  sibling, delivered as one wave (a broadcast of one block over equal
  constant delays collapses to a single step; unequal delays
  degenerate gracefully to per-event steps).  First arrival marks the
  block known (dedup); delivery requires the parent visible, else the
  block parks in a per-node pending buffer and is re-queued at the
  delivering timestamp once the parent lands (oracle
  unlock_children).  Flooding re-shares on first delivery.
* bk proposal: state-triggered — whenever some node's preferred block
  has a visible quorum (>= k confirming votes), at least one own vote,
  and a best-own-hash below the best visible replacement, one proposer
  per step appends a proposal at the current timestamp (no time
  advance), exactly the oracle's propose-within-the-event behavior.
  The quorum is selected at proposal time (k smallest own hashes,
  padded with others' votes of larger hash in append order) and
  stored, so the reward walk replays the oracle's constant/block
  schemes exactly.
* drain: after the activation target, deliveries keep processing only
  while they precede the next (never-executed) activation — the
  oracle's run() stops at the first activation event left in its
  queue, and messages beyond that horizon stay undelivered there too.

Documented approximations vs. the oracle (see docs/NETSIM.md for why
each is distribution-preserving on the honest grids we check):
parent-gating on parent0 only (the oracle gates on all parents; bk
proposals' quorum parents can lag parent0 on non-clique topologies),
proposals land one engine step after the triggering event at the same
timestamp, and bk quorum search uses a fixed ledger window after the
confirmed block (window misses are counted in `win_miss`, asserted 0
by the parity tests).

Times are float64: at sim_time ~ 6e6 (10k activations x 600s delay)
the f32 ulp is ~0.5s, enough to distort same-timestamp wave grouping.
`Engine` enters `jax.experimental.enable_x64()` around every trace
and call; non-time state stays explicitly i32/f32/bool.

Two execution modes share the Engine front-end:

* `event` — the general `lax.while_loop` above: any protocol, any
  dissemination, state-dependent message flow (flooding re-shares
  depend on who hears what first).
* `scan`  — a fused nakamoto fast path for simple dissemination,
  where every block is sent exactly once per link at mint, making the
  whole (activations x nodes) arrival-time matrix state-independent
  and presampleable; see `_scan_lane_fn`.  Identical statistics (the
  parity grid runs both), ~10x fewer ops per step, and every op
  carries the lane axis so vmap actually amortizes XLA:CPU dispatch —
  this is the mode that makes a batched sweep beat the serial oracle
  loop on wall-clock.  `mode="auto"` (default) picks it whenever it
  applies.
"""

from __future__ import annotations

from functools import partial

import numpy as np

from cpr_tpu import telemetry
from cpr_tpu.netsim.compile import (CompiledNet, compile_network,
                                    sample_delay_matrix)

SUPPORTED_PROTOCOLS = ("nakamoto", "bk", "ethereum-whitepaper",
                       "ethereum-byzantium", "spar")
_SCHEMES = ("constant", "block")
_ETH = ("ethereum-whitepaper", "ethereum-byzantium")


def supports(protocol: str, k: int = 1, scheme: str = "constant") -> bool:
    """True when the engine implements this protocol config."""
    if protocol == "nakamoto" or protocol in _ETH:
        return True
    return (protocol in ("bk", "spar") and k >= 1
            and (scheme or "constant") in _SCHEMES)


def _lane_fn(cn: CompiledNet, protocol: str, k: int, scheme: str,
             activations: int, B: int, M: int, F: int, W: int, S: int,
             U: int = 2):
    """Build lane(key, activation_delay) -> metrics dict.  All shapes
    static; closure constants come from the compiled network.  `U` is
    the ethereum uncle capacity per block (byzantium: exactly the
    protocol's cap of 2; whitepaper: a fixed budget whose overflow
    counts into `win_miss`, asserted 0 by the parity tests)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    is_bk = protocol == "bk"
    is_eth = protocol in _ETH
    byz = protocol == "ethereum-byzantium"
    is_spar = protocol == "spar"
    KQ = max(k - 1, 1)          # spar quorum row width (k-1 votes)
    N = int(cn.n)
    A = int(activations)
    C = N * F + N * N  # per-step push candidates: unlocks + sends
    ft = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    i32 = jnp.int32
    INF = jnp.asarray(jnp.inf, ft)

    kindm = jnp.asarray(cn.kind, i32)
    p0m = jnp.asarray(cn.p0, ft)
    p1m = jnp.asarray(cn.p1, ft)
    has_link = kindm >= 0
    logw = jnp.log(jnp.asarray(cn.compute, jnp.float32))
    arangeN = jnp.arange(N, dtype=i32)

    def init(key, activation_delay):
        key, k0 = jax.random.split(key)
        first = jax.random.exponential(k0, dtype=ft) * activation_delay
        st = dict(
            key=key,
            now=jnp.asarray(0.0, ft),
            next_act=first,
            n_act=jnp.asarray(0, i32),
            nb=jnp.asarray(1, i32),        # genesis occupies slot 0
            seq=jnp.asarray(0, i32),
            steps=jnp.asarray(0, i32),
            live=jnp.asarray(True, bool),
            parent0=jnp.full((B,), -1, i32),
            height=jnp.zeros((B,), i32),
            miner=jnp.full((B,), -1, i32),
            powh=jnp.full((B,), 2.0, jnp.float32),
            pref=jnp.zeros((N,), i32),
            vis=jnp.zeros((N, B), bool).at[:, 0].set(True),
            vis_at=jnp.full((N, B), jnp.inf, ft).at[:, 0].set(0.0),
            known=jnp.zeros((N, B), bool).at[:, 0].set(True),
            node_act=jnp.zeros((N,), i32),
            q_time=jnp.full((M,), jnp.inf, ft),
            q_dst=jnp.zeros((M,), i32),
            q_blk=jnp.zeros((M,), i32),
            q_seq=jnp.zeros((M,), i32),
            pend=jnp.full((N, F), -1, i32),
            drop_q=jnp.asarray(0, i32),
            drop_p=jnp.asarray(0, i32),
            drop_b=jnp.asarray(0, i32),
        )
        if is_bk:
            st.update(
                is_vote=jnp.zeros((B,), bool),
                lhash=jnp.full((B,), 2.0, jnp.float32),
                conf=jnp.zeros((N, B), i32),
                conf_own=jnp.zeros((N, B), i32),
                mybest=jnp.full((N, B), 2.0, jnp.float32),
                repl=jnp.full((N, B), 2.0, jnp.float32),
                noprop=jnp.zeros((N, B), bool),
                quorum=jnp.full((B, k), -1, i32),
                win_miss=jnp.asarray(0, i32),
            )
        if is_eth:
            st.update(
                work=jnp.zeros((B,), i32),
                uncles=jnp.full((B, U), -1, i32),
                win_miss=jnp.asarray(0, i32),
            )
        if is_spar:
            st.update(
                is_vote=jnp.zeros((B,), bool),
                conf=jnp.zeros((N, B), i32),
                conf_own=jnp.zeros((N, B), i32),
                quorum=jnp.full((B, KQ), -1, i32),
                win_miss=jnp.asarray(0, i32),
            )
        return st

    def bk_want(pref, conf, conf_own, mybest, repl, noprop):
        pj = pref
        cj = conf[arangeN, pj]
        oj = conf_own[arangeN, pj]
        mbj = mybest[arangeN, pj]
        rpj = repl[arangeN, pj]
        npj = noprop[arangeN, pj]
        return (cj >= k) & (oj >= 1) & (mbj < rpj) & ~npj

    def body(st, activation_delay):
        key, k_mine, k_pow, k_next, k_delay = jax.random.split(
            st["key"], 5)
        tmin = jnp.min(st["q_time"])
        has_q = jnp.isfinite(tmin)
        can_act = st["n_act"] < A
        if is_bk:
            want = bk_want(st["pref"], st["conf"], st["conf_own"],
                           st["mybest"], st["repl"], st["noprop"])
            is_prop = jnp.any(want)
        else:
            is_prop = jnp.asarray(False, bool)
        act_now = can_act & (st["next_act"] <= tmin)
        # oracle run(): the drain stops at the first (never-executed)
        # activation left in the queue — deliveries beyond that
        # horizon stay in flight
        recv_ok = has_q & ~(~can_act & (tmin >= st["next_act"]))
        is_act = ~is_prop & act_now
        is_recv = ~is_prop & ~act_now & recv_ok
        now2 = jnp.where(is_act, st["next_act"],
                         jnp.where(is_recv, tmin, st["now"]))

        # ---- delivery wave: every queue entry at (tmin, b) ----------
        wave0 = is_recv & (st["q_time"] == tmin)
        seqs = jnp.where(wave0, st["q_seq"], jnp.asarray(2**31 - 1, i32))
        i0 = jnp.argmin(seqs)
        b = jnp.where(is_recv, st["q_blk"][i0], 0)
        wave = wave0 & (st["q_blk"] == b)
        dvec = jnp.zeros((N + 1,), bool).at[
            jnp.where(wave, st["q_dst"], N)].max(True)
        dmask = dvec[:N]
        q_time_pop = jnp.where(wave, INF, st["q_time"])

        pb = st["parent0"][b]
        pbc = jnp.clip(pb, 0)
        pv = (pb < 0) | st["vis"][:, pbc]            # parent visible
        fresh = dmask & ~st["known"][:, b]
        deliver = dmask & ~st["vis"][:, b] & pv
        blocked = fresh & ~pv
        known2 = st["known"].at[arangeN, b].max(dmask)
        vis2 = st["vis"].at[arangeN, b].max(deliver)
        vis_at2 = st["vis_at"].at[arangeN, b].min(
            jnp.where(deliver, tmin, INF))

        # first arrival with an invisible parent parks in the pending
        # buffer (oracle: known-but-buffered); overflow is counted
        occ = st["pend"] >= 0
        has_free = ~jnp.all(occ, axis=1)
        slot = jnp.argmin(occ, axis=1).astype(i32)
        park = blocked & has_free
        pend2 = st["pend"].at[arangeN, slot].set(
            jnp.where(park, b, st["pend"][arangeN, slot]))
        drop_p2 = st["drop_p"] + jnp.sum(
            blocked & ~has_free).astype(i32)

        if is_bk:
            is_v = st["is_vote"][b]
            dv = deliver & is_v
            dp = deliver & ~is_v
            conf2 = st["conf"].at[arangeN, pbc].add(dv.astype(i32))
            noprop2 = st["noprop"].at[arangeN, pbc].min(~dv)
            repl2 = st["repl"].at[arangeN, pbc].min(
                jnp.where(dp, st["lhash"][b], jnp.float32(3.0)))
            # prefer: candidate = the chain block (vote -> its parent)
            bb = jnp.where(is_v, pbc, b)
            hb = st["height"][bb]
            hp = st["height"][st["pref"]]
            cb = conf2[arangeN, bb]
            cp = conf2[arangeN, st["pref"]]
            lb = st["lhash"][bb]
            lp = st["lhash"][st["pref"]]
            better = (hb > hp) | ((hb == hp) & (
                (cb > cp) | ((cb == cp) & (lb < lp))))
            pref2 = jnp.where(deliver & better, bb, st["pref"])
        elif is_eth:
            # ethereum.ml preference: byzantium by height, whitepaper
            # by cumulative work; strict > (incumbent wins ties)
            ekey = st["height"] if byz else st["work"]
            better = ekey[b] > ekey[st["pref"]]
            pref2 = jnp.where(deliver & better, b, st["pref"])
        elif is_spar:
            # ParallelBase prefer: candidate = the chain block (vote ->
            # the block it confirms, which IS its parent0); keys
            # (height, visible confirming votes), incumbent wins ties
            is_v = st["is_vote"][b]
            dv = deliver & is_v
            conf2 = st["conf"].at[arangeN, pbc].add(dv.astype(i32))
            bb = jnp.where(is_v, pbc, b)
            hb = st["height"][bb]
            hp = st["height"][st["pref"]]
            cb = conf2[arangeN, bb]
            cp = conf2[arangeN, st["pref"]]
            better = (hb > hp) | ((hb == hp) & (cb > cp))
            pref2 = jnp.where(deliver & better, bb, st["pref"])
        else:
            better = st["height"][b] > st["height"][st["pref"]]
            pref2 = jnp.where(deliver & better, b, st["pref"])

        # unlock: parked children whose parent just became visible are
        # re-queued at the delivering timestamp (oracle same-time
        # unlock_children; recursion happens via the re-queued entry)
        par_p = st["parent0"][jnp.clip(pend2, 0)]
        vis_par = (par_p < 0) | vis2[arangeN[:, None],
                                     jnp.clip(par_p, 0)]
        unl = (pend2 >= 0) & deliver[:, None] & vis_par
        pend3 = jnp.where(unl, -1, pend2)

        # ---- activation --------------------------------------------
        m = jax.random.categorical(k_mine, logw).astype(i32)
        powh_new = jax.random.uniform(k_pow, dtype=jnp.float32)
        next_act2 = jnp.where(
            is_act,
            st["next_act"]
            + jax.random.exponential(k_next, dtype=ft) * activation_delay,
            st["next_act"])
        parent_act = st["pref"][m]
        h_parent = st["height"][parent_act]
        n_act2 = st["n_act"] + is_act.astype(i32)
        node_act2 = st["node_act"].at[
            jnp.where(is_act, m, N)].add(1)

        # ---- ethereum draft: uncle selection at mint ----------------
        if is_eth:
            # 6-generation chain window from the miner's preference
            # (ethereum.ml chain_window): ancs[j] = (j+1)'th chain
            # ancestor of the tip, -1 past genesis
            tip = parent_act
            ancs = []
            cur = tip
            for _ in range(6):
                cur = jnp.where(cur > 0,
                                st["parent0"][jnp.clip(cur, 0)], -1)
                ancs.append(cur)
            anc = jnp.stack(ancs)                    # (6,)
            # in-chain set = tip + every window block's parents (chain
            # parent + its uncle list); candidates must avoid it
            winb = jnp.stack([tip] + ancs[:5])       # the 6 window blocks
            in_chain = jnp.concatenate([
                jnp.stack([tip] + ancs),
                st["uncles"][jnp.clip(winb, 0)].reshape(-1)])
            # candidate scan over a ledger window from the deepest
            # ancestor (uncles are minted after their chain parent, so
            # every candidate id exceeds it); a window that cannot see
            # the whole [deepest, nb) range is a potential silent miss
            # — counted, asserted 0 by parity
            e_start = jnp.clip(
                jnp.minimum(jnp.min(jnp.where(anc >= 0, anc, B)),
                            st["nb"]), 0, max(B - W, 0))
            sl_epar = lax.dynamic_slice(st["parent0"], (e_start,), (W,))
            sl_ekey = lax.dynamic_slice(
                st["height"] if byz else st["work"], (e_start,), (W,))
            sl_emn = lax.dynamic_slice(st["miner"], (e_start,), (W,))
            sl_evs = lax.dynamic_slice(st["vis"][m], (e_start,), (W,))
            egidx = e_start + jnp.arange(W, dtype=i32)
            par_in_anc = jnp.any((sl_epar[None, :] == anc[:, None])
                                 & (anc[:, None] >= 0), axis=0)
            not_chain = jnp.all(egidx[None, :] != in_chain[:, None],
                                axis=0)
            ecand = sl_evs & par_in_anc & not_chain & (egidx < st["nb"])
            # sort: own uncles first, then older (lower pref key) first
            skey = jnp.where(
                ecand,
                jnp.where(sl_emn == m, 0.0, 1e6)
                + sl_ekey.astype(jnp.float32), 1e9)
            e_ord = jnp.argsort(skey)
            n_cand = jnp.sum(ecand).astype(i32)
            n_unc = jnp.minimum(n_cand, U)
            iu = jnp.arange(U, dtype=i32)
            uncle_row = jnp.where(
                iu < n_unc,
                e_start + e_ord[jnp.clip(iu, 0, W - 1)], -1).astype(i32)
            # byzantium's cap of 2 is the protocol rule; the whitepaper
            # preset is unbounded, so dropping past U is a miss
            win_miss2 = st["win_miss"] + (is_act & (
                (st["nb"] > e_start + W)
                | ((not byz) & (n_cand > U)))).astype(i32)
            a_work = st["work"][tip] + 1 + n_unc

        # ---- spar draft: block iff k-1 confirming votes visible -----
        if is_spar:
            pj = parent_act
            can_block = st["conf"][m, pj] >= (k - 1)
            s_start = jnp.clip(pj + 1, 0, max(B - W, 0))
            sp_par = lax.dynamic_slice(st["parent0"], (s_start,), (W,))
            sp_iv = lax.dynamic_slice(st["is_vote"], (s_start,), (W,))
            sp_mn = lax.dynamic_slice(st["miner"], (s_start,), (W,))
            sp_vs = lax.dynamic_slice(st["vis"][m], (s_start,), (W,))
            onpar = (sp_par == pj) & sp_iv & sp_vs
            mine = onpar & (sp_mn == m)
            theirs = onpar & (sp_mn != m)
            n_mine = jnp.sum(mine).astype(i32)
            n_their = jnp.sum(theirs).astype(i32)
            cnt_ok = ((n_mine == st["conf_own"][m, pj])
                      & (n_their == st["conf"][m, pj]
                         - st["conf_own"][m, pj]))
            win_miss2 = st["win_miss"] + (
                is_act & can_block & ~cnt_ok).astype(i32)
            # quorum = k-1 confirming votes, own first then others',
            # each group in append (= mint-time) order — the stable
            # sort of spar.ml:205-213 (mint times are unique, so
            # append order IS time order)
            kq = k - 1
            take_mine = jnp.minimum(n_mine, kq)
            need = jnp.clip(kq - n_mine, 0, kq)
            mrank = jnp.cumsum(mine.astype(i32))
            r2m = jnp.zeros((W + 1,), i32).at[
                jnp.where(mine & (mrank <= kq), mrank, 0)].set(
                jnp.arange(W, dtype=i32))
            trank = jnp.cumsum(theirs.astype(i32))
            r2t = jnp.zeros((W + 1,), i32).at[
                jnp.where(theirs & (trank <= need), trank, 0)].set(
                jnp.arange(W, dtype=i32))
            iq = jnp.arange(KQ, dtype=i32)
            own_part = s_start + r2m[jnp.clip(iq + 1, 0, W)]
            their_part = s_start + r2t[
                jnp.clip(iq - take_mine + 1, 0, W)]
            q_row = jnp.where(iq < take_mine, own_part, their_part)
            q_row = jnp.where(iq < kq, q_row, -1).astype(i32)

        # ---- bk proposal (one proposer per step, no time advance) ---
        if is_bk:
            jstar = jnp.argmax(want).astype(i32)
            pjs = st["pref"][jstar]
            start = jnp.clip(pjs + 1, 0, max(B - W, 0))
            sl_par = lax.dynamic_slice(st["parent0"], (start,), (W,))
            sl_iv = lax.dynamic_slice(st["is_vote"], (start,), (W,))
            sl_ph = lax.dynamic_slice(st["powh"], (start,), (W,))
            sl_mn = lax.dynamic_slice(st["miner"], (start,), (W,))
            sl_vs = lax.dynamic_slice(st["vis"][jstar], (start,), (W,))
            onpar = (sl_par == pjs) & sl_iv & sl_vs
            mine = onpar & (sl_mn == jstar)
            theirs = onpar & (sl_mn != jstar)
            mb = st["mybest"][jstar, pjs]
            cand = theirs & (sl_ph > mb)
            n_mine = jnp.sum(mine).astype(i32)
            n_cand = jnp.sum(cand).astype(i32)
            feasible = (n_mine >= k) | (n_mine + n_cand >= k)
            # the incremental tallies are exact; a window that no
            # longer sees every counted vote is a silent corruption —
            # count it instead (parity asserts 0)
            cnt_ok = ((n_mine == st["conf_own"][jstar, pjs])
                      & (jnp.sum(theirs).astype(i32)
                         == st["conf"][jstar, pjs]
                         - st["conf_own"][jstar, pjs]))
            win_miss2 = st["win_miss"] + (
                is_prop & ~cnt_ok).astype(i32)
            ok_prop = is_prop & feasible & (st["nb"] < B)
            fail = is_prop & ~(feasible & (st["nb"] < B))
            noprop3 = noprop2.at[jstar, pjs].max(fail)
            # quorum selection: k smallest own hashes, padded with
            # candidate votes in append (= ledger index) order
            mine_ord = jnp.argsort(
                jnp.where(mine, sl_ph, jnp.float32(3.0)))
            take_mine = jnp.minimum(n_mine, k)
            need = jnp.clip(k - n_mine, 0, k)
            crank = jnp.cumsum(cand.astype(i32))
            r2i = jnp.zeros((W + 1,), i32).at[
                jnp.where(cand & (crank <= need), crank, 0)].set(
                jnp.arange(W, dtype=i32))
            i_arr = jnp.arange(k, dtype=i32)
            own_part = start + mine_ord[jnp.clip(i_arr, 0, W - 1)]
            their_part = start + r2i[
                jnp.clip(i_arr - take_mine + 1, 0, W)]
            q_row = jnp.where(i_arr < take_mine, own_part, their_part
                              ).astype(i32)
            quorum2 = st["quorum"].at[
                jnp.where(ok_prop, st["nb"], B)].set(q_row)
        else:
            ok_prop = jnp.asarray(False, bool)

        # ---- merged ledger append (activation or proposal) ----------
        ok_act = is_act & (st["nb"] < B)
        app = ok_act | ok_prop
        drop_b2 = st["drop_b"] + (
            (is_act | ok_prop) & (st["nb"] >= B)).astype(i32)
        if is_bk:
            a_parent = jnp.where(is_act, parent_act, pjs)
            a_height = jnp.where(is_act, h_parent,
                                 st["height"][pjs] + 1)
            a_miner = jnp.where(is_act, m, jstar)
            a_powh = jnp.where(is_act, powh_new, jnp.float32(2.0))
            a_lhash = jnp.where(is_act, jnp.float32(2.0), mb)
        elif is_spar:
            a_parent = parent_act
            # a vote sits at its confirmed block's height; a block one up
            a_height = h_parent + can_block.astype(i32)
            a_miner = m
            a_powh = powh_new
        else:
            a_parent = parent_act
            a_height = h_parent + 1
            a_miner = m
            a_powh = powh_new
        idxs = jnp.where(app, st["nb"], B)    # OOB scatters drop
        parent3 = st["parent0"].at[idxs].set(a_parent)
        height3 = st["height"].at[idxs].set(a_height)
        miner3 = st["miner"].at[idxs].set(a_miner)
        powh3 = st["powh"].at[idxs].set(a_powh)
        nb2 = st["nb"] + app.astype(i32)

        src = jnp.where(is_act, m, (jstar if is_bk else m))
        vis3 = vis2.at[src, idxs].set(True)
        known3 = known2.at[src, idxs].set(True)
        vis_at3 = vis_at2.at[src, idxs].min(now2)

        if is_bk:
            isv3 = st["is_vote"].at[idxs].set(is_act)
            lhash3 = st["lhash"].at[idxs].set(a_lhash)
            # vote mint: own tallies + best-own-hash on the parent
            vidx = jnp.where(ok_act, parent_act, B)
            conf3 = conf2.at[m, vidx].add(1)
            conf_own2 = st["conf_own"].at[m, vidx].add(1)
            mybest2 = st["mybest"].at[m, vidx].min(
                jnp.where(ok_act, powh_new, jnp.float32(3.0)))
            noprop4 = noprop3.at[m, vidx].min(False)
            # proposal: bump own replacement floor, prefer the child
            pidx = jnp.where(ok_prop, pjs, B)
            repl3 = repl2.at[jstar, pidx].min(mb)
            pref3 = pref2.at[jnp.where(ok_prop, jstar, N)].set(
                st["nb"])
        elif is_eth:
            work3 = st["work"].at[idxs].set(
                jnp.where(is_act, a_work, 0))
            uncles3 = st["uncles"].at[
                jnp.where(ok_act, st["nb"], B)].set(uncle_row)
            pref3 = pref2.at[jnp.where(ok_act, m, N)].set(st["nb"])
        elif is_spar:
            isv3 = st["is_vote"].at[idxs].set(is_act & ~can_block)
            # vote mint: own confirming tallies on the parent block
            vidx = jnp.where(ok_act & ~can_block, parent_act, B)
            conf3 = conf2.at[m, vidx].add(1)
            conf_own2 = st["conf_own"].at[m, vidx].add(1)
            quorum2 = st["quorum"].at[
                jnp.where(ok_act & can_block, st["nb"], B)].set(q_row)
            # a freshly mined block advances the miner's preference; a
            # vote leaves it on the same chain block
            pref3 = pref2.at[
                jnp.where(ok_act & can_block, m, N)].set(st["nb"])
        else:
            pref3 = pref2.at[jnp.where(ok_act, m, N)].set(st["nb"])

        # ---- push: unlock re-queues + link sends of one block -------
        delays = sample_delay_matrix(k_delay, kindm, p0m, p1m, ft)
        if cn.flooding:
            flood_src = deliver & (st["miner"][b] != arangeN)
        else:
            flood_src = jnp.zeros((N,), bool)
        send_src = jnp.where(is_recv, flood_src, (arangeN == src) & app)
        s_valid = send_src[:, None] & has_link
        s_time = now2 + delays
        s_blk = jnp.where(is_recv, b, st["nb"])

        c_valid = jnp.concatenate([unl.reshape(-1),
                                   s_valid.reshape(-1)])
        c_time = jnp.concatenate([jnp.full((N * F,), 1.0, ft) * now2,
                                  s_time.reshape(-1)])
        c_dst = jnp.concatenate([jnp.repeat(arangeN, F),
                                 jnp.tile(arangeN, N)])
        c_blk = jnp.concatenate([jnp.clip(pend2.reshape(-1), 0),
                                 jnp.full((N * N,), 1, i32) * s_blk])

        free = ~jnp.isfinite(q_time_pop)
        rank = jnp.cumsum(c_valid.astype(i32))
        n_valid = rank[-1]
        frank = jnp.cumsum(free.astype(i32))
        n_free = frank[-1]
        n_place = jnp.minimum(n_valid, n_free)
        placed = c_valid & (rank <= n_place)
        r2c = jnp.zeros((max(C, M) + 1,), i32).at[
            jnp.where(placed, rank, 0)].set(jnp.arange(C, dtype=i32))
        fill = free & (frank <= n_place)
        cidx = r2c[jnp.clip(frank, 0, C)]
        q_time2 = jnp.where(fill, c_time[cidx], q_time_pop)
        q_dst2 = jnp.where(fill, c_dst[cidx], st["q_dst"])
        q_blk2 = jnp.where(fill, c_blk[cidx], st["q_blk"])
        q_seq2 = jnp.where(fill, st["seq"] + frank, st["q_seq"])
        seq2 = st["seq"] + n_valid
        drop_q2 = st["drop_q"] + (n_valid - n_place)

        new = dict(
            key=key, now=now2, next_act=next_act2, n_act=n_act2,
            nb=nb2, seq=seq2, steps=st["steps"] + 1,
            parent0=parent3, height=height3, miner=miner3, powh=powh3,
            pref=pref3, vis=vis3, vis_at=vis_at3, known=known3,
            node_act=node_act2, q_time=q_time2, q_dst=q_dst2,
            q_blk=q_blk2, q_seq=q_seq2, pend=pend3,
            drop_q=drop_q2, drop_p=drop_p2, drop_b=drop_b2,
        )
        if is_bk:
            new.update(is_vote=isv3, lhash=lhash3, conf=conf3,
                       conf_own=conf_own2, mybest=mybest2, repl=repl3,
                       noprop=noprop4, quorum=quorum2,
                       win_miss=win_miss2)
            want2 = jnp.any(bk_want(pref3, conf3, conf_own2, mybest2,
                                    repl3, noprop4))
        else:
            if is_eth:
                new.update(work=work3, uncles=uncles3,
                           win_miss=win_miss2)
            if is_spar:
                new.update(is_vote=isv3, conf=conf3,
                           conf_own=conf_own2, quorum=quorum2,
                           win_miss=win_miss2)
            want2 = jnp.asarray(False, bool)
        tmin2 = jnp.min(q_time2)
        new["live"] = (want2 | (n_act2 < A)
                       | ((tmin2 < next_act2) & jnp.isfinite(tmin2)))
        return new

    def finalize(st):
        height = st["height"]
        pref = st["pref"]
        hp = height[pref]
        if is_bk or is_spar:
            # bk votes' parent0 is the block they extend; spar votes'
            # parent0 IS the block they confirm — either way the
            # per-block vote tally is one scatter over parent0
            votes = jnp.zeros((B,), i32).at[
                jnp.clip(st["parent0"], 0)].add(
                st["is_vote"].astype(i32))
            score = hp.astype(ft) * (A + 1.0) + votes[pref].astype(ft)
        elif is_eth:
            ekey = st["height"] if byz else st["work"]
            score = ekey[pref].astype(ft)
        else:
            score = hp.astype(ft)
        head = pref[jnp.argmax(score)]
        head_height = height[head]
        if is_bk:
            progress = head_height * k
            on_chain = head_height * (k + 1)
            walk_len = A // max(k, 1) + 3
        elif is_spar:
            # k PoWs (k-1 votes + the block) per confirmed height
            progress = head_height * k
            on_chain = head_height * k
            walk_len = A // max(k, 1) + 3
        elif is_eth:
            # whitepaper progresses by height, byzantium by work;
            # on_chain (block + its uncles) accumulates in the walk
            progress = st["work"][head] if byz else head_height
            on_chain = head_height          # placeholder, see below
            walk_len = A + 2
        else:
            progress = head_height
            on_chain = head_height
            walk_len = A + 2

        def rstep(carry, _):
            cur, rew, onc = carry
            ok = cur > 0
            cc = jnp.clip(cur, 0)
            if is_bk:
                if scheme == "block":
                    rew = rew.at[jnp.where(ok, st["miner"][cc], N)
                                 ].add(jnp.float32(k))
                else:
                    qr = st["quorum"][cc]
                    vm = st["miner"][jnp.clip(qr, 0)]
                    rew = rew.at[jnp.where(ok & (qr >= 0), vm, N)
                                 ].add(1.0)
            elif is_spar:
                if scheme == "block":
                    rew = rew.at[jnp.where(ok, st["miner"][cc], N)
                                 ].add(jnp.float32(k))
                else:
                    # constant: the block's miner and each quorum
                    # vote's miner get 1 (spar.ml rewards)
                    rew = rew.at[jnp.where(ok, st["miner"][cc], N)
                                 ].add(1.0)
                    qr = st["quorum"][cc]
                    vm = st["miner"][jnp.clip(qr, 0)]
                    rew = rew.at[jnp.where(ok & (qr >= 0), vm, N)
                                 ].add(1.0)
            elif is_eth:
                urow = st["uncles"][cc]
                nu = jnp.sum(urow >= 0).astype(i32)
                rew = rew.at[jnp.where(ok, st["miner"][cc], N)].add(
                    1.0 + nu.astype(jnp.float32) * 0.03125)
                um = st["miner"][jnp.clip(urow, 0)]
                if byz:
                    uh = st["height"][jnp.clip(urow, 0)]
                    amt = (8.0 - (st["height"][cc] - uh)
                           .astype(jnp.float32)) / 8.0
                else:
                    amt = jnp.full((U,), 0.9375, jnp.float32)
                rew = rew.at[jnp.where(ok & (urow >= 0), um, N)
                             ].add(amt)
                onc = onc + jnp.where(ok, 1 + nu, 0)
            else:
                rew = rew.at[jnp.where(ok, st["miner"][cc], N)
                             ].add(1.0)
            return (jnp.where(ok, st["parent0"][cc], 0), rew, onc), None

        (_, rewards, onc), _ = lax.scan(
            rstep, (head, jnp.zeros((N,), jnp.float32),
                    jnp.asarray(0, i32)), None,
            length=walk_len)
        if is_eth:
            on_chain = onc

        out = dict(
            head=head, head_height=head_height,
            progress=jnp.asarray(progress, ft),
            on_chain=jnp.asarray(on_chain, ft),
            sim_time=st["now"], n_blocks=st["nb"] - 1,
            n_act=st["n_act"], node_act=st["node_act"],
            reward=rewards, steps=st["steps"],
            drop_q=st["drop_q"], drop_p=st["drop_p"],
            drop_b=st["drop_b"],
            exhausted=st["live"] & (st["steps"] >= S),
        )
        out["win_miss"] = (st["win_miss"] if (is_bk or is_eth or is_spar)
                           else jnp.asarray(0, i32))
        return out

    def lane(key, activation_delay):
        st = init(key, activation_delay)
        st = jax.lax.while_loop(
            lambda s: s["live"] & (s["steps"] < S),
            partial(body, activation_delay=activation_delay), st)
        return finalize(st)

    return lane


def _scan_lane_fn(cn: CompiledNet, activations: int, L: int):
    """Fused nakamoto fast path for simple (non-flooding)
    dissemination: arrival times are state-independent (each block is
    sent exactly once per link at mint), so activation times, miners,
    and the whole (A, N) arrival matrix are presampled as dense
    vectorized draws, and the only sequential part — each miner's
    preference at its activation instant — runs as a `lax.scan` over
    activations.

    The scan carry stays O(L) scalars-and-ring — no O(A) or O(L*N)
    arrays — for two reasons: carried arrays with batched updates
    defeat XLA's in-place aliasing under vmap (each lane would copy
    every step), and an all-nodes (L, N) visibility fold per step is
    pure memory bandwidth that scales linearly with lanes.  Only the
    current miner's preference matters at each step, and that needs
    one (L,) arrival column: blocks older than the lookback window are
    guaranteed-arrived (else `win_miss`, asserted 0 by the parity
    tests), so their per-node best collapses to a running
    (hmax_old, first block id achieving it) scalar pair.  Ties among
    old blocks resolve by mint order — exact for equal-constant-delay
    grids (first minted arrives first everywhere), a measure-zero-ish
    documented approximation for random link delays.

    When every off-diagonal link is the same constant delay (the
    symmetric-clique grids), the column is computed from t/m slices
    with unbatched indices instead of gathers with batched indices —
    the only op class whose cost scales per-lane under vmap on
    XLA:CPU — which is what makes the batched sweep beat the serial
    oracle loop on wall-clock.  Rewards come from a reverse scan over
    mint order (parent ids strictly decrease along the chain), not a
    sequential forward walk."""
    import jax
    import jax.numpy as jnp
    import numpy as _np
    from jax import lax

    N = int(cn.n)
    A = int(activations)
    L = min(int(L), A)          # window cannot exceed the ledger
    ft = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    i32 = jnp.int32

    kindm = jnp.asarray(cn.kind, i32)
    p0m = jnp.asarray(cn.p0, ft)
    p1m = jnp.asarray(cn.p1, ft)
    logw = jnp.log(jnp.asarray(cn.compute, jnp.float32))
    arangeL = jnp.arange(L, dtype=i32)
    arangeN = jnp.arange(N, dtype=i32)

    # constant-equal-delay specialization: full off-diagonal
    # connectivity, all links constant with one shared value
    offdiag = ~_np.eye(N, dtype=bool)
    uniform_const = (bool(_np.all((cn.kind >= 0) == offdiag))
                     and bool(_np.all(cn.kind[offdiag] == 0))
                     and _np.unique(cn.p0[offdiag]).size == 1)
    D = float(cn.p0[0, 1]) if uniform_const else 0.0

    def lane(key, activation_delay):
        k_gap, k_mine, k_del = jax.random.split(key, 3)
        gaps = jax.random.exponential(k_gap, (A + 1,), dtype=ft)
        t = jnp.cumsum(gaps) * activation_delay  # (A+1,) mints + cutoff
        m = jax.random.categorical(k_mine, logw, shape=(A,)).astype(i32)
        if uniform_const:
            # arrivals are t_i + D off the miner's node; no RNG needed
            arr = t[:A, None] + jnp.where(
                arangeN[None, :] == m[:, None], 0.0, D)
        else:
            # per-block link delays from the miner's row of the
            # compiled planes; unlinked pairs never arrive (simple
            # dissemination: one send per link at mint, no relay)
            delays = sample_delay_matrix(
                k_del, kindm[m], p0m[m], p1m[m], ft)      # (A, N)
            linked = kindm[m] >= 0
            tm = t[:A, None]
            arr = jnp.where(linked, tm + delays, jnp.inf)
            arr = jnp.where(arangeN[None, :] == m[:, None], tm, arr)
        arr_flat = arr.reshape(A * N)
        BIG = 2.0 * t[A] + 4.0   # height dominates the (h, -arr) key

        def pref_key(h, a):
            """Lexicographic (height, earliest-arrival) as one f64 key;
            exact key ties fall back to the first window index = mint
            order, matching oracle delivery order for simultaneous
            arrivals."""
            return h.astype(ft) * BIG - a

        # ledger ids: genesis 0, activation i -> id i + 1
        def step(carry, i):
            ring_h, hmax_old, bidx_old, t_old, m_old = carry
            t_i = t[i]
            mi = m[i]
            start = jnp.maximum(i - L, 0)
            gidx = start + arangeL                   # activation index
            h_w = ring_h[gidx % L]
            if uniform_const:
                t_w = lax.dynamic_slice(t, (start,), (L,))
                m_w = lax.dynamic_slice(m, (start,), (L,))
                col = t_w + jnp.where(m_w == mi, 0.0, D)
                arr_old = t_old + jnp.where(m_old == mi, 0.0, D)
            else:
                col = arr_flat[gidx * N + mi]        # arrivals at miner
                arr_old = jnp.where(
                    bidx_old == 0, jnp.asarray(0.0, ft),
                    arr_flat[jnp.maximum(bidx_old - 1, 0) * N + mi])
            # the minting row itself has col == t_i (own arrival), and
            # future rows in a clamped early window have col > t_i, so
            # strict < is the whole visibility test
            key_w = jnp.where(col < t_i, pref_key(h_w, col), -jnp.inf)
            kw = jnp.max(key_w)
            # first-max selection without batched-index gathers
            atmax = key_w == kw
            sel_g = jnp.min(jnp.where(atmax, gidx, A))
            sel_h = jnp.sum(jnp.where(atmax & (gidx == sel_g), h_w, 0),
                            dtype=i32)
            use_old = pref_key(hmax_old, arr_old) >= kw
            parent = jnp.where(use_old, bidx_old, sel_g + 1)
            h_i = jnp.where(use_old, hmax_old, sel_h) + 1
            # the block aging out of the window (same ring slot we
            # overwrite) folds into the old-best scalars; the
            # must-have-landed check happens vectorized after the scan
            r = jnp.maximum(i - L, 0)
            h_leave = ring_h[i % L]
            upd_old = (i >= L) & (h_leave > hmax_old)
            hmax_old = jnp.where(upd_old, h_leave, hmax_old)
            bidx_old = jnp.where(upd_old, r + 1, bidx_old)
            t_old = jnp.where(upd_old, t[r], t_old)
            m_old = jnp.where(upd_old, m[r], m_old)
            ring_h = ring_h.at[i % L].set(h_i)
            return (ring_h, hmax_old, bidx_old, t_old, m_old), \
                (h_i, parent)

        carry0 = (jnp.zeros((L,), i32), jnp.asarray(0, i32),
                  jnp.asarray(0, i32), jnp.asarray(0.0, ft),
                  jnp.asarray(-1, i32))
        (ring_h, hmax_old, bidx_old, _, _), (hs, ps) = lax.scan(
            step, carry0, jnp.arange(A, dtype=i32), unroll=8)
        heights = jnp.concatenate([jnp.zeros((1,), i32), hs])
        # window-overflow detector, hoisted out of the loop: every
        # block must land everywhere (finite links) before it ages out
        # at its minting step + L
        if A > L:
            miss = jnp.sum(jnp.any(
                jnp.isfinite(arr[:A - L])
                & (arr[:A - L] > t[L:A, None]), axis=1)).astype(i32)
        else:
            miss = jnp.asarray(0, i32)

        # drain + winner: the oracle delivers what precedes the first
        # never-executed activation (t[A]); one full per-node fold at
        # the cutoff (window blocks vs the old-best representative)
        start = max(A - L, 0)
        gidx = start + arangeL
        arr_w = arr[start:start + L]                    # (L, N)
        h_w = ring_h[gidx % L]
        key_w = jnp.where(arr_w < t[A],
                          pref_key(h_w[:, None], arr_w), -jnp.inf)
        kw = jnp.max(key_w, axis=0)                     # (N,)
        atmax = key_w == kw[None, :]
        sel_g = jnp.min(jnp.where(atmax, gidx[:, None], A), axis=0)
        sel_h = jnp.sum(jnp.where(atmax & (gidx[:, None] == sel_g),
                                  h_w[:, None], 0), axis=0, dtype=i32)
        arr_old = jnp.where(bidx_old == 0, jnp.asarray(0.0, ft),
                            arr[jnp.maximum(bidx_old - 1, 0)])
        use_old = pref_key(hmax_old, arr_old) >= kw
        bh = jnp.where(use_old, hmax_old, sel_h)
        bidx = jnp.where(use_old, bidx_old, sel_g + 1)

        j_star = jnp.argmax(bh)                         # first-max
        head = bidx[j_star]
        head_height = jnp.max(bh)
        # on-chain mask by reverse scan over mint order: parent ids
        # strictly decrease along the chain, so walking ids A..1 with a
        # single moving pointer marks exactly the head chain
        ids = jnp.arange(1, A + 1, dtype=i32)

        def walk(cur, x):
            idx, par = x
            hit = idx == cur
            return jnp.where(hit, par, cur), hit

        _, on_chain = lax.scan(walk, head, (ids, ps), reverse=True)
        rewards = jnp.zeros((N + 1,), jnp.float32).at[
            jnp.where(on_chain, m, N)].add(1.0)[:N]
        node_act = jnp.zeros((N + 1,), i32).at[m].add(1)[:N]
        finite_arr = jnp.where(jnp.isfinite(arr) & (arr < t[A]),
                               arr, -jnp.inf)
        sim_time = jnp.maximum(t[A - 1], jnp.max(finite_arr))

        z = jnp.asarray(0, i32)
        return dict(
            head=head, head_height=head_height,
            progress=head_height.astype(ft),
            on_chain=head_height.astype(ft),
            sim_time=sim_time, n_blocks=jnp.asarray(A, i32),
            n_act=jnp.asarray(A, i32), node_act=node_act,
            reward=rewards, steps=jnp.asarray(A, i32),
            drop_q=z, drop_p=z, drop_b=z, win_miss=miss,
            exhausted=jnp.asarray(False, bool),
        )

    return lane


class Engine:
    """One compiled netsim program: fixed topology, protocol, and
    activation target; `run()` executes a batch of lanes (independent
    seed/activation-delay pairs) as a single jitted, vmapped call.

        eng = Engine(net, protocol="nakamoto", activations=10_000)
        out = eng.run(seeds=[0, 1, 2], activation_delays=[60.0] * 3)

    Returns numpy arrays keyed like the oracle metrics (progress,
    on_chain, sim_time, n_blocks, head_height, reward, node_act, ...)
    with a leading lane axis, plus capacity-overflow counters
    (drop_q/drop_p/drop_b/win_miss) and the `exhausted` step-cap flag
    — parity tests assert all of those are zero.
    """

    def __init__(self, net, *, protocol: str = "nakamoto", k: int = 1,
                 scheme: str = "constant", activations: int,
                 block_cap: int | None = None,
                 queue_cap: int | None = None, pend_cap: int = 8,
                 window: int | None = None, uncle_cap: int | None = None,
                 max_steps: int | None = None, x64: bool = True,
                 mode: str = "auto", lookback: int = 32,
                 mesh=None, mesh_axis: str = "d"):
        if protocol not in SUPPORTED_PROTOCOLS:
            raise ValueError(
                f"netsim supports protocols {SUPPORTED_PROTOCOLS}, "
                f"not '{protocol}'")
        scheme = scheme or "constant"
        if protocol in ("bk", "spar") and (k < 1
                                           or scheme not in _SCHEMES):
            raise ValueError(
                f"{protocol} needs k >= 1 and scheme in {_SCHEMES} "
                f"(got k={k}, scheme='{scheme}')")
        self.net = (net if isinstance(net, CompiledNet)
                    else compile_network(net))
        self.protocol = protocol
        self.k = int(k)
        self.scheme = scheme
        self.activations = int(activations)
        n, a = self.net.n, self.activations
        if protocol == "bk":
            # per chain height up to min(N, k) nodes hold own votes
            # and may each propose (plus replacements) before the
            # winner propagates — votes + that burst bounds the ledger
            self.B = block_cap or (
                a + min(n, self.k) * (a // max(self.k, 1) + 2) + 64)
        else:
            # nakamoto / ethereum / spar: every activation appends
            # exactly one PoW item (spar votes included)
            self.B = block_cap or a + 2
        # ethereum uncle capacity: byzantium's protocol cap of 2 is
        # exact; the whitepaper preset is unbounded, so a fixed budget
        # applies and overflow counts into win_miss
        self.U = int(uncle_cap or (2 if protocol == "ethereum-byzantium"
                                   else 8))
        self.M = queue_cap or max(256, 16 * n)
        self.F = int(pend_cap)
        self.W = min(self.B, window or max(256, 32 * (self.k + n)))
        self.S = max_steps or a * (n + 4) + 4096
        self.x64 = bool(x64)
        if mode not in ("auto", "event", "scan"):
            raise ValueError(f"mode must be auto/event/scan, not '{mode}'")
        scan_ok = protocol == "nakamoto" and not self.net.flooding
        if mode == "scan" and not scan_ok:
            raise ValueError(
                "scan mode needs nakamoto + simple dissemination "
                "(state-independent arrival times); use mode='event'")
        self.mode = "scan" if (mode == "auto" and scan_ok) or \
            mode == "scan" else "event"
        self.lookback = int(lookback)
        # mesh: shard the vmapped lane batch over a 1-D device mesh
        # (keys/delays/outputs all lane-major, so one NamedSharding
        # prefix partitions the whole program; lane counts must divide
        # the axis — docs/SCALING.md)
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.n_devices = (int(mesh.shape[mesh_axis])
                          if mesh is not None else 1)
        self._exe = {}          # lane count -> compiled executable

    def _ctx(self):
        import contextlib

        from jax.experimental import enable_x64

        return enable_x64() if self.x64 else contextlib.nullcontext()

    def _compiled(self, keys, delays):
        import jax

        L = keys.shape[0]
        exe = self._exe.get(L)
        if exe is None:
            if self.mode == "scan":
                fn = _scan_lane_fn(self.net, self.activations,
                                   self.lookback)
            else:
                fn = _lane_fn(self.net, self.protocol, self.k,
                              self.scheme, self.activations, self.B,
                              self.M, self.F, self.W, self.S, self.U)
            jitted = jax.jit(jax.vmap(fn))
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                from cpr_tpu.parallel.lanes import check_even_shards
                check_even_shards(L, self.mesh, axis=self.mesh_axis,
                                  what="netsim lanes")
                lane = NamedSharding(self.mesh,
                                     PartitionSpec(self.mesh_axis))
                jitted = jax.jit(jax.vmap(fn),
                                 in_shardings=(lane, lane),
                                 out_shardings=lane)
            tele = telemetry.current()
            with telemetry.compile_watch(), \
                    tele.span("netsim:compile", lanes=L):
                exe = jitted.lower(keys, delays).compile()
            self._exe[L] = exe
        return exe

    def run(self, seeds, activation_delays) -> dict:
        """Execute len(seeds) lanes (paired with activation_delays) as
        one device program; returns numpy arrays with lane axis 0."""
        import jax
        import jax.numpy as jnp

        seeds = list(seeds)
        delays = list(activation_delays)
        if len(seeds) != len(delays):
            raise ValueError("seeds and activation_delays must pair up")
        L = len(seeds)
        tele = telemetry.current()
        with self._ctx():
            keys = jnp.stack(
                [jax.random.PRNGKey(s) for s in seeds])
            dl = jnp.asarray(delays,
                             jnp.float64 if self.x64 else jnp.float32)
            if self.mesh is not None:
                # commit inputs to the compiled program's lane
                # sharding (an AOT executable does not auto-place
                # uncommitted host arrays the way jit does); refuse
                # uneven batches BEFORE device_put, with both values
                # named, instead of XLA's opaque sharding error
                from jax.sharding import NamedSharding, PartitionSpec

                from cpr_tpu.parallel.lanes import check_even_shards
                check_even_shards(L, self.mesh, axis=self.mesh_axis,
                                  what="netsim lanes")
                lane = NamedSharding(self.mesh,
                                     PartitionSpec(self.mesh_axis))
                keys = jax.device_put(keys, lane)
                dl = jax.device_put(dl, lane)
            exe = self._compiled(keys, dl)
            with tele.span("netsim:run", lanes=L,
                           activations=L * self.activations) as sp:
                out = sp.fence(exe(keys, dl))
        out = {kk: np.asarray(v) for kk, v in out.items()}
        tele.event("netsim", protocol=self.protocol, lanes=L,
                   activations=int(np.sum(out["n_act"])),
                   steps=int(np.max(out["steps"])),
                   drops=int(out["drop_q"].sum() + out["drop_p"].sum()
                             + out["drop_b"].sum()
                             + out["win_miss"].sum()))
        self._emit_device_metrics(out)
        return out

    def _emit_device_metrics(self, out):
        """Optional in-graph-style cells (CPR_DEVICE_METRICS=1)."""
        from cpr_tpu import device_metrics as dm

        if not dm.enabled():
            return
        spec = (dm.MetricsSpec().counter("steps").counter("queue_drops")
                .counter("pending_drops").counter("ledger_drops"))
        acc = spec.init()
        acc = spec.count(acc, "steps", out["steps"])
        acc = spec.count(acc, "queue_drops", out["drop_q"])
        acc = spec.count(acc, "pending_drops", out["drop_p"])
        acc = spec.count(acc, "ledger_drops", out["drop_b"])
        dm.emit("netsim", spec, acc, protocol=self.protocol)


def grid(seeds, activation_delays):
    """Cartesian (delay-major) lane grid: returns (seed_list,
    delay_list) ready for `Engine.run`."""
    ss, dd = [], []
    for d in activation_delays:
        for s in seeds:
            ss.append(int(s))
            dd.append(float(d))
    return ss, dd
