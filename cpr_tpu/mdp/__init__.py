"""MDP attack-search toolbox.

Reference counterpart: mdp/lib/ (implicit model interface, exhaustive
compiler, explicit MDP + solvers, RTDP, policy-guided exploration).

TPU re-design: the compiler emits flat transition arrays (COO triples +
per-(state,action) segments) instead of nested Python lists, and the
solvers (value iteration, policy evaluation) are jitted segment-sum sweeps
that run on TPU — optionally sharded over a device mesh
(`cpr_tpu.parallel`). Host-side pieces (BFS exploration, steady-state
sparse solves) stay on CPU like the reference.
"""

from cpr_tpu.mdp.implicit import Effect, Model, PTOWrapper, Transition  # noqa: F401
from cpr_tpu.mdp.compiler import Compiler  # noqa: F401
from cpr_tpu.mdp.explicit import (  # noqa: F401
    MDP,
    PaddedLayoutTooLarge,
    TensorMDP,
    ptmdp,
)
from cpr_tpu.mdp.frontier import FrontierCompiler  # noqa: F401
from cpr_tpu.mdp.explorer import Explorer  # noqa: F401
from cpr_tpu.mdp.grid import (  # noqa: F401
    Param,
    ParamError,
    ParamMDP,
    check_revalue_parity,
    compile_protocol,
    grid_value_iteration,
    param_pair,
    param_ptmdp,
    parametric_compile,
    parametric_compile_native,
    solve_grid_cached,
)
from cpr_tpu.mdp.rtdp import RTDP  # noqa: F401
from cpr_tpu.mdp.rtdp_graph import rtdp_graph, rtdp_sharded_polish  # noqa: F401
from cpr_tpu.mdp import generic  # noqa: F401
