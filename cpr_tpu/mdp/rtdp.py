"""Real-Time Dynamic Programming over implicit models.

Reference counterpart: mdp/lib/rtdp.py:27-458 — trajectory-sampled
asynchronous value iteration with eps-greedy + eps-honest exploration and
Barto/Sutton "exploring starts" drawn from a recent-state buffer.

Split of labor in this framework: the trajectory walk is inherently
sequential host work and stays in Python, but per-state bookkeeping lives
in growable numpy arrays and each state's outgoing transitions are cached
as flat (prob, dst, reward, progress) arrays, so a Bellman backup is two
gathers and a dot product instead of the reference's nested Python loops
— and `mdp()` hands the partially-explored table straight to the jitted
TPU value iteration (cpr_tpu.mdp.explicit) for final polishing, the same
way the compiler output does.

States are hashable values here (no explicit fingerprint plumbing like
the reference's state_hash_fn, rtdp.py:36-50); pass `state_key_fn` only
if full states are too large to keep as dict keys.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

import numpy as np

from cpr_tpu.mdp.explicit import MDP
from cpr_tpu.mdp.implicit import Model


@dataclass
class _ActionTable:
    """Cached outgoing transitions of one state: one row per action."""

    probs: list = field(default_factory=list)  # list[np.ndarray]
    dsts: list = field(default_factory=list)
    rews: list = field(default_factory=list)
    prgs: list = field(default_factory=list)
    honest: int = -1


class RTDP:
    """All exploration randomness flows through ONE explicit stream:
    `seed` builds a private `random.Random(seed)` (never the module
    global, so two RTDP instances — or RTDP and anything else using
    `random` — cannot perturb each other), or pass `rng` to share /
    control the stream directly (any object with the random.Random
    surface: random(), randrange(), choice(), choices()).  Same seed
    or same-state rng -> bit-identical runs; this is the deterministic
    host oracle the in-graph port (cpr_tpu/mdp/rtdp_graph.py) is
    value-checked against."""

    def __init__(self, model: Model, *, eps: float, eps_honest: float = 0.0,
                 es: float = 0.0, es_threshold: int = 500_000,
                 state_key_fn=None, seed: int = 0, rng=None):
        assert 0.0 <= eps <= 1.0 and 0.0 <= eps_honest <= 1.0
        assert eps + eps_honest <= 1.0 and 0.0 <= es <= 1.0
        self.model = model
        self.eps = eps
        self.eps_honest = eps_honest
        self.es = es
        self.es_threshold = es_threshold
        self._keep_full = state_key_fn is None
        self.key_of = state_key_fn or (lambda s: s)
        self.rng = rng if rng is not None else random.Random(seed)

        self._idx: dict = {}  # state key -> int id
        self._full: dict = {}  # int id -> full state (kept while needed)
        self._tables: dict[int, _ActionTable] = {}  # explored states only
        cap = 1024
        self.value = np.zeros(cap, np.float64)
        self.progress = np.zeros(cap, np.float64)
        self.count = np.zeros(cap, np.int64)

        self.es_buf: dict[int, tuple] = {}  # id -> (full state, last seen)
        self.i = 0
        self.n_episodes = 0
        self.episode_progress = 0.0
        self.progress_ewma = 0.0

        self.start_ids = []
        self.start_probs = []
        for s, p in model.start():
            self.start_ids.append(self._id_of(s))
            self.start_probs.append(p)
        self._start_new_episode()

    # -- state table -----------------------------------------------------

    def _id_of(self, full_state) -> int:
        key = self.key_of(full_state)
        sid = self._idx.get(key)
        if sid is None:
            sid = len(self._idx)
            self._idx[key] = sid
            if sid >= self.value.shape[0]:
                for name in ("value", "progress", "count"):
                    arr = getattr(self, name)
                    grown = np.zeros(arr.shape[0] * 2, arr.dtype)
                    grown[: arr.shape[0]] = arr
                    setattr(self, name, grown)
            if self._keep_full or not hasattr(self, "cur_id"):
                # with a key fn, full states are discarded after init
                # (start states stay; trajectories re-derive on demand)
                self._full[sid] = full_state
            v, p = self._initial_estimate(full_state)
            self.value[sid] = v
            self.progress[sid] = p
        return sid

    def _initial_estimate(self, full_state):
        """Optimistic-ish guidance: value of a fair shutdown from here
        (rtdp.py:281-306)."""
        v = p = 0.0
        for t in self.model.shutdown(full_state):
            key = self.key_of(t.state)
            sid = self._idx.get(key)
            fv = self.value[sid] if sid is not None else 0.0
            fp = self.progress[sid] if sid is not None else 0.0
            v += t.probability * (t.reward + fv)
            p += t.probability * (t.progress + fp)
        return v, p

    def _table_of(self, sid: int, full_state) -> _ActionTable:
        tab = self._tables.get(sid)
        if tab is not None:
            return tab
        tab = _ActionTable()
        actions = self.model.actions(full_state)
        for a in actions:
            ts = [t for t in self.model.apply(a, full_state)
                  if t.probability > 0.0]
            tab.probs.append(np.array([t.probability for t in ts]))
            tab.dsts.append(np.array([self._id_of(t.state) for t in ts]))
            tab.rews.append(np.array([t.reward for t in ts]))
            tab.prgs.append(np.array([t.progress for t in ts]))
        if actions:
            tab.honest = actions.index(self.model.honest(full_state))
        self._tables[sid] = tab
        return tab

    # -- episode control -------------------------------------------------

    def _start_new_episode(self):
        self.episode_progress = 0.0
        if self.es > 0.0 and self.rng.random() < self.es and self.es_buf:
            expired = [sid for sid, (_, seen) in self.es_buf.items()
                       if self.i - seen >= self.es_threshold]
            for sid in expired:
                del self.es_buf[sid]
            if self.es_buf:
                sid = self.rng.choice(list(self.es_buf))
                self.cur_id, self.cur_state = sid, self.es_buf[sid][0]
                return
        r = self.rng.random() * sum(self.start_probs)
        acc = 0.0
        for sid, p in zip(self.start_ids, self.start_probs):
            acc += p
            if r <= acc:
                break
        self.cur_id, self.cur_state = sid, self._full[sid]

    def _reset(self):
        self.n_episodes += 1
        self.progress_ewma = (self.progress_ewma * 0.999
                              + 0.001 * self.episode_progress)
        self._start_new_episode()

    # -- the loop --------------------------------------------------------

    def step(self):
        self.i += 1
        sid, full = self.cur_id, self.cur_state
        self.count[sid] += 1
        tab = self._table_of(sid, full)
        n = len(tab.probs)
        if n == 0:  # terminal
            self._reset()
            return

        best_a, best_q, best_p = 0, 0.0, 0.0
        for a in range(n):
            q = float(tab.probs[a] @ (tab.rews[a] + self.value[tab.dsts[a]]))
            if q > best_q or a == 0:
                best_a, best_q = a, q
                best_p = float(tab.probs[a]
                               @ (tab.prgs[a] + self.progress[tab.dsts[a]]))
        self.value[sid] = best_q
        self.progress[sid] = best_p

        x = self.rng.random()
        greedy = False
        if x < self.eps:
            a = self.rng.randrange(n)
        elif x < self.eps + self.eps_honest:
            a = tab.honest
        else:
            a, greedy = best_a, True

        j = self.rng.choices(range(len(tab.probs[a])),
                             weights=tab.probs[a])[0]
        dst = int(tab.dsts[a][j])
        self.episode_progress += float(tab.prgs[a][j])
        nxt_full = self._full.get(dst)
        if nxt_full is None:
            # re-derive the full state from the model transition
            action = self.model.actions(full)[a]
            for t in self.model.apply(action, full):
                if self._idx.get(self.key_of(t.state)) == dst:
                    nxt_full = t.state
                    break
        self.cur_id, self.cur_state = dst, nxt_full
        if greedy and self.es > 0.0:  # buffer only feeds exploring starts
            self.es_buf[dst] = (nxt_full, self.i)

    def run(self, steps: int):
        for _ in range(steps):
            self.step()
        return self

    def set_exploration(self, *, eps=None, eps_honest=None, es=None):
        if eps is not None:
            self.eps = eps
        if eps_honest is not None:
            self.eps_honest = eps_honest
        if es is not None:
            self.es = es

    # -- extraction ------------------------------------------------------

    @property
    def n_states(self) -> int:
        return len(self._idx)

    def start_value_and_progress(self):
        v = sum(p * self.value[sid]
                for sid, p in zip(self.start_ids, self.start_probs))
        g = sum(p * self.progress[sid]
                for sid, p in zip(self.start_ids, self.start_probs))
        return float(v), float(g)

    def mdp(self):
        """Extract the partially-explored MDP (rtdp.py:308-387): explored
        states keep their cached transitions; frontier states get one
        pseudo-action to a terminal sink paying their current value
        estimate.  Returns dict(mdp=, policy=, value=)."""
        n = self.n_states
        terminal = n
        m = MDP()
        policy = np.full(n + 1, -1, np.int64)
        value = np.zeros(n + 1, np.float64)
        value[:n] = self.value[:n]
        for sid in range(n):
            tab = self._tables.get(sid)
            if tab is None:
                m.add_transition(sid, 0, terminal, probability=1.0,
                                 reward=float(self.value[sid]), progress=0.0)
                policy[sid] = 0
                continue
            if not tab.probs:
                continue  # true terminal state
            best_a, best_q = 0, -np.inf
            for a in range(len(tab.probs)):
                q = float(tab.probs[a]
                          @ (tab.rews[a] + self.value[tab.dsts[a]]))
                for j in range(len(tab.probs[a])):
                    m.add_transition(
                        sid, a, int(tab.dsts[a][j]),
                        probability=float(tab.probs[a][j]),
                        reward=float(tab.rews[a][j]),
                        progress=float(tab.prgs[a][j]))
                if q > best_q:
                    best_a, best_q = a, q
            policy[sid] = best_a
        m.n_states = n + 1
        for sid, p in zip(self.start_ids, self.start_probs):
            m.start[sid] = p
        m.check()
        return dict(mdp=m, policy=policy, value=value)
