"""Parametric MDP compile + grid-batched value iteration.

The exact-analysis sweeps (measure_mdp battery, break-even curves, the
paper's alpha x gamma figures) all share one shape: for a FIXED
protocol + cutoff the transition structure (src, act, dst, reward,
progress) is identical across the whole grid — only the probability
column changes, and it changes as a *monomial* in alpha, 1-alpha,
gamma, 1-gamma (mdp/models/bitcoin_sm.py: every edge is literally
`self.alpha`, `self.gamma * (1.0 - self.alpha)`, ...).  Today every
grid point recompiles its own MDP from scratch (host BFS or the
native C++ compiler) and solves it in its own serial value_iteration
call.

This module amortizes both:

* **Parametric compile** — bind the implicit models' alpha/gamma to a
  tiny monomial tracer (`Param`: supports `*`, `1 - x`, float
  coefficients) so ONE BFS yields a `ParamMDP`: the usual flat COO
  columns plus per-transition exponents (i, j, k, l) and coefficient
  such that `prob = c * alpha^i (1-alpha)^j gamma^k (1-gamma)^l`.
  `revalue(alpha, gamma)` then materializes any grid point's
  probability column in one vectorized expression.  The native C++
  compiler is covered by a parallel exponent-columns path
  (`parametric_compile_native`): it forms alpha/gamma-dependent
  probabilities at exactly one site (the Continue mining/communication
  split `pc * pm`, plus the loop_honest start split) and never merges
  same-destination transitions, so exponents are recovered exactly by
  matching each probe-point probability against the closed key set.

* **Grid solve** — `grid_value_iteration` stacks the revalued columns
  into a [G, T] plane and runs the chunked VI sweep vmapped over the
  grid axis (mdp/explicit.py `make_grid_vi_chunk`), with per-point
  convergence masking (converged points bit-freeze their value/prog/
  policy like held serve lanes) and the grid axis optionally sharded
  over the device mesh (cpr_tpu/parallel/grid.py — embarrassingly
  parallel, no per-sweep collective).  Per-point fixpoints are
  bit-identical to solo `vi_chunked` solves of the same tensor
  (tests/test_mdp_grid.py, `make mdp-smoke`).

`check_revalue_parity` is the correctness guard: revalued columns must
match a fresh compile at probe points.  `solve_grid_cached` serves
whole solved grids (optimal-policy tables included) through a
content-fingerprint disk cache — the serve `mdp.solve_grid` op and the
break-even exact mode sit on top of it.  docs/MDP.md documents the
contract.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

import numpy as np

from cpr_tpu.mdp.compiler import Compiler
from cpr_tpu.mdp.explicit import MDP, ptmdp
from cpr_tpu.telemetry import now

# interior probe values for the tracer / exponent recovery: any
# 0 < alpha < 0.5, 0 < gamma < 1 pair works for the Python tracer;
# exponent recovery additionally needs the 9 monomial keys pairwise
# distinct (asserted at compile time), which these irrational-looking
# values guarantee with huge margin
PROBE_ALPHA = 0.3137557218
PROBE_GAMMA = 0.7243031127

_ONE = (0, 0, 0, 0)
# 1 - x on a pure coefficient-1 single-variable monomial flips it to
# the complementary variable: 1 - a = (1-a), 1 - (1-a) = a, same for g
_COMPLEMENT = {
    (1, 0, 0, 0): (0, 1, 0, 0),
    (0, 1, 0, 0): (1, 0, 0, 0),
    (0, 0, 1, 0): (0, 0, 0, 1),
    (0, 0, 0, 1): (0, 0, 1, 0),
}


class ParamError(TypeError):
    """An implicit model used alpha/gamma outside the monomial algebra
    the parametric compile supports (products and 1-x only)."""


class Param:
    """Monomial tracer: `coef * alpha^i (1-alpha)^j gamma^k (1-gamma)^l`.

    Supports exactly the algebra the implicit models use on their
    parameters — multiplication (by numbers and other monomials) and
    the complement `1 - x` of a bare variable — plus the float-context
    operations the compiler's validation needs (float(), comparisons,
    and addition, which exits to plain probe-value floats: the
    compiler only ever SUMS probabilities to check them against 1).
    Anything else raises ParamError so an unsupported model fails the
    compile loudly instead of mis-tracing."""

    __slots__ = ("coef", "expo", "value")

    def __init__(self, coef: float, expo: tuple, value: float):
        self.coef = float(coef)
        self.expo = tuple(int(e) for e in expo)
        self.value = float(value)

    def __repr__(self):
        i, j, k, l = self.expo
        return (f"Param({self.coef:g} * a^{i} (1-a)^{j} g^{k} (1-g)^{l}"
                f" = {self.value:g})")

    # -- the supported algebra -------------------------------------------

    def _mul(self, other):
        if isinstance(other, Param):
            return Param(self.coef * other.coef,
                         tuple(a + b for a, b in zip(self.expo,
                                                     other.expo)),
                         self.value * other.value)
        if isinstance(other, (int, float)):
            return Param(self.coef * other, self.expo,
                         self.value * other)
        return NotImplemented

    __mul__ = _mul
    __rmul__ = _mul

    def __rsub__(self, other):
        comp = _COMPLEMENT.get(self.expo)
        if (isinstance(other, (int, float)) and float(other) == 1.0
                and self.coef == 1.0 and comp is not None):
            return Param(1.0, comp, 1.0 - self.value)
        raise ParamError(
            f"parametric compile only supports 1 - x on a bare "
            f"alpha/gamma monomial, got {other!r} - {self!r}")

    def __sub__(self, other):
        raise ParamError(
            f"parametric compile does not support {self!r} - {other!r}")

    # addition exits the parametric domain: the compiler and the
    # models only sum probabilities to VALIDATE them (sum_to_one),
    # never to build a transition probability
    def _add(self, other):
        return self.value + float(other)

    __add__ = _add
    __radd__ = _add

    # -- float-context plumbing ------------------------------------------

    def __float__(self):
        return self.value

    def __bool__(self):
        return self.value != 0.0

    def __eq__(self, other):
        if isinstance(other, Param):
            return (self.coef, self.expo) == (other.coef, other.expo)
        if isinstance(other, (int, float)):
            return self.value == float(other)
        return NotImplemented

    def __hash__(self):
        return hash((self.coef, self.expo))

    def __lt__(self, other):
        return self.value < float(other)

    def __le__(self, other):
        return self.value <= float(other)

    def __gt__(self, other):
        return self.value > float(other)

    def __ge__(self, other):
        return self.value >= float(other)


def param_pair(probe_alpha: float = PROBE_ALPHA,
               probe_gamma: float = PROBE_GAMMA):
    """(alpha, gamma) tracer pair to bind into an implicit model."""
    assert 0.0 < probe_alpha < 0.5 and 0.0 < probe_gamma < 1.0
    return (Param(1.0, (1, 0, 0, 0), probe_alpha),
            Param(1.0, (0, 0, 1, 0), probe_gamma))


@dataclass(frozen=True)
class ParamMDP:
    """A compiled MDP whose probability column is symbolic in
    (alpha, gamma): `mdp` holds the shared structure with the
    PROBE-point probabilities (a fully valid MDP — check() passed on
    it), and `prob[t] = coef[t] * alpha^expo[t,0] (1-alpha)^expo[t,1]
    * gamma^expo[t,2] (1-gamma)^expo[t,3]` for any grid point.  The
    start distribution is parametric too (fc16's stochastic start is
    {alpha, 1-alpha})."""

    mdp: MDP
    coef: np.ndarray          # [T] float64
    expo: np.ndarray          # [T, 4] int16
    start_ids: np.ndarray     # [n_start] int32
    start_coef: np.ndarray    # [n_start] float64
    start_expo: np.ndarray    # [n_start, 4] int16
    probe_alpha: float
    probe_gamma: float
    meta: dict = field(default_factory=dict)

    @property
    def n_states(self) -> int:
        return self.mdp.n_states

    @property
    def n_transitions(self) -> int:
        return self.mdp.n_transitions

    def __repr__(self):
        return (f"ParamMDP({self.mdp!r}, probe=({self.probe_alpha:g}, "
                f"{self.probe_gamma:g}), meta={self.meta})")

    @staticmethod
    def _monomial(coef, expo, alpha: float, gamma: float) -> np.ndarray:
        a, g = float(alpha), float(gamma)
        e = expo
        return (coef * a ** e[:, 0] * (1.0 - a) ** e[:, 1]
                * g ** e[:, 2] * (1.0 - g) ** e[:, 3])

    def revalue(self, alpha: float, gamma: float) -> np.ndarray:
        """The [T] float64 probability column at (alpha, gamma) — one
        vectorized monomial evaluation, no recompile."""
        return self._monomial(self.coef, self.expo, alpha, gamma)

    def start_vector(self, alpha: float, gamma: float) -> np.ndarray:
        """The [S] float64 start distribution at (alpha, gamma)."""
        s = np.zeros(self.n_states, np.float64)
        s[self.start_ids] = self._monomial(self.start_coef,
                                           self.start_expo, alpha, gamma)
        return s

    def fingerprint(self) -> str:
        """Content hash of the parametric compile — the solve-cache
        key (solve_grid_cached): two compiles whose structure,
        exponents, or coefficients differ in any way (model fix,
        compiler change, different cutoff) can never share a cached
        solve."""
        src, act, dst, _, reward, progress = self.mdp.arrays()
        h = hashlib.sha256()
        h.update(repr((self.mdp.n_states, self.mdp.n_actions,
                       self.probe_alpha, self.probe_gamma,
                       sorted(self.meta.items()))).encode())
        for arr in (src, act, dst, reward, progress, self.coef,
                    self.expo, self.start_ids, self.start_coef,
                    self.start_expo):
            h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()[:24]


def _extract_param(p, what: str):
    """(coef, expo) of one traced probability; plain floats are
    constant monomials."""
    if isinstance(p, Param):
        return p.coef, p.expo
    if isinstance(p, (int, float)):
        return float(p), _ONE
    raise ParamError(f"{what} is {type(p).__name__}, expected a "
                     f"Param monomial or a plain number")


def _param_mdp_from(mdp: MDP, probe_alpha: float, probe_gamma: float,
                    meta: dict) -> ParamMDP:
    """Split a tracer-compiled MDP into probe-valued float columns +
    (coef, expo) parametric columns."""
    coef = np.empty(mdp.n_transitions, np.float64)
    expo = np.empty((mdp.n_transitions, 4), np.int16)
    for t, p in enumerate(mdp.prob):
        coef[t], expo[t] = _extract_param(p, f"transition {t} prob")
    start_ids = np.asarray(sorted(mdp.start), np.int32)
    start_coef = np.empty(len(start_ids), np.float64)
    start_expo = np.empty((len(start_ids), 4), np.int16)
    for i, sid in enumerate(start_ids):
        start_coef[i], start_expo[i] = _extract_param(
            mdp.start[int(sid)], f"start prob of state {sid}")
    # re-materialize the base MDP with plain probe-valued floats so
    # downstream tensor()/ptmdp/check() see an ordinary MDP
    src, act, dst, prob, reward, progress = mdp.arrays()
    base = MDP(n_states=mdp.n_states, n_actions=mdp.n_actions,
               start={int(s): float(p) for s, p in mdp.start.items()},
               src=src, act=act, dst=dst, prob=prob, reward=reward,
               progress=progress)
    return ParamMDP(mdp=base, coef=coef, expo=expo,
                    start_ids=start_ids, start_coef=start_coef,
                    start_expo=start_expo, probe_alpha=probe_alpha,
                    probe_gamma=probe_gamma, meta=dict(meta))


def parametric_compile(factory, *, probe_alpha: float = PROBE_ALPHA,
                       probe_gamma: float = PROBE_GAMMA,
                       meta: dict | None = None,
                       n_workers: int | None = None,
                       checkpoint_path: str | None = None) -> ParamMDP:
    """One frontier-batched compile of `factory(alpha=<tracer>,
    gamma=<tracer>)` -> ParamMDP.  The model runs unmodified — its
    probability expressions evaluate in the monomial tracer domain, so
    BFS order, state ids, and transition order are exactly those of a
    fresh compile at the probe point (the models' control flow depends
    on alpha/gamma only through comparisons, which the tracer answers
    with its probe value).  The (coef, expo) columns are carried
    through the columnar collect (FrontierCompiler trace_params), so
    the tracer inherits multi-core expansion and checkpointed resume;
    the result is bit-identical to the old serial
    `Compiler` + `_param_mdp_from` pair."""
    from cpr_tpu.mdp.frontier import FrontierCompiler

    a, g = param_pair(probe_alpha, probe_gamma)
    model = factory(alpha=a, gamma=g)
    meta = dict(meta or {})
    fc = FrontierCompiler(model, n_workers=n_workers,
                          checkpoint_path=checkpoint_path,
                          trace_params=True,
                          protocol=meta.get("protocol"),
                          cutoff=meta.get("cutoff"))
    return fc.param_mdp(probe_alpha=probe_alpha,
                        probe_gamma=probe_gamma, meta=meta)


def _native_keys(a: float, g: float):
    """The closed set of probability values the native generic
    compiler can emit at probe point (a, g), with their exponents.
    Verified against cpr_tpu/native/src/generic_compiler.cpp: alpha/
    gamma enter transition probabilities ONLY at the Continue action
    (`pc[ci] * pm[mi]` over pc = {g, 1-g}, pm = {a, 1-a}), Release/
    Consider are deterministic (prob 1), start probabilities under
    loop_honest are {a, 1-a}, and same-destination transitions are
    never merged — so every emitted probability is exactly one of
    these 9 IEEE doubles."""
    return [
        (1.0, _ONE),
        (a, (1, 0, 0, 0)),
        (1.0 - a, (0, 1, 0, 0)),
        (g, (0, 0, 1, 0)),
        (1.0 - g, (0, 0, 0, 1)),
        (g * a, (1, 0, 1, 0)),
        (g * (1.0 - a), (0, 1, 1, 0)),
        ((1.0 - g) * a, (1, 0, 0, 1)),
        ((1.0 - g) * (1.0 - a), (0, 1, 0, 1)),
    ]


def parametric_compile_native(proto: str, *, k: int = 0,
                              probe_alpha: float = PROBE_ALPHA,
                              probe_gamma: float = PROBE_GAMMA,
                              meta: dict | None = None,
                              **kw) -> ParamMDP:
    """ParamMDP from ONE native (C++) compile at the probe point: the
    exponent columns are recovered by matching each emitted
    probability against the closed native key set (_native_keys) —
    exact, because the compiler forms those values with the same IEEE
    double expressions.  Any probability outside the key set aborts
    (a compiler change that widened the probability algebra must fail
    loudly, not mis-parameterize)."""
    from cpr_tpu.mdp.generic.native import compile_native

    mdp = compile_native(proto, k=k, alpha=probe_alpha,
                         gamma=probe_gamma, **kw)
    keys = _native_keys(probe_alpha, probe_gamma)
    vals = np.asarray([v for v, _ in keys])
    expos = np.asarray([e for _, e in keys], np.int16)
    assert len(np.unique(vals)) == len(vals), \
        "probe point produced colliding native keys; pick another"

    def match(col, what):
        col = np.asarray(col, np.float64)
        idx = np.abs(col[:, None] - vals[None, :]).argmin(axis=1)
        bad = ~np.isclose(col, vals[idx], rtol=1e-12, atol=0.0)
        if bad.any():
            t = int(np.flatnonzero(bad)[0])
            raise ParamError(
                f"native {what} {t} has probability {col[t]!r} outside "
                f"the known monomial key set — the native compiler's "
                f"probability algebra changed; update _native_keys")
        return idx

    prob = np.asarray(mdp.prob, np.float64)
    idx = match(prob, "transition")
    # the key table is coefficient-1; the emitted value IS the
    # monomial, so coef is the ratio (exactly 1 in IEEE terms)
    coef = np.ones(len(prob), np.float64)
    expo = expos[idx]
    start_ids = np.asarray(sorted(mdp.start), np.int32)
    start_vals = np.asarray([mdp.start[int(s)] for s in start_ids])
    sidx = match(start_vals, "start entry")
    base = MDP(n_states=mdp.n_states, n_actions=mdp.n_actions,
               start={int(s): float(p)
                      for s, p in zip(start_ids, start_vals)},
               src=mdp.src, act=mdp.act, dst=mdp.dst, prob=mdp.prob,
               reward=mdp.reward, progress=mdp.progress)
    m = dict(meta or {}, proto=proto, k=k)
    return ParamMDP(mdp=base, coef=coef, expo=expo,
                    start_ids=start_ids,
                    start_coef=np.ones(len(start_ids), np.float64),
                    start_expo=expos[sidx], probe_alpha=probe_alpha,
                    probe_gamma=probe_gamma, meta=m)


def param_ptmdp(pm: ParamMDP, *, horizon: int) -> ParamMDP:
    """Parametric twin of explicit.ptmdp: the PTO continue probability
    `keep = (1 - 1/horizon)^progress` is a CONSTANT per transition
    (progress does not depend on alpha/gamma), so the transform only
    scales coefficients — continue rows by keep, the appended terminal
    rows by (1 - keep) — with exponents carried through unchanged.
    The base MDP goes through explicit.ptmdp itself, so row order
    matches by construction."""
    base = ptmdp(pm.mdp, horizon=horizon)
    _, _, _, _, _, progress = pm.mdp.arrays()
    keep = (1.0 - 1.0 / horizon) ** progress
    hp = progress != 0.0
    coef = np.concatenate([np.where(hp, pm.coef * keep, pm.coef),
                           (pm.coef * (1.0 - keep))[hp]])
    expo = np.concatenate([pm.expo, pm.expo[hp]])
    return ParamMDP(mdp=base, coef=coef, expo=expo,
                    start_ids=pm.start_ids, start_coef=pm.start_coef,
                    start_expo=pm.start_expo,
                    probe_alpha=pm.probe_alpha,
                    probe_gamma=pm.probe_gamma,
                    meta=dict(pm.meta, horizon=horizon))


def check_revalue_parity(pm: ParamMDP, fresh, points, *,
                         rtol: float = 1e-9) -> int:
    """The parity guard: for each (alpha, gamma) probe point, a FRESH
    compile via `fresh(alpha, gamma) -> MDP` must have identical
    state/transition counts and a probability column allclose (tight
    rtol, atol 0) to `pm.revalue(alpha, gamma)`; start distributions
    likewise.  Returns the number of points checked.  Probe at
    INTERIOR points: at gamma in {0, 1} the generic models skip
    zero-probability branches, so a fresh compile has a different
    (smaller) transition set — the revalued column is still correct
    there (the extra rows carry probability 0), it just cannot be
    compared row-for-row."""
    n = 0
    for alpha, gamma in points:
        m = fresh(alpha, gamma)
        if not isinstance(m, MDP):
            m = Compiler(m).mdp()
        if (m.n_states, m.n_transitions) != (pm.n_states,
                                             pm.n_transitions):
            raise AssertionError(
                f"parametric compile diverges from fresh compile at "
                f"({alpha}, {gamma}): {pm.n_states}/{pm.n_transitions} "
                f"vs {m.n_states}/{m.n_transitions} states/transitions")
        got = pm.revalue(alpha, gamma)
        want = m.arrays()[3]
        if not np.allclose(got, want, rtol=rtol, atol=0.0):
            worst = int(np.abs(got - want).argmax())
            raise AssertionError(
                f"revalued probability column diverges at "
                f"({alpha}, {gamma}), transition {worst}: "
                f"{got[worst]!r} vs fresh {want[worst]!r}")
        sv = pm.start_vector(alpha, gamma)
        for sid, p in m.start.items():
            if not np.isclose(sv[sid], float(p), rtol=rtol, atol=0.0):
                raise AssertionError(
                    f"start prob of state {sid} diverges at "
                    f"({alpha}, {gamma}): {sv[sid]!r} vs {float(p)!r}")
        n += 1
    return n


# -- the grid solver ---------------------------------------------------------


def grid_points(alphas, gammas):
    """The row-major (alpha-major) point list both the solver and its
    callers index by."""
    alphas = [float(a) for a in np.atleast_1d(alphas)]
    gammas = [float(g) for g in np.atleast_1d(gammas)]
    return alphas, gammas, [(a, g) for a in alphas for g in gammas]


def grid_value_iteration(pm: ParamMDP, alphas, gammas, *,
                         discount: float = 1.0, eps: float | None = None,
                         stop_delta: float | None = None,
                         max_iter: int = 0, chunk: int = 64,
                         dtype=None, mesh=None, axis: str = "d",
                         state_axis: str | None = None,
                         checkpoint_path: str | None = None,
                         checkpoint_every: int = 1,
                         protocol: str | None = None,
                         cutoff: int | None = None) -> dict:
    """Solve the whole (alphas x gammas) grid as ONE vmapped (and
    optionally grid-axis-sharded) chunked-VI program over `pm`'s
    shared transition structure.

    Semantics per point match `TensorMDP.value_iteration(impl=
    "chunked")` on the revalued tensor bit-for-bit: same chunk
    schedule, same stop rule at chunk granularity — a converged point
    is bit-frozen (value/prog/policy passed through unchanged) while
    the rest of the grid keeps sweeping.  `mesh` shards the [G] grid
    axis via cpr_tpu.parallel.make_grid_chunk_step (G must divide the
    axis; refused up front).  `state_axis` names a SECOND mesh axis to
    shard each point's STATE space over as well (the grid x state 2-D
    mesh, cpr_tpu.parallel.make_grid_state_chunk_step): pass a 2-D
    mesh whose axes are (`axis`, `state_axis`); both G and n_states
    must divide their axis, refused up front by name.
    checkpoint_path/checkpoint_every give per-grid-solve crash
    checkpoints + resume (resilience.save_grid_vi_checkpoint).

    Emits one typed `mdp_solve` telemetry event (schema v13: the v10
    fields plus `state_shards`/`halo_bytes`) with the protocol/cutoff
    labels, grid shape, total sweeps, and per-point convergence count.
    Returns a dict of grid-major arrays (see docs/MDP.md)."""
    import jax.numpy as jnp

    from cpr_tpu import telemetry
    from cpr_tpu.mdp.explicit import run_grid_chunk_driver
    from cpr_tpu.parallel.grid import make_grid_chunk_step

    dtype = jnp.float32 if dtype is None else dtype
    alphas, gammas, points = grid_points(alphas, gammas)
    G = len(points)
    assert G > 0, "empty grid"
    tm = pm.mdp.tensor(dtype)
    stop_delta = tm.resolve_stop_delta(discount=discount, eps=eps,
                                       stop_delta=stop_delta,
                                       max_iter=max_iter)
    tm._check_segment_width()
    t0 = now()
    probs = np.stack([pm.revalue(a, g) for a, g in points])
    starts = np.stack([pm.start_vector(a, g) for a, g in points])
    state_shards = 1
    if state_axis is not None:
        from cpr_tpu.parallel.state_shard import make_grid_state_chunk_step

        if mesh is None:
            raise ValueError(
                "state_axis requires a 2-D mesh whose axes are "
                f"({axis!r}, {state_axis!r}); got mesh=None")
        state_shards = int(mesh.shape[state_axis])
        # the composed builder closes over the probability plane (it
        # owns its [G, n_s * t_blk] bucketed layout), so its chunk_step
        # already has the run_grid_chunk_driver signature
        step, place = make_grid_state_chunk_step(
            tm, G, probs.astype(np.dtype(tm.prob.dtype)),
            discount=discount, mesh=mesh, axis=axis,
            state_axis=state_axis)
    else:
        chunk_step, place = make_grid_chunk_step(tm, G,
                                                 discount=discount,
                                                 mesh=mesh, axis=axis)
        probs_dev = place(probs.astype(np.dtype(tm.prob.dtype)))

        def step(carry, frozen, steps):
            return chunk_step(carry, probs_dev, frozen, steps)

    value, prog, policy, delta, conv_it, converged, it, resid = \
        run_grid_chunk_driver(
            step, place, G, pm.n_states, tm.prob.dtype, stop_delta,
            max_iter if max_iter > 0 else (1 << 30), chunk=chunk,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every)
    vi_time = now() - t0
    # per-point revenue: expected reward / expected progress from the
    # point's own start distribution (fc16 starts are alpha-dependent)
    num = (starts * value).sum(axis=1)
    den = (starts * prog).sum(axis=1)
    revenue = np.divide(num, den, out=np.zeros_like(num),
                        where=den != 0.0)
    from cpr_tpu.parallel.state_shard import state_halo_bytes

    halo = state_halo_bytes(pm.n_states, state_shards,
                            np.dtype(tm.prob.dtype))
    telemetry.current().event(
        "mdp_solve", protocol=protocol, cutoff=cutoff,
        grid=[len(alphas), len(gammas)], sweeps=int(it),
        converged=int(converged.sum()), points=G,
        n_states=pm.n_states, n_transitions=pm.n_transitions,
        n_devices=(int(np.prod(list(mesh.shape.values())))
                   if mesh is not None else 1),
        state_shards=state_shards, halo_bytes=int(halo),
        solve_s=round(vi_time, 6),
        points_per_sec=round(G / vi_time, 3) if vi_time > 0 else None,
        states_per_sec=(round(pm.n_states * int(it) / vi_time, 3)
                        if vi_time > 0 else None))
    return dict(
        grid_alphas=alphas, grid_gammas=gammas, grid_points=points,
        grid_value=value, grid_progress=prog, grid_policy=policy,
        grid_start=starts, grid_revenue=revenue, grid_delta=delta,
        grid_iter=conv_it, grid_converged=converged,
        vi_iter=int(it), vi_stop_delta=float(stop_delta),
        vi_residuals=resid, vi_time=vi_time,
    )


# -- protocol registry + cached solves ---------------------------------------


def compile_protocol(protocol: str, *, cutoff: int, k: int = 2,
                     native: bool = False,
                     probe_alpha: float = PROBE_ALPHA,
                     probe_gamma: float = PROBE_GAMMA,
                     n_workers: int | None = None,
                     checkpoint_path: str | None = None) -> ParamMDP:
    """Parametric compile of one battery protocol family: "fc16" /
    "aft20" (maximum_fork_length=cutoff) or "bitcoin" / "ghostdag"
    (generic model, dag_size_cutoff=cutoff; `native=True` uses the C++
    compiler's exponent-recovery path).  The Python paths ride the
    frontier-batched compiler; `n_workers` (default
    CPR_MDP_COMPILE_WORKERS) shards each frontier across worker
    processes and `checkpoint_path` enables between-round crash
    checkpoints — both bit-identity-preserving."""
    meta = dict(protocol=protocol, cutoff=int(cutoff))
    if protocol in ("fc16", "aft20"):
        from cpr_tpu.mdp.models import Aft20BitcoinSM, Fc16BitcoinSM

        cls = Fc16BitcoinSM if protocol == "fc16" else Aft20BitcoinSM
        return parametric_compile(
            lambda alpha, gamma: cls(alpha=alpha, gamma=gamma,
                                     maximum_fork_length=cutoff),
            probe_alpha=probe_alpha, probe_gamma=probe_gamma, meta=meta,
            n_workers=n_workers, checkpoint_path=checkpoint_path)
    if protocol in ("bitcoin", "ghostdag"):
        kk = k if protocol == "ghostdag" else 0
        if native:
            return parametric_compile_native(
                protocol, k=kk, probe_alpha=probe_alpha,
                probe_gamma=probe_gamma, collect_garbage="simple",
                dag_size_cutoff=cutoff, meta=meta)
        from cpr_tpu.mdp.generic import SingleAgent, get_protocol

        kw = {"k": kk} if protocol == "ghostdag" else {}
        return parametric_compile(
            lambda alpha, gamma: SingleAgent(
                get_protocol(protocol, **kw), alpha=alpha, gamma=gamma,
                collect_garbage="simple", merge_isomorphic=True,
                truncate_common_chain=True, dag_size_cutoff=cutoff),
            probe_alpha=probe_alpha, probe_gamma=probe_gamma, meta=meta,
            n_workers=n_workers, checkpoint_path=checkpoint_path)
    raise ValueError(f"unknown protocol {protocol!r}; expected fc16, "
                     f"aft20, bitcoin, or ghostdag")


def _cache_dir() -> str:
    """Solve-cache directory: CPR_MDP_CACHE > <CPR_TPU_CACHE>/mdp_grid
    > ~/.cache/cpr_tpu/mdp_grid (the break_even cache-dir pattern;
    delete the directory to bust the cache)."""
    d = os.environ.get("CPR_MDP_CACHE")
    if d:
        return d
    base = os.environ.get("CPR_TPU_CACHE")
    if base:
        return os.path.join(base, "mdp_grid")
    return os.path.join(os.path.expanduser("~"), ".cache", "cpr_tpu",
                        "mdp_grid")


def solve_grid_cached(protocol: str, *, cutoff: int, alphas, gammas,
                      horizon: int = 100, stop_delta: float = 1e-6,
                      discount: float = 1.0, k: int = 2,
                      native: bool = False, include_policy: bool = False,
                      cache: bool = True, mesh=None) -> dict:
    """Compile (parametric) + solve the grid, with the SOLVE cached on
    disk keyed by the ParamMDP content fingerprint + solve knobs: the
    cheap compile re-runs on every call and anything that changes its
    output — model fix, compiler change, different cutoff — changes
    the fingerprint and so invalidates the cached solve automatically.
    The serve `mdp.solve_grid` op and break_even's exact mode sit on
    this.  Returns a JSON-safe dict (policy tables as nested lists
    when include_policy)."""
    import cpr_tpu
    from cpr_tpu import resilience

    alphas, gammas, points = grid_points(alphas, gammas)
    pm = param_ptmdp(
        compile_protocol(protocol, cutoff=cutoff, k=k, native=native),
        horizon=horizon)
    fp = pm.fingerprint()
    key = dict(kind="mdp_grid", fingerprint=fp, alphas=alphas,
               gammas=gammas, horizon=horizon, stop_delta=stop_delta,
               discount=discount, include_policy=bool(include_policy),
               _version=cpr_tpu.__version__)
    h = hashlib.sha256(
        json.dumps(key, sort_keys=True).encode()).hexdigest()[:24]
    path = os.path.join(_cache_dir(), h + ".json")
    if cache and os.path.exists(path):
        # corruption is a MISS, never a crash: a truncated, bit-flipped
        # or garbage-JSON entry is quarantined + reported (typed
        # `integrity` event, action "regenerated") and the solve below
        # recomputes it; pre-v19 unsealed entries read fine, tagged
        # integrity: "unverified"
        from cpr_tpu import integrity
        try:
            data, tag = resilience.sealed_read_json(
                path, kind="mdp_grid_cache", action="regenerated")
            return dict(data["value"], cached=True, integrity=tag)
        except resilience.IntegrityError:
            pass
        except (OSError, KeyError, TypeError):
            integrity.quarantine(path, kind="mdp_grid_cache",
                                 reason="truncated", action="regenerated")
    vi = grid_value_iteration(pm, alphas, gammas, discount=discount,
                              stop_delta=stop_delta, mesh=mesh,
                              protocol=protocol, cutoff=cutoff)
    value = dict(
        protocol=protocol, cutoff=int(cutoff), horizon=int(horizon),
        stop_delta=float(stop_delta), discount=float(discount),
        fingerprint=fp, n_states=pm.n_states,
        n_transitions=pm.n_transitions, alphas=alphas, gammas=gammas,
        points=[list(p) for p in points],
        revenue=[round(float(r), 12) for r in vi["grid_revenue"]],
        converged=[bool(c) for c in vi["grid_converged"]],
        sweeps=int(vi["vi_iter"]),
        conv_iter=[int(i) for i in vi["grid_iter"]],
        solve_s=round(float(vi["vi_time"]), 6), cached=False,
    )
    if include_policy:
        value["policy"] = [[int(x) for x in row]
                           for row in vi["grid_policy"]]
    if cache:
        resilience.sealed_write_json(path, {"key": key, "value": value},
                                     site="cache")
    return value
