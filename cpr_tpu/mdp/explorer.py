"""Policy-guided incremental exploration of implicit models.

Reference counterpart: mdp/lib/policy_guided_explorer.py:13-131.  The
invariants carry over: the guiding policy's action is explored first and
always sits at positional action id 0, states are numbered in order of
discovery (on-policy states get the smallest ids), and any prefix of the
exploration yields an MDP whose positional policy `s -> 0` is exactly the
guiding policy — so policies solved on truncated MDPs of growing size
stay compatible with each other.

The truncated tables plug into the jitted value iteration like any other
MDP; growing-horizon sweeps (solve, enlarge, re-solve) are how the
reference sizes its state spaces, and the TPU solver makes the re-solve
step cheap.
"""

from __future__ import annotations

from cpr_tpu.mdp.explicit import MDP
from cpr_tpu.mdp.implicit import Model


class Explorer:
    def __init__(self, model: Model, policy):
        self.model = model
        self.policy = policy
        self.states: list = []  # state id -> state
        self.policy_actions: list[int] = []  # state id -> policy action idx
        self._ids: dict = {}
        self._mdp = MDP()
        self._policy_explored = 0  # ids < this have their policy action in
        self._fully_explored = 0  # ids < this have all actions in
        for s, p in model.start():
            self._mdp.start[self._id_of(s)] = p

    def _id_of(self, state) -> int:
        sid = self._ids.get(state)
        if sid is None:
            sid = len(self._ids)
            self._ids[state] = sid
            self.states.append(state)
        return sid

    @property
    def n_states(self) -> int:
        return len(self.states)

    def explore_along_policy(self, max_states: int = 0):
        """Expand the policy action of every discovered state (discovers
        new states, so this runs to a fixpoint)."""
        while self._policy_explored < self.n_states:
            if max_states and self.n_states > max_states:
                raise RuntimeError(
                    f"state budget exceeded: {self.n_states} > {max_states}")
            sid = self._policy_explored
            state = self.states[sid]
            actions = self.model.actions(state)
            if not actions:
                self.policy_actions.append(-1)  # terminal
                self._policy_explored += 1
                continue
            a = self.policy(state)
            self.policy_actions.append(actions.index(a))
            for t in self.model.apply(a, state):
                if t.probability == 0.0:
                    continue
                self._mdp.add_transition(
                    sid, 0, self._id_of(t.state),
                    probability=t.probability, reward=t.reward,
                    progress=t.progress)
            self._policy_explored += 1

    def explore_aside_policy(self, max_states: int = 0):
        """Expand the non-policy actions of every policy-explored state;
        newly found states then get their policy action expanded too."""
        self.explore_along_policy(max_states)
        while self._fully_explored < self._policy_explored:
            if max_states and self.n_states > max_states:
                raise RuntimeError(
                    f"state budget exceeded: {self.n_states} > {max_states}")
            sid = self._fully_explored
            state = self.states[sid]
            actions = self.model.actions(state)
            pa = self.policy_actions[sid]
            aid = 0  # the policy action occupies slot 0
            for i, a in enumerate(actions):
                if i == pa:
                    continue  # already explored as slot 0
                aid += 1
                for t in self.model.apply(a, state):
                    if t.probability == 0.0:
                        continue
                    self._mdp.add_transition(
                        sid, aid, self._id_of(t.state),
                        probability=t.probability, reward=t.reward,
                        progress=t.progress)
            self._fully_explored += 1
        # states discovered off-policy get their policy action expanded
        # too, under the same budget — so the caller's cap is honored and
        # a later mdp() call has nothing unbudgeted left to do
        self.explore_along_policy(max_states)

    def mdp(self, max_states: int = 0) -> MDP:
        """Finish policy exploration (every reachable state must at least
        abort into honest play) and return a copy of the table."""
        self.explore_along_policy(max_states)
        m = self._mdp
        # shallow per-field copies: the flat lists hold immutable scalars
        out = MDP(n_states=self.n_states, n_actions=m.n_actions,
                  start=dict(m.start), src=list(m.src), act=list(m.act),
                  dst=list(m.dst), prob=list(m.prob),
                  reward=list(m.reward), progress=list(m.progress))
        out.check()
        return out
