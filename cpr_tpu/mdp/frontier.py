"""Frontier-batched implicit -> explicit MDP compiler.

The serial `Compiler` (cpr_tpu/mdp/compiler.py) explores one state per
step: a dict hash per successor, six list.append calls per transition,
and a Python `sum_to_one` per (state, action).  At the state counts the
exact-analysis papers reach (arXiv:2007.05614, arXiv:2309.11924 grow
into the millions as cutoffs rise) that loop dominates end-to-end
wall-clock — the grid SOLVE has been one vmapped program since the
grid-batched VI landed.

`FrontierCompiler` replaces the per-state loop with whole-frontier
rounds:

* **Round semantics.**  A round expands every state of the current
  frontier (all states discovered in the previous round — their ids
  are one contiguous range, because ids are assigned in discovery
  order), collects the successors columnar, and appends one numpy
  chunk per round through the bulk `MDP.add_transitions` — no
  per-transition Python appends.

* **Id determinism contract.**  New states get ids in (source id,
  action slot, transition order) within the round.  FIFO BFS order is
  exactly that order, so the result is bit-identical to the serial
  `Compiler`: same state ids, same transition columns, same start map,
  same action_map.  Per-round dedup runs vectorized — np.unique over
  pickled state keys — with unique representatives mapped back to
  first-occurrence order before id assignment; the global state table
  still dedups by the state objects' own hash/eq, so a model whose
  equal states pickle differently loses only batching, never
  correctness.

* **Multi-core expansion.**  Because the merge order is deterministic,
  each frontier can be sharded across worker processes
  (concurrent.futures; the model is pickled once into each worker's
  initializer) and the shard payloads concatenated in shard order —
  bit-identical to inline expansion at any worker count.  The spawn
  context is used by default (fork-after-JAX-init is not worth the
  deadlock risk; override with CPR_MDP_COMPILE_MP_CONTEXT).

* **Validation.**  Per-round vectorized probability-mass check
  (group-boundary reduceat over the round's columns) replaces the
  serial per-state `sum_to_one` Python sum, with the same tolerance
  and the same AssertionError((state, action)) on violation.

* **Checkpoint / resume.**  Between rounds the partial columns, the
  frontier position, and the state-key table land in one atomic npz
  (resilience.save_compile_checkpoint); the `compile_round` fault site
  is occurrence-counted, so `kill@compile_round=N` + resume is proven
  bit-identical to an uninterrupted compile (tier-1 +
  tools/compile_smoke.py).

* **Telemetry.**  One schema-v12 `mdp_compile` event per compile
  (protocol/cutoff/rounds/states/transitions/n_workers, plus
  compile_s / states_per_sec extras the perf ledger lifts into
  `mdp_compile_states_per_sec` rows).

The parametric monomial tracer rides the same path: probe values are a
per-transition float column and the (coef, expo) columns travel
through the columnar collect, so `grid.parametric_compile` /
`grid.compile_protocol` (and everything above them: the grid VI
pipeline, solve_grid_cached, the ghostdag capstone) inherit the
batched compile.  See docs/MDP.md.
"""

from __future__ import annotations

import hashlib
import os
import pickle

import numpy as np

from cpr_tpu import resilience, telemetry
from cpr_tpu.telemetry import now

WORKERS_ENV_VAR = "CPR_MDP_COMPILE_WORKERS"
MP_CONTEXT_ENV_VAR = "CPR_MDP_COMPILE_MP_CONTEXT"
_PICKLE_PROTO = 5
_COL_NAMES = ("src", "act", "dst", "prob", "reward", "progress")


def resolve_workers(n: int | None = None) -> int:
    """Worker-process count: explicit argument, else
    CPR_MDP_COMPILE_WORKERS, else 1 (inline expansion)."""
    if n is None:
        n = int(os.environ.get(WORKERS_ENV_VAR, "1") or 1)
    return max(1, int(n))


def _expand_states(model, states, trace_params: bool,
                   with_keys: bool = False) -> dict:
    """Expand one frontier shard in order.  Returns a columnar payload:
    per-state semantic actions, per-(state, action) transition counts,
    and flat transition columns in (state order, action slot,
    transition order) — plus each successor state object.
    `with_keys` (worker shards only) additionally pickles a dedup key
    per successor so the vectorized np.unique pre-dedup runs on
    worker-encoded bytes; the inline path skips the encode and dedups
    through the state dict directly, which is cheaper when no worker
    parallelism pays for the pickling.  The merge is a plain
    concatenation in shard order."""
    actions_out: list = []
    tcounts: list[int] = []
    probs: list = []
    rewards: list = []
    progresses: list = []
    succs: list = []
    for state in states:
        actions = list(model.actions(state))
        actions_out.append(actions)
        for action in actions:
            ts = model.apply(action, state)
            tcounts.append(len(ts))
            probs.extend(t.probability for t in ts)
            rewards.extend(t.reward for t in ts)
            progresses.extend(t.progress for t in ts)
            succs.extend(t.state for t in ts)
    if trace_params:
        from cpr_tpu.mdp.grid import _extract_param

        ce = [_extract_param(p, "transition prob") for p in probs]
        coef = np.asarray([c for c, _ in ce], np.float64)
        expo = np.asarray([e for _, e in ce],
                          np.int16).reshape(len(ce), 4)
    else:
        coef = expo = None
    return dict(
        actions=actions_out,
        tcounts=np.asarray(tcounts, np.int64),
        # works for plain numbers and Param tracers alike (__float__)
        val=np.asarray(probs, np.float64),
        coef=coef, expo=expo,
        reward=np.asarray(rewards, np.float64),
        progress=np.asarray(progresses, np.float64),
        succs=succs,
        keys=([pickle.dumps(s, _PICKLE_PROTO) for s in succs]
              if with_keys else None),
    )


# worker-process state: the model is shipped ONCE through the pool
# initializer (pickled bytes), not once per round/shard
_WORKER: dict = {"model": None, "trace_params": False}


def _worker_init(model_blob: bytes, trace_params: bool):
    _WORKER["model"] = pickle.loads(model_blob)
    _WORKER["trace_params"] = bool(trace_params)


def _worker_expand(states):
    return _expand_states(_WORKER["model"], states,
                          _WORKER["trace_params"], with_keys=True)


class FrontierCompiler:
    """Drop-in batched twin of `Compiler`: same `mdp()` entry point,
    same `state_map` / `states` / `action_map` surfaces, bit-identical
    output.  Extra knobs: `n_workers` (frontier sharded across a
    process pool), `checkpoint_path`/`checkpoint_every` (between-round
    crash checkpoints + resume), `trace_params` (collect the monomial
    tracer's coef/expo per-transition columns for `param_mdp()`), and
    `protocol`/`cutoff` labels for the `mdp_compile` telemetry event."""

    # frontiers smaller than n_workers * min_shard expand inline: IPC
    # setup costs more than the round for the tiny early frontiers
    min_shard = 16

    def __init__(self, model, *, n_workers: int | None = None,
                 checkpoint_path: str | None = None,
                 checkpoint_every: int = 1,
                 trace_params: bool = False,
                 protocol: str | None = None,
                 cutoff: int | None = None):
        self.model = model
        self.n_workers = resolve_workers(n_workers)
        self.trace_params = bool(trace_params)
        self.protocol = protocol
        self.cutoff = cutoff
        self._ck_path = checkpoint_path
        self._ck_every = max(1, int(checkpoint_every))
        self._model_blob = pickle.dumps(model, _PICKLE_PROTO)
        self._model_fp = hashlib.sha256(self._model_blob).hexdigest()[:16]
        self.state_map: dict = {}
        self.states: list = []
        self.action_map: list[list] = []
        self._start: dict = {}
        self._cols: list[tuple] = []    # per-round column chunks
        self._pcols: list[tuple] = []   # per-round (coef, expo) chunks
        self._explored_upto = 0
        self._round = 0
        self._elapsed = 0.0
        self._resumed = False
        self._result = None
        self._pool = None
        if checkpoint_path and os.path.exists(checkpoint_path):
            self._resume(checkpoint_path)
        else:
            for state, probability in model.start():
                sid = self._id_of(state)
                self._start[sid] = probability

    # -- state table ------------------------------------------------------

    def _id_of(self, state) -> int:
        sid = self.state_map.get(state)
        if sid is None:
            sid = len(self.state_map)
            self.state_map[state] = sid
            self.states.append(state)
            self.action_map.append([])
        return sid

    @property
    def n_states(self) -> int:
        return len(self.state_map)

    # -- expansion --------------------------------------------------------

    def _expand(self, frontier: list) -> list[dict]:
        if (self.n_workers <= 1
                or len(frontier) < self.n_workers * self.min_shard):
            return [_expand_states(self.model, frontier,
                                   self.trace_params)]
        if self._pool is None:
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            ctx = multiprocessing.get_context(
                os.environ.get(MP_CONTEXT_ENV_VAR, "spawn"))
            self._pool = ProcessPoolExecutor(
                max_workers=self.n_workers, mp_context=ctx,
                initializer=_worker_init,
                initargs=(self._model_blob, self.trace_params))
        k = self.n_workers
        n = len(frontier)
        shards = [frontier[n * i // k: n * (i + 1) // k]
                  for i in range(k)]
        futs = [self._pool.submit(_worker_expand, s)
                for s in shards if s]
        # deterministic merge: results gathered in shard order
        return [f.result() for f in futs]

    def _absorb(self, lo: int, hi: int, payloads: list[dict]):
        """Merge one round's shard payloads (in shard order), validate
        probability mass, assign ids to the new states in first-sight
        order, and append the round's columns as one bulk chunk."""
        actions: list = []
        for p in payloads:
            actions.extend(p["actions"])
        self.action_map[lo:hi] = actions
        tcounts = np.concatenate([p["tcounts"] for p in payloads])
        total = int(tcounts.sum())
        na = np.asarray([len(a) for a in actions], np.int64)
        # (state, action) of each per-round transition group
        sid_of_group = np.repeat(np.arange(lo, hi, dtype=np.int64), na)
        off = np.cumsum(na) - na
        act_of_group = (np.arange(int(na.sum()), dtype=np.int64)
                        - np.repeat(off, na))
        if (tcounts == 0).any():
            g = int(np.flatnonzero(tcounts == 0)[0])
            state = self.states[int(sid_of_group[g])]
            action = actions[int(sid_of_group[g]) - lo][
                int(act_of_group[g])]
            raise AssertionError((state, action))
        if total == 0:
            return
        val = np.concatenate([p["val"] for p in payloads])
        reward = np.concatenate([p["reward"] for p in payloads])
        progress = np.concatenate([p["progress"] for p in payloads])
        succs: list = []
        for p in payloads:
            succs.extend(p["succs"])
        # vectorized per-round probability-mass validation: transitions
        # are contiguous per (state, action), so group sums are one
        # reduceat over the round's column (tolerance matches
        # sum_to_one: rel 1e-9, no absolute slack)
        starts = np.cumsum(tcounts) - tcounts
        sums = np.add.reduceat(val, starts)
        bad = ~np.isclose(sums, 1.0, rtol=1e-9, atol=0.0)
        if bad.any():
            g = int(np.flatnonzero(bad)[0])
            state = self.states[int(sid_of_group[g])]
            action = actions[int(sid_of_group[g]) - lo][
                int(act_of_group[g])]
            raise AssertionError((state, action))
        src = np.repeat(sid_of_group, tcounts).astype(np.int32)
        act = np.repeat(act_of_group, tcounts).astype(np.int32)
        if payloads[0]["keys"] is not None:
            # vectorized dedup over worker-pickled keys: unique keys,
            # representatives walked in first-occurrence order so new
            # ids land exactly in (source id, action slot, transition
            # order) — the serial first-sight order.  The global
            # _id_of dict lookup runs only on the unique
            # representatives, so a model whose equal states pickle
            # differently loses batching, never correctness.
            # np.asarray over bytes gives a fixed-width 'S' array
            # (pure C sort); trailing-null padding cannot collide
            # because every pickle ends with the non-null STOP opcode.
            keys: list = []
            for p in payloads:
                keys.extend(p["keys"])
            karr = np.asarray(keys)
            uniq, first_idx, inverse = np.unique(
                karr, return_index=True, return_inverse=True)
            uid_gid = np.empty(len(uniq), np.int64)
            for u in np.argsort(first_idx, kind="stable"):
                uid_gid[u] = self._id_of(succs[int(first_idx[u])])
            dst = uid_gid[inverse].astype(np.int32)
        else:
            # inline expansion: no worker parallelism paid for key
            # encoding, so dedup through the state dict directly
            # (exactly the serial compiler's per-successor cost)
            idf = self._id_of
            dst = np.fromiter((idf(s) for s in succs), np.int32,
                              len(succs))
        self._cols.append((src, act, dst, val, reward, progress))
        if self.trace_params:
            self._pcols.append((
                np.concatenate([p["coef"] for p in payloads]),
                np.concatenate([p["expo"] for p in payloads])))

    # -- the round driver -------------------------------------------------

    def _run(self):
        t0 = now()
        try:
            # v15 watermark: the compile's state/column tables live on
            # the host, so this is an RSS watermark on CPU — sampled
            # once per frontier round, `memory` event on exit (crash
            # path included)
            with telemetry.memory_watermark("mdp_compile") as wm:
                while self._explored_upto < len(self.states):
                    self._round += 1
                    resilience.fault_point("compile_round")
                    lo, hi = self._explored_upto, len(self.states)
                    self._absorb(lo, hi,
                                 self._expand(self.states[lo:hi]))
                    self._explored_upto = hi
                    wm.sample()
                    if (self._ck_path
                            and self._round % self._ck_every == 0):
                        self._save_checkpoint()
        finally:
            self._elapsed += now() - t0
            if self._pool is not None:
                self._pool.shutdown()
                self._pool = None

    # -- checkpoint / resume ----------------------------------------------

    def _columns_so_far(self) -> dict:
        cols = {}
        for i, name in enumerate(_COL_NAMES):
            parts = [c[i] for c in self._cols]
            cols[name] = (np.concatenate(parts) if parts else
                          np.zeros(0, np.int32 if i < 3 else np.float64))
        if self.trace_params:
            cols["coef"] = (np.concatenate([c for c, _ in self._pcols])
                            if self._pcols else np.zeros(0, np.float64))
            cols["expo"] = (np.concatenate([e for _, e in self._pcols])
                            if self._pcols
                            else np.zeros((0, 4), np.int16))
        return cols

    def _save_checkpoint(self):
        blob = pickle.dumps(
            {"states": self.states, "action_map": self.action_map,
             "start": self._start}, _PICKLE_PROTO)
        resilience.save_compile_checkpoint(
            self._ck_path, columns=self._columns_so_far(), blob=blob,
            round_idx=self._round, explored_upto=self._explored_upto,
            model_fp=self._model_fp)

    def _resume(self, path: str):
        try:
            st = resilience.load_compile_checkpoint(
                path, model_fp=self._model_fp)
        except resilience.IntegrityError:
            # quarantined + reported by sealed_read; the compile falls
            # back to a cold start — the frontier BFS is deterministic,
            # so the result is bit-identical either way
            return
        tab = pickle.loads(st["blob"])
        self.states = list(tab["states"])
        self.action_map = list(tab["action_map"])
        self._start = dict(tab["start"])
        self.state_map = {s: i for i, s in enumerate(self.states)}
        if len(st["src"]):
            self._cols = [tuple(st[n] for n in _COL_NAMES)]
        if self.trace_params and "coef" in st and len(st["coef"]):
            self._pcols = [(st["coef"], st["expo"])]
        self._round = int(st["round"])
        self._explored_upto = int(st["explored"])
        self._resumed = True
        telemetry.current().event("resume", path=path,
                                  update=self._round)

    # -- results ----------------------------------------------------------

    def mdp(self):
        """Run the compile to exhaustion and return the MDP —
        bit-identical (ids, columns, start map) to
        `Compiler(model).mdp()`.  Emits the schema-v12 `mdp_compile`
        telemetry event and deletes the crash-recovery checkpoint on
        completion."""
        if self._result is not None:
            return self._result
        from cpr_tpu.mdp.explicit import MDP

        self._run()
        m = MDP()
        m.start = dict(self._start)
        for cols in self._cols:
            m.add_transitions(*cols)
        m.n_states = max(m.n_states, len(self.states))
        m.consolidate()
        m.check()
        dt = self._elapsed
        telemetry.current().event(
            "mdp_compile", protocol=self.protocol, cutoff=self.cutoff,
            rounds=self._round, states=len(self.states),
            transitions=m.n_transitions, n_workers=self.n_workers,
            compile_s=round(dt, 6),
            states_per_sec=(round(len(self.states) / dt, 3)
                            if dt > 0 else None),
            resumed=self._resumed)
        if self._ck_path:
            for p in (self._ck_path, self._ck_path + ".json"):
                if os.path.exists(p):
                    os.unlink(p)
        self._result = m
        return m

    def param_mdp(self, *, probe_alpha: float, probe_gamma: float,
                  meta: dict | None = None):
        """The ParamMDP of a `trace_params=True` compile: the base MDP
        already holds the probe-valued float probability column (the
        tracer's per-transition probe values ARE the collected column);
        the (coef, expo) columns were carried through the columnar
        collect round by round.  Matches grid._param_mdp_from on a
        serial tracer compile bit-for-bit."""
        if not self.trace_params:
            raise ValueError("param_mdp() needs trace_params=True")
        from cpr_tpu.mdp.explicit import MDP
        from cpr_tpu.mdp.grid import ParamMDP, _extract_param

        m = self.mdp()
        if self._pcols:
            coef = np.concatenate([c for c, _ in self._pcols])
            expo = np.concatenate([e for _, e in self._pcols])
        else:
            coef = np.zeros(0, np.float64)
            expo = np.zeros((0, 4), np.int16)
        start_ids = np.asarray(sorted(m.start), np.int32)
        start_coef = np.empty(len(start_ids), np.float64)
        start_expo = np.empty((len(start_ids), 4), np.int16)
        for i, sid in enumerate(start_ids):
            start_coef[i], start_expo[i] = _extract_param(
                m.start[int(sid)], f"start prob of state {sid}")
        src, act, dst, prob, reward, progress = m.arrays()
        base = MDP(n_states=m.n_states, n_actions=m.n_actions,
                   start={int(s): float(p) for s, p in m.start.items()},
                   src=src, act=act, dst=dst, prob=prob, reward=reward,
                   progress=progress)
        return ParamMDP(mdp=base, coef=coef, expo=expo,
                        start_ids=start_ids, start_coef=start_coef,
                        start_expo=start_expo, probe_alpha=probe_alpha,
                        probe_gamma=probe_gamma, meta=dict(meta or {}))
