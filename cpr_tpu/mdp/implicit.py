"""Implicit MDP models.

Reference counterpart: mdp/lib/implicit_mdp.py:29-77 (`Model` with
start/actions/apply/shutdown/honest and `Transition{probability, state,
reward, progress, effect}`) and the probabilistic-termination wrapper
(mdp/lib/implicit_mdp.py:80-172) implementing the Bar-Zur et al. AFT'20
PTO horizon: each progress-making transition is split into a continue
branch with probability (1 - 1/H)^progress and a terminal branch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Hashable, Optional


@dataclass(frozen=True)
class Effect:
    """Optional per-transition bookkeeping (mdp/lib/implicit_mdp.py:9-17)."""

    blocks_mined: float = 0.0
    common_atk_reward: float = 0.0
    common_def_reward: float = 0.0
    common_progress: float = 0.0
    defender_rewrite_length: float = 0.0
    defender_rewrite_progress: float = 0.0
    defender_progress: float = 0.0


@dataclass(frozen=True)
class Transition:
    probability: float
    state: Hashable
    reward: float
    progress: float
    effect: Optional[Effect] = None


class Model:
    """Implicit (generative) MDP: states are hashable, transitions lazy."""

    def start(self) -> list[tuple[Hashable, float]]:
        raise NotImplementedError

    def actions(self, state) -> list[Any]:
        raise NotImplementedError

    def apply(self, action, state) -> list[Transition]:
        raise NotImplementedError

    def shutdown(self, state) -> list[Transition]:
        """Fair-shutdown mechanism called at episode end (forces release of
        withheld blocks so probabilistic termination doesn't punish
        risk-taking)."""
        raise NotImplementedError

    def honest(self, state):
        raise NotImplementedError


class PTOWrapper(Model):
    """Probabilistic termination (Bar-Zur et al. AFT'20).

    Progress-making transitions gain a terminal branch with probability
    1 - (1 - 1/horizon)^progress (mdp/lib/implicit_mdp.py:99-132).
    """

    def __init__(self, model: Model, *, horizon: int, terminal_state):
        assert horizon > 0
        assert isinstance(model, Model)
        assert not isinstance(model, PTOWrapper)
        self.unwrapped = model
        self.horizon = horizon
        self.terminal = terminal_state

    def start(self):
        return self.unwrapped.start()

    def actions(self, state):
        if state is self.terminal or state == self.terminal:
            return []
        return self.unwrapped.actions(state)

    def continue_probability(self, progress: float) -> float:
        return (1.0 - 1.0 / self.horizon) ** progress

    def apply(self, action, state):
        out = []
        for t in self.unwrapped.apply(action, state):
            if t.progress == 0.0:
                out.append(t)
                continue
            keep = self.continue_probability(t.progress)
            assert 0.0 < keep < 1.0
            out.append(
                Transition(
                    probability=t.probability * keep,
                    state=t.state,
                    reward=t.reward,
                    progress=t.progress,
                    effect=t.effect,
                )
            )
            out.append(
                Transition(
                    probability=t.probability * (1.0 - keep),
                    state=self.terminal,
                    reward=0.0,
                    progress=0.0,
                )
            )
        return out

    def shutdown(self, state):
        if state is self.terminal or state == self.terminal:
            return []
        out = []
        for t in self.unwrapped.shutdown(state):
            keep = self.continue_probability(t.progress)
            out.append(
                Transition(
                    probability=t.probability * keep,
                    state=t.state,
                    reward=t.reward,
                    progress=t.progress,
                    effect=t.effect,
                )
            )
            out.append(
                Transition(
                    probability=t.probability * (1.0 - keep),
                    state=self.terminal,
                    reward=t.reward,
                    progress=t.progress,
                    effect=t.effect,
                )
            )
        return out

    def honest(self, state):
        return self.unwrapped.honest(state)
