"""GhostDAG spec (k-cluster blue-set chain selection).

Reference counterpart: generic_v1/protocols/ghostdag.py:6-101, itself
after eprint.iacr.org/2018/104.pdf Algorithm 1: recursively pick the tip
with the largest blue past, then greedily admit anticone blocks whose
addition keeps every blue block's blue anticone within k.

The recursion is memoized on (dag, visible-subgraph mask) — subgraph
masks are ints, the DAG is a hashable value, so the cache key is free.
Miners are stateless: the visible set IS the state.
"""

from __future__ import annotations

from functools import lru_cache

from cpr_tpu.mdp.generic.dag import bits_of
from cpr_tpu.mdp.generic.protocols.base import ProtocolSpec


@lru_cache(maxsize=1 << 18)
def _blue_and_history(dag, subgraph: int, k: int):
    """(blue mask, history tuple) of the visible subgraph."""
    if subgraph == 1:  # genesis only
        return 1, (0,)

    def tips(sub):
        return [b for b in bits_of(sub) if not (dag.children(b) & sub)]

    blue, hist = {}, {}
    for t in tips(subgraph):
        past = dag.past(t) & subgraph
        blue[t], hist[t] = _blue_and_history(dag, past, k)
    b_max = min(tips(subgraph), key=lambda t: (-bin(blue[t]).count("1"), t))
    blue_set = blue[b_max] | (1 << b_max)
    history = hist[b_max] + (b_max,)

    def anticone(sub, b):
        return (sub & ~(1 << b) & ~(dag.past(b) & sub)
                & ~(dag.future(b) & sub))

    def is_k_cluster(sub, s_mask):
        for b in bits_of(s_mask):
            if bin(anticone(sub, b) & s_mask).count("1") > k:
                return False
        return True

    ac = anticone(subgraph, b_max)
    for b in sorted(bits_of(ac), key=lambda b: (dag.height(b), b)):
        if is_k_cluster(subgraph, blue_set | (1 << b)):
            blue_set |= 1 << b
            history = history + (b,)
    return blue_set, history


class GhostDag(ProtocolSpec):
    name = "ghostdag"

    def __init__(self, k: int = 3):
        self.k = k

    def init(self, view):
        return None  # stateless: the visible set is the state

    def mining(self, view, pstate):
        return tuple(bits_of(view.tips(view.visible)))

    def update(self, view, pstate, block):
        return None

    def history(self, view, pstate):
        _, hist = _blue_and_history(view.dag, view.visible, self.k)
        return list(hist)

    def progress(self, view, block):
        return 1.0

    def coinbase(self, view, block):
        return [(view.miner_of(block), 1.0)]

    def relabel(self, pstate, new_ids):
        return None

    def color(self, view, pstate, block):
        return 0

    def keep(self, view, pstate):
        return view.tips(view.visible)
