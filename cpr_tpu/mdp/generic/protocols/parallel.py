"""Parallel-voting spec (k votes confirm each block).

Reference counterpart: generic_v1/protocols/parallel.py:6-76.  A "vote"
is a block with exactly one parent; a "block" references k votes (its
parents) once enough votes confirm the head.  k >= 2 is required so the
parent count distinguishes votes from blocks.
"""

from __future__ import annotations

from cpr_tpu.mdp.generic.dag import bits_of
from cpr_tpu.mdp.generic.protocols.base import ProtocolSpec


class Parallel(ProtocolSpec):
    name = "parallel"

    def __init__(self, k: int = 3):
        assert k >= 2, "parallel: need k >= 2 to tell votes from blocks"
        self.k = k

    def is_vote(self, view, block):
        return len(view.parents(block)) == 1

    def init(self, view):
        return view.genesis

    def mining(self, view, head):
        votes = [b for b in bits_of(view.children(head))]
        if len(votes) >= self.k:
            votes.sort(key=lambda v: (view.miner_of(v) != view.me, v))
            return tuple(votes[: self.k])
        return (head,)

    def update(self, view, head, block):
        if self.is_vote(view, block):
            block = view.parents(block)[0]
        bh, hh = view.height(block), view.height(head)
        if bh > hh:
            return block
        if bh == hh and block != head:
            nb = bin(view.children(block)).count("1")
            nh = bin(view.children(head)).count("1")
            if nb > nh:
                return block
        return head

    def history(self, view, head):
        hist = []
        b = head
        while True:
            if not self.is_vote(view, b) or b == view.genesis:
                hist.append(b)
            if b == view.genesis:
                break
            b = view.parents(b)[0]
        hist.reverse()
        return hist

    def progress(self, view, block):
        return float(self.k + 1)

    def coinbase(self, view, block):
        out = [(view.miner_of(block), 1.0)]
        for p in view.parents(block):
            out.append((view.miner_of(p), 1.0))
        return out

    def relabel(self, head, new_ids):
        return new_ids[head]

    def color(self, view, head, block):
        return 1 if block == head else 0

    def keep(self, view, head):
        return (1 << head) | view.children(head)
