"""Ethereum uncle specs: whitepaper and Byzantium variants.

Reference counterpart: generic_v1/protocols/ethereum.py:6-73 (whitepaper:
every leaf whose parent sits within the last `h` history blocks is an
includable uncle, all uncles pay 1) and byzantium.py:6-31 (at most two
uncles, own first; heaviest progress preference; discounted uncle
rewards, nephew bonus 1/32).
"""

from __future__ import annotations

from cpr_tpu.mdp.generic.dag import bits_of
from cpr_tpu.mdp.generic.protocols.base import ProtocolSpec


class Ethereum(ProtocolSpec):
    name = "ethereum"

    def __init__(self, h: int = 7):
        # uncles need room between head and the uncle window: h >= 2
        self.h = h

    # the highest parent is the chain parent, the rest are uncles
    def parent_and_uncles(self, view, block):
        ps = sorted(view.parents(block), key=lambda p: -view.height(p))
        if not ps:
            return None, []
        return ps[0], ps[1:]

    def init(self, view):
        return view.genesis

    def available_uncles(self, view, head):
        hist = self.history(view, head)
        window = set(hist[-self.h - 1:-2])
        uncles = []
        for b in bits_of(view.visible):
            if view.children(b):
                continue  # not a leaf
            p, _ = self.parent_and_uncles(view, b)
            if p is not None and p in window:
                uncles.append(b)
        return uncles

    def mining(self, view, head):
        return tuple([head] + self.available_uncles(view, head))

    def update(self, view, head, block):
        return block if view.height(block) > view.height(head) else head

    def history(self, view, head):
        hist = []
        b = head
        while b is not None:
            hist.append(b)
            if b == view.genesis:
                break
            b, _ = self.parent_and_uncles(view, b)
        hist.reverse()
        return hist

    def progress(self, view, block):
        return 1.0

    def coinbase(self, view, block):
        _, uncles = self.parent_and_uncles(view, block)
        return [(view.miner_of(b), 1.0) for b in [block] + uncles]

    def relabel(self, head, new_ids):
        return new_ids[head]

    def color(self, view, head, block):
        return 1 if block == head else 0

    def keep(self, view, head):
        m = 1 << head
        for u in self.available_uncles(view, head):
            m |= 1 << u
        return m


class Byzantium(Ethereum):
    name = "byzantium"

    def mining(self, view, head):
        uncles = sorted(self.available_uncles(view, head),
                        key=lambda u: (view.miner_of(u) != view.me, u))
        return tuple([head] + uncles[:2])

    def _weight(self, view, block):
        return sum(self.progress(view, b)
                   for b in self.history(view, block)[1:])

    def update(self, view, head, block):
        if self._weight(view, block) > self._weight(view, head):
            return block
        return head

    def progress(self, view, block):
        _, uncles = self.parent_and_uncles(view, block)
        return 1.0 + len(uncles)

    def coinbase(self, view, block):
        _, uncles = self.parent_and_uncles(view, block)
        out = [(view.miner_of(block), 1.0 + 0.03125 * len(uncles))]
        h = view.height(block)
        max_d = self.h + 1
        for u in uncles:
            out.append((view.miner_of(u), (max_d - (h - view.height(u))) / max_d))
        return out
