"""Functional protocol-spec interface.

Reference counterpart: generic_v1/protocols/interface.py:1-117.  The
reference injects DAG accessors into a mutable spec object whose
`self.state` is a free-form DynObj; here a spec is a stateless strategy
object of pure functions over an immutable `View`, and the miner state
`pstate` is an explicit hashable value passed in and returned — which is
what lets the whole MDP state be a flat frozen dataclass.
"""

from __future__ import annotations

from typing import Hashable


class ProtocolSpec:
    """All methods are pure; `view` is a cpr_tpu.mdp.generic.dag.View
    restricted to the miner's visible blocks, `pstate` is the miner's
    protocol state (hashable)."""

    name: str = "?"

    def init(self, view) -> Hashable:
        """Initial miner state at genesis."""
        raise NotImplementedError

    def mining(self, view, pstate) -> tuple[int, ...]:
        """Parents of the block this miner would mine now."""
        raise NotImplementedError

    def update(self, view, pstate, block: int) -> Hashable:
        """New miner state after learning `block` (already in view)."""
        raise NotImplementedError

    def history(self, view, pstate) -> list[int]:
        """The miner's linear block history, genesis first."""
        raise NotImplementedError

    def progress(self, view, block: int) -> float:
        """Difficulty-adjustment progress contributed by a history block."""
        raise NotImplementedError

    def coinbase(self, view, block: int) -> list[tuple[int, float]]:
        """(miner, amount) rewards associated with a history block."""
        raise NotImplementedError

    def relabel(self, pstate, new_ids: dict[int, int]) -> Hashable:
        """Rewrite block ids inside the miner state."""
        raise NotImplementedError

    def color(self, view, pstate, block: int) -> int:
        """0/1 color capturing miner-state info for canonicalization."""
        raise NotImplementedError

    def keep(self, view, pstate) -> int:
        """Bitmask of relevant tips for garbage collection (the kept set
        is closed over parents by the model)."""
        raise NotImplementedError
