"""Protocol specifications for the generic DAG attack model.

Reference counterpart: mdp/lib/models/generic_v1/protocols/ — bitcoin
(bitcoin.py:6-44), ethereum whitepaper uncles (ethereum.py:6-73),
byzantium (byzantium.py:6-31), parallel voting (parallel.py:6-76), and
GhostDAG's k-cluster blue-set rule (ghostdag.py:6-101).
"""

from cpr_tpu.mdp.generic.protocols.base import ProtocolSpec
from cpr_tpu.mdp.generic.protocols.bitcoin import Bitcoin
from cpr_tpu.mdp.generic.protocols.ethereum import Byzantium, Ethereum
from cpr_tpu.mdp.generic.protocols.ghostdag import GhostDag
from cpr_tpu.mdp.generic.protocols.parallel import Parallel

_FACTORIES = {
    "bitcoin": Bitcoin,
    "ethereum": Ethereum,
    "byzantium": Byzantium,
    "parallel": Parallel,
    "ghostdag": GhostDag,
}


def protocol_names():
    return sorted(_FACTORIES)


def get_protocol(name: str, **kwargs) -> ProtocolSpec:
    try:
        factory = _FACTORIES[name]
    except KeyError:
        raise KeyError(
            f"unknown protocol '{name}'; choose from {protocol_names()}")
    return factory(**kwargs)


__all__ = [
    "ProtocolSpec",
    "Bitcoin",
    "Ethereum",
    "Byzantium",
    "Parallel",
    "GhostDag",
    "get_protocol",
    "protocol_names",
]
