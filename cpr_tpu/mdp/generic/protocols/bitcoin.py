"""Bitcoin (longest chain) spec.

Reference counterpart: generic_v1/protocols/bitcoin.py:6-44.
"""

from __future__ import annotations

from cpr_tpu.mdp.generic.protocols.base import ProtocolSpec


class Bitcoin(ProtocolSpec):
    name = "bitcoin"

    def init(self, view):
        return view.genesis  # pstate = preferred head

    def mining(self, view, head):
        return (head,)

    def update(self, view, head, block):
        return block if view.height(block) > view.height(head) else head

    def history(self, view, head):
        hist = []
        b = head
        while True:
            hist.append(b)
            if b == view.genesis:
                break
            b = view.parents(b)[0]
        hist.reverse()
        return hist

    def progress(self, view, block):
        return 1.0

    def coinbase(self, view, block):
        return [(view.miner_of(block), 1.0)]

    def relabel(self, head, new_ids):
        return new_ids[head]

    def color(self, view, head, block):
        return 1 if block == head else 0

    def keep(self, view, head):
        return 1 << head
