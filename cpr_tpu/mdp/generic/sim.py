"""Cross-validation simulators for the generic protocol specs.

Reference counterpart: mdp/lib/models/generic_v1/sim.py:5-131 —
SingleMinerSim (one miner extends its own chain; sanity-checks reward
and progress accounting) and NetworkSim (a small discrete-event network
of miners with sampled mining and message delays, judged by an
omniscient observer).  These validate the protocol specs independently
of the attack model: honest networks must pay each miner its compute
share and keep progress consistent.

Built on the same immutable GDag/View machinery as the model, so a spec
that passes here exercises exactly the code the MDP compiler uses.
"""

from __future__ import annotations

import heapq
import random
from typing import Callable

from cpr_tpu.mdp.generic.dag import GDag, View
from cpr_tpu.mdp.generic.protocols.base import ProtocolSpec


class SingleMinerSim:
    """One miner, no network: every block is delivered instantly."""

    def __init__(self, proto: ProtocolSpec):
        self.proto = proto
        self.dag = GDag.genesis_dag()
        self.visible = 1
        self.pstate = proto.init(View(self.dag, 1, 0))

    def view(self) -> View:
        return View(self.dag, self.visible, 0)

    def step(self):
        parents = self.proto.mining(self.view(), self.pstate)
        self.dag, b = self.dag.append(parents, 0)
        self.visible |= 1 << b
        self.pstate = self.proto.update(self.view(), self.pstate, b)

    def reward_and_progress(self):
        view = self.view()
        hist = self.proto.history(view, self.pstate)
        rew = prg = 0.0
        for b in hist[1:]:
            prg += self.proto.progress(view, b)
            for _, amount in self.proto.coinbase(view, b):
                rew += amount
        return rew, prg

    def run(self, max_progress: float):
        rew = prg = 0.0
        while prg < max_progress:
            self.step()
            rew, prg = self.reward_and_progress()
        return rew, prg


class NetworkSim:
    """Discrete-event network of honest miners running a protocol spec;
    an omniscient judge miner scores the final history
    (generic_v1/sim.py:54-131)."""

    def __init__(self, proto: ProtocolSpec, *, n_miners: int,
                 mining_delay: Callable[[random.Random], float],
                 select_miner: Callable[[random.Random], int],
                 message_delay: Callable[[random.Random], float],
                 seed: int = 0):
        self.proto = proto
        self.rng = random.Random(seed)
        self.n_miners = n_miners
        self.dag = GDag.genesis_dag()
        self.visible = [1] * n_miners  # per-miner bitmask
        self.pstates = [proto.init(View(self.dag, 1, i))
                        for i in range(n_miners)]
        self.mining_delay = mining_delay
        self.select_miner = select_miner
        self.message_delay = message_delay
        self.clock = 0.0
        self._seq = 0
        self.queue: list = []
        self._push(self.mining_delay(self.rng), ("mine",))

    def _push(self, delay, event):
        heapq.heappush(self.queue, (self.clock + delay, self._seq, event))
        self._seq += 1

    def _view(self, i) -> View:
        return View(self.dag, self.visible[i], i)

    def _deliver(self, i, b):
        if self.visible[i] & (1 << b):
            return
        for p in self.dag.parents[b]:  # in-order delivery
            self._deliver(i, p)
        self.visible[i] |= 1 << b
        self.pstates[i] = self.proto.update(self._view(i),
                                            self.pstates[i], b)

    def _mine(self):
        m = self.select_miner(self.rng)
        parents = self.proto.mining(self._view(m), self.pstates[m])
        self.dag, b = self.dag.append(parents, m)
        self._deliver(m, b)
        for i in range(self.n_miners):
            if i != m:
                self._push(self.message_delay(self.rng),
                           ("recv", i, b))
        self._push(self.mining_delay(self.rng), ("mine",))

    def step(self):
        self.clock, _, event = heapq.heappop(self.queue)
        if event[0] == "mine":
            self._mine()
        else:
            _, i, b = event
            self._deliver(i, b)

    def judge(self):
        """Omniscient scoring: per-miner rewards + progress of the full
        visibility history."""
        view = View(self.dag, self.dag.all_mask(), -1)
        # replay deliveries in topological order
        vis = 1
        judge_state = self.proto.init(View(GDag.genesis_dag(), 1, -1))
        for b in range(1, self.dag.size()):
            vis |= 1 << b
            judge_state = self.proto.update(
                View(self.dag, vis, -1), judge_state, b)
        hist = self.proto.history(view, judge_state)
        rewards = [0.0] * self.n_miners
        prg = 0.0
        for b in hist[1:]:
            prg += self.proto.progress(view, b)
            for miner, amount in self.proto.coinbase(view, b):
                if 0 <= miner < self.n_miners:
                    rewards[miner] += amount
        return dict(time=self.clock, blocks=self.dag.size(),
                    rewards=rewards, progress=prg)

    def run(self, max_progress: float):
        # judging replays the DAG; amortize by checking periodically
        while True:
            for _ in range(16):
                self.step()
            out = self.judge()
            if out["progress"] >= max_progress:
                return out
