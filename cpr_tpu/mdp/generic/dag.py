"""Immutable small-DAG value type + visibility-filtered views.

Reference counterpart: the mutable `DAG` class and visibility-filtered
`Miner` wrapper of mdp/lib/models/generic_v1/model.py:15-311.  The
reference mutates shared adjacency lists and freezes objects before
hashing them with xxhash; here a DAG is a frozen value — nested parent
tuples plus a miner tuple — so states hash and compare structurally for
free, and per-DAG derived data (children, heights) is memoized on the
value itself via lru_cache.

Block ids are dense ints, topologically ordered (id of a child is larger
than the ids of all its parents); block 0 is the genesis.  Sets of blocks
travel as int bitmasks (bit b = block b), which keeps the whole model
state hashable and makes set algebra single integer ops.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache


def bits_of(mask: int):
    """Iterate the set bits of a mask, ascending (= topological order)."""
    while mask:
        low = mask & -mask
        yield low.bit_length() - 1
        mask ^= low


def mask_of(blocks) -> int:
    m = 0
    for b in blocks:
        m |= 1 << b
    return m


@dataclass(frozen=True)
class GDag:
    """parents[b] is the sorted tuple of b's parents; miners[b] is the
    miner id (genesis: -1)."""

    parents: tuple[tuple[int, ...], ...]
    miners: tuple[int, ...]

    @staticmethod
    def genesis_dag() -> "GDag":
        return GDag(parents=((),), miners=(-1,))

    @property
    def genesis(self) -> int:
        return 0

    def size(self) -> int:
        return len(self.parents)

    def all_mask(self) -> int:
        return (1 << self.size()) - 1

    def append(self, parents, miner: int) -> tuple["GDag", int]:
        """Value-append: returns (new dag, new block id)."""
        ps = tuple(sorted(parents))
        assert all(0 <= p < self.size() for p in ps), (ps, self.size())
        return (
            GDag(self.parents + (ps,), self.miners + (miner,)),
            self.size(),
        )

    def children(self, block: int) -> int:
        return _children(self)[block]

    def height(self, block: int) -> int:
        return _heights(self)[block]

    def past(self, block: int) -> int:
        """Bitmask of all ancestors of `block` (excluding it)."""
        return _pasts(self)[block]

    def future(self, block: int) -> int:
        """Bitmask of all descendants of `block` (excluding it)."""
        acc = 0
        stack = self.children(block)
        while stack:
            b = stack & -stack
            stack ^= b
            if not acc & b:
                acc |= b
                stack |= self.children(b.bit_length() - 1) & ~acc
        return acc

    def topo_sorted(self, mask: int) -> list[int]:
        """Blocks of `mask` in topological (= id) order; ids are kept
        topologically sorted as a class invariant."""
        return list(bits_of(mask))

    def relabel(self, order: list[int]) -> tuple["GDag", dict[int, int]]:
        """Rebuild the DAG keeping exactly the blocks in `order` (which
        must be topologically sorted and closed under parents within
        itself); returns (new dag, old id -> new id)."""
        new_ids = {b: i for i, b in enumerate(order)}
        parents = tuple(
            tuple(sorted(new_ids[p] for p in self.parents[b] if p in new_ids))
            for b in order
        )
        miners = tuple(
            -1 if i == 0 else self.miners[b] for i, b in enumerate(order)
        )
        return GDag(parents=parents, miners=miners), new_ids


@lru_cache(maxsize=1 << 16)
def _children(dag: GDag) -> tuple[int, ...]:
    ch = [0] * dag.size()
    for b, ps in enumerate(dag.parents):
        for p in ps:
            ch[p] |= 1 << b
    return tuple(ch)


@lru_cache(maxsize=1 << 16)
def _heights(dag: GDag) -> tuple[int, ...]:
    h = [0] * dag.size()
    for b, ps in enumerate(dag.parents):
        for p in ps:
            h[b] = max(h[b], h[p] + 1)
    return tuple(h)


@lru_cache(maxsize=1 << 16)
def _pasts(dag: GDag) -> tuple[int, ...]:
    pa = [0] * dag.size()
    for b, ps in enumerate(dag.parents):
        for p in ps:
            pa[b] |= pa[p] | (1 << p)
    return tuple(pa)


@dataclass(frozen=True)
class View:
    """A miner's visibility-filtered window onto a DAG (the reference's
    `Miner` children-filtering, generic_v1/model.py:261-265): parents are
    always fully visible (delivery is topological), children are
    restricted to the visible set."""

    dag: GDag
    visible: int  # bitmask
    me: int  # miner id (judge views use -1)

    @property
    def genesis(self) -> int:
        return 0

    def parents(self, block: int) -> tuple[int, ...]:
        return self.dag.parents[block]

    def children(self, block: int) -> int:
        return self.dag.children(block) & self.visible

    def height(self, block: int) -> int:
        return self.dag.height(block)

    def miner_of(self, block: int) -> int:
        return self.dag.miners[block]

    def tips(self, subgraph: int) -> int:
        """Blocks of `subgraph` without visible children in `subgraph`."""
        acc = 0
        for b in bits_of(subgraph):
            if not (self.dag.children(b) & subgraph):
                acc |= 1 << b
        return acc

    def past_in(self, subgraph: int, block: int) -> int:
        return self.dag.past(block) & subgraph

    def future_in(self, subgraph: int, block: int) -> int:
        return self.dag.future(block) & subgraph

    def anticone(self, subgraph: int, block: int) -> int:
        return (
            subgraph
            & ~(1 << block)
            & ~self.past_in(subgraph, block)
            & ~self.future_in(subgraph, block)
        )
