"""Canonical labeling of colored DAGs, self-contained.

Reference counterpart: generic_v1/model.py:591-682 delegates canonical
labeling to pynauty (the nauty C library) and then repairs topological
order.  This environment does not ship pynauty, and the DAGs here are
tiny (garbage collection + common-chain truncation keep them to a
handful of blocks), so a compact individualization-refinement search is
both sufficient and dependency-free:

1. refine: iterate colors to the coarsest stable partition where a
   vertex's color determines the multiset of its parent and child colors
   (directed 1-WL refinement);
2. individualize: if the partition is not discrete, branch over every
   vertex of the first non-singleton cell (an isomorphism-invariant
   choice), giving it a fresh color, and recurse;
3. certificate: each discrete partition yields an ordering; keep the
   ordering whose relabeled (color, parent-set) rows are lexicographically
   smallest.

Isomorphic colored DAGs produce identical certificates, so relabeling by
the canonical order merges isomorphic MDP states exactly like the
reference's nauty path does.
"""

from __future__ import annotations

from functools import lru_cache


def _refine(n, parents, children, colors):
    """Directed color refinement to a stable partition; colors are dense
    ranks, refining the input coloring."""
    while True:
        if len(set(colors)) == n:
            return colors  # already discrete
        sig = [
            (
                colors[v],
                tuple(sorted(colors[p] for p in parents[v])),
                tuple(sorted(colors[c] for c in children[v])),
            )
            for v in range(n)
        ]
        rank = {s: i for i, s in enumerate(sorted(set(sig)))}
        new = [rank[s] for s in sig]
        if new == colors:
            return colors
        colors = new


def _certificate(order, parents, orig_colors):
    new_id = {b: i for i, b in enumerate(order)}
    return tuple(
        (orig_colors[b], tuple(sorted(new_id[p] for p in parents[b])))
        for b in order
    )


def _search(n, parents, children, colors, orig_colors):
    colors = _refine(n, parents, children, colors)
    cells: dict[int, list[int]] = {}
    for v, c in enumerate(colors):
        cells.setdefault(c, []).append(v)
    target = None
    for c in sorted(cells):
        if len(cells[c]) > 1:
            target = cells[c]
            break
    if target is None:
        order = sorted(range(n), key=lambda v: colors[v])
        return _certificate(order, parents, orig_colors), order
    best = None
    for v in target:
        branched = list(colors)
        branched[v] = n  # fresh color, larger than every rank
        cand = _search(n, parents, children, branched, orig_colors)
        if best is None or cand[0] < best[0]:
            best = cand
    return best


@lru_cache(maxsize=1 << 16)
def canonical_order(parents: tuple[tuple[int, ...], ...],
                    colors: tuple[int, ...],
                    heights: tuple[int, ...]) -> tuple[int, ...]:
    """Canonical, topologically-sorted ordering of a colored DAG.

    The raw canonical order ignores the model's invariant that block ids
    are topologically sorted; sorting blocks by (height, canonical rank)
    restores it while remaining a deterministic function of canonical
    data — so the result is still canonical (generic_v1/model.py:627-645
    repairs nauty's labels the same way, for the same reason).
    """
    n = len(parents)
    if len(set(colors)) == n:
        # colors already discrete: they ARE a canonical rank, so sort
        # directly on (height, color) without any search
        return tuple(sorted(range(n),
                            key=lambda b: (heights[b], colors[b])))
    children: list[list[int]] = [[] for _ in range(n)]
    for b, ps in enumerate(parents):
        for p in ps:
            children[p].append(b)
    rank = {c: i for i, c in enumerate(sorted(set(colors)))}
    start = [rank[c] for c in colors]
    _, order = _search(n, parents, children, start, colors)
    pos = {b: i for i, b in enumerate(order)}
    return tuple(sorted(range(n), key=lambda b: (heights[b], pos[b])))
