"""Generic DAG-protocol attack models (single-agent Release/Consider/
Continue MDPs over explicit block DAGs).

Reference counterpart: mdp/lib/models/generic_v1/ — the generic
single-agent attack model (model.py:339-530), state canonicalization
(model.py:591-682), garbage collection and common-chain truncation
(model.py:971-1117), and the protocol specs bitcoin/ethereum/byzantium/
parallel/ghostdag (protocols/).

TPU-first split: all of this is *compile-time* host work — BFS state
enumeration with hashing and canonical labeling is inherently dynamic and
does not belong under jit.  The output is a flat transition table (COO
tensors) that the jitted segment-sum value iteration and the mesh-sharded
solver (cpr_tpu/mdp/explicit.py, cpr_tpu/parallel) chew on.  Unlike the
reference, states here are immutable hashable values (visibility sets as
int bitmasks, parent lists as nested tuples) so fingerprinting is plain
`hash`, and canonical labeling is a self-contained individualization-
refinement search instead of a pynauty dependency.
"""

from cpr_tpu.mdp.generic.dag import GDag, View
from cpr_tpu.mdp.generic.model import (
    Continue,
    Consider,
    Release,
    SingleAgent,
)
from cpr_tpu.mdp.generic.protocols import get_protocol, protocol_names

__all__ = [
    "GDag",
    "View",
    "SingleAgent",
    "Release",
    "Consider",
    "Continue",
    "get_protocol",
    "protocol_names",
]
