"""Native (C++) generic-MDP compiler bindings.

The Python `SingleAgent` + `Compiler` pair is the semantic anchor; this
module drives the C++ twin (cpr_tpu/native/src/generic_compiler.cpp)
through ctypes for the state spaces the capstone needs (BASELINE.md
config 5: GhostDAG at millions of transitions), where the host-side
Python BFS is ~100x too slow on one core.  Parity is enforced by tests:
state/transition counts and VI start values must match the Python
compiler exactly on overlapping (small) cutoffs.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from cpr_tpu.mdp.explicit import MDP
from cpr_tpu.native import load_lib

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native",
                    "src", "generic_compiler.cpp")
_SO = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native",
                   "libgeneric_compiler.so")

_GC_MODES = {None: 0, "simple": 1, "judge": 2}


def lib() -> ctypes.CDLL:
    L = load_lib(_SRC, _SO, opt="-O3")
    if getattr(L, "_gmc_bound", False):
        return L
    L.gmc_compile.restype = ctypes.c_void_p
    L.gmc_compile.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_double, ctypes.c_double,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int64,
    ]
    for f in ("gmc_n_states", "gmc_n_transitions", "gmc_n_start"):
        getattr(L, f).restype = ctypes.c_int64
        getattr(L, f).argtypes = [ctypes.c_void_p]
    L.gmc_error.restype = ctypes.c_char_p
    L.gmc_error.argtypes = [ctypes.c_void_p]
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    L.gmc_copy.restype = None
    L.gmc_copy.argtypes = [ctypes.c_void_p, i32p, i32p, i32p,
                           f64p, f64p, f64p]
    L.gmc_copy_start.restype = None
    L.gmc_copy_start.argtypes = [ctypes.c_void_p, i32p, f64p]
    L.gmc_free.restype = None
    L.gmc_free.argtypes = [ctypes.c_void_p]
    L._gmc_bound = True
    return L


def compile_native(
    proto: str = "ghostdag",
    *,
    k: int = 2,
    alpha: float,
    gamma: float,
    collect_garbage: str | None = "simple",
    dag_size_cutoff: int | None = None,
    traditional_height_cutoff: int | None = None,
    loop_honest: bool = False,
    merge_isomorphic: bool = True,
    truncate_common_chain: bool = True,
    reward_common_chain: bool = False,
    force_consider_own: bool = False,
    max_states: int = 50_000_000,
) -> MDP:
    """BFS-compile the generic model natively; same flags as
    `SingleAgent`, same MDP container out (numpy-backed columns).

    Protocols: bitcoin, ghostdag (k = cluster size), parallel (k =
    votes), ethereum / byzantium (k = uncle window h, default 7).
    """
    L = lib()
    h = L.gmc_compile(
        proto.encode(), k, alpha, gamma,
        -1 if dag_size_cutoff is None else dag_size_cutoff,
        -1 if traditional_height_cutoff is None
        else traditional_height_cutoff,
        _GC_MODES[collect_garbage], int(merge_isomorphic),
        int(truncate_common_chain), int(loop_honest),
        int(reward_common_chain), int(force_consider_own), max_states)
    if not h:
        raise RuntimeError(
            f"native compile failed: {L.gmc_error(None).decode()}")
    try:
        err = L.gmc_error(h)
        if err:
            raise RuntimeError(f"native compile failed: {err.decode()}")
        nt = L.gmc_n_transitions(h)
        ns = L.gmc_n_start(h)
        src = np.empty(nt, np.int32)
        act = np.empty(nt, np.int32)
        dst = np.empty(nt, np.int32)
        prob = np.empty(nt, np.float64)
        reward = np.empty(nt, np.float64)
        progress = np.empty(nt, np.float64)
        L.gmc_copy(h, src, act, dst, prob, reward, progress)
        sid = np.empty(ns, np.int32)
        sp = np.empty(ns, np.float64)
        L.gmc_copy_start(h, sid, sp)
        mdp = MDP(
            n_states=int(L.gmc_n_states(h)),
            n_actions=int(act.max()) + 1 if nt else 0,
            start={int(s): float(p) for s, p in zip(sid, sp)},
            src=src, act=act, dst=dst, prob=prob, reward=reward,
            progress=progress)
        # same invariant gate every Python-compiled table passes through
        # (compiler.py mdp() -> check()); vectorized, ~1s at 4M rows
        mdp.check()
        return mdp
    finally:
        L.gmc_free(h)


__all__ = ["compile_native", "lib"]
