"""Generic single-agent DAG attack model (Release/Consider/Continue).

Reference counterpart: generic_v1/model.py — SingleAgentImp's action
machinery (:339-530), the SingleAgent implicit MDP with alpha/gamma
randomness (:729-969), garbage collection (:971-1026), honest-loop and
common-chain truncation (:1028-1118), and isomorphic-state merging via
canonical relabeling (:591-682).

Modeled after Sapirshtein et al. FC'16 and Bar-Zur et al. AFT'20: one
attacker (miner 0) plays against one defender (miner 1) on an explicit
block DAG.  The attacker *ignores* blocks until it Considers them (its
protocol state advances lazily) and *withholds* its own blocks until it
Releases them; Continue rolls the communication (gamma) and mining
(alpha) randomness.

Everything here is host-side compile-time work; the compiled transition
table is what runs on TPU (jitted/sharded value iteration).  The state
is one flat frozen dataclass — DAG value + four bitmask sets + two
protocol states — so hashing, equality, and memoization need no manual
fingerprinting, unlike the reference's freeze()/xxhash discipline.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Hashable, Optional

from cpr_tpu.mdp.generic.canon import canonical_order
from cpr_tpu.mdp.generic.dag import GDag, View, bits_of
from cpr_tpu.mdp.generic.protocols.base import ProtocolSpec
from cpr_tpu.mdp.implicit import Model, Transition

ATTACKER, DEFENDER = 0, 1


@dataclass(frozen=True)
class Release:
    block: int


@dataclass(frozen=True)
class Consider:
    block: int


@dataclass(frozen=True)
class Continue:
    pass


@dataclass(frozen=True)
class AgentState:
    dag: GDag
    avis: int  # attacker-visible bitmask
    dvis: int  # defender-visible bitmask
    withheld: int  # attacker blocks not yet released
    ignored: int  # blocks the attacker has not Considered yet
    astate: Hashable  # attacker protocol state
    dstate: Hashable  # defender protocol state

    def aview(self) -> View:
        return View(self.dag, self.avis, ATTACKER)

    def dview(self) -> View:
        return View(self.dag, self.dvis, DEFENDER)


def _initial_state(proto: ProtocolSpec) -> AgentState:
    dag = GDag.genesis_dag()
    av = View(dag, 1, ATTACKER)
    dv = View(dag, 1, DEFENDER)
    return AgentState(dag=dag, avis=1, dvis=1, withheld=0, ignored=0,
                      astate=proto.init(av), dstate=proto.init(dv))


class SingleAgent(Model):
    """Implicit MDP over AgentState; plug into cpr_tpu.mdp.Compiler /
    PTOWrapper and solve with the jitted (or mesh-sharded) VI."""

    def __init__(
        self,
        proto: ProtocolSpec,
        *,
        alpha: float,
        gamma: float,
        collect_garbage: Optional[str] = "simple",  # None|"simple"|"judge"
        dag_size_cutoff: Optional[int] = None,
        traditional_height_cutoff: Optional[int] = None,
        loop_honest: bool = False,
        merge_isomorphic: bool = True,
        truncate_common_chain: bool = True,
        reward_common_chain: bool = False,
        force_consider_own: bool = False,
    ):
        assert 0.0 <= alpha <= 1.0 and 0.0 <= gamma <= 1.0
        assert collect_garbage in (None, "simple", "judge")
        if truncate_common_chain and loop_honest:
            raise ValueError(
                "choose either truncate_common_chain or loop_honest")
        # NOTE: loop_honest closes the state space only when honest play
        # reaches the snap condition (clean linear history, fresh tip) —
        # true for bitcoin, NOT for uncle-/vote-bearing protocols
        # (ethereum/byzantium/parallel/ghostdag), where the BFS is then
        # unbounded below the dag_size_cutoff growth guard.  Use
        # truncate_common_chain for those (generic_v1/model.py:1028-71
        # has the same reach).
        if reward_common_chain and not truncate_common_chain:
            raise ValueError(
                "reward_common_chain requires truncate_common_chain")
        self.proto = proto
        self.alpha = alpha
        self.gamma = gamma
        self.collect_garbage = collect_garbage
        self.dag_size_cutoff = dag_size_cutoff
        self.traditional_height_cutoff = traditional_height_cutoff
        self.loop_honest = loop_honest
        self.merge_isomorphic = merge_isomorphic
        self.truncate_common_chain = truncate_common_chain
        self.reward_common_chain = reward_common_chain
        self.force_consider_own = force_consider_own

        if loop_honest:
            self.reset_attacker = self._normalize_opt(
                self._mine(_initial_state(proto), ATTACKER))
            self.reset_defender = self._normalize_opt(
                self._mine(_initial_state(proto), DEFENDER))
        else:
            self.start_state = self._normalize_opt(_initial_state(proto))

    # -- elementary moves ------------------------------------------------

    def _deliver_defender(self, s: AgentState, block: int) -> AgentState:
        assert not s.dvis & (1 << block), "deliver once"
        assert all(s.dvis & (1 << p) for p in s.dag.parents[block])
        dvis = s.dvis | (1 << block)
        dstate = self.proto.update(View(s.dag, dvis, DEFENDER),
                                   s.dstate, block)
        return replace(s, dvis=dvis, dstate=dstate)

    def _do_consider(self, s: AgentState, block: int) -> AgentState:
        assert s.ignored & (1 << block)
        avis = s.avis | (1 << block)
        astate = self.proto.update(View(s.dag, avis, ATTACKER),
                                   s.astate, block)
        return replace(s, ignored=s.ignored & ~(1 << block),
                       avis=avis, astate=astate)

    def _do_release(self, s: AgentState, block: int) -> AgentState:
        assert s.withheld & (1 << block)
        return replace(s, withheld=s.withheld & ~(1 << block))

    def _just_released(self, s: AgentState) -> int:
        """Released attacker blocks the defender has not seen."""
        mined_by_atk = 0
        for b in range(1, s.dag.size()):
            if s.dag.miners[b] == ATTACKER:
                mined_by_atk |= 1 << b
        return mined_by_atk & ~s.withheld & ~s.dvis

    def _defender_fresh(self, s: AgentState) -> int:
        """Defender blocks the defender has not seen yet (its own mining
        reaches it with the next communication round)."""
        mined_by_def = 0
        for b in range(1, s.dag.size()):
            if s.dag.miners[b] == DEFENDER:
                mined_by_def |= 1 << b
        return mined_by_def & ~s.dvis

    def _do_communication(self, s: AgentState, atk_fast: bool) -> AgentState:
        released = s.dag.topo_sorted(self._just_released(s))
        fresh = s.dag.topo_sorted(self._defender_fresh(s))
        order = released + fresh if atk_fast else fresh + released
        for b in order:
            s = self._deliver_defender(s, b)
        return s

    def _mine(self, s: AgentState, miner: int) -> AgentState:
        if miner == ATTACKER:
            parents = self.proto.mining(s.aview(), s.astate)
            dag, b = s.dag.append(parents, ATTACKER)
            s = replace(s, dag=dag, ignored=s.ignored | (1 << b),
                        withheld=s.withheld | (1 << b))
            if self.force_consider_own:
                s = self._do_consider(s, b)
            return s
        parents = self.proto.mining(s.dview(), s.dstate)
        dag, b = s.dag.append(parents, DEFENDER)
        return replace(s, dag=dag, ignored=s.ignored | (1 << b))

    # -- action surface --------------------------------------------------

    def _to_release(self, s: AgentState) -> list[int]:
        return [b for b in bits_of(s.withheld)
                if not any(s.withheld & (1 << p) for p in s.dag.parents[b])]

    def _to_consider(self, s: AgentState) -> list[int]:
        return [b for b in bits_of(s.ignored)
                if not any(s.ignored & (1 << p) for p in s.dag.parents[b])]

    def actions(self, s: AgentState):
        if self.traditional_height_cutoff is not None:
            if max(s.dag.height(b)
                   for b in range(s.dag.size())) >= self.traditional_height_cutoff:
                return [self.honest(s)]
        if self.dag_size_cutoff is not None:
            if s.dag.size() >= self.dag_size_cutoff:
                return [self.honest(s)]
        acts: list = [Consider(b) for b in self._to_consider(s)]
        acts += [Release(b) for b in self._to_release(s)]
        acts.append(Continue())
        return acts

    def honest(self, s: AgentState):
        """Consider first (lowest id), then release, then continue —
        honest nodes neither ignore nor withhold."""
        tc = self._to_consider(s)
        if tc:
            return Consider(tc[0])
        tr = self._to_release(s)
        if tr:
            return Release(tr[0])
        return Continue()

    def start(self):
        if self.loop_honest:
            return [(self.reset_attacker, self.alpha),
                    (self.reset_defender, 1.0 - self.alpha)]
        return [(self.start_state, 1.0)]

    # -- transitions -----------------------------------------------------

    def apply(self, action, s: AgentState):
        if isinstance(action, Release):
            return self._finalize(s, [
                (1.0, self._do_release(s, action.block))])
        if isinstance(action, Consider):
            return self._finalize(s, [
                (1.0, self._do_consider(s, action.block))])
        assert isinstance(action, Continue)
        a, g = self.alpha, self.gamma
        cases = []
        for p_comm, fast in ((g, True), (1.0 - g, False)):
            for p_mine, miner in ((a, ATTACKER), (1.0 - a, DEFENDER)):
                if p_comm * p_mine == 0.0:
                    continue
                nxt = self._mine(self._do_communication(s, fast), miner)
                cases.append((p_comm * p_mine, nxt))
        return self._finalize(s, cases)

    def shutdown(self, s: AgentState):
        cases = []
        for p, fast in ((self.gamma, True), (1.0 - self.gamma, False)):
            if p == 0.0:
                continue
            nxt = self._do_communication(replace(s, withheld=0), fast)
            cases.append((p, nxt))
        return self._finalize(s, cases)

    # -- reward + state-space reduction ----------------------------------

    def _measure(self, s: AgentState, hist: list[int]):
        """(attacker reward, progress) summed over non-genesis history
        blocks, judged by the defender's view."""
        view = s.dview()
        rew = prg = 0.0
        for b in hist:
            prg += self.proto.progress(view, b)
            for miner, amount in self.proto.coinbase(view, b):
                if miner == ATTACKER:
                    rew += amount
        return rew, prg

    def _finalize(self, old: AgentState, cases):
        if not self.reward_common_chain:
            old_hist = self.proto.history(old.dview(), old.dstate)
            assert old_hist[0] == 0
            old_rew, old_prg = self._measure(old, old_hist[1:])

        out = []
        for prob, new in cases:
            rew = prg = 0.0
            if not self.reward_common_chain:
                new_hist = self.proto.history(new.dview(), new.dstate)
                assert new_hist[0] == 0
                new_rew, new_prg = self._measure(new, new_hist[1:])
                rew, prg = new_rew - old_rew, new_prg - old_prg

            if self.collect_garbage:
                new = self._gc(new)
            if self.loop_honest:
                new = self._loop_honest(new)
            if self.truncate_common_chain:
                pre = new
                new, cut_hist = self._truncate(new)
                if self.reward_common_chain:
                    rew, prg = self._measure(pre, cut_hist)
            new = self._normalize_opt(new)
            out.append(Transition(probability=prob, state=new,
                                  reward=rew, progress=prg))
        return out

    def _relabel(self, s: AgentState, order: list[int]) -> AgentState:
        dag, new_ids = s.dag.relabel(order)

        def remap(mask: int) -> int:
            out = 0
            for b in bits_of(mask):
                if b in new_ids:
                    out |= 1 << new_ids[b]
            return out

        return AgentState(
            dag=dag,
            avis=remap(s.avis), dvis=remap(s.dvis),
            withheld=remap(s.withheld), ignored=remap(s.ignored),
            astate=self.proto.relabel(s.astate, new_ids),
            dstate=self.proto.relabel(s.dstate, new_ids),
        )

    def _gc(self, s: AgentState) -> AgentState:
        """Drop stale blocks: keep anything still undelivered to one of
        the parties, anything a protocol view marks relevant (plus, in
        "judge" mode, what an omniscient defender would keep), closed
        over ancestry (generic_v1/model.py:971-1026)."""
        every = s.dag.all_mask()
        keep = (every & ~s.avis) | (every & ~s.dvis)
        keep |= self.proto.keep(s.aview(), s.astate)
        keep |= self.proto.keep(s.dview(), s.dstate)
        if self.collect_garbage == "judge":
            dstate, dvis = s.dstate, s.dvis
            for b in s.dag.topo_sorted(every & ~dvis):
                dvis |= 1 << b
                dstate = self.proto.update(
                    View(s.dag, dvis, DEFENDER), dstate, b)
            keep |= self.proto.keep(View(s.dag, dvis, DEFENDER), dstate)
        keep |= 1  # genesis
        closed = keep
        for b in bits_of(keep):
            closed |= s.dag.past(b)
        if closed == every:
            return s
        return self._relabel(s, s.dag.topo_sorted(closed))

    def _truncate(self, s: AgentState):
        """Chop the common history prefix, making its last viable block
        the new genesis (generic_v1/model.py:1073-1118).  Returns
        (state, old-history-prefix-that-was-cut) — the prefix feeds
        reward_common_chain accounting."""
        atk = self.proto.history(s.aview(), s.astate)
        dfn = self.proto.history(s.dview(), s.dstate)
        assert atk[0] == 0 and dfn[0] == 0
        next_genesis = 0
        for i in range(1, min(len(atk), len(dfn))):
            b = atk[i]
            if b != dfn[i]:
                break
            past = s.dag.past(b)
            past_and_b = past | (1 << b)
            viable = all(
                (s.dag.children(p) & ~past_and_b) == 0
                for p in bits_of(past))
            if viable:
                next_genesis = b
        if next_genesis == 0:
            return s, []
        cut = []
        for b in dfn[1:]:
            cut.append(b)
            if b == next_genesis:
                break
        keep_mask = (1 << next_genesis) | s.dag.future(next_genesis)
        truncated = self._relabel(s, s.dag.topo_sorted(keep_mask))
        return truncated, cut

    def _loop_honest(self, s: AgentState) -> AgentState:
        """Snap honest-looking states back to the start states so the
        honest policy loops on a closed set (generic_v1/model.py:1028-71)."""
        last = s.dag.size() - 1
        if last == 0:
            return s
        every = s.dag.all_mask()
        last_bit = 1 << last

        def common(loop_state):
            assert s.avis == every & ~last_bit or s.avis == every
            if s.dvis != every & ~last_bit:
                return s
            atk = self.proto.history(s.aview(), s.astate)
            dfn = self.proto.history(s.dview(), s.dstate)
            if atk != dfn:
                return s
            hist_mask = 0
            for b in dfn[:-1]:
                hist_mask |= 1 << b
            if hist_mask != s.dag.past(dfn[-1]):
                return s
            return loop_state

        if (s.dag.miners[last] == ATTACKER and s.withheld == last_bit
                and s.ignored == last_bit and s.avis == every & ~last_bit):
            return common(self.reset_attacker)
        if (s.dag.miners[last] == DEFENDER and s.withheld == 0
                and s.ignored == last_bit and s.avis == every & ~last_bit):
            return common(self.reset_defender)
        return s

    def _normalize_opt(self, s: AgentState) -> AgentState:
        if not self.merge_isomorphic:
            return s
        colors = []
        av, dv = s.aview(), s.dview()
        for b in range(s.dag.size()):
            c = 0 if b == 0 else (1 + s.dag.miners[b])
            c |= ((s.dvis >> b) & 1) << 2
            c |= ((s.avis >> b) & 1) << 3
            c |= ((s.withheld >> b) & 1) << 4
            c |= ((s.ignored >> b) & 1) << 5
            if s.dvis & (1 << b):
                c |= self.proto.color(dv, s.dstate, b) << 6
            if s.avis & (1 << b):
                c |= self.proto.color(av, s.astate, b) << 7
            colors.append(c)
        order = canonical_order(s.dag.parents, tuple(colors),
                                tuple(s.dag.height(b)
                                      for b in range(s.dag.size())))
        if list(order) == list(range(s.dag.size())):
            return s
        return self._relabel(s, list(order))
