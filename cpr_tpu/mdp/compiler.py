"""Implicit -> explicit MDP compiler (exhaustive BFS).

Reference counterpart: mdp/lib/compiler.py:6-90. Same contract — BFS from
the start states, integer ids assigned on first sight, positional action
ids per state — but transitions are appended to flat arrays (the
device-ready layout) and the semantic action behind each positional slot
is recorded so policies can be executed outside the MDP (e.g. inside the
JAX environments).
"""

from __future__ import annotations

from collections import deque

from cpr_tpu.mdp.explicit import MDP, sum_to_one
from cpr_tpu.mdp.implicit import Model


class Compiler:
    def __init__(self, model: Model):
        self.model = model
        self.state_map: dict = {}
        self.action_map: list[list] = []  # state id -> semantic actions
        self.states: list = []  # state id -> state (for debugging/policies)
        self._queue: deque = deque()
        self._explored: set[int] = set()
        self._mdp = MDP()
        for state, probability in model.start():
            sid = self._id_of(state)
            self._mdp.start[sid] = probability

    def _id_of(self, state) -> int:
        sid = self.state_map.get(state)
        if sid is None:
            sid = len(self.state_map)
            self.state_map[state] = sid
            self.states.append(state)
            self.action_map.append([])
            self._queue.append(state)
        return sid

    @property
    def n_states(self) -> int:
        return len(self.state_map)

    @property
    def queued(self) -> int:
        return len(self._queue)

    def explore(self, steps: int = 1000) -> bool:
        """Explore up to `steps` states; returns False when exhausted."""
        for _ in range(steps):
            if not self._queue:
                return False
            self.step()
        return True

    def step(self):
        state = self._queue.popleft()
        sid = self.state_map[state]
        if sid in self._explored:
            return
        self._explored.add(sid)
        actions = list(self.model.actions(state))
        self.action_map[sid] = actions
        for aid, action in enumerate(actions):
            transitions = self.model.apply(action, state)
            assert sum_to_one([t.probability for t in transitions]), (state, action)
            for t in transitions:
                self._mdp.add_transition(
                    sid, aid, self._id_of(t.state),
                    probability=t.probability, reward=t.reward,
                    progress=t.progress,
                )

    def mdp(self, finish_exploration: bool = True) -> MDP:
        if finish_exploration:
            while self._queue:
                self.step()
        elif self._queue:
            raise RuntimeError("unfinished exploration")
        self._mdp.n_states = max(self._mdp.n_states, len(self.state_map))
        self._mdp.check()
        return self._mdp
