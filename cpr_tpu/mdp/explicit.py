"""Explicit (tabular) MDPs as flat transition arrays + JAX solvers.

Reference counterpart: mdp/lib/explicit_mdp.py — `MDP` with nested
`tab[state][action] -> [Transition]` lists, a single-threaded Python value
iteration (:97-177), reachable-state search (:179), markov-chain extraction
and steady state via scipy sparse (:210-326), and policy evaluation (:328).

TPU re-design: transitions live in flat COO arrays (src, act, dst, prob,
reward, progress). Value iteration and policy evaluation become jitted
`segment_sum` sweeps under `lax.while_loop` — one dense Bellman backup is
two gathers, one multiply-add, and one segmented reduction, which XLA maps
onto the VPU; the sweep can be sharded over a device mesh by partitioning
the transition arrays (see cpr_tpu.parallel.sharded_value_iteration).
Host-side pieces (builder, invariant check, steady-state sparse solve)
remain numpy/scipy, like the reference.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse
import scipy.sparse.linalg

from cpr_tpu.telemetry import now


def sum_to_one(xs) -> bool:
    return math.isclose(sum(xs), 1.0, rel_tol=1e-9)


# ceiling (bytes) on the dense [S*A, K] padded tables padded_layout()
# materializes for the device RTDP path; ~2 GiB by default
PAD_BYTES_ENV_VAR = "CPR_MDP_PAD_BYTES"
_PAD_BYTES_DEFAULT = 2 << 30


class PaddedLayoutTooLarge(MemoryError):
    """padded_layout() refused to materialize its dense [S*A, K]
    tables: the actual byte size exceeds the CPR_MDP_PAD_BYTES
    ceiling.  Large compiles should solve through the COO segment-sum
    sweep (value_iteration impl="chunked"/"while"), which never pads."""


# opt-in ceiling (bytes) on one device's VI working set — COO columns
# + [S, A] Q planes + the [S] value/progress/policy vectors.  0 (the
# default) disables the guard; chips with a known HBM budget set it so
# an over-sized single-device solve refuses by name instead of letting
# the runtime OOM mid-sweep.  The state-sharded solver checks its
# PER-SHARD working set against the same ceiling.
VI_BYTES_ENV_VAR = "CPR_VI_BYTES"
_VI_BYTES_DEFAULT = 0


class ViWorkingSetTooLarge(MemoryError):
    """A VI solve's per-device working set exceeds the CPR_VI_BYTES
    ceiling.  Shard the state axis over more devices
    (cpr_tpu.parallel.sharded_state_value_iteration) or raise the
    ceiling explicitly."""


def vi_working_set_bytes(T: int, S: int, A: int, dtype, *,
                         shards: int = 1) -> int:
    """Per-device bytes a chunked COO sweep keeps resident: T
    transition rows (per shard when state-sharded), the shard's
    [S/shards, A] Q-value/Q-progress planes, and the replicated [S]
    value/progress/policy vectors every shard's `value[dst]` gather
    reads."""
    item = np.dtype(dtype).itemsize
    cols = T * (3 * np.dtype(np.int32).itemsize + 3 * item)
    planes = 2 * (S // shards) * A * item
    vectors = 3 * S * item
    return int(cols + planes + vectors)


def check_vi_working_set(T: int, S: int, A: int, dtype, *,
                         shards: int = 1):
    """Refuse (by name) a VI solve whose per-device working set
    exceeds the opt-in CPR_VI_BYTES ceiling — no-op when unset."""
    ceiling = int(os.environ.get(VI_BYTES_ENV_VAR, _VI_BYTES_DEFAULT))
    if ceiling <= 0:
        return
    need = vi_working_set_bytes(T, S, A, dtype, shards=shards)
    if need > ceiling:
        label = (f"{shards} state shard(s)" if shards > 1
                 else "one device")
        raise ViWorkingSetTooLarge(
            f"VI working set needs {need:,} bytes per device at "
            f"{label} (T={T:,} transition rows/shard, S={S:,}, A={A}, "
            f"dtype={np.dtype(dtype)}), over the {VI_BYTES_ENV_VAR} "
            f"ceiling of {ceiling:,}; shard the state axis over more "
            f"devices (cpr_tpu.parallel.sharded_state_value_iteration) "
            f"or raise the ceiling explicitly")


@dataclass
class MDP:
    """Host-side MDP builder with flat transition storage.

    Action ids are positional per state (the compiler enumerates each
    state's available actions in order), matching the reference compiler
    convention (mdp/lib/compiler.py:49-54).
    """

    n_states: int = 0
    n_actions: int = 0
    start: dict[int, float] = field(default_factory=dict)
    src: list[int] = field(default_factory=list)
    act: list[int] = field(default_factory=list)
    dst: list[int] = field(default_factory=list)
    prob: list[float] = field(default_factory=list)
    reward: list[float] = field(default_factory=list)
    progress: list[float] = field(default_factory=list)

    # column dtypes of the materialized COO layout, in field order
    _COL_DTYPES = (np.int32, np.int32, np.int32,
                   np.float64, np.float64, np.float64)

    @property
    def n_transitions(self) -> int:
        return len(self.src) + sum(len(c[0]) for c in
                                   getattr(self, "_chunks", ()) or ())

    def __repr__(self):
        s, a, t = self.n_states, self.n_actions, self.n_transitions
        per = t / s if s else 0.0
        return f"MDP of size {s} / {a} / {t} / {per:.1f}"

    def add_transition(self, src: int, act: int, dst: int, *, probability: float,
                       reward: float, progress: float):
        if getattr(self, "_chunks", None):
            # bulk chunks already appended: route through the columnar
            # path so transition order (and therefore state-id
            # assignment downstream) stays the call order under mixed
            # add_transition/add_transitions use
            self.add_transitions([src], [act], [dst], [probability],
                                 [reward], [progress])
            return
        assert src >= 0 and dst >= 0 and act >= 0
        self._arrays_cache = None  # invalidate materialized columns
        self.n_states = max(self.n_states, src + 1, dst + 1)
        self.n_actions = max(self.n_actions, act + 1)
        self.src.append(src)
        self.act.append(act)
        self.dst.append(dst)
        self.prob.append(probability)
        self.reward.append(reward)
        self.progress.append(progress)

    def add_transitions(self, src, act, dst, prob, reward, progress):
        """Bulk columnar append: one numpy chunk per call, no
        per-transition Python work.  Chunks stack up in a growable
        side list and are concatenated lazily by arrays() (or folded
        into the public columns by consolidate()), so a frontier-
        batched compile appends each BFS round in O(1) list pushes
        instead of six list.append calls per transition.  Probability
        columns must already be numeric — the monomial tracer's Param
        objects travel as separate coef/expo columns on the bulk path
        (cpr_tpu/mdp/frontier.py), never inside `prob`."""
        cols = tuple(np.asarray(c, dt) for c, dt in
                     zip((src, act, dst, prob, reward, progress),
                         self._COL_DTYPES))
        n = len(cols[0])
        if any(c.ndim != 1 or len(c) != n for c in cols):
            raise ValueError(
                "add_transitions wants six equal-length 1-d columns, "
                f"got lengths {[c.shape for c in cols]}")
        if n == 0:
            return
        if min(int(cols[0].min()), int(cols[1].min()),
               int(cols[2].min())) < 0:
            raise ValueError("negative state/action id in bulk append")
        self._arrays_cache = None
        self.n_states = max(self.n_states, int(cols[0].max()) + 1,
                            int(cols[2].max()) + 1)
        self.n_actions = max(self.n_actions, int(cols[1].max()) + 1)
        chunks = getattr(self, "_chunks", None)
        if chunks is None:
            chunks = self._chunks = []
        chunks.append(cols)

    def consolidate(self):
        """Fold any pending bulk chunks into the public column fields
        (as numpy arrays), so code that reads `mdp.src` etc. directly
        sees the full transition set.  Returns self.  After this the
        MDP behaves like a ptmdp()-built one: columns are arrays, and
        further single add_transition calls are not supported."""
        arrs = self.arrays()
        (self.src, self.act, self.dst,
         self.prob, self.reward, self.progress) = arrs
        self._chunks = []
        self._arrays_cache = arrs
        return self

    def arrays(self):
        """Materialized COO columns, cached: check()/tensor()/ptmdp and
        the parametric grid pipeline all call this, and rebuilding six
        numpy arrays from Python lists per call dominates for
        multi-million-transition native compiles.  add_transition /
        add_transitions invalidate; callers must treat the tuple as
        read-only.  Fast path is zero-copy: when a column is already a
        numpy array of the right dtype (consolidated bulk compiles,
        ptmdp outputs), np.asarray returns it as-is."""
        cached = getattr(self, "_arrays_cache", None)
        if cached is not None:
            return cached
        base = (self.src, self.act, self.dst,
                self.prob, self.reward, self.progress)
        chunks = getattr(self, "_chunks", None) or []
        cols = []
        for i, dt in enumerate(self._COL_DTYPES):
            parts = ([np.asarray(base[i], dt)] if len(base[0]) else [])
            parts += [c[i] for c in chunks]
            if not parts:
                cols.append(np.zeros(0, dt))
            elif len(parts) == 1:
                cols.append(parts[0])
            else:
                cols.append(np.concatenate(parts))
        out = tuple(cols)
        self._arrays_cache = out
        return out

    def check(self) -> bool:
        """Invariant check (mirrors mdp/lib/explicit_mdp.py:63-95):
        start distribution sums to one, per-(state,action) outgoing
        probabilities sum to one, actions are contiguous per state.

        Runs on the sorted (src, act) key pairs via group-boundary
        reduceat — O(T log T) time, O(T) memory — instead of two dense
        S x A host planes, so checking a multi-million-transition
        native compile stays cheap even for sparse action sets
        (check_dense keeps the old dense implementation as the parity
        oracle)."""
        src, act, dst, prob, _, _ = self.arrays()
        assert sum_to_one(self.start.values())
        for s in self.start:
            assert 0 <= s < self.n_states
        key = src.astype(np.int64) * self.n_actions + act
        if len(key):
            order = np.argsort(key, kind="stable")
            ks = key[order]
            first = np.ones(len(ks), dtype=bool)
            first[1:] = ks[1:] != ks[:-1]
            group = np.flatnonzero(first)
            uniq = ks[group]
            sums = np.add.reduceat(prob[order], group)
            bad = ~np.isclose(sums, 1.0, rtol=1e-9)
            assert not bad.any(), \
                f"probabilities do not sum to 1 at {uniq[bad]}"
            # action contiguity per state: the distinct action ids of a
            # state must be exactly {0..max}; with uniq sorted and
            # deduplicated, that is max == count - 1 per state group
            state = uniq // self.n_actions
            acts = uniq % self.n_actions
            sfirst = np.ones(len(uniq), dtype=bool)
            sfirst[1:] = state[1:] != state[:-1]
            sgroup = np.flatnonzero(sfirst)
            amax = np.maximum.reduceat(acts, sgroup)
            count = np.diff(np.append(sgroup, len(uniq)))
            assert (amax == count - 1).all(), "non-contiguous actions"
        assert dst.max(initial=-1) < self.n_states
        return True

    def check_dense(self) -> bool:
        """The original dense S x A invariant check — kept as the
        parity oracle for check() (tests/test_mdp_grid.py); O(S*A)
        memory, do not call on large sparse compiles."""
        src, act, dst, prob, _, _ = self.arrays()
        assert sum_to_one(self.start.values())
        for s in self.start:
            assert 0 <= s < self.n_states
        key = src.astype(np.int64) * self.n_actions + act
        sums = np.zeros(self.n_states * self.n_actions)
        np.add.at(sums, key, prob)
        present = np.zeros(self.n_states * self.n_actions, dtype=bool)
        present[key] = True
        bad = present & ~np.isclose(sums, 1.0, rtol=1e-9)
        assert not bad.any(), f"probabilities do not sum to 1 at {np.where(bad)[0]}"
        # action contiguity per state: if action k present, all j<k present
        # == row-wise monotone decreasing presence
        pres = present.reshape(self.n_states, self.n_actions)
        assert (pres[:, :-1] | ~pres[:, 1:]).all(), "non-contiguous actions"
        assert dst.max(initial=-1) < self.n_states
        return True

    def tensor(self, dtype=jnp.float32) -> "TensorMDP":
        src, act, dst, prob, reward, progress = self.arrays()
        start = np.zeros(self.n_states, dtype=np.float64)
        for s, p in self.start.items():
            start[s] = p
        return TensorMDP(
            n_states=self.n_states,
            n_actions=self.n_actions,
            src=jnp.asarray(src),
            act=jnp.asarray(act),
            dst=jnp.asarray(dst),
            prob=jnp.asarray(prob, dtype),
            reward=jnp.asarray(reward, dtype),
            progress=jnp.asarray(progress, dtype),
            start=jnp.asarray(start, dtype),
        )


def ptmdp(old: MDP, *, horizon: int) -> MDP:
    """Explicit-level probabilistic-termination transform.

    Adds one terminal state and splits every progress-making transition
    into continue/terminate branches with continue probability
    (1 - 1/horizon)^progress (reference: mdp/lib/models/aft20barzur.py:244-304).
    """
    assert horizon > 0
    terminal = old.n_states
    src, act, dst, prob, reward, progress = old.arrays()
    keep = (1.0 - 1.0 / horizon) ** progress
    hp = progress != 0.0  # progress-making rows split in two
    term = np.full(hp.sum(), terminal, np.int32)
    zeros = np.zeros(hp.sum())
    new = MDP(
        n_states=old.n_states + 1,
        n_actions=old.n_actions,
        start=dict(old.start),
        src=np.concatenate([src, src[hp]]),
        act=np.concatenate([act, act[hp]]),
        dst=np.concatenate([dst, term]).astype(np.int32),
        prob=np.concatenate([np.where(hp, prob * keep, prob),
                             (prob * (1.0 - keep))[hp]]),
        reward=np.concatenate([reward, zeros]),
        progress=np.concatenate([progress, zeros]),
    )
    return new


def _greedy_backup(qv, qp, valid, any_valid):
    """Masked argmax backup: ties to lowest action id; action-less states
    get value 0 / policy -1 (mdp/lib/explicit_mdp.py:123-146)."""
    S = qv.shape[0]
    qv_masked = jnp.where(valid, qv, -jnp.inf)
    best_a = jnp.argmax(qv_masked, axis=1)
    best_v = jnp.where(any_valid, qv_masked[jnp.arange(S), best_a], 0.0)
    best_p = jnp.where(any_valid, qp[jnp.arange(S), best_a], 0.0)
    policy = jnp.where(any_valid, best_a, -1)
    return best_v, best_p, policy


def make_vi_sweep(S: int, A: int, reduce=lambda x: x):
    """Build one Bellman sweep over flat COO transitions. `reduce` hooks a
    cross-device reduction (psum) in for transition-sharded sweeps
    (cpr_tpu.parallel.sharded_value_iteration)."""

    def sweep(src, act, dst, prob, reward, progress, valid, any_valid,
              discount, value, prog):
        seg = src * jnp.int32(A) + act
        qv = reduce(jax.ops.segment_sum(
            prob * (reward + discount * value[dst]), seg,
            num_segments=S * A)).reshape(S, A)
        qp = reduce(jax.ops.segment_sum(
            prob * (progress + discount * prog[dst]), seg,
            num_segments=S * A)).reshape(S, A)
        return _greedy_backup(qv, qp, valid, any_valid)

    return sweep


def _valid_actions(src, act, prob, S: int, A: int, reduce=lambda x: x):
    """Per-(state,action) availability mask. Masked on probability mass so
    zero-probability padding entries (transition sharding) are inert; real
    compiled actions always carry positive total mass (probabilities sum
    to one per action)."""
    seg = src * jnp.int32(A) + act
    mass = reduce(jax.ops.segment_sum(
        jnp.where(prob > 0, 1.0, 0.0), seg, num_segments=S * A))
    valid = (mass > 0).reshape(S, A)
    return valid, valid.any(axis=1)


# residual-trajectory ring length: the while_loop cannot stack a
# data-dependent number of deltas, so the last VI_RESID_LEN ride in a
# fixed ring in the carry (one scatter per sweep — noise next to the
# segment_sum backup).  Converted to chronological order host-side by
# ring_residuals().
VI_RESID_LEN = 512


def vi_while_loop(src, act, dst, prob, reward, progress, S, A, discount,
                  stop_delta, max_iter, reduce=lambda x: x,
                  resid_len=VI_RESID_LEN):
    """Shared VI driver: Bellman sweeps until the value delta drops below
    stop_delta or max_iter is hit. `reduce` hooks the cross-device psum
    for transition-sharded execution.

    Returns (value, progress, policy, delta, it, resid): `resid` is the
    convergence history — the per-sweep value deltas in a ring buffer
    of `resid_len` (static; 0 disables, giving a (0,) placeholder).
    Sweep j (1-based) writes slot (j-1) % resid_len; ring_residuals()
    unrolls it."""
    sweep = make_vi_sweep(S, A, reduce)
    valid, any_valid = _valid_actions(src, act, prob, S, A, reduce)

    def run(value, prog):
        return sweep(src, act, dst, prob, reward, progress, valid, any_valid,
                     discount, value, prog)

    def cond(carry):
        _, _, _, delta, i, _ = carry
        return (delta > stop_delta) & (i < max_iter)

    def body(carry):
        value, prog, _, _, i, resid = carry
        v2, p2, pol = run(value, prog)
        delta = jnp.abs(v2 - value).max()
        if resid_len:
            resid = resid.at[i % resid_len].set(delta)
        return v2, p2, pol, delta, i + 1, resid

    z = jnp.zeros(S, prob.dtype)
    v, p, pol = run(z, z)
    delta = jnp.abs(v - z).max()
    resid = jnp.zeros(resid_len, prob.dtype)
    if resid_len:
        resid = resid.at[0].set(delta)
    return jax.lax.while_loop(cond, body, (v, p, pol, delta, 1, resid))


def ring_residuals(resid, it: int):
    """Chronological residual trajectory from a vi_while_loop ring:
    the deltas of the last min(it, resid_len) sweeps, oldest first."""
    r = np.asarray(resid)
    L = len(r)
    if L == 0 or it <= 0:
        return np.zeros(0, r.dtype if L else np.float32)
    if it <= L:
        return r[:it]
    return np.roll(r, -(it % L))


def vi_residuals_event(impl: str, it: int, resid, stop_delta, delta):
    """Emit the schema-v2 `vi_residuals` telemetry event for a finished
    solve (no-op when no sink is active) and return the trajectory as a
    host array.  The emitted list is capped at the last VI_RESID_LEN
    sweeps — `truncated` flags solves whose early history was dropped
    (the while impl's ring already enforces the same cap on device)."""
    from cpr_tpu import telemetry

    resid = np.asarray(resid)
    tail = resid[-VI_RESID_LEN:]
    telemetry.current().event(
        "vi_residuals", impl=impl, n_sweeps=int(it),
        residuals=[float(d) for d in tail],
        truncated=int(it) > len(tail),
        stop_delta=float(stop_delta), final_delta=float(delta))
    return resid


@partial(jax.jit, static_argnums=(6, 7, 10, 11))
def _vi_loop(src, act, dst, prob, reward, progress, S, A, discount,
             stop_delta, max_iter, resid_len=VI_RESID_LEN):
    return vi_while_loop(src, act, dst, prob, reward, progress, S, A,
                         discount, stop_delta, max_iter,
                         resid_len=resid_len)


def resolve_vi_impl(impl: str | None) -> str:
    """Shared impl selection for the single-device and sharded
    solvers: explicit arg > CPR_VI_IMPL env > "while"."""
    impl = impl or os.environ.get("CPR_VI_IMPL", "while")
    if impl not in ("while", "chunked"):
        raise ValueError(f"unknown VI impl '{impl}'")
    return impl


@partial(jax.jit, static_argnums=(3, 4))
def _vi_valid(src, act, prob, S, A):
    return _valid_actions(src, act, prob, S, A)


def make_vi_chunk(S: int, A: int, reduce=lambda x: x):
    """Build the `chunk` unconditional-Bellman-sweeps scan — the
    device-while-free VI step.  The axon TPU worker has faulted inside
    the while_loop VI at every size tried (round-2 finding); running
    fixed-size chunks with HOST-side convergence checks between calls
    removes the data-dependent device loop from the program entirely,
    at the cost of up to chunk-1 extra (idempotent-at-fixpoint) sweeps.
    `reduce` hooks the cross-device psum exactly like make_vi_sweep."""
    sweep = make_vi_sweep(S, A, reduce)

    def chunk_body(src, act, dst, prob, reward, progress, valid,
                   any_valid, discount, value, prog, chunk):
        # policy rides in the carry (only the final one matters);
        # stacking it per sweep would materialize chunk x S ints on the
        # memory-tight device this impl exists for
        def body(carry, _):
            value, prog, _ = carry
            v2, p2, pol = sweep(src, act, dst, prob, reward, progress,
                                valid, any_valid, discount, value, prog)
            return (v2, p2, pol), jnp.abs(v2 - value).max()

        pol0 = jnp.full((S,), -1, jnp.int32)
        (v, p, pol), deltas = jax.lax.scan(
            body, (value, prog, pol0), None, length=chunk)
        # full per-sweep deltas: the convergence history the host
        # driver already syncs on — (chunk,) floats, not just the last
        return v, p, pol, deltas

    return chunk_body


@partial(jax.jit, static_argnums=(6, 7, 13))
def _vi_chunk(src, act, dst, prob, reward, progress, S, A, discount,
              value, prog, valid, any_valid, chunk):
    """Jitted single-device chunk step; the loop-invariant valid-action
    masks come in precomputed (_vi_valid) so per-chunk dispatches don't
    re-pay that segment_sum."""
    return make_vi_chunk(S, A)(src, act, dst, prob, reward, progress,
                               valid, any_valid, discount, value, prog,
                               chunk)


def _anderson_mix(hist):
    """Anderson (type-II) mixing over the chunk map g = G(x) on the
    JOINT (value, progress) system: weights a (sum 1) minimize the
    concatenated residual ||sum a_i (g_i - x_i)|| over both vectors —
    near the fixpoint the greedy policy is stable and value/progress
    iterate under the SAME transition operator, so one weight vector
    accelerates both consistently (mixing on the value residual alone
    left progress ~1e-3 off at the joint stop point — revenue is
    value/progress, so both must land).  `hist` holds (x_value, x_prog,
    g_value, g_prog) tuples, newest last; the Gram matrix is m x m
    (m <= 3) via device dots, solved on host with a small ridge."""
    m = len(hist)
    fv = [gv - xv for xv, _, gv, _ in hist]
    fp = [gp - xp for _, xp, _, gp in hist]
    G = np.array([[float(jnp.vdot(fv[i], fv[j]))
                   + float(jnp.vdot(fp[i], fp[j]))
                   for j in range(m)] for i in range(m)], np.float64)
    G += (1e-10 * (np.trace(G) / m + 1e-30)) * np.eye(m)
    try:
        w = np.linalg.solve(G, np.ones(m))
    except np.linalg.LinAlgError:
        return hist[-1][2], hist[-1][3]
    if not np.isfinite(w).all() or abs(w.sum()) < 1e-12:
        return hist[-1][2], hist[-1][3]
    a = w / w.sum()
    value = sum(float(ai) * gv for ai, (_, _, gv, _) in zip(a, hist))
    prog = sum(float(ai) * gp for ai, (_, _, _, gp) in zip(a, hist))
    return value, prog


def run_chunk_driver(chunk_step, S, dtype, stop_delta, max_iter,
                     chunk: int = 64, accel_m: int = 0,
                     checkpoint_path: str | None = None,
                     checkpoint_every: int = 1,
                     value0=None, prog0=None,
                     predicted_bytes: int | None = None):
    """Shared host loop for device-while-free VI: call
    `chunk_step(value, prog, steps) -> (value, prog, pol, deltas)` in
    full chunks with a chunk=1 tail (steps is a static argnum in both
    impls, so an arbitrary tail size would compile a fresh program per
    distinct max_iter % chunk; the 1-sweep program compiles once and
    serves every tail), stopping when the last in-chunk delta drops
    below stop_delta.  Used by both the single-device vi_chunked and
    the shard_map'd cpr_tpu.parallel sharded solver.

    `accel_m > 1` turns on Anderson acceleration between chunks
    (VERDICT r4 #7: plain Jacobi needed 3568 sweeps for the GhostDAG
    cutoff-8 capstone).  The fixpoint is untouched and convergence is
    still certified by a PLAIN sweep's delta inside the next chunk, so
    a bad extrapolation can slow things down but never corrupt the
    result; the safeguard drops the history whenever the post-mix
    delta grows.

    `checkpoint_path` makes a multi-hour solve preemption-safe: the
    post-chunk (value, progress, iteration, residual history) is saved
    atomically every `checkpoint_every` chunks, an existing file seeds
    the solve (validated against S/dtype), and the file is deleted on
    completion — it is crash-recovery scratch, not an artifact.  The
    checkpoint stores the PLAIN chunk output, so with accel_m=0 a
    killed-and-resumed solve replays the exact sweep sequence
    (bit-identical result); with acceleration on, resume drops the
    mixing history (the fixpoint is unchanged, the path there may
    differ).  Each chunk dispatch is retried on transient device
    faults via resilience.with_retries.

    `value0`/`prog0` warm-start the solve (the RTDP handoff —
    cpr_tpu/mdp/rtdp_graph.py seeds the sharded polish with its
    partially-explored table); an existing checkpoint overrides a
    warm start, so resume replays the checkpointed trajectory."""
    from cpr_tpu import resilience, telemetry

    # distinct buffers: a chunk_step that donates its carry (the
    # state-sharded solver) must not see the same zeros array twice
    value = (jnp.zeros(S, dtype) if value0 is None
             else jnp.asarray(value0, dtype))
    prog = (jnp.zeros(S, dtype) if prog0 is None
            else jnp.asarray(prog0, dtype))
    it = 0
    delta = jnp.inf
    pol = None
    hist: list = []
    prev_delta = None
    resids: list = []
    if checkpoint_path is not None and os.path.exists(checkpoint_path):
        try:
            v0, p0, it, r0 = resilience.load_vi_checkpoint(
                checkpoint_path, S=S, dtype=dtype)
            value, prog = jnp.asarray(v0), jnp.asarray(p0)
            resids = [r0] if r0.size else []
            telemetry.current().event("resume", path=checkpoint_path,
                                      update=int(it), scope="vi")
        except resilience.IntegrityError:
            # the damaged checkpoint is already quarantined + reported
            # (typed `integrity` event); the solve falls back to a
            # cold start, which recomputes the same deterministic
            # trajectory — bit-identical to never having checkpointed
            it = 0
    chunks_done = 0
    # v15 watermark: one allocator read per chunk (the convergence
    # check already syncs there, so the probe rides an existing host
    # round-trip), emitting the typed `memory` event on exit — crash
    # path included.  `predicted_bytes` carries the
    # vi_working_set_bytes claim so the report puts prediction and
    # measurement side by side.
    with telemetry.memory_watermark(
            "vi", predicted_bytes=predicted_bytes) as wm:
        while it < max_iter:
            step = chunk if max_iter - it >= chunk else 1
            x_value, x_prog = value, prog

            def one_chunk():
                resilience.fault_point("vi_chunk")
                return chunk_step(x_value, x_prog, step)

            g_value, g_prog, pol, deltas = resilience.with_retries(
                one_chunk, max_attempts=3, base_delay_s=0.2,
                max_delay_s=5.0, name="vi_chunk")
            it += step
            value, prog = g_value, g_prog
            # the convergence check below already syncs on the chunk,
            # so pulling the full per-sweep delta vector costs no
            # extra trip
            resids.append(np.asarray(deltas))
            delta = deltas[-1]
            chunks_done += 1
            wm.sample()
            converged = float(delta) <= float(stop_delta)
            if (checkpoint_path is not None and not converged
                    and chunks_done % checkpoint_every == 0):
                resilience.save_vi_checkpoint(
                    checkpoint_path, value=value, prog=prog, it=it,
                    resids=resids, stop_delta=float(stop_delta))
                telemetry.current().event(
                    "checkpoint", path=checkpoint_path,
                    what="vi", update=int(it))
            if converged:
                break
            # never mix on the way out: a max_iter exit must return
            # the plain chunk output (delta/policy describe THAT
            # iterate; an extrapolation is only ever validated by the
            # next chunk)
            if accel_m > 1 and step == chunk and it < max_iter:
                if prev_delta is not None and float(delta) > prev_delta:
                    hist = []  # extrapolation hurt: fall back to plain
                else:
                    hist = (hist + [(x_value, x_prog, g_value, g_prog)]
                            )[-accel_m:]
                    if len(hist) >= 2:
                        value, prog = _anderson_mix(hist)
                prev_delta = float(delta)
    if checkpoint_path is not None and os.path.exists(checkpoint_path):
        # crash-recovery scratch only: a finished solve must not leave
        # a checkpoint a later (different) solve could seed from
        os.unlink(checkpoint_path)
        try:
            os.unlink(checkpoint_path + ".json")
        except OSError:
            pass
    resid = (np.concatenate(resids) if resids
             else np.zeros(0, np.dtype(dtype)))
    return value, prog, pol, delta, it, resid


def vi_chunked(src, act, dst, prob, reward, progress, S, A, discount,
               stop_delta, max_iter, chunk: int = 64, accel_m: int = 0,
               checkpoint_path: str | None = None,
               checkpoint_every: int = 1):
    """Host-driven VI: repeat `_vi_chunk` until the last in-chunk delta
    drops below stop_delta (or max_iter sweeps ran).  Same fixpoint and
    return shape as vi_while_loop (the residual trajectory here is the
    FULL per-sweep history, not a ring) — extra post-convergence sweeps
    are no-ops on a converged value function.  `accel_m` opts into Anderson
    acceleration (see run_chunk_driver; ~5x fewer sweeps measured on
    the fc16 PT-MDP, same fixpoint to stop_delta).  `checkpoint_path`
    opts into between-chunk crash checkpoints + resume
    (run_chunk_driver)."""
    valid, any_valid = _vi_valid(src, act, prob, S, A)

    def chunk_step(value, prog, steps):
        return _vi_chunk(src, act, dst, prob, reward, progress, S, A,
                         discount, value, prog, valid, any_valid, steps)

    return run_chunk_driver(chunk_step, S, prob.dtype, stop_delta,
                            max_iter, chunk, accel_m=accel_m,
                            checkpoint_path=checkpoint_path,
                            checkpoint_every=checkpoint_every,
                            predicted_bytes=vi_working_set_bytes(
                                int(src.shape[0]), S, A, prob.dtype))


def make_grid_vi_chunk(S: int, A: int, reduce=lambda x: x):
    """Grid-batched twin of make_vi_chunk: one chunk of Bellman sweeps
    vmapped over a [G] grid axis — shared (src, act, dst, reward,
    progress) structure, per-point probability columns [G, T] and
    per-point (value, prog, policy) planes [G, S] riding in the carry.

    Per-point convergence masking: `frozen` [G] bools bit-freeze a
    converged point's carry exactly like held serve lanes — the chunk
    runs unconditionally (no ragged compute on device) and the outputs
    of frozen points are replaced by their inputs at chunk end, so a
    point frozen after the chunk where its last in-chunk delta crossed
    stop_delta holds exactly the solo vi_chunked fixpoint (the solo
    driver also only stops at chunk boundaries).  Frozen points report
    delta 0 so the host driver's history stays interpretable.

    The valid-action masks are recomputed per chunk inside the program
    (one segment_sum per point per chunk — noise next to chunk*2
    backup segment_sums) rather than carried as [G, S, A] planes."""
    chunk_body = make_vi_chunk(S, A, reduce)

    def grid_chunk(carry, src, act, dst, probs, reward, progress,
                   discount, frozen, chunk):
        value, prog, pol = carry

        def per_point(prob, v, p):
            valid, any_valid = _valid_actions(src, act, prob, S, A,
                                              reduce)
            return chunk_body(src, act, dst, prob, reward, progress,
                              valid, any_valid, discount, v, p, chunk)

        v2, p2, pol2, deltas = jax.vmap(per_point)(probs, value, prog)
        fz = frozen[:, None]
        v2 = jnp.where(fz, value, v2)
        p2 = jnp.where(fz, prog, p2)
        pol2 = jnp.where(fz, pol, pol2)
        deltas = jnp.where(fz, jnp.zeros_like(deltas), deltas)
        return (v2, p2, pol2), deltas

    return grid_chunk


def run_grid_chunk_driver(chunk_step, place, G, S, dtype, stop_delta,
                          max_iter, chunk: int = 64,
                          checkpoint_path: str | None = None,
                          checkpoint_every: int = 1):
    """Host loop for grid-batched chunked VI — run_chunk_driver's
    semantics (full chunks with a chunk=1 tail, with_retries around
    each dispatch, between-chunk checkpoint/resume) at grid
    granularity: per-point convergence is tracked host-side and fed
    back as the `frozen` mask, and the whole grid stops when every
    point froze or max_iter sweeps ran.

    `chunk_step(carry, frozen, steps) -> (carry, deltas[G, steps])`
    with carry = (value, prog, policy) planes [G, S] (the policy rides
    in the carry so a frozen point keeps its converged policy across
    later chunks); `place(x)` device-puts a host array under the
    caller's grid sharding (identity for single-device).

    Returns (value, prog, policy, delta[G], conv_iter[G], converged[G],
    it, resid[G, it]) — conv_iter is the sweep count at which each
    point froze (chunk-boundary granularity; the full budget for
    unconverged points)."""
    from cpr_tpu import resilience, telemetry

    np_dtype = np.dtype(dtype)
    value = np.zeros((G, S), np_dtype)
    prog = np.zeros((G, S), np_dtype)
    pol = np.full((G, S), -1, np.int32)
    frozen = np.zeros(G, dtype=bool)
    conv_it = np.zeros(G, np.int64)
    final_delta = np.full(G, np.inf)
    it = 0
    resids: list = []
    if checkpoint_path is not None and os.path.exists(checkpoint_path):
        try:
            st = resilience.load_grid_vi_checkpoint(
                checkpoint_path, G=G, S=S, dtype=np_dtype)
        except resilience.IntegrityError:
            # quarantined + reported by sealed_read; cold-start fallback
            # recomputes the same deterministic trajectory
            st = None
        if st is not None:
            value, prog, pol = st["value"], st["prog"], st["pol"]
            frozen = st["frozen"].copy()
            conv_it = st["conv_it"].copy()
            final_delta = st["final_delta"].copy()
            it = int(st["it"])
            resids = [st["resid"]] if st["resid"].size else []
            telemetry.current().event("resume", path=checkpoint_path,
                                      update=it, scope="grid_vi")
    carry = (place(value), place(prog), place(pol))
    chunks_done = 0
    # v15 watermark: one allocator read per chunk, riding the same
    # host sync the convergence check forces; the typed `memory`
    # event (scope mdp_grid) emits on exit, crash path included
    with telemetry.memory_watermark("mdp_grid") as wm:
        while it < max_iter and not bool(frozen.all()):
            step = chunk if max_iter - it >= chunk else 1
            frozen_dev = place(frozen)
            prev_carry = carry

            def one_chunk():
                resilience.fault_point("vi_chunk")
                return chunk_step(prev_carry, frozen_dev, step)

            carry, deltas = resilience.with_retries(
                one_chunk, max_attempts=3, base_delay_s=0.2,
                max_delay_s=5.0, name="grid_vi_chunk")
            it += step
            # the convergence check syncs on the chunk anyway; the
            # full [G, step] delta plane is the residual history
            d = np.asarray(deltas)
            resids.append(d)
            last = d[:, -1]
            live = ~frozen
            final_delta[live] = last[live]
            newly = live & (last <= float(stop_delta))
            conv_it[newly] = it
            frozen |= newly
            chunks_done += 1
            wm.sample()
            if (checkpoint_path is not None and not bool(frozen.all())
                    and chunks_done % checkpoint_every == 0):
                resilience.save_grid_vi_checkpoint(
                    checkpoint_path, value=np.asarray(carry[0]),
                    prog=np.asarray(carry[1]), pol=np.asarray(carry[2]),
                    frozen=frozen, conv_it=conv_it,
                    final_delta=final_delta, it=it, resids=resids,
                    stop_delta=float(stop_delta))
                telemetry.current().event(
                    "checkpoint", path=checkpoint_path,
                    what="grid_vi", update=it)
    if checkpoint_path is not None and os.path.exists(checkpoint_path):
        # crash-recovery scratch only, exactly like run_chunk_driver
        os.unlink(checkpoint_path)
        try:
            os.unlink(checkpoint_path + ".json")
        except OSError:
            pass
    conv_it[~frozen] = it  # unconverged points ran the whole budget
    resid = (np.concatenate(resids, axis=1) if resids
             else np.zeros((G, 0), np_dtype))
    return (np.asarray(carry[0]), np.asarray(carry[1]),
            np.asarray(carry[2]), final_delta, conv_it, frozen.copy(),
            it, resid)


@partial(jax.jit, static_argnums=(6, 9))
def _pe_loop(src, dst, prob, reward, progress, onpolicy, S, discount, theta,
             max_iter):
    w = jnp.where(onpolicy, prob, 0.0)

    def cond(carry):
        _, _, delta, i = carry
        return (delta > theta) & (i < max_iter)

    def body(carry):
        rew, prg, _, i = carry
        r2 = jax.ops.segment_sum(
            w * (reward + discount * rew[dst]), src, num_segments=S)
        p2 = jax.ops.segment_sum(
            w * (progress + discount * prg[dst]), src, num_segments=S)
        return r2, p2, jnp.abs(r2 - rew).max(), i + 1

    z = jnp.zeros(S, prob.dtype)
    return jax.lax.while_loop(cond, body, (z, z, jnp.inf, 0))


@partial(jax.jit, static_argnums=(4, 5, 6, 7))
def _rtdp_loop(Tdst, Tpack, start_cdf, key, S, A, steps, batch,
               eps, discount, value0, prog0):
    """Batched asynchronous VI with eps-greedy trajectory sampling.

    Tdst: [S*A, K] padded destination ids; Tpack: [S*A, K, 3] padded
    (prob, reward, progress).  Each of `batch` lanes walks the MDP under
    the eps-greedy policy of the CURRENT value estimate, applying a
    greedy Bellman backup to every visited state (RTDP, Barto et al.);
    terminal lanes restart from the start distribution."""
    Tprob = Tpack[..., 0]
    valid_a = Tprob.reshape(S, A, -1).sum(-1) > 0  # [S, A]
    any_valid = valid_a.any(-1)  # [S]
    B = batch
    bi = jnp.arange(B)

    def draw_start(k):
        # inverse-CDF draw (a categorical over S logits would cost
        # O(batch*S) gumbel noise per step).  side='right' skips
        # zero-mass prefix states at u == 0.0; scaling u into the
        # realized cdf range keeps a float32 cumsum shortfall from
        # landing past the last massive state.
        u = jax.random.uniform(k, (B,)) * start_cdf[-1]
        return jnp.clip(jnp.searchsorted(start_cdf, u, side="right"),
                        0, S - 1).astype(jnp.int32)

    def body(carry, _):
        V, P, s, k = carry
        k, k1, k2, k3, k4 = jax.random.split(k, 5)
        rows = s[:, None] * A + jnp.arange(A)  # [B, A]
        dstb = Tdst[rows]  # [B, A, K]
        packb = Tpack[rows]
        probb, rewb, prgb = packb[..., 0], packb[..., 1], packb[..., 2]
        q = (probb * (rewb + discount * V[dstb])).sum(-1)  # [B, A]
        qp = (probb * (prgb + discount * P[dstb])).sum(-1)
        va = valid_a[s]
        has_a = any_valid[s]
        # the same masked greedy backup VI sweeps use (shape-generic)
        newv, newp, a_greedy = _greedy_backup(q, qp, va, has_a)
        V = V.at[s].set(newv)
        P = P.at[s].set(newp)
        # eps-greedy behavior action over the valid set
        a_rand = jax.random.categorical(
            k1, jnp.where(va, 0.0, -jnp.inf), axis=-1)
        a_beh = jnp.where(jax.random.uniform(k2, (B,)) < eps,
                          a_rand, a_greedy)
        a_beh = jnp.where(has_a, a_beh, 0)
        # sample the successor from the chosen action's transitions
        prow = probb[bi, a_beh]  # [B, K]; padding prob 0 ~ never drawn
        nxt = jax.random.categorical(k3, jnp.log(prow + 1e-30), axis=-1)
        s_next = dstb[bi, a_beh, nxt]
        # restart terminal/action-less lanes from the start distribution
        s_next = jnp.where(any_valid[s_next] & has_a, s_next,
                           draw_start(k4))
        return (V, P, s_next, k), None

    key, k0 = jax.random.split(key)
    s0 = draw_start(k0)
    (V, P, s, _), _ = jax.lax.scan(
        body, (value0, prog0, s0, key), None, length=steps)
    return V, P


@dataclass(frozen=True)
class TensorMDP:
    """Device-resident MDP: COO transitions + jitted solvers."""

    n_states: int
    n_actions: int
    src: jax.Array
    act: jax.Array
    dst: jax.Array
    prob: jax.Array
    reward: jax.Array
    progress: jax.Array
    start: jax.Array

    # -- value iteration --------------------------------------------------

    def resolve_stop_delta(self, *, discount, eps, stop_delta, max_iter=0):
        """Abort rule of eps-optimal VI (mdp/lib/explicit_mdp.py:106-110).
        For discount == 1 the eps formula degenerates to 0, so an explicit
        stop_delta — or a bare max_iter (fixed number of sweeps) — is
        required."""
        assert 0.0 < discount <= 1.0
        if stop_delta is None:
            if eps is None:
                if max_iter > 0:
                    return 0.0  # run exactly max_iter sweeps
                raise ValueError("need eps, stop_delta, or max_iter")
            if discount == 1.0:
                raise ValueError(
                    "eps-optimality is undefined at discount=1; pass "
                    "stop_delta (absolute value-delta threshold) instead"
                )
            stop_delta = eps * (1.0 - discount) / discount
        assert max_iter > 0 or stop_delta > 0, "infinite iteration"
        return stop_delta

    def _check_segment_width(self):
        assert self.n_states * self.n_actions < 2**31, (
            "state-action space exceeds int32 segment ids; "
            "shard the MDP (cpr_tpu.parallel.sharded_value_iteration) "
            "over more devices with a split state space instead"
        )

    def value_iteration(self, *, max_iter: int = 0, discount: float = 1.0,
                        eps: float | None = None, stop_delta: float | None = None,
                        verbose: bool = False, impl: str | None = None,
                        checkpoint_path: str | None = None,
                        checkpoint_every: int = 1):
        """eps-optimal value iteration (reference semantics:
        mdp/lib/explicit_mdp.py:97-177 — double-buffered dense sweep that
        also tracks expected progress and the greedy policy; ties go to
        the lowest action id; states without actions get value 0 and
        policy -1).

        impl: "while" (default; lax.while_loop, one device program) or
        "chunked" (fixed-size scan chunks, host-side convergence check —
        the axon-TPU fault workaround, see _vi_chunk).  The env var
        CPR_VI_IMPL overrides the default so on-chip tooling can switch
        without code changes; both produce the same fixpoint.

        checkpoint_path (chunked impl only): save resumable solve state
        between chunks and seed from an existing file — the while impl
        is a single device program with no host seam to checkpoint at
        (docs/RESILIENCE.md)."""
        stop_delta = self.resolve_stop_delta(
            discount=discount, eps=eps, stop_delta=stop_delta, max_iter=max_iter)
        self._check_segment_width()
        check_vi_working_set(int(self.src.shape[0]), self.n_states,
                             self.n_actions, self.prob.dtype)
        impl = resolve_vi_impl(impl)
        if checkpoint_path is not None and impl == "while":
            raise ValueError(
                "checkpoint_path requires impl='chunked': the while impl "
                "runs as one device program with no between-chunk seam")
        t0 = now()
        run = (_vi_loop if impl == "while" else
               partial(vi_chunked, checkpoint_path=checkpoint_path,
                       checkpoint_every=checkpoint_every))
        value, progress, policy, delta, it, resid = run(
            self.src, self.act, self.dst, self.prob, self.reward,
            self.progress, self.n_states, self.n_actions,
            jnp.asarray(discount, self.prob.dtype),
            jnp.asarray(stop_delta, self.prob.dtype),
            max_iter if max_iter > 0 else (1 << 30),
        )
        if impl == "while":
            resid = ring_residuals(resid, int(it))
        resid = vi_residuals_event(impl, int(it), resid, stop_delta,
                                   delta)
        if verbose:
            print(f"value iteration: {int(it)} sweeps, delta {float(delta):g}")
        return dict(
            vi_discount=discount,
            vi_delta=float(delta),
            vi_stop_delta=stop_delta,
            vi_policy=np.asarray(policy),
            vi_value=np.asarray(value),
            vi_progress=np.asarray(progress),
            vi_iter=int(it),
            vi_max_iter=max_iter,
            vi_residuals=resid,
            vi_time=now() - t0,
        )

    def policy_evaluation(self, policy, *, theta: float, discount: float = 1.0,
                          max_iter: int | None = None):
        """Iterative evaluation of a fixed (positional-action) policy
        (reference: mdp/lib/explicit_mdp.py:328-378)."""
        rew, prg, _, it = _pe_loop(
            self.src, self.dst, self.prob, self.reward, self.progress,
            jnp.asarray(policy, jnp.int32)[self.src] == self.act,
            self.n_states,
            jnp.asarray(discount, self.prob.dtype),
            jnp.asarray(theta, self.prob.dtype),
            max_iter if max_iter is not None else (1 << 30),
        )
        return dict(pe_reward=np.asarray(rew), pe_progress=np.asarray(prg),
                    pe_iter=int(it))

    # -- device RTDP ------------------------------------------------------

    def padded_layout(self):
        """[S*A, K] padded per-(state,action) transition tables — the
        gather-friendly twin of the COO layout, for solvers that index
        by (state, action) instead of sweeping all transitions.
        Memoized on the instance: iterative rtdp() refinement rounds
        (warm starts) reuse the sort + dense build + device transfer."""
        cached = getattr(self, "_padded_cache", None)
        if cached is not None:
            return cached
        S, A = self.n_states, self.n_actions
        dtype = np.dtype(self.prob.dtype)  # honor the tensor()'s dtype
        src = np.asarray(self.src, np.int64)
        act = np.asarray(self.act, np.int64)
        key = src * A + act
        order = np.argsort(key, kind="stable")
        key_s = key[order]
        pos = np.arange(len(key_s)) - np.searchsorted(key_s, key_s)
        K = int(pos.max()) + 1 if len(key_s) else 1
        need = S * A * K * (np.dtype(np.int32).itemsize
                            + 3 * dtype.itemsize)
        ceiling = int(os.environ.get(PAD_BYTES_ENV_VAR,
                                     _PAD_BYTES_DEFAULT))
        if need > ceiling:
            raise PaddedLayoutTooLarge(
                f"padded [S*A, K] layout needs {need:,} bytes "
                f"(S={S}, A={A}, K={K}, dtype={dtype}), over the "
                f"{PAD_BYTES_ENV_VAR} ceiling of {ceiling:,}; solve "
                f"large compiles through the COO sweep "
                f"(value_iteration impl='chunked') instead of the "
                f"padded rtdp() path, or raise the ceiling explicitly")
        Tdst = np.zeros((S * A, K), np.int32)
        Tpack = np.zeros((S * A, K, 3), dtype)
        Tdst[key_s, pos] = np.asarray(self.dst, np.int32)[order]
        Tpack[key_s, pos, 0] = np.asarray(self.prob, dtype)[order]
        Tpack[key_s, pos, 1] = np.asarray(self.reward, dtype)[order]
        Tpack[key_s, pos, 2] = np.asarray(self.progress, dtype)[order]
        out = (jnp.asarray(Tdst), jnp.asarray(Tpack), K)
        object.__setattr__(self, "_padded_cache", out)  # frozen dataclass
        return out

    def rtdp(self, key, *, steps: int, batch: int = 256, eps: float = 0.2,
             discount: float = 1.0, value0=None, progress0=None):
        """Device-side RTDP: `batch` parallel eps-greedy trajectories,
        asynchronous greedy Bellman backups on every visited state —
        one jitted scan, no host round-trips.

        The TPU-native counterpart of the host RTDP (cpr_tpu/mdp/rtdp.py
        samples an *implicit* model on the host; this solves the
        *compiled* table without full sweeps, converging on the states
        reachable under near-greedy play).  Returns dict with rtdp_value
        / rtdp_progress arrays; unvisited states keep their init."""
        assert steps > 0 and batch > 0 and 0.0 <= eps <= 1.0
        self._check_segment_width()  # rows index by s*A+a in int32 too
        Tdst, Tpack, K = self.padded_layout()
        dtype = self.prob.dtype
        start_cdf = jnp.cumsum(jnp.asarray(self.start, dtype))
        z = jnp.zeros(self.n_states, dtype)
        v0 = z if value0 is None else jnp.asarray(value0, dtype)
        p0 = z if progress0 is None else jnp.asarray(progress0, dtype)
        t0 = now()
        V, P = _rtdp_loop(Tdst, Tpack, start_cdf, key, self.n_states,
                          self.n_actions, steps, batch,
                          jnp.asarray(eps, dtype),
                          jnp.asarray(discount, dtype), v0, p0)
        return dict(rtdp_value=np.asarray(V), rtdp_progress=np.asarray(P),
                    rtdp_steps=steps, rtdp_batch=batch,
                    rtdp_time=now() - t0)

    # -- start-state aggregates -------------------------------------------

    def start_value(self, values) -> float:
        return float(jnp.asarray(values) @ self.start)

    # -- markov chain / steady state (host, scipy) ------------------------

    def _numpy(self):
        return (np.asarray(self.src), np.asarray(self.act), np.asarray(self.dst),
                np.asarray(self.prob, np.float64),
                np.asarray(self.reward, np.float64),
                np.asarray(self.progress, np.float64))

    def reachable_states(self, policy, *, start_state=None):
        """States visited under a policy (mdp/lib/explicit_mdp.py:179-208)."""
        src, act, dst, prob, _, _ = self._numpy()
        adj: dict[int, list[int]] = {}
        for i in range(len(src)):
            if prob[i] == 0.0:
                continue
            if policy[src[i]] == act[i]:
                adj.setdefault(int(src[i]), []).append(int(dst[i]))
        todo = set()
        if start_state is None:
            todo = {int(s) for s, p in enumerate(np.asarray(self.start)) if p > 0}
        else:
            todo = {int(start_state)}
        seen = set()
        while todo:
            s = todo.pop()
            seen.add(s)
            if policy[s] < 0:
                continue
            for d in adj.get(s, []):
                if d not in seen:
                    todo.add(d)
        return seen

    def markov_chain(self, policy, *, start_state):
        """Policy-induced markov chain as scipy sparse matrices
        (mdp/lib/explicit_mdp.py:210-250)."""
        reachable = sorted(self.reachable_states(policy, start_state=start_state))
        mc_of = {s: i for i, s in enumerate(reachable)}
        src, act, dst, prob, rew, prg = self._numpy()
        rows, cols, prbs, rews, prgs = [], [], [], [], []
        covered = set()
        for i in range(len(src)):
            s = int(src[i])
            if s not in mc_of or policy[s] != act[i] or prob[i] == 0.0:
                continue
            covered.add(s)
            rows.append(mc_of[s])
            cols.append(mc_of[int(dst[i])])
            prbs.append(prob[i])
            rews.append(rew[i])
            prgs.append(prg[i])
        for s in reachable:
            if s not in covered:  # terminal: self loop
                rows.append(mc_of[s])
                cols.append(mc_of[s])
                prbs.append(1.0)
                rews.append(0.0)
                prgs.append(0.0)
        n = len(reachable)
        return dict(
            prb=scipy.sparse.coo_matrix((prbs, (rows, cols)), shape=(n, n)),
            rew=scipy.sparse.coo_matrix((rews, (rows, cols)), shape=(n, n)),
            prg=scipy.sparse.coo_matrix((prgs, (rows, cols)), shape=(n, n)),
            mdp_states=reachable,
        )

    def steady_state(self, policy, *, start_state):
        """Stationary distribution of the policy-induced chain via a sparse
        least-norm solve (mdp/lib/explicit_mdp.py:252-326)."""
        t0 = now()
        mc = self.markov_chain(policy, start_state=start_state)
        prb = mc["prb"]
        n = prb.shape[0]
        rows = list(prb.row) + list(range(n)) + list(range(n))
        cols = list(prb.col) + list(range(n)) + [n] * n
        vals = list(prb.data) + [-1.0] * n + [1.0] * n
        Q = scipy.sparse.csr_matrix((vals, (rows, cols)), shape=(n, n + 1))
        QTQ = Q.dot(Q.transpose())
        b = np.ones(n)
        v = scipy.sparse.linalg.spsolve(QTQ, b)
        if np.isnan(v).any():
            lsqr = scipy.sparse.linalg.lsqr(QTQ, b)
            v = lsqr[0]
            v = v / v.sum()
        assert math.isclose(v.sum(), 1.0, rel_tol=1e-5)
        ss = np.zeros(self.n_states)
        for mc_s, mdp_s in enumerate(mc["mdp_states"]):
            ss[mdp_s] = v[mc_s]
        return dict(ss=ss, ss_reachable=n,
                    ss_nonzero=int((v != 0).sum()),
                    ss_time=now() - t0)
