"""In-graph RTDP: device-resident exploration, no host round-trips.

The host RTDP (cpr_tpu/mdp/rtdp.py) walks one trajectory at a time in
Python — every step is a dict lookup plus a numpy dot product, and the
device sits idle.  `TensorMDP.rtdp` already batches the walk as a
jitted `lax.scan`, but it runs a FIXED number of steps and keeps no
exploration state beyond the value table.  This module finishes the
port (ROADMAP item 1, "exploration stays host-bound"):

* a `lax.while_loop` instead of a fixed scan — the loop watches a
  damped residual of its own greedy backups and exits as soon as the
  estimate stops moving (or the step budget runs out), so easy tables
  do not pay the full budget;
* device-resident `visits` counters — the per-state visit histogram
  comes back with the values (coverage diagnostics, and the natural
  prioritization signal for downstream sweeps);
* a fixed-capacity priority buffer of the highest-|delta| states seen
  so far (top-k merge per step, the in-graph analog of the host
  RTDP's exploring-starts buffer): restarting lanes resume from a
  buffered high-error state with probability `restart_p` instead of
  always re-rolling the start distribution, which focuses the batch
  on the frontier where the estimate is still wrong;
* `rtdp_sharded_polish` — the capstone handoff: run the in-graph
  exploration, then feed its table to the state-sharded exact VI
  (cpr_tpu.parallel.sharded_state_value_iteration value0/progress0)
  so the final fixpoint is exact while the sharded sweeps start from
  a near-converged estimate.

Same transition layout as `TensorMDP.rtdp` (`padded_layout()`s
[S*A, K] tables) and the same masked `_greedy_backup`, so the
per-visited-state math is identical to the scan version and to the
exact sweeps.  All sampling flows from the single `key` argument —
bit-reproducible across calls by construction.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from cpr_tpu.mdp.explicit import TensorMDP, _greedy_backup
from cpr_tpu.telemetry import now

__all__ = ["rtdp_graph", "rtdp_sharded_polish"]


@partial(jax.jit, static_argnums=(4, 5, 6, 7, 8))
def _rtdp_graph_loop(Tdst, Tpack, start_cdf, key, S, A, max_steps,
                     batch, cap, eps, restart_p, discount, stop_delta,
                     decay, value0, prog0):
    """The while_loop program: `batch` eps-greedy walkers, greedy
    Bellman backups on every visited state, visit counters, and a
    top-k priority buffer feeding restarts.  Stops when the damped
    backup residual falls to `stop_delta` or after `max_steps`."""
    Tprob = Tpack[..., 0]
    valid_a = Tprob.reshape(S, A, -1).sum(-1) > 0  # [S, A]
    any_valid = valid_a.any(-1)  # [S]
    B = batch
    bi = jnp.arange(B)

    def draw_start(k):
        # inverse-CDF draw, exactly as _rtdp_loop (explicit.py)
        u = jax.random.uniform(k, (B,)) * start_cdf[-1]
        return jnp.clip(jnp.searchsorted(start_cdf, u, side="right"),
                        0, S - 1).astype(jnp.int32)

    def cond(carry):
        _, _, _, _, _, _, _, t, resid = carry
        return (t < max_steps) & (resid > stop_delta)

    def body(carry):
        V, P, visits, buf_s, buf_pri, s, k, t, resid = carry
        k, k1, k2, k3, k4, k5, k6 = jax.random.split(k, 7)
        rows = s[:, None] * A + jnp.arange(A)  # [B, A]
        dstb = Tdst[rows]  # [B, A, K]
        packb = Tpack[rows]
        probb, rewb, prgb = packb[..., 0], packb[..., 1], packb[..., 2]
        q = (probb * (rewb + discount * V[dstb])).sum(-1)  # [B, A]
        qp = (probb * (prgb + discount * P[dstb])).sum(-1)
        va = valid_a[s]
        has_a = any_valid[s]
        newv, newp, a_greedy = _greedy_backup(q, qp, va, has_a)
        delta_lane = jnp.abs(newv - V[s])  # [B]
        V = V.at[s].set(newv)
        P = P.at[s].set(newp)
        visits = visits.at[s].add(1)
        # top-k merge of this step's |delta|s into the priority buffer
        # (duplicate state ids are harmless: a stale entry just
        # restarts a lane somewhere the estimate RECENTLY moved)
        all_pri = jnp.concatenate([buf_pri, delta_lane])
        all_s = jnp.concatenate([buf_s, s])
        buf_pri, top = jax.lax.top_k(all_pri, cap)
        buf_s = all_s[top]
        # eps-greedy behavior action over the valid set
        a_rand = jax.random.categorical(
            k1, jnp.where(va, 0.0, -jnp.inf), axis=-1)
        a_beh = jnp.where(jax.random.uniform(k2, (B,)) < eps,
                          a_rand, a_greedy)
        a_beh = jnp.where(has_a, a_beh, 0)
        prow = probb[bi, a_beh]  # [B, K]; padding prob 0 ~ never drawn
        nxt = jax.random.categorical(k3, jnp.log(prow + 1e-30), axis=-1)
        s_next = dstb[bi, a_beh, nxt]
        # restarts: terminal/action-less lanes resume from a buffered
        # high-error state w.p. restart_p, else from the start CDF
        filled = buf_pri > 0.0
        logits = jnp.where(filled, 0.0, -jnp.inf)
        logits = jnp.where(filled.any(), logits, jnp.zeros_like(logits))
        pick = buf_s[jax.random.categorical(k4, logits, shape=(B,))]
        use_buf = (jax.random.uniform(k5, (B,)) < restart_p) & filled.any()
        restart = jnp.where(use_buf, pick, draw_start(k6))
        s_next = jnp.where(any_valid[s_next] & has_a, s_next, restart)
        # damped running peak; the inf sentinel (step 0) is replaced
        # outright or it would stay inf forever and disable early exit
        resid = jnp.maximum(jnp.where(jnp.isinf(resid), 0.0,
                                      resid * decay),
                            delta_lane.max())
        return (V, P, visits, buf_s, buf_pri, s_next, k, t + 1, resid)

    key, k0 = jax.random.split(key)
    carry0 = (value0, prog0, jnp.zeros(S, jnp.int32),
              jnp.zeros(cap, jnp.int32),
              jnp.full(cap, -jnp.inf, value0.dtype),
              draw_start(k0), key, jnp.int32(0),
              jnp.asarray(jnp.inf, value0.dtype))
    V, P, visits, buf_s, buf_pri, _, _, t, resid = jax.lax.while_loop(
        cond, body, carry0)
    return V, P, visits, buf_s, buf_pri, t, resid


def rtdp_graph(tm: TensorMDP, key, *, max_steps: int, batch: int = 256,
               buffer: int = 1024, eps: float = 0.2,
               restart_p: float = 0.5, discount: float = 1.0,
               stop_delta: float = 0.0, decay: float = 0.95,
               value0=None, progress0=None) -> dict:
    """In-graph RTDP over a compiled TensorMDP (module docstring).

    `stop_delta` > 0 enables early exit: the loop tracks
    `resid = max(resid * decay, <this step's max backup delta>)` — a
    damped running peak, so one quiet step cannot stop a loop that is
    still finding new states — and exits when it drops below the
    threshold.  At the default 0.0 the loop runs exactly `max_steps`
    steps (matching `TensorMDP.rtdp`'s fixed budget).

    Returns dict(rtdp_value, rtdp_progress, rtdp_visits, rtdp_buffer
    (the [buffer] highest-|delta| state ids, -1 where unfilled),
    rtdp_steps (steps actually run), rtdp_resid, rtdp_time)."""
    assert max_steps > 0 and batch > 0 and buffer > 0
    assert 0.0 <= eps <= 1.0 and 0.0 <= restart_p <= 1.0
    assert 0.0 < decay < 1.0
    tm._check_segment_width()  # rows index by s*A+a in int32 too
    Tdst, Tpack, _ = tm.padded_layout()
    dtype = tm.prob.dtype
    start_cdf = jnp.cumsum(jnp.asarray(tm.start, dtype))
    z = jnp.zeros(tm.n_states, dtype)
    v0 = z if value0 is None else jnp.asarray(value0, dtype)
    p0 = z if progress0 is None else jnp.asarray(progress0, dtype)
    t0 = now()
    V, P, visits, buf_s, buf_pri, t, resid = _rtdp_graph_loop(
        Tdst, Tpack, start_cdf, key, tm.n_states, tm.n_actions,
        max_steps, batch, buffer, jnp.asarray(eps, dtype),
        jnp.asarray(restart_p, dtype), jnp.asarray(discount, dtype),
        jnp.asarray(stop_delta, dtype), jnp.asarray(decay, dtype),
        v0, p0)
    buf = np.where(np.asarray(buf_pri) > 0.0, np.asarray(buf_s), -1)
    return dict(rtdp_value=np.asarray(V), rtdp_progress=np.asarray(P),
                rtdp_visits=np.asarray(visits), rtdp_buffer=buf,
                rtdp_steps=int(t), rtdp_resid=float(resid),
                rtdp_batch=batch, rtdp_time=now() - t0)


def rtdp_sharded_polish(tm: TensorMDP, mesh, key, *, rtdp_steps: int,
                        batch: int = 256, buffer: int = 1024,
                        eps: float = 0.2, restart_p: float = 0.5,
                        rtdp_stop_delta: float = 0.0,
                        discount: float = 1.0,
                        stop_delta: float | None = None,
                        vi_eps: float | None = None, max_iter: int = 0,
                        axis: str = "d", chunk: int = 64,
                        pad_states: bool = False,
                        checkpoint_path: str | None = None,
                        checkpoint_every: int = 1,
                        protocol: str | None = None,
                        cutoff: int | None = None) -> dict:
    """Explore in-graph, polish exactly: `rtdp_graph` hands its
    partially-converged (value, progress) table to the state-sharded
    chunked VI as a warm start, so the exact solve starts sweeps from
    a near-fixpoint instead of zero.  Same return dict as
    `sharded_state_value_iteration` plus the rtdp_* diagnostics
    (prefixed as returned by rtdp_graph)."""
    from cpr_tpu.parallel import sharded_state_value_iteration

    r = rtdp_graph(tm, key, max_steps=rtdp_steps, batch=batch,
                   buffer=buffer, eps=eps, restart_p=restart_p,
                   discount=discount, stop_delta=rtdp_stop_delta)
    vi = sharded_state_value_iteration(
        tm, mesh, axis=axis, max_iter=max_iter, discount=discount,
        eps=vi_eps, stop_delta=stop_delta, chunk=chunk,
        checkpoint_path=checkpoint_path,
        checkpoint_every=checkpoint_every,
        value0=r["rtdp_value"], progress0=r["rtdp_progress"],
        pad_states=pad_states, protocol=protocol, cutoff=cutoff)
    vi.update((k, v) for k, v in r.items() if k.startswith("rtdp_"))
    return vi
