"""Literature and generic protocol MDP models.

Reference counterpart: mdp/lib/models/ (fc16sapirshtein, aft20barzur,
generic_v0, generic_v1).
"""

from cpr_tpu.mdp.models.bitcoin_sm import (  # noqa: F401
    Aft20BitcoinSM,
    Fc16BitcoinSM,
    map_params,
    mappable_params,
)
