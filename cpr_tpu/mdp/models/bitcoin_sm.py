"""Bitcoin selfish-mining MDP models from the literature.

Two variants, matching the reference:

- `Fc16BitcoinSM`: Sapirshtein et al., FC'16 (reference:
  mdp/lib/models/fc16sapirshtein.py:22-264). Randomness folded into the
  actions; stochastic start (first block already mined).
- `Aft20BitcoinSM`: Bar-Zur et al., AFT'20 (reference:
  mdp/lib/models/aft20barzur.py:28-241, itself checked against the
  authors' code). Deterministic Adopt/Override/Match; randomness only in
  Wait; Match becomes a fork state; deterministic empty start.

State is (a, h, fork): secret-chain length, public-chain length since the
last fork, and the match relevance flag.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

from cpr_tpu.mdp.explicit import MDP, sum_to_one
from cpr_tpu.mdp.implicit import Model, Transition

ADOPT, OVERRIDE, MATCH, WAIT = 0, 1, 2, 3
IRRELEVANT, RELEVANT, ACTIVE = 0, 1, 2


@dataclass(frozen=True, order=True)
class BState:
    a: int
    h: int
    fork: int


class _BitcoinSM(Model):
    """Shared parameter handling and state-space truncation."""

    def __init__(self, *, alpha: float, gamma: float,
                 maximum_fork_length: int, maximum_dag_size: int = 0):
        if not 0.0 <= alpha < 0.5:
            raise ValueError("alpha must be between 0 and 0.5")
        if not 0.0 <= gamma <= 1.0:
            raise ValueError("gamma must be between 0 and 1")
        self.alpha = alpha
        self.gamma = gamma
        self.mfl = maximum_fork_length
        self.mds = maximum_dag_size

    def __repr__(self):
        return (f"{type(self).__name__}(alpha={self.alpha}, gamma={self.gamma}, "
                f"maximum_fork_length={self.mfl}, maximum_dag_size={self.mds})")

    def truncated(self, s: BState) -> bool:
        """Stop growing forks beyond the truncation bounds
        (fc16sapirshtein.py:67-77)."""
        if self.mfl > 0 and (s.a >= self.mfl or s.h >= self.mfl):
            return True
        if self.mds > 0 and (s.a + s.h + 1 >= self.mds):
            return True
        return False

    def _mining_split(self, mk_attacker, mk_defender):
        """Two transitions: attacker finds the next block w.p. alpha."""
        return [
            Transition(probability=self.alpha, **mk_attacker),
            Transition(probability=1.0 - self.alpha, **mk_defender),
        ]

    def shutdown(self, s: BState):
        """Fair shutdown: settle the fork in the attacker's favour where it
        leads, by gamma-coinflip on a tie (fc16sapirshtein.py:198-225)."""
        out = []
        for snew, p in self.start():
            if s.h > s.a:
                out.append(Transition(probability=p, state=snew, reward=0.0,
                                      progress=s.h))
            elif s.a > s.h:
                out.append(Transition(probability=p, state=snew, reward=s.a,
                                      progress=s.a))
            else:
                out.append(Transition(probability=p * self.gamma, state=snew,
                                      reward=s.a, progress=s.a))
                out.append(Transition(probability=p * (1.0 - self.gamma),
                                      state=snew, reward=0.0, progress=s.h))
        assert sum_to_one(t.probability for t in out)
        return out


class Fc16BitcoinSM(_BitcoinSM):
    """FC'16 formulation: every action immediately resolves the next mining
    event (fc16sapirshtein.py:93-190)."""

    def start(self):
        return [
            (BState(1, 0, IRRELEVANT), self.alpha),
            (BState(0, 1, IRRELEVANT), 1.0 - self.alpha),
        ]

    def actions(self, s: BState):
        acts = []
        if not self.truncated(s):
            acts.append(WAIT)
        if s.a > s.h:
            acts.append(OVERRIDE)
        if s.a >= s.h and s.fork == RELEVANT:
            acts.append(MATCH)
        acts.append(ADOPT)
        return acts

    def apply(self, action, s: BState):
        if action == ADOPT:
            return self._mining_split(
                dict(state=BState(1, 0, IRRELEVANT), reward=0.0, progress=s.h),
                dict(state=BState(0, 1, IRRELEVANT), reward=0.0, progress=s.h),
            )
        if action == OVERRIDE:
            assert s.a > s.h
            return self._mining_split(
                dict(state=BState(s.a - s.h, 0, IRRELEVANT),
                     reward=s.h + 1.0, progress=s.h + 1.0),
                dict(state=BState(s.a - s.h - 1, 1, RELEVANT),
                     reward=s.h + 1.0, progress=s.h + 1.0),
            )
        if action == MATCH or (action == WAIT and s.fork == ACTIVE):
            # the race: defender mines on the attacker's release w.p. gamma
            assert action == WAIT or s.a >= s.h
            return [
                Transition(probability=self.alpha,
                           state=BState(s.a + 1, s.h, ACTIVE),
                           reward=0.0, progress=0.0),
                Transition(probability=self.gamma * (1.0 - self.alpha),
                           state=BState(s.a - s.h, 1, RELEVANT),
                           reward=float(s.h), progress=float(s.h)),
                Transition(probability=(1.0 - self.gamma) * (1.0 - self.alpha),
                           state=BState(s.a, s.h + 1, RELEVANT),
                           reward=0.0, progress=0.0),
            ]
        if action == WAIT:
            return self._mining_split(
                dict(state=BState(s.a + 1, s.h, IRRELEVANT), reward=0.0,
                     progress=0.0),
                dict(state=BState(s.a, s.h + 1, RELEVANT), reward=0.0,
                     progress=0.0),
            )
        raise ValueError(f"invalid action {action}")

    def honest(self, s: BState):
        return OVERRIDE if s.a > s.h else ADOPT


class Aft20BitcoinSM(_BitcoinSM):
    """AFT'20 formulation: deterministic Adopt/Override/Match, mining
    randomness only in Wait (aft20barzur.py:103-212)."""

    def start(self):
        return [(BState(0, 0, IRRELEVANT), 1.0)]

    def actions(self, s: BState):
        acts = []
        if not self.truncated(s):
            acts.append(WAIT)
        if s.a > s.h:
            acts.append(OVERRIDE)
        if s.a >= s.h and s.fork == RELEVANT:
            acts.append(MATCH)
        if s.h > 0:  # h == 0 would loop with zero progress
            acts.append(ADOPT)
        return acts

    def apply(self, action, s: BState):
        if action == ADOPT:
            return [Transition(probability=1.0, state=BState(0, 0, IRRELEVANT),
                               reward=0.0, progress=s.h)]
        if action == OVERRIDE:
            assert s.a > s.h
            return [Transition(probability=1.0,
                               state=BState(s.a - s.h - 1, 0, IRRELEVANT),
                               reward=s.h + 1.0, progress=s.h + 1.0)]
        if action == MATCH:
            assert s.fork == RELEVANT and s.a >= s.h
            return [Transition(probability=1.0, state=BState(s.a, s.h, ACTIVE),
                               reward=0.0, progress=0.0)]
        if action == WAIT:
            if s.fork != ACTIVE:
                return self._mining_split(
                    dict(state=BState(s.a + 1, s.h, IRRELEVANT), reward=0.0,
                         progress=0.0),
                    dict(state=BState(s.a, s.h + 1, RELEVANT), reward=0.0,
                         progress=0.0),
                )
            return [
                Transition(probability=self.alpha,
                           state=BState(s.a + 1, s.h, ACTIVE),
                           reward=0.0, progress=0.0),
                Transition(probability=(1.0 - self.alpha) * self.gamma,
                           state=BState(s.a - s.h, 1, RELEVANT),
                           reward=float(s.h), progress=float(s.h)),
                Transition(probability=(1.0 - self.alpha) * (1.0 - self.gamma),
                           state=BState(s.a, s.h + 1, RELEVANT),
                           reward=0.0, progress=0.0),
            ]
        raise ValueError(f"invalid action {action}")

    def honest(self, s: BState):
        if s.a == s.h == 0:
            return WAIT
        if s.a > s.h:
            return OVERRIDE
        if s.a == s.h and s.fork == RELEVANT:
            return MATCH
        return ADOPT


# -- probability reparameterization ---------------------------------------

mappable_params = dict(alpha=0.125, gamma=0.25)


def map_params(m: MDP, *, alpha: float, gamma: float) -> MDP:
    """Rewrite an MDP compiled at `mappable_params` to new (alpha, gamma)
    by exact probability-value substitution (reference:
    mdp/lib/models/fc16sapirshtein.py:231-264). Lets one compilation serve
    a whole parameter sweep."""
    assert 0.0 <= alpha <= 1.0 and 0.0 <= gamma <= 1.0
    a, g = mappable_params["alpha"], mappable_params["gamma"]
    keys = np.array([1.0, a, 1.0 - a, (1.0 - a) * g, (1.0 - a) * (1.0 - g)])
    vals = np.array([1.0, alpha, 1.0 - alpha, (1.0 - alpha) * gamma,
                     (1.0 - alpha) * (1.0 - gamma)])
    assert len(set(keys.tolist())) == len(keys), "mappable_params not mappable"

    def remap(p: float) -> float:
        i = np.argmin(np.abs(keys - p))
        assert np.isclose(keys[i], p), f"probability {p} not mappable"
        return float(vals[i])

    out = MDP(n_states=m.n_states, n_actions=m.n_actions,
              start={s: remap(p) for s, p in m.start.items()},
              src=list(m.src), act=list(m.act), dst=list(m.dst),
              prob=[remap(p) for p in m.prob],
              reward=list(m.reward), progress=list(m.progress))
    out.check()
    return out
