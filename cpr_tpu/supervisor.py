"""Supervised accelerator subprocesses: heartbeat watchdog,
probe-before-run, and probe-gated warm-restart retry.

ROADMAP item 5's TPU-attempt-hardening half.  The experimental axon
backend's observed failure mode is a *hang*, not an exception — a
crashed worker wedges backend init for the next process, and a long
device call past the worker's ~60-75 s per-call ceiling kills it — so
every accelerator entry point used to burn a full wall-clock budget
(360 s in bench.py) before writing the chip off.  This module is the
shared replacement for the per-tool Popen watchdogs (bench.py's
`_attempt`, tools/tpu_scaling_curve.py's `measure_point`,
tools/bisect_common.py), built from four pieces:

* **Heartbeat protocol** — `maybe_start_heartbeat()` in the child
  starts a daemon thread that writes one JSON line per period to
  stderr: `{"kind": "hb", "phase": ..., "n_events": ...}` where
  `phase` is the innermost `child_phase(...)` marker or open telemetry
  span path, and `n_events` is the telemetry emit counter.  The parent
  (`run_child`) resets its quiet timer on any non-beat output, on any
  beat showing *progress* (phase changed or n_events advanced), and on
  any beat claiming a `slow_ok` phase (compile/measure/... — phases
  where a minutes-long silent device call is legitimate and only the
  wall budget applies).  Identical no-progress beats outside those
  phases — exactly what a wedged device call produces, since the beat
  thread keeps running while the main thread blocks — do NOT reset it,
  so the stall is declared after `quiet_s` instead of the wall budget.
  A child that never beats (or an unparseable beat stream) leaves the
  monitor unarmed and the parent degrades to wall-clock-only
  watchdogging; malformed lines never crash the parent.

* **Probe-before-run** — `probe()` runs `python -m cpr_tpu.supervisor
  --probe` in a bounded subprocess: a tiny jit on whatever backend
  comes up, one JSON result line.  `supervise()` runs it before
  committing the real workload, so a wedged chip costs
  ~`probe_timeout_s`, not a whole measurement round.

* **Warm-restart retry** — `supervise()` maps the child's exit status
  onto the shared resilience taxonomy (guard rc -> `GuardFailure`,
  never retried; stall/hang -> `HeartbeatStall`/`SupervisedHang`;
  other rc -> `TransientFault` with `.rc`) and runs the attempts
  through `resilience.with_retries`.  A hang is re-attempted only
  after a fresh probe passes (at most `max_restarts` warm restarts);
  a failed probe, or exhausted attempts, re-raises so the caller's
  next rung (ladder descent, CPU fallback) takes over — escalation
  stays the caller's policy, detection is this module's.

* **Typed telemetry** — every decision emits a schema-v6 `supervisor`
  event (`action` probe|heartbeat_stall|hang|warm_restart|escalation,
  `site`, `reason`, timings), rendered by tools/trace_summary.py and
  consumed by the perf layer (probe rows never become baselines;
  rows measured after a warm restart carry `restart_count`).

Env knobs (parent side, read by `SupervisorConfig.from_env`):
`CPR_SUPERVISOR_TIMEOUT` (wall budget per attempt, s),
`CPR_SUPERVISOR_QUIET` (heartbeat stall interval, s),
`CPR_SUPERVISOR_HEARTBEAT` (child beat period, s; 0 disables),
`CPR_SUPERVISOR_PROBE_TIMEOUT`, `CPR_SUPERVISOR_RESTARTS`,
`CPR_SUPERVISOR_PROBE` (0 skips probe-before-run).  Child side:
the parent sets `CPR_SUPERVISOR_HEARTBEAT_S` (beat period — its
presence is what turns beating on) and `CPR_SUPERVISOR_RESTART`
(how many warm restarts preceded this attempt; `restart_count()`
reads it so measured rows can self-tag).

Deterministic proof: `CPR_FAULT_INJECT="hang@run=1"` blocks the child
at its `fault_point("run")` site and `hang@probe=1` blocks the probe
(cpr_tpu/resilience.py), so stall detection, warm restart, and
escalation are each exercised by tier-1 tests and
`make supervisor-smoke` without a wedgeable device.

Import-time this module is jax-free like telemetry/resilience/perf —
the parent process must never own a backend; only the children (and
the `--probe` / `--selftest-child` modes of this file) import jax.
"""

from __future__ import annotations

import json
import os
import queue
import subprocess
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager

from cpr_tpu import telemetry
from cpr_tpu.monitor.blackbox import dump_blackbox
from cpr_tpu.resilience import (GuardFailure, TransientFault,
                                default_classify, fault_point,
                                with_retries)
from cpr_tpu.telemetry import now

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HEARTBEAT_ENV_VAR = "CPR_SUPERVISOR_HEARTBEAT_S"
RESTART_ENV_VAR = "CPR_SUPERVISOR_RESTART"

# beat phases where a long silent device call is legitimate (substring
# match): backend bring-up, compiles, and measured kernels can run
# minutes with no host-side progress — only the wall budget applies.
# Everything else quiet past `quiet_s` is a stall.
DEFAULT_SLOW_OK = ("init", "compile", "measure", "bench:", "sweep",
                   "netsim")


# -- failure taxonomy (extends cpr_tpu.resilience) ---------------------------


class SupervisedHang(TransientFault):
    """Child ran past the wall budget.  Transient in the taxonomy, but
    `supervise` only re-attempts it after a fresh device probe passes —
    a hang means a possibly-wedged device, never worth blind retry."""


class HeartbeatStall(SupervisedHang):
    """Child's heartbeat showed no progress for `quiet_s` — the fast
    path to the same verdict as `SupervisedHang`, detected in seconds
    instead of the wall budget."""


class ProbeFailure(TransientFault):
    """The probe-before-run device health check failed or hung: the
    workload was never committed.  The caller escalates (CPU rung)."""


# -- child side --------------------------------------------------------------

_child_phases: list[str] = []
_beat_thread: threading.Thread | None = None


@contextmanager
def child_phase(name: str):
    """Mark a named phase for the heartbeat to report — used around
    regions that hold no telemetry span but may be legitimately slow
    and silent (jax import + backend bring-up: `child_phase("init")`,
    which DEFAULT_SLOW_OK grants the full wall budget)."""
    _child_phases.append(name)
    try:
        yield
    finally:
        _child_phases.pop()


def current_phase() -> str | None:
    """What the next beat reports: the innermost `child_phase` marker,
    else the innermost open telemetry span path, else None.  Read from
    the beat thread while the main thread pushes/pops — EAFP."""
    try:
        return _child_phases[-1]
    except IndexError:
        return telemetry.current().span_path()


def restart_count() -> int:
    """How many warm restarts preceded this (child) process — 0 for a
    first attempt.  Measured rows stamp this so the perf ledger can
    tag post-restart numbers (`restart_count` ledger field)."""
    try:
        return int(os.environ.get(RESTART_ENV_VAR) or 0)
    except ValueError:
        return 0


def maybe_start_heartbeat(period_s: float | None = None, stream=None):
    """Start the child-side beat thread if the parent asked for one
    (CPR_SUPERVISOR_HEARTBEAT_S in the env, or an explicit period).
    Call it FIRST in child main, before any jax import, so even an
    init wedge beats.  Idempotent; returns the thread or None.

    The thread is a daemon writing to stderr (the telemetry JSONL
    protocol piggybacked on the stderr pipe): one beat per period with
    the current phase and the telemetry emit counter as the progress
    signal.  It must never touch jax or take locks the main thread
    holds — json.dumps over five scalars only."""
    global _beat_thread
    if period_s is None:
        raw = os.environ.get(HEARTBEAT_ENV_VAR, "")
        try:
            period_s = float(raw) if raw else 0.0
        except ValueError:
            period_s = 0.0
    if period_s <= 0:
        return None
    if _beat_thread is not None and _beat_thread.is_alive():
        return _beat_thread

    def beat():
        while True:
            line = json.dumps({
                "kind": "hb", "t": round(now(), 3),
                "phase": current_phase(),
                "n_events": telemetry.current().n_emitted,
                "pid": os.getpid()})
            try:
                out = stream if stream is not None else sys.stderr
                out.write(line + "\n")
                out.flush()
            except (OSError, ValueError):
                return  # parent gone / stream closed: stop beating
            time.sleep(period_s)

    _beat_thread = threading.Thread(target=beat, name="cpr-heartbeat",
                                    daemon=True)
    _beat_thread.start()
    return _beat_thread


# -- parent side: heartbeat monitor ------------------------------------------


class HeartbeatMonitor:
    """Parses the child's stderr for beats and tracks the quiet timer.

    Activity (= quiet-timer reset) is: any non-beat line, the first
    beat (arming), a beat whose phase changed or whose n_events
    advanced, or a beat claiming a slow_ok phase.  Identical
    no-progress beats outside slow_ok phases are NOT activity — that
    signature (beat thread alive, main thread frozen) is the stall.

    Defensive by contract: `observe` never raises, whatever bytes the
    child interleaves (partial JSON, stderr noise, binary junk); an
    unparseable stream simply never arms the monitor and `stalled`
    stays False — wall-clock-only degradation, the pre-supervisor
    behavior."""

    def __init__(self, slow_ok=DEFAULT_SLOW_OK, t0: float | None = None):
        self.slow_ok = tuple(slow_ok)
        self.armed = False
        self.beats = 0
        self.last_activity = now() if t0 is None else t0
        self.last_phase: str | None = None
        self.last_n_events = -1

    def activity(self, t: float | None = None):
        self.last_activity = now() if t is None else t

    def _slow_ok(self, phase) -> bool:
        return isinstance(phase, str) and any(
            pat in phase for pat in self.slow_ok)

    def observe(self, line: str, t: float | None = None) -> bool:
        """Feed one child stderr line.  Returns True when the line was
        a heartbeat (consumed — callers should not forward it)."""
        t = now() if t is None else t
        beat = None
        s = line.strip() if isinstance(line, str) else ""
        if s.startswith("{"):
            try:
                obj = json.loads(s)
            except ValueError:
                obj = None
            if isinstance(obj, dict) and obj.get("kind") == "hb":
                beat = obj
        if beat is None:
            self.activity(t)
            return False
        self.beats += 1
        phase = beat.get("phase")
        n_events = beat.get("n_events")
        numeric = isinstance(n_events, (int, float))
        progressed = (phase != self.last_phase
                      or (numeric and n_events > self.last_n_events))
        if not self.armed or progressed or self._slow_ok(phase):
            self.activity(t)
        self.armed = True
        self.last_phase = phase if isinstance(phase, str) else None
        if numeric:
            self.last_n_events = n_events
        return True

    def stalled(self, quiet_s: float, t: float | None = None) -> bool:
        if not self.armed:
            return False
        t = now() if t is None else t
        return (t - self.last_activity) > quiet_s


# -- parent side: one watchdogged child --------------------------------------


class Attempt:
    """Result of one `run_child` run.  `status` is "ok" (rc 0),
    "failed" (nonzero rc, `.rc` set), "stalled" (heartbeat quiet past
    `quiet_s`, child killed) or "hung" (wall budget exhausted, child
    killed)."""

    def __init__(self, status: str, rc: int | None, json_lines: list,
                 stdout: str, stderr_tail: str, dur_s: float,
                 hb_armed: bool, hb_beats: int,
                 stall_phase: str | None):
        self.status = status
        self.rc = rc
        self.json_lines = json_lines
        self.stdout = stdout
        self.stderr_tail = stderr_tail
        self.dur_s = dur_s
        self.hb_armed = hb_armed
        self.hb_beats = hb_beats
        self.stall_phase = stall_phase

    @property
    def payload(self) -> str:
        return "\n".join(self.json_lines)


def _reader(stream, which: str, q: queue.Queue):
    try:
        for line in stream:
            q.put((which, line))
    except (OSError, ValueError):
        pass
    finally:
        q.put((which, None))


def run_child(cmd, *, wall_timeout_s: float, quiet_s: float | None = None,
              heartbeat_s: float | None = None, env=None, cwd=None,
              slow_ok=DEFAULT_SLOW_OK, kill_grace_s: float = 10.0,
              forward_stderr: bool = True, on_start=None) -> Attempt:
    """Run one child under the watchdog.  Never raises on child
    misbehavior — the status on the returned `Attempt` says what
    happened; `supervise` maps it onto the failure taxonomy.

    Output protocol (bench.py's, now shared): result lines are stdout
    lines starting with "{"; stderr is diagnostics plus (when
    `heartbeat_s` is set) the beat stream, forwarded live to this
    process's stderr with beats filtered out.  Manual Popen + kill +
    bounded reap because subprocess.run's post-kill wait is untimed —
    a child stuck in uninterruptible device I/O (observed: D-state on
    the device fd) would hang the parent forever; such a child is
    abandoned to its daemon readers.

    `on_start(proc)` (optional) is invoked with the live Popen handle
    right after spawn — run_child blocks until the child exits, so a
    caller that must interact with a long-lived child (e.g. SIGTERM a
    serving process once its clients finish) captures the handle here
    and signals from another thread."""
    child_env = dict(os.environ if env is None else env)
    # trace context: every supervised child inherits this process
    # tree's run id (minted here on first use), so its telemetry
    # stream stitches against the parent's (tools/trace_stitch.py)
    child_env.update(telemetry.trace_env())
    if heartbeat_s:
        child_env[HEARTBEAT_ENV_VAR] = str(heartbeat_s)
    else:
        child_env.pop(HEARTBEAT_ENV_VAR, None)
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            errors="replace", env=child_env, cwd=cwd)
    if on_start is not None:
        on_start(proc)
    q: queue.Queue = queue.Queue()
    for stream, which in ((proc.stdout, "out"), (proc.stderr, "err")):
        threading.Thread(target=_reader, args=(stream, which, q),
                         daemon=True).start()
    start = now()
    mon = HeartbeatMonitor(slow_ok=slow_ok, t0=start)
    out_lines: list[str] = []
    err_tail: deque = deque(maxlen=60)
    open_streams = 2
    status = None

    def drain_one(timeout: float) -> bool:
        nonlocal open_streams
        try:
            which, line = q.get(timeout=timeout)
        except queue.Empty:
            return False
        if line is None:
            open_streams -= 1
            return True
        if which == "err":
            if not mon.observe(line):
                err_tail.append(line)
                if forward_stderr:
                    sys.stderr.write(line)
        else:
            mon.activity()
            out_lines.append(line.rstrip("\n"))
        return True

    while True:
        drain_one(0.2)
        if open_streams == 0 and proc.poll() is not None:
            status = "ok" if proc.returncode == 0 else "failed"
            break
        t = now()
        if t - start >= wall_timeout_s:
            status = "hung"
            break
        if quiet_s is not None and mon.stalled(quiet_s, t):
            status = "stalled"
            break
    if status in ("hung", "stalled"):
        proc.kill()
        try:
            proc.wait(timeout=kill_grace_s)
        except subprocess.TimeoutExpired:
            pass  # unkillable (D-state on the device fd): abandon it
        # brief drain so diagnostics written before the kill survive
        reap_until = now() + 1.0
        while open_streams and now() < reap_until:
            drain_one(0.1)
    json_lines = [ln for ln in out_lines if ln.startswith("{")]
    return Attempt(status, proc.returncode, json_lines,
                   "\n".join(out_lines), "".join(err_tail),
                   now() - start, mon.armed, mon.beats, mon.last_phase)


# -- probe-before-run --------------------------------------------------------


def probe_cmd() -> list:
    return [sys.executable, "-m", "cpr_tpu.supervisor", "--probe"]


def selftest_cmd() -> list:
    return [sys.executable, "-m", "cpr_tpu.supervisor", "--selftest-child"]


def _event(action: str, site: str, reason: str, **extra):
    telemetry.current().event("supervisor", action=action, site=site,
                              reason=reason, **extra)


def probe(config: "SupervisorConfig | None" = None, *, env=None) -> dict:
    """Bounded device health check in a fresh subprocess: a tiny jit on
    whatever backend comes up, one JSON line back.  Returns {ok,
    status, reason, backend, dur_s} and emits the `supervisor` probe
    event.  No heartbeat — the probe's whole budget is small, and its
    own wall timeout is the detector."""
    cfg = config or SupervisorConfig.from_env()
    a = run_child(probe_cmd(), wall_timeout_s=cfg.probe_timeout_s,
                  quiet_s=None, env=env, cwd=_REPO_ROOT,
                  kill_grace_s=cfg.kill_grace_s)
    info: dict = {}
    for ln in a.json_lines:
        try:
            obj = json.loads(ln)
        except ValueError:
            continue
        if isinstance(obj, dict) and obj.get("probe"):
            info = obj
    ok = a.status == "ok" and bool(info.get("ok"))
    reason = ("ok" if ok
              else f"hung past {cfg.probe_timeout_s:g}s"
              if a.status == "hung"
              else f"rc={a.rc}" if a.status == "failed"
              else "exited 0 without a probe row")
    _event(action="probe", site="device", reason=reason, ok=ok,
           backend=info.get("backend"), dur_s=round(a.dur_s, 3))
    return {"ok": ok, "status": a.status, "reason": reason,
            "backend": info.get("backend"), "dur_s": a.dur_s}


# -- supervise: probe + watchdog + warm restart ------------------------------


class SupervisorConfig:
    """Tunables for one supervised workload.  Constructor values are
    code-level; `from_env()` lets the CPR_SUPERVISOR_* knobs override
    whatever the call site chose (bad values fail fast, before any
    watchdog budget is spent)."""

    def __init__(self, *, wall_timeout_s: float = 360.0,
                 quiet_s: float = 30.0, heartbeat_s: float = 5.0,
                 probe_timeout_s: float = 45.0, max_restarts: int = 1,
                 probe_first: bool = True, retry_pause_s: float = 15.0,
                 transient_attempts: int = 2, kill_grace_s: float = 10.0,
                 slow_ok=DEFAULT_SLOW_OK):
        if wall_timeout_s <= 0 or probe_timeout_s <= 0:
            raise ValueError("supervisor: timeouts must be positive")
        if max_restarts < 0 or transient_attempts < 1:
            raise ValueError("supervisor: bad attempt budget")
        self.wall_timeout_s = float(wall_timeout_s)
        self.quiet_s = float(quiet_s) if quiet_s else None
        self.heartbeat_s = float(heartbeat_s) if heartbeat_s else None
        self.probe_timeout_s = float(probe_timeout_s)
        self.max_restarts = int(max_restarts)
        self.probe_first = bool(probe_first)
        self.retry_pause_s = float(retry_pause_s)
        self.transient_attempts = int(transient_attempts)
        self.kill_grace_s = float(kill_grace_s)
        self.slow_ok = tuple(slow_ok)

    @property
    def max_attempts(self) -> int:
        # one budget serving both retry kinds: transient-rc retries and
        # probe-gated warm restarts (the restart cap is enforced
        # separately in the classifier)
        return max(self.transient_attempts, 1 + self.max_restarts)

    @classmethod
    def from_env(cls, **defaults) -> "SupervisorConfig":
        def num(var, key, cast=float):
            raw = os.environ.get(var)
            if raw is None or raw == "":
                return
            try:
                defaults[key] = cast(raw)
            except ValueError:
                raise SystemExit(
                    f"supervisor: bad {var}={raw!r} (want a number)"
                ) from None
        num("CPR_SUPERVISOR_TIMEOUT", "wall_timeout_s")
        num("CPR_SUPERVISOR_QUIET", "quiet_s")
        num("CPR_SUPERVISOR_HEARTBEAT", "heartbeat_s")
        num("CPR_SUPERVISOR_PROBE_TIMEOUT", "probe_timeout_s")
        num("CPR_SUPERVISOR_RESTARTS", "max_restarts", int)
        num("CPR_SUPERVISOR_PROBE", "probe_first", lambda v: bool(int(v)))
        return cls(**defaults)


class Outcome:
    """Successful `supervise` result: the child's JSON payload plus
    how hard the supervisor had to work for it."""

    def __init__(self, payload: str, restarts: int, attempts: int,
                 dur_s: float):
        self.payload = payload
        self.restarts = restarts
        self.attempts = attempts
        self.dur_s = dur_s


def supervise(cmd, *, site: str, config: SupervisorConfig | None = None,
              env=None, cwd=None, guard_rc: int | None = None,
              require_json: bool = True, on_retry=None,
              classify=None) -> Outcome:
    """Run `cmd` supervised: optional probe-before-run, heartbeat +
    wall watchdog per attempt, transient-rc retry and probe-gated warm
    restart through `with_retries`.  Raises `GuardFailure` (child
    exited `guard_rc`; never retried), `ProbeFailure` (device probe
    failed before/after a hang), `HeartbeatStall`/`SupervisedHang`
    (hang with restarts exhausted), or `TransientFault` (other child
    failures, `.rc` attached) — the caller owns the next rung.

    `classify` extends retryability for non-hang exceptions (default:
    `resilience.default_classify`); `on_retry(attempt, exc, delay)`
    is forwarded to `with_retries` (bench stamps worker-fault
    timestamps with it)."""
    cfg = config or SupervisorConfig.from_env()
    t0 = now()
    if cfg.probe_first:
        pr = probe(cfg, env=env)
        if not pr["ok"]:
            _event(action="escalation", site=site,
                   reason=f"probe-before-run failed ({pr['reason']}); "
                          f"workload never committed")
            # escalations are crash-adjacent: preserve the parent's
            # own telemetry tail before the caller's next rung acts
            dump_blackbox(f"supervisor:escalation:{site}")
            raise ProbeFailure(
                f"{site}: device probe failed ({pr['reason']})")
    state = {"restarts": 0, "attempts": 0}

    def attempt() -> Outcome:
        state["attempts"] += 1
        child_env = dict(os.environ if env is None else env)
        if state["restarts"]:
            child_env[RESTART_ENV_VAR] = str(state["restarts"])
        a = run_child(cmd, wall_timeout_s=cfg.wall_timeout_s,
                      quiet_s=cfg.quiet_s, heartbeat_s=cfg.heartbeat_s,
                      env=child_env, cwd=cwd, slow_ok=cfg.slow_ok,
                      kill_grace_s=cfg.kill_grace_s)
        if a.status == "ok" and (a.json_lines or not require_json):
            return Outcome(a.payload, state["restarts"],
                           state["attempts"], now() - t0)
        if a.status == "ok":
            fault = TransientFault(
                f"{site}: child exited 0 with no JSON payload")
            fault.rc = 0
            raise fault
        if a.status == "failed":
            if guard_rc is not None and a.rc == guard_rc:
                raise GuardFailure(
                    f"{site}: child exited guard rc {a.rc}")
            fault = TransientFault(f"{site}: child rc={a.rc}")
            fault.rc = a.rc
            raise fault
        if a.status == "stalled":
            _event(action="heartbeat_stall", site=site,
                   reason=f"no heartbeat progress for {cfg.quiet_s:g}s "
                          f"(phase={a.stall_phase}); child killed",
                   dur_s=round(a.dur_s, 3), beats=a.hb_beats)
            raise HeartbeatStall(
                f"{site}: heartbeat stall after {a.dur_s:.0f}s "
                f"(quiet {cfg.quiet_s:g}s, phase={a.stall_phase})")
        _event(action="hang", site=site,
               reason=f"wall budget {cfg.wall_timeout_s:g}s exhausted"
                      + ("" if a.hb_armed else
                         " (no heartbeat seen: wall-clock-only)"),
               dur_s=round(a.dur_s, 3))
        raise SupervisedHang(
            f"{site}: hung past {cfg.wall_timeout_s:g}s wall budget")

    base_classify = classify or default_classify

    def _classify(exc: BaseException) -> bool:
        if isinstance(exc, GuardFailure):
            return False
        if isinstance(exc, SupervisedHang):
            # warm restart is probe-gated: a hang only earns another
            # attempt when a fresh probe proves the device recovered
            if state["restarts"] >= cfg.max_restarts:
                return False
            pr = probe(cfg, env=env)
            if not pr["ok"]:
                return False
            state["restarts"] += 1
            _event(action="warm_restart", site=site,
                   reason=f"probe ok ({pr['backend']}) after "
                          f"{type(exc).__name__}; warm restart "
                          f"{state['restarts']}/{cfg.max_restarts}")
            return True
        return base_classify(exc)

    try:
        return with_retries(attempt, classify=_classify,
                            max_attempts=cfg.max_attempts,
                            base_delay_s=cfg.retry_pause_s,
                            max_delay_s=cfg.retry_pause_s,
                            jitter_frac=0.0, on_retry=on_retry,
                            name=f"supervise:{site}")
    except GuardFailure:
        raise  # deterministic: no escalation rung may mask it
    except Exception as exc:  # noqa: BLE001 — record, then re-raise
        _event(action="escalation", site=site,
               reason=f"attempts exhausted ({type(exc).__name__}: "
                      f"{exc}); caller's next rung takes over",
               attempts=state["attempts"], restarts=state["restarts"])
        dump_blackbox(f"supervisor:escalation:{site}")
        raise


# -- child entry points ------------------------------------------------------


def _probe_child():
    """`python -m cpr_tpu.supervisor --probe`: tiny-jit health check on
    whatever backend comes up.  The fault point fires BEFORE the jax
    import so an injected hang@probe costs no bring-up; a real wedge
    hangs in jax.devices() and the parent's wall timeout catches it."""
    t0 = now()
    fault_point("probe")
    import jax

    devs = jax.devices()
    # jaxlint: disable-next-line=jit-in-loop — one-shot health check
    val = float(jax.jit(lambda x: x + 1.0)(1.0))
    ok = val == 2.0 and len(devs) > 0
    print(json.dumps({"probe": True, "ok": ok,
                      "backend": devs[0].platform,
                      "device_count": len(devs),
                      "probe_s": round(now() - t0, 3)}), flush=True)
    if not ok:
        sys.exit(1)


def _selftest_child():
    """`python -m cpr_tpu.supervisor --selftest-child`: the jax-free
    stand-in workload for tier-1 tests and `make supervisor-smoke` —
    beats, passes its `run` fault point (where hang@run blocks), and
    prints one JSON row."""
    maybe_start_heartbeat()
    fault_point("run")
    print(json.dumps({"selftest": True, "ok": True, "pid": os.getpid(),
                      "restart_count": restart_count()}), flush=True)


if __name__ == "__main__":
    if "--probe" in sys.argv:
        _probe_child()
    elif "--selftest-child" in sys.argv:
        _selftest_child()
    else:
        raise SystemExit(__doc__)
