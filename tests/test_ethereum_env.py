"""Ethereum env tests: honest-share integration checks (the analog of the
reference's orphan-rate batteries, cpr_protocols.ml:200-657), DAG/uncle
validity invariants (ethereum.ml:102-151), and policy smoke runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu.envs.ethereum import EthereumSSZ
from cpr_tpu.params import make_params

# deep stochastic battery: opt-in (fast coverage lives in
# test_protocol_smoke.py)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module", params=["byzantium", "whitepaper"])
def env(request):
    return EthereumSSZ(request.param, max_steps_hint=160)


def run_policy(env, name, alpha, gamma=0.5, n_envs=192, episode_steps=128,
               seed=0):
    params = make_params(alpha=alpha, gamma=gamma, max_steps=episode_steps)
    policy = env.policies[name]
    keys = jax.random.split(jax.random.PRNGKey(seed), n_envs)
    stats = jax.vmap(
        lambda k: env.episode_stats(k, params, policy, episode_steps + 32)
    )(keys)
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    return atk / (atk + dfn)


def test_honest_policy_yields_alpha(env):
    # honest behaviour earns the compute share in expectation
    for alpha in [0.2, 0.4]:
        rel = run_policy(env, "honest", alpha)
        assert abs(rel - alpha) < 0.04, (alpha, rel)


def test_dag_structure_invariants(env):
    """Roll an episode under fn19 and check Ethereum validity
    (ethereum.ml:102-151) on the final DAG: heights/works consistent,
    uncle recency and uniqueness."""
    params = make_params(alpha=0.4, gamma=0.5, max_steps=128)
    state, obs = env.reset(jax.random.PRNGKey(3), params)
    step = jax.jit(env.step)
    policy = env.policies["fn19"]
    for _ in range(128):
        state, obs, r, done, info = step(state, policy(obs), params)
    dag = state.dag
    n = int(dag.n)
    assert not bool(dag.overflow)
    parents = np.stack([np.asarray(q) for q in dag.parents], axis=1)[:n]
    height = np.asarray(dag.height)[:n]
    work = np.asarray(dag.aux)[:n]
    miner = np.asarray(dag.miner)[:n]
    assert height[0] == 0 and work[0] == 0
    for i in range(1, n):
        ps = parents[i][parents[i] >= 0]
        p, uncles = ps[0], ps[1:]
        # check_height / check_work (ethereum.ml:118-119)
        assert height[i] == height[p] + 1
        assert work[i] == work[p] + 1 + len(uncles)
        assert miner[i] >= 0
        assert len(uncles) <= env.max_uncles
        # chain ancestors of p, up to the 6-generation window
        chain = []
        b = p
        for _ in range(6):
            chain.append(b)
            row = parents[b][parents[b] >= 0]
            if len(row) == 0:
                break
            b = row[0]
        chain_uncles = {
            u for c in chain[:-1] or chain
            for u in parents[c][parents[c] >= 0][1:]
        }
        for u in uncles:
            # check_recent (ethereum.ml:124-127)
            k = height[i] - height[u]
            assert 1 <= k <= 6, (i, u, k)
            # direct child of a chain ancestor (ethereum.ml:131-134)
            up = parents[u][parents[u] >= 0]
            assert len(up) >= 1 and up[0] in chain, (i, u)
            # uniqueness in parents and chain (ethereum.ml:128-137)
            assert list(ps).count(u) == 1
            assert u not in chain
            assert u not in chain_uncles


def test_uncles_are_rewarded(env):
    """Forks under fn19 must produce uncle inclusions: total reward beyond
    1/block on the winning chain."""
    params = make_params(alpha=0.4, gamma=0.5, max_steps=160)
    policy = env.policies["fn19"]
    keys = jax.random.split(jax.random.PRNGKey(7), 128)
    stats = jax.vmap(
        lambda k: env.episode_stats(k, params, policy, 192)
    )(keys)
    total = (np.asarray(stats["episode_reward_attacker"])
             + np.asarray(stats["episode_reward_defender"])).mean()
    # height of winner chain bounds the block-only payout at 1/block;
    # uncle inclusion pays strictly more than 1 per linear block
    progress = np.asarray(stats["episode_progress"]).mean()
    heights = progress if env.progress == "height" else None
    if heights is not None:
        assert total > heights * 1.001, (total, heights)
    else:
        assert total > 0


def test_policies_run_and_terminate(env):
    params = make_params(alpha=0.4, gamma=0.5, max_steps=96)
    for name, policy in env.policies.items():
        traj = env.rollout(jax.random.PRNGKey(5), params, policy, 200)
        done = np.asarray(traj[3])
        assert done.sum() >= 1, name
        actions = np.asarray(traj[1])
        assert actions.min() >= 0 and actions.max() < env.n_actions


def test_selfish_mining_beats_honest_at_high_alpha():
    env = EthereumSSZ("byzantium", max_steps_hint=224)
    rel_h = run_policy(env, "honest", 0.42, gamma=0.9)
    rel_s = run_policy(env, "fn19pkel", 0.42, gamma=0.9, episode_steps=192)
    # measured ~0.43 honest vs ~0.53 fn19pkel; require a real margin
    assert rel_s > rel_h + 0.05, (rel_h, rel_s)
    assert rel_s > 0.42 + 0.05, rel_s


def test_random_policy_no_crash():
    """Random actions must not violate invariants (the reference's
    "random" battery, cpr_protocols.ml:658-782)."""
    env = EthereumSSZ("byzantium", max_steps_hint=160)
    params = make_params(alpha=0.3, gamma=0.3, max_steps=128)

    def random_policy(obs):
        # hash the observation into a pseudo-random action
        h = jnp.abs(jnp.sum(obs * 1e4)).astype(jnp.int32)
        return h % env.n_actions

    traj = env.rollout(jax.random.PRNGKey(11), params, random_policy, 256)
    reward = np.asarray(traj[2])
    assert np.isfinite(reward).all()
