"""Training-driver control logic (fast tier, deterministic).

The collapse protections (VERDICT r4 #3) must be testable without a
real collapse — the nakamoto CPU demo stayed stable even at 20x
learning rate — so the driver's revert path is driven with scripted
eval scores and verified down to the restored parameters.
"""

import json

import jax
import numpy as np


def test_driver_revert_restores_best_params(monkeypatch, tmp_path):
    """Best-checkpoint revert-on-collapse fires on a scripted collapse
    and RESTORES the best parameters: with scores [0.5, 0.1] over two
    updates, the final revert happens right before the loop ends, so
    train_from_config must return the exact parameters the best
    (first) eval saw — not the drifted collapsed ones."""
    from cpr_tpu.train import driver as drv
    from cpr_tpu.train.config import TrainConfig

    scores = iter([0.5, 0.1])  # update 1 is best; update 2 collapses
    calls = []

    def fake_eval(env, cfg, net_params, **kw):
        s = next(scores)
        calls.append((s, net_params))
        return [dict(alpha=0.4, gamma=0.5, relative_reward=s,
                     reward_per_progress=s, episode_progress=1.0)]

    monkeypatch.setattr(drv, "evaluate_per_alpha", fake_eval)
    cfg = TrainConfig(
        protocol="nakamoto", alpha=0.4, episode_len=16, n_envs=8,
        total_updates=2, revert_frac=0.8,
        ppo=dict(n_steps=8, n_minibatches=2, update_epochs=1, lr=1e-3),
        eval=dict(freq=1, start_at_iteration=0))
    params, hist, rows = drv.train_from_config(
        cfg, out_dir=str(tmp_path), n_updates=2)

    reverts = [json.loads(ln) for ln in
               open(tmp_path / "metrics.jsonl") if '"revert"' in ln]
    assert len(reverts) == 1 and reverts[0]["best"] == 0.5, reverts

    best_seen = calls[0][1]
    collapsed_seen = calls[1][1]
    # training genuinely drifted between evals...
    drifted = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(best_seen),
                        jax.tree_util.tree_leaves(collapsed_seen)))
    assert drifted
    # ...and the revert restored the best checkpoint bit-for-bit
    for a, b in zip(jax.tree_util.tree_leaves(best_seen),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
