"""Training-driver control logic (fast tier, deterministic).

The collapse protections (VERDICT r4 #3) must be testable without a
real collapse — the nakamoto CPU demo stayed stable even at 20x
learning rate — so the driver's revert path is driven with scripted
eval scores and verified down to the restored parameters.
"""

import json

import jax
import numpy as np
import pytest


def test_driver_revert_restores_best_params(monkeypatch, tmp_path):
    """Best-checkpoint revert-on-collapse fires on a scripted collapse
    and RESTORES the best parameters: with scores [0.5, 0.1] over two
    updates, the final revert happens right before the loop ends, so
    train_from_config must return the exact parameters the best
    (first) eval saw — not the drifted collapsed ones."""
    from cpr_tpu.train import driver as drv
    from cpr_tpu.train.config import TrainConfig

    scores = iter([0.5, 0.1])  # update 1 is best; update 2 collapses
    calls = []

    def fake_eval(env, cfg, net_params, **kw):
        s = next(scores)
        calls.append((s, net_params))
        return [dict(alpha=0.4, gamma=0.5, relative_reward=s,
                     reward_per_progress=s, episode_progress=1.0)]

    monkeypatch.setattr(drv, "evaluate_per_alpha", fake_eval)
    cfg = TrainConfig(
        protocol="nakamoto", alpha=0.4, episode_len=16, n_envs=8,
        total_updates=2, revert_frac=0.8,
        ppo=dict(n_steps=8, n_minibatches=2, update_epochs=1, lr=1e-3),
        eval=dict(freq=1, start_at_iteration=0))
    params, hist, rows = drv.train_from_config(
        cfg, out_dir=str(tmp_path), n_updates=2)

    reverts = [json.loads(ln) for ln in
               open(tmp_path / "metrics.jsonl") if '"revert"' in ln]
    assert len(reverts) == 1 and reverts[0]["best"] == 0.5, reverts

    best_seen = calls[0][1]
    collapsed_seen = calls[1][1]
    # training genuinely drifted between evals...
    drifted = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(best_seen),
                        jax.tree_util.tree_leaves(collapsed_seen)))
    assert drifted
    # ...and the revert restored the best checkpoint bit-for-bit
    for a, b in zip(jax.tree_util.tree_leaves(best_seen),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_driver_metrics_stream_is_observable(monkeypatch, tmp_path):
    """Every update row in metrics.jsonl carries the span-derived
    wall_s/steps_per_sec, the header line (and manifest.json) embeds
    the run manifest, and the stream is flushed per update — a reader
    polling the file mid-run sees every completed update, not just the
    eval-point batches."""
    from cpr_tpu.train import driver as drv
    from cpr_tpu.train.config import TrainConfig

    seen_on_disk = []

    def fake_eval(env, cfg, net_params, **kw):
        # runs at the last update, AFTER its row was written: whatever
        # is on disk now proves the per-update flush
        with open(tmp_path / "metrics.jsonl") as f:
            seen_on_disk.extend(json.loads(ln) for ln in f)
        return [dict(alpha=0.4, gamma=0.5, relative_reward=0.3,
                     reward_per_progress=0.3, episode_progress=1.0)]

    monkeypatch.setattr(drv, "evaluate_per_alpha", fake_eval)
    cfg = TrainConfig(
        protocol="nakamoto", alpha=0.4, episode_len=16, n_envs=8,
        total_updates=2,
        ppo=dict(n_steps=8, n_minibatches=2, update_epochs=1, lr=1e-3),
        eval=dict(freq=2, start_at_iteration=0))
    drv.train_from_config(cfg, out_dir=str(tmp_path), n_updates=2)

    manifest = json.load(open(tmp_path / "manifest.json"))
    assert manifest["backend"] == "cpu"
    assert manifest["config"]["protocol"] == "nakamoto"

    lines = [json.loads(ln) for ln in open(tmp_path / "metrics.jsonl")]
    header = lines[0]
    assert header["run"] is True
    assert header["manifest"]["backend"] == "cpu"  # copied-out files
    updates = [ln for ln in lines if "update" in ln and "entropy" in ln]
    assert len(updates) == 2
    for u in updates:
        assert u["wall_s"] > 0
        # rate derived from the fenced span over this update's steps
        assert u["steps_per_sec"] == pytest.approx(
            8 * 8 / u["wall_s"], rel=0.05)
    # both update rows (plus the header) were flushed BEFORE eval ran
    assert len(seen_on_disk) >= 3
