"""v16 artifact-integrity plane: sealed envelopes, quarantine,
injected artifact damage, chaos schedules, full-jitter backoff, and
the corruption-recovery policies of every consumer (caches regenerate,
checkpoint resume cold-starts bit-identically, snapshot loads refuse
loudly, ledger/archive skip-and-report).

`make chaos-smoke` proves the same plane end-to-end under a randomized
seeded campaign; these tests pin each seam in isolation.
"""

import json
import os

import numpy as np
import pytest

from cpr_tpu import integrity, resilience, telemetry
from cpr_tpu.integrity import (ARTIFACT_ACTIONS, ChaosSchedule,
                               IntegrityError)

# -- sealed envelope ---------------------------------------------------------


def test_seal_roundtrip_verified():
    payload = b"\x00\x01binary payload\xff" * 7
    data = integrity.seal(payload)
    assert integrity.is_sealed(data)
    out, tag = integrity.unseal(data, artifact="x", kind="t")
    assert out == payload and tag == "verified"


def test_unseal_legacy_bytes_pass_through_unverified():
    raw = b'{"value": 42}'
    out, tag = integrity.unseal(raw, artifact="x", kind="t")
    assert out == raw and tag == "unverified"
    # empty file: nothing to verify, downstream deserializer judges
    assert integrity.unseal(b"") == (b"", "unverified")


@pytest.mark.parametrize("mangle,reason", [
    # payload shorter than the header promises
    (lambda d: d[:-3], "truncated"),
    # header line torn off mid-way
    (lambda d: d[: d.find(b"\n")], "truncated"),
    # a bit flip inside the payload: only the digest can see it
    (lambda d: d[:-1] + bytes([d[-1] ^ 0xFF]), "checksum"),
    # sealed by a future build
    (lambda d: d.replace(b"CPRSEAL1 1 ", b"CPRSEAL1 9 ", 1), "version"),
], ids=["short-payload", "torn-header", "bit-flip", "future-schema"])
def test_unseal_typed_reasons(mangle, reason):
    data = integrity.seal(b"payload bytes here")
    with pytest.raises(IntegrityError) as ei:
        integrity.unseal(mangle(data), artifact="/a/f", kind="k")
    assert ei.value.reason == reason
    assert ei.value.artifact == "/a/f" and ei.value.kind == "k"
    assert "/a/f" in str(ei.value)  # names the file to look at


# -- quarantine --------------------------------------------------------------


def test_quarantine_moves_artifact_and_sidecar_and_emits(tmp_path):
    art = tmp_path / "ck.npz"
    art.write_bytes(b"damaged")
    (tmp_path / "ck.npz.json").write_text('{"it": 3}')
    tele = tmp_path / "tele.jsonl"
    telemetry.configure(str(tele))
    try:
        dest = integrity.quarantine(str(art), kind="vi_checkpoint",
                                    reason="checksum")
    finally:
        telemetry.configure(None)
    assert not art.exists()
    assert open(dest, "rb").read() == b"damaged"
    qdir = integrity.quarantine_dir(str(art))
    assert os.path.dirname(dest) == qdir
    assert json.load(open(dest + ".json")) == {"it": 3}
    (e,) = [json.loads(ln) for ln in open(tele)]
    assert e["kind"] == "event" and e["name"] == "integrity"
    assert e["artifact"] == str(art)
    assert e["artifact_kind"] == "vi_checkpoint"
    assert e["reason"] == "checksum" and e["action"] == "quarantined"
    assert e["quarantine"] == dest


def test_quarantine_dedups_names_and_survives_missing_file(tmp_path):
    art = tmp_path / "f.json"
    for expect in ("f.json", "f.json.1"):
        art.write_bytes(b"x")
        dest = integrity.quarantine(str(art), kind="cache",
                                    reason="truncated", emit=False)
        assert os.path.basename(dest) == expect
    # vanished underneath us: no crash, detection still counts
    assert integrity.quarantine(str(art), kind="cache",
                                reason="truncated", emit=False) is None


# -- injected artifact damage ------------------------------------------------


def test_damage_actions_produce_their_typed_reasons(tmp_path):
    for action, reason in [("corrupt", "checksum"),
                           ("truncate", "truncated")]:
        p = tmp_path / f"{action}.bin"
        resilience.sealed_write(str(p), b"sealed artifact payload")
        integrity.damage_artifact(str(p), action)
        with pytest.raises(IntegrityError) as ei:
            integrity.unseal(p.read_bytes(), artifact=str(p), kind="t")
        assert ei.value.reason == reason
    # garble_json destroys the magic: reads as a legacy (unverified)
    # file whose deserializer is the detector of last resort
    p = tmp_path / "garble.json"
    resilience.sealed_write(str(p), b'{"k": 1}')
    integrity.damage_artifact(str(p), "garble_json")
    payload, tag = integrity.unseal(p.read_bytes())
    assert tag == "unverified"
    with pytest.raises(ValueError):
        json.loads(payload)
    with pytest.raises(ValueError, match="unknown artifact damage"):
        integrity.damage_artifact(str(p), "melt")


# -- chaos schedules ---------------------------------------------------------


def test_chaos_schedule_replayable_and_specs_valid():
    seen = set()
    for seed in range(12):
        a = ChaosSchedule(seed, rounds=2, replicas=2)
        b = ChaosSchedule(seed, rounds=2, replicas=2)
        assert a.describe() == b.describe()
        assert json.loads(json.dumps(a.describe())) == a.describe()
        # every emitted spec must parse under the real fault grammar
        for spec in [*a.fleet_specs(), a.solve_specs(),
                     f"{a.cache_action()}@cache=1"]:
            assert resilience.parse_fault_specs(spec)
        assert a.cache_action() in ARTIFACT_ACTIONS
        damage, kill = a.solve_specs().split(",")
        assert damage.split("@")[0] in ARTIFACT_ACTIONS
        assert kill.startswith("kill@vi_chunk=")
        # the kill lands one chunk after the damaged write, so the
        # corrupt checkpoint is what resume must recover past
        assert (int(kill.split("=")[1])
                == int(damage.split("=")[1]) + 1)
        seen.add(json.dumps(a.describe(), sort_keys=True))
    assert len(seen) > 1  # the seed actually randomizes


# -- artifact fault counters -------------------------------------------------


def test_artifact_counters_isolated_from_compute_counters(
        tmp_path, monkeypatch):
    """`corrupt@vi_chunk=1` means the 1st checkpoint WRITE even when
    the compute-site counter at the same name is further along — and
    compute actions never fire on the write path."""
    monkeypatch.setenv(resilience.FAULT_ENV_VAR,
                       "corrupt@vi_chunk=1,kill@vi_chunk=2")
    p = tmp_path / "ck.bin"
    # two compute passes first: kill@vi_chunk=2 fires on the second
    assert resilience.fault_point("vi_chunk") is None
    resilience.atomic_write_bytes(str(p), integrity.seal(b"payload"))
    # the write path still sees artifact-occurrence #1
    assert resilience.artifact_fault_point("vi_chunk", str(p)) \
        == "corrupt"
    with pytest.raises(IntegrityError):
        integrity.unseal(p.read_bytes(), artifact=str(p))
    with pytest.raises(resilience.InjectedKill):
        resilience.fault_point("vi_chunk")


def test_sealed_write_read_seam_with_legacy_compat(tmp_path):
    sealed = tmp_path / "new.bin"
    resilience.sealed_write(str(sealed), b"abc")
    assert resilience.sealed_read(str(sealed)) == (b"abc", "verified")
    legacy = tmp_path / "old.json"
    resilience.atomic_write_text(str(legacy), '{"v": 1}')
    payload, tag = resilience.sealed_read_json(str(legacy), kind="c")
    assert payload == {"v": 1} and tag == "unverified"


def test_sealed_read_quarantines_with_callers_action(tmp_path):
    p = tmp_path / "cache.json"
    resilience.sealed_write_json(str(p), {"k": 1})
    integrity.damage_artifact(str(p), "truncate")
    tele = tmp_path / "tele.jsonl"
    telemetry.configure(str(tele))
    try:
        with pytest.raises(IntegrityError) as ei:
            resilience.sealed_read_json(str(p), kind="mdp_grid_cache",
                                        action="regenerated")
    finally:
        telemetry.configure(None)
    assert ei.value.reason == "truncated"
    assert not p.exists()  # moved, never re-readable as live state
    (e,) = [json.loads(ln) for ln in open(tele)]
    assert (e["name"], e["artifact_kind"], e["action"]) \
        == ("integrity", "mdp_grid_cache", "regenerated")


# -- full-jitter backoff (satellite: thundering-herd spread) -----------------


def test_with_retries_full_jitter_spreads_over_whole_window():
    def run(jitter, rolls):
        delays, it = [], iter(rolls)

        def fail():
            raise OSError("transient")

        with pytest.raises(OSError):
            resilience.with_retries(
                fail, max_attempts=len(rolls) + 1, base_delay_s=1.0,
                max_delay_s=4.0, jitter=jitter, rng=lambda: next(it),
                sleep=delays.append)
        return delays

    rolls = [0.0, 0.5, 0.999, 0.25]
    caps = [1.0, 2.0, 4.0, 4.0]  # base * 2**k capped at max
    # full jitter: uniform over [0, cap] — near-zero delays included,
    # so a fleet retrying the same shed spreads instead of clumping
    assert run("full", rolls) == [c * r for c, r in zip(caps, rolls)]
    # additive keeps the deterministic floor: delay >= cap always
    additive = run("additive", rolls)
    assert additive == [c * (1.0 + 0.25 * r)
                       for c, r in zip(caps, rolls)]
    assert all(d >= c for d, c in zip(additive, caps))
    with pytest.raises(ValueError, match="jitter"):
        resilience.with_retries(lambda: None, jitter="bogus")


# -- supervisor probe under io_error (satellite) -----------------------------


def test_probe_io_error_is_probe_failure_never_retried(monkeypatch):
    """An io_error at the probe fault site must surface as a failed
    probe -> ProbeFailure before any workload attempt — not enter the
    transient retry loop (the device never answered; retrying the
    workload against it would just burn the restart budget)."""
    from cpr_tpu import supervisor
    from cpr_tpu.supervisor import ProbeFailure, SupervisorConfig

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env[resilience.FAULT_ENV_VAR] = "io_error@probe=1"
    out = supervisor.probe(
        SupervisorConfig(probe_timeout_s=120.0), env=env)
    assert out["ok"] is False and out["status"] == "failed"

    ran = []
    monkeypatch.setattr(supervisor, "run_child",
                        lambda *a, **k: ran.append(1))
    # supervise consumes the REAL probe outcome from above (run_child
    # is stubbed out, so re-probing in-process is off the table)
    monkeypatch.setattr(supervisor, "probe", lambda cfg, env=None: out)
    cfg = SupervisorConfig(wall_timeout_s=30.0, probe_timeout_s=120.0,
                           probe_first=True, transient_attempts=3,
                           retry_pause_s=0.0)
    with pytest.raises(ProbeFailure, match="probe failed"):
        supervisor.supervise(["never-spawned"], site="t", config=cfg,
                             env=env)
    assert ran == []  # the workload was never committed


# -- cache corruption is a miss (satellite) ----------------------------------


@pytest.mark.parametrize("action", ["truncate", "garble_json"])
def test_solve_grid_cache_corruption_is_miss_and_recompute(
        tmp_path, monkeypatch, action):
    from cpr_tpu.mdp.grid import solve_grid_cached

    monkeypatch.setenv("CPR_MDP_CACHE", str(tmp_path))
    kw = dict(cutoff=4, alphas=(0.3,), gammas=(0.5,), horizon=20,
              stop_delta=1e-4)
    first = solve_grid_cached("fc16", **kw)
    assert first["cached"] is False
    (entry,) = [p for p in tmp_path.iterdir() if p.suffix == ".json"]
    integrity.damage_artifact(str(entry), action)

    tele = tmp_path / "tele.jsonl"
    telemetry.configure(str(tele))
    try:
        second = solve_grid_cached("fc16", **kw)
    finally:
        telemetry.configure(None)
    assert second["cached"] is False  # corruption = miss, not a crash
    assert second["revenue"] == first["revenue"]
    events = [json.loads(ln) for ln in open(tele)]
    (e,) = [e for e in events if e.get("name") == "integrity"]
    assert e["artifact_kind"] == "mdp_grid_cache"
    assert e["action"] == "regenerated"
    assert os.path.isdir(integrity.quarantine_dir(str(entry)))
    # the regenerated entry serves verified hits again
    third = solve_grid_cached("fc16", **kw)
    assert third["cached"] is True and third["integrity"] == "verified"
    assert third["revenue"] == first["revenue"]


@pytest.mark.parametrize("action", ["truncate", "garble_json"])
def test_attack_sweep_cache_corruption_is_miss_and_recompute(
        tmp_path, monkeypatch, action):
    from cpr_tpu import netsim, network

    monkeypatch.setenv("CPR_ATTACK_CACHE", str(tmp_path))
    net = network.two_agents(alpha=0.3, activation_delay=60.0)
    kw = dict(policies=("honest",), alphas=(0.3,),
              activation_delays=(60.0,), activations=200, reps=2,
              seed=3)
    first = netsim.attack_sweep_cached(net, "two-agents", **kw)
    assert first["cached"] is False
    (entry,) = [p for p in tmp_path.iterdir() if p.suffix == ".json"]
    integrity.damage_artifact(str(entry), action)

    tele = tmp_path / "tele.jsonl"
    telemetry.configure(str(tele))
    try:
        second = netsim.attack_sweep_cached(net, "two-agents", **kw)
    finally:
        telemetry.configure(None)
    assert second["cached"] is False

    def deterministic(rows):  # wall-clock timing rides every row
        return [{k: v for k, v in r.items()
                 if k != "machine_duration_s"} for r in rows]

    assert deterministic(second["rows"]) == deterministic(first["rows"])
    events = [json.loads(ln) for ln in open(tele)]
    (e,) = [e for e in events if e.get("name") == "integrity"]
    assert e["artifact_kind"] == "attack_cache"
    assert e["action"] == "regenerated"
    third = netsim.attack_sweep_cached(net, "two-agents", **kw)
    assert third["cached"] is True and third["integrity"] == "verified"


# -- policy snapshots refuse loudly (satellite) ------------------------------


@pytest.fixture
def snapshot(tmp_path):
    import jax
    import jax.numpy as jnp

    from cpr_tpu.train.driver import export_policy_snapshot
    from cpr_tpu.train.ppo import ActorCritic

    net = ActorCritic(3, (8,))
    params = net.init(jax.random.PRNGKey(1), jnp.zeros(5))
    path = str(tmp_path / "policy.msgpack")
    export_policy_snapshot(path, params, protocol="nakamoto",
                           n_actions=3, observation_length=5,
                           hidden=(8,))
    return path


def test_snapshot_missing_sidecar_is_named_actionable_error(snapshot):
    from cpr_tpu.train.driver import load_policy_snapshot

    os.remove(snapshot + ".json")
    with pytest.raises(IntegrityError) as ei:
        load_policy_snapshot(snapshot)
    assert ei.value.reason == "sidecar_missing"
    msg = str(ei.value)
    assert snapshot in msg and "export_policy_snapshot" in msg


def test_snapshot_fingerprint_mismatch_names_both_hashes(snapshot):
    import hashlib

    from cpr_tpu.train.driver import load_policy_snapshot

    meta = json.load(open(snapshot + ".json"))
    expected = meta["payload_sha256"]
    stale = hashlib.sha256(b"some other params").hexdigest()
    meta["payload_sha256"] = stale
    resilience.atomic_write_json(snapshot + ".json", meta)
    with pytest.raises(IntegrityError) as ei:
        load_policy_snapshot(snapshot)
    assert ei.value.reason == "sidecar_missing"
    msg = str(ei.value)
    assert stale[:12] in msg and expected[:12] in msg  # found vs want


def test_snapshot_corrupt_payload_refused_with_integrity_event(
        snapshot, tmp_path):
    from cpr_tpu.train.driver import load_policy_snapshot

    integrity.damage_artifact(snapshot, "corrupt")
    tele = tmp_path / "tele.jsonl"
    telemetry.configure(str(tele))
    try:
        with pytest.raises(IntegrityError) as ei:
            load_policy_snapshot(snapshot)
    finally:
        telemetry.configure(None)
    # the sidecar fingerprint sees the damage first — either way the
    # load REFUSES rather than serving a bit-flipped policy
    assert ei.value.reason in ("sidecar_missing", "checksum")
    events = [json.loads(ln) for ln in open(tele)]
    assert any(e.get("name") == "integrity"
               and e.get("action") == "refused" for e in events)


def test_snapshot_clean_load_reports_verified(snapshot):
    from cpr_tpu.train.driver import load_policy_snapshot

    policy, meta = load_policy_snapshot(snapshot)
    assert meta["integrity"] == "verified"


# -- VI checkpoint resume falls back past corruption -------------------------


def _contraction_step(value, prog, steps):
    import jax.numpy as jnp

    deltas = []
    v = jnp.asarray(value)
    for _ in range(steps):
        nv = (v + 1.0) / 2.0
        deltas.append(jnp.max(jnp.abs(nv - v)))
        v = nv
    return v, prog, jnp.zeros_like(v, jnp.int32), jnp.stack(deltas)


def _run_vi(checkpoint_path=None):
    from cpr_tpu.mdp.explicit import run_chunk_driver

    return run_chunk_driver(_contraction_step, 8, np.float32, 1e-4, 64,
                            chunk=4, checkpoint_path=checkpoint_path)


@pytest.mark.parametrize("action", list(ARTIFACT_ACTIONS))
def test_vi_resume_past_damaged_checkpoint_bit_identical(
        tmp_path, monkeypatch, action):
    """The chaos-campaign core at unit scale: damage checkpoint write
    2, kill chunk 3, resume.  The corrupt checkpoint quarantines
    (garbled files included — the deserializer of last resort funnels
    into the same typed path) and the cold-started resume equals the
    uninterrupted solve byte for byte."""
    ref_value, _, _, _, ref_it, ref_resid = _run_vi()

    ck = str(tmp_path / "vi-ck.npz")
    monkeypatch.setenv(resilience.FAULT_ENV_VAR,
                       f"{action}@vi_chunk=2,kill@vi_chunk=3")
    with pytest.raises(resilience.InjectedKill):
        _run_vi(checkpoint_path=ck)
    monkeypatch.delenv(resilience.FAULT_ENV_VAR)

    tele = tmp_path / "tele.jsonl"
    telemetry.configure(str(tele))
    try:
        value, _, _, _, it, resid = _run_vi(checkpoint_path=ck)
    finally:
        telemetry.configure(None)
    assert it == ref_it
    np.testing.assert_array_equal(np.asarray(value),
                                  np.asarray(ref_value))
    np.testing.assert_array_equal(resid, ref_resid)
    events = [json.loads(ln) for ln in open(tele)]
    (e,) = [e for e in events if e.get("name") == "integrity"]
    assert e["artifact_kind"] == "vi_checkpoint"
    assert e["action"] == "quarantined"
    assert not any(e.get("name") == "resume" for e in events)
    assert os.listdir(integrity.quarantine_dir(ck))
    # recovery scratch still cleaned up on completion
    assert not os.path.exists(ck)


# -- ledger rows: verify-on-read ---------------------------------------------


def test_ledger_tampered_row_skipped_with_one_deduped_event(tmp_path):
    from cpr_tpu.perf.ledger import Ledger, normalize_row

    path = str(tmp_path / "ledger.jsonl")
    led = Ledger(path)
    led.append([normalize_row(dict(metric="serve_p99_s", backend="cpu",
                                   value=0.2, unit="s"), rnd=1),
                normalize_row(dict(metric="serve_p99_s", backend="cpu",
                                   value=0.21, unit="s"), rnd=2)])
    rows = led.records()
    assert len(rows) == 2
    assert integrity.row_digest(rows[0]) == rows[0]["row_id"]

    # tamper: inflate a value but keep the original row_id
    mutant = dict(rows[-1], value=999.0)
    with open(path, "a") as f:
        f.write(json.dumps(mutant, sort_keys=True) + "\n")
        f.write("{torn json\n")

    tele = tmp_path / "tele.jsonl"
    telemetry.configure(str(tele))
    try:
        fresh = Ledger(path)
        kept = fresh.records()
        again = fresh.records()  # second read: events must not repeat
    finally:
        telemetry.configure(None)
    assert [r["value"] for r in kept] == [0.2, 0.21]
    assert [r["value"] for r in again] == [0.2, 0.21]
    events = [json.loads(ln) for ln in open(tele)
              if json.loads(ln).get("name") == "integrity"]
    assert len(events) == 2  # one checksum + one torn line, no dupes
    assert {e["reason"] for e in events} == {"checksum", "truncated"}
    assert all(e["artifact_kind"] == "ledger_row" for e in events)
    assert all(e["artifact"].startswith(path + ":") for e in events)


# -- archive records: verify-on-read -----------------------------------------


def test_archive_corrupt_record_skipped_and_quarantined(tmp_path):
    from cpr_tpu.perf import archive

    root = str(tmp_path / "arch")
    rec = archive.archive_run(run="run-x", root=root)
    assert rec["integrity"] == "verified"
    assert archive.load_run("run-x", root) == rec

    p = archive.record_path("run-x", root)
    raw = open(p).read().replace('"run-x"', '"run-y"', 1)
    resilience.atomic_write_text(p, raw)  # content no longer hashes
    tele = tmp_path / "tele.jsonl"
    telemetry.configure(str(tele))
    try:
        assert archive.load_run("run-x", root) is None
        assert archive.find_runs(root) == []
    finally:
        telemetry.configure(None)
    assert os.listdir(integrity.quarantine_dir(p))
    events = [json.loads(ln) for ln in open(tele)
              if json.loads(ln).get("name") == "integrity"]
    assert events and all(e["artifact_kind"] == "archive_record"
                          for e in events)


def test_archive_legacy_record_reads_unverified(tmp_path):
    from cpr_tpu.perf import archive

    root = str(tmp_path / "arch")
    rec = archive.archive_run(run="run-z", root=root)
    p = archive.record_path("run-z", root)
    legacy = {k: v for k, v in json.loads(open(p).read()).items()
              if k not in ("record_sha256", "integrity")}
    resilience.atomic_write_text(p, json.dumps(legacy) + "\n")
    loaded = archive.load_run("run-z", root)
    assert loaded["integrity"] == "unverified"
    assert loaded["run"] == rec["run"]
