"""Sharded resident lanes (cpr_tpu/parallel/lanes.py) on the virtual
8-device mesh: bit-identity against the single-device lane API, the
uneven-shard refusals, the mesh-threaded serve engine, and the netsim
lane sharding — the fast-tier twins of `make multichip-smoke`."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")

MAX_STEPS = 16
LANES = 8
N_DEV = 4


def _env_and_params():
    from cpr_tpu.envs import registry
    from cpr_tpu.params import make_params

    env = registry.get_sized("nakamoto", MAX_STEPS)
    return env, make_params(alpha=0.25, gamma=0.5, max_steps=MAX_STEPS)


def _mesh(n=N_DEV):
    from cpr_tpu.parallel import default_mesh

    return default_mesh(devices=jax.devices()[:n])


def _keys(seeds):
    return jax.vmap(jax.random.PRNGKey)(
        jnp.asarray(seeds, dtype=jnp.uint32))


def _assert_trees_equal(a, b, what):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb),
                                      err_msg=what)


def test_sharded_step_lanes_bit_identical_with_holds_and_admission():
    """Six ticks with pseudo-random admit/hold/step masks: the sharded
    stepper must track env.step_lanes bit-for-bit — held lanes frozen
    (PRNG key included), admissions spliced, outputs equal."""
    from cpr_tpu.parallel import make_sharded_lane_fns

    env, params = _env_and_params()
    fns = make_sharded_lane_fns(env, _mesh())
    rng = np.random.RandomState(7)

    keys = _keys(range(LANES))
    fresh_keys = _keys(range(100, 100 + LANES))
    single = env.init_lanes(keys, params)
    sharded = fns.init_lanes(keys, params)
    _assert_trees_equal(single, sharded, "init_lanes carry")
    fresh_s = env.init_lanes(fresh_keys, params)
    fresh_m = fns.init_lanes(fresh_keys, params)

    for t in range(6):
        actions = jnp.asarray(
            rng.randint(0, env.n_actions, LANES), jnp.int32)
        admit = jnp.asarray(rng.rand(LANES) < 0.25)
        step = jnp.asarray(rng.rand(LANES) < 0.7)
        single, out_s = env.step_lanes(single, actions, admit, fresh_s,
                                       step, params)
        sharded, out_m = fns.step_lanes(sharded, actions, admit,
                                        fresh_m, step, params)
        _assert_trees_equal(out_s, out_m, f"tick {t} outputs")
        _assert_trees_equal(single, sharded, f"tick {t} carry")


def test_sharded_carry_stays_lane_partitioned():
    """The carry must come back under the lane NamedSharding after
    init and after a (donated) step — chained dispatches reshard
    nothing."""
    from cpr_tpu.parallel import make_sharded_lane_fns

    env, params = _env_and_params()
    fns = make_sharded_lane_fns(env, _mesh())
    carry = fns.init_lanes(_keys(range(LANES)), params)
    fresh = fns.init_lanes(_keys(range(50, 50 + LANES)), params)
    zeros = jnp.zeros(LANES, jnp.int32)
    mask = jnp.ones(LANES, bool)
    carry, _ = fns.step_lanes(carry, zeros, ~mask, fresh, mask, params)
    _, obs = carry
    assert not obs.sharding.is_fully_replicated
    assert obs.sharding.spec == fns.lane.spec


def test_uneven_lane_batches_refused_with_both_values_named():
    """6 lanes over 4 devices must raise a ValueError naming both the
    batch and the device count — from every lane entry point, the env
    batch placer, and the mesh-wrapped stats fn — not XLA's opaque
    sharding error."""
    from cpr_tpu.parallel import make_sharded_lane_fns, shard_envs

    env, params = _env_and_params()
    mesh = _mesh()
    fns = make_sharded_lane_fns(env, mesh)
    bad_keys = _keys(range(6))

    with pytest.raises(ValueError, match=r"6 lanes.*4 devices"):
        fns.init_lanes(bad_keys, params)
    with pytest.raises(ValueError, match=r"6 lanes.*4 devices"):
        fns.reset_lanes(bad_keys, params)

    carry = fns.init_lanes(_keys(range(LANES)), params)
    with pytest.raises(ValueError, match=r"6 lanes.*4 devices"):
        fns.step_lanes(carry, jnp.zeros(6, jnp.int32),
                       jnp.zeros(6, bool), carry, jnp.ones(6, bool),
                       params)

    with pytest.raises(ValueError, match=r"6 batched envs.*4 devices"):
        shard_envs(mesh, {"x": jnp.zeros((6, 3))})

    fn = env.make_episode_stats_fn(params, env.policies["honest"],
                                   MAX_STEPS, mesh=mesh)
    with pytest.raises(ValueError, match=r"6 episode streams.*4 devices"):
        fn(bad_keys)


def test_mesh_needs_multiple_of_device_count_message():
    """The refusal text carries the remainder and the fix."""
    from cpr_tpu.parallel.lanes import check_even_shards

    mesh = _mesh()
    assert check_even_shards(8, mesh) == 4
    with pytest.raises(ValueError) as ei:
        check_even_shards(10, mesh, what="lanes")
    msg = str(ei.value)
    assert "10 % 4 = 2" in msg and "multiple of the device count" in msg


def test_resident_engine_mesh_parity_and_report_devices():
    """ResidentEngine(mesh=) must splice and burst bit-identically to
    the single-device engine, and stamp the device span into its
    report (the cfg_devices fingerprint source)."""
    from cpr_tpu.serve.engine import ResidentEngine

    env, params = _env_and_params()
    eng1 = ResidentEngine(env, params, n_lanes=LANES, burst=MAX_STEPS)
    eng4 = ResidentEngine(env, params, n_lanes=LANES, burst=MAX_STEPS,
                          mesh=_mesh())
    assert eng1.n_devices == 1 and eng4.n_devices == N_DEV
    eng1.start()
    eng4.start()

    seeds = {lane: 40 + lane for lane in range(LANES - 2)}
    obs1 = eng1.splice(seeds)
    obs4 = eng4.splice(seeds)
    for lane in seeds:
        np.testing.assert_array_equal(obs1[lane], obs4[lane],
                                      err_msg=f"splice obs lane {lane}")

    pid = eng1.policy_ids["honest"]
    assert eng4.policy_ids["honest"] == pid
    lane_policy = {lane: pid for lane in seeds}  # 2 lanes stay held
    for burst in range(2):
        out1 = eng1.burst_run(lane_policy)
        out4 = eng4.burst_run(lane_policy)
        for k in out1:
            np.testing.assert_array_equal(
                np.asarray(out1[k]), np.asarray(out4[k]),
                err_msg=f"burst {burst} register {k}")

    r1, r4 = eng1.report(), eng4.report()
    assert r1["n_devices"] == 1 and r4["n_devices"] == N_DEV
    assert r1["steps"] == r4["steps"]

    with pytest.raises(ValueError, match=r"6 lanes.*4 devices"):
        ResidentEngine(env, params, n_lanes=6, burst=MAX_STEPS,
                       mesh=_mesh())


def test_netsim_engine_mesh_parity_and_guard():
    """netsim.Engine(mesh=) output arrays must equal the single-device
    run bit-for-bit, and uneven lane batches are refused up front."""
    from cpr_tpu import netsim
    from cpr_tpu.network import symmetric_clique

    net = symmetric_clique(5, activation_delay=30.0,
                           propagation_delay=1.0)
    eng1 = netsim.Engine(net, protocol="nakamoto", activations=100)
    eng4 = netsim.Engine(net, protocol="nakamoto", activations=100,
                         mesh=_mesh())
    assert eng1.n_devices == 1 and eng4.n_devices == N_DEV
    seeds, delays = list(range(LANES)), [30.0] * LANES
    out1 = eng1.run(seeds, delays)
    out4 = eng4.run(seeds, delays)
    assert sorted(out1) == sorted(out4)
    for k in out1:
        np.testing.assert_array_equal(out1[k], out4[k], err_msg=k)

    with pytest.raises(ValueError, match=r"6 netsim lanes.*4 devices"):
        eng4.run(list(range(6)), [30.0] * 6)


def test_sharded_episode_stats_parity():
    """make_episode_stats_fn(mesh=) — chunked and unchunked — must
    reproduce the single-device stats bit-for-bit."""
    env, params = _env_and_params()
    mesh = _mesh()
    keys = _keys(range(LANES))
    pol = env.policies["honest"]
    for chunk in (None, MAX_STEPS // 2):
        plain = env.make_episode_stats_fn(params, pol, MAX_STEPS,
                                          chunk=chunk)(keys)
        sharded = env.make_episode_stats_fn(params, pol, MAX_STEPS,
                                            chunk=chunk, mesh=mesh)(keys)
        _assert_trees_equal(plain, sharded, f"stats chunk={chunk}")
