"""Oracle sub-block selectors (tailstorm.ml:271-313 altruistic,
:329-380 heuristic, :418-506 optimal).

Drives the standalone C++ unit binary (native/src/test_selectors.cpp),
which builds crafted vote forests where the three selections MUST
differ and checks the own-reward ordering optimal >= heuristic >=
altruistic over 300 randomized forests x 4 incentive schemes — the
property a silently suboptimal enumeration would break.  Env-side
twins live in tests/test_quorum_selectors.py (same ordering property
on the env's candidate-frame machinery).
"""

import os
import subprocess
import sys

SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "cpr_tpu", "native", "src")


def test_selector_unit_battery(tmp_path):
    exe = tmp_path / "test_selectors"
    subprocess.run(
        ["g++", "-O1", "-std=c++17", "test_selectors.cpp", "-o", str(exe)],
        cwd=SRC, check=True, capture_output=True, text=True)
    out = subprocess.run([str(exe)], check=True, capture_output=True,
                         text=True)
    assert "selectors ok" in out.stdout, out.stdout


def test_oracle_accepts_selector_suffix():
    """The scheme string's ':selector' suffix parses and runs for both
    protocols (API contract used by the cross-engine anchors)."""
    from cpr_tpu import native

    for proto in ("tailstorm", "stree"):
        for sel in ("discount", "discount:altruistic", "discount:optimal"):
            o = native.OracleSim(proto, k=3, scheme=sel,
                                 topology="two_agents", alpha=0.3,
                                 gamma=0.5, seed=3)
            o.run(500)
            r = o.rewards(2)
            assert r[0] + r[1] > 0
            o.close()


if __name__ == "__main__":
    sys.exit(subprocess.call(
        [sys.executable, "-m", "pytest", "-x", "-q", __file__]))
