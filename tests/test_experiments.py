"""Experiments layer tests: sweeps + TSV, break-even search, and the
config-driven training driver (schedules, per-alpha eval, checkpoints).

Mirrors the reference's experiment drivers (honest_net.ml,
withholding.ml, break_even.py, cfg_model + ppo.py) in miniature.
"""

import os

import numpy as np
import pytest

from cpr_tpu.experiments import (break_even, honest_net_rows, withholding_rows,
                                 write_tsv)
from cpr_tpu.train.config import Range, TrainConfig
from cpr_tpu.train.driver import (evaluate_per_alpha, load_checkpoint,
                                  build_env, train_from_config)


def test_write_tsv_unions_columns(tmp_path):
    rows = [{"a": 1, "b": 2.5}, {"b": 3.0, "c": "x"}]
    text = write_tsv(rows, str(tmp_path / "out.tsv"))
    lines = text.strip().split("\n")
    assert lines[0] == "a\tb\tc"
    assert lines[1] == "1\t2.5\t"
    assert lines[2] == "\t3\tx"
    assert (tmp_path / "out.tsv").read_text() == text


def test_honest_net_sweep_rows():
    rows = honest_net_rows(
        protocols=(("nakamoto", {}), ("bk", dict(k=4, scheme="constant"))),
        activation_delays=(60.0, 600.0), n_activations=2_000)
    assert len(rows) == 4
    for r in rows:
        assert 0.0 <= r["orphan_rate"] < 0.2, r
        assert r["machine_duration_s"] > 0
    # easier difficulty -> fewer orphans (per protocol)
    by = {(r["protocol"], r["activation_delay"]): r for r in rows}
    assert (by[("nakamoto", 600.0)]["orphan_rate"]
            <= by[("nakamoto", 60.0)]["orphan_rate"] + 1e-9)
    write_tsv(rows)  # serializes cleanly


def test_withholding_sweep_grid():
    rows = withholding_rows(
        "nakamoto", policies=["honest", "sapirshtein-2016-sm1"],
        alphas=(0.25, 0.4), gammas=(0.0, 0.5), episode_len=128, reps=64)
    assert len(rows) == 2 * 2 * 2
    honest = {(r["alpha"], r["gamma"]): r for r in rows
              if r["attack"].endswith("honest")}
    sm1 = {(r["alpha"], r["gamma"]): r for r in rows
           if r["attack"].endswith("sm1")}
    for (a, g), r in honest.items():
        assert abs(r["relative_reward"] - a) < 0.05, r
    # SM1 beats honest at alpha=0.4, gamma=0.5
    assert sm1[(0.4, 0.5)]["relative_reward"] > \
        honest[(0.4, 0.5)]["relative_reward"]


def test_honest_net_sweep_captures_task_errors():
    """csv_runner.ml:83-102 analog: one bad config yields an error row,
    the rest of the sweep still completes."""
    rows = honest_net_rows(
        protocols=(("nakamoto", {}), ("no-such-protocol", {})),
        activation_delays=(60.0,), n_activations=500)
    assert len(rows) == 2
    ok = [r for r in rows if "error" not in r]
    bad = [r for r in rows if "error" in r]
    assert len(ok) == 1 and ok[0]["protocol"] == "nakamoto"
    assert len(bad) == 1 and bad[0]["protocol"] == "no-such-protocol"
    assert bad[0]["error"]  # non-empty "Type: message" string
    text = write_tsv(rows)
    assert "error" in text.split("\n")[0].split("\t")


def test_honest_net_analysis_expand_and_pivot():
    """honest_net.py:35-69 analog: per-node arrays expand to gini /
    weakest-strongest / efficiency columns; pivot keys by protocol."""
    from cpr_tpu.experiments import efficiency_pivot, expand_rows, gini

    assert gini([1, 1, 1, 1]) == 0.0
    assert gini([0, 0, 0, 4]) == pytest.approx(0.75)

    rows = honest_net_rows(
        protocols=(("nakamoto", {}), ("bad-proto", {})),
        activation_delays=(60.0, 600.0), n_nodes=5, n_activations=2_000)
    ex = expand_rows(rows)
    good = [r for r in ex if not r.get("error")]
    assert len(good) == 2
    for r in good:
        # uniform clique compute: compute gini 0, everyone ~1/5 of work
        assert r["compute_gini"] == 0.0
        assert abs(r["activations_weakest"] - 0.2) < 0.05
        # activations sum to the sim's total
        acts = [int(x) for x in r["node_activations"].split("|")]
        assert sum(acts) == r["activations"]
        # honest play: efficiency near 1, small reward gini
        assert abs(r["efficiency_weakest"] - 1.0) < 0.25
        assert r["reward_gini"] < 0.15
    piv = efficiency_pivot(ex)
    assert ("nakamoto", 1, "constant") in piv
    assert set(piv[("nakamoto", 1, "constant")]) == {60.0, 600.0}
    # error rows pass through expand unexpanded and stay out of the pivot
    assert not any(k[0] == "bad-proto" for k in piv)
    write_tsv(ex)


def test_withholding_sweep_captures_task_errors():
    rows = withholding_rows(
        "nakamoto", policies=["honest", "no-such-policy"],
        alphas=(0.3,), gammas=(0.5,), episode_len=64, reps=8)
    bad = [r for r in rows if "error" in r]
    ok = [r for r in rows if "error" not in r]
    assert len(bad) == 1 and bad[0]["attack"] == "nakamoto-no-such-policy"
    assert len(ok) == 1 and "relative_reward" in ok[0]


def test_break_even_sm1():
    """SM1 with gamma=0.5 breaks even in the literature around
    alpha~0.25; the search must land in a sane band."""
    a = break_even("nakamoto", "sapirshtein-2016-sm1", gamma=0.5,
                   support=(0.15, 0.45), tol=0.01, episode_len=256,
                   reps=256)
    assert 0.18 <= a <= 0.33, a
    # the cache makes the second call instant and identical
    b = break_even("nakamoto", "sapirshtein-2016-sm1", gamma=0.5,
                   support=(0.15, 0.45), tol=0.01, episode_len=256,
                   reps=256)
    assert a == b


def test_measure_mdp_rows():
    """measure-ours.py analog: sizes + wall-times + revenue per model,
    with the transition cap honored."""
    from cpr_tpu.experiments.measure_mdp import measure_rows
    from cpr_tpu.mdp.models import Fc16BitcoinSM

    rows = measure_rows(
        [("small", lambda: Fc16BitcoinSM(alpha=0.3, gamma=0.5,
                                         maximum_fork_length=8)),
         ("capped", lambda: Fc16BitcoinSM(alpha=0.3, gamma=0.5,
                                          maximum_fork_length=12))],
        horizon=20, max_transitions=2000)
    assert rows[0]["vi_iter"] > 0 and 0.2 < rows[0]["revenue"] < 0.6
    assert rows[1].get("skipped") == "transition cap"
    write_tsv(rows)


def test_rl_eval_episode_rows_and_aggregate(tmp_path):
    """rl-eval notebook layer: per-episode rows over a grid for a
    hard-coded policy and a (fresh) trained net, aggregated to the
    rl-results table shape."""
    import jax
    import jax.numpy as jnp

    from cpr_tpu.experiments import aggregate, episode_rows
    from cpr_tpu.train.ppo import ActorCritic
    from cpr_tpu.envs.registry import get_sized

    rows = episode_rows("nakamoto",
                        ["honest", "sapirshtein-2016-sm1"],
                        alphas=(0.3, 0.45), gammas=(0.5,),
                        episode_len=128, reps=16)
    assert {r["policy"] for r in rows} == \
        {"honest", "sapirshtein-2016-sm1"}
    assert all(r["kind"] == "hard-coded" for r in rows)
    # 128-step episodes in a 136-step rollout: ~1 episode per lane
    assert len(rows) >= 2 * 2 * 16

    agg = aggregate(rows)
    by = {(r["policy"], r["alpha"]): r for r in agg}
    honest = by[("honest", 0.3)]
    assert honest["n"] >= 16
    assert abs(honest["relrew_mean"] - 0.3) < 0.05
    assert honest["relrew_std"] >= 0.0
    assert honest["orphans_mean"] >= 1.0  # activations >= progress
    # SM1 beats honest at alpha=0.45 in the aggregate, like the
    # notebooks' model table
    assert by[("sapirshtein-2016-sm1", 0.45)]["relrew_mean"] > \
        by[("honest", 0.45)]["relrew_mean"]

    # trained kind: an untrained net's greedy policy still produces
    # valid episode rows tagged for the trained-vs-hard-coded compare
    env = get_sized("nakamoto", 128)
    net = ActorCritic(env.n_actions, (16,))
    params = net.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, env.observation_length)))
    trows = episode_rows("nakamoto", "ppo-seed0", alphas=(0.3,),
                         gammas=(0.5,), episode_len=128, reps=8,
                         kind="trained", net_params=params, hidden=(16,))
    assert trows and all(r["kind"] == "trained" and
                         r["policy"] == "ppo-seed0" for r in trows)
    write_tsv(rows + trows)


def test_config_yaml_roundtrip(tmp_path):
    cfg = TrainConfig.from_yaml(
        os.path.join(os.path.dirname(__file__), "..", "cpr_tpu", "train",
                     "configs", "nakamoto.yaml"))
    assert isinstance(cfg.alpha, Range)
    assert cfg.alpha_is_scheduled()
    lanes = cfg.lane_alphas(8)
    assert lanes[0] == pytest.approx(0.15)
    assert lanes[-1] == pytest.approx(0.45)
    assert len(cfg.eval_alphas()) >= 2


def test_config_validation():
    with pytest.raises(Exception):
        TrainConfig(gamma=1.5)


def test_train_driver_end_to_end(tmp_path):
    """Tiny assumption-scheduled training run: alpha range -> extended
    observations, per-alpha eval rows, best/last checkpoints."""
    cfg = TrainConfig(
        protocol="nakamoto", alpha=Range(min=0.2, max=0.4), gamma=0.5,
        episode_len=32, n_envs=64, total_updates=4,
        ppo=dict(n_steps=16, n_minibatches=2, update_epochs=2,
                 layer_size=16),
        eval=dict(freq=2, start_at_iteration=1, alpha_step=0.1,
                  episodes_per_alpha=16))
    env = build_env(cfg)
    assert env.observation_length == 6  # 4 fields + alpha + gamma
    params, history, eval_rows = train_from_config(
        cfg, out_dir=str(tmp_path), n_updates=4)
    assert len(history) == 4
    assert eval_rows and {"alpha", "relative_reward",
                          "update"} <= set(eval_rows[0])
    assert os.path.exists(tmp_path / "last-model.msgpack")
    assert os.path.exists(tmp_path / "best-model.msgpack")
    restored = load_checkpoint(str(tmp_path / "last-model.msgpack"),
                               env, cfg)
    for a, b in zip(jax_leaves(params), jax_leaves(restored)):
        np.testing.assert_array_equal(a, b)
    # restored params evaluate
    rows = evaluate_per_alpha(env, cfg, restored, episodes_per_alpha=8)
    assert len(rows) == len(cfg.eval_alphas())


def jax_leaves(tree):
    import jax
    return jax.tree.leaves(tree)


def test_all_shipped_configs_parse_and_resolve():
    """Every YAML in train/configs parses into a TrainConfig and its
    protocol key resolves through the registry (the reference ships 18
    configs under experiments/train/configs/)."""
    from cpr_tpu.envs import registry

    cfg_dir = os.path.join(os.path.dirname(__file__), "..", "cpr_tpu",
                           "train", "configs")
    names = sorted(f for f in os.listdir(cfg_dir) if f.endswith(".yaml"))
    assert len(names) >= 18
    for name in names:
        cfg = TrainConfig.from_yaml(os.path.join(cfg_dir, name))
        env = registry.get_sized(cfg.protocol, cfg.episode_len)
        assert env.n_actions >= 4, name


def test_dense_per_progress_training():
    """dense_per_progress: per-step emission + end correction sums to the
    true per-progress objective; the driver trains under it."""
    cfg = TrainConfig(
        protocol="nakamoto", alpha=0.33, gamma=0.5, episode_len=16,
        reward="dense_per_progress", n_envs=32, total_updates=2,
        ppo=dict(n_steps=24, n_minibatches=2, update_epochs=1,
                 layer_size=16),
        eval=dict(freq=100))
    params, history, eval_rows = train_from_config(cfg, n_updates=2)
    assert len(history) == 2
    assert np.isfinite(history[-1]["mean_step_reward"])


def test_dense_env_sized_for_runaway_budget():
    """Dense episodes may run 4x episode_len steps; the env must hold
    them (<=2 appends/step in tailstorm), and the sparse-only shapings
    are rejected up front."""
    cfg = TrainConfig(protocol="tailstorm-8-constant-heuristic",
                      episode_len=64, reward="dense_per_progress")
    env = build_env(cfg)
    assert env.capacity >= 2 * 4 * 64
    with pytest.raises(Exception):
        TrainConfig(reward="dense_per_progress", shape="cut")
    # small hints with large k still hold a full quorum frame
    from cpr_tpu.envs import registry
    tiny = registry.get_sized("tailstorm-8-constant-heuristic", 8)
    assert tiny.capacity >= tiny.C_MAX


def test_measure_rtdp_sweep():
    """measure-rtdp analog: RTDP rows approach the exact VI revenue as
    the step budget grows (sprint-2 measurement shape)."""
    from cpr_tpu.experiments.measure_rtdp import (measure_rtdp_rows,
                                                  rtdp_battery)

    rows = measure_rtdp_rows(
        rtdp_battery(alphas=(0.33,), fork_len=6)[:1],
        horizon=20, step_budgets=(5_000, 40_000))
    assert [r["steps"] for r in rows] == [5_000, 40_000]
    assert rows[-1]["abs_error"] < 0.02
    assert rows[-1]["n_states"] >= rows[0]["n_states"]
    write_tsv(rows)


@pytest.mark.slow
def test_config_battery_trains_each_family():
    """One tiny end-to-end training run per protocol family's shipped
    config: catches config -> env -> trainer integration gaps the
    parse/resolve test cannot (e.g. observation-length or capacity
    mismatches under schedules)."""
    import numpy as np

    cfg_dir = os.path.join(os.path.dirname(__file__), "..", "cpr_tpu",
                           "train", "configs")
    for name in ("spar-4.yaml", "stree-4-constant.yaml",
                 "sdag-4-constant.yaml", "bk-8.yaml",
                 "tailstorm-8-discount.yaml"):
        cfg = TrainConfig.from_yaml(os.path.join(cfg_dir, name))
        # shrink only the size knobs; keep the shipped hyperparameters
        cfg = cfg.model_copy(update=dict(
            n_envs=8, episode_len=16,
            ppo=cfg.ppo.model_copy(update=dict(
                n_steps=8, n_minibatches=2, update_epochs=1,
                layer_size=16)),
            eval=cfg.eval.model_copy(update=dict(freq=100))))
        params, history, rows = train_from_config(cfg, n_updates=1)
        assert np.isfinite(history[-1]["mean_step_reward"]), name


def test_report_layer_tables():
    """The executable report layer (cpr_tpu.experiments.report)
    reproduces the reference's end tables with the expected shape:
    honest_net.py:62-75's two pivots and the rl-results-condensed
    model table."""
    from cpr_tpu.experiments.report import (honest_net_report,
                                            render_pivot,
                                            rl_eval_report)

    protos = (("nakamoto", {}),
              ("bk", dict(k=4, scheme="constant")),
              ("tailstorm", dict(k=4, scheme="discount")))
    delays = (30.0, 120.0)
    expanded, pivots, text = honest_net_report(
        protocols=protos, activation_delays=delays, n_nodes=5,
        n_activations=600)
    assert len(expanded) == len(protos) * len(delays)
    eff = pivots["efficiency_weakest"]
    # one pivot column per protocol config, one cell per delay
    assert len(eff) == len(protos)
    for col in eff.values():
        assert set(col) == set(delays)
        for v in col.values():
            assert 0.0 <= v <= 2.0
    tail = pivots["tailstorm_reward_activations_gini_delta"]
    assert len(tail) == 1 and set(next(iter(tail.values()))) == set(delays)
    assert "efficiency_weakest" in text

    rows, table, text2 = rl_eval_report(
        "nakamoto", alphas=(0.25, 0.4), episode_len=64, reps=4)
    policies = {r["policy"] for r in table}
    assert len(policies) >= 2  # the env's hard-coded policy battery
    assert {r["alpha"] for r in table} == {0.25, 0.4}
    for r in table:
        assert r["n"] >= 1 and 0.0 <= r["relrew_mean"] <= 1.0
    assert text2.splitlines()[0].startswith("protocol\tpolicy")


def test_train_report_shape(tmp_path):
    import json

    p = tmp_path / "metrics.jsonl"
    rows = [{"update": i, "mean_step_reward": 0.1, "entropy": 1.0,
             "pg_loss": -1e-4} for i in range(4)]
    rows += [{"eval": True, "update": 3, "alpha": a, "gamma": 0.5,
              "relative_reward": a + 0.05} for a in (0.25, 0.35)]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    from cpr_tpu.experiments.report import train_report
    curve, final_eval, text = train_report(str(p))
    assert len(curve) == 4 and len(final_eval) == 2
    assert text.splitlines()[0].startswith("update\t")
    assert "0.3000" in text and "0.4000" in text
