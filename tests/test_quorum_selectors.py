"""Sub-block selector ordering on the env-side quorum machinery
(cpr_tpu/envs/quorum.py): optimal >= heuristic >= altruistic own
reward on the SAME candidate frame, over randomized vote forests —
the property a silently suboptimal search would break (VERDICT r4 #4).
The C++ oracle twin battery lives in tests/test_native_selectors.py;
cross-engine episode anchors in tests/test_oracle_equivalence.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu.core import dag as D
from cpr_tpu.envs import quorum as Q

VOTE = 1
C = 16


def build_forest(rng, n_votes, k):
    """Random vote forest confirming summary 0 on a mask-enabled dag;
    votes store their summary in `signer` and depth in `aux` (the
    tailstorm/stree convention)."""
    dag = D.empty(64, 2, anc_masks=True)
    dag, root = D.append(dag, jnp.array([-1, -1], jnp.int32), kind=0,
                         height=0, signer=D.NONE)
    ids = []
    for i in range(n_votes):
        if ids and rng.random() < 0.5:
            parent = int(ids[rng.integers(len(ids))])
            depth = int(dag.aux[parent]) + 1
        else:
            parent, depth = int(root), 1
        dag, v = D.append(
            dag, jnp.array([parent, -1], jnp.int32), kind=VOTE, height=0,
            aux=depth, signer=root, miner=int(rng.integers(2)),
            pow_hash=float(rng.random()), time=float(i + 1))
        ids.append(int(v))
    return dag, root, ids


def own_reward(dag, frame, leaves_c, k, discount, punish):
    """The env's own payout for a selected leaves set (the same scoring
    quorum_optimal applies), computed independently here."""
    cidx, cvalid, abits, oh = frame
    sel = (leaves_c[:, None] & abits).any(axis=0)
    if not bool(sel.any()):
        return -1.0
    score_c = jnp.where(cvalid, Q.oh_gather(
        oh, dag.aux.astype(jnp.float32) - dag.pow_hash), -jnp.inf)
    j = int(jnp.argmax(jnp.where(leaves_c, score_c, -jnp.inf)))
    depth_max = int(jnp.max(jnp.where(sel, Q.oh_gather(
        oh, dag.aux).astype(jnp.int32), -1)))
    r = (depth_max / k) if discount else 1.0
    paid = np.asarray(abits[j]) if punish else np.asarray(sel)
    own = np.asarray((Q.oh_gather(oh, dag.miner == 0) > 0.5)) & paid
    return r * float(own.sum())


@pytest.mark.parametrize("scheme", ["constant", "discount", "punish",
                                    "hybrid"])
def test_selector_own_reward_ordering(scheme):
    discount = scheme in ("discount", "hybrid")
    punish = scheme in ("punish", "hybrid")
    rng = np.random.default_rng(7)
    checked = 0
    for trial in range(40):
        k = int(rng.integers(2, 5))
        n = k + int(rng.integers(0, 5))
        dag, root, ids = build_forest(rng, n, k)
        cand = dag.exists() & (dag.kind == VOTE) & (dag.signer == root)
        own = dag.miner == 0
        frame = Q.candidate_frame(dag, cand, C, VOTE)
        cidx, cvalid, abits, oh = frame

        window = Q.optimal_window(k, C)
        combos = Q.optimal_combos(k, window)
        found_o, leaves_o = Q.quorum_optimal(
            dag, cidx, cvalid, abits, oh, own, dag.aux, k, combos, k=k,
            discount=discount, punish=punish)
        found_h, leaves_h = Q.quorum_heuristic(
            dag, cidx, cvalid, abits, oh, own, k)
        n_a, _, leaves_a, n_cand = Q.quorum_altruistic(
            dag, cidx, cvalid, abits, oh, own, dag.born_at, dag.aux, k)

        if not bool(found_o):
            continue
        checked += 1
        ro = own_reward(dag, frame, leaves_o, k, discount, punish)
        rh = own_reward(dag, frame, leaves_h, k, discount, punish) \
            if bool(found_h) else -1.0
        ra = own_reward(dag, frame, leaves_a, k, discount, punish) \
            if int(n_a) == k else -1.0
        assert ro + 1e-6 >= rh, (trial, scheme, ro, rh)
        assert ro + 1e-6 >= ra, (trial, scheme, ro, ra)
    assert checked >= 15, f"only {checked} frames had an optimal quorum"
