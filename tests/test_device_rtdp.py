"""Device-side RTDP (TensorMDP.rtdp): batched async VI with sampled
trajectories, the TPU-native counterpart of the host RTDP."""

import jax
import numpy as np
import pytest

from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.models import Fc16BitcoinSM


def _tm(fork_len=8, horizon=20):
    return ptmdp(Compiler(Fc16BitcoinSM(
        alpha=0.3, gamma=0.5, maximum_fork_length=fork_len)).mdp(),
        horizon=horizon).tensor()


def test_padded_layout_partitions_probability():
    tm = _tm()
    Tdst, Tpack, K = tm.padded_layout()
    mass = np.asarray(Tpack[..., 0]).reshape(
        tm.n_states, tm.n_actions, K).sum(-1)
    present = mass > 0
    np.testing.assert_allclose(mass[present], 1.0, rtol=1e-5)
    # padded rows carry exactly the COO transition count
    assert int((np.asarray(Tpack[..., 0]) > 0).sum()) == len(tm.src)


def test_device_rtdp_converges_to_vi():
    tm = _tm()
    vi = tm.value_iteration(stop_delta=1e-8)
    exact = tm.start_value(vi["vi_value"]) / tm.start_value(
        vi["vi_progress"])
    r = tm.rtdp(jax.random.PRNGKey(1), steps=4000, batch=128, eps=0.25)
    est = tm.start_value(r["rtdp_value"]) / tm.start_value(
        r["rtdp_progress"])
    assert abs(est - exact) / exact < 0.02, (est, exact)
    # RTDP touches only near-greedy-reachable states
    visited = int((np.asarray(r["rtdp_value"]) != 0).sum())
    assert 0 < visited < tm.n_states


def test_device_rtdp_warm_start():
    """Warm-starting from the exact values keeps them (greedy backups
    are a fixed point there)."""
    tm = _tm()
    vi = tm.value_iteration(stop_delta=1e-9)
    r = tm.rtdp(jax.random.PRNGKey(2), steps=500, batch=64, eps=0.2,
                value0=vi["vi_value"], progress0=vi["vi_progress"])
    exact = tm.start_value(vi["vi_value"])
    warm = tm.start_value(r["rtdp_value"])
    assert abs(warm - exact) < 5e-4, (warm, exact)


@pytest.mark.slow  # ~45s; fc16 convergence covers the fast tier
def test_device_rtdp_ghostdag_native_table():
    """Deep-attack MDPs need hot exploration (the attack path runs
    through low-value withholding states): with eps=0.5 the device RTDP
    converges to the exact optimum on the native-compiled GhostDAG."""
    from cpr_tpu.mdp.generic.native import compile_native

    tm = ptmdp(compile_native("ghostdag", k=2, alpha=0.33, gamma=0.5,
                              collect_garbage="simple", dag_size_cutoff=5),
               horizon=20).tensor()
    vi = tm.value_iteration(stop_delta=1e-8)
    exact = tm.start_value(vi["vi_value"]) / tm.start_value(
        vi["vi_progress"])
    r = tm.rtdp(jax.random.PRNGKey(3), steps=30000, batch=256, eps=0.5)
    est = tm.start_value(r["rtdp_value"]) / tm.start_value(
        r["rtdp_progress"])
    assert abs(est - exact) / exact < 0.005, (est, exact)
