"""Tailstorm env tests: stochastic integration checks in the style of the
reference's orphan-rate batteries (cpr_protocols.ml:200-657) plus DAG
structure invariants mirroring tailstorm.ml:156-180 validity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu.core import dag as D
from cpr_tpu.envs.tailstorm import SUMMARY, VOTE, TailstormSSZ
from cpr_tpu.params import make_params

# deep stochastic battery: opt-in (fast coverage lives in
# test_protocol_smoke.py)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def env():
    return TailstormSSZ(k=4, incentive_scheme="constant",
                        subblock_selection="heuristic", max_steps_hint=160)


def run_policy(env, name, alpha, n_envs=128, episode_steps=128, seed=0,
               gamma=0.5):
    params = make_params(alpha=alpha, gamma=gamma, max_steps=episode_steps)
    policy = env.policies[name]
    keys = jax.random.split(jax.random.PRNGKey(seed), n_envs)
    stats = jax.vmap(
        lambda k: env.episode_stats(k, params, policy, episode_steps + 32)
    )(keys)
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    return atk / (atk + dfn)


def test_honest_policy_yields_alpha(env):
    # honest behaviour earns the compute share; constant rewards pay 1 per
    # confirmed vote (tailstorm.ml:204-217)
    for alpha in [0.25, 0.4]:
        rel = run_policy(env, "honest", alpha)
        assert abs(rel - alpha) < 0.05, (alpha, rel)


def test_dag_structure_invariants(env):
    """Roll an episode, then check tailstorm validity (tailstorm.ml:156-180)
    on the final DAG: votes have one parent, depth = parent depth + 1 and
    the parent's summary; summaries reference k unique votes via leaves
    sorted by (depth desc, hash asc)."""
    params = make_params(alpha=0.35, gamma=0.5, max_steps=128)
    state, obs = env.reset(jax.random.PRNGKey(3), params)
    step = jax.jit(env.step)
    policy = env.policies["get-ahead"]
    for _ in range(128):
        state, obs, r, done, info = step(state, policy(obs), params)
    dag = state.dag
    n = int(dag.n)
    assert not bool(dag.overflow)
    parents = np.stack([np.asarray(q) for q in dag.parents], axis=1)[:n]
    kind = np.asarray(dag.kind)[:n]
    height = np.asarray(dag.height)[:n]
    depth = np.asarray(dag.aux)[:n]
    signer = np.asarray(dag.signer)[:n]
    powh = np.asarray(dag.pow_hash)[:n]

    def closure(leaf):
        seen = set()
        cur = leaf
        while cur >= 0 and kind[cur] == VOTE:
            seen.add(cur)
            cur = parents[cur][0]
        return seen

    for i in range(1, n):
        ps = parents[i][parents[i] >= 0]
        if kind[i] == VOTE:
            assert len(ps) == 1
            p = ps[0]
            assert depth[i] == depth[p] + 1
            assert np.isfinite(powh[i])
            # vote's summary link: parent's summary (or the parent itself)
            want = p if kind[p] == SUMMARY else signer[p]
            assert signer[i] == want
            assert height[i] == height[want]
        else:
            # summary: k unique votes in the leaf closure, all confirming
            # the previous summary; leaves sorted by (depth desc, hash asc)
            votes = set()
            for leaf in ps:
                assert kind[leaf] == VOTE
                votes |= closure(leaf)
            assert len(votes) == env.k, (i, ps)
            prevs = {signer[v] for v in votes}
            assert len(prevs) == 1
            assert height[i] == height[prevs.pop()] + 1
            keys = [(-depth[leaf], powh[leaf]) for leaf in ps]
            assert keys == sorted(keys), (i, keys)


def test_progress_tracks_activations(env):
    # honest run: nearly every PoW vote ends up confirmed (low orphan
    # rate), so progress ~= n_activations (progress unit = one vote,
    # tailstorm.ml:72)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=160)
    stats = env.episode_stats(
        jax.random.PRNGKey(7), params, env.policies["honest"], 192)
    prog = float(stats["episode_progress"])
    acts = float(stats["episode_n_activations"])
    assert prog > 0
    assert prog <= acts + env.k
    assert prog / acts > 0.8, (prog, acts)


def test_policies_run_and_terminate(env):
    params = make_params(alpha=0.4, gamma=0.5, max_steps=96)
    for name, policy in env.policies.items():
        traj = env.rollout(jax.random.PRNGKey(5), params, policy, 160)
        done = np.asarray(traj[3])
        assert done.sum() >= 1, name
        actions = np.asarray(traj[1])
        assert actions.min() >= 0 and actions.max() < env.n_actions


def test_withholding_beats_honest_at_high_alpha(env):
    rel_h = run_policy(env, "honest", 0.44)
    rel_w = run_policy(env, "get-ahead", 0.44, episode_steps=160)
    assert rel_w > rel_h - 0.02, (rel_h, rel_w)


def test_discount_scheme_bounds_rewards():
    # discount pays depth/k per vote (tailstorm.ml:211-217): per-progress
    # reward must be <= 1 and > 0
    env = TailstormSSZ(k=4, incentive_scheme="discount", max_steps_hint=96)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=64)
    stats = env.episode_stats(
        jax.random.PRNGKey(11), params, env.policies["honest"], 96)
    total = float(stats["episode_reward_attacker"]
                  + stats["episode_reward_defender"])
    prog = float(stats["episode_progress"])
    assert 0 < total <= prog + 1e-3, (total, prog)


def test_altruistic_selection_runs():
    env = TailstormSSZ(k=4, subblock_selection="altruistic",
                       max_steps_hint=96)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=64)
    stats = env.episode_stats(
        jax.random.PRNGKey(13), params, env.policies["honest"], 96)
    assert float(stats["episode_progress"]) > 0
