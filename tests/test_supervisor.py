"""Device supervisor (cpr_tpu/supervisor, PR 8): heartbeat watchdog,
probe-before-run, and probe-gated warm restart.

Three layers, cheapest first: pure HeartbeatMonitor parsing (the
satellite-3 robustness contract — whatever bytes a child interleaves,
the parent never crashes and at worst degrades to wall-clock-only
watchdogging), real `run_child` subprocesses over tiny inline scripts
(stall/hang/ok status mapping without importing jax), `supervise`
semantics with the probe and the child monkeypatched (taxonomy mapping
and retry counts — the coverage the old bench._attempt tests held),
and ONE full-cycle acceptance test over real children with
CPR_FAULT_INJECT=hang@run: stall detected by heartbeat well under the
wall budget, exactly one probe-gated warm restart, escalation, typed
event trail.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cpr_tpu import resilience, supervisor, telemetry  # noqa: E402
from cpr_tpu.resilience import GuardFailure, TransientFault  # noqa: E402
from cpr_tpu.supervisor import (Attempt, HeartbeatMonitor,  # noqa: E402
                                HeartbeatStall, ProbeFailure,
                                SupervisedHang, SupervisorConfig)


def _beat(phase="work", n_events=1, **extra):
    return json.dumps({"kind": "hb", "t": 0.0, "phase": phase,
                       "n_events": n_events, "pid": 1, **extra})


# -- HeartbeatMonitor: parser robustness (satellite 3) -----------------------


def test_monitor_never_raises_on_junk_and_stays_unarmed():
    """Malformed output — partial JSON, binary junk, JSON that is not a
    beat, beats with wrong-typed fields — must never crash `observe`;
    a stream with no valid beat never arms the monitor, so `stalled`
    stays False forever (wall-clock-only degradation)."""
    mon = HeartbeatMonitor(t0=0.0)
    junk = ['{"kind": "hb"', "Traceback (most recent call last):",
            "\x00\xffbinary\x01", "", "   ", '{"not": "a beat"}',
            "[1, 2, 3]", "{broken", '"just a string"']
    for line in junk:
        assert mon.observe(line, t=1.0) is False  # forwarded, not eaten
    assert mon.armed is False and mon.beats == 0
    assert mon.stalled(0.001, t=1e9) is False
    # wrong-typed beat fields are still a beat (consumed), still safe
    assert mon.observe(_beat(phase=1234, n_events="x"), t=2.0) is True
    assert mon.armed is True and mon.last_phase is None


def test_monitor_no_progress_beats_do_not_reset_quiet_timer():
    """The stall signature: beat thread alive, main thread frozen —
    identical non-slow_ok beats must NOT count as activity."""
    mon = HeartbeatMonitor(slow_ok=("compile",), t0=0.0)
    assert mon.observe(_beat(), t=0.0) is True  # first beat arms
    for t in (1.0, 2.0, 3.0, 4.0):
        mon.observe(_beat(), t=t)  # no progress
    assert mon.beats == 5
    assert mon.stalled(3.0, t=4.0) is True
    assert mon.stalled(5.0, t=4.0) is False  # quiet_s not yet exceeded


def test_monitor_progress_and_slow_ok_and_noise_reset_timer():
    mon = HeartbeatMonitor(slow_ok=("compile",), t0=0.0)
    mon.observe(_beat(n_events=1), t=0.0)
    mon.observe(_beat(n_events=2), t=5.0)  # n_events advanced
    assert mon.stalled(3.0, t=6.0) is False
    mon.observe(_beat(phase="other", n_events=2), t=10.0)  # phase change
    assert mon.stalled(3.0, t=11.0) is False
    # slow_ok phase: identical beats keep resetting (substring match)
    for t in (15.0, 20.0, 25.0):
        mon.observe(_beat(phase="bench:compile", n_events=2), t=t)
    assert mon.stalled(3.0, t=26.0) is False
    # any non-beat child output is activity too
    mon.observe(_beat(phase="work", n_events=2), t=30.0)
    mon.observe("some stderr diagnostic\n", t=35.0)
    assert mon.stalled(3.0, t=36.0) is False
    assert mon.stalled(3.0, t=40.0) is True


# -- child-side helpers ------------------------------------------------------


def test_child_phase_nesting_and_restart_count(monkeypatch):
    assert supervisor.current_phase() is None  # no phase, no open span
    with supervisor.child_phase("outer"):
        with supervisor.child_phase("inner"):
            assert supervisor.current_phase() == "inner"
        assert supervisor.current_phase() == "outer"
    assert supervisor.current_phase() is None
    monkeypatch.delenv(supervisor.RESTART_ENV_VAR, raising=False)
    assert supervisor.restart_count() == 0
    monkeypatch.setenv(supervisor.RESTART_ENV_VAR, "2")
    assert supervisor.restart_count() == 2
    monkeypatch.setenv(supervisor.RESTART_ENV_VAR, "junk")
    assert supervisor.restart_count() == 0


def test_heartbeat_thread_beats_with_phase_and_is_idempotent(monkeypatch):
    monkeypatch.delenv(supervisor.HEARTBEAT_ENV_VAR, raising=False)
    assert supervisor.maybe_start_heartbeat() is None  # env off
    assert supervisor.maybe_start_heartbeat(0) is None

    lines = []

    class CappedStream:
        def write(self, s):
            lines.append(s)

        def flush(self):
            if len(lines) >= 5:
                raise OSError("cap reached: stop the beat thread")

    monkeypatch.setattr(supervisor, "_beat_thread", None)
    with supervisor.child_phase("unit-phase"):
        t = supervisor.maybe_start_heartbeat(0.05, stream=CappedStream())
        assert t is not None
        # idempotent while alive: a second call returns the same thread
        assert supervisor.maybe_start_heartbeat(0.05) is t
        t.join(timeout=10.0)
    assert not t.is_alive()
    beats = [json.loads(s) for s in lines]
    assert len(beats) == 5
    assert all(b["kind"] == "hb" and b["pid"] == os.getpid()
               for b in beats)
    assert all(b["phase"] == "unit-phase" for b in beats)
    monkeypatch.setattr(supervisor, "_beat_thread", None)


# -- run_child over real (jax-free) children ---------------------------------


def _inline(code: str) -> list:
    return [sys.executable, "-u", "-c", textwrap.dedent(code)]


def test_run_child_ok_collects_json_payload():
    a = supervisor.run_child(_inline("""
        import sys
        print("diagnostic noise")
        print('{"row": 1}')
        sys.stderr.write("stderr diagnostic\\n")
        print('{"row": 2}')
    """), wall_timeout_s=60.0, quiet_s=None, forward_stderr=False)
    assert a.status == "ok" and a.rc == 0
    assert a.json_lines == ['{"row": 1}', '{"row": 2}']
    assert a.payload == '{"row": 1}\n{"row": 2}'
    assert "diagnostic noise" in a.stdout
    assert "stderr diagnostic" in a.stderr_tail
    assert a.hb_armed is False  # no heartbeat requested


def test_run_child_declares_stall_well_under_wall_budget():
    """A child whose beat thread stays alive while its main thread is
    frozen (identical non-slow_ok beats) is killed after ~quiet_s, not
    after the wall budget."""
    a = supervisor.run_child(_inline("""
        import json, sys, time
        while True:
            sys.stderr.write(json.dumps(
                {"kind": "hb", "phase": "wedge", "n_events": 1}) + "\\n")
            sys.stderr.flush()
            time.sleep(0.1)
    """), wall_timeout_s=60.0, quiet_s=1.0, kill_grace_s=5.0,
        forward_stderr=False)
    assert a.status == "stalled"
    assert a.dur_s < 20.0  # nowhere near the 60 s wall budget
    assert a.hb_armed and a.hb_beats >= 2
    assert a.stall_phase == "wedge"


def test_run_child_degrades_to_wall_clock_without_beats():
    # silent child: never arms, wall budget is the only detector
    a = supervisor.run_child(_inline("""
        import time
        time.sleep(60)
    """), wall_timeout_s=1.5, quiet_s=0.5, kill_grace_s=5.0,
        forward_stderr=False)
    assert a.status == "hung" and a.hb_armed is False
    # noisy-but-beatless child: every junk line is activity, so the
    # quiet timer never fires and the wall budget still bounds it
    a = supervisor.run_child(_inline("""
        import sys, time
        while True:
            sys.stderr.write("{not json, not a beat\\n")
            sys.stderr.flush()
            time.sleep(0.2)
    """), wall_timeout_s=1.5, quiet_s=0.8, kill_grace_s=5.0,
        forward_stderr=False)
    assert a.status == "hung" and a.hb_armed is False


def test_run_child_reports_failed_rc():
    a = supervisor.run_child(_inline("raise SystemExit(7)"),
                             wall_timeout_s=30.0, forward_stderr=False)
    assert a.status == "failed" and a.rc == 7


# -- supervise: taxonomy mapping + retry counts (probe/child faked) ----------


def _fake_attempt(status, rc=None, json_lines=(), stall_phase=None,
                  hb=False):
    return Attempt(status, rc, list(json_lines),
                   "\n".join(json_lines), "", 0.01, hb,
                   3 if hb else 0, stall_phase)


def _cfg(**kw):
    base = dict(wall_timeout_s=5.0, quiet_s=1.0, heartbeat_s=0.2,
                probe_timeout_s=5.0, max_restarts=1, probe_first=False,
                retry_pause_s=0.0, transient_attempts=2,
                kill_grace_s=0.5)
    base.update(kw)
    return SupervisorConfig(**base)


def test_supervise_guard_rc_never_retried(monkeypatch):
    calls = []
    monkeypatch.setattr(supervisor, "run_child",
                        lambda *a, **k: (calls.append(1),
                                         _fake_attempt("failed", rc=3))[1])
    with pytest.raises(GuardFailure):
        supervisor.supervise(["child"], site="t", config=_cfg(),
                             guard_rc=3)
    assert len(calls) == 1  # guard: no second child spawned


def test_supervise_transient_rc_retried_once_then_raises(monkeypatch):
    calls = []
    monkeypatch.setattr(supervisor, "run_child",
                        lambda *a, **k: (calls.append(1),
                                         _fake_attempt("failed", rc=139))[1])
    with pytest.raises(TransientFault) as ei:
        supervisor.supervise(["child"], site="t", config=_cfg())
    assert ei.value.rc == 139
    assert len(calls) == 2  # transient_attempts=2: one re-attempt


def test_supervise_ok_without_json_is_transient_unless_waived(monkeypatch):
    monkeypatch.setattr(supervisor, "run_child",
                        lambda *a, **k: _fake_attempt("ok", rc=0))
    with pytest.raises(TransientFault) as ei:
        supervisor.supervise(["child"], site="t", config=_cfg())
    assert ei.value.rc == 0
    out = supervisor.supervise(["child"], site="t", config=_cfg(),
                               require_json=False)
    assert out.payload == "" and out.attempts == 1 and out.restarts == 0


def test_supervise_success_returns_payload_and_counts(monkeypatch):
    monkeypatch.setattr(
        supervisor, "run_child",
        lambda *a, **k: _fake_attempt("ok", rc=0,
                                      json_lines=['{"v": 1}']))
    out = supervisor.supervise(["child"], site="t", config=_cfg())
    assert json.loads(out.payload) == {"v": 1}
    assert out.attempts == 1 and out.restarts == 0


def test_supervise_probe_gate_blocks_workload(monkeypatch):
    ran = []
    monkeypatch.setattr(supervisor, "run_child",
                        lambda *a, **k: ran.append(1))
    monkeypatch.setattr(supervisor, "probe",
                        lambda cfg, env=None: {"ok": False,
                                               "status": "hung",
                                               "reason": "hung past 5s",
                                               "backend": None,
                                               "dur_s": 5.0})
    with pytest.raises(ProbeFailure, match="hung past 5s"):
        supervisor.supervise(["child"], site="t",
                             config=_cfg(probe_first=True))
    assert ran == []  # the workload was never committed


def test_supervise_warm_restart_exactly_once_with_event_trail(
        monkeypatch, tmp_path):
    """The acceptance shape at unit scale: stall -> probe-gated warm
    restart (restart env stamped on the retried child) -> stall again
    -> escalation, with the typed v6 event trail."""
    envs, probes = [], []
    monkeypatch.setattr(
        supervisor, "run_child",
        lambda *a, **k: (envs.append(k.get("env")),
                         _fake_attempt("stalled", stall_phase="run",
                                       hb=True))[1])
    monkeypatch.setattr(
        supervisor, "probe",
        lambda cfg, env=None: (probes.append(1),
                               {"ok": True, "status": "ok",
                                "reason": "ok", "backend": "cpu",
                                "dur_s": 0.1})[1])
    trace = tmp_path / "t.jsonl"
    telemetry.configure(str(trace))
    try:
        with pytest.raises(HeartbeatStall):
            supervisor.supervise(["child"], site="t", config=_cfg())
    finally:
        telemetry.configure(None)
    assert len(envs) == 2 and len(probes) == 1
    assert supervisor.RESTART_ENV_VAR not in envs[0]
    assert envs[1][supervisor.RESTART_ENV_VAR] == "1"
    events = [json.loads(ln) for ln in open(trace)]
    actions = [e["action"] for e in events
               if e.get("name") == "supervisor"]
    assert actions == ["heartbeat_stall", "warm_restart",
                       "heartbeat_stall", "escalation"]
    for e in events:
        if e.get("name") == "supervisor":
            for key in telemetry.EVENT_FIELDS["supervisor"]:
                assert key in e, e


def test_supervise_hang_with_failed_probe_never_restarts(monkeypatch):
    calls = []
    monkeypatch.setattr(supervisor, "run_child",
                        lambda *a, **k: (calls.append(1),
                                         _fake_attempt("hung"))[1])
    monkeypatch.setattr(supervisor, "probe",
                        lambda cfg, env=None: {"ok": False,
                                               "status": "failed",
                                               "reason": "rc=1",
                                               "backend": None,
                                               "dur_s": 0.1})
    with pytest.raises(SupervisedHang):
        supervisor.supervise(["child"], site="t", config=_cfg())
    assert len(calls) == 1  # wedged device: no blind re-attempt


# -- config knobs ------------------------------------------------------------


def test_supervisor_config_env_overrides_and_validation(monkeypatch):
    for var in ("CPR_SUPERVISOR_TIMEOUT", "CPR_SUPERVISOR_QUIET",
                "CPR_SUPERVISOR_HEARTBEAT", "CPR_SUPERVISOR_PROBE_TIMEOUT",
                "CPR_SUPERVISOR_RESTARTS", "CPR_SUPERVISOR_PROBE"):
        monkeypatch.delenv(var, raising=False)
    cfg = SupervisorConfig.from_env(wall_timeout_s=100.0)
    assert cfg.wall_timeout_s == 100.0 and cfg.probe_first is True
    monkeypatch.setenv("CPR_SUPERVISOR_QUIET", "7.5")
    monkeypatch.setenv("CPR_SUPERVISOR_RESTARTS", "2")
    monkeypatch.setenv("CPR_SUPERVISOR_PROBE", "0")
    cfg = SupervisorConfig.from_env(wall_timeout_s=100.0)
    assert (cfg.quiet_s, cfg.max_restarts, cfg.probe_first) == (7.5, 2,
                                                                False)
    assert cfg.max_attempts == 3  # 1 + max_restarts beats transient 2
    monkeypatch.setenv("CPR_SUPERVISOR_TIMEOUT", "not-a-number")
    with pytest.raises(SystemExit, match="CPR_SUPERVISOR_TIMEOUT"):
        SupervisorConfig.from_env()
    with pytest.raises(ValueError):
        SupervisorConfig(wall_timeout_s=0.0)
    with pytest.raises(ValueError):
        SupervisorConfig(max_restarts=-1)


# -- the tier-1 acceptance proof: real children, injected hang ---------------


def test_injected_hang_full_cycle_over_real_children(tmp_path):
    """ISSUE-8 acceptance: with CPR_FAULT_INJECT=hang@run wedging the
    real selftest child, the heartbeat declares the stall well under
    the wall budget, a real probe child gates exactly one warm restart,
    the restarted child re-fires the per-process one-shot and stalls
    again, and supervise escalates — all visible as typed events."""
    trace = tmp_path / "supervise.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env[resilience.FAULT_ENV_VAR] = "hang@run"
    env[telemetry.TELEMETRY_ENV_VAR] = str(trace)
    env.pop(supervisor.HEARTBEAT_ENV_VAR, None)
    cfg = SupervisorConfig(wall_timeout_s=300.0, quiet_s=2.0,
                           heartbeat_s=0.2, probe_timeout_s=120.0,
                           max_restarts=1, retry_pause_s=0.1)
    telemetry.configure(str(trace))
    t0 = time.time()
    try:
        with pytest.raises(SupervisedHang):
            supervisor.supervise(supervisor.selftest_cmd(),
                                 site="t1:wedge", config=cfg, env=env)
    finally:
        telemetry.configure(None)
    elapsed = time.time() - t0
    # two stall detections at quiet_s=2 plus probe/import overhead:
    # nowhere near the 2 x 300 s the wall budget alone would burn
    assert elapsed < 150.0, elapsed
    events = [json.loads(ln) for ln in open(trace)]
    sup = [e for e in events if e.get("name") == "supervisor"]
    actions = [e["action"] for e in sup]
    assert actions.count("heartbeat_stall") == 2
    assert actions.count("warm_restart") == 1
    assert actions.count("escalation") == 1
    assert actions.count("probe") == 2  # before-run + the restart gate
    assert all(e["ok"] for e in sup if e["action"] == "probe")
    # each wedged child logged its injected fault to the shared sink
    # before blocking (O_APPEND keeps the multi-process lines whole)
    faults = [e for e in events if e.get("name") == "fault_injected"]
    assert len(faults) == 2 and all(e["site"] == "run" for e in faults)


def test_probe_child_runs_clean_on_cpu():
    """The real --probe child end-to-end: one bounded subprocess, one
    JSON verdict line, probe() parses it and emits the typed event."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(resilience.FAULT_ENV_VAR, None)
    env.pop(telemetry.TELEMETRY_ENV_VAR, None)
    out = supervisor.probe(
        SupervisorConfig(probe_timeout_s=120.0), env=env)
    assert out["ok"] is True and out["status"] == "ok"
    assert out["backend"] == "cpu"


def test_selftest_child_reports_restart_count():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(resilience.FAULT_ENV_VAR, None)
    env[supervisor.RESTART_ENV_VAR] = "1"
    r = subprocess.run(supervisor.selftest_cmd(), env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0
    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["selftest"] is True and row["restart_count"] == 1
