"""Drop-in proof for the gym surface: third-party trainers driving the
registered envs through nothing but the public gymnasium API.

Reference counterpart: the reference trains its OCaml-backed gym envs
through stable-baselines3 (experiments/train/ppo.py:283,399-453) and
ships rl_zoo3 hyperparams for the Rust gym.  Two tiers here:

- test_sb3_smoke: literally sb3's PPO for a few hundred steps.  sb3 is
  not in this image (no-install environment), so it skips cleanly here
  and runs wherever sb3 exists.
- test_torch_trainer_smoke / test_batched_core_torch_rollout: a minimal
  REINFORCE loop written directly against the gymnasium contract
  (reset/step 5-tuple, spaces, reward float) with a torch policy — the
  exact surface sb3 consumes, exercised end-to-end with a third-party
  tensor library rather than this repo's JAX stack.
"""

import numpy as np
import pytest

import cpr_tpu.gym  # noqa: F401  (registers the env ids)
import gymnasium


def test_sb3_smoke():
    sb3 = pytest.importorskip(
        "stable_baselines3",
        reason="stable-baselines3 not installed in this image")
    env = gymnasium.make("cpr-nakamoto-v0")
    model = sb3.PPO("MlpPolicy", env, n_steps=64, batch_size=64,
                    n_epochs=1, verbose=0)
    model.learn(total_timesteps=256)
    obs, _ = env.reset(seed=0)
    action, _ = model.predict(obs, deterministic=True)
    assert env.action_space.contains(int(action))


def test_torch_trainer_smoke():
    """A REINFORCE loop over Core: third-party (torch) policy, public
    gymnasium API only — the sb3 substrate contract."""
    torch = pytest.importorskip("torch")

    env = gymnasium.make("cpr-nakamoto-v0")
    obs_dim = int(np.prod(env.observation_space.shape))
    n_act = int(env.action_space.n)
    policy = torch.nn.Sequential(
        torch.nn.Linear(obs_dim, 32), torch.nn.Tanh(),
        torch.nn.Linear(32, n_act))
    opt = torch.optim.Adam(policy.parameters(), lr=3e-3)

    total_steps = 0
    for episode in range(3):
        obs, info = env.reset(seed=episode)
        logps, rewards = [], []
        terminated = truncated = False
        while not (terminated or truncated) and len(rewards) < 200:
            logits = policy(torch.as_tensor(obs, dtype=torch.float32))
            dist = torch.distributions.Categorical(logits=logits)
            action = dist.sample()
            obs, reward, terminated, truncated, info = env.step(
                int(action))
            assert isinstance(reward, float) or np.isscalar(reward)
            logps.append(dist.log_prob(action))
            rewards.append(float(reward))
            total_steps += 1
        ret = torch.as_tensor(np.cumsum(rewards[::-1])[::-1].copy(),
                              dtype=torch.float32)
        loss = -(torch.stack(logps) * ret).mean()
        opt.zero_grad()
        loss.backward()
        opt.step()
    assert total_steps >= 3  # episodes ran and the optimizer stepped


def test_batched_core_torch_rollout():
    """BatchedCore's vectorized 5-tuple consumed by a torch loop."""
    torch = pytest.importorskip("torch")

    from cpr_tpu.gym import BatchedCore

    env = BatchedCore("nakamoto", n_envs=8, max_steps=64)
    obs, info = env.reset(seed=0)
    assert obs.shape[0] == 8
    for _ in range(16):
        logits = torch.zeros((8, int(env.action_space.nvec[0])))
        actions = torch.distributions.Categorical(
            logits=logits).sample().numpy()
        obs, rewards, terminated, truncated, info = env.step(actions)
        assert obs.shape[0] == 8 and rewards.shape == (8,)
        assert terminated.shape == (8,) and truncated.shape == (8,)
