"""cpr_tpu.serve: resident lane API, continuous batching, and the
service front-end.

The load-bearing contracts, each proven bit-for-bit where the ISSUE-9
acceptance demands it:

* `step_lanes` admission replays `rollout()` — a lane admitted
  mid-flight with seed S produces the identical trajectory to
  `rollout(PRNGKey(S), ...)`, and lane retire/re-admit never leaks
  state across sessions sharing a lane;
* the gym adapters re-expressed over the resident stepper match the
  legacy per-instance jit paths they replaced (Core step-then-reset,
  BatchedCore step + host-sync + reset-splice) output-for-output;
* the in-graph policy burst completes episodes identically to rollout;
* the asyncio server round-trips all of it over the wire, including a
  graceful drain, and the serve report rows ingest into the perf
  ledger and gate (satellite f).

Shapes are kept tiny and constant (nakamoto max_steps=16, 4 lanes,
burst 8) so every test reuses the same compiled programs.
"""

import asyncio
import json
import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu.envs import registry
from cpr_tpu.params import make_params
from cpr_tpu.serve import LaneScheduler, ResidentEngine, ServeClient
from cpr_tpu.serve import protocol as wire

MAX_STEPS = 16
N_LANES = 4
BURST = 8


@pytest.fixture(scope="module")
def env():
    return registry.get_sized("nakamoto", MAX_STEPS)


@pytest.fixture(scope="module")
def params():
    return make_params(alpha=0.25, gamma=0.5, max_steps=MAX_STEPS)


def _lane_keys(seeds):
    return jax.vmap(jax.random.PRNGKey)(
        jnp.asarray(seeds, dtype=jnp.uint32))


def _solo(env, params, seed, n_steps):
    """Reference trajectory: one auto-resetting rollout stream."""
    obs, action, reward, done, info = env.rollout(
        jax.random.PRNGKey(seed), params, env.policies["honest"], n_steps)
    return (np.asarray(obs), np.asarray(action), np.asarray(reward),
            np.asarray(done), {k: np.asarray(v) for k, v in info.items()})


def _masks(n, lanes=None):
    m = np.zeros(n, bool)
    for i in (range(n) if lanes is None else lanes):
        m[i] = True
    return jnp.asarray(m)


# -- resident stepper ------------------------------------------------------


def test_mid_flight_admission_is_bit_identical_to_rollout(env, params):
    """A lane admitted at tick 7 of a busy block replays
    rollout(PRNGKey(17)) exactly: pre-step obs, actions, rewards,
    dones, and episode aggregates, across episode boundaries."""
    honest = env.policies["honest"]
    honest_v = jax.jit(jax.vmap(honest))
    carry = env.init_lanes(_lane_keys(range(N_LANES)), params)
    template = env.init_lanes(_lane_keys(range(N_LANES)), params)
    no_admit = _masks(N_LANES, [])
    all_step = _masks(N_LANES)
    lane, admit_tick, total = 2, 7, 40
    rows = []
    for t in range(total):
        if t == admit_tick:
            fresh = env.init_lanes(_lane_keys([17] * N_LANES), params)
            carry, _ = env.step_lanes(
                carry, jnp.zeros(N_LANES, jnp.int32),
                _masks(N_LANES, [lane]), fresh, _masks(N_LANES, []),
                params)
        pre = np.asarray(carry[1])
        acts = jnp.asarray(honest_v(jnp.asarray(pre)), jnp.int32)
        carry, (_, reward, done, info) = env.step_lanes(
            carry, acts, no_admit, template, all_step, params)
        rows.append((pre[lane], int(np.asarray(acts)[lane]),
                     float(reward[lane]), bool(done[lane]),
                     {k: float(v[lane]) for k, v in info.items()}))
    n = total - admit_tick
    obs, action, reward, done, info = _solo(env, params, 17, n)
    for t, (pre, act, rew, dn, inf) in enumerate(rows[admit_tick:]):
        assert np.array_equal(pre, obs[t]), f"obs diverged at tick {t}"
        assert act == int(action[t])
        assert rew == float(reward[t])
        assert dn == bool(done[t])
        for k, v in inf.items():
            assert v == float(info[k][t]), (k, t)


def test_lane_reuse_does_not_leak_state(env, params):
    """Retire/backfill: the same lane serving seed 5 then seed 9 gives
    each session the exact solo-rollout trajectory of its own seed —
    nothing survives the re-admission splice, and held lanes stay
    bit-frozen."""
    honest = env.policies["honest"]
    carry = env.init_lanes(_lane_keys(range(N_LANES)), params)
    template = env.init_lanes(_lane_keys(range(N_LANES)), params)
    lane, n = 1, 12
    held_before = None

    def run_session(carry, seed):
        fresh = env.init_lanes(_lane_keys([seed] * N_LANES), params)
        carry, _ = env.step_lanes(
            carry, jnp.zeros(N_LANES, jnp.int32), _masks(N_LANES, [lane]),
            fresh, _masks(N_LANES, []), params)
        rows = []
        for _ in range(n):
            pre = np.asarray(carry[1])
            act = jnp.zeros(N_LANES, jnp.int32).at[lane].set(
                jnp.asarray(honest(jnp.asarray(pre[lane])), jnp.int32))
            carry, (_, reward, done, info) = env.step_lanes(
                carry, act, _masks(N_LANES, []), template,
                _masks(N_LANES, [lane]), params)
            rows.append((float(reward[lane]), bool(done[lane]),
                         float(info["episode_reward_attacker"][lane])))
        return carry, rows

    carry, first = run_session(carry, 5)
    held_before = np.asarray(carry[1][3]).copy()
    carry, second = run_session(carry, 9)
    assert np.array_equal(held_before, np.asarray(carry[1][3])), \
        "held lane 3 observation changed while never stepped"
    for seed, rows in ((5, first), (9, second)):
        _, _, reward, done, info = _solo(env, params, seed, n)
        for t, (rew, dn, att) in enumerate(rows):
            assert rew == float(reward[t]), (seed, t)
            assert dn == bool(done[t]), (seed, t)
            assert att == float(info["episode_reward_attacker"][t])


# -- gym adapters vs the legacy per-instance jit paths ---------------------


def test_batched_core_matches_legacy_step_reset_splice(env, params):
    """BatchedCore.step (one resident dispatch) vs the path it
    replaced: vmapped step, host sync on done, then a reset from the
    post-step lane key spliced in with a full-tree where."""
    from cpr_tpu.gym import BatchedCore

    n_envs, seed, total = 3, 5, 40
    core = BatchedCore("nakamoto", n_envs=n_envs, max_steps=MAX_STEPS,
                       seed=seed)
    new_obs, _ = core.reset()

    key = jax.random.PRNGKey(seed)
    key, k = jax.random.split(key)
    keys = jax.random.split(k, n_envs)
    state, obs = jax.vmap(lambda kk: env.reset(kk, params))(keys)
    assert np.array_equal(new_obs, np.asarray(obs, np.float64))

    vstep = jax.jit(lambda s, a: jax.vmap(
        lambda ss, aa: env.step(ss, aa, params))(s, a))
    vreset = jax.jit(lambda ks: jax.vmap(
        lambda kk: env.reset(kk, params))(ks))
    honest_v = jax.jit(jax.vmap(env.policies["honest"]))
    for t in range(total):
        acts = np.asarray(honest_v(jnp.asarray(obs)), np.int32)
        state, obs2, reward, done, info = vstep(state, jnp.asarray(acts))
        rstate, robs = vreset(state.key)
        where = lambda d, a, b: jnp.where(  # noqa: E731
            d.reshape(d.shape + (1,) * (a.ndim - 1)), a, b)
        state = jax.tree.map(lambda a, b: where(done, a, b), rstate, state)
        obs = where(done, robs, obs2)

        n_obs, n_rew, n_done, _, n_info = core.step(acts)
        assert np.array_equal(n_obs, np.asarray(obs, np.float64)), t
        assert np.array_equal(n_rew, np.asarray(reward)), t
        assert np.array_equal(n_done, np.asarray(done)), t
        for kf, v in n_info.items():
            assert np.array_equal(v, np.asarray(info[kf])), (kf, t)


def test_core_matches_legacy_jit_step_loop(env, params):
    """Core.step (resident width-1 lane) vs the legacy per-instance
    jit(reset)/jit(step) loop, through a full episode plus the
    follow-up reset (same PRNG bookkeeping on both sides)."""
    from cpr_tpu.gym import Core

    seed = 3
    core = Core("nakamoto", max_steps=MAX_STEPS, seed=seed)
    new_obs, _ = core.reset()

    jstep = jax.jit(lambda s, a: env.step(s, a, params))
    jreset = jax.jit(lambda k: env.reset(k, params))
    key = jax.random.PRNGKey(seed)
    key, k = jax.random.split(key)
    state, obs = jreset(k)
    assert np.array_equal(new_obs, np.asarray(obs, np.float64))

    done = False
    steps = 0
    while not done:
        act = core.policy(np.asarray(obs), "honest")
        state, obs, reward, done, info = jstep(state, jnp.asarray(act))
        n_obs, n_rew, n_done, _, n_info = core.step(act)
        assert np.array_equal(n_obs, np.asarray(obs, np.float64))
        assert n_rew == float(reward) and n_done == bool(done)
        for kf, v in n_info.items():
            assert v == float(info[kf]), kf
        steps += 1
        assert steps <= MAX_STEPS + 1
    key, k2 = jax.random.split(key)
    _, obs_r = jreset(k2)
    new_obs2, _ = core.reset()
    assert np.array_equal(new_obs2, np.asarray(obs_r, np.float64))


# -- the resident engine ---------------------------------------------------


def test_engine_burst_completes_episodes_like_rollout(env, params):
    """In-graph policy bursts: each spliced lane's first completed
    episode carries the same aggregates as the solo rollout of its
    seed (actions computed by the same policy inside the program)."""
    engine = ResidentEngine(env, params, n_lanes=N_LANES, burst=BURST)
    engine.start()
    hid = engine.policy_ids["honest"]
    seeds = {0: 5, 2: 9}
    obs0 = engine.splice(seeds)
    assert set(obs0) == set(seeds)
    bursts = [engine.burst_run({ln: hid for ln in seeds})
              for _ in range(3 * MAX_STEPS // BURST)]
    for lane, seed in seeds.items():
        obs, _, _, s_done, s_info = _solo(env, params, seed, MAX_STEPS + 1)
        assert np.array_equal(obs0[lane], obs[0]), \
            f"admitted obs0 mismatch for lane {lane}"
        # first burst whose first-done register fired for this lane
        b = next(i for i, o in enumerate(bursts) if o["done"][lane])
        idx = b * BURST + int(bursts[b]["done_step"][lane])
        s_idx = int(np.argmax(s_done))
        assert idx == s_idx
        assert (bursts[b]["episode_reward_attacker"][lane]
                == s_info["episode_reward_attacker"][s_idx])
        assert (bursts[b]["episode_n_steps"][lane]
                == s_info["episode_n_steps"][s_idx])
    rep = engine.report()
    assert rep["steps"] == len(seeds) * 3 * MAX_STEPS
    assert rep["bursts"] == 3 * MAX_STEPS // BURST
    assert rep["steps_per_sec"] > 0


def test_engine_rejects_empty_policy_table(env, params):
    class Dummy:
        policies = {}

    with pytest.raises(ValueError, match="no servable policies"):
        ResidentEngine(Dummy(), params, n_lanes=2)


# -- scheduler -------------------------------------------------------------


def test_scheduler_backfill_and_occupancy():
    sched = LaneScheduler(2)
    a, b, c = object(), object(), object()
    assert sched.enqueue(a) == 0 and sched.enqueue(b) == 1
    assert sched.enqueue(c) == 2
    assert sched.place() == [(0, a), (1, b)]
    assert sched.occupancy() == 1.0 and sched.n_queued() == 1
    assert sched.place() == []  # full: c waits
    assert sched.retire(0) is a
    assert sched.place() == [(0, c)]  # backfill into the freed lane
    assert sched.assigned() == {0: c, 1: b}
    assert sched.cancel(a) is False  # already placed+retired, not queued
    evicted = sched.drain()
    assert set(evicted) == {b, c}
    assert sched.n_assigned() == 0 and sched.n_queued() == 0
    with pytest.raises(ValueError):
        LaneScheduler(0)


def test_scheduler_priority_ordering_and_cancel_edge_cases():
    """Satellite 3: priority classes order placement (FIFO within a
    class), cancel is exact about what it can drop, drain evicts
    queued-then-placed, and the bounded queue raises QueueFull."""
    from cpr_tpu.serve.scheduler import QueueFull

    sched = LaneScheduler(2, max_queued=4)
    a, b, c, d = object(), object(), object(), object()
    assert sched.enqueue(a, priority=2) == 0
    assert sched.enqueue(b, priority=1) == 0  # ahead of batch a
    assert sched.enqueue(c, priority=1) == 1  # FIFO within class 1
    assert sched.enqueue(d, priority=0) == 0  # interactive: the front
    assert sched.place() == [(0, d), (1, b)]
    # cancel: unknown session, already-placed session -> False;
    # still-queued session -> True
    assert sched.cancel(object()) is False
    assert sched.cancel(d) is False
    assert sched.cancel(c) is True
    assert sched.n_queued() == 1  # only a remains
    # drain evicts queued first, then placed (ascending lane id)
    assert sched.drain() == [a, d, b]
    assert sched.n_queued() == 0 and sched.n_assigned() == 0
    # the bound: 4 queued, the 5th raises instead of growing
    for i in range(4):
        sched.enqueue(object())
    with pytest.raises(QueueFull, match="capacity"):
        sched.enqueue(object())


def test_scheduler_tenant_quota_skips_without_blocking():
    """A tenant at quota stays queued (aging normally) while sessions
    of other tenants behind it still place; a same-tick retire frees
    the quota and the next place() backfills the parked session."""
    sched = LaneScheduler(2, tenant_quota=1)
    a, b, c = object(), object(), object()
    sched.enqueue(a, tenant="t")
    sched.enqueue(b, tenant="t")
    sched.enqueue(c, tenant="u")
    # a holds t's one lane; b is at quota and parked; c jumps past it
    assert sched.place() == [(0, a), (1, c)]
    assert sched.n_queued() == 1
    assert sched.tenant_load("t") == 2  # one lane held + one queued
    assert sched.tenant_load("u") == 1
    assert sched.tenant_load(None) == 0
    # retire -> same-tick backfill: freeing t's lane admits b
    assert sched.retire(0) is a
    assert sched.place() == [(0, b)]
    assert sched.tenant_load("t") == 1


# -- wire protocol ---------------------------------------------------------


def test_protocol_frame_roundtrip_and_eof():
    obj = {"op": "hello", "xs": [1, 2.5, "s"], "none": None}

    async def run():
        r = asyncio.StreamReader()
        r.feed_data(wire.pack_frame(obj))
        r.feed_eof()
        return await wire.read_frame(r), await wire.read_frame(r)

    first, second = asyncio.run(run())
    assert first == obj
    assert second is None  # clean EOF at a frame boundary

    async def torn():
        r = asyncio.StreamReader()
        r.feed_data(wire.pack_frame(obj)[:3])
        r.feed_eof()
        return await wire.read_frame(r)

    with pytest.raises(wire.ProtocolError, match="mid-header"):
        asyncio.run(torn())
    with pytest.raises(wire.ProtocolError, match="exceeds"):
        wire.pack_frame({"x": "y" * (wire.MAX_FRAME + 1)})


# -- policy snapshots ------------------------------------------------------


def test_policy_snapshot_roundtrip(tmp_path, env):
    from cpr_tpu.train.driver import (export_policy_snapshot,
                                      load_policy_snapshot)
    from cpr_tpu.train.ppo import ActorCritic

    hidden = (8,)
    net = ActorCritic(env.n_actions, hidden)
    net_params = net.init(jax.random.PRNGKey(1),
                          jnp.zeros(env.observation_length))
    path = str(tmp_path / "policy.msgpack")
    export_policy_snapshot(path, net_params, protocol="nakamoto",
                           n_actions=env.n_actions,
                           observation_length=env.observation_length,
                           hidden=hidden, score=1.25)
    policy, meta = load_policy_snapshot(path)
    assert meta["protocol"] == "nakamoto" and meta["score"] == 1.25
    obs = jnp.linspace(0.0, 1.0, env.observation_length)
    logits, _ = net.apply(net_params, obs)
    assert int(policy(obs)) == int(jnp.argmax(logits))


# -- server end-to-end -----------------------------------------------------


def test_server_end_to_end_over_the_wire(env, params):
    """In-process server: a seeded policy episode and an interactive
    episode stepped through the wire both reproduce the solo rollout
    of their seed; stats report; drain op shuts the loop down."""
    engine = ResidentEngine(env, params, n_lanes=N_LANES, burst=BURST)
    engine.start()
    from cpr_tpu.serve.server import ServeServer

    ports: queue.Queue = queue.Queue()

    def run():
        async def amain():
            server = ServeServer(engine, heartbeat_s=0.2,
                                 idle_sleep_s=0.001)
            await server.start()
            ports.put(server.port)
            await server.serve_until_drained()

        asyncio.run(amain())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    port = ports.get(timeout=60)
    honest = env.policies["honest"]
    try:
        with ServeClient("127.0.0.1", port, timeout=120) as c:
            hello = c.request("hello")
            assert hello["ok"] and hello["n_lanes"] == N_LANES
            assert "honest" in hello["policies"]

            r = c.request("episode.run", policy="honest", seed=7)
            assert r["ok"] and r["policy"] == "honest" and r["seed"] == 7
            # v8: every reply carries its own trace id + latency
            # breakdown; the internal _lane/_splice_s keys never leak
            assert isinstance(r["trace_id"], str) and r["trace_id"]
            lat = r["latency"]
            assert lat["queue_wait_s"] >= 0.0 and lat["service_s"] >= 0.0
            assert abs(lat["total_s"]
                       - (lat["queue_wait_s"] + lat["service_s"])) < 1e-6
            assert "_lane" not in r and "_splice_s" not in r
            _, _, _, done, info = _solo(env, params, 7, MAX_STEPS + 1)
            idx = int(np.argmax(done))
            ep = r["episode"]
            assert ep["reward_attacker"] == float(
                info["episode_reward_attacker"][idx])
            assert ep["reward_defender"] == float(
                info["episode_reward_defender"][idx])
            assert ep["n_steps"] == int(info["episode_n_steps"][idx])

            o = c.request("episode.open", seed=11)
            assert o["ok"]
            obs, _, reward, done, _ = _solo(env, params, 11,
                                            MAX_STEPS + 1)
            assert np.array_equal(np.asarray(o["obs"]), obs[0])
            cur = np.asarray(o["obs"])
            for step in range(MAX_STEPS + 1):
                act = int(honest(jnp.asarray(cur)))
                s = c.request("episode.step", session=o["session"],
                              action=act)
                assert s["ok"]
                assert s["latency"]["total_s"] >= 0.0 and "_lane" not in s
                assert s["reward"] == float(reward[step]), step
                assert s["done"] == bool(done[step]), step
                if s["done"]:
                    break
                cur = np.asarray(s["obs"])
            assert s["done"]
            dead = c.request("episode.step", session=o["session"],
                             action=0)
            assert not dead["ok"] and "session" in dead["error"]

            stats = c.request("stats")
            assert stats["ok"] and stats["report"]["steps"] > 0
            assert stats["occupancy"] == 0.0  # everything retired
            # v8 SLO surface: backlog age, in-flight op counts, and
            # the per-op-family latency histograms
            assert stats["oldest_queued_s"] == 0.0
            assert stats["pending_steps"] == 0
            assert stats["exec_ops"] == 0
            fams = stats["latencies"]
            assert fams["episode.run"]["count"] >= 1
            assert 0.0 < fams["episode.run"]["p50_s"] \
                <= fams["episode.run"]["p99_s"]
            assert fams["episode.step"]["count"] >= MAX_STEPS
            assert c.request("drain")["ok"]
    finally:
        t.join(60)
    assert not t.is_alive(), "server loop did not drain"


def _spawn_server(server):
    """Run one ServeServer loop in a daemon thread; returns (thread,
    bound port)."""
    ports: queue.Queue = queue.Queue()

    def run():
        async def amain():
            await server.start()
            ports.put(server.port)
            await server.serve_until_drained()

        asyncio.run(amain())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, ports.get(timeout=60)


def test_drain_under_load_never_hangs_blocking_clients(env, params):
    """Satellite b: SIGTERM/drain while executor ops are in flight.
    The op already running on the worker thread finishes and its
    client gets the real reply; the op still queued behind it is
    cancelled by shutdown(cancel_futures=True) and its client gets a
    draining refusal — nobody hangs on a dropped future."""
    import time
    from concurrent.futures import ThreadPoolExecutor

    engine = ResidentEngine(env, params, n_lanes=N_LANES, burst=BURST)
    engine.start()
    from cpr_tpu.serve.server import ServeServer

    server = ServeServer(engine, heartbeat_s=0.2, idle_sleep_s=0.001)
    entered, release = threading.Event(), threading.Event()
    calls = []

    def slow_query(req):
        calls.append(req)
        if len(calls) == 1:
            entered.set()
            assert release.wait(30.0), "test never released the op"
        return dict(ok=True, n=len(calls))

    server._netsim_query = slow_query
    t, port = _spawn_server(server)

    def query():
        with ServeClient("127.0.0.1", port, timeout=60) as c:
            return c.request("netsim.query")

    try:
        with ThreadPoolExecutor(max_workers=2) as pool:
            fa = pool.submit(query)
            assert entered.wait(30.0), "first query never reached the " \
                                       "executor"
            fb = pool.submit(query)
            with ServeClient("127.0.0.1", port, timeout=60) as c:
                for _ in range(500):  # until both ops are in flight
                    if c.request("stats")["exec_ops"] >= 2:
                        break
                    time.sleep(0.01)
                else:
                    raise AssertionError("second query never in flight")
                assert c.request("drain")["ok"]
            rb = fb.result(timeout=30)
            assert not rb.get("ok") and rb.get("draining"), rb
            assert rb["latency"]["total_s"] >= 0.0  # refusals carry one
            release.set()
            ra = fa.result(timeout=30)
            assert ra.get("ok") and ra["n"] == 1, ra
    finally:
        release.set()
    t.join(60)
    assert not t.is_alive(), "server loop did not drain under load"
    assert len(calls) == 1, "the cancelled queued op ran anyway"


def test_request_trace_propagates_across_the_wire(env, params, tmp_path):
    """The client's _trace frame field and the server's reply agree on
    one trace id, and both sides emit a v8 `request` event carrying
    it (in-process both land on the same sink)."""
    from cpr_tpu import telemetry
    from cpr_tpu.serve.server import ServeServer

    engine = ResidentEngine(env, params, n_lanes=N_LANES, burst=BURST)
    engine.start()
    trace = tmp_path / "trace.jsonl"
    telemetry.configure(str(trace))
    try:
        server = ServeServer(engine, heartbeat_s=5.0, idle_sleep_s=0.001)
        t, port = _spawn_server(server)
        with ServeClient("127.0.0.1", port, timeout=120) as c:
            r = c.request("episode.run", policy="honest", seed=3)
            assert r["ok"]
            assert c.request("drain")["ok"]
        t.join(60)
        assert not t.is_alive()
    finally:
        telemetry.configure(None)
    events = [json.loads(ln) for ln in
              trace.read_text().splitlines() if ln.strip()]
    reqs = [e for e in events if e.get("kind") == "event"
            and e.get("name") == "request"
            and e.get("op") == "episode.run"]
    roles = {e["role"] for e in reqs}
    assert roles == {"server", "client"}
    by_role = {e["role"]: e for e in reqs}
    assert (by_role["client"]["trace_id"]
            == by_role["server"]["trace_id"] == r["trace_id"])
    assert by_role["client"]["run"] == by_role["server"]["run"]
    assert by_role["server"]["status"] == "ok"
    # the client's total includes the wire, so it bounds the server's
    assert (by_role["client"]["total_s"]
            >= by_role["server"]["total_s"] > 0.0)


# -- admission control (fleet PR) ------------------------------------------


def test_server_admission_control_sheds_in_band(env, params, tmp_path):
    """Tentpole (a): with all lanes held, a tenant over quota, a stale
    backlog, and a full bounded queue each get an in-band shed refusal
    — ok=False / shed=True / reason / retry_after on a live connection
    — with a typed v9 admission event per refusal and the shed
    accounting in stats and the drain report."""
    import socket as socketlib
    import time

    from cpr_tpu import telemetry
    from cpr_tpu.serve.server import ServeServer

    engine = ResidentEngine(env, params, n_lanes=N_LANES, burst=BURST)
    engine.start()
    trace = tmp_path / "trace.jsonl"
    telemetry.configure(str(trace))
    try:
        server = ServeServer(engine, heartbeat_s=5.0, idle_sleep_s=0.001,
                             slo_s=0.3, max_queued=2, tenant_quota=1)
        t, port = _spawn_server(server)
        # one raw socket per parked run: the server answers frames on
        # a connection strictly in order, so a second frame behind a
        # blocked run would never even be read
        raws = [socketlib.create_connection(("127.0.0.1", port),
                                            timeout=60)
                for _ in range(2)]

        def park(sock, seed, want_queued, c):
            sock.sendall(wire.pack_frame(dict(
                op="episode.run", policy="honest", seed=seed)))
            for _ in range(500):
                if c.request("stats")["queued"] >= want_queued:
                    return
                time.sleep(0.01)
            raise AssertionError(f"run seed={seed} never queued")

        def open_lanes(c, first_tenant=None):
            out = []
            for i in range(N_LANES):
                o = c.request("episode.open", seed=100 + i,
                              tenant=first_tenant if i == 0 else None)
                assert o["ok"], o
                out.append(o["session"])
            return out

        def release(c, sessions):
            for sid in sessions:
                assert c.request("episode.close", session=sid)["ok"]
            for _ in range(500):
                st = c.request("stats")
                if st["queued"] == 0 and st["assigned"] == 0:
                    return
                time.sleep(0.01)
            raise AssertionError("backlog never drained")

        try:
            with ServeClient("127.0.0.1", port, timeout=120) as c:
                sessions = open_lanes(c, first_tenant="hog")
                # tenant "hog" already holds a lane: over quota
                r = c.request("episode.run", policy="honest", seed=1,
                              tenant="hog")
                assert not r["ok"] and r["shed"]
                assert r["reason"] == "tenant_quota"
                assert r["error"].startswith("shed")
                assert r["retry_after"] >= 0.1
                # park one run (all lanes held, it waits), let the
                # backlog age past the batch-class SLO budget
                # (slo_s * 0.5): batch traffic sheds, queue not full
                park(raws[0], 2, 1, c)
                time.sleep(2 * 0.3)
                r = c.request("episode.run", policy="honest", seed=3,
                              priority="batch")
                assert not r["ok"] and r["reason"] == "slo_breach"
                # reset the backlog (stale queues shed everything via
                # the SLO check, so queue_full needs a fresh queue),
                # then hold the lanes and fill the bound
                release(c, sessions)
                sessions = open_lanes(c)
                park(raws[0], 4, 1, c)
                park(raws[1], 6, 2, c)
                r = c.request("episode.run", policy="honest", seed=5)
                assert not r["ok"] and r["reason"] == "queue_full"
                stats = c.request("stats")
                assert stats["sheds"] == 3
                assert stats["shed_reasons"] == {"tenant_quota": 1,
                                                 "slo_breach": 1,
                                                 "queue_full": 1}
                # release the lanes; the parked runs complete normally
                release(c, sessions)
                assert c.request("drain")["ok"]
        finally:
            for sock in raws:
                sock.close()
        t.join(60)
        assert not t.is_alive()
    finally:
        telemetry.configure(None)
    events = [json.loads(ln) for ln in trace.read_text().splitlines()
              if ln.strip()]
    adm = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "admission"]
    assert [e["reason"] for e in adm] == ["tenant_quota", "slo_breach",
                                          "queue_full"]
    for e in adm:
        assert e["op"] == "episode.run" and e["retry_after_s"] > 0.0
    assert adm[0]["tenant"] == "hog"
    assert adm[1]["priority"] == "batch"
    # shed refusals are "refused" on the request trail, never "error"
    refused = [e for e in events if e.get("name") == "request"
               and e.get("role") == "server"
               and e.get("status") == "refused"]
    assert len(refused) >= 3
    # the drain report carries the shed accounting + per-class tails
    (report,) = [e for e in events if e.get("name") == "serve"
                 and e.get("action") == "report"]
    d = report["detail"]
    assert d["sheds"] == 3
    assert d["shed_reasons"] == {"tenant_quota": 1, "slo_breach": 1,
                                 "queue_full": 1}
    assert 0.0 < d["shed_rate"] < 1.0
    assert d["class_p99_s"].get("normal", 0) > 0.0


def test_server_rejects_unknown_priority_class(env, params):
    from cpr_tpu.serve.server import _priority_of

    assert _priority_of({"priority": "interactive"}) == (0, "interactive")
    assert _priority_of({"priority": 2}) == (2, "batch")
    assert _priority_of({"priority": 99}) == (2, "batch")  # clamped
    assert _priority_of({}) == (1, "normal")
    with pytest.raises(ValueError, match="unknown priority"):
        _priority_of({"priority": "platinum"})


def test_call_with_retry_honors_shed_and_drain_taxonomy():
    """Satellite 1: a shed refusal is transient — the retry backoff
    stretches to the server's retry_after hint; a drain refusal is
    terminal; exhaustion re-raises the last ShedRefusal."""
    c = ServeClient.__new__(ServeClient)
    c._addr = ("127.0.0.1", 1)
    c._timeout = 1.0
    c._sock = object()  # non-None: attempt() never reconnects
    replies = [dict(ok=False, shed=True, error="shed: queue_full",
                    reason="queue_full", retry_after=0.4),
               dict(ok=True, n=1)]
    calls, sleeps = [], []
    c.request = lambda op, **f: (calls.append(op), replies.pop(0))[1]
    out = c.call_with_retry("episode.run", base_delay_s=0.01,
                            sleep=sleeps.append, seed=7)
    assert out == dict(ok=True, n=1)
    assert calls == ["episode.run", "episode.run"]
    assert sleeps == [0.4]  # the hint stretched the tiny base delay

    c._sock = object()
    c.request = lambda op, **f: dict(ok=False, error="draining",
                                     draining=True)
    with pytest.raises(wire.DrainRefusal):
        c.call_with_retry("episode.run", sleep=lambda s: None)

    c._sock = object()
    c.request = lambda op, **f: dict(ok=False, shed=True,
                                     error="shed: slo_breach",
                                     reason="slo_breach",
                                     retry_after=0.01)
    with pytest.raises(wire.ShedRefusal) as ei:
        c.call_with_retry("episode.run", max_attempts=2,
                          sleep=lambda s: None)
    assert ei.value.retry_after_s == pytest.approx(0.01)


# -- the fleet router (unit surface; fleet-smoke covers end-to-end) --------


def test_router_pick_refuse_and_pinned_bookkeeping():
    """Tentpole (b) unit surface: least-loaded pick with exclusion,
    shed-shaped in-band refusals, rsid translation edge cases, and the
    purge-on-replica-loss path — all without spawning children."""
    from cpr_tpu.serve.router import ServeRouter

    with pytest.raises(ValueError, match="at least one replica"):
        ServeRouter([], 0, workdir="/tmp/unused")
    router = ServeRouter(["--lanes", "2"], 2, workdir="/tmp/unused")
    r0, r1 = router.replicas
    assert router._pick(set()) is None  # nothing up yet
    r0.state = r1.state = "up"
    r0.inflight, r1.inflight = 3, 1
    assert router._pick(set()) is r1  # least loaded
    assert router._pick({1}) is r0  # exclusion
    r1.inflight = 3
    assert router._pick(set()) is r0  # index breaks ties
    # refusals are shed-shaped and counted; a restarting replica
    # stretches the retry_after quote
    resp = router._refuse("replica_lost", "episode.step", replica=0)
    assert not resp["ok"] and resp["shed"]
    assert resp["reason"] == "replica_lost"
    assert resp["retry_after"] == 1.0
    r1.state = "starting"
    assert router._refuse("replica_lost", "x")["retry_after"] == 5.0
    assert router.router_stats()["refused"] == 2

    async def go():
        # unknown rsid: close is idempotent-ok, step is a plain error
        ok = await router._route_pinned(
            dict(op="episode.close", session=99), "episode.close")
        assert ok["ok"]
        resp = await router._route_pinned(
            dict(op="episode.step", session=99, action=0),
            "episode.step")
        assert not resp["ok"] and "session" in resp["error"]
        # a session pinned to a lost replica refuses in-band and the
        # mapping is purged (the client reopens elsewhere)
        router._sessions[5] = (1, 42)
        r1.state = "down"
        resp = await router._route_pinned(
            dict(op="episode.step", session=5, action=0),
            "episode.step")
        assert resp["shed"] and resp["reason"] == "replica_lost"
        assert 5 not in router._sessions

    asyncio.run(go())


def test_router_stamps_seeds_before_first_forward():
    """The deterministic-failover precondition: every episode.run
    reaching a replica carries an explicit seed — router-stamped from
    its own base (1 << 21, above the servers' 1 << 20) when the client
    sent none, passed through untouched otherwise."""
    from cpr_tpu.serve.router import ServeRouter

    router = ServeRouter([], 1, workdir="/tmp/unused")
    seen = []

    async def fake_failover(req, op):
        seen.append(dict(req))
        return dict(ok=True)

    router._route_failover = fake_failover

    async def go():
        await router._route_episode_run(dict(op="episode.run"))
        await router._route_episode_run(dict(op="episode.run"))
        await router._route_episode_run(dict(op="episode.run", seed=7))

    asyncio.run(go())
    assert seen[0]["seed"] == 1 << 21
    assert seen[1]["seed"] == (1 << 21) + 1
    assert seen[2]["seed"] == 7


def test_ledger_lifts_per_class_p99_and_shed_rate(tmp_path):
    """The drain report's class_p99_s map becomes one cfg_class-tagged
    serve_p99_s row per class (distinct fingerprints, so each class
    gates against its own history) and shed_rate a lower-is-better
    serve_shed_rate row."""
    from cpr_tpu.perf.ledger import Ledger

    trace = tmp_path / "t.jsonl"
    events = [
        {"kind": "manifest", "backend": "cpu",
         "config": {"entry": "serve", "n_lanes": 4}},
        {"kind": "event", "name": "serve", "ts": 1.0,
         "action": "report", "session": None,
         "detail": {"steps_per_sec": 500.0,
                    "class_p99_s": {"normal": 0.5, "batch": 0.9},
                    "shed_rate": 0.25}},
    ]
    trace.write_text("".join(json.dumps(e) + "\n" for e in events))
    ledger = Ledger(str(tmp_path / "l.jsonl"))
    assert ledger.ingest_trace(str(trace)) == 4
    recs = ledger.records()
    p99 = [r for r in recs if r["metric"] == "serve_p99_s"]
    by_cls = {r["config"]["cfg_class"]: r for r in p99}
    assert set(by_cls) == {"normal", "batch"}
    assert by_cls["normal"]["value"] == 0.5
    assert by_cls["batch"]["value"] == 0.9
    assert all(r["direction"] == "lower" for r in p99)
    assert (by_cls["normal"]["fingerprint"]
            != by_cls["batch"]["fingerprint"])
    (shed,) = [r for r in recs if r["metric"] == "serve_shed_rate"]
    assert shed["value"] == 0.25 and shed["unit"] == "fraction"
    assert shed["direction"] == "lower"  # no _s suffix: explicit


# -- perf ledger ingestion + gate (satellite f) ----------------------------


def test_ledger_ingests_and_gates_serve_rows(tmp_path):
    from cpr_tpu.perf.gate import gate_row
    from cpr_tpu.perf.ledger import Ledger

    trace = tmp_path / "serve_trace.jsonl"
    events = [{"kind": "manifest", "backend": "cpu",
               "config": {"entry": "serve", "n_lanes": 4, "burst": 8}}]
    for i, (sps, occ) in enumerate([(1000.0, 0.9), (1010.0, 0.95),
                                    (1020.0, 1.0)]):
        events.append({"kind": "event", "name": "serve", "ts": float(i),
                       "action": "report", "session": None,
                       "detail": {"steps_per_sec": sps, "occupancy": occ,
                                  "steps": 4096, "episodes": 64}})
    trace.write_text("".join(json.dumps(e) + "\n" for e in events))

    ledger = Ledger(str(tmp_path / "ledger.jsonl"))
    assert ledger.ingest_trace(str(trace)) == 6
    assert ledger.ingest_trace(str(trace)) == 0  # idempotent
    recs = ledger.records()
    sps_rows = [r for r in recs if r["metric"] == "serve_steps_per_sec"]
    occ_rows = [r for r in recs if r["metric"] == "serve_occupancy"]
    assert len(sps_rows) == 3 and len(occ_rows) == 3
    assert all(r["backend"] == "cpu" for r in sps_rows)
    assert all(r["unit"] == "steps/sec" for r in sps_rows)
    assert all(r["config"].get("cfg_n_lanes") == 4 for r in sps_rows)
    assert len({r["fingerprint"] for r in sps_rows}) == 1

    # history: 1000/1010/1020 -> median 1010, tight MAD; a matching
    # candidate passes, a sagging one warns, a collapsed one fails
    def candidate(value):
        c = dict(sps_rows[-1], value=value)
        c["row_id"] = f"cand-{value}"
        return c

    assert gate_row(candidate(1015.0), recs)["verdict"] == "pass"
    assert gate_row(candidate(850.0), recs)["verdict"] == "warn"
    assert gate_row(candidate(500.0), recs)["verdict"] == "fail"
    # occupancy rows are baseline-eligible the same way
    assert gate_row(dict(occ_rows[-1], row_id="c2"),
                    recs)["verdict"] == "pass"


def test_serve_event_schema_declared():
    from cpr_tpu.telemetry import EVENT_FIELDS, SCHEMA_VERSION

    assert SCHEMA_VERSION >= 8
    assert EVENT_FIELDS["serve"] == ("action", "session", "detail")
    assert EVENT_FIELDS["request"] == (
        "trace_id", "op", "status", "queue_wait_s", "service_s",
        "total_s")
