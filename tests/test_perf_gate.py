"""Perf ledger + runtime regression gate (cpr_tpu/perf, PR 7).

Pure-JSON tests: synthetic ledgers with seeded regressions, drifted
configs, and outage-poisoned histories, plus the acceptance contract
over the REAL tracked banks — `perf_report --gate` must exit zero on
the current trail, and a CPU-fallback row must never be judged against
a TPU baseline.
"""

import importlib.util
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cpr_tpu import perf, telemetry  # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _row(metric="m_env_steps_per_sec_per_chip", backend="tpu",
         value=100.0, rnd=None, source="synthetic", **extra):
    """One synthetic ledger record (distinct rounds -> distinct
    row_ids even at equal values)."""
    return perf.normalize_row(
        {"metric": metric, "backend": backend, "value": value, **extra},
        source=source, rnd=rnd)


# -- normalization over the real tracked banks --------------------------------


def test_tracked_banks_normalize_with_outage_backfill():
    """Every BENCH*.json row normalizes; the pre-tagging driver-round
    CPU fallbacks (r02/r05 — their stderr tails record the backend
    switch) come out outage-tagged, so they can never be baselines."""
    rows = list(perf.iter_bank_rows(REPO))
    assert rows, "tracked BENCH*.json banks missing"
    recs = [perf.normalize_row(r, source=s, rnd=n, tail_hint=h)
            for r, s, n, h in rows]
    assert all(r["row_id"] and r["fingerprint"] for r in recs)
    driver_cpu = [r for r in recs if r["backend"] == "cpu"
                  and r["source"].startswith("BENCH_r")]
    assert driver_cpu, "expected the banked CPU-fallback rounds"
    assert all(r["outage"] for r in driver_cpu)
    assert all(r["fallback_reason"] for r in driver_cpu)
    tpu = [r for r in recs if r["backend"] == "tpu"]
    assert tpu and not any(r["outage"] for r in tpu)


def test_ledger_append_only_and_idempotent(tmp_path):
    led = perf.Ledger(str(tmp_path / "ledger.jsonl"))
    recs = [_row(value=v, rnd=i) for i, v in enumerate([100.0, 101.0,
                                                        102.0])]
    assert led.append(recs) == 3
    assert led.append(recs) == 0  # content-addressed dedup
    with open(led.path) as f:
        before = f.read()
    # a foreign line (hand-edit, older writer) survives appends verbatim
    # in the FILE — but its row_id does not match its content, so
    # verify-on-read (v16) skips it with a typed `integrity` event and
    # it can never become a gate baseline
    alien = json.dumps({"ledger": 1, "row_id": "feedc0ffee00",
                        "metric": "hand_added"})
    with open(led.path, "a") as f:
        f.write(alien + "\n")
    assert led.append([_row(value=103.0, rnd=9)]) == 1
    with open(led.path) as f:
        after = f.read()
    assert after.startswith(before.rstrip("\n") + "\n")
    assert alien in after
    recs = led.records()
    assert len(recs) == 4
    assert all(r["metric"] != "hand_added" for r in recs)


def test_ingest_banks_idempotent(tmp_path):
    led = perf.Ledger(str(tmp_path / "l.jsonl"))
    assert led.ingest_banks(REPO) > 0
    assert led.ingest_banks(REPO) == 0


# -- gate verdicts on synthetic histories -------------------------------------


def test_gate_bands_on_quiet_history():
    """MAD=0 history: the fractional floors are the band — warn below
    -10%, fail below -25%, improvements always pass."""
    hist = [_row(value=100.0, rnd=i) for i in range(5)]
    for value, verdict in [(98.0, "pass"), (89.0, "warn"),
                           (74.0, "fail"), (150.0, "pass")]:
        res = perf.gate_row(_row(value=value), hist)
        assert res["verdict"] == verdict, (value, res)
    res = perf.gate_row(_row(value=74.0), hist)
    assert res["baseline"]["median"] == 100.0
    assert res["baseline"]["n"] == 5
    assert not res["config_drift"]


def test_noisy_history_widens_band():
    """A trail that honestly fluctuates (the bk 15x improvement arc)
    must not flag every fluctuation: the MAD term widens the band past
    the fractional floor."""
    hist = [_row(value=v, rnd=i)
            for i, v in enumerate([100.0, 60.0, 140.0, 80.0, 120.0])]
    assert perf.gate_row(_row(value=40.0), hist)["verdict"] == "pass"


def test_outage_and_error_rows_never_baselines():
    """Outage-poisoned history: fallback/error rows are excluded even
    when their values would dominate the top-k pool."""
    healthy = [_row(value=100.0, rnd=i) for i in range(3)]
    poison = [_row(value=1000.0, rnd=10 + i, outage=True,
                   fallback_reason="wedged backend") for i in range(2)]
    poison.append(_row(value=2000.0, rnd=20, error="guard failed"))
    res = perf.gate_row(_row(value=95.0), healthy + poison)
    assert res["verdict"] == "pass"
    assert res["baseline"]["median"] == 100.0
    assert res["baseline"]["n"] == 3


def test_probe_rows_never_baselines_and_never_gated():
    """Supervisor provenance (ledger v2): a probe row is a device
    health check, not a measurement — it must neither enter a baseline
    pool (even with a dominating value) nor be judged itself."""
    healthy = [_row(value=100.0, rnd=i) for i in range(3)]
    probes = [_row(value=9000.0, rnd=10 + i, probe=True)
              for i in range(2)]
    res = perf.gate_row(_row(value=95.0), healthy + probes)
    assert res["verdict"] == "pass"
    assert res["baseline"]["median"] == 100.0
    assert res["baseline"]["n"] == 3
    res = perf.gate_row(_row(value=1.0, probe=True), healthy)
    assert res["verdict"] == "skip" and "probe" in res["reason"]
    assert res["baseline"] is None


def test_restart_count_tagged_but_rows_stay_baseline_eligible():
    """A row measured after a warm restart is a REAL measurement — it
    carries `restart_count` for provenance (recovery-window numbers
    read 2-5x slow) but stays in the baseline pool; junk counts
    normalize to 0 instead of wedging ingestion."""
    rec = _row(value=90.0, restart_count=1)
    assert rec["ledger"] == perf.LEDGER_VERSION
    assert rec["restart_count"] == 1 and rec["probe"] is False
    assert _row(value=1.0, restart_count="two")["restart_count"] == 0
    hist = ([_row(value=100.0, rnd=i) for i in range(2)]
            + [_row(value=100.0, rnd=5, restart_count=1)])
    res = perf.gate_row(_row(value=95.0), hist)
    assert res["verdict"] == "pass"
    assert res["baseline"]["n"] == 3  # post-restart row counted


def test_synthetic_supervised_trail_gates_clean(tmp_path, monkeypatch):
    """A trail shaped like one supervised bench round — healthy
    history, then a probe row and a post-warm-restart measurement —
    banks and gates without the probe poisoning anything."""
    led = perf.Ledger(str(tmp_path / "l.jsonl"))
    led.append([_row(value=100.0 + i, rnd=i + 1) for i in range(3)])
    led.append([_row(value=1.0, rnd=4, probe=True),
                _row(value=98.0, rnd=4, restart_count=1)])
    results = [perf.gate_row(r, led.records())
               for r in led.records() if r["round"] == 4]
    verdicts = sorted(r["verdict"] for r in results)
    assert verdicts == ["pass", "skip"]
    s = perf.gate_summary(results)
    assert s["ok"] and s["skip"] == 1


def test_cpu_fallback_never_judged_against_tpu_baseline():
    """The acceptance contract: backends never mix.  An untagged CPU
    row sees no baseline in an all-TPU history (first measurement); a
    tagged fallback row is skipped outright."""
    tpu_hist = [_row(value=3e8, rnd=i) for i in range(5)]
    res = perf.gate_row(_row(backend="cpu", value=1e6), tpu_hist)
    assert res["verdict"] == "pass"
    assert res["baseline"] is None
    assert "first measurement" in res["reason"]
    res = perf.gate_row(
        _row(backend="cpu", value=1e6, outage=True,
             fallback_reason="tpu attempts unsuccessful"), tpu_hist)
    assert res["verdict"] == "skip"
    assert res["baseline"] is None


def test_error_candidate_and_missing_value_skip():
    hist = [_row(value=100.0, rnd=i) for i in range(3)]
    assert perf.gate_row(_row(value=1.0, error="boom"),
                         hist)["verdict"] == "skip"
    res = perf.gate_row(
        perf.normalize_row({"metric": "m_env_steps_per_sec_per_chip",
                            "backend": "tpu"}), hist)
    assert res["verdict"] == "skip"


def test_config_drift_flagged_and_same_fingerprint_preferred():
    hist = [_row(value=100.0, rnd=i, cfg_n_envs=8192) for i in range(3)]
    res = perf.gate_row(_row(value=95.0, cfg_n_envs=4096), hist)
    assert res["config_drift"] and res["verdict"] == "pass"
    # once same-fingerprint history exists it wins over the drifted pool
    mixed = hist + [_row(value=50.0, rnd=9, cfg_n_envs=4096)]
    res = perf.gate_row(_row(value=48.0, cfg_n_envs=4096), mixed)
    assert not res["config_drift"]
    assert res["baseline"]["median"] == 50.0


def test_ledger_v3_direction_field_and_inference():
    """Ledger v3: every record carries a gate direction — `*_s`
    metrics (latencies) are lower-is-better, everything else higher;
    an explicit row key overrides the name inference, junk falls back
    to it."""
    assert perf.metric_direction("serve_p99_s") == "lower"
    assert perf.metric_direction("compile_s") == "lower"
    assert perf.metric_direction("serve_steps_per_sec") == "higher"
    assert perf.metric_direction("serve_occupancy") == "higher"
    rec = _row(metric="serve_p99_s", value=0.5)
    assert rec["ledger"] >= 3 and rec["direction"] == "lower"
    assert _row(value=100.0)["direction"] == "higher"
    rec = perf.normalize_row({"metric": "weird_metric", "backend": "tpu",
                              "value": 1.0, "direction": "lower"})
    assert rec["direction"] == "lower"
    rec = perf.normalize_row({"metric": "x_per_sec", "backend": "tpu",
                              "value": 1.0, "direction": "sideways"})
    assert rec["direction"] == "higher"


def test_ledger_v4_cfg_devices_backfills_and_fingerprints():
    """Ledger v4: every config fingerprint carries the device span.
    Rows with no `n_devices` key measured one device (backfill-exact,
    not a guess), an explicit span lands verbatim, junk normalizes to
    1 — and a 4-chip row fingerprints as a DIFFERENT measurement from
    the otherwise-identical 1-chip row."""
    one = _row(value=100.0)
    four = _row(value=100.0, n_devices=4)
    assert one["ledger"] == perf.LEDGER_VERSION == 5
    assert one["config"]["cfg_devices"] == 1
    assert four["config"]["cfg_devices"] == 4
    assert one["fingerprint"] != four["fingerprint"]
    assert _row(value=1.0, n_devices="many")["config"]["cfg_devices"] == 1
    # an explicit cfg_devices config key wins over the n_devices spell
    rec = _row(value=1.0, cfg_devices=2)
    assert rec["config"]["cfg_devices"] == 2


def test_serve_report_n_devices_lifts_into_cfg_devices(tmp_path):
    """iter_trace_rows: the drain report's own device span is
    authoritative for the lifted rows' cfg_devices — it lands even
    when the manifest config says nothing, and it overrides a stale
    manifest `devices` key."""
    trace = tmp_path / "t.jsonl"
    events = [{"kind": "manifest", "backend": "cpu",
               "config": {"entry": "serve", "devices": 1}},
              {"kind": "event", "name": "serve", "action": "report",
               "session": None,
               "detail": {"steps_per_sec": 1000.0, "occupancy": 0.9,
                          "p50_s": 0.02, "p99_s": 0.2, "n_devices": 4}}]
    trace.write_text("".join(json.dumps(e) + "\n" for e in events))
    rows = [perf.normalize_row(row, source=src)
            for row, src in perf.iter_trace_rows(str(trace))]
    assert rows
    assert all(r["config"]["cfg_devices"] == 4 for r in rows)
    # no n_devices in the report (pre-v4 serve trace): backfill to 1
    events[1]["detail"].pop("n_devices")
    events[0]["config"].pop("devices")
    trace.write_text("".join(json.dumps(e) + "\n" for e in events))
    rows = [perf.normalize_row(row, source=src)
            for row, src in perf.iter_trace_rows(str(trace))]
    assert all(r["config"]["cfg_devices"] == 1 for r in rows)


def test_gate_drift_fallback_never_crosses_device_counts():
    """Ledger v4 gate semantics: config drift still gates within a
    device count, but a 4-chip candidate with only 1-chip history is a
    FIRST measurement — on a 1-core CI host the 4-virtual-device rate
    is honestly slower, and failing it against 1-chip baselines would
    re-create exactly the drift cfg_devices exists to prevent."""
    one_chip = [_row(value=100.0, rnd=i, cfg_n_envs=8192)
                for i in range(3)]
    # 60% below the 1-chip trail, but at a different device count:
    # pass, with the first-measurement reason naming the count
    res = perf.gate_row(_row(value=40.0, n_devices=4,
                             cfg_n_envs=8192), one_chip)
    assert res["verdict"] == "pass"
    assert res["baseline"] is None and not res["config_drift"]
    assert "cfg_devices=4" in res["reason"]
    # once 4-chip history exists, an off-fingerprint 4-chip candidate
    # drifts against THAT pool, never the 1-chip rows
    mixed = one_chip + [_row(value=40.0, rnd=9, n_devices=4,
                             cfg_n_envs=8192)]
    res = perf.gate_row(_row(value=38.0, n_devices=4,
                             cfg_n_envs=4096), mixed)
    assert res["verdict"] == "pass" and res["config_drift"]
    assert res["baseline"]["median"] == 40.0
    # and a genuine same-count regression still fails
    res = perf.gate_row(_row(value=10.0, n_devices=4,
                             cfg_n_envs=4096), mixed)
    assert res["verdict"] == "fail"


def test_gate_drift_fallback_never_crosses_state_shard_counts():
    """v13 twin of the device-count rule: a state-sharded VI rate
    (cfg_state_shards, state_shard.py) pays per-sweep halo traffic a
    1-shard solve does not, so the drift fallback must never judge a
    4-shard candidate against 1-shard history (or vice versa)."""
    one_shard = [_row(metric="mdp_states_per_sec", value=100.0, rnd=i,
                      cfg_protocol="fc16") for i in range(3)]
    # 60% below the 1-shard trail but at 4 state shards: first
    # measurement, with the shard count named in the reason
    res = perf.gate_row(_row(metric="mdp_states_per_sec", value=40.0,
                             cfg_state_shards=4, cfg_protocol="fc16"),
                        one_shard)
    assert res["verdict"] == "pass"
    assert res["baseline"] is None and not res["config_drift"]
    assert "cfg_state_shards=4" in res["reason"]
    # once 4-shard history exists, an off-fingerprint 4-shard
    # candidate drifts against THAT pool, never the 1-shard rows
    mixed = one_shard + [_row(metric="mdp_states_per_sec", value=40.0,
                              rnd=9, cfg_state_shards=4,
                              cfg_protocol="fc16")]
    res = perf.gate_row(_row(metric="mdp_states_per_sec", value=38.0,
                             cfg_state_shards=4, cfg_protocol="aft20"),
                        mixed)
    assert res["verdict"] == "pass" and res["config_drift"]
    assert res["baseline"]["median"] == 40.0
    # and a genuine same-shard-count regression still fails
    res = perf.gate_row(_row(metric="mdp_states_per_sec", value=10.0,
                             cfg_state_shards=4, cfg_protocol="aft20"),
                        mixed)
    assert res["verdict"] == "fail"


def test_mdp_solve_state_shards_lift_into_ledger(tmp_path):
    """iter_trace_rows, v13: an mdp_solve event carrying state_shards
    + states_per_sec banks an mdp_states_per_sec row fingerprinted by
    cfg_state_shards; an unsharded event (state_shards 1 or absent)
    yields rows WITHOUT the key, so pre-v13 row ids are unchanged."""
    trace = tmp_path / "t.jsonl"
    base = {"kind": "event", "name": "mdp_solve", "protocol": "fc16",
            "cutoff": 6, "grid": [1, 1], "sweeps": 640, "converged": 1,
            "points": 1, "solve_s": 2.0, "points_per_sec": 0.5}
    events = [
        {"kind": "manifest", "backend": "cpu", "config": {}},
        {**base, "n_devices": 4, "state_shards": 4,
         "halo_bytes": 1024, "states_per_sec": 5000.0},
        {**base, "state_shards": 1, "states_per_sec": 9000.0},
    ]
    trace.write_text("".join(json.dumps(e) + "\n" for e in events))
    rows = [perf.normalize_row(row, source=src, rnd=i)
            for i, (row, src) in
            enumerate(perf.iter_trace_rows(str(trace)))]
    sps = [r for r in rows if r["metric"] == "mdp_states_per_sec"]
    assert len(sps) == 2
    sharded = [r for r in sps if r["value"] == 5000.0][0]
    solo = [r for r in sps if r["value"] == 9000.0][0]
    assert sharded["config"]["cfg_state_shards"] == 4
    assert sharded["config"]["cfg_devices"] == 4
    assert sharded["unit"] == "states/sec"
    assert "cfg_state_shards" not in solo["config"]
    assert sharded["fingerprint"] != solo["fingerprint"]


def test_perf_report_scaling_table(tmp_path, capsys):
    """scaling_groups: rows split only by cfg_devices group into one
    scaling view with direction-aware best, speedup vs the smallest
    count, and efficiency = speedup / device ratio; the markdown
    report grows a Device scaling section."""
    pr = _load_tool("perf_report")
    recs = [
        _row(metric="serve_steps_per_sec", backend="cpu", value=100.0,
             rnd=1, cfg_lanes=8),
        _row(metric="serve_steps_per_sec", backend="cpu", value=95.0,
             rnd=2, cfg_lanes=8),  # best-per-count keeps the 100
        _row(metric="serve_steps_per_sec", backend="cpu", value=300.0,
             rnd=3, cfg_lanes=8, n_devices=4),
        # lower-is-better: best per count is the SMALLEST latency
        _row(metric="serve_p99_s", backend="cpu", value=0.4, rnd=1),
        _row(metric="serve_p99_s", backend="cpu", value=0.2, rnd=2,
             n_devices=4),
        _row(metric="serve_p99_s", backend="cpu", value=0.3, rnd=3,
             n_devices=4),
        # single device count only: never a scaling group
        _row(metric="lonely_per_sec", backend="cpu", value=5.0, rnd=1),
        # a differing non-device config key splits the group
        _row(metric="serve_steps_per_sec", backend="cpu", value=9.0,
             rnd=4, cfg_lanes=16),
    ]
    scaling = pr.scaling_groups(recs)
    by_metric = {g["metric"]: g for g in scaling}
    assert set(by_metric) == {"serve_steps_per_sec", "serve_p99_s"}
    sps = {r["devices"]: r for r in
           by_metric["serve_steps_per_sec"]["rows"]}
    assert sps[1]["value"] == 100.0 and sps[4]["value"] == 300.0
    assert sps[4]["speedup"] == pytest.approx(3.0)
    assert sps[4]["efficiency"] == pytest.approx(0.75)
    p99 = {r["devices"]: r for r in by_metric["serve_p99_s"]["rows"]}
    assert p99[4]["value"] == 0.2  # best = lowest latency
    assert p99[4]["speedup"] == pytest.approx(2.0)
    lines = list(pr.scaling_lines(scaling))
    assert any("serve_steps_per_sec" in ln and "3.00x" in ln
               for ln in lines)

    led = perf.Ledger(str(tmp_path / "l.jsonl"))
    led.append(recs)
    md = tmp_path / "report.md"
    assert pr.main([led.path, "--markdown", str(md)]) == 0
    out = capsys.readouterr().out
    assert "speedup" in out and "3.00x" in out
    text = md.read_text()
    assert "## Device scaling" in text and "0.75" not in text  # table is %
    assert "| serve_steps_per_sec | cpu | 4 | 300 | 3.00x | 75% |" in text


def test_gate_band_flips_for_lower_is_better_metrics():
    """Satellite a: a serve_p99_s history at a quiet 0.5s — a matching
    candidate passes, +10%+ warns, +25%+ fails, and an improvement
    (smaller latency) always passes; the higher-is-better banding of
    the surrounding tests is untouched."""
    hist = [_row(metric="serve_p99_s", backend="cpu", value=0.5, rnd=i)
            for i in range(5)]
    for value, verdict in [(0.51, "pass"), (0.58, "warn"),
                           (0.90, "fail"), (0.10, "pass")]:
        res = perf.gate_row(_row(metric="serve_p99_s", backend="cpu",
                                 value=value), hist)
        assert res["verdict"] == verdict, (value, res)
        assert res["direction"] == "lower"
    res = perf.gate_row(_row(metric="serve_p99_s", backend="cpu",
                             value=0.90), hist)
    assert res["baseline"]["median"] == 0.5
    assert res["baseline"]["best"] == 0.5
    assert "fail_above" in res["baseline"]
    assert "lower is better" in res["reason"]


def test_serve_report_latency_rows_ingest_with_direction(tmp_path):
    """iter_trace_rows lifts the drain report's p50_s/p99_s alongside
    the throughput rows, direction-stamped for the flipped band."""
    trace = tmp_path / "t.jsonl"
    events = [{"kind": "manifest", "backend": "cpu",
               "config": {"entry": "serve", "n_lanes": 4}},
              {"kind": "event", "name": "serve", "action": "report",
               "session": None,
               "detail": {"steps_per_sec": 1000.0, "occupancy": 0.9,
                          "p50_s": 0.02, "p99_s": 0.2}}]
    trace.write_text("".join(json.dumps(e) + "\n" for e in events))
    rows = {r["metric"]: r for r in
            (perf.normalize_row(row, source=src)
             for row, src in perf.iter_trace_rows(str(trace)))}
    assert set(rows) == {"serve_steps_per_sec", "serve_occupancy",
                         "serve_p50_s", "serve_p99_s"}
    assert rows["serve_p50_s"]["value"] == 0.02
    assert rows["serve_p99_s"]["direction"] == "lower"
    assert rows["serve_p99_s"]["unit"] == "seconds"
    assert rows["serve_steps_per_sec"]["direction"] == "higher"
    assert rows["serve_p99_s"]["config"].get("cfg_n_lanes") == 4


def test_gate_summary_counts():
    hist = [_row(value=100.0, rnd=i) for i in range(5)]
    results = [perf.gate_row(_row(value=v), hist)
               for v in (98.0, 89.0, 74.0)]
    results.append(perf.gate_row(
        _row(backend="cpu", value=1.0, outage=True,
             fallback_reason="x"), hist))
    s = perf.gate_summary(results)
    assert (s["pass"], s["warn"], s["fail"], s["skip"]) == (1, 1, 1, 1)
    assert not s["ok"]


# -- the typed perf_gate event (schema v5) ------------------------------------


def test_gate_event_validates_and_renders(tmp_path, capsys):
    """emit_gate_event round-trips trace_summary --validate --expect
    perf_gate; dropping a declared v5 field is caught."""
    ts = _load_tool("trace_summary")
    trace = tmp_path / "t.jsonl"
    try:
        telemetry.configure(str(trace))
        hist = [_row(value=100.0, rnd=i) for i in range(3)]
        perf.emit_gate_event(perf.gate_row(_row(value=70.0), hist))
    finally:
        telemetry.configure(None)
    with open(trace, "a") as f:
        f.write(json.dumps({"kind": "manifest", "backend": "cpu",
                            "schema": telemetry.SCHEMA_VERSION}) + "\n")
    events, bad = ts.read_events(str(trace))
    assert ts.validate(events, bad, expect=("perf_gate",)) == []
    (ev,) = [e for e in events if e.get("name") == "perf_gate"]
    assert ev["verdict"] == "fail"
    assert all(k in ev for k in telemetry.EVENT_FIELDS["perf_gate"])
    ts.main(["trace_summary", str(trace)])
    out = capsys.readouterr().out
    assert "perf gate" in out and "fail" in out

    lame = tmp_path / "lame.jsonl"
    lines = []
    for line in trace.read_text().splitlines():
        e = json.loads(line)
        if e.get("name") == "perf_gate":
            del e["verdict"]
        lines.append(json.dumps(e))
    lame.write_text("\n".join(lines) + "\n")
    events, bad = ts.read_events(str(lame))
    errors = ts.validate(events, bad)
    assert any("perf_gate" in err and "verdict" in err for err in errors)
    with pytest.raises(SystemExit) as exc:
        ts.main(["trace_summary", str(lame), "--validate"])
    assert exc.value.code == 1
    capsys.readouterr()


# -- perf_report: the CLI gate ------------------------------------------------


def test_perf_report_gate_exits_zero_on_tracked_banks(capsys):
    """Acceptance criterion: the gate passes the CURRENT banked trail —
    the r02/r05 CPU-fallback rows surface as SKIP, never FAIL."""
    pr = _load_tool("perf_report")
    assert pr.main(["--root", REPO, "--gate"]) == 0
    out = capsys.readouterr().out
    assert "perf-gate: PASS" in out
    assert "0 fail" in out
    assert "SKIP" in out  # the banked fallback rows are visible, not gated


def test_perf_report_seeded_regression_exits_nonzero(tmp_path, capsys):
    led = perf.Ledger(str(tmp_path / "l.jsonl"))
    hist = [_row(value=100.0 + i, rnd=i + 1) for i in range(5)]
    # newest row (unknown round = live) seeded 60% below the trail
    led.append(hist + [_row(value=40.0, source="zz_live")])
    pr = _load_tool("perf_report")
    assert pr.main([led.path, "--gate"]) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out
    # report-only mode surfaces the same verdict but exits zero
    assert pr.main([led.path]) == 0
    capsys.readouterr()


def test_perf_report_gate_fails_on_p99_regression(tmp_path, capsys):
    """The ISSUE-10 acceptance: a fresh serve_p99_s row regressing
    past the banked band FAILs `perf_report --gate` exactly like a
    steps/sec drop would."""
    led = perf.Ledger(str(tmp_path / "l.jsonl"))
    hist = [_row(metric="serve_p99_s", backend="cpu",
                 value=0.200 + 0.001 * i, rnd=i + 1) for i in range(5)]
    led.append(hist + [_row(metric="serve_p99_s", backend="cpu",
                            value=0.400, source="zz_live")])
    pr = _load_tool("perf_report")
    assert pr.main([led.path, "--gate"]) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out and "lower-is-better" in out
    # an *improved* (lower) fresh row gates clean
    led2 = perf.Ledger(str(tmp_path / "l2.jsonl"))
    led2.append(hist + [_row(metric="serve_p99_s", backend="cpu",
                             value=0.150, source="zz_live")])
    assert pr.main([led2.path, "--gate"]) == 0
    capsys.readouterr()


def test_perf_report_since_metric_filters_and_markdown(tmp_path, capsys):
    import argparse

    led = perf.Ledger(str(tmp_path / "l.jsonl"))
    led.append([
        _row(metric="aaa_env_steps_per_sec_per_chip", value=100.0, rnd=1),
        _row(metric="aaa_env_steps_per_sec_per_chip", value=101.0, rnd=4),
        _row(metric="bbb_env_steps_per_sec_per_chip", value=5.0, rnd=4),
    ])
    pr = _load_tool("perf_report")
    ns = argparse.Namespace(ledger=led.path, root=REPO, trace=None,
                            since=3, metric="aaa")
    recs = pr.load_records(ns)
    assert {r["round"] for r in recs} == {4}
    assert {r["metric"] for r in recs} == {
        "aaa_env_steps_per_sec_per_chip"}
    md = tmp_path / "report.md"
    assert pr.main([led.path, "--metric", "aaa",
                    "--markdown", str(md)]) == 0
    out = capsys.readouterr().out
    assert "aaa_env" in out and "bbb_env" not in out
    text = md.read_text()
    assert "Perf ledger report" in text and "aaa_env" in text
    # no rows at all: usage-style exit, not a silent pass
    assert pr.main([str(tmp_path / "nope.jsonl")]) == 2
    capsys.readouterr()


def test_perf_report_reads_trace_rates(tmp_path, capsys):
    """--trace lifts span per_sec counters into the same trend surface
    (backend/config from the preceding manifest)."""
    trace = tmp_path / "run.jsonl"
    trace.write_text(
        json.dumps({"kind": "manifest", "backend": "cpu",
                    "config": {"n_envs": 64}}) + "\n"
        + json.dumps({"kind": "span", "path": "bench:nakamoto_sm1",
                      "per_sec": {"env_steps": 123456.0}}) + "\n")
    rows = list(perf.iter_trace_rows(str(trace)))
    assert rows
    rec = perf.normalize_row(rows[0][0], source=rows[0][1])
    assert rec["metric"] == "bench:nakamoto_sm1:env_steps_per_sec"
    assert rec["backend"] == "cpu"
    assert rec["config"].get("cfg_n_envs") == 64
    pr = _load_tool("perf_report")
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert pr.main([str(empty), "--trace", str(trace)]) == 0
    out = capsys.readouterr().out
    assert "env_steps_per_sec" in out


# -- bank_and_gate: the bench's self-gate entry point -------------------------


def test_bank_and_gate_roundtrip(tmp_path, monkeypatch):
    """One call banks the row (idempotently) and returns the verdict
    against the banked history under `root`."""
    root = tmp_path
    bank = [{"metric": "bk8_withholding_env_steps_per_sec_per_chip",
             "backend": "tpu", "value": 500000 + 1000 * i,
             "unit": "env-steps/sec/chip", "cfg_n_envs": 8192}
            for i in range(3)]
    (root / "BENCH_CONFIGS_tpu_r03.json").write_text(json.dumps(bank))
    monkeypatch.delenv(perf.LEDGER_ENV_VAR, raising=False)
    row = dict(bank[0], value=498000)
    res = perf.bank_and_gate(row, root=str(root))
    assert res["verdict"] == "pass"
    assert res["baseline"]["n"] == 3
    led = perf.Ledger(perf.default_ledger_path(str(root)))
    assert led.path.startswith(str(root))
    n_after_first = len(led.records())
    assert n_after_first == 4  # 3 banked + the live row
    # same row again: ledger unchanged (dedup), verdict stable
    res2 = perf.bank_and_gate(row, root=str(root))
    assert res2["verdict"] == "pass"
    assert len(led.records()) == n_after_first
    # a seeded regression against the same bank fails
    res3 = perf.bank_and_gate(dict(row, value=100000), root=str(root))
    assert res3["verdict"] == "fail"
    # an outage fallback row banks but is never judged
    res4 = perf.bank_and_gate(
        dict(row, backend="cpu", value=900, outage=True,
             fallback_reason="tpu attempts unsuccessful"),
        root=str(root))
    assert res4["verdict"] == "skip"
