"""Sdag env tests (sdag.ml validity + stochastic batteries)."""

import jax
import numpy as np
import pytest

from cpr_tpu.envs.sdag import BLOCK, VOTE, SdagSSZ
from cpr_tpu.params import make_params

# deep stochastic battery: opt-in (fast coverage lives in
# test_protocol_smoke.py)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def env():
    return SdagSSZ(k=4, incentive_scheme="constant", max_steps_hint=192)


def run_policy(env, name, alpha, n_envs=96, episode_steps=128, seed=0):
    params = make_params(alpha=alpha, gamma=0.5, max_steps=episode_steps)
    policy = env.policies[name]
    keys = jax.random.split(jax.random.PRNGKey(seed), n_envs)
    stats = jax.vmap(
        lambda k: env.episode_stats(k, params, policy, episode_steps + 32)
    )(keys)
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    return atk / (atk + dfn)


def test_honest_policy_yields_alpha(env):
    for alpha in [0.25, 0.4]:
        rel = run_policy(env, "honest", alpha)
        assert abs(rel - alpha) < 0.05, (alpha, rel)


def test_dag_structure_invariants(env):
    """sdag.ml:139-172: a vote's number equals its closure cardinality and
    all parents share its block; a block's confirmed closure has exactly
    k-1 votes confirming the previous block."""
    params = make_params(alpha=0.35, gamma=0.5, max_steps=160)
    state, obs = env.reset(jax.random.PRNGKey(3), params)
    step = jax.jit(env.step)
    policy = env.policies["release-block"]
    for _ in range(160):
        state, obs, r, done, info = step(state, policy(obs), params)
    dag = state.dag
    n = int(dag.n)
    assert not bool(dag.overflow)
    parents = np.stack([np.asarray(q) for q in dag.parents], axis=1)[:n]
    kind = np.asarray(dag.kind)[:n]
    height = np.asarray(dag.height)[:n]
    vote_no = np.asarray(dag.aux)[:n]
    signer = np.asarray(dag.signer)[:n]
    powh = np.asarray(dag.pow_hash)[:n]

    def closure(starts):
        seen = set()
        stack = [s for s in starts if s >= 0 and kind[s] == VOTE]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            for p in parents[cur]:
                if p >= 0 and kind[p] == VOTE:
                    stack.append(p)
        return seen

    saw_block = False
    for i in range(1, n):
        ps = parents[i][parents[i] >= 0]
        assert np.isfinite(powh[i])
        if kind[i] == VOTE:
            assert len(ps) >= 1
            cl = closure(list(ps))
            assert vote_no[i] == len(cl) + 1, (i, vote_no[i], cl)
            blocks = {p if kind[p] == BLOCK else signer[p] for p in ps}
            assert blocks == {signer[i]}
            assert height[i] == height[signer[i]]
        else:
            saw_block = True
            cl = closure(list(ps))
            assert len(cl) == env.k - 1, (i, cl)
            prevs = {signer[v] for v in cl}
            assert len(prevs) == 1
            assert height[i] == height[prevs.pop()] + 1
    assert saw_block


def test_progress_tracks_activations(env):
    params = make_params(alpha=0.3, gamma=0.5, max_steps=160)
    stats = env.episode_stats(
        jax.random.PRNGKey(7), params, env.policies["honest"], 192)
    prog = float(stats["episode_progress"])
    acts = float(stats["episode_n_activations"])
    assert prog > 0 and prog / acts > 0.6, (prog, acts)


def test_policies_run_and_terminate(env):
    params = make_params(alpha=0.4, gamma=0.5, max_steps=96)
    for name, policy in env.policies.items():
        traj = env.rollout(jax.random.PRNGKey(5), params, policy, 160)
        done = np.asarray(traj[3])
        assert done.sum() >= 1, name


def test_discount_scheme_bounds_rewards():
    env = SdagSSZ(k=4, incentive_scheme="discount", max_steps_hint=96)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=64)
    stats = env.episode_stats(
        jax.random.PRNGKey(11), params, env.policies["honest"], 96)
    total = float(stats["episode_reward_attacker"]
                  + stats["episode_reward_defender"])
    prog = float(stats["episode_progress"])
    assert 0 < total <= prog + env.k, (total, prog)


def test_altruistic_selection_runs():
    env = SdagSSZ(k=4, subblock_selection="altruistic", max_steps_hint=96)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=64)
    stats = env.episode_stats(
        jax.random.PRNGKey(13), params, env.policies["honest"], 96)
    assert float(stats["episode_progress"]) > 0
