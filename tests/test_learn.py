"""cpr_tpu.learn: the always-on sampler/learner loop (ISSUE 20).

The load-bearing contracts:

* the experience rings record exactly what the lanes stepped (masked
  scatter vs a numpy reference, ring wrap unrolled oldest-first) and
  partial lanes are dropped-and-counted, never padded;
* sampler key streams are `fold_in` siblings of the lane key — they
  can alias neither the env-dynamics stream nor the legacy rollout's
  `split` children, and per-step keys never repeat across drains;
* hot-swap is zero-drain and bit-deterministic: scripted lanes
  produce bitwise-identical trajectories whether or not a swap landed
  between their bursts, an identical snapshot is a no-op, and a
  structurally different params tree is refused with the typed
  IntegrityError (never a silent retrace);
* the learner's PPO update runs on fed windows (donated train state,
  finite metrics) and its published snapshots round-trip through the
  sealed loader with matching fingerprints;
* the v17 `learn` event is schema-typed, the drain report's learn
  block lifts into both perf-ledger rows, and the staleness gauge
  feeds the burn-rate alert engine.

Shapes stay tiny (nakamoto max_steps=16, 4 lanes, burst 8) so the
module reuses a handful of compiled programs.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu import telemetry
from cpr_tpu.envs import registry
from cpr_tpu.integrity import IntegrityError
from cpr_tpu.learn import ROLES, buffer
from cpr_tpu.learn.feed import decode_batch, encode_batch
from cpr_tpu.params import make_params
from cpr_tpu.serve.engine import ResidentEngine
from cpr_tpu.train.ppo import (ActorCritic, PPOConfig,
                               make_experience_update, make_lane_rollout,
                               make_train)

MAX_STEPS = 16
N_LANES = 4
BURST = 8


@pytest.fixture(scope="module")
def env():
    return registry.get_sized("nakamoto", MAX_STEPS)


@pytest.fixture(scope="module")
def params():
    return make_params(alpha=0.25, gamma=0.5, max_steps=MAX_STEPS)


@pytest.fixture(scope="module")
def net_and_params(env):
    net = ActorCritic(env.n_actions, (8,))
    p = net.init(jax.random.PRNGKey(42),
                 jnp.zeros((1, env.observation_length)))
    return net, jax.device_get(p)


def _swap_engine(env, params, net, p, *, sample=True, fingerprint="fp0"):
    eng = ResidentEngine(
        env, params, n_lanes=N_LANES, burst=BURST,
        swap_policies={"ppo": (lambda w, o: net.apply(w, o)[0], p,
                               fingerprint)},
        sample_policies=("ppo",) if sample else (),
        experience=BURST if sample else 0)
    eng.start()
    eng.splice({lane: 100 + lane for lane in range(N_LANES)})
    return eng


# -- ring buffers ----------------------------------------------------------


def test_record_matches_numpy_reference_with_ring_wrap():
    L, C, D = 3, 4, 2
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(L, dtype=jnp.uint32))
    exp = buffer.init_buffer(jax.vmap(buffer.experience_stream)(keys), C, D)
    rng = np.random.default_rng(0)
    n_steps = 2 * C + 1
    # lane 2 goes dead halfway: its ring must freeze exactly there
    live_plan = np.ones((n_steps, L), bool)
    live_plan[C:, 2] = False
    ref = {k: [[] for _ in range(L)] for k in buffer.FIELDS}
    for s in range(n_steps):
        obs = rng.normal(size=(L, D)).astype(np.float32)
        action = rng.integers(0, 3, L).astype(np.int32)
        reward = rng.normal(size=L).astype(np.float32)
        done = rng.random(L) < 0.3
        era = rng.normal(size=L).astype(np.float32)
        erd = rng.normal(size=L).astype(np.float32)
        pol = rng.integers(0, 5, L).astype(np.int32)
        exp = buffer.record(
            exp, jnp.asarray(live_plan[s]), jnp.asarray(obs),
            jnp.asarray(action), jnp.asarray(reward), jnp.asarray(done),
            {"episode_reward_attacker": jnp.asarray(era),
             "episode_reward_defender": jnp.asarray(erd)},
            jnp.asarray(pol))
        vals = dict(obs=obs, action=action, reward=reward, done=done,
                    era=era, erd=erd, policy=pol)
        for lane in range(L):
            if live_plan[s, lane]:
                for k in buffer.FIELDS:
                    ref[k][lane].append(vals[k][lane])
    host = jax.device_get(exp)
    # cursors advanced per live step only; t matches (no drain yet)
    np.testing.assert_array_equal(host["cursor"], [n_steps, n_steps, C])
    np.testing.assert_array_equal(host["t"], host["cursor"])
    last_obs = rng.normal(size=(L, D)).astype(np.float32)
    batch = buffer.consolidate(host, last_obs)
    # every lane filled (lane 2 exactly at capacity)
    np.testing.assert_array_equal(batch["lanes"], [0, 1, 2])
    assert batch["steps"] == 3 * C and batch["partial"] == 0
    for i, lane in enumerate(batch["lanes"]):
        for k in buffer.FIELDS:
            want = np.stack(ref[k][lane][-C:])  # newest C, time order
            np.testing.assert_array_equal(batch[k][i], want, err_msg=k)
        np.testing.assert_array_equal(batch["last_obs"][i],
                                      last_obs[lane])


def test_consolidate_drops_and_counts_partial_lanes():
    L, C, D = 2, 4, 1
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(L, dtype=jnp.uint32))
    exp = buffer.init_buffer(jax.vmap(buffer.experience_stream)(keys), C, D)
    live = jnp.asarray([True, False])
    for s in range(C - 1):  # neither lane fills
        exp = buffer.record(
            exp, live, jnp.zeros((L, D)), jnp.zeros(L, jnp.int32),
            jnp.zeros(L), jnp.zeros(L, bool),
            {"episode_reward_attacker": jnp.zeros(L),
             "episode_reward_defender": jnp.zeros(L)},
            jnp.zeros(L, jnp.int32))
    batch = buffer.consolidate(jax.device_get(exp), np.zeros((L, D)))
    assert batch["steps"] == 0 and batch["lanes"].size == 0
    assert batch["partial"] == 1
    assert batch["dropped_steps"] == C - 1
    assert batch["obs"].shape == (0, C, D)


def test_experience_stream_cannot_alias_env_or_legacy_keys():
    key = jax.random.PRNGKey(7)
    stream = buffer.experience_stream(key)
    # sibling derivation: distinct from the lane's own env-dynamics
    # key AND from every child the legacy rollout's split would spend
    assert not np.array_equal(np.asarray(stream), np.asarray(key))
    legacy = np.asarray(jax.random.split(key, 16))
    lanes = np.asarray(jax.vmap(
        lambda i: jax.random.fold_in(stream, i))(jnp.arange(16)))
    both = np.concatenate([legacy, lanes])
    assert len({tuple(k) for k in both}) == 32, "key stream collision"


def test_step_keys_never_repeat_across_drains():
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(2, dtype=jnp.uint32))
    exp = buffer.init_buffer(jax.vmap(buffer.experience_stream)(keys), 2, 1)
    seen = set()
    live = jnp.ones(2, bool)
    for _ in range(5):  # several capacity-2 windows with drains between
        for _ in range(2):
            for k in np.asarray(buffer.step_keys(exp)):
                seen.add(tuple(k))
            exp = buffer.record(
                exp, live, jnp.zeros((2, 1)), jnp.zeros(2, jnp.int32),
                jnp.zeros(2), jnp.zeros(2, bool),
                {"episode_reward_attacker": jnp.zeros(2),
                 "episode_reward_defender": jnp.zeros(2)},
                jnp.zeros(2, jnp.int32))
        # drain: cursor resets, t keeps counting
        exp = dict(exp, cursor=jnp.zeros_like(exp["cursor"]))
    assert len(seen) == 2 * 2 * 5, "step key reused across drains"


# -- engine learning plane -------------------------------------------------


def test_sampling_is_reproducible_and_varied(env, params, net_and_params):
    net, p = net_and_params
    drains = []
    for _ in range(2):
        eng = _swap_engine(env, params, net, p)
        ids = {lane: eng.policy_ids["ppo#sample"]
               for lane in range(N_LANES)}
        eng.burst_run(ids, occupancy=1.0)
        drains.append(eng.drain_experience())
    a, b = drains
    assert a is not None and a["steps"] == N_LANES * BURST
    for k in buffer.FIELDS + ("lanes", "last_obs"):
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)
    # sampled, not collapsed: across lanes x steps some actions differ
    assert len(np.unique(a["action"])) > 1


def test_hot_swap_is_bit_deterministic_for_unswapped_lanes(
        env, params, net_and_params):
    net, p = net_and_params
    p2 = jax.device_get(net.init(jax.random.PRNGKey(43),
                                 jnp.zeros((1, env.observation_length))))
    a = _swap_engine(env, params, net, p)
    b = _swap_engine(env, params, net, p)
    # lanes 0/1 scripted, lanes 2/3 on the swappable net
    ids = {0: a.policy_ids["honest"], 1: a.policy_ids["honest"],
           2: a.policy_ids["ppo"], 3: a.policy_ids["ppo#sample"]}
    out_a = a.burst_run(ids, occupancy=1.0)
    out_b = b.burst_run(ids, occupancy=1.0)
    for k in out_a:
        np.testing.assert_array_equal(
            np.asarray(out_a[k]), np.asarray(out_b[k]), err_msg=k)
    # swap lands on B only, between bursts — zero drain, no re-splice
    swapped = b.swap_policy("ppo", p2, fingerprint="fp2")
    assert swapped == {"swapped": True, "fingerprint": "fp2"}
    assert b.policy_fingerprint("ppo") == "fp2"
    out_a2 = a.burst_run(ids, occupancy=1.0)
    out_b2 = b.burst_run(ids, occupancy=1.0)
    for lane in (0, 1):  # scripted lanes: bitwise unperturbed
        for k in out_a2:
            np.testing.assert_array_equal(
                np.asarray(out_a2[k])[lane], np.asarray(out_b2[k])[lane],
                err_msg=f"{k}[lane {lane}]")


def test_identical_snapshot_swap_is_noop(env, params, net_and_params):
    net, p = net_and_params
    eng = _swap_engine(env, params, net, p, sample=False)
    out = eng.swap_policy("ppo", p, fingerprint="fp0")
    assert out["swapped"] is False and out["reason"] == "identical"
    assert eng.swaps == 0


def test_structural_mismatch_is_refused_typed(env, params, net_and_params):
    net, p = net_and_params
    other = ActorCritic(env.n_actions, (12,))  # different hidden width
    p_bad = jax.device_get(other.init(
        jax.random.PRNGKey(1), jnp.zeros((1, env.observation_length))))
    eng = _swap_engine(env, params, net, p, sample=False)
    with pytest.raises(IntegrityError):
        eng.swap_policy("ppo", p_bad, fingerprint="fp-bad")
    assert eng.policy_fingerprint("ppo") == "fp0"  # still serving


def test_unknown_swap_name_raises(env, params, net_and_params):
    net, p = net_and_params
    eng = _swap_engine(env, params, net, p, sample=False)
    with pytest.raises(ValueError, match="swappable"):
        eng.swap_policy("nope", p)


def test_server_refuses_protocol_mismatched_snapshot(
        tmp_path, env, params, net_and_params):
    from cpr_tpu.serve.server import ServeServer
    from cpr_tpu.train.driver import export_policy_snapshot

    net, p = net_and_params
    eng = _swap_engine(env, params, net, p, sample=False)
    server = ServeServer(eng, protocol="nakamoto")
    bad = str(tmp_path / "wrong-proto.msgpack")
    export_policy_snapshot(bad, p, protocol="spar",
                           n_actions=env.n_actions,
                           observation_length=env.observation_length,
                           hidden=[8])
    out = server._swap_from_path(bad)
    assert out.get("refused") and not out.get("ok")
    assert eng.policy_fingerprint("ppo") == "fp0"  # keeps serving
    good = str(tmp_path / "right-proto.msgpack")
    meta = export_policy_snapshot(good, p, protocol="nakamoto",
                                  n_actions=env.n_actions,
                                  observation_length=env.observation_length,
                                  hidden=[8])
    out = server._swap_from_path(good)
    assert out["ok"] and out["swapped"]
    assert eng.policy_fingerprint("ppo") == out["fingerprint"]
    assert server.snapshot_staleness_s() is not None


# -- feed codec ------------------------------------------------------------


def test_feed_codec_roundtrip(env, params, net_and_params):
    net, p = net_and_params
    eng = _swap_engine(env, params, net, p)
    eng.burst_run({lane: eng.policy_ids["ppo#sample"]
                   for lane in range(N_LANES)}, occupancy=1.0)
    batch = eng.drain_experience()
    back = decode_batch(json.loads(json.dumps(encode_batch(batch))))
    for k, v in batch.items():
        if isinstance(v, np.ndarray):
            np.testing.assert_array_equal(back[k], v, err_msg=k)
            assert back[k].dtype == v.dtype, k
        else:
            assert back[k] == v, k


# -- learner ---------------------------------------------------------------


def _cfg():
    return PPOConfig(n_envs=N_LANES, n_steps=BURST, lr=1e-3,
                     update_epochs=1, n_minibatches=1, hidden=(8,))


def test_experience_update_changes_params_finitely(env):
    cfg = _cfg()
    net, init_fn, update, _ = make_experience_update(
        env.n_actions, env.observation_length, cfg)
    ts = init_fn(jax.random.PRNGKey(0))
    # donated input: keep a host copy for the comparison
    before = jax.device_get(ts.params)
    T, N, D = cfg.n_steps, cfg.n_envs, env.observation_length
    rng = np.random.default_rng(3)
    batch = dict(
        obs=jnp.asarray(rng.normal(size=(T, N, D)), jnp.float32),
        action=jnp.asarray(rng.integers(0, env.n_actions, (T, N)),
                           jnp.int32),
        reward=jnp.asarray(rng.normal(size=(T, N)), jnp.float32),
        done=jnp.asarray(rng.random((T, N)) < 0.2),
        era=jnp.asarray(rng.normal(size=(T, N)), jnp.float32),
        erd=jnp.asarray(rng.normal(size=(T, N)), jnp.float32),
        last_obs=jnp.asarray(rng.normal(size=(N, D)), jnp.float32))
    ts, _, metrics = update(ts, batch, jax.random.PRNGKey(1))
    after = jax.device_get(ts.params)
    diffs = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(a - b).max()), before, after))
    assert max(diffs) > 0, "update left params untouched"
    for k in ("pg_loss", "v_loss", "entropy"):
        assert np.isfinite(float(metrics[k])), k


def test_lane_rollout_drives_make_train(env, params):
    cfg = _cfg()
    rollout = make_lane_rollout(env, params, cfg)
    init_fn, train_step = make_train(env, params, cfg,
                                     rollout_phase=rollout)
    carry = init_fn(jax.random.PRNGKey(0))
    carry, metrics = train_step(carry)
    assert np.isfinite(float(metrics["pg_loss"]))
    assert np.isfinite(float(metrics["mean_step_reward"]))


def test_learner_pool_update_publish_roundtrip(tmp_path, env, params,
                                               net_and_params):
    from cpr_tpu.learn.learner import Learner, params_fingerprint
    from cpr_tpu.train.driver import load_policy_network

    net, p = net_and_params
    cfg = _cfg()
    lr = Learner(env, cfg, protocol="nakamoto",
                 publish_dir=str(tmp_path), publish_every=1, seed=0)
    assert lr.fingerprint == params_fingerprint(lr.ts.params)
    lr.publish()  # seq 0, the pre-traffic baseline
    eng = _swap_engine(env, params, net, p)
    eng.burst_run({lane: eng.policy_ids["ppo#sample"]
                   for lane in range(N_LANES)}, occupancy=1.0)
    fed = decode_batch(encode_batch(eng.drain_experience()))
    before = lr.fingerprint
    reply = lr.ingest(fed)
    assert reply["updated"] == 1 and reply["pool"] == 0
    assert lr.updates == 1 and lr.publishes == 2
    assert lr.fingerprint != before
    latest = json.loads(
        (tmp_path / "latest.json").read_text())
    assert latest["seq"] == 1
    _, p_pub, meta = load_policy_network(latest["path"])
    assert meta["payload_sha256"] == latest["fingerprint"] \
        == lr.fingerprint
    # the published params hot-swap cleanly into the serving engine
    out = eng.swap_policy("ppo", p_pub,
                          fingerprint=meta["payload_sha256"])
    assert out["swapped"] and eng.swaps == 1


def test_learner_refuses_mismatched_window(tmp_path, env):
    from cpr_tpu.learn.learner import Learner

    lr = Learner(env, _cfg(), protocol="nakamoto",
                 publish_dir=str(tmp_path))
    D = env.observation_length
    bad = dict(lanes=np.zeros(1, np.int32),
               obs=np.zeros((1, BURST + 1, D), np.float32),
               action=np.zeros((1, BURST + 1), np.int32),
               reward=np.zeros((1, BURST + 1), np.float32),
               done=np.zeros((1, BURST + 1), bool),
               era=np.zeros((1, BURST + 1), np.float32),
               erd=np.zeros((1, BURST + 1), np.float32),
               policy=np.zeros((1, BURST + 1), np.int32),
               last_obs=np.zeros((1, D), np.float32),
               steps=BURST + 1, partial=0, dropped_steps=0)
    with pytest.raises(ValueError, match="window length"):
        lr.ingest(bad)


# -- observability ---------------------------------------------------------


def test_learn_event_is_schema_typed():
    assert telemetry.SCHEMA_VERSION == 17
    assert telemetry.EVENT_FIELDS["learn"] == (
        "role", "steps", "batches", "fingerprint", "staleness_s")
    assert ROLES == ("sample", "feed", "update", "publish", "swap")


def test_ledger_lifts_learn_rows(tmp_path):
    from cpr_tpu.perf.ledger import iter_trace_rows, metric_direction

    trace = tmp_path / "serve.jsonl"
    lines = [
        dict(kind="manifest", backend="cpu", run="r1",
             config=dict(entry="serve", protocol="nakamoto")),
        dict(kind="event", name="serve", action="report",
             detail=dict(steps_per_sec=10.0,
                         learn=dict(samples=512, samples_per_sec=64.0,
                                    snapshot_staleness_s=1.5, swaps=3))),
    ]
    trace.write_text("\n".join(json.dumps(x) for x in lines) + "\n")
    rows = {r["metric"]: r for r, _ in iter_trace_rows(str(trace))}
    assert rows["learn_samples_per_sec"]["value"] == 64.0
    assert rows["learn_snapshot_staleness_s"]["value"] == 1.5
    assert metric_direction("learn_snapshot_staleness_s") == "lower"
    assert metric_direction("learn_samples_per_sec") == "higher"


def test_staleness_gauge_feeds_alert_engine():
    from cpr_tpu.monitor.alerts import AlertEngine

    clock = [0.0]
    eng = AlertEngine(1.0, staleness_slo_s=5.0,
                      windows=((60.0, "page", 1.0),),
                      now_fn=lambda: clock[0])
    eng.record_staleness(2.0)
    assert eng.evaluate() == []  # under budget
    eng.record_staleness(None)  # dropped at the door
    clock[0] = 1.0
    eng.record_staleness(20.0)  # gauge: latest reading judges alone
    fired = eng.evaluate()
    assert [a["signal"] for a in fired] == ["snapshot_staleness"]
    assert fired[0]["value"] == 20.0 and fired[0]["budget"] == 5.0
    # engines without the budget never see the signal
    off = AlertEngine(1.0, windows=((60.0, "page", 1.0),),
                      now_fn=lambda: 0.0)
    off.record_staleness(1e9)
    assert off.evaluate() == []


def test_heartbeat_and_report_carry_learning_fields(
        env, params, net_and_params):
    from cpr_tpu.serve.server import ServeServer

    net, p = net_and_params
    eng = _swap_engine(env, params, net, p)
    server = ServeServer(eng, protocol="nakamoto")
    assert server.snapshot_staleness_s() is not None
    # an engine without swap policies has no staleness gauge
    plain = ResidentEngine(env, params, n_lanes=N_LANES, burst=BURST)
    assert ServeServer(plain).snapshot_staleness_s() is None
