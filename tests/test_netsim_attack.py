"""Attack-subsystem tests: engine invariants, sweep row schema, the
disk cache, and the degenerate-network equivalence anchor.

The anchor (ISSUE 14 acceptance): on a zero-delay two-node clique a
Match can never split the single honest miner, so the in-network
attacker must reproduce the two-party NakamotoSSZ env at gamma=0.
Both sides are seeded Monte-Carlo estimates, so the comparison is a
band on mean relative revenue per (policy, alpha) cell, not an exact
match; at this config (env: 512 steps x 64 reps, netsim: 1500
activations x 6 reps) the observed max gap is 0.036, against a stated
tolerance of 0.05.
"""

import numpy as np
import pytest

from cpr_tpu import netsim, network


def _run_grid(eng, alphas, n_pol, reps, seed=7, delay=60.0):
    ss, dd, aa, pp = [], [], [], []
    for ai, a in enumerate(alphas):
        for pi in range(n_pol):
            for r in range(reps):
                ss.append(seed + 1000 * ai + 100 * pi + r)
                dd.append(delay)
                aa.append(float(a))
                pp.append(pi)
    return eng.run(ss, dd, aa, pp)


def _assert_clean(out):
    for key in ("drop_q", "drop_p", "drop_b", "win_miss"):
        assert not np.any(out[key]), (key, out[key])
    assert not np.any(out["exhausted"]), out["steps"]


def test_attack_engine_validation():
    net = network.two_agents(alpha=0.3, activation_delay=60.0)
    with pytest.raises(ValueError, match="netsim attack supports"):
        netsim.AttackEngine(net, protocol="tailstorm", activations=100)
    with pytest.raises(ValueError, match="unknown attack policies"):
        netsim.AttackEngine(net, activations=100,
                            policies=("honest", "nope"))
    eng = netsim.AttackEngine(net, activations=100)
    with pytest.raises(ValueError, match="alphas must lie"):
        eng.run([0], [60.0], [1.5], [0])
    with pytest.raises(ValueError, match="pair up"):
        eng.run([0, 1], [60.0], [0.3], [0])
    assert not netsim.attack_supports("spar", k=4)
    assert netsim.attack_supports("nakamoto")


def test_attack_engine_invariants():
    """In-network attacker on a real multi-node clique: overflow-free,
    conserved rewards (nakamoto pays 1/block, so attacker + defender
    revenue == head height), all activations accounted."""
    net = network.symmetric_clique(4, activation_delay=30.0,
                                   propagation_delay=10.0)
    eng = netsim.AttackEngine(net, activations=500, topology="clique-4",
                              policies=("honest",
                                        "sapirshtein-2016-sm1"))
    out = _run_grid(eng, alphas=(0.3,), n_pol=2, reps=2)
    _assert_clean(out)
    assert np.all(out["node_act"].sum(axis=1) == 500)
    hh = np.asarray(out["head_height"], np.float64)
    total = (np.asarray(out["reward_attacker"], np.float64)
             + np.asarray(out["reward_defender"], np.float64))
    np.testing.assert_allclose(total, hh, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out["reward"]).sum(axis=1),
                               hh, atol=1e-4)
    assert np.all(hh > 0)


def test_attack_sweep_rows_schema():
    """Supported protocols produce withholding-schema rows; unsupported
    ones degrade to error rows with a machine-readable reason."""
    net = network.two_agents(alpha=0.3, activation_delay=60.0)
    rows = netsim.attack_sweep(
        [("two-agents", net)],
        protocols=(("nakamoto", {}), ("tailstorm", {"k": 8})),
        policies=("honest",), alphas=(0.3,), activation_delays=(60.0,),
        activations=200, reps=2, seed=3)
    good = [r for r in rows if "error" not in r]
    bad = [r for r in rows if "error" in r]
    assert len(good) == 1 and len(bad) == 1
    row = good[0]
    for key in ("protocol", "attack", "alpha", "gamma", "episode_len",
                "reps", "reward_attacker", "reward_defender",
                "relative_reward", "reward_per_progress",
                "machine_duration_s", "topology", "activation_delay",
                "n_nodes", "engine"):
        assert key in row, key
    assert row["attack"] == "nakamoto-honest"
    assert row["gamma"] == -1.0  # gamma emerges from the topology
    assert row["engine"] == "netsim-attack"
    assert 0.0 < row["relative_reward"] < 1.0
    assert bad[0]["reason"] == "unsupported-protocol"
    assert "netsim attack supports protocols" in bad[0]["error"]


def test_attack_sweep_cached(tmp_path, monkeypatch):
    monkeypatch.setenv("CPR_ATTACK_CACHE", str(tmp_path))
    net = network.two_agents(alpha=0.3, activation_delay=60.0)
    kw = dict(policies=("honest",), alphas=(0.3,),
              activation_delays=(60.0,), activations=200, reps=2,
              seed=3)
    first = netsim.attack_sweep_cached(net, "two-agents", **kw)
    assert first["cached"] is False
    assert len(first["rows"]) == 1
    second = netsim.attack_sweep_cached(net, "two-agents", **kw)
    assert second["cached"] is True
    assert second["rows"] == first["rows"]
    # any knob change changes the key
    third = netsim.attack_sweep_cached(net, "two-agents",
                                       **{**kw, "seed": 4})
    assert third["cached"] is False


def test_serve_attack_sweep_dispatch(tmp_path, monkeypatch):
    """The serve op is a thin blocking wrapper over
    attack_sweep_cached: exercise the handler directly (the socket
    path, SIGTERM drain, and cache-hit replay are covered by
    `make attack-smoke`)."""
    from cpr_tpu.serve.server import ServeServer

    monkeypatch.setenv("CPR_ATTACK_CACHE", str(tmp_path))
    srv = ServeServer.__new__(ServeServer)
    srv.attack_policies = {}
    srv.attack_fingerprint = ""
    req = dict(topology={"kind": "two-agents",
                         "activation_delay": 60.0},
               policies=["honest"], alphas=[0.3], activations=200,
               reps=2, seed=3)
    out = srv._attack_sweep(req)
    assert out["ok"] and out["cached"] is False
    assert out["topology"] == "two-agents"
    assert len(out["rows"]) == 1
    assert out["rows"][0]["attack"] == "nakamoto-honest"
    again = srv._attack_sweep(req)
    assert again["cached"] is True
    # arbitrary topologies travel over the wire as GraphML
    from cpr_tpu.network import symmetric_clique, to_graphml
    xml = to_graphml(symmetric_clique(3, activation_delay=30.0,
                                      propagation_delay=5.0))
    out2 = srv._attack_sweep(dict(
        topology={"kind": "graphml", "xml": xml, "label": "wire-3"},
        policies=["honest"], alphas=[0.3], activations=150, reps=1))
    assert out2["ok"] and out2["topology"] == "wire-3"
    assert out2["rows"][0]["n_nodes"] == 3


def test_degenerate_two_party_equivalence():
    """ISSUE 14 anchor: zero-delay two-node clique == two-party
    NakamotoSSZ env at gamma=0, per (policy, alpha) mean relative
    revenue within 0.05 (observed max gap 0.036 at this config)."""
    from cpr_tpu.experiments.withholding import withholding_rows

    alphas = (0.2, 0.33, 0.45)
    pols = ("honest", "eyal-sirer-2014", "sapirshtein-2016-sm1")
    rows = withholding_rows("nakamoto", policies=list(pols),
                            alphas=alphas, gammas=(0.0,),
                            episode_len=512, reps=64, seed=7)
    env_rel = {(r["attack"].removeprefix("nakamoto-"), r["alpha"]):
               r["relative_reward"] for r in rows}

    net = network.two_agents(alpha=0.33, activation_delay=60.0)
    eng = netsim.AttackEngine(net, activations=1500,
                              topology="two-agents", policies=pols)
    reps = 6
    out = _run_grid(eng, alphas, len(pols), reps)
    _assert_clean(out)
    ra = out["reward_attacker"].reshape(len(alphas), len(pols), reps)
    rd = out["reward_defender"].reshape(len(alphas), len(pols), reps)
    rel = (ra / (ra + rd)).mean(-1)
    for ai, a in enumerate(alphas):
        for pi, p in enumerate(pols):
            gap = abs(float(rel[ai, pi]) - env_rel[(p, a)])
            assert gap < 0.05, (p, a, float(rel[ai, pi]), env_rel[(p, a)])
    # sanity of the physics itself: honest tracks alpha, selfish
    # mining at gamma=0 loses at alpha=1/3 and wins big at 0.45
    assert abs(float(rel[0, 0]) - 0.2) < 0.03
    assert float(rel[1, 1]) < 0.34
    assert float(rel[2, 2]) > 0.55
