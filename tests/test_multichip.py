"""Multi-chip sharding paths on the virtual 8-device mesh.

The driver separately executes __graft_entry__.dryrun_multichip; this
keeps the same dp x tp PPO train step and the sharded VI under the
regular suite so regressions surface before the driver run (VERDICT
round-1: the tp path had no test besides the dryrun itself).
"""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


def test_shard_map_shim_public_api_branch(monkeypatch):
    """On jax >= 0.6 the shim must call jax.shard_map with the
    `check_vma` spelling — pinned with a stub so a future rename
    breaks here, not deep inside a sharded VI trace."""
    from cpr_tpu import parallel

    calls = {}

    def fake_shard_map(body, *, mesh, in_specs, out_specs, **kw):
        calls.update(kw, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)
        return body

    monkeypatch.setattr(jax, "shard_map", fake_shard_map,
                        raising=False)
    body = lambda x: x  # noqa: E731
    out = parallel._shard_map(body, mesh="m", in_specs="i",
                              out_specs="o", check_vma=False)
    assert out is body
    assert calls == dict(check_vma=False, mesh="m", in_specs="i",
                         out_specs="o")


def test_shard_map_shim_experimental_fallback(monkeypatch):
    """Without jax.shard_map (jax < 0.6) the shim must route to
    jax.experimental.shard_map with the knob respelled `check_rep`."""
    import jax.experimental.shard_map as esm

    from cpr_tpu import parallel

    calls = {}

    def fake_shard_map(body, *, mesh, in_specs, out_specs, **kw):
        calls.update(kw)
        return body

    monkeypatch.delattr(jax, "shard_map", raising=False)
    monkeypatch.setattr(esm, "shard_map", fake_shard_map)
    out = parallel._shard_map(lambda x: x, mesh="m", in_specs="i",
                              out_specs="o", check_vma=True)
    assert callable(out)
    assert calls == dict(check_rep=True)
    assert "check_vma" not in calls


def test_dp_tp_train_step_and_sharded_vi():
    from jax.sharding import Mesh

    from cpr_tpu.envs.nakamoto import NakamotoSSZ
    from cpr_tpu.params import make_params
    from cpr_tpu.train.ppo import PPOConfig, make_train, shardings

    devices = jax.devices()[:8]
    mesh = Mesh(np.asarray(devices).reshape(4, 2), ("dp", "tp"))

    env = NakamotoSSZ()
    env_params = make_params(alpha=0.35, gamma=0.5, max_steps=32)
    cfg = PPOConfig(n_envs=16, n_steps=8, n_minibatches=2,
                    update_epochs=2, hidden=(16, 16))
    init_fn, train_step = make_train(env, env_params, cfg)
    ts, env_state, obs, key = init_fn(jax.random.PRNGKey(0))

    batch_sharding, param_spec = shardings(mesh)
    env_state = jax.tree.map(
        lambda x: jax.device_put(x, batch_sharding), env_state)
    obs = jax.device_put(obs, batch_sharding)
    sharded_params = jax.tree_util.tree_map_with_path(
        lambda path, x: jax.device_put(x, param_spec(path, x)), ts.params)
    ts = ts.replace(params=sharded_params)

    (ts, env_state, obs, key), metrics = jax.jit(train_step)(
        (ts, env_state, obs, key))
    jax.block_until_ready(metrics)
    assert np.isfinite(float(metrics["pg_loss"]))
    # parameters keep their tp sharding through the update
    kernel = jax.tree_util.tree_leaves(ts.params)[0]
    assert not kernel.sharding.is_fully_replicated or kernel.ndim == 1


def test_sharded_rollout_chunked_matches_unchunked():
    """sharded_rollout with chunk= must agree with the one-call path —
    and the sharded inputs must stay partitioned through the chunked
    host loop (the single-device chunk driver's multichip twin)."""
    from cpr_tpu.envs.nakamoto import NakamotoSSZ
    from cpr_tpu.params import make_params
    from cpr_tpu.parallel import default_mesh, sharded_rollout

    env = NakamotoSSZ()
    params = make_params(alpha=0.35, gamma=0.5, max_steps=24)
    mesh = default_mesh(devices=jax.devices()[:8])
    keys = jax.random.split(jax.random.PRNGKey(3), 32)
    pol = env.policies["sapirshtein-2016-sm1"]
    whole = sharded_rollout(env, mesh, keys, params, pol, 48)
    parts = sharded_rollout(env, mesh, keys, params, pol, 48, chunk=20)
    # the chunked path must keep per-env outputs mesh-partitioned, not
    # silently replicate them
    assert not parts["episode_progress"].sharding.is_fully_replicated
    for k in whole:
        np.testing.assert_allclose(np.asarray(whole[k]),
                                   np.asarray(parts[k]), rtol=1e-5,
                                   err_msg=k)


def test_dag_env_train_step_and_ghostdag_shard_vi():
    """The round-4 dryrun extensions under the regular suite: the dp x tp
    PPO train step over a DAG-family env (tailstorm — the env state
    carries the whole per-env DAG pytree), and the mesh-sharded chunked
    VI over a GhostDAG generic-DAG model — the kernels the capstone
    actually shards on chips."""
    from jax.sharding import Mesh

    from cpr_tpu.envs.tailstorm import TailstormSSZ
    from cpr_tpu.mdp import ptmdp
    from cpr_tpu.mdp.generic.native import compile_native
    from cpr_tpu.parallel import sharded_value_iteration
    from cpr_tpu.params import make_params
    from cpr_tpu.train.ppo import PPOConfig, make_train, shardings

    devices = jax.devices()[:8]
    mesh = Mesh(np.asarray(devices).reshape(4, 2), ("dp", "tp"))

    env = TailstormSSZ(k=2, incentive_scheme="discount",
                       subblock_selection="heuristic", max_steps_hint=24)
    cfg = PPOConfig(n_envs=16, n_steps=4, n_minibatches=2,
                    update_epochs=1, hidden=(16, 16))
    init_fn, train_step = make_train(
        env, make_params(alpha=0.35, gamma=0.5, max_steps=24), cfg)
    ts, env_state, obs, key = init_fn(jax.random.PRNGKey(1))
    batch_sharding, param_spec = shardings(mesh)
    env_state = jax.tree.map(
        lambda x: jax.device_put(x, batch_sharding), env_state)
    obs = jax.device_put(obs, batch_sharding)
    ts = ts.replace(params=jax.tree_util.tree_map_with_path(
        lambda path, x: jax.device_put(x, param_spec(path, x)), ts.params))
    (ts, env_state, obs, key), metrics = jax.jit(train_step)(
        (ts, env_state, obs, key))
    jax.block_until_ready(metrics)
    assert np.isfinite(float(metrics["pg_loss"]))
    assert 0.0 < float(metrics["entropy"]) <= np.log(env.n_actions) + 0.1

    flat_mesh = Mesh(np.asarray(devices), ("d",))
    table = compile_native("ghostdag", k=2, alpha=0.3, gamma=0.5,
                           collect_garbage="simple", dag_size_cutoff=5)
    tm = ptmdp(table, horizon=10).tensor()
    vi = sharded_value_iteration(tm, flat_mesh, stop_delta=1e-5,
                                 impl="chunked", chunk=8)
    # sharded chunked solve equals the single-device while solve
    single = tm.value_iteration(stop_delta=1e-5)
    rev_sharded = tm.start_value(vi["vi_value"])
    rev_single = tm.start_value(single["vi_value"])
    assert abs(rev_sharded - rev_single) < 1e-4, (rev_sharded, rev_single)
