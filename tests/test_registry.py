"""Registry + protocol-key parser tests (cpr_protocols.ml:786-903 expect
test analog)."""

import pytest

from cpr_tpu.envs import registry


def test_family_keys_present():
    ks = registry.keys()
    for family in ("nakamoto", "ethereum", "bk", "spar", "stree", "sdag",
                   "tailstorm"):
        assert family in ks


@pytest.mark.parametrize("key,cls,attrs,kwargs", [
    ("nakamoto", "NakamotoSSZ", {}, {}),
    ("ethereum-byzantium", "EthereumSSZ", {}, {"max_steps_hint": 32}),
    ("bk-4-constant", "BkSSZ", {"k": 4, "incentive_scheme": "constant"},
     {"max_steps_hint": 32}),
    ("spar-4-block", "SparSSZ", {"k": 4, "incentive_scheme": "block"},
     {"max_steps_hint": 32}),
    ("stree-4-discount-altruistic", "StreeSSZ",
     {"k": 4, "incentive_scheme": "discount",
      "subblock_selection": "altruistic"}, {"max_steps_hint": 32}),
    ("sdag-4-constant-altruistic", "SdagSSZ", {"k": 4},
     {"max_steps_hint": 32}),
    ("tailstorm-4-discount-heuristic", "TailstormSSZ",
     {"k": 4, "incentive_scheme": "discount"}, {"max_steps_hint": 32}),
])
def test_parse_and_instantiate(key, cls, attrs, kwargs):
    env = registry.get(key, **kwargs)
    assert type(env).__name__ == cls
    for a, v in attrs.items():
        assert getattr(env, a) == v, (a, getattr(env, a), v)


def test_bad_keys_rejected():
    for key in ("tailstorm-x-discount", "foo", "bk-4-constant-extra-bits",
                "ethereum-petersburg",
                # every option is mandatory, as in the reference grammar
                # (cpr_protocols.ml:800-811)
                "bk-4", "stree-4-constant", "tailstorm-8-discount",
                # k bounds: sdag requires k >= 2 (sdag.ml:24)
                "sdag-1-constant-altruistic", "bk-0-constant"):
        with pytest.raises(KeyError):
            registry.get(key)


def test_describe_info_strings():
    from cpr_tpu.envs import registry

    all_info = registry.describe()
    assert set(all_info) == set(registry.keys())
    assert all(all_info.values()), "every family needs an info string"
    assert "longest chain" in registry.describe("nakamoto")
    assert registry.describe("tailstorm-8-discount-heuristic")
