"""Stree env tests (stree.ml validity + stochastic batteries)."""

import jax
import numpy as np
import pytest

from cpr_tpu.envs.stree import BLOCK, VOTE, StreeSSZ
from cpr_tpu.params import make_params

# deep stochastic battery: opt-in (fast coverage lives in
# test_protocol_smoke.py)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def env():
    return StreeSSZ(k=4, incentive_scheme="constant", max_steps_hint=192)


def run_policy(env, name, alpha, n_envs=128, episode_steps=128, seed=0):
    params = make_params(alpha=alpha, gamma=0.5, max_steps=episode_steps)
    policy = env.policies[name]
    keys = jax.random.split(jax.random.PRNGKey(seed), n_envs)
    stats = jax.vmap(
        lambda k: env.episode_stats(k, params, policy, episode_steps + 32)
    )(keys)
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    return atk / (atk + dfn)


def test_honest_policy_yields_alpha(env):
    for alpha in [0.25, 0.4]:
        rel = run_policy(env, "honest", alpha)
        assert abs(rel - alpha) < 0.05, (alpha, rel)


def test_dag_structure_invariants(env):
    """stree.ml:128-152: votes have one parent, depth = parent + 1, same
    block; blocks have a block parent plus leaves whose closure has
    exactly k-1 unique votes, all confirming the parent block."""
    params = make_params(alpha=0.35, gamma=0.5, max_steps=160)
    state, obs = env.reset(jax.random.PRNGKey(3), params)
    step = jax.jit(env.step)
    policy = env.policies["release-block"]
    for _ in range(160):
        state, obs, r, done, info = step(state, policy(obs), params)
    dag = state.dag
    n = int(dag.n)
    assert not bool(dag.overflow)
    parents = np.stack([np.asarray(q) for q in dag.parents], axis=1)[:n]
    kind = np.asarray(dag.kind)[:n]
    height = np.asarray(dag.height)[:n]
    depth = np.asarray(dag.aux)[:n]
    signer = np.asarray(dag.signer)[:n]
    powh = np.asarray(dag.pow_hash)[:n]

    def closure(leaf):
        seen = set()
        cur = leaf
        while cur >= 0 and kind[cur] == VOTE:
            seen.add(cur)
            cur = parents[cur][0]
        return seen

    saw_block = False
    for i in range(1, n):
        ps = parents[i][parents[i] >= 0]
        assert np.isfinite(powh[i])
        if kind[i] == VOTE:
            assert len(ps) == 1
            p = ps[0]
            assert depth[i] == depth[p] + 1
            want = p if kind[p] == BLOCK else signer[p]
            assert signer[i] == want
            assert height[i] == height[want]
        else:
            saw_block = True
            p0, leaves = ps[0], ps[1:]
            assert kind[p0] == BLOCK
            assert height[i] == height[p0] + 1
            votes = set()
            for leaf in leaves:
                assert kind[leaf] == VOTE
                votes |= closure(leaf)
            assert len(votes) == env.k - 1, (i, leaves)
            assert all(signer[v] == p0 for v in votes)
    assert saw_block


def test_progress_tracks_activations(env):
    params = make_params(alpha=0.3, gamma=0.5, max_steps=160)
    stats = env.episode_stats(
        jax.random.PRNGKey(7), params, env.policies["honest"], 192)
    prog = float(stats["episode_progress"])
    acts = float(stats["episode_n_activations"])
    assert prog > 0 and prog / acts > 0.7, (prog, acts)


def test_policies_run_and_terminate(env):
    params = make_params(alpha=0.4, gamma=0.5, max_steps=96)
    for name, policy in env.policies.items():
        traj = env.rollout(jax.random.PRNGKey(5), params, policy, 160)
        done = np.asarray(traj[3])
        assert done.sum() >= 1, name


def test_discount_scheme_bounds_rewards():
    env = StreeSSZ(k=4, incentive_scheme="discount", max_steps_hint=96)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=64)
    stats = env.episode_stats(
        jax.random.PRNGKey(11), params, env.policies["honest"], 96)
    total = float(stats["episode_reward_attacker"]
                  + stats["episode_reward_defender"])
    prog = float(stats["episode_progress"])
    assert 0 < total <= prog + env.k, (total, prog)


def test_altruistic_selection_runs():
    env = StreeSSZ(k=4, subblock_selection="altruistic", max_steps_hint=96)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=64)
    stats = env.episode_stats(
        jax.random.PRNGKey(13), params, env.policies["honest"], 96)
    assert float(stats["episode_progress"]) > 0
