"""Frontier-batched MDP compile tests (docs/MDP.md): bit-identity of
FrontierCompiler against the serial Compiler (inline, multi-worker,
and across a kill@compile_round + resume), ParamMDP coef/expo parity
through the columnar tracer collect, the bulk MDP.add_transitions
chunk semantics, the padded_layout memory guard, the v12 `mdp_compile`
telemetry event + its perf-ledger rows, and the serve break_even exact
mode riding solve_grid_cached."""

import importlib.util
import json
import os

import numpy as np
import pytest

from cpr_tpu import telemetry
from cpr_tpu.mdp import Compiler, FrontierCompiler, PaddedLayoutTooLarge
from cpr_tpu.mdp import grid
from cpr_tpu.mdp.explicit import MDP, ptmdp
from cpr_tpu.mdp.models import Aft20BitcoinSM, Fc16BitcoinSM
from cpr_tpu.resilience import FAULT_ENV_VAR, InjectedKill

MFL = 6
COLS = ("src", "act", "dst", "prob", "reward", "progress")


def fc16_model():
    return Fc16BitcoinSM(alpha=0.33, gamma=0.7, maximum_fork_length=MFL)


def ghostdag_model():
    from cpr_tpu.mdp.generic import SingleAgent, get_protocol

    return SingleAgent(get_protocol("ghostdag", k=2), alpha=0.3,
                       gamma=0.5, collect_garbage="simple",
                       merge_isomorphic=True, truncate_common_chain=True,
                       dag_size_cutoff=5)


MODELS = {
    "fc16": fc16_model,
    "aft20": lambda: Aft20BitcoinSM(alpha=0.33, gamma=0.7,
                                    maximum_fork_length=MFL),
    "ghostdag": ghostdag_model,
}


def assert_mdp_equal(a: MDP, b: MDP):
    assert a.n_states == b.n_states and a.n_actions == b.n_actions
    assert a.n_transitions == b.n_transitions
    assert dict(a.start) == dict(b.start)
    for x, y, name in zip(a.arrays(), b.arrays(), COLS):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=name)


# ------------------------------------------------------- bit-identity


@pytest.mark.parametrize("proto", sorted(MODELS))
def test_frontier_bit_identical_to_serial(proto):
    ref = Compiler(MODELS[proto]()).mdp()
    out = FrontierCompiler(MODELS[proto]()).mdp()
    assert_mdp_equal(ref, out)


def test_frontier_multiworker_bit_identical():
    ref = Compiler(fc16_model()).mdp()
    fc = FrontierCompiler(fc16_model(), n_workers=2)
    fc.min_shard = 1  # tiny fixture: force sharded expansion
    assert_mdp_equal(ref, fc.mdp())


def test_param_mdp_parity_including_exponent_columns():
    a, g = grid.param_pair(grid.PROBE_ALPHA, grid.PROBE_GAMMA)
    ref = grid._param_mdp_from(
        Compiler(Fc16BitcoinSM(alpha=a, gamma=g,
                               maximum_fork_length=MFL)).mdp(),
        grid.PROBE_ALPHA, grid.PROBE_GAMMA, {})
    out = grid.parametric_compile(
        lambda alpha, gamma: Fc16BitcoinSM(alpha=alpha, gamma=gamma,
                                           maximum_fork_length=MFL))
    assert_mdp_equal(ref.mdp, out.mdp)
    np.testing.assert_array_equal(ref.coef, out.coef)
    np.testing.assert_array_equal(ref.expo, out.expo)
    np.testing.assert_array_equal(ref.start_ids, out.start_ids)
    np.testing.assert_array_equal(ref.start_coef, out.start_coef)
    np.testing.assert_array_equal(ref.start_expo, out.start_expo)


# -------------------------------------------------- checkpoint/resume


def test_compile_round_kill_and_resume_bit_identical(tmp_path,
                                                     monkeypatch):
    ref = Compiler(fc16_model()).mdp()
    ck = str(tmp_path / "compile-ck.npz")
    monkeypatch.setenv(FAULT_ENV_VAR, "kill@compile_round=3")
    with pytest.raises(InjectedKill):
        FrontierCompiler(fc16_model(), checkpoint_path=ck).mdp()
    assert os.path.exists(ck)  # rounds 1-2 landed before the crash

    monkeypatch.delenv(FAULT_ENV_VAR)
    out = FrontierCompiler(fc16_model(), checkpoint_path=ck).mdp()
    assert_mdp_equal(ref, out)
    # crash-recovery scratch is deleted once the compile completes
    assert not os.path.exists(ck) and not os.path.exists(ck + ".json")


def test_checkpoint_rejects_different_model(tmp_path, monkeypatch):
    ck = str(tmp_path / "compile-ck.npz")
    monkeypatch.setenv(FAULT_ENV_VAR, "kill@compile_round=2")
    with pytest.raises(InjectedKill):
        FrontierCompiler(fc16_model(), checkpoint_path=ck).mdp()
    monkeypatch.delenv(FAULT_ENV_VAR)
    other = Fc16BitcoinSM(alpha=0.4, gamma=0.7, maximum_fork_length=MFL)
    with pytest.raises(ValueError, match="is for model"):
        FrontierCompiler(other, checkpoint_path=ck)


# ---------------------------------------------------------- telemetry


def _load_trace_summary():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_summary.py")
    spec = importlib.util.spec_from_file_location("trace_summary", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_mdp_compile_event_validates(tmp_path):
    trace = tmp_path / "compile.jsonl"
    telemetry.configure(str(trace))
    try:
        telemetry.current().manifest(config={"role": "test-frontier"})
        FrontierCompiler(fc16_model(), protocol="fc16",
                         cutoff=MFL).mdp()
    finally:
        telemetry.configure(None)
    ts = _load_trace_summary()
    events, bad = ts.read_events(str(trace))
    assert ts.validate(events, bad, expect=("mdp_compile",)) == []
    (ev,) = [e for e in events if e.get("name") == "mdp_compile"]
    assert ev["protocol"] == "fc16" and ev["cutoff"] == MFL
    assert ev["n_workers"] == 1 and ev["resumed"] is False
    assert ev["rounds"] > 1 and ev["states"] == 88
    assert ev["states_per_sec"] > 0


def test_mdp_compile_event_banks_in_ledger(tmp_path):
    from cpr_tpu.perf.ledger import Ledger

    trace = tmp_path / "compile.jsonl"
    telemetry.configure(str(trace))
    try:
        telemetry.current().manifest(config={"devices": 1})
        FrontierCompiler(fc16_model(), protocol="fc16",
                         cutoff=MFL).mdp()
    finally:
        telemetry.configure(None)
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    assert led.ingest_trace(str(trace)) >= 1
    by_metric = {r["metric"]: r for r in led.records()}
    row = by_metric["mdp_compile_states_per_sec"]
    assert row["unit"] == "states/sec" and row["value"] > 0
    assert row["config"]["cfg_protocol"] == "fc16"
    assert row["config"]["cfg_cutoff"] == MFL
    assert row["config"]["cfg_workers"] == 1


# -------------------------------------------------- bulk transitions


def test_add_transitions_matches_serial_appends():
    a, b = MDP(), MDP()
    rows = [(0, 0, 1, 0.3, 1.0, 0.0), (0, 0, 2, 0.7, 0.0, 1.0),
            (1, 1, 0, 1.0, 0.5, 0.5)]
    for r in rows:
        a.add_transition(r[0], r[1], r[2], probability=r[3],
                         reward=r[4], progress=r[5])
    cols = list(zip(*rows))
    b.add_transitions(*cols)
    assert a.n_states == b.n_states and a.n_actions == b.n_actions
    for x, y in zip(a.arrays(), b.arrays()):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_add_transitions_cache_invalidation_and_mixed_use():
    m = MDP()
    m.add_transitions([0], [0], [1], [1.0], [0.0], [0.0])
    first = m.arrays()
    assert m.arrays() is first  # cached + zero-copy on the fast path
    # single-transition append after a bulk chunk keeps call order
    m.add_transition(1, 0, 0, probability=1.0, reward=2.0, progress=0.5)
    assert m.arrays() is not first  # appends invalidate the cache
    src, act, dst, prob, reward, progress = m.arrays()
    np.testing.assert_array_equal(src, [0, 1])
    np.testing.assert_array_equal(reward, [0.0, 2.0])
    assert m.n_transitions == 2 and m.n_states == 2
    m.add_transitions([0, 1], [1, 1], [1, 1], [0.5, 0.5], [0, 0], [0, 0])
    assert m.n_transitions == 4 and m.n_actions == 2
    np.testing.assert_array_equal(m.arrays()[1], [0, 0, 1, 1])


def test_consolidate_folds_chunks_into_fields():
    m = MDP()
    m.add_transitions([0, 0], [0, 0], [1, 2], [0.4, 0.6], [1, 0], [0, 1])
    m.add_transitions([1], [0], [0], [1.0], [0.0], [1.0])
    assert m.consolidate() is m
    assert isinstance(m.src, np.ndarray) and len(m.src) == 3
    assert m.arrays()[0] is m.src  # zero-copy after consolidation
    m.start = {0: 1.0}
    m.check()


def test_add_transitions_rejects_ragged_and_negative():
    m = MDP()
    with pytest.raises(ValueError, match="equal-length"):
        m.add_transitions([0, 1], [0], [1], [1.0], [0.0], [0.0])
    with pytest.raises(ValueError, match="negative"):
        m.add_transitions([-1], [0], [1], [1.0], [0.0], [0.0])
    m.add_transitions([], [], [], [], [], [])  # empty append is a no-op
    assert m.n_transitions == 0


# --------------------------------------------------- padded layout guard


def test_padded_layout_memory_guard(monkeypatch):
    pt = ptmdp(Compiler(fc16_model()).mdp(), horizon=10)
    assert pt.tensor().padded_layout()  # default ~2 GiB ceiling passes
    monkeypatch.setenv("CPR_MDP_PAD_BYTES", "1")
    with pytest.raises(PaddedLayoutTooLarge) as ei:
        pt.tensor().padded_layout()
    # the fallback is named so the error is actionable
    assert "COO sweep" in str(ei.value)
    assert "CPR_MDP_PAD_BYTES" in str(ei.value)


# ------------------------------------------------- serve break_even exact


def test_serve_break_even_exact_round_trip(tmp_path, monkeypatch):
    """The exact mode of the break_even.* ops rides solve_grid_cached:
    first query computes, the repeat is a fingerprint-keyed disk-cache
    hit surfaced by the `cached` flag (the full socket path is covered
    by tools/compile_smoke.py + serve-smoke)."""
    from cpr_tpu.serve.server import ServeServer

    monkeypatch.setenv("CPR_MDP_CACHE", str(tmp_path))
    srv = ServeServer.__new__(ServeServer)
    req = dict(mode="exact", protocol="fc16", gamma=0.5, cutoff=MFL,
               alphas=[0.25, 0.4], horizon=30)
    out = srv._break_even(dict(req), "break_even.revenue")
    assert out["ok"] and out["mode"] == "exact"
    assert out["cached"] is False and len(out["revenue"]) == 2
    assert out["revenue"] == sorted(out["revenue"])
    again = srv._break_even(dict(req), "break_even.revenue")
    assert again["cached"] is True
    assert again["revenue"] == out["revenue"]
    assert again["fingerprint"] == out["fingerprint"]

    be = srv._break_even(dict(mode="exact", protocol="fc16", gamma=0.5,
                              cutoff=MFL, support=(0.1, 0.45), grid=5,
                              horizon=30), "break_even.alpha")
    assert be["ok"] and 0.1 <= be["alpha"] <= 0.45
    assert "fingerprint" in be
