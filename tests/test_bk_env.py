"""Bₖ env tests: stochastic integration checks in the style of the
reference's orphan-rate batteries (cpr_protocols.ml:200-657) plus DAG
structure invariants (the analog of the Rust gym's dag_check,
gym/rust/src/generic/mod.rs:107)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu.core import dag as D
from cpr_tpu.envs.bk import BLOCK, VOTE, BkSSZ
from cpr_tpu.params import make_params

# deep stochastic battery: opt-in (fast coverage lives in
# test_protocol_smoke.py)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def env():
    return BkSSZ(k=4, incentive_scheme="constant", max_steps_hint=160)


def run_policy(env, name, alpha, n_envs=192, episode_steps=128, seed=0):
    params = make_params(alpha=alpha, gamma=0.5, max_steps=episode_steps)
    policy = env.policies[name]
    keys = jax.random.split(jax.random.PRNGKey(seed), n_envs)
    stats = jax.vmap(
        lambda k: env.episode_stats(k, params, policy, episode_steps + 32)
    )(keys)
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    return atk / (atk + dfn)


def test_honest_policy_yields_alpha(env):
    # honest behaviour earns the compute share; constant rewards pay per
    # vote included in a block (bk.ml:151-161)
    for alpha in [0.2, 0.4]:
        rel = run_policy(env, "honest", alpha)
        assert abs(rel - alpha) < 0.04, (alpha, rel)


def test_dag_structure_invariants(env):
    """Roll an episode and check Bₖ validity (bk.ml:110-132) on the final
    DAG: votes have one block parent at the same height; blocks have a
    block parent at height-1 plus exactly k votes ordered by hash."""
    params = make_params(alpha=0.35, gamma=0.5, max_steps=128)
    state, obs = env.reset(jax.random.PRNGKey(3), params)
    step = jax.jit(env.step)
    policy = env.policies["get-ahead"]
    for _ in range(128):
        state, obs, r, done, info = step(state, policy(obs), params)
    dag = state.dag
    n = int(dag.n)
    assert not bool(dag.overflow)
    parents = np.stack([np.asarray(q) for q in dag.parents], axis=1)[:n]
    kind = np.asarray(dag.kind)[:n]
    height = np.asarray(dag.height)[:n]
    powh = np.asarray(dag.pow_hash)[:n]
    for i in range(1, n):
        ps = parents[i][parents[i] >= 0]
        if kind[i] == VOTE:
            assert len(ps) == 1
            assert kind[ps[0]] == BLOCK
            assert height[i] == height[ps[0]]
            assert np.isfinite(powh[i])
        else:
            assert kind[ps[0]] == BLOCK
            assert height[i] == height[ps[0]] + 1
            votes = ps[1:]
            assert len(votes) == env.k, (i, ps)
            assert all(kind[v] == VOTE for v in votes)
            hashes = powh[votes]
            assert (np.diff(hashes) > 0).all(), "votes must be hash-ordered"


def test_policies_run_and_terminate(env):
    params = make_params(alpha=0.4, gamma=0.5, max_steps=96)
    for name, policy in env.policies.items():
        traj = env.rollout(jax.random.PRNGKey(5), params, policy, 200)
        done = np.asarray(traj[3])
        assert done.sum() >= 1, name  # episodes complete
        actions = np.asarray(traj[1])
        assert actions.min() >= 0 and actions.max() < env.n_actions


def test_withholding_beats_honest_at_high_alpha(env):
    # the avoid-loss policy should out-earn the honest share for a strong
    # attacker (the reference's withholding experiments,
    # experiments/simulate/withholding.ml)
    rel_h = run_policy(env, "honest", 0.44)
    rel_w = run_policy(env, "avoid-loss", 0.44, episode_steps=192)
    # measured ~0.44 honest vs ~0.59 avoid-loss; require a real margin
    assert rel_w > rel_h + 0.05, (rel_h, rel_w)
    assert rel_w > 0.44 + 0.05, rel_w
