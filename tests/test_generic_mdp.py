"""Generic DAG-protocol MDP family tests.

Mirrors the reference's validation strategy
(mdp/lib/models/generic_v1/test/test_single_agent_model.py): random walks
around the honest policy must earn ~alpha per progress, exploration must
not violate invariants, and canonicalization must merge isomorphic states
without changing values.  Adds the capstone: GhostDAG compiles to an
explicit table and the mesh-sharded VI reproduces the single-device
solve.
"""

import random

import numpy as np
import pytest

from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.generic import SingleAgent, get_protocol
from cpr_tpu.mdp.generic.canon import canonical_order


def walk(m, n, exp=0.0, seed=42):
    rng = random.Random(seed)
    s = m.start()[0][0]
    prg = rew = 0.0
    for _ in range(n):
        if rng.random() < exp:
            opts = m.actions(s)
            a = opts[rng.randrange(len(opts))]
        else:
            a = m.honest(s)
        ts = m.apply(a, s)
        assert abs(sum(t.probability for t in ts) - 1.0) < 1e-9
        t = rng.choices(ts, weights=[t.probability for t in ts])[0]
        s, prg, rew = t.state, prg + t.progress, rew + t.reward
    return rew, prg, s


PROTOS = [
    ("bitcoin", {}),
    ("ethereum", {}),
    ("byzantium", {}),
    ("parallel", {"k": 3}),
    ("ghostdag", {"k": 3}),
]


@pytest.mark.parametrize("name,kw", PROTOS)
def test_honest_walk_earns_alpha(name, kw):
    m = SingleAgent(get_protocol(name, **kw), alpha=0.33, gamma=0.5,
                    collect_garbage="simple", merge_isomorphic=False,
                    truncate_common_chain=True)
    rew, prg, s = walk(m, 400)
    assert 0.27 <= rew / prg <= 0.40, rew / prg
    # truncation keeps the DAG bounded along honest play
    assert s.dag.size() <= 8


@pytest.mark.parametrize("name,kw", PROTOS)
def test_exploring_walk_keeps_invariants(name, kw):
    m = SingleAgent(get_protocol(name, **kw), alpha=0.33, gamma=0.5,
                    collect_garbage="simple", merge_isomorphic=True,
                    truncate_common_chain=True)
    rew, prg, s = walk(m, 60, exp=0.4)
    assert prg >= 0.0 and s.dag.size() >= 1


def test_honest_policy_evaluation_yields_alpha():
    alpha = 0.3
    m = SingleAgent(get_protocol("bitcoin"), alpha=alpha, gamma=0.5,
                    collect_garbage="simple", merge_isomorphic=True,
                    truncate_common_chain=True, dag_size_cutoff=6)
    c = Compiler(m)
    mdp = ptmdp(c.mdp(), horizon=30)
    tm = mdp.tensor()
    policy = np.full(mdp.n_states, -1, np.int32)
    for sid, st in enumerate(c.states):
        policy[sid] = c.action_map[sid].index(c.model.honest(st))
    pe = tm.policy_evaluation(policy, theta=1e-7)
    rev = tm.start_value(pe["pe_reward"]) / tm.start_value(pe["pe_progress"])
    assert abs(rev - alpha) < 0.005, rev


def test_optimal_between_honest_and_upper_bound():
    alpha, gamma = 0.35, 0.5
    m = SingleAgent(get_protocol("bitcoin"), alpha=alpha, gamma=gamma,
                    collect_garbage="simple", merge_isomorphic=True,
                    truncate_common_chain=True, dag_size_cutoff=6)
    tm = ptmdp(Compiler(m).mdp(), horizon=30).tensor()
    vi = tm.value_iteration(stop_delta=1e-6)
    rev = tm.start_value(vi["vi_value"]) / tm.start_value(vi["vi_progress"])
    assert alpha - 0.005 <= rev <= alpha / (1 - alpha) + 1e-6, rev


def test_merge_isomorphic_preserves_value_and_shrinks():
    kw = dict(alpha=0.32, gamma=0.6, collect_garbage="simple",
              truncate_common_chain=True, dag_size_cutoff=6)
    merged = Compiler(SingleAgent(get_protocol("bitcoin"),
                                  merge_isomorphic=True, **kw)).mdp()
    plain = Compiler(SingleAgent(get_protocol("bitcoin"),
                                 merge_isomorphic=False, **kw)).mdp()
    assert merged.n_states < plain.n_states
    vi_m = ptmdp(merged, horizon=20).tensor()
    vi_p = ptmdp(plain, horizon=20).tensor()
    r_m = vi_m.value_iteration(stop_delta=1e-7)
    r_p = vi_p.value_iteration(stop_delta=1e-7)
    assert abs(vi_m.start_value(r_m["vi_value"])
               - vi_p.start_value(r_p["vi_value"])) < 1e-4


def test_canonical_order_invariant_under_relabeling():
    """Permuting a colored DAG (topologically) must not change its
    canonical form."""
    rng = random.Random(0)
    parents = ((), (0,), (0,), (1, 2), (1, 2), (3,))
    colors = (0, 1, 1, 2, 2, 1)
    heights = (0, 1, 1, 2, 2, 3)

    def canon_form(parents, colors, heights):
        order = canonical_order(parents, colors, heights)
        new_id = {b: i for i, b in enumerate(order)}
        return tuple(
            (colors[b], tuple(sorted(new_id[p] for p in parents[b])))
            for b in order
        )

    base = canon_form(parents, colors, heights)
    # swap the two interchangeable height-1 siblings and the height-2 pair
    perm = {0: 0, 1: 2, 2: 1, 3: 4, 4: 3, 5: 5}
    p2 = tuple(tuple(sorted(perm[p] for p in parents[b]))
               for b in sorted(range(6), key=lambda b: perm[b]))
    c2 = tuple(colors[b] for b in sorted(range(6), key=lambda b: perm[b]))
    assert canon_form(p2, c2, heights) == base
    assert rng is not None


def test_ghostdag_capstone_sharded_vi():
    """BASELINE.md target config 5: GhostDAG MDP value iteration solved
    by the mesh-sharded sweep, equal to the single-device solve."""
    from cpr_tpu.parallel import default_mesh, sharded_value_iteration

    m = SingleAgent(get_protocol("ghostdag", k=2), alpha=0.3, gamma=0.5,
                    collect_garbage="simple", merge_isomorphic=True,
                    truncate_common_chain=True, dag_size_cutoff=5)
    tm = ptmdp(Compiler(m).mdp(), horizon=20).tensor()
    single = tm.value_iteration(stop_delta=1e-6)
    sharded = sharded_value_iteration(tm, default_mesh(), stop_delta=1e-6)
    np.testing.assert_allclose(
        sharded["vi_value"], single["vi_value"], rtol=1e-6, atol=1e-7)


def test_loop_honest_closes_state_space():
    m = SingleAgent(get_protocol("bitcoin"), alpha=0.3, gamma=0.5,
                    collect_garbage="simple", merge_isomorphic=True,
                    loop_honest=True, truncate_common_chain=False)
    starts = {s for s, _ in m.start()}
    # honest play from each start must stay within a small closed set
    seen = set()
    frontier = list(starts)
    while frontier:
        s = frontier.pop()
        if s in seen:
            continue
        seen.add(s)
        for t in m.apply(m.honest(s), s):
            if t.state not in seen:
                frontier.append(t.state)
        assert len(seen) < 50, "honest loop did not close"
    assert starts <= seen
