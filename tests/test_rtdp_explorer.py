"""RTDP + policy-guided explorer tests.

Mirrors mdp/lib/rtdp_test.py (RTDP on literature Bitcoin models with PTO
horizons, monotone start value) and policy_guided_explorer_test.py
(prefix-compatible truncated MDPs), with the convergence criterion made
explicit: RTDP's start value must approach the exhaustive VI solution.
"""

import numpy as np

from cpr_tpu.mdp import RTDP, Compiler, Explorer, PTOWrapper, ptmdp
from cpr_tpu.mdp.generic import SingleAgent, get_protocol
from cpr_tpu.mdp.models import Fc16BitcoinSM

TERM = "terminal"


def vi_start_value(model_factory, horizon):
    c = Compiler(model_factory())
    tm = ptmdp(c.mdp(), horizon=horizon).tensor()
    vi = tm.value_iteration(stop_delta=1e-7)
    return tm.start_value(vi["vi_value"])


def test_rtdp_converges_to_vi_on_fc16():
    factory = lambda: Fc16BitcoinSM(alpha=0.3, gamma=0.5,  # noqa: E731
                                    maximum_fork_length=6)
    horizon = 20
    ref = vi_start_value(factory, horizon)
    agent = RTDP(PTOWrapper(factory(), horizon=horizon, terminal_state=TERM),
                 eps=0.2, eps_honest=0.2, es=0.2, seed=1)
    agent.run(60_000)
    v, _ = agent.start_value_and_progress()
    assert abs(v - ref) / ref < 0.05, (v, ref)


def test_rtdp_settles_near_vi_and_mdp_roundtrip():
    factory = lambda: Fc16BitcoinSM(alpha=0.35, gamma=0.6,  # noqa: E731
                                    maximum_fork_length=5)
    horizon = 15
    ref = vi_start_value(factory, horizon)
    model = PTOWrapper(factory(), horizon=horizon, terminal_state=TERM)
    agent = RTDP(model, eps=0.3, eps_honest=0.3, seed=3)
    # the shutdown-based init is optimistic guidance: estimates start
    # high and settle toward the exhaustive VI value from above
    for _ in range(10):
        agent.run(2_000)
    v, _ = agent.start_value_and_progress()
    assert abs(v - ref) / ref < 0.05, (v, ref)
    # the extracted partial MDP re-solves close to the agent's estimate
    out = agent.mdp()
    tm = out["mdp"].tensor()
    vi = tm.value_iteration(stop_delta=1e-7)
    assert abs(tm.start_value(vi["vi_value"]) - v) / max(v, 1.0) < 0.05


def test_rtdp_on_generic_dag_model():
    """RTDP drives the generic DAG model without exhaustive compilation
    (the reference pairing: rtdp over generic_v1, measure-rtdp.py)."""
    m = SingleAgent(get_protocol("bitcoin"), alpha=0.33, gamma=0.5,
                    collect_garbage="simple", merge_isomorphic=True,
                    truncate_common_chain=True)
    agent = RTDP(PTOWrapper(m, horizon=12, terminal_state=TERM),
                 eps=0.15, eps_honest=0.25, seed=5)
    agent.run(8_000)
    v, p = agent.start_value_and_progress()
    # honest baseline earns ~alpha per progress; the optimum at these
    # params is near-honest, so the estimate should sit in a sane band
    assert 0.2 <= v / p <= 0.6, (v, p)
    assert agent.n_states > 100


def test_explorer_prefix_compatible():
    m = SingleAgent(get_protocol("bitcoin"), alpha=0.3, gamma=0.2,
                    collect_garbage="simple", merge_isomorphic=True,
                    loop_honest=True, truncate_common_chain=False)
    model = PTOWrapper(m, horizon=10, terminal_state=TERM)
    e = Explorer(model, model.honest)
    e.explore_along_policy(max_states=50_000)
    small = e.mdp()
    n_small = e.n_states
    # the guiding policy is positional action 0 everywhere
    for sid in range(small.n_states):
        if e.policy_actions[sid] >= 0:
            acts = model.actions(e.states[sid])
            assert acts[e.policy_actions[sid]] == model.honest(e.states[sid])
    prefix_before = list(e.states[:n_small])
    e.explore_aside_policy(max_states=200_000)
    big = e.mdp()
    assert big.n_states > n_small
    # prefix compatibility: the first n_small states are the same states,
    # and action 0 still encodes the guiding policy in the bigger MDP
    assert list(e.states[:n_small]) == prefix_before
    src = np.asarray(big.src)
    act = np.asarray(big.act)
    for sid in range(n_small):
        assert ((src == sid) & (act == 0)).any() or \
            e.policy_actions[sid] == -1


def test_explorer_policy_value_grows_with_exploration():
    """Solving the truncated MDPs of growing size yields non-decreasing
    optimal value (more options can only help the attacker)."""
    m = SingleAgent(get_protocol("bitcoin"), alpha=0.35, gamma=0.5,
                    collect_garbage="simple", merge_isomorphic=True,
                    loop_honest=True, truncate_common_chain=False)
    model = PTOWrapper(m, horizon=10, terminal_state=TERM)
    e = Explorer(model, model.honest)
    e.explore_along_policy(max_states=100_000)
    v_policy = _solve(e.mdp())
    e.explore_aside_policy(max_states=400_000)
    v_aside = _solve(e.mdp())
    assert v_aside >= v_policy - 1e-6, (v_policy, v_aside)


def _solve(m):
    tm = m.tensor()
    vi = tm.value_iteration(stop_delta=1e-7)
    return tm.start_value(vi["vi_value"])
