"""Seeded violations for the donate-carry rule (parallel/ is a
registered hot path)."""

import jax


@jax.jit
def step(carry, x):  # finding: decorated carry loop, no donation
    return carry, x


def make(step_fn):
    return jax.jit(step_fn)  # finding: step-like name, no donation


run = jax.jit(lambda state: state)  # finding: lambda carry-ish param
