"""Clean: donated carries, and jits whose first arg is not a carry."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=0)
def step(carry, x):
    return carry, x


@jax.jit
def evaluate(params, batch):  # not a carry pytree
    return params, batch


run = jax.jit(lambda state: state, donate_argnums=0)
named = jax.jit(lambda state: state, donate_argnames="state")
