"""Clean: decorator jits, bind-once-then-call, factory functions."""

import jax


@jax.jit
def step(x):
    return x + 1


def make_runner(fn):
    # a factory constructs the jit once and returns it; callers reuse
    # the same cache
    runner = jax.jit(fn)

    def run(xs):
        return [runner(x) for x in xs]

    return run


def sweep(fn, batches):
    jitted = jax.jit(fn)
    return [jitted(b) for b in batches]
