"""Clean counterparts: perf_counter intervals, tz-aware stamps."""

import time
from datetime import datetime, timezone


def measure(fn):
    t0 = time.perf_counter()
    fn()
    stamp = datetime.now(timezone.utc)
    return time.perf_counter() - t0, stamp
