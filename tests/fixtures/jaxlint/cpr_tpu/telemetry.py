"""Minimal typed-event schema; the event-schema rule resolves this
EVENT_FIELDS literal cross-module by AST (the file is never imported)."""

EVENT_FIELDS = {
    "compile": ("fn", "compile_s"),
    "retry": ("attempt", "delay_s", "error"),
    "request": ("trace_id", "op", "status", "total_s"),
    "admission": ("reason", "op", "priority", "tenant",
                  "retry_after_s"),
    "route": ("action", "replica", "op"),
    "attack_sweep": ("protocol", "topology", "lanes", "policies",
                     "drops"),
    "mdp_compile": ("protocol", "cutoff", "rounds", "states",
                    "transitions", "n_workers"),
    "alert": ("signal", "severity", "window_s", "value", "budget",
              "burn_rate"),
    "perf_gate": ("metric", "backend", "verdict", "value", "baseline",
                  "run", "baseline_runs"),
    "memory": ("scope", "peak_bytes", "source"),
    "integrity": ("artifact", "artifact_kind", "reason",
                      "action"),
    "learn": ("role", "steps", "batches", "fingerprint",
              "staleness_s"),
}
