"""Seeded violations for the jit-in-loop rule."""

import jax


def sweep(fns, xs):
    out = []
    for fn in fns:
        jitted = jax.jit(fn)  # finding: fresh cache every iteration
        out.append(jitted(xs))
    y = jax.jit(lambda v: v + 1)(xs)  # finding: jit-and-call
    return out, y
