"""Clean: typed events with every declared field; untyped names and
dynamic payloads are out of the rule's scope."""


def report(tele, fn_name, dt, err, extra, tid):
    tele.event("compile", fn=fn_name, compile_s=dt)
    tele.event("compile", fn=fn_name, compile_s=dt, cached=True)
    tele.event("custom_untyped", whatever=1)
    tele.event("compile", **extra)  # dynamic kwargs: not checkable
    tele.emit({"kind": "event", "name": "retry", "attempt": 1,
               "delay_s": 0.5, "error": err})
    tele.event("request", trace_id=tid, op="episode.run", status="ok",
               total_s=dt, role="client")  # extras ride free-form
    tele.event("admission", reason="slo_breach", op="episode.run",
               priority=1, tenant=None, retry_after_s=dt)
    tele.emit({"kind": "event", "name": "route", "action": "route",
               "replica": 0, "op": "episode.run", "seed": 7})
    tele.event("attack_sweep", protocol="nakamoto",
               topology="two-agents", lanes=54, policies=3, drops=0,
               lanes_per_sec=dt)  # extras ride free-form
    tele.event("mdp_compile", protocol="fc16", cutoff=8, rounds=17,
               states=1024, transitions=6144, n_workers=4,
               compile_s=dt, states_per_sec=dt)  # extras ride free-form
    tele.event("alert", signal="p99_over_slo", severity="ticket",
               window_s=60.0, value=dt, budget=0.5, burn_rate=dt,
               cls="batch", threshold=1.0)  # extras ride free-form
    tele.event("perf_gate", metric="serve_p99_s", backend="cpu",
               verdict="fail", value=dt, baseline=None,
               run=tid, baseline_runs=[],
               reason="x")  # extras ride free-form
    tele.event("memory", scope="serve", peak_bytes=1 << 28,
               source="rss", in_use_bytes=1 << 27,
               n_samples=12)  # extras ride free-form
    tele.event("integrity", artifact="/tmp/ckpt.npz",
               artifact_kind="vi_checkpoint", reason="checksum",
               action="quarantined",
               quarantine="/tmp/q")  # extras ride free-form
    tele.event("learn", role="sample", steps=4096, batches=1,
               fingerprint=tid, staleness_s=dt,
               lanes=16, partial=0)  # extras ride free-form
