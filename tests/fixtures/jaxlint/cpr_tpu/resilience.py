"""The one module allowed raw open(..., 'w'): it IS the atomic-write
implementation (tmp + fsync + os.replace), so the rule exempts it."""

import os


def atomic_write_text(path, text):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
