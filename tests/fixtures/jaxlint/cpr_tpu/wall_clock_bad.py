"""Seeded violations for the wall-clock rule."""

import time
from datetime import datetime


def measure(fn):
    t0 = time.time()  # finding: wall-clock interval bracket
    fn()
    stamp = datetime.now()  # finding: naive wall-clock stamp
    return time.time() - t0, stamp
