"""Seeded violations for the host-sync rule."""

import numpy as np
from jax import lax


def scan_mean(xs):
    def body(carry, x):
        total = carry + float(x)  # finding: float() on a traced value
        host = np.asarray(x)  # finding: host transfer in a traced body
        del host
        return total, x.item()  # finding: .item() syncs per step

    return lax.scan(body, 0.0, xs)


def wait(x):
    # finding: bool() in the while_loop cond
    return lax.while_loop(lambda s: bool(s < 4), lambda s: s + 1, x)
