"""Clean: split-rebind, per-iteration fold_in, indexed sub-keys."""

import jax


def sample(n):
    key = jax.random.PRNGKey(0)
    # the split-rebind idiom consumes and replaces the key in one step
    key, k1, k2 = jax.random.split(key, 3)
    a = jax.random.normal(k1, (n,))
    b = jax.random.uniform(k2, (n,))
    c = jax.random.normal(key, (n,))  # the rebound key is fresh
    return a, b, c


def rollout(steps, n):
    key = jax.random.PRNGKey(1)
    out = []
    for i in range(steps):
        step_key = jax.random.fold_in(key, i)  # derivation, not reuse
        out.append(jax.random.normal(step_key, (n,)))
    return out


def batched(n):
    keys = jax.random.split(jax.random.PRNGKey(2), n)
    a = jax.random.normal(keys[0])
    b = jax.random.normal(keys[1])  # indexed sub-keys are distinct
    return a, b
