"""Seeded violations for the event-schema rule (schema: telemetry.py
in this fixture tree declares compile and retry as typed events)."""


def report(tele, fn_name, tid):
    tele.event("compile", fn=fn_name)  # finding: missing compile_s
    # finding: missing delay_s, error
    tele.emit({"kind": "event", "name": "retry", "attempt": 1})
    # finding: missing total_s (the v8 request-latency contract)
    tele.event("request", trace_id=tid, op="episode.run", status="ok")
    # finding: missing priority, tenant, retry_after_s (v9 admission)
    tele.event("admission", reason="queue_full", op="episode.run")
    # finding: missing op (v9 route)
    tele.emit({"kind": "event", "name": "route", "action": "requeue",
               "replica": 1})
    # finding: missing policies, drops (v11 attack_sweep)
    tele.event("attack_sweep", protocol="nakamoto",
               topology="two-agents", lanes=54)
    # finding: missing states, transitions, n_workers (v12 mdp_compile)
    tele.event("mdp_compile", protocol="fc16", cutoff=8, rounds=17)
    # finding: missing burn_rate (v14 alert — an alert without its
    # burn rate is unjudgeable)
    tele.event("alert", signal="shed_rate", severity="page",
               window_s=30.0, value=0.4, budget=0.02)
    # finding: missing run, baseline_runs (v15 perf_gate — a verdict
    # without provenance cannot be chased through the run archive)
    tele.event("perf_gate", metric="serve_p99_s", backend="cpu",
               verdict="fail", value=0.8, baseline=None)
    # finding: missing source (v15 memory — a watermark is only
    # comparable when it says what was sampled: device stats or rss)
    tele.event("memory", scope="serve", peak_bytes=1 << 28)
    # finding: missing reason, action (v16 integrity — a corruption
    # report that doesn't say WHY the bytes were rejected or WHAT the
    # consumer did about it is unactionable)
    tele.event("integrity", artifact="/tmp/ckpt.npz",
               artifact_kind="vi_checkpoint")
    # finding: missing fingerprint, staleness_s (v17 learn — a swap
    # that doesn't say WHICH snapshot is serving or how stale the
    # previous one got breaks the whole correlation chain)
    tele.event("learn", role="swap", steps=None, batches=None)
