"""Seeded violations for the key-reuse rule."""

import jax


def sample(n):
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n,))
    b = jax.random.uniform(key, (n,))  # finding: identical stream replays
    return a, b


def rollout(steps, n):
    key = jax.random.PRNGKey(1)
    out = []
    for _ in range(steps):
        # finding: key bound outside the loop, consumed every iteration
        out.append(jax.random.normal(key, (n,)))
    return out
