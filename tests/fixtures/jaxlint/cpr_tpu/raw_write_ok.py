"""Clean: reads, append streams, atomic helpers, and the escape hatch."""

from cpr_tpu.resilience import atomic_write_json, atomic_write_text


def sink(path, line, obj):
    with open(path) as f:  # read
        f.read()
    with open(path, "a") as f:  # append never truncates
        f.write(line)
    atomic_write_text(path, line)
    atomic_write_json(path + ".json", obj)
    # a deliberate raw write carries a reasoned inline disable
    # jaxlint: disable-next-line=raw-write
    with open(path + ".scratch", "w") as f:
        f.write(line)
    with open(path + ".scratch2", "w") as f:  # jaxlint: disable=raw-write
        f.write(line)
