"""Seeded violations for the raw-write rule."""

import io
import json


def dump(path, obj, blob):
    with open(path, "w") as f:  # finding: truncating write
        json.dump(obj, f)
    with io.open(path, mode="wb") as f:  # finding: mode= keyword
        f.write(blob)
    with open(path, "x") as f:  # finding: exclusive create
        f.write("")
