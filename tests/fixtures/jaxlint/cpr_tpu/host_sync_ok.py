"""Clean: traced bodies stay on device; host syncs happen after."""

import numpy as np
from jax import lax


def scan_mean(xs):
    def body(carry, x):
        return carry + x, x

    total, ys = lax.scan(body, 0.0, xs)
    # syncing AFTER the loop is the sanctioned pattern
    return float(total), np.asarray(ys)


def wait(x):
    return lax.while_loop(lambda s: s < 4, lambda s: s + 1, x)
