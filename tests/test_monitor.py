"""cpr_tpu.monitor: the fleet health plane (schema v14).

Units: the live `MetricsRegistry` (counter/gauge semantics, Prometheus
text 0.0.4 grammar, the empty-histogram and `__overflow__` cardinality
edges, callable-board indirection), the `--metrics-port` HTTP endpoint,
the multi-window SLO burn-rate `AlertEngine` (fake clock: fire,
cooldown, recovery, the None-never-reaches-burn-math contract), and the
crash flight recorder (ring capacity, dump format, never-raises).

Integration (satellite d): the dump triggers are proven through the
REAL machinery — a `kill@replica=0` fault injected into a live serve
subprocess, and a SIGTERM preemption drain — each leaving a
schema-valid blackbox artifact that `trace_summary --validate` accepts
standalone.
"""

import importlib.util
import json
import os
import re
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from cpr_tpu import resilience, telemetry
from cpr_tpu.latency import OVERFLOW_FAMILY, LatencyBoard, LatencyHistogram
from cpr_tpu.monitor.alerts import (DEFAULT_SHED_BUDGET, PAGE_BURN,
                                    TICKET_BURN, AlertEngine, burn_rate,
                                    default_windows, emit_alert)
from cpr_tpu.monitor.blackbox import blackbox_path, dump_blackbox
from cpr_tpu.monitor.expo import MetricsServer
from cpr_tpu.monitor.registry import (PROMETHEUS_CONTENT_TYPE,
                                      MetricsRegistry)
from cpr_tpu.serve import protocol as wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every Prometheus text-format sample line: name{labels} value
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})? '
    r'(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|\+Inf|-Inf|NaN)$')


def _assert_prometheus_grammar(text: str):
    """Line-by-line grammar check shared with the fleet smoke: every
    line is a comment or a well-formed sample, and no Python `None`
    ever leaks into the exposition."""
    assert "None" not in text
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"


# -- MetricsRegistry ---------------------------------------------------------


def test_counter_gauge_semantics_and_kind_conflict():
    reg = MetricsRegistry(namespace="t")
    reg.inc("requests_total", op="run")
    reg.inc("requests_total", 2.0, op="run")
    reg.inc("requests_total", op="stats")
    reg.set("queued", 7)
    reg.set("queued", 3)  # gauges overwrite, counters accumulate
    j = reg.to_json()
    by_labels = {tuple(sorted(s["labels"].items())): s["value"]
                 for s in j["counters"]["requests_total"]}
    assert by_labels[(("op", "run"),)] == 3.0
    assert by_labels[(("op", "stats"),)] == 1.0
    assert j["gauges"]["queued"][0]["value"] == 3.0
    # a name is one kind forever: the conflict is an error, not a
    # silent second family
    with pytest.raises(ValueError, match="is a counter"):
        reg.set("requests_total", 1.0)
    with pytest.raises(ValueError, match="max_series"):
        MetricsRegistry(max_series=0)


def test_gauge_set_none_unsets_the_series():
    """`set(None)` is the explicit no-data path: the series disappears
    from both expositions instead of rendering a bogus value (how an
    empty histogram's None quantile stays out of the text format)."""
    reg = MetricsRegistry(namespace="t")
    reg.set("p99_s", 0.25, cls="interactive")
    assert "p99_s" in reg.render_prometheus()
    reg.set("p99_s", None, cls="interactive")
    out = reg.render_prometheus()
    # the family's HELP/TYPE comments may remain; no SAMPLE does
    assert not [ln for ln in out.splitlines()
                if ln.startswith("t_p99_s")]
    assert reg.to_json()["gauges"]["p99_s"] == []
    _assert_prometheus_grammar(out)


def test_prometheus_text_grammar_and_label_escaping():
    reg = MetricsRegistry(namespace="cpr_serve",
                          const_labels={"replica": "0"})
    reg.inc("sheds_total", reason='queue_full "x"\nnasty\\path',
            tenant="t-1")
    reg.set("occupancy", 0.5)
    board = LatencyBoard()
    for d in (0.001, 0.01, 0.01, 0.1):
        board.observe("episode.run", d)
    reg.attach_board("latency_seconds", board,
                     help="request latency")
    out = reg.render_prometheus()
    _assert_prometheus_grammar(out)
    # const labels ride every series; escapes round the funny chars
    assert 'replica="0"' in out
    assert r'reason="queue_full \"x\"\nnasty\\path"' in out
    # one HELP/TYPE pair per family, histogram declared as such
    assert out.count("# TYPE cpr_serve_latency_seconds histogram") == 1
    assert "# TYPE cpr_serve_sheds_total counter" in out
    assert "# TYPE cpr_serve_occupancy gauge" in out


def test_histogram_buckets_are_cumulative_and_sum_to_count():
    board = LatencyBoard()
    durs = [0.001, 0.003, 0.01, 0.02, 0.5]
    for d in durs:
        board.observe("episode.run", d)
    reg = MetricsRegistry(namespace="t")
    reg.attach_board("lat", board)
    out = reg.render_prometheus()
    _assert_prometheus_grammar(out)
    buckets = []
    for line in out.splitlines():
        if line.startswith("t_lat_bucket"):
            le = re.search(r'le="([^"]+)"', line).group(1)
            buckets.append((le, int(line.rsplit(" ", 1)[1])))
    # cumulative and non-decreasing, closed by le="+Inf" == _count
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)
    assert buckets[-1][0] == "+Inf" and buckets[-1][1] == len(durs)
    (count_line,) = [ln for ln in out.splitlines()
                     if ln.startswith("t_lat_count")]
    assert int(count_line.rsplit(" ", 1)[1]) == len(durs)
    (sum_line,) = [ln for ln in out.splitlines()
                   if ln.startswith("t_lat_sum")]
    assert float(sum_line.rsplit(" ", 1)[1]) == pytest.approx(sum(durs))
    # every finite le is a real edge, parseable as a float
    for le, _ in buckets[:-1]:
        assert float(le) > 0


def test_empty_histogram_renders_all_zero_never_none():
    """The v14 empty-histogram edge: a family that exists but has seen
    nothing (a replica that merged in an idle peer) renders explicit
    zeros — all buckets 0, `+Inf` 0, `_sum 0`, `_count 0` — and no
    `None` anywhere in the text."""
    board = LatencyBoard()
    board.merge_dict({"idle": LatencyHistogram().to_dict()})
    assert board.get("idle").count == 0
    reg = MetricsRegistry(namespace="t")
    reg.attach_board("lat", board)
    out = reg.render_prometheus()
    _assert_prometheus_grammar(out)
    samples = [ln for ln in out.splitlines()
               if not ln.startswith("#")]
    assert samples, "an empty family still renders"
    assert all(ln.rsplit(" ", 1)[1] == "0" for ln in samples)
    # the structured path is honest the same way: no fake quantiles
    j = reg.to_json()
    assert j["histograms"]["lat"]["idle"] == {"count": 0}
    assert j["histograms_raw"]["lat"]["idle"]["count"] == 0


def test_series_cardinality_folds_into_overflow_label():
    """Past max_series, novel label combinations fold into one series
    whose every label value is the explicit `__overflow__` marker —
    visible in the exposition, never dropped (the registry twin of the
    LatencyBoard family bound)."""
    reg = MetricsRegistry(namespace="t", max_series=2)
    reg.inc("requests_total", op="a")
    reg.inc("requests_total", op="b")
    reg.inc("requests_total", op="c")
    reg.inc("requests_total", op="d")
    reg.inc("requests_total", op="a")  # existing series still lands home
    j = reg.to_json()
    series = {s["labels"]["op"]: s["value"]
              for s in j["counters"]["requests_total"]}
    assert series == {"a": 2.0, "b": 1.0, OVERFLOW_FAMILY: 2.0}
    out = reg.render_prometheus()
    _assert_prometheus_grammar(out)
    assert f'op="{OVERFLOW_FAMILY}"' in out


def test_attach_board_accepts_callable_and_rejects_junk():
    """The router REPLACES its fleet board wholesale on every refresh,
    so `attach_board` takes a zero-arg callable resolved at scrape
    time: the render always sees the current board, not a stale
    reference."""
    reg = MetricsRegistry(namespace="t")
    holder = {"board": LatencyBoard()}
    holder["board"].observe("episode.run", 0.01)
    reg.attach_board("fleet", lambda: holder["board"])
    assert "t_fleet_count" in reg.render_prometheus()
    (line,) = [ln for ln in reg.render_prometheus().splitlines()
               if ln.startswith("t_fleet_count")]
    assert line.endswith(" 1")
    # wholesale replacement (a fresh merge) is visible immediately
    fresh = LatencyBoard()
    fresh.merge_dict(holder["board"].to_dict())
    fresh.merge_dict(holder["board"].to_dict())
    holder["board"] = fresh
    (line,) = [ln for ln in reg.render_prometheus().splitlines()
               if ln.startswith("t_fleet_count")]
    assert line.endswith(" 2")
    assert reg.to_json()["histograms_raw"]["fleet"]["episode.run"][
        "count"] == 2
    with pytest.raises(TypeError, match="LatencyBoard"):
        reg.attach_board("junk", {"not": "a board"})
    with pytest.raises(ValueError, match="already registered"):
        reg.inc("dup")
        reg.attach_board("dup", LatencyBoard())


def test_to_json_raw_form_is_mergeable():
    """`histograms_raw` is the fleet-merge input: a downstream board
    must be able to `merge_dict` it exactly."""
    board = LatencyBoard()
    for d in (0.01, 0.02, 0.04):
        board.observe("episode.run", d)
    reg = MetricsRegistry(namespace="t")
    reg.attach_board("lat", board)
    downstream = LatencyBoard()
    downstream.merge_dict(reg.to_json()["histograms_raw"]["lat"])
    assert downstream.get("episode.run").count == 3
    assert downstream.get("episode.run").sum_s == pytest.approx(0.07)


# -- MetricsServer (the --metrics-port HTTP endpoint) ------------------------


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.headers.get("Content-Type"), \
                r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, None, ""


def test_metrics_server_serves_text_format_and_404s():
    reg = MetricsRegistry(namespace="t")
    reg.inc("requests_total", op="run")
    srv = MetricsServer(reg.render_prometheus, port=0)
    port = srv.start()
    try:
        assert port > 0
        for path in ("/", "/metrics", "/metrics?x=1"):
            status, ctype, body = _get(port, path)
            assert status == 200
            assert ctype == PROMETHEUS_CONTENT_TYPE
            _assert_prometheus_grammar(body)
            assert "t_requests_total" in body
        # the scrape is live: later increments show on the next GET
        reg.inc("requests_total", op="run")
        _, _, body = _get(port, "/metrics")
        assert 't_requests_total{op="run"} 2' in body
        assert _get(port, "/nope")[0] == 404
    finally:
        srv.stop()
    with pytest.raises(OSError):  # stopped means the port is released
        socket.create_connection(("127.0.0.1", port), timeout=0.5)


def test_metrics_server_500s_on_broken_render():
    srv = MetricsServer(lambda: 1 / 0, port=0)
    port = srv.start()
    try:
        assert _get(port, "/metrics")[0] == 500
    finally:
        srv.stop()


# -- AlertEngine -------------------------------------------------------------


def test_default_windows_scale_from_slo_with_floors_and_caps():
    assert default_windows(1.0) == ((10.0, "page", PAGE_BURN),
                                    (60.0, "ticket", TICKET_BURN))
    # tiny SLOs floor (5 s / 30 s), huge ones cap (5 min / 1 h)
    assert default_windows(0.01) == ((5.0, "page", PAGE_BURN),
                                     (30.0, "ticket", TICKET_BURN))
    assert default_windows(1000.0) == ((300.0, "page", PAGE_BURN),
                                       (3600.0, "ticket", TICKET_BURN))


def test_burn_rate_never_sees_missing_data():
    assert burn_rate(0.04, 0.02) == pytest.approx(2.0)
    assert burn_rate(None, 0.02) is None
    assert burn_rate(0.04, None) is None
    assert burn_rate(0.04, 0.0) is None
    assert burn_rate(0.04, -1.0) is None


def _engine(**kw):
    """An engine on a fake clock with one tight page window."""
    clock = [0.0]
    kw.setdefault("windows", ((5.0, "page", PAGE_BURN),))
    kw.setdefault("min_samples", 4)
    eng = AlertEngine(slo_s=kw.pop("slo_s", 0.5),
                      now_fn=lambda: clock[0], **kw)
    return eng, clock


def test_shed_rate_alert_fires_cools_down_and_recovers():
    eng, clock = _engine()
    for _ in range(8):
        eng.record_admission(shed=True)
    (alert,) = eng.evaluate()
    assert alert["signal"] == "shed_rate" and alert["cls"] is None
    assert alert["severity"] == "page" and alert["window_s"] == 5.0
    assert alert["value"] == pytest.approx(1.0)
    assert alert["budget"] == pytest.approx(DEFAULT_SHED_BUDGET)
    assert alert["burn_rate"] == pytest.approx(1.0 / 0.02)
    assert eng.summary() == {"active": [alert], "fired": 1}
    # the breach persists but the cooldown gates the re-emit ...
    clock[0] = 2.0
    assert eng.evaluate() == []
    assert eng.summary()["active"] == [alert] and eng.n_fired == 1
    # ... until one full window has passed
    clock[0] = 5.0
    for _ in range(4):
        eng.record_admission(shed=True)
    assert len(eng.evaluate()) == 1 and eng.n_fired == 2
    # recovery: the shed fraction dropping under budget clears active
    clock[0] = 9.9
    for _ in range(200):
        eng.record_admission(shed=False)
    assert eng.evaluate() == []
    assert eng.summary() == {"active": [], "fired": 2}


def test_p99_over_slo_alert_is_per_class_and_sample_gated():
    eng, clock = _engine(class_slo={"interactive": 0.1, "batch": 2.0})
    eng.record_latency("interactive", None)  # dropped at the door
    for _ in range(3):
        eng.record_latency("interactive", 5.0)
    assert eng.evaluate() == []  # under min_samples: skipped, not None
    for _ in range(5):
        eng.record_latency("interactive", 5.0)
        eng.record_latency("batch", 0.01)  # well inside its budget
    (alert,) = eng.evaluate()
    assert alert["signal"] == "p99_over_slo"
    assert alert["cls"] == "interactive"
    assert alert["value"] == pytest.approx(5.0)
    assert alert["budget"] == pytest.approx(0.1)
    assert alert["burn_rate"] == pytest.approx(50.0)
    # old samples age out of the window: the signal goes quiet
    clock[0] = 100.0
    for _ in range(8):
        eng.record_latency("batch", 0.01)
    assert eng.evaluate() == []


def test_budgetless_class_is_skipped_not_nonsense():
    """slo_s=None and no class budget: the p99 signal cannot be judged
    and is skipped outright — None never reaches burn-rate math."""
    eng, _ = _engine(slo_s=None)
    for _ in range(16):
        eng.record_latency("interactive", 99.0)
        eng.record_admission(shed=False)
    assert eng.evaluate() == []
    assert eng.summary() == {"active": [], "fired": 0}


def test_emit_alert_is_v14_schema_complete(tmp_path):
    path = tmp_path / "alert.jsonl"
    telemetry.configure(str(path))
    try:
        emit_alert({"signal": "shed_rate", "severity": "page",
                    "window_s": 5.0, "value": 0.4, "budget": 0.02,
                    "burn_rate": 20.0, "cls": None, "threshold": 4.0,
                    "slo_s": 0.5})
    finally:
        telemetry.configure(None)
    (ev,) = [json.loads(ln) for ln in open(path)]
    assert ev["name"] == "alert"
    missing = [k for k in telemetry.EVENT_FIELDS["alert"]
               if k not in ev]
    assert not missing


# -- flight recorder (ring + dump) -------------------------------------------


def test_blackbox_ring_capacity_env_and_oldest_first(monkeypatch):
    monkeypatch.setenv(telemetry.BLACKBOX_ENV_VAR, "16")
    monkeypatch.setattr(telemetry, "_blackbox", None)  # fresh ring
    assert telemetry.blackbox_capacity() == 16
    tele = telemetry.Telemetry()  # sinkless: the ring still records
    for i in range(40):
        tele.event("tick", i=i)
    events = telemetry.blackbox_events()
    assert len(events) == 16
    assert [e["i"] for e in events] == list(range(24, 40))
    # a junk capacity falls back to the default instead of crashing
    monkeypatch.setenv(telemetry.BLACKBOX_ENV_VAR, "banana")
    assert telemetry.blackbox_capacity() == \
        telemetry.BLACKBOX_DEFAULT_EVENTS


def test_dump_blackbox_writes_validating_artifact(tmp_path,
                                                  monkeypatch):
    monkeypatch.setattr(telemetry, "_blackbox", None)
    tele = telemetry.Telemetry()
    tele.event("marker", n=1)
    tele.event("marker", n=2)
    path = dump_blackbox("test:unit", dest_dir=str(tmp_path))
    assert path == blackbox_path(str(tmp_path))
    name = os.path.basename(path)
    assert re.fullmatch(
        rf"blackbox-{telemetry.run_id()}-{os.getpid()}\.jsonl", name)
    # atomic publish: the final name only, no orphaned tmp sibling
    assert [p.name for p in tmp_path.iterdir()] == [name]
    lines = [json.loads(ln) for ln in open(path)]
    man, events = lines[0], lines[1:]
    assert man["kind"] == "manifest" and man["backend"]
    assert man["config"]["entry"] == "blackbox"
    assert man["config"]["reason"] == "test:unit"
    assert man["config"]["n_events"] == len(events) == 2
    assert man["config"]["capacity"] == telemetry.blackbox_capacity()
    assert [e["n"] for e in events] == [1, 2]  # oldest-first
    # the dump is a standalone trace: the validator accepts it
    ts = _load_trace_summary()
    read, bad = ts.read_events(path)
    assert ts.validate(read, bad) == []


def test_emit_and_blackbox_survive_concurrent_emitters(tmp_path,
                                                       monkeypatch):
    """v15 regression: the serve tick loop, the heartbeat thread, and
    the metrics HTTP threads all emit into one sink while dump_blackbox
    may fire from a crash path.  Every JSONL line must stay intact (no
    interleaved partial writes) and the ring copy must never blow up
    mid-append (`RuntimeError: deque mutated during iteration`)."""
    import threading

    monkeypatch.setenv(telemetry.BLACKBOX_ENV_VAR, "64")
    monkeypatch.setattr(telemetry, "_blackbox", None)
    sink = tmp_path / "concurrent.jsonl"
    tele = telemetry.Telemetry(str(sink))
    n_threads, n_events = 8, 200
    errors = []

    def emitter(tid):
        try:
            for i in range(n_events):
                tele.event("tick", thread=tid, i=i)
        except Exception as e:  # pragma: no cover — the regression
            errors.append(e)

    def dumper():
        try:
            for _ in range(50):
                telemetry.blackbox_events()
                dump_blackbox("test:concurrent",
                              dest_dir=str(tmp_path / "bb"))
        except Exception as e:  # pragma: no cover — the regression
            errors.append(e)

    threads = [threading.Thread(target=emitter, args=(t,))
               for t in range(n_threads)] + \
        [threading.Thread(target=dumper)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60.0)
    tele.close()
    assert not errors
    lines = sink.read_text().splitlines()
    assert len(lines) == n_threads * n_events
    parsed = [json.loads(ln) for ln in lines]  # no torn writes
    # nothing lost: every (thread, i) pair landed exactly once
    seen = {(e["thread"], e["i"]) for e in parsed}
    assert len(seen) == n_threads * n_events
    assert tele.n_emitted == n_threads * n_events
    # the ring holds the last `capacity` events, all well-formed
    ring = telemetry.blackbox_events()
    assert len(ring) == 64
    assert all(e["name"] == "tick" for e in ring)


def test_dump_blackbox_never_raises(monkeypatch):
    def boom(*a, **kw):
        raise OSError("disk is gone")

    monkeypatch.setattr(resilience, "atomic_write_text", boom)
    assert dump_blackbox("test:broken-disk") is None


# -- crash-path integration (satellite d): the real triggers -----------------


def _load_trace_summary():
    path = os.path.join(REPO, "tools", "trace_summary.py")
    spec = importlib.util.spec_from_file_location("trace_summary", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _spawn_serve_child(tmp_path, extra_env=None, extra_args=()):
    """One real serve subprocess on tiny geometry, blackbox directed
    at tmp_path, telemetry to a sibling stream.  Returns (proc, ready
    dict) once the ready file lands."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["CPR_BLACKBOX_DIR"] = str(tmp_path)
    env[telemetry.TELEMETRY_ENV_VAR] = str(tmp_path / "serve.jsonl")
    env.update(extra_env or {})
    ready = tmp_path / "ready.json"
    cmd = [sys.executable, "-m", "cpr_tpu.serve.server",
           "--port", "0", "--ready-file", str(ready),
           "--lanes", "2", "--burst", "4", "--max-steps", "16",
           "--heartbeat-s", "0.2", *extra_args]
    proc = subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    deadline = time.time() + 180.0
    while time.time() < deadline:
        if proc.poll() is not None:
            out, err = proc.communicate()
            raise AssertionError(
                f"server died before ready (rc={proc.returncode})\n"
                f"{out}\n{err}")
        try:
            info = json.loads(ready.read_text())
            return proc, info
        except (OSError, ValueError):
            time.sleep(0.05)
    proc.kill()
    raise AssertionError("server not ready within 180s")


def _blackbox_dumps(tmp_path):
    return sorted(tmp_path.glob("blackbox-*.jsonl"))


def _read_dump(path):
    lines = [json.loads(ln) for ln in open(path)]
    return lines[0], lines[1:]


def test_injected_kill_at_replica_dumps_blackbox(tmp_path):
    """kill@replica=0 through the real injector: the InjectedKill
    unwinds the serve main like the crash it stands in for, and the
    main wrapper's dump trigger leaves a schema-valid blackbox whose
    ring recorded the injected fault itself."""
    proc, info = _spawn_serve_child(
        tmp_path,
        extra_env={resilience.FAULT_ENV_VAR: "kill@replica=0",
                   telemetry.BLACKBOX_ENV_VAR: "64"},
        extra_args=("--replica-index", "0"))
    # one fire-and-forget episode keeps the tick loop bursting; the
    # fault fires after the first completed burst, so the reply may
    # never come back — send raw and only wait on the process
    with socket.create_connection(("127.0.0.1", info["port"]),
                                  timeout=10) as s:
        s.sendall(wire.pack_frame(
            dict(op="episode.run", policy="honest", seed=0)))
        rc = proc.wait(timeout=180)
    out, err = proc.communicate()
    assert rc != 0, f"injected kill must not exit clean\n{out}\n{err}"
    (dump,) = _blackbox_dumps(tmp_path)
    man, events = _read_dump(dump)
    assert man["config"]["reason"] == "serve:InjectedKill"
    assert man["config"]["pid"] == info["pid"]
    assert len(events) <= 64  # capped at the ring bound
    # the flight recorder caught the fault marker on its way down
    faults = [e for e in events if e.get("name") == "fault_injected"]
    assert faults and faults[0]["site"] == "replica"
    ts = _load_trace_summary()
    read, bad = ts.read_events(str(dump))
    assert ts.validate(read, bad) == []


def test_sigterm_preemption_drains_and_dumps_blackbox(tmp_path):
    """The preemption path: SIGTERM lands in the preemption guard, the
    serve loop drains gracefully (exit 0), and the post-drain trigger
    still dumps the blackbox — a preempted replica leaves the same
    artifact a crashed one does."""
    proc, info = _spawn_serve_child(tmp_path)
    os.kill(proc.pid, signal.SIGTERM)
    rc = proc.wait(timeout=180)
    out, err = proc.communicate()
    assert rc == 0, f"preemption drain must exit clean\n{out}\n{err}"
    (dump,) = _blackbox_dumps(tmp_path)
    man, events = _read_dump(dump)
    assert man["config"]["reason"].startswith("serve:preempt:")
    assert events, "the drain's own events are in the ring"
    ts = _load_trace_summary()
    read, bad = ts.read_events(str(dump))
    assert ts.validate(read, bad) == []
