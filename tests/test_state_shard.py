"""State-sharded Bellman backups + in-graph RTDP (PR 16).

The acceptance contract of cpr_tpu/parallel/state_shard.py and
cpr_tpu/mdp/rtdp_graph.py on the 8-virtual-CPU-device mesh:

* sharded VI fixpoints bit-identical to the single-device
  `impl="chunked"` solve on fc16@6, aft20@6, and a generic ghostdag
  compile, at 1 vs 4 devices, including through kill@vi_chunk+resume;
* uneven state blocks refused by name from every entry point;
* grid x state 2-D mesh composition parity with the 1-D grid solve;
* the CPR_VI_BYTES working-set guard: a ceiling the single-device
  path refuses under is enough for the 4-shard path to complete;
* in-graph RTDP: seeded bit-reproducibility, convergence to the exact
  start value, damped-residual early exit, and the sharded-VI polish
  handoff reaching the exact fixpoint in fewer sweeps.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cpr_tpu import telemetry  # noqa: E402
from cpr_tpu.mdp.explicit import (MDP, ViWorkingSetTooLarge, ptmdp,  # noqa: E402
                                  vi_working_set_bytes)
from cpr_tpu.mdp.grid import (compile_protocol, grid_value_iteration,  # noqa: E402
                              param_ptmdp)
from cpr_tpu.mdp.rtdp_graph import rtdp_graph, rtdp_sharded_polish  # noqa: E402
from cpr_tpu.parallel import (default_mesh,  # noqa: E402
                              make_grid_state_chunk_step,
                              partition_by_state_block,
                              sharded_state_value_iteration,
                              state_halo_bytes)
from cpr_tpu.resilience import FAULT_ENV_VAR, InjectedKill  # noqa: E402

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8,
    reason="needs the 8-virtual-device CPU mesh (conftest XLA_FLAGS)")

ALPHA, GAMMA = 0.35, 0.5


def _mesh(n):
    return default_mesh(devices=jax.devices()[:n])


def _materialize(pm, alpha=ALPHA, gamma=GAMMA, dtype=jnp.float32):
    """One grid point of a ParamMDP as a plain TensorMDP."""
    m = pm.mdp
    sv = pm._monomial(pm.start_coef, pm.start_expo, alpha, gamma)
    m2 = MDP(n_states=m.n_states, n_actions=m.n_actions,
             start={int(s): float(v)
                    for s, v in zip(pm.start_ids, sv)},
             src=m.src, act=m.act, dst=m.dst,
             prob=pm.revalue(alpha, gamma),
             reward=m.reward, progress=m.progress)
    return m2.tensor(dtype)


@pytest.fixture(scope="module")
def fc16_pm():
    return param_ptmdp(compile_protocol("fc16", cutoff=6), horizon=20)


@pytest.fixture(scope="module")
def aft20_pm():
    return param_ptmdp(compile_protocol("aft20", cutoff=6), horizon=20)


@pytest.fixture(scope="module")
def fc16_tm(fc16_pm):
    return _materialize(fc16_pm)


@pytest.fixture(scope="module")
def ghostdag_tm():
    from cpr_tpu.mdp.generic.native import compile_native

    table = compile_native("ghostdag", k=2, alpha=ALPHA, gamma=GAMMA,
                           collect_garbage="simple", dag_size_cutoff=5)
    return ptmdp(table, horizon=10).tensor(jnp.float32)


# -- partition contract ------------------------------------------------------


def test_partition_round_trips_and_pads_inert(fc16_tm):
    """Every original transition lands in its source block with src
    localized; pad rows carry src_local == s_blk (out-of-range segment
    id — dropped by the scatter-add) and probability 0."""
    n = 4
    S = fc16_tm.n_states
    S_pad = S + (-S % n)
    (src_l, act, dst, prob, reward, progress), slot, t_blk = \
        partition_by_state_block(fc16_tm, n, S_pad)
    s_blk = S_pad // n
    src = np.asarray(fc16_tm.src)
    blk = src // s_blk
    assert np.array_equal(src_l[slot] + blk * s_blk, src)
    for col, ref in ((act, fc16_tm.act), (dst, fc16_tm.dst),
                     (prob, fc16_tm.prob), (reward, fc16_tm.reward),
                     (progress, fc16_tm.progress)):
        assert np.array_equal(col[slot], np.asarray(ref))
    pad = np.ones(n * t_blk, bool)
    pad[slot] = False
    assert np.all(src_l[pad] == s_blk)
    assert np.all(prob[pad] == 0.0)
    # the ptmdp horizon transform interleaves shutdown rows, so this
    # tensor exercises the argsort (non-pre-bucketed) path; a raw
    # frontier compile with nondecreasing src takes the split fast
    # path — both land in the same padded layout contract asserted
    # above
    assert not np.all(src[1:] >= src[:-1])


def test_partition_refuses_uneven_and_short_pad(fc16_tm):
    with pytest.raises(ValueError, match="cannot shard"):
        partition_by_state_block(fc16_tm, 4)  # S=89, not a multiple
    with pytest.raises(ValueError, match="cannot shard"):
        partition_by_state_block(fc16_tm, 4, S_pad=88)  # < n_states


def test_halo_bytes():
    assert state_halo_bytes(100, 1, np.float32) == 0
    # 4 shards x 2 vectors x 75 remote entries x 4 bytes
    assert state_halo_bytes(100, 4, np.float32) == 2 * 75 * 4 * 4


# -- named refusals from every entry point -----------------------------------


def test_uneven_states_refused_by_name(fc16_pm, fc16_tm):
    mesh = _mesh(4)
    with pytest.raises(ValueError, match=r"cannot shard 89 states"):
        sharded_state_value_iteration(fc16_tm, mesh, stop_delta=1e-6)
    with pytest.raises(ValueError, match=r"cannot shard 89 states"):
        make_grid_state_chunk_step(
            fc16_tm, 4, np.zeros((4, fc16_tm.src.shape[0])),
            discount=1.0,
            mesh=jax.sharding.Mesh(
                np.asarray(jax.devices()[:8]).reshape(2, 4), ("g", "s")))
    # the grid axis is refused by the same rule
    with pytest.raises(ValueError, match=r"cannot shard 3 grid points"):
        make_grid_state_chunk_step(
            fc16_tm, 3, np.zeros((3, fc16_tm.src.shape[0])),
            discount=1.0,
            mesh=jax.sharding.Mesh(
                np.asarray(jax.devices()[:4]).reshape(2, 2), ("g", "s")))
    with pytest.raises(ValueError, match=r"cannot shard 89 states"):
        grid_value_iteration(
            fc16_pm, (0.25, 0.4), (0.5,), stop_delta=1e-6,
            mesh=jax.sharding.Mesh(
                np.asarray(jax.devices()[:4]).reshape(2, 2), ("g", "s")),
            axis="g", state_axis="s")
    with pytest.raises(ValueError, match="2-D mesh"):
        grid_value_iteration(fc16_pm, (0.25,), (0.5,), stop_delta=1e-6,
                             mesh=None, state_axis="s")


def test_while_impl_refused(fc16_tm):
    with pytest.raises(ValueError, match="impl='chunked'"):
        sharded_state_value_iteration(fc16_tm, _mesh(1), impl="while",
                                      stop_delta=1e-6)


# -- bit-identity vs the single-device chunked solve -------------------------


@pytest.mark.parametrize("tm_fixture",
                         ["fc16_tm", "aft20_tm_", "ghostdag_tm"])
def test_sharded_bit_identity_1_vs_4(tm_fixture, request, fc16_tm,
                                     aft20_pm, ghostdag_tm):
    tm = (fc16_tm if tm_fixture == "fc16_tm" else
          _materialize(aft20_pm) if tm_fixture == "aft20_tm_" else
          ghostdag_tm)
    ref = tm.value_iteration(stop_delta=1e-6, impl="chunked")
    for n in (1, 4):
        got = sharded_state_value_iteration(
            tm, _mesh(n), stop_delta=1e-6, pad_states=True)
        assert got["vi_iter"] == ref["vi_iter"], (tm_fixture, n)
        for k in ("vi_value", "vi_progress", "vi_policy"):
            assert np.array_equal(got[k], ref[k]), (tm_fixture, n, k)
        assert got["vi_state_shards"] == n
        assert got["vi_halo_bytes"] == (0 if n == 1 else
                                        state_halo_bytes(
                                            tm.n_states
                                            + (-tm.n_states % n),
                                            n, tm.prob.dtype))


def test_sharded_no_pad_exact_division(aft20_pm):
    """aft20@6 has S=94: divisible by 2, so the default (no padding)
    path runs and stays bit-identical."""
    tm = _materialize(aft20_pm)
    assert tm.n_states % 2 == 0
    ref = tm.value_iteration(stop_delta=1e-6, impl="chunked")
    got = sharded_state_value_iteration(tm, _mesh(2), stop_delta=1e-6)
    assert got["vi_iter"] == ref["vi_iter"]
    for k in ("vi_value", "vi_progress", "vi_policy"):
        assert np.array_equal(got[k], ref[k])


def test_sharded_kill_resume_bit_identical(fc16_tm, tmp_path,
                                           monkeypatch):
    """kill@vi_chunk mid-solve through the SHARDED path: the resumed
    run lands on exactly the uninterrupted sharded fixpoint (which is
    itself the single-device fixpoint) and cleans up the checkpoint."""
    mesh = _mesh(4)
    clean = sharded_state_value_iteration(
        fc16_tm, mesh, stop_delta=1e-6, pad_states=True, chunk=32)
    ck = str(tmp_path / "svi-ck.npz")
    monkeypatch.setenv(FAULT_ENV_VAR, "kill@vi_chunk=3")
    with pytest.raises(InjectedKill):
        sharded_state_value_iteration(
            fc16_tm, mesh, stop_delta=1e-6, pad_states=True, chunk=32,
            checkpoint_path=ck)
    assert os.path.exists(ck)  # chunks 1-2 landed before the crash
    monkeypatch.delenv(FAULT_ENV_VAR)
    got = sharded_state_value_iteration(
        fc16_tm, mesh, stop_delta=1e-6, pad_states=True, chunk=32,
        checkpoint_path=ck)
    assert got["vi_iter"] == clean["vi_iter"]
    for k in ("vi_value", "vi_progress", "vi_policy"):
        assert np.array_equal(got[k], clean[k])
    assert not os.path.exists(ck)  # finished solves leave no seed


# -- grid x state composition ------------------------------------------------


def test_grid_state_composition_parity(aft20_pm):
    """The 2-D (grid x state) mesh solve equals the 1-D grid solve
    bit-for-bit — per-point fixpoints, freeze iterations, sweep
    count."""
    alphas, gammas = (0.3, 0.4), (0.25, 0.75)
    ref = grid_value_iteration(aft20_pm, alphas, gammas,
                               stop_delta=1e-6, mesh=None)
    mesh2 = jax.sharding.Mesh(
        np.asarray(jax.devices()[:4]).reshape(2, 2), ("g", "s"))
    got = grid_value_iteration(aft20_pm, alphas, gammas,
                               stop_delta=1e-6, mesh=mesh2, axis="g",
                               state_axis="s")
    assert got["vi_iter"] == ref["vi_iter"]
    assert np.array_equal(got["grid_iter"], ref["grid_iter"])
    for k in ("grid_value", "grid_progress", "grid_policy"):
        assert np.array_equal(np.asarray(got[k]), np.asarray(ref[k])), k


# -- the working-set guard: sharding unlocks refused sizes -------------------


def test_working_set_guard_sharded_completes(ghostdag_tm, monkeypatch):
    """ISSUE-16 acceptance, scaled to CI: pick a CPR_VI_BYTES ceiling
    between the 4-shard and single-device working sets — the
    single-device path refuses the ghostdag solve by name while the
    sharded path completes it end-to-end (same fixpoint as an
    unguarded solve)."""
    tm = ghostdag_tm
    S, A = tm.n_states, tm.n_actions
    T = int(np.asarray(tm.src).shape[0])
    n = 4
    S_pad = S + (-S % n)
    _, _, t_blk = partition_by_state_block(tm, n, S_pad)
    single = vi_working_set_bytes(T, S, A, tm.prob.dtype)
    sharded = vi_working_set_bytes(t_blk, S_pad, A, tm.prob.dtype,
                                   shards=n)
    assert sharded < single  # the whole point of the state axis
    ceiling = (sharded + single) // 2
    monkeypatch.setenv("CPR_VI_BYTES", str(ceiling))
    with pytest.raises(ViWorkingSetTooLarge, match="CPR_VI_BYTES"):
        tm.value_iteration(stop_delta=1e-6, impl="chunked")
    got = sharded_state_value_iteration(
        tm, _mesh(n), stop_delta=1e-6, pad_states=True)
    monkeypatch.delenv("CPR_VI_BYTES")
    ref = tm.value_iteration(stop_delta=1e-6, impl="chunked")
    for k in ("vi_value", "vi_progress", "vi_policy"):
        assert np.array_equal(got[k], ref[k])


# -- telemetry ---------------------------------------------------------------


def test_sharded_solve_event_carries_shard_extras(fc16_tm, tmp_path):
    trace = tmp_path / "svi.jsonl"
    telemetry.configure(str(trace))
    try:
        telemetry.current().manifest(config={"role": "test-state-shard"})
        sharded_state_value_iteration(
            fc16_tm, _mesh(4), stop_delta=1e-6, pad_states=True,
            protocol="fc16", cutoff=6)
    finally:
        telemetry.configure(None)
    import json

    events = [json.loads(ln) for ln in open(trace)]
    (ev,) = [e for e in events if e.get("name") == "mdp_solve"]
    assert ev["protocol"] == "fc16" and ev["cutoff"] == 6
    assert ev["state_shards"] == 4
    assert ev["halo_bytes"] > 0
    assert ev["states_per_sec"] > 0
    assert ev["sweeps"] > 0 and ev["converged"] == 1


# -- in-graph RTDP -----------------------------------------------------------


def test_rtdp_graph_converges_and_reproduces(fc16_tm):
    exact = fc16_tm.value_iteration(stop_delta=1e-7)
    sv_exact = fc16_tm.start_value(exact["vi_value"])
    key = jax.random.PRNGKey(0)
    r = rtdp_graph(fc16_tm, key, max_steps=3000, batch=128, buffer=256)
    assert r["rtdp_steps"] == 3000  # stop_delta=0: full budget
    sv = fc16_tm.start_value(r["rtdp_value"])
    assert abs(sv - sv_exact) < 1e-3 * max(1.0, abs(sv_exact))
    assert (r["rtdp_visits"] > 0).sum() > 0.5 * fc16_tm.n_states
    assert (r["rtdp_buffer"] >= 0).any()
    # same key -> bit-identical everything
    r2 = rtdp_graph(fc16_tm, key, max_steps=3000, batch=128, buffer=256)
    for k in ("rtdp_value", "rtdp_progress", "rtdp_visits",
              "rtdp_buffer"):
        assert np.array_equal(r[k], r2[k]), k
    # different key -> a different exploration trace
    r3 = rtdp_graph(fc16_tm, jax.random.PRNGKey(7), max_steps=3000,
                    batch=128, buffer=256)
    assert not np.array_equal(r["rtdp_visits"], r3["rtdp_visits"])


def test_rtdp_graph_early_exit(fc16_tm):
    r = rtdp_graph(fc16_tm, jax.random.PRNGKey(0), max_steps=100_000,
                   batch=128, buffer=256, stop_delta=1e-4)
    assert r["rtdp_steps"] < 100_000
    assert r["rtdp_resid"] <= 1e-4


def test_rtdp_host_oracle_value_check(fc16_tm):
    """The in-graph port and the host RTDP's deterministic rng agree
    on what they are estimating: both land on the exact start value
    (the host oracle runs on the same compiled table via the
    explicit-MDP extraction contract, so the exact VI start value is
    the shared oracle)."""
    exact = fc16_tm.value_iteration(stop_delta=1e-7)
    sv_exact = fc16_tm.start_value(exact["vi_value"])
    r = rtdp_graph(fc16_tm, jax.random.PRNGKey(3), max_steps=4000,
                   batch=128, buffer=256)
    assert fc16_tm.start_value(r["rtdp_value"]) == pytest.approx(
        sv_exact, rel=1e-3)


def test_rtdp_sharded_polish_handoff(fc16_tm):
    """Explore in-graph, polish exactly: the handoff reaches the cold
    exact fixpoint (to stop_delta) in no more sweeps than the cold
    solve, with the rtdp_* diagnostics riding along."""
    cold = fc16_tm.value_iteration(stop_delta=1e-7, impl="chunked")
    vi = rtdp_sharded_polish(
        fc16_tm, _mesh(4), jax.random.PRNGKey(0), rtdp_steps=2000,
        batch=128, stop_delta=1e-7, pad_states=True)
    assert vi["vi_iter"] <= cold["vi_iter"]
    assert np.allclose(vi["vi_value"], cold["vi_value"], atol=1e-5)
    assert vi["vi_state_shards"] == 4
    assert vi["rtdp_steps"] == 2000 and vi["rtdp_batch"] == 128


def test_host_rtdp_accepts_rng_instance():
    """Satellite: the host RTDP threads one explicit random stream —
    same seed or equal-state rng instances walk bit-identical
    trajectories; the module-global `random` is never consulted."""
    import random as random_mod

    from cpr_tpu.mdp.models import Fc16BitcoinSM
    from cpr_tpu.mdp.rtdp import RTDP

    mk = lambda: Fc16BitcoinSM(alpha=0.3, gamma=0.5,  # noqa: E731
                               maximum_fork_length=4)
    a = RTDP(mk(), eps=0.3, seed=11).run(400)
    b = RTDP(mk(), eps=0.3, rng=random_mod.Random(11)).run(400)
    assert a.n_states == b.n_states
    np.testing.assert_array_equal(a.value[:a.n_states],
                                  b.value[:b.n_states])
    np.testing.assert_array_equal(a.count[:a.n_states],
                                  b.count[:b.n_states])
    # and a different seed explores differently
    c = RTDP(mk(), eps=0.3, seed=12).run(400)
    assert (a.n_states != c.n_states
            or not np.array_equal(a.count[:a.n_states],
                                  c.count[:c.n_states]))
