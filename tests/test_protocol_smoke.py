"""Fast-tier protocol coverage: every env family constructs, jits, and
behaves sanely on tiny shapes.

The deep stochastic batteries (test_*_env.py) are the slow tier
(--runslow); this file is their always-on floor, shaped after the
reference's three-battery structure (cpr_protocols.ml:200-782): honest
runs stay near alpha, the honest policy through the attack space stays
~honest, and random policies don't violate invariants.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu.envs import registry
from cpr_tpu.params import make_params

# one config per family + one per selection algorithm; the remaining
# scheme/selection combinations live in the slow-tier batteries
KEYS = (
    "nakamoto",
    "ethereum-byzantium",
    "bk-4-constant",
    "spar-4-block",
    "stree-4-constant-optimal",
    "sdag-4-constant-altruistic",
    "tailstorm-4-discount-heuristic",
    "tailstormjune-4-block",
)

ALPHA = 0.3


def run_honest(env, n_envs=32, max_steps=48):
    params = make_params(alpha=ALPHA, gamma=0.5, max_steps=max_steps)
    keys = jax.random.split(jax.random.PRNGKey(0), n_envs)
    f = jax.jit(jax.vmap(lambda k: env.episode_stats(
        k, params, env.policies["honest"], max_steps + 8)))
    return jax.block_until_ready(f(keys))


@pytest.mark.parametrize("key", KEYS)
def test_honest_policy_earns_alpha(key):
    env = registry.get_sized(key, 48)
    stats = run_honest(env)
    a = np.asarray(stats["episode_reward_attacker"]).mean()
    d = np.asarray(stats["episode_reward_defender"]).mean()
    assert a + d > 0
    assert abs(a / (a + d) - ALPHA) < 0.08, (key, a / (a + d))


@pytest.mark.parametrize("key", ["bk-4-constant",
                                 "tailstorm-4-discount-heuristic"])
def test_random_policy_keeps_invariants(key):
    """The reference's `random` battery (cpr_protocols.ml:658-782) in
    miniature: random actions must not crash or overflow the DAG."""
    env = registry.get_sized(key, 48)
    params = make_params(alpha=0.4, gamma=0.5, max_steps=48)

    def random_policy(obs):
        # pseudo-random but jittable: hash the observation
        h = jnp.abs(jnp.sum(obs * 1000.0)).astype(jnp.int32)
        return h % env.n_actions

    keys = jax.random.split(jax.random.PRNGKey(1), 16)
    f = jax.jit(jax.vmap(lambda k: env.episode_stats(
        k, params, random_policy, 56)))
    stats = jax.block_until_ready(f(keys))
    assert np.isfinite(
        np.asarray(stats["episode_reward_attacker"])).all()
    assert (np.asarray(stats["episode_progress"]) >= 0).all()


def test_observation_bounds():
    for key in ("nakamoto", "bk-4-constant"):
        env = registry.get_sized(key, 48)
        params = make_params(alpha=0.3, gamma=0.5, max_steps=32)
        state, obs = jax.jit(env.reset)(jax.random.PRNGKey(0), params)
        lo = np.asarray(env.low)
        hi = np.asarray(env.high)
        o = np.asarray(obs)
        assert (o >= lo - 1e-6).all() and (o <= hi + 1e-6).all(), key


def test_logical_reset_matches_full_select():
    """The O(reset_dag_rows) logical DAG reset in auto-reset streams
    (JaxEnv.select_reset) must be trajectory-identical to the full
    tree.map select: slots >= reset_dag_rows are dead after a reset
    (exists()-masked until an append rewrites every field), so only the
    first rows plus (n, overflow) carry state across the boundary."""
    from cpr_tpu.envs.bk import BkSSZ

    env = BkSSZ(k=4, incentive_scheme="constant", max_steps_hint=64)
    assert env.reset_dag_rows is not None
    params = make_params(alpha=0.4, gamma=0.5, max_steps=12)
    policy = env.policies["get-ahead"]
    keys = jax.random.split(jax.random.PRNGKey(3), 16)
    # >= 4 episode boundaries per stream at max_steps=12
    fast = jax.vmap(lambda k: env.rollout(k, params, policy, 50))(keys)
    env.reset_dag_rows = None  # force the always-safe full select
    try:
        full = jax.vmap(lambda k: env.rollout(k, params, policy, 50))(keys)
    finally:
        env.reset_dag_rows = type(env).reset_dag_rows
    for a, b in zip(jax.tree.leaves(fast), jax.tree.leaves(full)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
