"""Crash-safety layer (cpr_tpu/resilience.py) and its wiring.

The acceptance criterion is behavioral, not structural: a run that is
killed mid-training and resumed must produce a metrics history
bit-identical to one that was never interrupted, GuardFailure must
never be retried while transient faults are, and every recovery path
is driven by the deterministic CPR_FAULT_INJECT harness instead of a
real outage.  The training-loop tests reuse the exact env/PPO geometry
of test_train_driver.py so the jitted train step compiles once per
pytest process.
"""

import gc
import hashlib
import json
import os
import signal

import numpy as np
import pytest

from cpr_tpu import resilience, telemetry
from cpr_tpu.resilience import (FaultSpec, GuardFailure, InjectedKill,
                                TransientFault, default_classify,
                                with_retries)


# -- retry/backoff -----------------------------------------------------------


def test_with_retries_backoff_sequence_and_success():
    delays, calls = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    out = with_retries(flaky, max_attempts=4, base_delay_s=0.5,
                       max_delay_s=10.0, jitter_frac=0.0,
                       sleep=delays.append)
    assert out == "ok" and len(calls) == 3
    assert delays == [0.5, 1.0]  # base * 2**(attempt-1)


def test_with_retries_caps_delay_and_jitters_within_bound():
    delays = []

    def always():
        raise OSError("x")

    with pytest.raises(OSError):
        with_retries(always, max_attempts=4, base_delay_s=1.0,
                     max_delay_s=1.5, jitter_frac=0.25,
                     sleep=delays.append, rng=lambda: 1.0)
    # attempts 2/3 would be 2.0/4.0 uncapped; capped at 1.5 then
    # jittered by the full 25%
    assert delays == pytest.approx([1.25, 1.875, 1.875])


def test_with_retries_guard_failure_never_retried():
    calls = []

    def guard():
        calls.append(1)
        raise GuardFailure("deterministic")

    with pytest.raises(GuardFailure):
        with_retries(guard, max_attempts=5, sleep=lambda s: None)
    assert len(calls) == 1


def test_with_retries_injected_kill_is_fatal():
    calls = []

    def kill():
        calls.append(1)
        raise InjectedKill("kill@update=1")

    with pytest.raises(InjectedKill):
        with_retries(kill, max_attempts=5, sleep=lambda s: None)
    assert len(calls) == 1


def test_assertion_error_is_transient_by_classification():
    """The masquerade invariant: assertions from jax internals are
    infra failures, not correctness guards — they must retry."""
    assert default_classify(AssertionError("xla internal")) is True
    assert default_classify(GuardFailure("rule")) is False
    assert default_classify(TransientFault("chip claim")) is True
    assert default_classify(OSError("io")) is True


def test_with_retries_emits_retry_events(tmp_path):
    path = tmp_path / "tele.jsonl"
    telemetry.configure(str(path))
    try:
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise OSError("blip")
            return 1

        with_retries(flaky, max_attempts=3, base_delay_s=0.01,
                     jitter_frac=0.0, sleep=lambda s: None, name="unit")
    finally:
        telemetry.configure(None)
    events = [json.loads(ln) for ln in open(path)]
    retries = [e for e in events if e.get("name") == "retry"]
    assert len(retries) == 1
    e = retries[0]
    assert e["kind"] == "event" and e["site"] == "unit"
    for k in telemetry.EVENT_FIELDS["retry"]:
        assert k in e, e
    assert "OSError" in e["error"]


# -- fault-injection grammar -------------------------------------------------


def test_fault_spec_grammar():
    s = FaultSpec("kill@update=7")
    assert (s.action, s.site, s.index) == ("kill", "update", 7)
    # bare action@site defaults to index 1 — the whole story for sites
    # hit once per process (the supervisor's probe/run fault points)
    s = FaultSpec("hang@probe")
    assert (s.action, s.site, s.index) == ("hang", "probe", 1)
    assert [s.raw for s in resilience.parse_fault_specs(
        "kill@update=7, io_error@checkpoint=2, hang@run")] == [
        "kill@update=7", "io_error@checkpoint=2", "hang@run"]
    for bad in ("kill=7", "explode@update=7", "kill@update=x",
                "kill@a@b=1", "@update=1"):
        with pytest.raises(ValueError):
            FaultSpec(bad)
    assert resilience.parse_fault_specs("") == []


def test_fault_injector_is_one_shot_and_counts_occurrences():
    inj = resilience.FaultInjector(
        resilience.parse_fault_specs("io_error@checkpoint=2"))
    assert inj.fire("checkpoint") is None  # occurrence 1
    with pytest.raises(OSError):
        inj.fire("checkpoint")  # occurrence 2 fires...
    assert inj.fire("checkpoint") is None  # ...once: spec disarmed
    # indexed sites: only the pinned loop index matches
    inj = resilience.FaultInjector(
        resilience.parse_fault_specs("kill@update=3"))
    assert inj.fire("update", 2) is None
    with pytest.raises(InjectedKill):
        inj.fire("update", 3)
    assert inj.fire("update", 3) is None


def test_injector_rebuilds_when_env_changes(monkeypatch):
    monkeypatch.setenv(resilience.FAULT_ENV_VAR, "fault@vi_chunk=1")
    with pytest.raises(TransientFault):
        resilience.fault_point("vi_chunk")
    # a resumed run unsets the var: the stale armed state must not
    # survive the rebuild
    monkeypatch.delenv(resilience.FAULT_ENV_VAR)
    assert resilience.fault_point("vi_chunk") is None


# -- atomic writes -----------------------------------------------------------


def test_atomic_write_failure_leaves_original_intact(tmp_path, monkeypatch):
    path = tmp_path / "artifact.bin"
    resilience.atomic_write_bytes(str(path), b"original")

    def boom(src, dst):
        raise OSError("injected rename failure")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        resilience.atomic_write_bytes(str(path), b"replacement")
    monkeypatch.undo()
    assert path.read_bytes() == b"original"
    # the failed attempt's tmp file was cleaned up
    assert os.listdir(tmp_path) == ["artifact.bin"]


def test_save_checkpoint_round_trips_params_and_meta(tmp_path):
    from flax import serialization
    from cpr_tpu.train.driver import save_checkpoint

    path = str(tmp_path / "model.msgpack")
    params = {"w": np.arange(4.0, dtype=np.float32)}
    save_checkpoint(path, params, meta=dict(update=3, score=0.5))
    meta = json.load(open(path + ".json"))
    # v16: the sidecar gains the sealed payload's fingerprint so
    # load_policy_snapshot can prove the msgpack/meta pair is untorn
    sha = meta.pop("payload_sha256")
    assert meta == {"update": 3, "score": 0.5}
    payload, tag = resilience.sealed_read(path, kind="model_checkpoint")
    assert tag == "verified"
    assert hashlib.sha256(payload).hexdigest() == sha
    restored = serialization.from_bytes(
        {"w": np.zeros(4, np.float32)}, payload)
    np.testing.assert_array_equal(restored["w"], params["w"])


# -- preemption --------------------------------------------------------------


def test_preemption_guard_catches_sigterm_and_restores_handler():
    before = signal.getsignal(signal.SIGTERM)
    with resilience.preemption_guard():
        assert not resilience.preempt_requested()
        os.kill(os.getpid(), signal.SIGTERM)
        assert resilience.preempt_requested()
        assert resilience.preempt_reason() == "SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is before
    # re-entry clears the stale flag
    with resilience.preemption_guard():
        assert not resilience.preempt_requested()


# -- snapshots + metrics-log helpers -----------------------------------------


def _fake_carry(fill: float):
    """A carry-shaped pytree: (obj-with-.params, env_state, obs, key)."""
    from flax.training import train_state
    import optax

    ts = train_state.TrainState.create(
        apply_fn=lambda *a: None,
        params={"w": np.full(4, fill, np.float32)},
        tx=optax.adam(1e-3))  # adam: non-trivial opt_state (mu/nu/count)
    if fill:  # make the optimizer moments distinguishable from init
        ts = ts.apply_gradients(grads={"w": np.full(4, fill, np.float32)})
    return (ts, {"height": np.full(2, fill, np.int32)},
            np.full(3, fill, np.float32), np.arange(2, dtype=np.uint32))


def test_train_snapshot_round_trip(tmp_path):
    path = str(tmp_path / "snap.msgpack")
    carry = _fake_carry(2.5)
    best_params = {"w": np.full(4, 9.0, np.float32)}
    resilience.save_train_snapshot(path, carry, update=7, best=0.625,
                                   best_params=best_params,
                                   config={"seed": 0})
    got, got_best, meta = resilience.load_train_snapshot(
        path, _fake_carry(0.0))
    assert meta["update"] == 7 and meta["best"] == 0.625
    np.testing.assert_array_equal(got[0].params["w"], carry[0].params["w"])
    # optimizer state (adam moments + step count) restores exactly
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(got[0].opt_state),
                    jax.tree_util.tree_leaves(carry[0].opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(got[0].step) == int(carry[0].step) == 1
    np.testing.assert_array_equal(got[1]["height"], carry[1]["height"])
    np.testing.assert_array_equal(got[3], carry[3])
    np.testing.assert_array_equal(got_best["w"], best_params["w"])
    assert json.load(open(path + ".json"))["config"] == {"seed": 0}


def test_train_snapshot_without_best_and_version_gate(tmp_path, monkeypatch):
    path = str(tmp_path / "snap.msgpack")
    resilience.save_train_snapshot(path, _fake_carry(1.0), update=2,
                                   best=float("-inf"), best_params=None)
    _, got_best, meta = resilience.load_train_snapshot(
        path, _fake_carry(0.0))
    assert got_best is None and meta["best"] is None
    monkeypatch.setattr(resilience, "SNAPSHOT_VERSION",
                        resilience.SNAPSHOT_VERSION + 1)
    with pytest.raises(ValueError, match="version"):
        resilience.load_train_snapshot(path, _fake_carry(0.0))


def test_vi_checkpoint_round_trip_and_validation(tmp_path):
    path = str(tmp_path / "vi.npz")
    value = np.linspace(0, 1, 8).astype(np.float32)
    prog = np.ones(8, np.float32)
    resilience.save_vi_checkpoint(path, value=value, prog=prog, it=12,
                                  resids=[np.ones(4, np.float32)],
                                  stop_delta=1e-6)
    v, p, it, resid = resilience.load_vi_checkpoint(
        path, S=8, dtype=np.float32)
    np.testing.assert_array_equal(v, value)
    np.testing.assert_array_equal(p, prog)
    assert it == 12 and resid.shape == (4,)
    with pytest.raises(ValueError, match="S="):
        resilience.load_vi_checkpoint(path, S=9, dtype=np.float32)
    with pytest.raises(ValueError, match="dtype"):
        resilience.load_vi_checkpoint(path, S=8, dtype=np.float64)


def test_trim_metrics_log_and_fingerprint(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    rows = [{"run": True, "total_updates": 4},
            {"update": 1, "loss": 0.5, "wall_s": 0.1, "steps_per_sec": 10},
            {"update": 2, "loss": 0.4, "wall_s": 0.2},
            {"eval": True, "update": 2, "relative_reward": 0.3},
            {"update": 3, "loss": 0.3},  # orphan past the snapshot
            {"preempted": True, "update": 3, "reason": "SIGTERM"}]
    with open(path, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in rows)
    resilience.trim_metrics_log(path, 2)
    kept = [json.loads(ln) for ln in open(path)]
    assert [r.get("update") for r in kept] == [None, 1, 2, 2]
    assert kept[0]["run"] is True
    # fingerprint: headers + lifecycle rows gone, volatile keys stripped
    fp = resilience.metrics_fingerprint(path)
    assert fp == [{"update": 1, "loss": 0.5}, {"update": 2, "loss": 0.4},
                  {"eval": True, "update": 2, "relative_reward": 0.3}]


# -- the `hang` fault action (PR 8: supervisor's deterministic wedge) --------
# (the bench child-process protocol itself — status -> taxonomy mapping,
# guard/hang retry counts — moved to tests/test_supervisor.py with the
# watchdog, which bench.py now delegates to)


def test_hang_fire_blocks_then_disarms(monkeypatch):
    """An injected hang blocks for CPR_FAULT_HANG_S (approximating a
    wedged backend that neither returns nor raises), then the one-shot
    disarms — the bookkeeping the warm-restart proof relies on: a
    RESTARTED child re-fires because its injector counters are fresh,
    while within one process the site fires once."""
    import time as _time

    monkeypatch.setenv(resilience.HANG_DURATION_ENV_VAR, "0.2")
    inj = resilience.FaultInjector(resilience.parse_fault_specs(
        "hang@run"))
    t0 = _time.time()
    assert inj.fire("run") == "hang"  # cooperative: returns, not raises
    assert _time.time() - t0 >= 0.15  # actually blocked for the budget
    assert inj.fire("run") is None  # disarmed
    # indexed form pins a later occurrence
    monkeypatch.setenv(resilience.HANG_DURATION_ENV_VAR, "0.01")
    inj = resilience.FaultInjector(resilience.parse_fault_specs(
        "hang@run=2"))
    assert inj.fire("run") is None
    assert inj.fire("run") == "hang"


def test_hang_emits_fault_injected_event_before_blocking(
        tmp_path, monkeypatch):
    """The fault_injected event must hit the sink BEFORE the block:
    the hung process is about to be killed, and the trace is how a
    post-mortem learns where the hang was injected."""
    monkeypatch.setenv(resilience.HANG_DURATION_ENV_VAR, "0.01")
    monkeypatch.setenv(resilience.FAULT_ENV_VAR, "hang@mysite")
    path = tmp_path / "tele.jsonl"
    telemetry.configure(str(path))
    try:
        assert resilience.fault_point("mysite") == "hang"
    finally:
        telemetry.configure(None)
    events = [json.loads(ln) for ln in open(path)]
    (e,) = [e for e in events if e.get("name") == "fault_injected"]
    assert e["spec"] == "hang@mysite" and e["site"] == "mysite"


# -- chunked-VI checkpoint/resume (host seam, synthetic contraction) ---------


def _contraction_step(value, prog, steps):
    """chunk_step contract stand-in: `steps` Jacobi sweeps of the map
    v <- (v + 1) / 2 (fixpoint 1), per-sweep max deltas returned."""
    import jax.numpy as jnp

    deltas = []
    v = jnp.asarray(value)
    for _ in range(steps):
        nv = (v + 1.0) / 2.0
        deltas.append(jnp.max(jnp.abs(nv - v)))
        v = nv
    return v, prog, jnp.zeros_like(v, jnp.int32), jnp.stack(deltas)


def _run_vi(checkpoint_path=None):
    from cpr_tpu.mdp.explicit import run_chunk_driver

    return run_chunk_driver(_contraction_step, 8, np.float32, 1e-4, 64,
                            chunk=4, checkpoint_path=checkpoint_path)


def test_vi_chunk_kill_and_resume_bit_identical(tmp_path, monkeypatch):
    ref_value, _, _, ref_delta, ref_it, ref_resid = _run_vi()
    assert float(ref_delta) <= 1e-4 and ref_it == 16

    ck = str(tmp_path / "vi-ck.npz")
    monkeypatch.setenv(resilience.FAULT_ENV_VAR, "kill@vi_chunk=3")
    with pytest.raises(InjectedKill):
        _run_vi(checkpoint_path=ck)
    assert os.path.exists(ck)  # chunks 1-2 landed before the crash

    monkeypatch.delenv(resilience.FAULT_ENV_VAR)
    value, _, _, delta, it, resid = _run_vi(checkpoint_path=ck)
    assert it == ref_it
    np.testing.assert_array_equal(np.asarray(value), np.asarray(ref_value))
    np.testing.assert_array_equal(resid, ref_resid)
    # crash-recovery scratch is deleted once the solve completes
    assert not os.path.exists(ck) and not os.path.exists(ck + ".json")


def test_vi_chunk_transient_fault_is_retried(tmp_path, monkeypatch):
    ref_value = np.asarray(_run_vi()[0])
    tele_path = tmp_path / "tele.jsonl"
    monkeypatch.setenv(resilience.FAULT_ENV_VAR, "fault@vi_chunk=1")
    telemetry.configure(str(tele_path))
    try:
        value, *_ = _run_vi()
    finally:
        telemetry.configure(None)
    np.testing.assert_array_equal(np.asarray(value), ref_value)
    events = [json.loads(ln) for ln in open(tele_path)]
    assert any(e.get("name") == "retry" and e.get("site") == "vi_chunk"
               for e in events)
    assert any(e.get("name") == "fault_injected" for e in events)


def test_while_impl_refuses_checkpoint_path():
    from cpr_tpu.mdp import Compiler, ptmdp
    from cpr_tpu.mdp.models import Fc16BitcoinSM

    c = Compiler(Fc16BitcoinSM(alpha=0.25, gamma=0.5,
                               maximum_fork_length=4))
    tm = ptmdp(c.mdp(), horizon=10).tensor()
    with pytest.raises(ValueError, match="while"):
        tm.value_iteration(stop_delta=1e-4, impl="while",
                           checkpoint_path="/tmp/nope.npz")


# -- training-loop integration (same jit geometry as test_train_driver) ------


def _tiny_cfg(**over):
    from cpr_tpu.train.config import TrainConfig

    kw = dict(protocol="nakamoto", alpha=0.4, episode_len=16, n_envs=8,
              total_updates=4,
              ppo=dict(n_steps=8, n_minibatches=2, update_epochs=1,
                       lr=1e-3),
              eval=dict(freq=2, start_at_iteration=0))
    kw.update(over)
    return TrainConfig(**kw)


@pytest.fixture
def fake_eval(monkeypatch):
    """Deterministic scripted eval (constant score): keeps the focus on
    loop control and avoids compiling the eval kernel."""
    from cpr_tpu.train import driver as drv

    def fn(env, cfg, net_params, **kw):
        return [dict(alpha=0.4, gamma=0.5, relative_reward=0.3,
                     reward_per_progress=0.3, episode_progress=1.0)]

    monkeypatch.setattr(drv, "evaluate_per_alpha", fn)
    return fn


def test_kill_and_resume_bit_identical_history(tmp_path, monkeypatch,
                                               fake_eval):
    """THE acceptance criterion: kill at update 4 with the last
    snapshot at update 2, resume, and the full metrics history equals
    an uninterrupted run's — including the orphan update-3 row the
    snapshot never saw (trimmed and re-produced)."""
    from cpr_tpu.train import driver as drv

    a, b = tmp_path / "a", tmp_path / "b"
    cfg = _tiny_cfg()
    drv.train_from_config(cfg, out_dir=str(a), snapshot_freq=2)

    monkeypatch.setenv(resilience.FAULT_ENV_VAR, "kill@update=4")
    with pytest.raises(InjectedKill):
        drv.train_from_config(cfg, out_dir=str(b), snapshot_freq=2)
    monkeypatch.delenv(resilience.FAULT_ENV_VAR)
    # the crash left rows 1-3 but a snapshot at 2: row 3 is an orphan
    pre = [json.loads(ln) for ln in open(b / "metrics.jsonl")]
    assert any(r.get("update") == 3 and "eval" not in r for r in pre)
    assert json.load(open(b / "snapshot.msgpack.json"))["update"] == 2

    params, hist, _ = drv.train_from_config(
        cfg, out_dir=str(b), snapshot_freq=2, resume=True)
    assert len(hist) == 2  # resumed segment only: updates 3 and 4
    fp_a = resilience.metrics_fingerprint(str(a / "metrics.jsonl"))
    fp_b = resilience.metrics_fingerprint(str(b / "metrics.jsonl"))
    assert fp_a == fp_b
    ups = [r["update"] for r in fp_b if "eval" not in r]
    assert ups == [1, 2, 3, 4]  # no duplicates after the trim


def test_resume_past_corrupt_snapshot_cold_starts_bit_identical(
        tmp_path, monkeypatch, fake_eval):
    """v16 recovery policy for the training loop: a bit-flipped
    snapshot is quarantined and resume falls back to a cold start —
    whose full metrics history equals an uninterrupted run's, because
    the corrupt bytes were never deserialized into the carry."""
    from cpr_tpu import integrity
    from cpr_tpu.train import driver as drv

    a, b = tmp_path / "a", tmp_path / "b"
    cfg = _tiny_cfg()
    drv.train_from_config(cfg, out_dir=str(a), snapshot_freq=2)

    monkeypatch.setenv(resilience.FAULT_ENV_VAR, "kill@update=4")
    with pytest.raises(InjectedKill):
        drv.train_from_config(cfg, out_dir=str(b), snapshot_freq=2)
    monkeypatch.delenv(resilience.FAULT_ENV_VAR)
    snap = str(b / "snapshot.msgpack")
    integrity.damage_artifact(snap, "corrupt")

    _, hist, _ = drv.train_from_config(
        cfg, out_dir=str(b), snapshot_freq=2, resume=True)
    assert len(hist) == 4  # the resumed segment IS the whole run
    assert os.listdir(integrity.quarantine_dir(snap))
    fp_a = resilience.metrics_fingerprint(str(a / "metrics.jsonl"))
    fp_b = resilience.metrics_fingerprint(str(b / "metrics.jsonl"))
    assert fp_a == fp_b


def test_resume_rejects_config_mismatch(tmp_path, fake_eval):
    from cpr_tpu.train import driver as drv

    cfg = _tiny_cfg(total_updates=2)
    drv.train_from_config(cfg, out_dir=str(tmp_path), snapshot_freq=1)
    with pytest.raises(ValueError, match="config"):
        drv.train_from_config(_tiny_cfg(total_updates=2, seed=1),
                              out_dir=str(tmp_path), resume=True)
    with pytest.raises(ValueError, match="resume"):
        drv.train_from_config(cfg, resume=True)  # no out_dir, no path


def test_injected_io_error_on_checkpoint_is_retried(tmp_path, monkeypatch,
                                                    fake_eval):
    from cpr_tpu.train import driver as drv

    tele_path = tmp_path / "tele.jsonl"
    monkeypatch.setenv(resilience.FAULT_ENV_VAR, "io_error@checkpoint=1")
    telemetry.configure(str(tele_path))
    try:
        drv.train_from_config(_tiny_cfg(total_updates=2),
                              out_dir=str(tmp_path / "run"),
                              snapshot_freq=2)
    finally:
        telemetry.configure(None)
    assert os.path.exists(tmp_path / "run" / "last-model.msgpack")
    events = [json.loads(ln) for ln in open(tele_path)]
    assert any(e.get("name") == "retry"
               and str(e.get("site", "")).startswith("save:")
               for e in events)
    assert any(e.get("name") == "fault_injected"
               and e.get("site") == "checkpoint" for e in events)
    # artifact kinds ride as `what` (the record `kind` stays "event")
    kinds = {e.get("what") for e in events
             if e.get("name") == "checkpoint"}
    assert {"last", "best", "snapshot"} <= kinds


def test_injected_preempt_snapshots_and_exits_clean(tmp_path, monkeypatch,
                                                    fake_eval):
    from cpr_tpu.train import driver as drv

    monkeypatch.setenv(resilience.FAULT_ENV_VAR, "preempt@update=2")
    _, hist, _ = drv.train_from_config(_tiny_cfg(), out_dir=str(tmp_path),
                                       snapshot_freq=2)
    monkeypatch.delenv(resilience.FAULT_ENV_VAR)
    assert len(hist) == 1  # stopped cooperatively before update 2
    assert os.path.exists(tmp_path / "preempt-model.msgpack")
    rows = [json.loads(ln) for ln in open(tmp_path / "metrics.jsonl")]
    pre = [r for r in rows if r.get("preempted")]
    assert pre and pre[0]["update"] == 1
    assert json.load(open(tmp_path / "snapshot.msgpack.json"))["update"] == 1


def test_injected_nan_triggers_nonfinite_revert(tmp_path, monkeypatch,
                                                fake_eval):
    """nan@update=2 poisons the params before update 2; with a best
    checkpoint from the update-1 eval, the driver must log the
    poisoned row, revert, and finish with finite parameters."""
    import jax
    from cpr_tpu.train import driver as drv

    monkeypatch.setenv(resilience.FAULT_ENV_VAR, "nan@update=2")
    params, hist, _ = drv.train_from_config(
        _tiny_cfg(total_updates=3, eval=dict(freq=1, start_at_iteration=0)),
        out_dir=str(tmp_path), snapshot_freq=3)
    monkeypatch.delenv(resilience.FAULT_ENV_VAR)
    rows = [json.loads(ln) for ln in open(tmp_path / "metrics.jsonl")]
    reverts = [r for r in rows if r.get("revert")]
    assert reverts and reverts[0]["reason"] == "nonfinite_loss"
    assert reverts[0]["update"] == 2
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree_util.tree_leaves(params))


def test_eval_fn_cache_keyed_by_object_not_id(fake_eval):
    """Regression: the eval-fn cache was keyed on id(env); a GC'd env's
    id can be reused, serving a jitted fn closed over the wrong env.
    The weak-keyed cache cannot hold an entry for a dead env."""
    from cpr_tpu.train import driver as drv

    class Env:  # stand-in; the cache only needs a weakref-able key
        pass

    before = len(drv._EVAL_FN_CACHE)
    e1, e2 = Env(), Env()
    drv._EVAL_FN_CACHE[e1] = {("h", 16): "fn1"}
    drv._EVAL_FN_CACHE[e2] = {("h", 16): "fn2"}
    assert drv._EVAL_FN_CACHE[e1] != drv._EVAL_FN_CACHE[e2]
    del e1, e2
    gc.collect()
    assert len(drv._EVAL_FN_CACHE) == before
