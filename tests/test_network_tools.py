"""Distribution library, network topologies + GraphML round-trip,
custom-topology simulation, the graphml_runner pipe, and safety bounds.

Mirrors the reference's distribution round-trip tests
(distributions.ml:155-184), network GraphML tests (network.ml:234-270),
graphml_runner.ml, and the safety-bounds comparison (bounds.ml).
"""

import random

import jax
import numpy as np
import pytest

from cpr_tpu import distributions as dist
from cpr_tpu import network as netlib
from cpr_tpu.experiments.graphml_runner import run_graphml, visualize
from cpr_tpu.experiments.safety_bounds import (GR22Params, t1lower, t1upper,
                                               violation_rate)


def test_distribution_string_roundtrip():
    """distributions.ml:155-184 expectations."""
    for s in ("constant 1", "constant 0", "constant 1.2",
              "uniform 1.2 2", "exponential 1.2", "geometric 0.5",
              "discrete 1 2 3"):
        d = dist.of_string(s)
        assert dist.of_string(d.to_string()) == d
    for bad in ("", "random", "constant", "uniform 1",
                "exponential 1 2", "discrete"):
        with pytest.raises(ValueError):
            dist.of_string(bad)


def test_distribution_sampling_moments():
    rng = random.Random(0)
    u = dist.uniform(1.0, 3.0)
    e = dist.exponential(2.5)
    us = [u.sample(rng) for _ in range(4000)]
    es = [e.sample(rng) for _ in range(4000)]
    assert abs(np.mean(us) - 2.0) < 0.05
    assert all(1.0 <= x <= 3.0 for x in us)
    assert abs(np.mean(es) - 2.5) < 0.15
    # jax face agrees
    keys = jax.random.split(jax.random.PRNGKey(0), 4000)
    js = jax.vmap(e.sample_jax)(keys)
    assert abs(float(js.mean()) - 2.5) < 0.15


def test_distribution_faces_agree_on_support_and_mean():
    """Property over all five kinds: the host face `sample(rng)` and
    the jitted face `sample_jax(key)` draw from the same distribution —
    same support bounds (the shared GEOM_TAIL_CLAMP fixes the geometric
    ceiling, which used to differ between faces) and the declared mean
    `ev` within sampling tolerance."""
    cases = (dist.constant(1.5), dist.uniform(1.0, 3.0),
             dist.exponential(2.0), dist.geometric(0.3),
             dist.discrete([1.0, 2.0, 3.0]))
    n = 4000
    rng = random.Random(1)
    keys = jax.random.split(jax.random.PRNGKey(1), n)
    for d in cases:
        hs = np.array([d.sample(rng) for _ in range(n)])
        js = np.asarray(jax.vmap(d.sample_jax)(keys), dtype=float)
        for xs in (hs, js):
            if d.kind == "constant":
                assert np.all(xs == 1.5)
            elif d.kind == "uniform":
                assert xs.min() >= 1.0 and xs.max() <= 3.0
            elif d.kind == "exponential":
                assert xs.min() >= 0.0
            elif d.kind == "geometric":
                # integer trial counts, >= 1, capped by the tail clamp
                cap = np.ceil(np.log(dist.GEOM_TAIL_CLAMP)
                              / np.log(1.0 - d.params[0]))
                assert np.all(xs == np.round(xs))
                assert xs.min() >= 1.0 and xs.max() <= cap
            else:  # discrete: indices into the weight vector
                assert set(np.unique(xs)) <= {0.0, 1.0, 2.0}
            # both faces sit on the declared mean...
            tol = 0.15 * max(d.ev, 1.0)
            assert abs(xs.mean() - d.ev) < tol, (d.kind, xs.mean())
        # ...and therefore on each other
        assert abs(hs.mean() - js.mean()) < 0.2 * max(d.ev, 1.0), d.kind
    # degenerate geometric: p >= 1 collapses to exactly 1 on both faces
    g1 = dist.geometric(1.0)
    assert g1.sample(rng) == 1.0
    assert float(g1.sample_jax(keys[0])) == 1.0


def test_network_graphml_roundtrip():
    net = netlib.selfish_mining(alpha=0.3, gamma=0.5, defenders=3,
                                activation_delay=30.0,
                                propagation_delay=1.0)
    xml = netlib.to_graphml(net)
    back = netlib.of_graphml(xml)
    assert back.activation_delay == net.activation_delay
    assert len(back.nodes) == len(net.nodes)
    for a, b in zip(net.nodes, back.nodes):
        assert a.compute == pytest.approx(b.compute)
        assert [(l.dest, l.delay) for l in a.links] == \
            [(l.dest, l.delay) for l in b.links]


def test_fixture_topologies_roundtrip():
    """The shipped GraphML fixtures (tests/fixtures/topologies/) parse,
    round-trip through to_graphml/of_graphml, and have the documented
    shape — so topology-axis tests never depend on external files."""
    import os

    fixdir = os.path.join(os.path.dirname(__file__), "fixtures",
                          "topologies")

    def load(name):
        with open(os.path.join(fixdir, name)) as f:
            return netlib.of_graphml(f.read())

    ring = load("ring-6.xml")
    assert len(ring.nodes) == 6
    assert ring.activation_delay == 60.0
    assert ring.dissemination == "flooding"
    for i, node in enumerate(ring.nodes):
        # undirected ring: reverse links materialized, degree 2
        assert sorted(l.dest for l in node.links) == \
            sorted(((i - 1) % 6, (i + 1) % 6))
        assert all(l.delay == dist.exponential(2) for l in node.links)

    clusters = load("two-cluster-8.xml")
    assert len(clusters.nodes) == 8
    assert clusters.nodes[0].compute == 2.0  # attacker-heavy node 0
    bridge = [l for l in clusters.nodes[3].links if l.dest == 4]
    assert bridge and bridge[0].delay == dist.uniform(10, 20)
    assert sorted(l.dest for l in clusters.nodes[0].links) == [1, 2, 3]

    for net in (ring, clusters):
        back = netlib.of_graphml(netlib.to_graphml(net))
        assert back.activation_delay == net.activation_delay
        assert back.dissemination == net.dissemination
        for a, b in zip(net.nodes, back.nodes):
            assert a.compute == pytest.approx(b.compute)
            assert [(l.dest, l.delay) for l in a.links] == \
                [(l.dest, l.delay) for l in b.links]


def _graphml_with_delay(delay_str):
    net = netlib.symmetric_clique(3, activation_delay=20.0,
                                  propagation_delay=1.0)
    return netlib.to_graphml(net).replace("constant 1", delay_str)


def test_graphml_delay_kind_error_paths():
    """Unsupported delay kinds fail with a clear message at the right
    layer: unknown kinds at parse (of_graphml -> of_string), oracle-
    unsupported kinds at simulate, netsim-unsupported at compile."""
    with pytest.raises(ValueError, match="unknown distribution 'warp'"):
        netlib.of_graphml(_graphml_with_delay("warp 1"))
    with pytest.raises(ValueError, match="takes 1 parameter"):
        netlib.of_graphml(_graphml_with_delay("exponential 1 2"))
    # discrete parses, but neither engine runs it as a link delay
    net = netlib.of_graphml(_graphml_with_delay("discrete 1 2"))
    with pytest.raises(ValueError,
                       match="oracle supports constant/uniform/"
                             "exponential link delays, not 'discrete'"):
        netlib.simulate(net, activations=10)
    from cpr_tpu import netsim
    with pytest.raises(ValueError,
                       match="netsim supports constant/uniform/"
                             "exponential/geometric link delays, "
                             "not 'discrete'"):
        netsim.compile_network(net)
    # geometric: netsim-only — the oracle rejects it, netsim compiles
    geo = netlib.of_graphml(_graphml_with_delay("geometric 0.5"))
    with pytest.raises(ValueError, match="not 'geometric'"):
        netlib.simulate(geo, activations=10)
    assert netsim.compile_network(geo).n == 3


def test_custom_topology_simulation():
    """A star network: the hub relays nothing (simple dissemination),
    so leaves only learn hub blocks — leaves orphan each other."""
    z = dist.constant(0.5)
    nodes = [netlib.NetNode(0.4, [netlib.Link(1, z), netlib.Link(2, z)]),
             netlib.NetNode(0.3, [netlib.Link(0, z)]),
             netlib.NetNode(0.3, [netlib.Link(0, z)])]
    net = netlib.Network(nodes=nodes, activation_delay=10.0)
    sim = netlib.simulate(net, activations=3000, seed=1)
    assert sim.metric("head_height") > 0
    rw = sim.rewards(3)
    # hub hears everyone: it earns at least its share
    assert rw[0] / sum(rw) >= 0.35, rw
    sim.close()


def test_random_topology_sweep():
    """simulate-topology analog: generated sparse networks simulate and
    show delay-dependent orphan rates."""
    rates = {}
    for prop in (0.5, 8.0):
        net = netlib.random_regular(
            8, 3, activation_delay=30.0,
            delay=dist.constant(prop), seed=2)
        sim = netlib.simulate(net, activations=4000, seed=3)
        rates[prop] = 1.0 - sim.metric("head_height") / sim.metric(
            "n_blocks")
        sim.close()
    assert rates[0.5] < rates[8.0], rates


def test_preferential_attachment_generator(tmp_path):
    """create-networks.R parity: BA topology with exponential compute,
    distance-keyed delays, net_bias-derived activation delay; the batch
    writer feeds the GraphML consumption pipeline end to end."""
    net = netlib.preferential_attachment(13, 2, distribution="uniform",
                                         seed=7)
    assert len(net.nodes) == 13
    assert abs(sum(nd.compute for nd in net.nodes) - 1.0) < 1e-9
    # m=2 attachment: 1 + 2*(n-2) edges -> mean degree just under 4
    n_links = sum(len(nd.links) for nd in net.nodes)
    assert n_links == 2 * (1 + 2 * 11)
    assert net.dissemination == "flooding"
    stats = netlib.topology_stats(net)
    assert all(s["farness"] > 0 and s["net_bias"] > 0 for s in stats)
    assert abs(net.activation_delay -
               2 * sum(s["net_bias"] for s in stats) / 13) < 1e-9
    # determinism + distribution validation
    again = netlib.preferential_attachment(13, 2, distribution="uniform",
                                           seed=7)
    assert netlib.to_graphml(again) == netlib.to_graphml(net)
    with pytest.raises(ValueError, match="unknown distribution"):
        netlib.preferential_attachment(8, 2, distribution="gauss")

    # batch -> GraphML files -> round-trip -> oracle simulation
    paths = netlib.write_topology_batch(str(tmp_path), count=2, n=10)
    assert len(paths) == 6 and all(p.endswith("-graphml.xml")
                                   for p in paths)
    back = netlib.of_graphml(open(paths[0]).read())
    assert len(back.nodes) == 10
    s = netlib.simulate(back, protocol="nakamoto", activations=2000,
                        seed=1)
    progress = s.metric("progress")
    s.close()
    # activation_delay = 2x mean net_bias intentionally sits close to
    # the message delay (the generator's stress point — the R study
    # measures orphan rates here), so expect real orphans but a
    # functioning majority chain
    assert progress > 2000 * 0.5


def test_graphml_runner_pipe():
    net = netlib.symmetric_clique(4, activation_delay=20.0,
                                  propagation_delay=1.0)
    out = run_graphml(netlib.to_graphml(net), protocol="nakamoto",
                      activations=200, seed=2)
    assert "vertex" in out and "run_protocol" in out
    out2 = run_graphml(netlib.to_graphml(net), protocol="bk-4-constant",
                       activations=200, seed=2)
    assert "vertex" in out2


def test_visualize_dot():
    dot = visualize("nakamoto", activations=12, n_nodes=3, seed=4)
    assert dot.startswith("digraph") and dot.count("->") >= 12


def test_safety_bound_between_analytical_bounds():
    """Monte-Carlo violation rate of the rigged model sits between the
    Guo-Ren lower and upper bounds (bounds.ml's comparison)."""
    k, alpha, lam, delta = 4, 0.2, 0.2, 1.0
    x = GR22Params(k=k, delta=delta, lam=lam, rho=1.0 - alpha)
    mc = violation_rate(k=k, alpha=alpha, lam=lam, delta=delta,
                        episodes=3000, seed=5)
    assert t1lower(x) * 0.1 <= mc <= t1upper(x), \
        (t1lower(x), mc, t1upper(x))
    # deeper confirmation -> safer
    mc8 = violation_rate(k=8, alpha=alpha, lam=lam, delta=delta,
                         episodes=3000, seed=6)
    assert mc8 <= mc
