"""gymnasium plugin boundary tests.

Mirrors the reference's gym tests (gym/ocaml/test/test_envs.py:24-40
check_env + wrapper behaviours; test_protocols.py policy runs) against
the JAX engine: registered ids construct, the env contract holds, built-in
policies run through the gym surface, and every wrapper behaves.
"""

import gymnasium
import numpy as np
import pytest
from gymnasium.utils.env_checker import check_env

import cpr_tpu.gym  # noqa: F401  (import registers the env ids)
from cpr_tpu.gym import BatchedCore, Core, env_fn, wrappers


def test_env_ids_registered():
    for eid in ("core-v0", "cpr-v0", "cpr-nakamoto-v0", "cpr-tailstorm-v0"):
        assert eid in gymnasium.envs.registry


def test_check_env_core():
    check_env(Core("nakamoto", max_steps=32), skip_render_check=True)


def test_check_env_composed():
    env = gymnasium.make("cpr-nakamoto-v0", episode_len=32)
    check_env(env.unwrapped, skip_render_check=True)


def test_core_requires_termination_criterion():
    with pytest.raises(Exception, match="max_steps"):
        Core("nakamoto")


def test_honest_policy_through_gym_surface():
    """Honest policy earns ~alpha relative reward (the reference's
    test_protocols.py pattern, run through gym)."""
    alpha = 0.3
    env = Core("nakamoto", alpha=alpha, gamma=0.5, max_steps=256, seed=4)
    rels = []
    for ep in range(8):
        obs, _ = env.reset()
        while True:
            obs, r, term, trunc, info = env.step(env.policy(obs, "honest"))
            if term or trunc:
                a = info["episode_reward_attacker"]
                d = info["episode_reward_defender"]
                rels.append(a / (a + d))
                break
    assert abs(np.mean(rels) - alpha) < 0.08, np.mean(rels)


def test_policy_name_error():
    env = Core("nakamoto", max_steps=16)
    obs, _ = env.reset()
    with pytest.raises(ValueError, match="not a valid policy"):
        env.policy(obs, "no-such-policy")


def test_sparse_relative_wrapper():
    env = wrappers.SparseRelativeRewardWrapper(
        Core("nakamoto", alpha=0.25, max_steps=64, seed=0))
    obs, _ = env.reset()
    rewards = []
    while True:
        obs, r, term, trunc, info = env.step(env.env.policy(obs, "honest"))
        rewards.append(r)
        if term or trunc:
            break
    assert all(r == 0.0 for r in rewards[:-1])
    a = info["episode_reward_attacker"]
    d = info["episode_reward_defender"]
    assert rewards[-1] == pytest.approx(a / (a + d))


def test_assumption_schedule_cycles_and_extends_obs():
    alphas = [0.1, 0.2, 0.3]
    env = wrappers.AssumptionScheduleWrapper(
        Core("nakamoto", max_steps=8, seed=1), alpha=alphas, gamma=0.5)
    seen = []
    for _ in range(6):
        obs, _ = env.reset()
        assert obs.shape[-1] == 6  # 4 fields + alpha + gamma
        assert obs[-2] == pytest.approx(env.asw_alpha)
        assert obs[-1] == pytest.approx(0.5)
        obs, r, term, trunc, info = env.step(0)
        assert info["alpha"] == env.asw_alpha
        seen.append(env.asw_alpha)
    assert seen == [0.1, 0.2, 0.3, 0.1, 0.2, 0.3]
    # env params actually track the schedule
    assert float(env.unwrapped.params.alpha) == pytest.approx(env.asw_alpha)


def test_pretend_assumptions_mask_observation_only():
    env = wrappers.AssumptionScheduleWrapper(
        Core("nakamoto", max_steps=8), alpha=0.3, gamma=0.5,
        pretend_alpha=0.45)
    obs, _ = env.reset()
    assert obs[-2] == pytest.approx(0.45)  # shown
    assert float(env.unwrapped.params.alpha) == pytest.approx(0.3)  # real


def test_extend_observation_wrapper():
    fields = [(lambda w, i: i["episode_progress"], 0.0, np.inf, -1.0)]
    env = wrappers.ExtendObservationWrapper(
        Core("nakamoto", max_steps=8), fields)
    obs, _ = env.reset()
    assert obs[-1] == -1.0
    obs, *_ = env.step(0)
    assert obs.shape[-1] == 5
    # policy dispatch strips the extension
    env.policy(obs, "honest")


def test_episode_recorder_and_clear_info():
    env = wrappers.EpisodeRecorderWrapper(
        wrappers.ClearInfoWrapper(
            wrappers.SparseRelativeRewardWrapper(
                Core("nakamoto", alpha=0.3, max_steps=16, seed=2)),
            keep_keys=("episode_reward_attacker",
                       "episode_reward_defender")),
        n=4, info_keys=("episode_reward_attacker",))
    obs, _ = env.reset()
    for _ in range(3):
        while True:
            obs, r, term, trunc, info = env.step(0)
            assert set(info) <= {"episode_reward_attacker",
                                 "episode_reward_defender"}
            if term or trunc:
                obs, _ = env.reset()
                break
    assert len(env.erw_history) == 3
    assert all("episode_reward" in e for e in env.erw_history)


def test_dense_per_progress_accumulates_to_sparse_objective():
    """Dense rewards accumulate (after the end-of-episode mismatch fix)
    to exactly the sparse per-progress objective of the same episode:
    episode_reward_attacker / episode_progress (wrappers.py:54-113)."""
    dense = env_fn(protocol="nakamoto", episode_len=32, alpha=0.3,
                   gamma=0.5, reward="dense_per_progress",
                   normalize_reward=False, seed=7)
    obs, _ = dense.reset(seed=11)
    total = 0.0
    while True:
        obs, r, term, trunc, info = dense.step(dense.policy(obs, "honest"))
        total += r
        if term or trunc:
            break
    assert info["episode_progress"] > 0
    assert total == pytest.approx(
        info["episode_reward_attacker"] / info["episode_progress"],
        rel=1e-6)


def test_batched_core_auto_resets():
    env = BatchedCore("nakamoto", n_envs=32, alpha=0.33, gamma=0.5,
                      max_steps=16, seed=3)
    obs, _ = env.reset()
    assert obs.shape == (32, 4)
    dones = 0
    for _ in range(40):
        obs, r, done, trunc, info = env.step(np.zeros(32, np.int64))
        dones += int(done.sum())
    assert dones > 0  # lanes terminated and auto-reset
    assert obs.shape == (32, 4)


def test_env_fn_reward_normalization():
    env = env_fn(protocol="nakamoto", episode_len=16, alpha=0.4,
                 reward="sparse_relative", normalize_reward=True)
    obs, _ = env.reset()
    while True:
        obs, r, term, trunc, info = env.step(0)
        if term or trunc:
            break
    # normalized: raw relative reward divided by alpha
    assert r == pytest.approx(
        (info["episode_reward_attacker"]
         / max(info["episode_reward_attacker"]
               + info["episode_reward_defender"], 1e-12)) / 0.4)
