"""v15 attribution-plane tests: the run archive
(cpr_tpu/perf/archive.py), the span-level trace diff
(tools/trace_diff.py), live memory watermarks
(telemetry.MemoryWatermark), and the ledger/gate provenance that ties
them together — `run` on every banked row, `run`/`baseline_runs` on
every verdict, `<scope>_peak_bytes` capacity rows.

`make obs-smoke` proves the same chain end-to-end against a real
supervised server pair; these tests pin the pieces in isolation.
"""

import importlib.util
import json
import os
import sys

import pytest

from cpr_tpu import telemetry
from cpr_tpu.perf import archive
from cpr_tpu.perf.gate import emit_gate_event, gate_row
from cpr_tpu.perf.ledger import Ledger

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, REPO)


def _load_tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _manifest(run, config=None, **extra):
    return dict({"kind": "manifest", "schema": telemetry.SCHEMA_VERSION,
                 "run": run, "backend": "cpu", "git_sha": "deadbeef01",
                 "time_utc": "2026-08-07T00:00:00+00:00",
                 "config": config if config is not None else {"n": 512}},
                **extra)


def _span(path, dur_s, **counters):
    name = path.rsplit("/", 1)[-1]
    e = {"kind": "span", "name": name, "path": path,
         "depth": path.count("/"), "t_start": 0.0, "t_end": dur_s,
         "dur_s": dur_s}
    if counters:
        e["counters"] = dict(counters)
        e["per_sec"] = {k: v / dur_s for k, v in counters.items()}
    return e


def _write_trace(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return str(path)


# -- run archive -------------------------------------------------------------


def test_archive_roundtrip_merge_and_query(tmp_path):
    root = str(tmp_path / "arch")
    t1 = _write_trace(tmp_path / "server.jsonl",
                      [_manifest("run-aaaa"), _span("tick", 0.5)])
    rec = archive.archive_run(paths=[t1], root=root,
                              roles={t1: "server"}, label="first")
    assert rec["run"] == "run-aaaa"
    assert rec["git_sha"] == "deadbeef01" and rec["backend"] == "cpu"
    assert rec["fingerprint"] == archive.config_fingerprint({"n": 512})
    (art,) = rec["artifacts"]
    assert art["kind"] == archive.KIND_TELEMETRY
    assert art["role"] == "server" and art["n_spans"] == 1

    # re-archiving the same artifact converges; a new one merges in
    t2 = _write_trace(tmp_path / "client.jsonl",
                      [_manifest("run-aaaa"), _span("req", 0.1)])
    rec2 = archive.archive_run(paths=[t1, t2], root=root)
    assert {a["path"] for a in rec2["artifacts"]} == {t1, t2}
    assert rec2["label"] == "first"  # carried from the prior record

    loaded = archive.load_run("run-aaaa", root)
    assert loaded == rec2
    assert archive.load_run("no-such-run", root) is None

    # the query side: git-sha prefix, fingerprint, time window
    assert [r["run"] for r in archive.find_runs(root)] == ["run-aaaa"]
    assert archive.find_runs(root, git_sha="deadbe")
    assert archive.find_runs(
        root, fingerprint=archive.config_fingerprint({"n": 512}))
    assert not archive.find_runs(root, git_sha="feedface")
    assert archive.find_runs(root, since="2026-08-01",
                             until="2026-08-31")
    assert not archive.find_runs(root, until="2026-01-01")

    # the audit index appended one line per archive_run call
    with open(archive.index_path(root)) as f:
        idx = [json.loads(ln) for ln in f]
    assert len(idx) == 2 and all(i["run"] == "run-aaaa" for i in idx)


def test_archive_discovery_and_primary_stream(tmp_path):
    root = str(tmp_path / "arch")
    scratch = tmp_path / "scratch"
    scratch.mkdir()
    child = _write_trace(scratch / "child.jsonl",
                         [_manifest("run-bbbb"), _span("a", 0.1),
                          _span("b", 0.1), _span("c", 0.1)])
    other = _write_trace(scratch / "other.jsonl",
                         [_manifest("run-zzzz"), _span("x", 0.1)])
    server = _write_trace(tmp_path / "server.jsonl",
                          [_manifest("run-bbbb"), _span("tick", 0.5)])
    rec = archive.archive_run(paths=[server], root=root,
                              roles={server: "server"},
                              search_dirs=[str(scratch)])
    got = {a["path"] for a in rec["artifacts"]}
    assert got == {server, child}  # other run's stream NOT swept in
    assert other not in got
    # role "server" outranks the span-richer unlabeled child stream
    assert archive.primary_stream(rec) == server
    assert set(archive.run_streams(rec)) == {server, child}
    assert archive.run_streams(rec, role="server") == [server]


def test_archive_requires_a_run_id(tmp_path):
    bare = _write_trace(tmp_path / "bare.jsonl", [_span("tick", 0.1)])
    with pytest.raises(ValueError, match="no run id"):
        archive.archive_run(paths=[bare], root=str(tmp_path / "a"))
    # explicit run= resolves it
    rec = archive.archive_run(paths=[bare], run="run-cccc",
                              root=str(tmp_path / "a"))
    assert rec["run"] == "run-cccc"


def test_archive_env_override(tmp_path, monkeypatch):
    monkeypatch.setenv(archive.ARCHIVE_ENV_VAR, str(tmp_path / "env"))
    assert archive.archive_dir() == str(tmp_path / "env")
    assert archive.archive_dir("/explicit") == "/explicit"
    monkeypatch.delenv(archive.ARCHIVE_ENV_VAR)
    assert archive.archive_dir() == archive.DEFAULT_ARCHIVE_DIR


# -- span-level trace diff ---------------------------------------------------


def _ab_traces(tmp_path, stall=0.8):
    """Baseline and candidate: same shape, candidate's `tick/burst`
    grew by `stall` seconds of pure self time."""
    base = _write_trace(tmp_path / "a.jsonl", [
        _manifest("run-base"),
        _span("tick/burst", 0.1, env_steps=1000),
        _span("tick", 0.15),
        {"kind": "event", "name": "memory", "scope": "serve",
         "peak_bytes": 1000, "source": "rss"},
    ])
    cand = _write_trace(tmp_path / "b.jsonl", [
        _manifest("run-cand"),
        _span("tick/burst", 0.1 + stall, env_steps=1000),
        _span("tick", 0.15 + stall),
        _span("drain", 0.02),  # only in the candidate
        {"kind": "event", "name": "memory", "scope": "serve",
         "peak_bytes": 3000, "source": "rss"},
    ])
    return base, cand


def test_trace_diff_blames_self_time_not_ancestors(tmp_path):
    td = _load_tool("trace_diff")
    base, cand = _ab_traces(tmp_path)
    result = td.diff(td.collect(td.read_events([base])),
                     td.collect(td.read_events([cand])))
    top = result["culprits"][0]
    # the leaf that actually ate the time wins; the parent's self time
    # is unchanged (its growth is all in the child), so it ranks below
    assert top["path"] == "tick/burst"
    assert top["d_self_s"] == pytest.approx(0.8)
    assert top["share_of_delta"] == pytest.approx(1.0, abs=0.1)
    parent = next(r for r in result["culprits"]
                  if r["path"] == "tick")
    assert parent["d_self_s"] == pytest.approx(0.0, abs=1e-9)
    # end-to-end sums ROOT spans only (tick + drain, not the child)
    assert result["end_to_end_s"]["baseline"] == pytest.approx(0.15)
    assert result["end_to_end_s"]["candidate"] == pytest.approx(0.97)
    only = next(r for r in result["culprits"] if r["path"] == "drain")
    assert only["only_in"] == "candidate"
    assert result["overlap"] == 2
    # the satellite planes ride the same diff
    (mem,) = result["memory"]
    assert mem["scope"] == "serve"
    assert (mem["baseline_peak_bytes"],
            mem["candidate_peak_bytes"]) == (1000, 3000)
    rate = next(r for r in result["rates"]
                if r["counter"] == "tick/burst:env_steps")
    assert rate["pct"] < -80  # the stall cratered the span rate


def test_trace_diff_resolves_archived_run_ids(tmp_path, capsys):
    td = _load_tool("trace_diff")
    root = str(tmp_path / "arch")
    base, cand = _ab_traces(tmp_path)
    archive.archive_run(paths=[base], root=root)
    archive.archive_run(paths=[cand], root=root)
    bl, cl, result = td.run_diff("run-base", "run-cand", root)
    assert (bl, cl) == ("run-base", "run-cand")
    assert result["culprits"][0]["path"] == "tick/burst"
    # CLI: overlapping sides exit 0 and print the culprit table
    assert td.main([base, cand]) == 0
    out = capsys.readouterr().out
    assert "tick/burst" in out
    # an unknown archive run is a usage error
    with pytest.raises(SystemExit):
        td.resolve_side("no-such-run", root)


def test_trace_diff_no_overlap_exits_1(tmp_path, capsys):
    td = _load_tool("trace_diff")
    a = _write_trace(tmp_path / "x.jsonl",
                     [_manifest("r1"), _span("alpha", 0.1)])
    b = _write_trace(tmp_path / "y.jsonl",
                     [_manifest("r2"), _span("beta", 0.1)])
    assert td.main([a, b]) == 1
    capsys.readouterr()


# -- memory watermarks -------------------------------------------------------


def test_memory_watermark_samples_and_emits_valid_event(tmp_path):
    sink = tmp_path / "mem.jsonl"
    tele = telemetry.Telemetry(str(sink))
    tele.emit(telemetry.run_manifest())
    with telemetry.memory_watermark("vi", tele,
                                    predicted_bytes=4096) as wm:
        wm.sample()
    tele.close()
    # on the CPU CI host the RSS fallback must keep the plane alive
    assert wm.n_samples >= 3  # enter + explicit + exit
    assert wm.source in ("device", "rss")
    assert wm.peak_bytes and wm.peak_bytes > 0
    assert wm.in_use_bytes and wm.in_use_bytes <= wm.peak_bytes
    assert wm.delta_bytes is not None
    snap = wm.snapshot()
    assert snap["scope"] == "vi" and snap["peak_bytes"] == wm.peak_bytes
    events = [json.loads(ln) for ln in open(sink)]
    (mem,) = [e for e in events if e.get("name") == "memory"]
    for field in telemetry.EVENT_FIELDS["memory"]:
        assert field in mem, f"memory event lacks {field}"
    assert mem["scope"] == "vi" and mem["predicted_bytes"] == 4096
    # the full stream validates with the expectation asserted
    ts = _load_tool("trace_summary")
    read, bad = ts.read_events(str(sink))
    assert ts.validate(read, bad, expect=("memory",)) == []


def test_memory_watermark_emits_even_on_exception(tmp_path):
    sink = tmp_path / "crash.jsonl"
    tele = telemetry.Telemetry(str(sink))
    with pytest.raises(RuntimeError, match="boom"):
        with telemetry.memory_watermark("mdp_compile", tele):
            raise RuntimeError("boom")
    tele.close()
    events = [json.loads(ln) for ln in open(sink)]
    (mem,) = [e for e in events if e.get("name") == "memory"]
    assert mem["scope"] == "mdp_compile"


def test_device_memory_stats_rss_fallback_is_tagged():
    stats = telemetry.device_memory_stats()
    assert stats, "no memory source at all on this host"
    for dev, ms in stats.items():
        if ms.get("source") == "rss":
            assert dev == "process:rss"
            assert ms["peak_bytes_in_use"] >= ms["bytes_in_use"] > 0
        else:  # a real allocator entry stays untagged
            assert "source" not in ms


def test_process_memory_orders_rss_and_peak():
    pm = telemetry.process_memory()
    assert pm is not None
    rss, peak = pm
    assert 0 < rss <= peak


# -- ledger v5 provenance + capacity rows ------------------------------------


def _ledger_trace(tmp_path, name, run, peak, p99=0.02):
    return _write_trace(tmp_path / name, [
        _manifest(run),
        {"kind": "event", "name": "memory", "scope": "vi",
         "peak_bytes": peak, "in_use_bytes": peak // 2,
         "source": "rss", "n_samples": 3},
        {"kind": "event", "name": "serve", "action": "report",
         "session": None,
         "detail": {"steps_per_sec": 1e5, "occupancy": 0.9,
                    "p50_s": 0.01, "p99_s": p99, "n_devices": 1}},
    ])


def test_ledger_v5_stamps_run_and_lifts_memory_rows(tmp_path):
    ledger = Ledger(str(tmp_path / "ledger.jsonl"))
    ledger.ingest_trace(
        _ledger_trace(tmp_path, "a.jsonl", "run-base", peak=1 << 20))
    ledger.ingest_trace(
        _ledger_trace(tmp_path, "b.jsonl", "run-cand", peak=1 << 21))
    records = ledger.records()
    assert all(r["run"] in ("run-base", "run-cand") for r in records)
    mem_rows = [r for r in records if r["metric"] == "vi_peak_bytes"]
    assert len(mem_rows) == 2
    for r in mem_rows:
        assert r["direction"] == "lower" and r["unit"] == "bytes"
        assert r["config"]["cfg_mem_source"] == "rss"
    # run is provenance, NOT config: both runs share a fingerprint,
    # which is exactly what lets them gate against each other
    assert mem_rows[0]["fingerprint"] == mem_rows[1]["fingerprint"]
    assert mem_rows[0]["row_id"] != mem_rows[1]["row_id"]


def test_gate_carries_run_and_baseline_runs(tmp_path):
    ledger = Ledger(str(tmp_path / "ledger.jsonl"))
    ledger.ingest_trace(
        _ledger_trace(tmp_path, "a.jsonl", "run-base", peak=1 << 20))
    ledger.ingest_trace(_ledger_trace(tmp_path, "b.jsonl", "run-cand",
                                      peak=1 << 20, p99=0.5))
    records = ledger.records()
    cand = next(r for r in records if r["metric"] == "serve_p99_s"
                and r["run"] == "run-cand")
    res = gate_row(cand, records)
    assert res["verdict"] == "fail"  # 0.5s vs 0.02s, lower-is-better
    assert res["run"] == "run-cand"
    assert res["baseline_runs"] == ["run-base"]
    assert res["baseline"]["best_run"] == "run-base"
    # the emitted perf_gate event satisfies its own v15 schema
    sink = tmp_path / "gate.jsonl"
    tele = telemetry.configure(str(sink))
    try:
        emit_gate_event(res)
    finally:
        telemetry.configure(None)
    (ev,) = [json.loads(ln) for ln in open(sink)]
    for field in telemetry.EVENT_FIELDS["perf_gate"]:
        assert field in ev, f"perf_gate event lacks {field}"
    assert ev["run"] == "run-cand"
    assert ev["baseline_runs"] == ["run-base"]


def test_memory_rows_gate_lower_is_better(tmp_path):
    ledger = Ledger(str(tmp_path / "ledger.jsonl"))
    ledger.ingest_trace(
        _ledger_trace(tmp_path, "a.jsonl", "run-base", peak=1 << 20))
    ledger.ingest_trace(_ledger_trace(tmp_path, "b.jsonl", "run-cand",
                                      peak=(1 << 20) * 2))
    records = ledger.records()
    cand = next(r for r in records if r["metric"] == "vi_peak_bytes"
                and r["run"] == "run-cand")
    res = gate_row(cand, records)
    # a 2x working-set jump fails exactly like a 2x latency jump
    assert res["verdict"] == "fail" and res["direction"] == "lower"
    assert res["baseline_runs"] == ["run-base"]
