"""Statistical validation of the collapsed Nakamoto SSZ env.

Mirrors the reference's test strategy of stochastic integration tests with
closed-form expectations (cpr_protocols.ml:200-477) and the cross-model
validation of MDP models against literature results (mdp/lib/models/
fc16sapirshtein.py, aft20barzur_test.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu.envs.nakamoto import NakamotoSSZ, ADOPT, OVERRIDE, MATCH, WAIT
from cpr_tpu.params import make_params


def es2014_revenue(alpha, gamma):
    """Closed-form relative revenue of the ES'14/SM1 selfish-mining strategy
    (Eyal & Sirer 2014, eq. 8)."""
    a, g = alpha, gamma
    return (a * (1 - a) ** 2 * (4 * a + g * (1 - 2 * a)) - a**3) / (
        1 - a * (1 + (2 - a) * a)
    )


def run_policy(env, policy_name, alpha, gamma, n_envs=512, n_steps=768,
               episode_steps=128, seed=0):
    params = make_params(alpha=alpha, gamma=gamma, max_steps=episode_steps)
    policy = env.policies[policy_name]
    keys = jax.random.split(jax.random.PRNGKey(seed), n_envs)
    stats = jax.vmap(lambda k: env.episode_stats(k, params, policy, n_steps))(keys)
    atk = np.asarray(stats["episode_reward_attacker"])
    dfn = np.asarray(stats["episode_reward_defender"])
    return atk.mean() / (atk.mean() + dfn.mean())


@pytest.fixture(scope="module")
def env():
    return NakamotoSSZ(unit_observation=True)


def test_obs_roundtrip(env):
    params = make_params(alpha=0.3, gamma=0.5, max_steps=64)
    state, obs = env.reset(jax.random.PRNGKey(0), params)
    assert obs.shape == (4,)
    assert np.all(np.asarray(obs) >= env.low - 1e-6)
    assert np.all(np.asarray(obs) <= env.high + 1e-6)
    h, a, diff, event = env.decode_obs(obs)
    assert int(a) + int(h) == 1  # exactly one block after the first draw
    assert int(diff) == int(a) - int(h)


def test_step_smoke(env):
    params = make_params(alpha=0.3, gamma=0.5, max_steps=8)
    state, obs = env.reset(jax.random.PRNGKey(1), params)
    step = jax.jit(env.step)
    for action in [WAIT, MATCH, OVERRIDE, ADOPT, WAIT, WAIT, WAIT, WAIT]:
        state, obs, reward, done, info = step(state, jnp.int32(action), params)
    assert bool(done)  # max_steps = 8 reached
    assert np.isfinite(float(reward))
    assert float(info["episode_n_steps"]) == 8
    # info contract mirrors the reference step info list (engine.ml:224-241)
    from cpr_tpu.envs.base import INFO_KEYS
    assert set(info) == set(INFO_KEYS)


def test_honest_policy_yields_alpha(env):
    # honest behaviour earns exactly the compute share in expectation
    # (reference battery "policy", cpr_protocols.ml:478-657)
    for alpha in [0.1, 0.3, 0.45]:
        rel = run_policy(env, "honest", alpha, 0.5)
        assert abs(rel - alpha) < 0.015, (alpha, rel)


def test_sm1_matches_eyal_sirer_closed_form(env):
    # SM1 == ES'14 strategy; its revenue has a closed form. High alpha needs
    # longer episodes: private leads grow long and truncation biases the
    # relative reward down (fork still live at episode end).
    for alpha, gamma, ep in [(0.3, 0.0, 256), (0.35, 0.5, 256),
                             (0.4, 0.9, 512), (0.45, 0.5, 1024)]:
        want = es2014_revenue(alpha, gamma)
        got = run_policy(env, "sapirshtein-2016-sm1", alpha, gamma,
                         n_envs=768, n_steps=ep + ep // 4, episode_steps=ep)
        assert abs(got - want) < 0.02, (alpha, gamma, want, got)


def test_selfish_mining_unprofitable_below_threshold(env):
    # with gamma=0 the ES'14 profitability threshold is alpha = 1/3
    rel = run_policy(env, "sapirshtein-2016-sm1", 0.25, 0.0)
    assert rel < 0.25 + 0.01


def test_policies_return_valid_actions(env):
    params = make_params(alpha=0.45, gamma=0.9, max_steps=64)
    for name, policy in env.policies.items():
        traj = env.rollout(jax.random.PRNGKey(3), params, policy, 256)
        actions = np.asarray(traj[1])
        assert actions.min() >= 0 and actions.max() < env.n_actions, name


def test_termination_by_progress(env):
    params = make_params(alpha=0.3, gamma=0.5, max_progress=16.0)
    state, obs = env.reset(jax.random.PRNGKey(4), params)
    done = jnp.bool_(False)
    for _ in range(512):
        state, obs, r, done, info = env.step(state, jnp.int32(WAIT), params)
        if bool(done):
            break
    assert bool(done)
    assert float(info["episode_progress"]) >= 16.0
