"""cpr_tpu.latency: the histogram/quantile math behind the serving SLO
plane.  Jax-free host code, so these are plain-math tests: quantile
estimates are checked against true sample quantiles within the ~7%
log-bucket error the module documents, and the degenerate shapes
(empty, single-sample, underflow/overflow, clock skew) are pinned.
"""

import json
import math

import pytest

from cpr_tpu.latency import (OVERFLOW_FAMILY, LatencyBoard,
                             LatencyHistogram, default_edges)


def test_default_edges_are_log_uniform_and_span_the_range():
    edges = default_edges()
    assert edges[0] == pytest.approx(1e-6)
    assert edges[-1] == pytest.approx(1e3)
    ratios = [b / a for a, b in zip(edges, edges[1:])]
    assert all(r == pytest.approx(ratios[0], rel=1e-9) for r in ratios)
    with pytest.raises(ValueError, match="increasing"):
        LatencyHistogram((1.0, 1.0, 2.0))
    with pytest.raises(ValueError, match="increasing"):
        LatencyHistogram(())


def test_empty_histogram_is_honest():
    h = LatencyHistogram()
    assert h.quantile(0.5) is None
    assert h.snapshot() == {"count": 0}
    with pytest.raises(ValueError, match="quantile"):
        LatencyHistogram().quantile(1.5)


def test_single_sample_reports_the_sample_not_a_bucket_edge():
    h = LatencyHistogram()
    h.observe(0.0123)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(q) == pytest.approx(0.0123)
    snap = h.snapshot()
    assert snap["count"] == 1
    assert snap["mean_s"] == snap["min_s"] == snap["max_s"] \
        == pytest.approx(0.0123)


def test_quantiles_track_true_sample_quantiles_within_bucket_error():
    h = LatencyHistogram()
    # log-uniform samples over 1ms..100ms: true q-quantile is
    # 10**(-3 + 2q); the estimate must stay inside the documented ~7%
    samples = [10.0 ** (-3.0 + 2.0 * i / 999.0) for i in range(1000)]
    for s in samples:
        h.observe(s)
    for q in (0.10, 0.50, 0.95, 0.99):
        true = 10.0 ** (-3.0 + 2.0 * q)
        assert h.quantile(q) == pytest.approx(true, rel=0.08), q
    # quantiles are monotone in q
    qs = [h.quantile(q / 20.0) for q in range(21)]
    assert qs == sorted(qs)
    snap = h.snapshot()
    assert snap["p50_s"] <= snap["p95_s"] <= snap["p99_s"]
    assert snap["count"] == 1000
    assert snap["mean_s"] == pytest.approx(sum(samples) / 1000.0)


def test_clock_skew_and_out_of_range_observations_never_corrupt():
    h = LatencyHistogram()
    h.observe(-0.5)  # skewed stamps clamp to 0
    h.observe(float("nan"))  # skipped outright
    h.observe(float("inf"))
    h.observe(1e-9)  # underflow bucket
    h.observe(1e9)  # overflow bucket
    assert h.count == 3
    assert h.min_s == 0.0 and h.max_s == 1e9
    # estimates stay inside the observed range even for the open-ended
    # under/overflow buckets
    assert 0.0 <= h.quantile(0.01) <= h.quantile(0.99) <= 1e9


def test_merge_sums_counts_and_rejects_differing_edges():
    a, b = LatencyHistogram(), LatencyHistogram()
    for v in (0.001, 0.002, 0.004):
        a.observe(v)
    for v in (0.008, 0.016):
        b.observe(v)
    a.merge(b)
    assert a.count == 5
    assert a.min_s == 0.001 and a.max_s == 0.016
    assert a.sum_s == pytest.approx(0.031)
    with pytest.raises(ValueError, match="differing edges"):
        a.merge(LatencyHistogram((0.1, 1.0)))


def test_board_is_lazy_per_family_and_json_ready():
    board = LatencyBoard()
    assert board.families == () and board.snapshot() == {}
    board.observe("episode.run", 0.5)
    board.observe("episode.run", 0.7)
    board.observe("device.splice", 0.001)
    assert board.families == ("device.splice", "episode.run")
    assert board.get("episode.run").count == 2
    assert board.get("nope") is None
    snap = board.snapshot()
    assert set(snap) == {"device.splice", "episode.run"}
    assert snap["episode.run"]["count"] == 2
    assert 0.5 <= snap["episode.run"]["p99_s"] <= 0.7
    json.dumps(snap)  # the stats/heartbeat/report embedding
    assert all(math.isfinite(v) for v in snap["episode.run"].values())


def test_board_family_cardinality_is_bounded():
    """Satellite 2: unbounded family names (a tenant id or trace id
    leaking into the family string) must not grow the board without
    limit — novel families past the cap pool into OVERFLOW_FAMILY,
    while already-minted families keep observing normally."""
    board = LatencyBoard(max_families=3)
    for i in range(3):
        board.observe(f"fam{i}", 0.01)
    assert len(board.families) == 3
    # the flood: 50 novel names all land in the one overflow family
    for i in range(50):
        board.observe(f"leak-{i}", 0.02)
    fams = board.families
    assert len(fams) == 4  # 3 real + overflow, never 53
    assert OVERFLOW_FAMILY in fams
    assert board.get(OVERFLOW_FAMILY).count == 50
    assert board.get("leak-7") is None
    # established families are unaffected by the flood
    board.observe("fam1", 0.03)
    assert board.get("fam1").count == 2
    snap = board.snapshot()
    assert snap[OVERFLOW_FAMILY]["count"] == 50
    with pytest.raises(ValueError, match="max_families"):
        LatencyBoard(max_families=0)


# -- the v14 mergeable wire form (fleet latency merge) -----------------------


def test_histogram_wire_roundtrip_is_exact():
    """to_dict/from_dict round-trips counts, moments, and quantiles
    bit-for-bit: the fleet merge is bucket-sum arithmetic, not a
    quantile-of-quantiles approximation."""
    h = LatencyHistogram()
    for v in (0.001, 0.002, 0.004, 0.3, 12.0):
        h.observe(v)
    raw = h.to_dict()
    json.dumps(raw)  # it rides the metrics.scrape reply
    assert raw["n_edges"] == len(h.edges)
    assert raw["count"] == 5 and raw["min_s"] == 0.001
    assert sum(c for _, c in raw["buckets"]) == 5
    back = LatencyHistogram.from_dict(raw)
    assert back.snapshot() == h.snapshot()
    assert back.counts == h.counts
    # the empty histogram round-trips honestly: no fake extrema
    empty = LatencyHistogram.from_dict(LatencyHistogram().to_dict())
    assert empty.count == 0 and empty.quantile(0.5) is None
    assert LatencyHistogram().to_dict()["min_s"] is None


def test_from_dict_rejects_corrupt_wire_forms():
    """A silent wire-form misalignment would corrupt every fleet
    quantile downstream, so each inconsistency is a hard error."""
    good = LatencyHistogram()
    good.observe(0.01)
    raw = good.to_dict()
    with pytest.raises(ValueError, match="edges"):
        LatencyHistogram.from_dict(dict(raw, n_edges=7))
    with pytest.raises(ValueError, match="out of range"):
        LatencyHistogram.from_dict(
            dict(raw, buckets=[[10**6, 1]]))
    with pytest.raises(ValueError, match="negative"):
        LatencyHistogram.from_dict(dict(raw, buckets=[[0, -1]]))
    with pytest.raises(ValueError, match="header says"):
        LatencyHistogram.from_dict(dict(raw, count=99))


def test_board_merge_dict_is_exact_bucket_sum():
    """The router's fleet merge: two replicas' boards combined through
    the wire form equal one board that saw every observation."""
    rep_a, rep_b, direct = (LatencyBoard() for _ in range(3))
    obs_a = [("episode.run", 0.01), ("episode.run", 0.04),
             ("stats", 0.001)]
    obs_b = [("episode.run", 0.02), ("netsim.query", 0.2)]
    for fam, v in obs_a:
        rep_a.observe(fam, v)
        direct.observe(fam, v)
    for fam, v in obs_b:
        rep_b.observe(fam, v)
        direct.observe(fam, v)
    fleet = LatencyBoard()
    fleet.merge_dict(rep_a.to_dict())
    fleet.merge_dict(rep_b.to_dict())
    assert fleet.snapshot() == direct.snapshot()
    assert fleet.get("episode.run").count == 3


def test_board_merge_dict_folds_novel_families_into_overflow():
    """A hostile (or just chatty) replica payload cannot blow up
    router memory: families novel past max_families merge into
    OVERFLOW_FAMILY — counted there, never dropped."""
    fleet = LatencyBoard(max_families=2)
    fleet.observe("a", 0.01)
    fleet.observe("b", 0.01)
    payload = LatencyBoard()
    for fam in ("a", "c", "d"):
        payload.observe(fam, 0.02)
    fleet.merge_dict(payload.to_dict())
    assert set(fleet.families) == {"a", "b", OVERFLOW_FAMILY}
    assert fleet.get("a").count == 2  # existing families merge home
    assert fleet.get(OVERFLOW_FAMILY).count == 2  # c + d pooled
