"""cpr_tpu.latency: the histogram/quantile math behind the serving SLO
plane.  Jax-free host code, so these are plain-math tests: quantile
estimates are checked against true sample quantiles within the ~7%
log-bucket error the module documents, and the degenerate shapes
(empty, single-sample, underflow/overflow, clock skew) are pinned.
"""

import json
import math

import pytest

from cpr_tpu.latency import (OVERFLOW_FAMILY, LatencyBoard,
                             LatencyHistogram, default_edges)


def test_default_edges_are_log_uniform_and_span_the_range():
    edges = default_edges()
    assert edges[0] == pytest.approx(1e-6)
    assert edges[-1] == pytest.approx(1e3)
    ratios = [b / a for a, b in zip(edges, edges[1:])]
    assert all(r == pytest.approx(ratios[0], rel=1e-9) for r in ratios)
    with pytest.raises(ValueError, match="increasing"):
        LatencyHistogram((1.0, 1.0, 2.0))
    with pytest.raises(ValueError, match="increasing"):
        LatencyHistogram(())


def test_empty_histogram_is_honest():
    h = LatencyHistogram()
    assert h.quantile(0.5) is None
    assert h.snapshot() == {"count": 0}
    with pytest.raises(ValueError, match="quantile"):
        LatencyHistogram().quantile(1.5)


def test_single_sample_reports_the_sample_not_a_bucket_edge():
    h = LatencyHistogram()
    h.observe(0.0123)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(q) == pytest.approx(0.0123)
    snap = h.snapshot()
    assert snap["count"] == 1
    assert snap["mean_s"] == snap["min_s"] == snap["max_s"] \
        == pytest.approx(0.0123)


def test_quantiles_track_true_sample_quantiles_within_bucket_error():
    h = LatencyHistogram()
    # log-uniform samples over 1ms..100ms: true q-quantile is
    # 10**(-3 + 2q); the estimate must stay inside the documented ~7%
    samples = [10.0 ** (-3.0 + 2.0 * i / 999.0) for i in range(1000)]
    for s in samples:
        h.observe(s)
    for q in (0.10, 0.50, 0.95, 0.99):
        true = 10.0 ** (-3.0 + 2.0 * q)
        assert h.quantile(q) == pytest.approx(true, rel=0.08), q
    # quantiles are monotone in q
    qs = [h.quantile(q / 20.0) for q in range(21)]
    assert qs == sorted(qs)
    snap = h.snapshot()
    assert snap["p50_s"] <= snap["p95_s"] <= snap["p99_s"]
    assert snap["count"] == 1000
    assert snap["mean_s"] == pytest.approx(sum(samples) / 1000.0)


def test_clock_skew_and_out_of_range_observations_never_corrupt():
    h = LatencyHistogram()
    h.observe(-0.5)  # skewed stamps clamp to 0
    h.observe(float("nan"))  # skipped outright
    h.observe(float("inf"))
    h.observe(1e-9)  # underflow bucket
    h.observe(1e9)  # overflow bucket
    assert h.count == 3
    assert h.min_s == 0.0 and h.max_s == 1e9
    # estimates stay inside the observed range even for the open-ended
    # under/overflow buckets
    assert 0.0 <= h.quantile(0.01) <= h.quantile(0.99) <= 1e9


def test_merge_sums_counts_and_rejects_differing_edges():
    a, b = LatencyHistogram(), LatencyHistogram()
    for v in (0.001, 0.002, 0.004):
        a.observe(v)
    for v in (0.008, 0.016):
        b.observe(v)
    a.merge(b)
    assert a.count == 5
    assert a.min_s == 0.001 and a.max_s == 0.016
    assert a.sum_s == pytest.approx(0.031)
    with pytest.raises(ValueError, match="differing edges"):
        a.merge(LatencyHistogram((0.1, 1.0)))


def test_board_is_lazy_per_family_and_json_ready():
    board = LatencyBoard()
    assert board.families == () and board.snapshot() == {}
    board.observe("episode.run", 0.5)
    board.observe("episode.run", 0.7)
    board.observe("device.splice", 0.001)
    assert board.families == ("device.splice", "episode.run")
    assert board.get("episode.run").count == 2
    assert board.get("nope") is None
    snap = board.snapshot()
    assert set(snap) == {"device.splice", "episode.run"}
    assert snap["episode.run"]["count"] == 2
    assert 0.5 <= snap["episode.run"]["p99_s"] <= 0.7
    json.dumps(snap)  # the stats/heartbeat/report embedding
    assert all(math.isfinite(v) for v in snap["episode.run"].values())


def test_board_family_cardinality_is_bounded():
    """Satellite 2: unbounded family names (a tenant id or trace id
    leaking into the family string) must not grow the board without
    limit — novel families past the cap pool into OVERFLOW_FAMILY,
    while already-minted families keep observing normally."""
    board = LatencyBoard(max_families=3)
    for i in range(3):
        board.observe(f"fam{i}", 0.01)
    assert len(board.families) == 3
    # the flood: 50 novel names all land in the one overflow family
    for i in range(50):
        board.observe(f"leak-{i}", 0.02)
    fams = board.families
    assert len(fams) == 4  # 3 real + overflow, never 53
    assert OVERFLOW_FAMILY in fams
    assert board.get(OVERFLOW_FAMILY).count == 50
    assert board.get("leak-7") is None
    # established families are unaffected by the flood
    board.observe("fam1", 0.03)
    assert board.get("fam1").count == 2
    snap = board.snapshot()
    assert snap[OVERFLOW_FAMILY]["count"] == 50
    with pytest.raises(ValueError, match="max_families"):
        LatencyBoard(max_families=0)
