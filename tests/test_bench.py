"""Bench harness smoke: every BASELINE config measure runs at tiny
sizes on the CPU mesh and passes its own correctness guard.

The real numbers come from `python bench.py` / `--configs` on the chip
(driver artifact + BENCH_CONFIGS.json); these tests only keep the
harness importable and honest — a broken guard or a config that can't
compile should fail HERE, not in the one driver-run bench window per
round (the round-2 lesson: bench failures on the chip are expensive).
"""

import sys
import os

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402


def test_measure_nakamoto_guard():
    rate, rel = bench.measure_nakamoto(64, n_steps=2200, reps=1)
    assert rate > 0
    assert bench.SM1_GUARD[0] < rel < bench.SM1_GUARD[1], rel


@pytest.mark.slow  # compiles the 3 heaviest kernels in the repo
def test_measure_config_guards():
    for name, spec in bench.CONFIGS.items():
        kw = dict(spec["cpu"])
        kw["n_envs"] = min(kw["n_envs"], 32)
        rate, check = getattr(bench, spec["fn"])(**kw, reps=1)
        lo, hi = spec["guard"]
        assert rate > 0, name
        assert lo < check < hi, (name, check)
